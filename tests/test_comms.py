"""Comms tests — the reference's multi-rank round-trip suite run on the
8-device virtual CPU mesh (mirrors python/raft/raft/test/test_comms.py,
which drives perform_test_comms_* across a Dask cluster; here the cluster
is the virtual mesh, SURVEY.md §4 'TPU equivalent')."""

import numpy as np
import pytest

import jax

from raft_tpu.comms import (
    build_comms,
    run_all_self_tests,
    mnmg_knn,
    mnmg_kmeans_fit,
)
from raft_tpu.comms import self_test as st
from raft_tpu.cluster import KMeansParams
from raft_tpu.spatial import brute_force_knn


@pytest.fixture(scope="module")
def comms():
    return build_comms(jax.devices()[:8])


def test_comms_size(comms):
    assert comms.size == 8


@pytest.mark.parametrize(
    "fn",
    [
        st.test_collective_allreduce,
        st.test_collective_broadcast,
        st.test_collective_reduce,
        st.test_collective_allgather,
        st.test_collective_gather,
        st.test_collective_gatherv,
        st.test_collective_reducescatter,
        st.test_pointToPoint_simple_send_recv,
    ],
)
def test_collective_roundtrip(comms, fn):
    assert fn(comms) is True


def test_comm_split(comms):
    assert st.test_collective_comm_split(comms) is True


def test_run_all(comms):
    results = run_all_self_tests(comms)
    assert all(results.values()), results


def test_bcast_nonzero_root(comms):
    assert st.test_collective_broadcast(comms, root=3) is True


# ---------------------------------------------------------------------------
# MNMG algorithms vs single-device oracle
# ---------------------------------------------------------------------------


def test_mnmg_knn_matches_single(comms, rng_np):
    index = rng_np.standard_normal((330, 16)).astype(np.float32)  # ragged/8
    queries = rng_np.standard_normal((23, 16)).astype(np.float32)
    d_m, i_m = mnmg_knn(comms, index, queries, 7, metric="sqeuclidean")
    d_s, i_s = brute_force_knn(index, queries, 7, metric="sqeuclidean")
    np.testing.assert_allclose(np.asarray(d_m), np.asarray(d_s), rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(i_m), np.asarray(i_s))


def test_mnmg_kmeans_clusters_blobs(comms):
    from raft_tpu.random import make_blobs, RngState

    X, y = make_blobs(800, 8, n_clusters=4, cluster_std=0.3, state=RngState(5),
                      center_box=(-6.0, 6.0))
    X = np.asarray(X)
    out = mnmg_kmeans_fit(comms, X, KMeansParams(n_clusters=4, seed=1))
    labels = np.asarray(out.labels)
    assert labels.shape == (800,)
    # purity against ground truth
    y = np.asarray(y)
    total = sum(
        np.bincount(y[labels == c]).max()
        for c in range(4)
        if (labels == c).any()
    )
    assert total / 800 > 0.9
    assert np.isfinite(float(out.inertia))
