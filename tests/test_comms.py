"""Comms tests — the reference's multi-rank round-trip suite run on the
8-device virtual CPU mesh (mirrors python/raft/raft/test/test_comms.py,
which drives perform_test_comms_* across a Dask cluster; here the cluster
is the virtual mesh, SURVEY.md §4 'TPU equivalent')."""

import numpy as np
import pytest

import jax

from raft_tpu.comms import (
    build_comms,
    run_all_self_tests,
    mnmg_knn,
    mnmg_kmeans_fit,
)
from raft_tpu.comms import self_test as st
from raft_tpu.cluster import KMeansParams
from raft_tpu.spatial import brute_force_knn


@pytest.fixture(scope="module")
def comms():
    return build_comms(jax.devices()[:8])


def test_comms_size(comms):
    assert comms.size == 8


@pytest.mark.parametrize(
    "fn",
    [
        st.test_collective_allreduce,
        st.test_collective_broadcast,
        st.test_collective_reduce,
        st.test_collective_allgather,
        st.test_collective_gather,
        st.test_collective_gatherv,
        st.test_collective_reducescatter,
        st.test_pointToPoint_simple_send_recv,
    ],
)
def test_collective_roundtrip(comms, fn):
    assert fn(comms) is True


def test_comm_split(comms):
    assert st.test_collective_comm_split(comms) is True


def test_run_all(comms):
    results = run_all_self_tests(comms)
    assert all(results.values()), results


def test_bcast_nonzero_root(comms):
    assert st.test_collective_broadcast(comms, root=3) is True


# ---------------------------------------------------------------------------
# MNMG algorithms vs single-device oracle
# ---------------------------------------------------------------------------


def test_mnmg_knn_matches_single(comms, rng_np):
    index = rng_np.standard_normal((330, 16)).astype(np.float32)  # ragged/8
    queries = rng_np.standard_normal((23, 16)).astype(np.float32)
    d_m, i_m = mnmg_knn(comms, index, queries, 7, metric="sqeuclidean")
    d_s, i_s = brute_force_knn(index, queries, 7, metric="sqeuclidean")
    np.testing.assert_allclose(np.asarray(d_m), np.asarray(d_s), rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(i_m), np.asarray(i_s))


def test_mnmg_kmeans_clusters_blobs(comms):
    from raft_tpu.random import make_blobs, RngState

    X, y = make_blobs(800, 8, n_clusters=4, cluster_std=0.3, state=RngState(5),
                      center_box=(-6.0, 6.0))
    X = np.asarray(X)
    out = mnmg_kmeans_fit(comms, X, KMeansParams(n_clusters=4, seed=1))
    labels = np.asarray(out.labels)
    assert labels.shape == (800,)
    # purity against ground truth
    y = np.asarray(y)
    total = sum(
        np.bincount(y[labels == c]).max()
        for c in range(4)
        if (labels == c).any()
    )
    assert total / 800 > 0.9
    assert np.isfinite(float(out.inertia))


def test_p2p_batch_tagged(comms):
    """Tagged deferred isend/irecv/waitall (reference core/comms.hpp:440-508):
    multiple in-flight transfers, two tags, a repeated source within one tag
    (forces a second ppermute round)."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    def body(x):
        c = comms.device_comms()
        p2p = c.p2p_batch()
        # tag 0: 0->3 and 1->2 (one round)
        p2p.isend(x * 10, src=0, dest=3, tag=0)
        p2p.irecv(src=0, dest=3, tag=0)
        p2p.isend(x * 20, src=1, dest=2, tag=0)
        p2p.irecv(src=1, dest=2, tag=0)
        # tag 1: source 4 sends twice (second round needed)
        p2p.isend(x + 1, src=4, dest=5, tag=1)
        p2p.irecv(src=4, dest=5, tag=1)
        p2p.isend(x + 2, src=4, dest=6, tag=1)
        p2p.irecv(src=4, dest=6, tag=1)
        got = p2p.waitall()
        return jnp.stack([
            got[(0, 3, 0)], got[(1, 2, 0)], got[(4, 5, 1)], got[(4, 6, 1)],
        ])

    x = jnp.arange(1, 9, dtype=jnp.float32).reshape(8, 1)  # rank r holds r+1
    out = comms.shard_map(body, in_specs=P("ranks"), out_specs=P(None, "ranks"))(x)
    out = np.asarray(out)  # (4, 8) — transfer t as delivered on each rank
    assert out[0, 3] == 1.0 * 10     # rank 0's value*10 delivered at rank 3
    assert out[1, 2] == 2.0 * 20
    assert out[2, 5] == 5.0 + 1
    assert out[3, 6] == 5.0 + 2
    # non-destinations read zeros — including a rank that IS a destination
    # of a DIFFERENT transfer in the same round (out[1] is transfer
    # (1, 2, 0); rank 3 received (0, 3, 0) in that round but must read 0
    # under the (1, 2, 0) key)
    assert out[0, 0] == 0.0 and out[3, 1] == 0.0
    assert out[1, 3] == 0.0 and out[0, 2] == 0.0


def test_p2p_batch_unmatched_raises(comms):
    from raft_tpu import errors as err
    from jax.sharding import PartitionSpec as P
    import jax.numpy as jnp

    def body(x):
        c = comms.device_comms()
        p2p = c.p2p_batch()
        p2p.isend(x, src=0, dest=1, tag=0)
        # no matching irecv
        try:
            p2p.waitall()
        except err.RaftException:
            return x  # expected
        return x * 0  # unreachable: waitall must raise

    x = jnp.ones((8, 1), jnp.float32)
    out = comms.shard_map(body, in_specs=P("ranks"), out_specs=P("ranks"))(x)
    assert np.asarray(out).sum() == 8.0


def test_p2p_batch_mixed_shapes(comms):
    """Transfers with different shapes under one tag split into separate
    ppermute rounds instead of erroring (the reference's tagged p2p has no
    same-size requirement across endpoint pairs)."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    def body(x):
        c = comms.device_comms()
        p2p = c.p2p_batch()
        wide = jnp.concatenate([x, x], axis=-1)      # (1, 2) per rank
        p2p.isend(x, src=0, dest=1, tag=0)           # (1, 1)
        p2p.irecv(src=0, dest=1, tag=0)
        p2p.isend(wide, src=2, dest=3, tag=0)        # (1, 2) — new round
        p2p.irecv(src=2, dest=3, tag=0)
        got = p2p.waitall()
        return got[(0, 1, 0)] + got[(2, 3, 0)][:, :1]

    x = jnp.arange(1, 9, dtype=jnp.float32).reshape(8, 1)
    out = np.asarray(
        comms.shard_map(body, in_specs=P("ranks"), out_specs=P("ranks"))(x)
    )
    assert out[1, 0] == 1.0   # rank 0's value at rank 1
    assert out[3, 0] == 3.0   # rank 2's value at rank 3
    assert out[0, 0] == 0.0 and out[2, 0] == 0.0


# ---------------------------------------------------------------------------
# hierarchical (2-level ICI x DCN) communicator
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def hcomms():
    from raft_tpu.comms import build_comms_hierarchical

    return build_comms_hierarchical(jax.devices()[:8], mesh_shape=(2, 4))


def test_hierarchical_allreduce_matches_flat(hcomms):
    """reduce-scatter(ICI) + allreduce(DCN) + allgather(ICI) must equal a
    flat psum over both axes (the NCCL tree-algorithm identity)."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    def body(x):
        h = hcomms.hierarchical_allreduce(x)
        flat = hcomms.device_comms().allreduce(x)
        return h, flat

    # global (32, 4): each of the 8 ranks holds a (4, 4) local block, whose
    # leading dim is divisible by the inner (ici) size for reduce-scatter
    x = jnp.arange(32 * 4, dtype=jnp.float32).reshape(32, 4)
    h, flat = hcomms.shard_map(
        body, in_specs=P(("dcn", "ici")), out_specs=P(("dcn", "ici")),
    )(x)
    np.testing.assert_allclose(np.asarray(h), np.asarray(flat), rtol=1e-6)
    want = np.asarray(x).reshape(8, 4, 4).sum(0)          # global block sum
    got = np.asarray(flat).reshape(8, 4, 4)
    for r in range(8):
        np.testing.assert_allclose(got[r], want, rtol=1e-6)


def test_hierarchical_axis_levels(hcomms):
    """Inner collectives stay within a slice; outer cross slices."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    def body(x):
        inner_sum = hcomms.inner_comms().allreduce(x)    # per-slice sums
        outer_sum = hcomms.outer_comms().allreduce(x)    # per-position sums
        return inner_sum, outer_sum

    x = jnp.arange(1, 9, dtype=jnp.float32).reshape(8, 1)  # rank r: r+1
    inner, outer = hcomms.shard_map(
        body, in_specs=P(("dcn", "ici")), out_specs=P(("dcn", "ici")),
    )(x)
    inner = np.asarray(inner).ravel()
    outer = np.asarray(outer).ravel()
    # mesh (2, 4): slice 0 = ranks 0-3 (values 1..4, sum 10),
    # slice 1 = ranks 4-7 (values 5..8, sum 26)
    np.testing.assert_allclose(inner[:4], 10.0)
    np.testing.assert_allclose(inner[4:], 26.0)
    # outer pairs (r, r+4): values (r+1) + (r+5)
    np.testing.assert_allclose(outer, [6, 8, 10, 12, 6, 8, 10, 12])


# -- precondition contracts (ISSUE 3 satellites) ----------------------------


def test_allgatherv_overflow_raises_clearly(comms):
    """A contribution larger than max_count must raise a RaftLogicError
    naming the contract — not jnp.pad's unrelated negative-pad error."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    def body(x):
        c = comms.device_comms()
        mine = x[0]                                  # (8, 1) per rank
        slots, counts = c.allgatherv(mine, mine.shape[0], max_count=4)
        return slots

    x = jnp.ones((8, 8, 1), jnp.float32)  # 8 rows/rank > max_count=4
    with pytest.raises(ValueError, match="max_count"):
        comms.shard_map(
            body, in_specs=P("ranks"), out_specs=P(None, "ranks"),
        )(x)


def test_reducescatter_indivisible_raises(comms):
    """Both reducescatter paths check divisibility up front; the non-SUM
    path would otherwise silently slice a truncated shard."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    for op in ("sum", "max"):
        def body(x):
            c = comms.device_comms()
            return c.reducescatter(x[0], op=op)[None]

        x = jnp.ones((8, 12), jnp.float32)  # 12 % 8 != 0
        with pytest.raises(ValueError, match="divisible"):
            comms.shard_map(
                body, in_specs=P("ranks"), out_specs=P("ranks"),
            )(x)


def test_p2p_batch_retry_after_validation_error(comms):
    """Regression (ISSUE 3): a waitall rejected by validation must clear
    the recorded sends/recvs, so a corrected retry on the SAME batch
    succeeds instead of tripping over stale duplicate keys."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from raft_tpu import errors as err

    def body(x):
        c = comms.device_comms()
        p2p = c.p2p_batch()
        # attempt 1: unmatched (no irecv) -> validation error
        p2p.isend(x * 10, src=0, dest=3, tag=0)
        try:
            p2p.waitall()
        except err.RaftException:
            pass  # expected; state must now be clear
        # attempt 2 on the same batch: the corrected transfer set —
        # before the fix, the stale (0, 3, 0) send collided here as a
        # duplicate key
        p2p.isend(x * 10, src=0, dest=3, tag=0)
        p2p.irecv(src=0, dest=3, tag=0)
        got = p2p.waitall()
        return got[(0, 3, 0)]

    x = jnp.arange(1, 9, dtype=jnp.float32).reshape(8, 1)
    out = np.asarray(
        comms.shard_map(body, in_specs=P("ranks"), out_specs=P("ranks"))(x)
    )
    assert out[3, 0] == 10.0  # rank 0's value*10 delivered at rank 3
    assert out[0, 0] == 0.0
