"""kNN / selection tests — mirrors the reference oracle patterns
(cpp/test/spatial/selection.cu, cpp/test/spatial/knn.cu,
cpp/test/spatial/haversine.cu, cpp/test/spatial/epsilon_neighborhood.cu)."""

import numpy as np
import pytest

from raft_tpu.spatial import (
    SelectKAlgo,
    select_k,
    select_k_blocked,
    brute_force_knn,
    knn_merge_parts,
    haversine_knn,
    epsilon_neighborhood,
)


def naive_knn(queries, index, k, metric="l2"):
    if metric == "l2":
        d = np.sqrt(((queries[:, None, :] - index[None, :, :]) ** 2).sum(-1))
    elif metric == "sqeuclidean":
        d = ((queries[:, None, :] - index[None, :, :]) ** 2).sum(-1)
    elif metric == "l1":
        d = np.abs(queries[:, None, :] - index[None, :, :]).sum(-1)
    elif metric == "inner_product":
        d = queries @ index.T
    order = np.argsort(d, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(d, order, axis=1), order


@pytest.mark.parametrize("algo", [SelectKAlgo.TOPK, SelectKAlgo.SORT])
def test_select_k(algo, rng_np):
    d = rng_np.standard_normal((30, 100)).astype(np.float32)
    vals, idxs = select_k(d, 7, algo=algo)
    want = np.sort(d, axis=1)[:, :7]
    np.testing.assert_allclose(np.asarray(vals), want, rtol=1e-6)
    np.testing.assert_allclose(
        np.take_along_axis(d, np.asarray(idxs), axis=1), want, rtol=1e-6
    )


def test_select_k_max(rng_np):
    d = rng_np.standard_normal((10, 50)).astype(np.float32)
    vals, _ = select_k(d, 5, select_min=False)
    want = -np.sort(-d, axis=1)[:, :5]
    np.testing.assert_allclose(np.asarray(vals), want, rtol=1e-6)


def test_select_k_blocked_matches(rng_np):
    d = rng_np.standard_normal((12, 333)).astype(np.float32)
    v1, i1 = select_k(d, 9)
    v2, i2 = select_k_blocked(d, 9, block_n=64)
    np.testing.assert_allclose(np.asarray(v1), np.asarray(v2), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


def test_select_k_carries_indices(rng_np):
    d = rng_np.standard_normal((4, 20)).astype(np.float32)
    labels = rng_np.integers(100, 200, (4, 20)).astype(np.int32)
    vals, idxs = select_k(d, 3, indices=labels)
    pos = np.argsort(np.asarray(d), axis=1)[:, :3]
    np.testing.assert_array_equal(np.asarray(idxs), np.take_along_axis(labels, pos, 1))


@pytest.mark.parametrize("metric", ["l2", "sqeuclidean", "l1", "inner_product"])
def test_brute_force_knn_single(metric, rng_np):
    index = rng_np.standard_normal((200, 16)).astype(np.float32)
    queries = rng_np.standard_normal((35, 16)).astype(np.float32)
    k = 8
    sel_min = metric != "inner_product"
    if metric == "inner_product":
        # inner product is a similarity; reference searches max via negation
        dists, idxs = brute_force_knn(index, queries, k, metric="sqeuclidean")
        want_d, want_i = naive_knn(queries, index, k, "sqeuclidean")
    else:
        dists, idxs = brute_force_knn(index, queries, k, metric=metric)
        want_d, want_i = naive_knn(queries, index, k, metric)
    np.testing.assert_allclose(np.asarray(dists), want_d, rtol=2e-4, atol=2e-4)
    np.testing.assert_array_equal(np.asarray(idxs), want_i)


def test_brute_force_knn_blocked_paths(rng_np):
    index = rng_np.standard_normal((257, 8)).astype(np.float32)
    queries = rng_np.standard_normal((19, 8)).astype(np.float32)
    d1, i1 = brute_force_knn(index, queries, 5, metric="l2")
    d2, i2 = brute_force_knn(index, queries, 5, metric="l2", block_n=64, block_q=7)
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


def test_brute_force_knn_parts(rng_np):
    """Partitioned search == monolithic search with translated ids
    (reference knn_merge_parts, cpp/test/spatial/knn.cu)."""
    full = rng_np.standard_normal((300, 12)).astype(np.float32)
    queries = rng_np.standard_normal((21, 12)).astype(np.float32)
    parts = [full[:100], full[100:180], full[180:]]
    d1, i1 = brute_force_knn(parts, queries, 6, metric="sqeuclidean")
    d2, i2 = brute_force_knn(full, queries, 6, metric="sqeuclidean")
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


def test_knn_merge_parts_translations(rng_np):
    pd = np.sort(rng_np.random((2, 5, 3)).astype(np.float32), axis=2)
    pi = np.tile(np.arange(3, dtype=np.int32), (2, 5, 1))
    d, i = knn_merge_parts(pd, pi, translations=[0, 1000])
    assert np.asarray(i).max() >= 1000 or np.asarray(pd)[1].min() > np.asarray(pd)[0].max()
    # merged distances are the 3 smallest of the union per query
    union = pd.transpose(1, 0, 2).reshape(5, 6)
    np.testing.assert_allclose(np.asarray(d), np.sort(union, 1)[:, :3], rtol=1e-6)


def test_haversine_knn(rng_np):
    lat = rng_np.uniform(-np.pi / 2, np.pi / 2, 50)
    lon = rng_np.uniform(-np.pi, np.pi, 50)
    index = np.stack([lat, lon], 1).astype(np.float32)
    queries = index[:9]
    d, i = haversine_knn(index, queries, 4)
    # each query's nearest neighbor is itself at distance 0
    np.testing.assert_array_equal(np.asarray(i)[:, 0], np.arange(9))
    np.testing.assert_allclose(np.asarray(d)[:, 0], 0.0, atol=1e-3)


def test_epsilon_neighborhood(rng_np):
    x = rng_np.standard_normal((40, 6)).astype(np.float32)
    y = rng_np.standard_normal((30, 6)).astype(np.float32)
    eps = 2.5
    adj, vd = epsilon_neighborhood(x, y, eps)
    d2 = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
    want = d2 <= eps**2
    np.testing.assert_array_equal(np.asarray(adj), want)
    np.testing.assert_array_equal(np.asarray(vd), want.sum(1))


def test_brute_force_knn_mixed_partitions_with_tuning_args(rng_np):
    """compute_dtype on a mixed partition set must not raise while any
    partition takes the fused path; it must raise when none does."""
    import jax.numpy as jnp
    import pytest
    from raft_tpu import errors

    q = rng_np.standard_normal((8, 16)).astype(np.float32)
    small = rng_np.standard_normal((500, 16)).astype(np.float32)
    with pytest.raises(errors.RaftException):
        # all partitions scan-routed (CPU backend, tiny n): args dropped
        brute_force_knn(
            [small, small], q, 3, compute_dtype=jnp.bfloat16,
        )
    # forcing fused consumes the args without raising
    big = rng_np.standard_normal((8192, 16)).astype(np.float32)
    d, i = brute_force_knn(
        [big], q, 3, metric="sqeuclidean", use_fused=True,
        compute_dtype=jnp.float32,
    )
    assert d.shape == (8, 3)
