"""Cross-host serving tier (raft_tpu/comms/multihost.py + the 2-level
merge tail in both sharded engines) — ISSUE 9 acceptance, all on the
8-device virtual CPU mesh reshaped into host-sim 2-level geometries:

* byte accounting: the hierarchical ICI x DCN merge moves >= 4x fewer
  cross-host bytes per query than the flat deployment-width allgather
  at the same (k, ways) from one real 8-chip host up;
* the 2x4 host-sim hierarchical merge is BIT-IDENTICAL to the flat 1x8
  merge on the same placed shards with ``wire="f32"`` (both engines),
  and matches up to the documented bf16 k-boundary quantization with
  the compressed serving wire (selected entries' values exact after
  the f32 rerank tail);
* host-aware placement: ``place_index(..., replication=2)`` on a
  HierarchicalComms defaults to the whole-host replica stripe, and a
  WHOLE host down keeps coverage == 1.0 with results bit-identical to
  the healthy mesh, zero retraces across die -> failover -> heal;
* elastic host resharding: one index placed across 1x8 / 2x4 / 4x2 and
  shrunk to a 2x2 fleet (through the v3 checkpoint path) answers
  identically on every geometry — no rebuild;
* ``hierarchical_allreduce`` pads-and-slices odd leading dims instead
  of raising (the old hard precondition).

docs/multihost.md states the full contract.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.comms import (
    build_comms,
    build_comms_hierarchical,
    comms_levels,
    dcn_merge_accounting,
    host_aware_offset,
    host_rank_mask,
    mnmg_ivf_flat_build,
    mnmg_ivf_flat_search,
    mnmg_ivf_pq_build,
    mnmg_ivf_pq_search,
    place_index,
)
from raft_tpu.comms.multihost import hier_axes
from raft_tpu.resilience import FailoverPlan, ReplicaPlacement
from raft_tpu.spatial.ann import (
    IVFFlatParams,
    IVFPQParams,
    load_index,
    save_index,
)

K = 10
NQ = 32


@pytest.fixture(scope="module")
def flat8():
    return build_comms(jax.devices()[:8])


@pytest.fixture(scope="module")
def hier24():
    return build_comms_hierarchical(jax.devices()[:8], mesh_shape=(2, 4))


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(7)
    x = rng.standard_normal((4096, 16)).astype(np.float32)
    q = rng.standard_normal((NQ, 16)).astype(np.float32)
    return x, q


@pytest.fixture(scope="module")
def flat_index(flat8, dataset):
    x, _ = dataset
    return mnmg_ivf_flat_build(
        flat8, x, IVFFlatParams(n_lists=32, kmeans_n_iters=4, seed=0),
        metric="sqeuclidean",
    )


@pytest.fixture(scope="module")
def pq_index(flat8, dataset):
    x, _ = dataset
    return mnmg_ivf_pq_build(flat8, x, IVFPQParams(
        n_lists=32, pq_dim=4, pq_bits=6, kmeans_n_iters=4, seed=0,
    ))


# ---------------------------------------------------------------------------
# DCN byte accounting — the >= 4x acceptance
# ---------------------------------------------------------------------------


class TestByteAccounting:
    def test_at_least_4x_from_one_real_host_up(self):
        """ISSUE 9 acceptance: >= 4x fewer cross-host bytes per query
        than the flat deployment-width allgather at the same (k, ways),
        for every host count at the real 8-chip-host geometry — and for
        BOTH wire formats."""
        for wire in ("bf16", "f32"):
            for n_hosts in (2, 4, 8, 64):
                acc = dcn_merge_accounting(
                    K, n_hosts, 8, wire=wire
                )
                assert acc["ratio"] >= 4.0, acc
                # the flat side of the model: every off-host chip's
                # uncompressed (k,) part crosses DCN
                assert acc["flat_bytes_per_query"] == (
                    (n_hosts * 8 - 8) * K * 8
                )

    def test_ratio_grows_with_chips_per_host(self):
        """The flat tail pays per CHIP, the hierarchical one per HOST —
        the saving scales with the very thing that makes hosts big."""
        r = [
            dcn_merge_accounting(K, 4, c)["ratio"]
            for c in (4, 8, 16, 32)
        ]
        assert r == sorted(r) and r[-1] > 4 * r[0] / 2

    def test_host_sim_2x4_f32_exactly_flat_over_slices(self):
        """The 2x4 host-sim geometry (the bench row's shape): f32 wire
        quadruples down to exactly the slice count's share."""
        acc = dcn_merge_accounting(K, 2, 4, wire="f32")
        assert acc["ratio"] == pytest.approx(4.0)
        bacc = dcn_merge_accounting(K, 2, 4, wire="bf16")
        # bf16 trades a smaller exchange for the rerank psum; the model
        # must count BOTH terms
        assert bacc["hier_bytes_per_query"] == pytest.approx(
            K * 6 + 2 * 0.5 * K * 4
        )

    def test_bad_geometry_rejected(self):
        with pytest.raises(ValueError):
            dcn_merge_accounting(0, 2, 8)
        with pytest.raises(ValueError):
            dcn_merge_accounting(K, 2, 8, wire="fp8")


# ---------------------------------------------------------------------------
# topology helpers
# ---------------------------------------------------------------------------


class TestTopology:
    def test_comms_levels(self, flat8, hier24):
        assert comms_levels(flat8) == (1, 8)
        assert comms_levels(hier24) == (2, 4)

    def test_hier_axes_one_slice_is_flat(self):
        """A 2-level mesh with ONE slice carries no DCN traffic — the
        flat merge tail is already optimal and hier_axes must say so."""
        h = build_comms_hierarchical(
            jax.devices()[:8], mesh_shape=(1, 8)
        )
        assert hier_axes(h.mesh, h.axis) is None
        assert comms_levels(h) == (1, 8)

    def test_host_of_and_sizes(self, hier24):
        assert (hier24.outer_size, hier24.inner_size) == (2, 4)
        assert [hier24.host_of(r) for r in range(8)] == [
            0, 0, 0, 0, 1, 1, 1, 1,
        ]
        with pytest.raises(ValueError):
            hier24.host_of(8)

    def test_host_rank_mask(self):
        np.testing.assert_array_equal(
            host_rank_mask([1, 0], 4),
            np.array([1, 1, 1, 1, 0, 0, 0, 0], np.int32),
        )
        with pytest.raises(ValueError):
            host_rank_mask(np.ones((2, 2)), 4)

    def test_host_aware_offset(self):
        assert host_aware_offset(8, 4, 2) == 4
        assert host_aware_offset(8, 2, 2) == 4    # 4 hosts, step 2 hosts
        assert host_aware_offset(8, 2, 4) == 2    # 4 hosts, step 1 host
        with pytest.raises(ValueError):
            host_aware_offset(8, 3, 2)            # not a whole host count
        with pytest.raises(ValueError):
            host_aware_offset(8, 4, 3)            # R > host count


# ---------------------------------------------------------------------------
# host-aware replica placement
# ---------------------------------------------------------------------------


class TestHostAwarePlacement:
    def test_striped_inner_size_host_disjoint(self):
        p = ReplicaPlacement.striped(8, 2, inner_size=4)
        assert p.offset == 4 and p.inner_size == 4
        assert p.host_disjoint
        for s in range(8):
            assert len(set(p.holder_hosts(s))) == 2

    def test_same_host_stripe_rejected(self):
        with pytest.raises(ValueError):
            ReplicaPlacement.striped(8, 2, offset=1, inner_size=4)

    def test_more_copies_than_hosts_rejected(self):
        with pytest.raises(ValueError):
            ReplicaPlacement.striped(8, 4, inner_size=4)
        # ... but fine when enough hosts exist
        p = ReplicaPlacement.striped(8, 4, inner_size=2)
        assert p.host_disjoint

    def test_rank_only_placement_unchanged(self):
        """inner_size defaults to the PR 5 rank-only contract — same
        stripe, host axis absent."""
        p = ReplicaPlacement.striped(8, 2)
        assert (p.offset, p.inner_size) == (4, 1)
        assert not p.host_disjoint  # no host axis to be disjoint over

    def test_from_host_health_routes_whole_host(self):
        p = ReplicaPlacement.striped(8, 2, inner_size=4)
        plan = FailoverPlan.from_host_health(p, [1, 0])
        assert plan.fully_covered
        # every shard primary on the dead host fails over (copy 1)
        assert plan.route.tolist() == [0, 0, 0, 0, 1, 1, 1, 1]
        with pytest.raises(ValueError):
            FailoverPlan.from_host_health(p, [1, 0, 1])  # wrong host count

    def test_place_index_host_aware_default(self, hier24, flat_index):
        """place_index on a HierarchicalComms defaults the replica
        stripe to whole hosts — R copies of a shard never share one."""
        idx = place_index(hier24, flat_index, replication=2)
        assert int(idx.replica_offset) == 4
        p = ReplicaPlacement.striped(
            8, 2, int(idx.replica_offset), inner_size=4
        )
        assert p.host_disjoint


# ---------------------------------------------------------------------------
# the two-stage merge vs the flat program — bit-identity + wire contract
# ---------------------------------------------------------------------------


def _flat_ref(flat8, flat_index, q):
    return mnmg_ivf_flat_search(
        flat8, flat_index, q, K, n_probes=8, qcap=NQ,
    )


class TestHierarchicalMerge:
    def test_f32_wire_bit_identical_to_flat_merge(
        self, flat8, hier24, flat_index, dataset
    ):
        """ISSUE 9 acceptance: same shards, same (k, ways) — the 2x4
        hierarchical merge with the uncompressed wire returns exactly
        the flat 1x8 program's (dists, ids)."""
        _, q = dataset
        dv, iv = _flat_ref(flat8, flat_index, q)
        hidx = place_index(hier24, flat_index)
        dh, ih = mnmg_ivf_flat_search(
            hier24, hidx, q, K, n_probes=8, qcap=NQ, wire="f32",
        )
        np.testing.assert_array_equal(np.asarray(ih), np.asarray(iv))
        np.testing.assert_array_equal(np.asarray(dh), np.asarray(dv))

    def test_bf16_wire_documented_quantization(
        self, flat8, hier24, flat_index, dataset
    ):
        """The compressed serving wire: selected entries carry EXACT
        f32 values (the rerank tail), and any id divergence from the
        flat merge sits at the k-boundary within one bf16 ulp."""
        _, q = dataset
        dv, iv = _flat_ref(flat8, flat_index, q)
        hidx = place_index(hier24, flat_index)
        db, ib = mnmg_ivf_flat_search(
            hier24, hidx, q, K, n_probes=8, qcap=NQ, wire="bf16",
        )
        dv, iv = np.asarray(dv), np.asarray(iv)
        db, ib = np.asarray(db), np.asarray(ib)
        same = ib == iv
        # agreeing slots are EXACT — wire rounding never reaches the
        # reported values
        np.testing.assert_array_equal(db[same], dv[same])
        # diverging slots (boundary ties) stay inside the bf16
        # quantization band of the flat value
        if (~same).any():
            a, b = db[~same], dv[~same]
            # bf16 carries 8 significand bits -> relative spacing 2^-8;
            # a boundary tie can swap entries up to ~2 ulp apart
            assert np.all(
                np.abs(a - b) <= np.abs(b) * 2.0 ** -7 + 1e-6
            )
        # and the wire never degrades more than a sliver of the answer
        assert same.mean() > 0.97

    def test_pq_engine_hier_matches_flat(
        self, flat8, hier24, pq_index, dataset
    ):
        _, q = dataset
        dv, iv = mnmg_ivf_pq_search(
            flat8, pq_index, q, K, n_probes=8, refine_ratio=4.0,
            qcap=NQ,
        )
        hidx = place_index(hier24, pq_index)
        dh, ih = mnmg_ivf_pq_search(
            hier24, hidx, q, K, n_probes=8, refine_ratio=4.0,
            qcap=NQ, wire="f32",
        )
        np.testing.assert_array_equal(np.asarray(ih), np.asarray(iv))
        np.testing.assert_array_equal(np.asarray(dh), np.asarray(dv))

    def test_wire_static_ignored_on_flat_mesh(self, flat8, flat_index,
                                              dataset, monkeypatch):
        """On a 1-level mesh ``wire`` is normalized out of the cache
        key — bf16 and f32 callers share ONE compiled program (there is
        no DCN stage to compress)."""
        from raft_tpu.comms import mnmg_ivf_flat as mod

        _, q = dataset
        created = []
        orig = mod._cached_search

        def recording(*a, **k):
            fn = orig(*a, **k)
            created.append(fn)
            return fn

        monkeypatch.setattr(mod, "_cached_search", recording)
        r1 = mod.mnmg_ivf_flat_search(
            flat8, flat_index, q, K, n_probes=8, qcap=NQ, wire="bf16",
        )
        r2 = mod.mnmg_ivf_flat_search(
            flat8, flat_index, q, K, n_probes=8, qcap=NQ, wire="f32",
        )
        assert created[0] is created[1]
        np.testing.assert_array_equal(
            np.asarray(r1[1]), np.asarray(r2[1])
        )

    def test_unknown_wire_rejected(self, hier24, flat_index, dataset):
        _, q = dataset
        hidx = place_index(hier24, flat_index)
        with pytest.raises(ValueError):
            mnmg_ivf_flat_search(
                hier24, hidx, q, K, n_probes=8, qcap=NQ, wire="fp8",
            )

    def test_merge_ways_floor_is_inner_width(self, hier24, flat_index,
                                             dataset):
        """On a 2-level mesh merge_ways emulates a wider HOST (the ICI
        stage), so its floor is the slice width — 4 is legal on 2x4
        (it would be rejected on the flat 8-rank mesh) and 8 emulates
        8-chip hosts."""
        _, q = dataset
        hidx = place_index(hier24, flat_index)
        d4, i4 = mnmg_ivf_flat_search(
            hier24, hidx, q, K, n_probes=8, qcap=NQ, merge_ways=4,
            wire="f32",
        )
        d8, i8 = mnmg_ivf_flat_search(
            hier24, hidx, q, K, n_probes=8, qcap=NQ, merge_ways=8,
            wire="f32",
        )
        # absent-peer padding contributes nothing
        np.testing.assert_array_equal(np.asarray(i4), np.asarray(i8))
        np.testing.assert_array_equal(np.asarray(d4), np.asarray(d8))
        with pytest.raises(ValueError):
            mnmg_ivf_flat_search(
                hier24, hidx, q, K, n_probes=8, qcap=NQ, merge_ways=2,
            )


# ---------------------------------------------------------------------------
# provenance select — the DCN stage's building block
# ---------------------------------------------------------------------------


def test_merge_parts_provenance_select_k_roundtrip():
    from raft_tpu.spatial.selection import (
        merge_parts_provenance_select_k,
        merge_parts_select_k,
    )

    rng = np.random.default_rng(3)
    pv = np.sort(
        rng.standard_normal((3, 5, 6)).astype(np.float32), axis=-1
    )
    pi = rng.integers(0, 10_000, (3, 5, 6)).astype(np.int32)
    vals, ids, part, slot = merge_parts_provenance_select_k(
        jnp.asarray(pv), jnp.asarray(pi), 4
    )
    mv, mi = merge_parts_select_k(jnp.asarray(pv), jnp.asarray(pi), 4)
    np.testing.assert_array_equal(np.asarray(vals), np.asarray(mv))
    np.testing.assert_array_equal(np.asarray(ids), np.asarray(mi))
    # provenance points back at the exact source entry
    part, slot = np.asarray(part), np.asarray(slot)
    for i in range(5):
        for j in range(4):
            assert pv[part[i, j], i, slot[i, j]] == np.asarray(vals)[i, j]
            assert pi[part[i, j], i, slot[i, j]] == np.asarray(ids)[i, j]


# ---------------------------------------------------------------------------
# whole-host failure — coverage 1.0, bit-identical, zero retraces
# ---------------------------------------------------------------------------


class TestHostFailure:
    def test_whole_host_down_bit_identical_zero_retrace(
        self, hier24, flat_index, dataset, monkeypatch
    ):
        """ISSUE 9 acceptance: R=2 host-aware placement, a WHOLE host
        dies -> coverage stays 1.0 and results are bit-identical to the
        healthy mesh, across die -> failover -> heal with ZERO retraces
        of the one compiled program."""
        from raft_tpu.comms import mnmg_ivf_flat as mod

        _, q = dataset
        idx = place_index(hier24, flat_index, replication=2)
        placement = ReplicaPlacement.striped(
            8, 2, int(idx.replica_offset), inner_size=4
        )
        created = []
        orig = mod._cached_search

        def recording(*a, **k):
            fn = orig(*a, **k)
            created.append(fn)
            return fn

        monkeypatch.setattr(mod, "_cached_search", recording)
        kw = dict(n_probes=8, qcap=NQ, wire="f32")
        healthy = mod.mnmg_ivf_flat_search(
            hier24, idx, q, K, shard_mask=True, **kw,
        )
        fn = created[0]
        size0 = fn._cache_size()
        assert healthy.partial is False
        for dead_host in (0, 1):
            alive = host_rank_mask(
                [int(h != dead_host) for h in range(2)], 4
            )
            plan = FailoverPlan.from_host_health(
                placement, [int(h != dead_host) for h in range(2)]
            )
            down = mod.mnmg_ivf_flat_search(
                hier24, idx, q, K, shard_mask=alive, failover=plan,
                **kw,
            )
            assert down.partial is False
            assert float(np.asarray(down.coverage).min()) == 1.0
            np.testing.assert_array_equal(
                np.asarray(down.ids), np.asarray(healthy.ids)
            )
            np.testing.assert_array_equal(
                np.asarray(down.distances),
                np.asarray(healthy.distances),
            )
        healed = mod.mnmg_ivf_flat_search(
            hier24, idx, q, K, shard_mask=True, **kw,
        )
        np.testing.assert_array_equal(
            np.asarray(healed.ids), np.asarray(healthy.ids)
        )
        assert all(f is fn for f in created), \
            "host flips must reuse the one compiled program"
        assert fn._cache_size() == size0, \
            "host die -> failover -> heal must not retrace"

    def test_whole_host_down_bf16_serving_wire(self, hier24, flat_index,
                                               dataset):
        """The compressed serving wire under host failure: coverage
        stays 1.0 and ids match the healthy mesh everywhere except
        (possibly) k-boundary ties inside the bf16 band — failover
        moves candidates BETWEEN slices, so boundary ties may resolve
        differently (docs/multihost.md "Wire quantization")."""
        _, q = dataset
        idx = place_index(hier24, flat_index, replication=2)
        placement = ReplicaPlacement.striped(
            8, 2, int(idx.replica_offset), inner_size=4
        )
        healthy = mnmg_ivf_flat_search(
            hier24, idx, q, K, n_probes=8, qcap=NQ, shard_mask=True,
            wire="bf16",
        )
        plan = FailoverPlan.from_host_health(placement, [0, 1])
        down = mnmg_ivf_flat_search(
            hier24, idx, q, K, n_probes=8, qcap=NQ,
            shard_mask=host_rank_mask([0, 1], 4), failover=plan,
            wire="bf16",
        )
        assert float(np.asarray(down.coverage).min()) == 1.0
        same = np.asarray(down.ids) == np.asarray(healthy.ids)
        assert same.mean() > 0.97
        np.testing.assert_array_equal(
            np.asarray(down.distances)[same],
            np.asarray(healthy.distances)[same],
        )

    def test_half_host_down_host_aware_still_covers(self, hier24,
                                                    flat_index, dataset):
        """Sub-host (rank-granular) failures on a host-aware placement
        keep the PR 5 contract: any single rank down, coverage 1.0,
        bit-identical."""
        _, q = dataset
        idx = place_index(hier24, flat_index, replication=2)
        placement = ReplicaPlacement.striped(
            8, 2, int(idx.replica_offset), inner_size=4
        )
        kw = dict(n_probes=8, qcap=NQ, wire="f32")
        healthy = mnmg_ivf_flat_search(
            hier24, idx, q, K, shard_mask=True, **kw,
        )
        alive = np.ones(8, np.int32)
        alive[5] = 0
        plan = FailoverPlan.from_health(placement, alive)
        down = mnmg_ivf_flat_search(
            hier24, idx, q, K, shard_mask=alive, failover=plan, **kw,
        )
        assert float(np.asarray(down.coverage).min()) == 1.0
        np.testing.assert_array_equal(
            np.asarray(down.ids), np.asarray(healthy.ids)
        )


# ---------------------------------------------------------------------------
# elastic host resharding — grow/shrink the fleet, no rebuild
# ---------------------------------------------------------------------------


class TestElasticReshard:
    def test_same_answers_across_host_geometries(self, flat8, hier24,
                                                 flat_index, dataset):
        """One build serves identically from 1x8, 2x4, and 4x2 host
        geometries — re-placement is pure data movement."""
        _, q = dataset
        ref_d, ref_i = _flat_ref(flat8, flat_index, q)
        for shape in ((2, 4), (4, 2)):
            h = (
                hier24 if shape == (2, 4)
                else build_comms_hierarchical(
                    jax.devices()[:8], mesh_shape=shape
                )
            )
            idx = place_index(h, flat_index)
            d, i = mnmg_ivf_flat_search(
                h, idx, q, K, n_probes=8, qcap=NQ, wire="f32",
            )
            np.testing.assert_array_equal(np.asarray(i), np.asarray(ref_i))
            np.testing.assert_array_equal(np.asarray(d), np.asarray(ref_d))

    def test_shrink_host_fleet_through_checkpoint(self, flat8, hier24,
                                                  flat_index, dataset,
                                                  tmp_path):
        """Losing half the fleet: a 2x4-placed REPLICATED index saved
        to the v3 checkpoint restores onto a 2x2 mesh (half the chips,
        same host count) via the reshard path with identical answers —
        replication re-applied host-aware on the smaller fleet."""
        _, q = dataset
        ref_d, ref_i = _flat_ref(flat8, flat_index, q)
        big = place_index(hier24, flat_index, replication=2)
        path = tmp_path / "hier.idx"
        save_index(big, path)
        small_comms = build_comms_hierarchical(
            jax.devices()[:4], mesh_shape=(2, 2)
        )
        restored = load_index(path)
        small = place_index(small_comms, restored, replication=2)
        assert small.sorted_ids.shape[0] == 4
        assert int(small.replica_offset) == 2      # host-aware on 2x2
        d, i = mnmg_ivf_flat_search(
            small_comms, small, q, K, n_probes=8, qcap=NQ, wire="f32",
        )
        np.testing.assert_array_equal(np.asarray(i), np.asarray(ref_i))
        np.testing.assert_array_equal(np.asarray(d), np.asarray(ref_d))

    def test_grow_host_fleet_no_rebuild(self, flat_index, dataset):
        """Growing 1 host -> 2 hosts: the 4-rank single-host layout
        re-places onto the 2x4 8-rank fleet without a rebuild."""
        _, q = dataset
        small_comms = build_comms(jax.devices()[:4])
        small = place_index(small_comms, flat_index)   # reshards to 4
        ds, is_ = mnmg_ivf_flat_search(
            small_comms, small, q, K, n_probes=8, qcap=NQ,
        )
        grown_comms = build_comms_hierarchical(
            jax.devices()[:8], mesh_shape=(2, 4)
        )
        grown = place_index(grown_comms, small)
        dg, ig = mnmg_ivf_flat_search(
            grown_comms, grown, q, K, n_probes=8, qcap=NQ, wire="f32",
        )
        np.testing.assert_array_equal(np.asarray(ig), np.asarray(is_))
        np.testing.assert_array_equal(np.asarray(dg), np.asarray(ds))


# ---------------------------------------------------------------------------
# the open-loop executor over the 2-level mesh — the DCN exchange rides
# the in-flight window (the merge tail is IN the one fused dispatch, so
# max_in_flight > 1 pipelines it against the next micro-batch's shard
# compute; docs/multihost.md "Pipelining")
# ---------------------------------------------------------------------------


class TestExecutorPipelining:
    def test_executor_host_failover_in_flight_zero_retrace(
        self, hier24, flat_index, dataset, monkeypatch
    ):
        """ISSUE 9 tentpole integration: ONE ServingExecutor with an
        in-flight window of 2 serves an open-loop stream through the
        hierarchical 2x4 program across a whole-host die -> failover ->
        heal cycle — host health flows through set_runtime as the same
        shard_mask/route runtime inputs rank failures use, every answer
        is bit-identical to the healthy mesh at coverage 1.0, and the
        compiled program never retraces (the DCN stage is inside the
        fused dispatch, so the window pipelines it for free)."""
        from raft_tpu.comms import mnmg_ivf_flat as mod
        from raft_tpu.serving import ServingExecutor

        _, q = dataset                              # (32, 16) queries
        buckets = (8, 16)
        idx = place_index(hier24, flat_index, replication=2)
        placement = ReplicaPlacement.striped(
            8, 2, int(idx.replica_offset), inner_size=4
        )
        created = []
        orig = mod._cached_search

        def recording(*a, **k):
            fn = orig(*a, **k)
            created.append(fn)
            return fn

        monkeypatch.setattr(mod, "_cached_search", recording)

        def run(qq, shard_mask=None, failover=None):
            return mod.mnmg_ivf_flat_search(
                hier24, idx, qq, K, n_probes=8, qcap=16, wire="f32",
                shard_mask=shard_mask if shard_mask is not None
                else np.ones(8, np.int32),
                failover=failover,
            )

        plan0 = FailoverPlan.from_host_health(placement, [1, 1])
        ref = run(jnp.asarray(q[:16]), shard_mask=host_rank_mask([1, 1], 4),
                  failover=plan0)
        iref, vref = np.asarray(ref.ids), np.asarray(ref.distances)
        # warm both bucket shapes BEFORE the audit mark
        for b in buckets:
            jax.block_until_ready(run(
                jnp.zeros((b, q.shape[1]), jnp.float32),
                shard_mask=host_rank_mask([1, 1], 4), failover=plan0,
            ))
        fn = created[0]
        size0 = fn._cache_size()

        ex = ServingExecutor(
            run, buckets, dim=q.shape[1], flush_age_s=0.0,
            max_in_flight=2,
            runtime_inputs={
                "shard_mask": host_rank_mask([1, 1], 4),
                "failover": plan0,
            },
        )
        results = []

        def wave():
            futs = [
                (list(range(s, s + m)), ex.submit(q[s:s + m]))
                for s, m in ((0, 5), (5, 3), (8, 8), (0, 16))
            ]
            for rows, fut in futs:
                results.append((rows, fut.result(timeout=120)))

        try:
            wave()                                   # healthy traffic
            # host 1 dies mid-stream: all 4 of its chips at once
            host_alive = [1, 0]
            ex.set_runtime(
                shard_mask=host_rank_mask(host_alive, 4),
                failover=FailoverPlan.from_host_health(
                    placement, host_alive
                ),
            )
            wave()                                   # degraded traffic
            ex.set_runtime(shard_mask=host_rank_mask([1, 1], 4),
                           failover=plan0)
            wave()                                   # healed traffic
            st = ex.stats()
        finally:
            ex.close()

        assert st.completed == len(results) and st.failed == 0
        for rows, res in results:
            np.testing.assert_array_equal(np.asarray(res.coverage), 1.0)
            assert bool(np.asarray(res.row_valid).all())
            np.testing.assert_array_equal(res.ids, iref[rows])
            np.testing.assert_array_equal(res.distances, vref[rows])
        assert all(f is fn for f in created), \
            "the stream must reuse the one compiled hierarchical program"
        assert fn._cache_size() == size0, \
            "host die -> failover -> heal through the executor must " \
            "not retrace"


# ---------------------------------------------------------------------------
# the bench row at a tiny config — coverage of bench/bench_mnmg.py's
# cross_host harness on every CPU run (ISSUE 9 satellite)
# ---------------------------------------------------------------------------


def test_cross_host_bench_row_tiny_config():
    """cross_host_row on a tiny 8-device host-sim geometry: both QPS
    measurements land, the DCN byte model carries the >= 4x acceptance,
    and the in-row host die -> failover -> heal audit reports zero
    retraces with coverage 1.0 and bit-identical results."""
    from bench.bench_mnmg import cross_host_row

    row = cross_host_row(
        n=2048, d=8, nq=16, k=4, n_probes=4, n_lists=8,
        chain=(1, 3), escalate=0,
    )
    assert "error" not in row, row
    assert row["metric"].startswith("mnmg_cross_host_2048x8")
    assert row["value"] > 0 and row["flat_e2e_qps"] > 0
    assert row["unit"] == "QPS"
    assert row["wire"] == "bf16"
    # 3.2x at the 2x4 host-sim shape — the >= 4x acceptance holds from
    # one REAL 8-chip host up (TestByteAccounting pins it); the bench
    # row reports its own geometry's model honestly
    assert row["dcn_bytes_ratio"] >= 3.0
    assert row["dcn_bytes_per_query"] < row["flat_dcn_bytes_per_query"]
    assert row["health_flip_retraces"] == 0
    assert row["coverage_host_down"] == 1.0
    assert row["host_down_bitident"] is True
    for key in ("merge_ms_hier", "merge_ms_flat", "spread", "repeats"):
        assert key in row, key


# ---------------------------------------------------------------------------
# hierarchical_allreduce pad-and-slice (ISSUE 9 satellite)
# ---------------------------------------------------------------------------


class TestHierarchicalAllreduce:
    @pytest.mark.parametrize("n0", [7, 1, 9])
    def test_odd_leading_dim_pads_and_slices(self, hier24, n0):
        """The old hard divisibility precondition is gone: an odd
        leading dim is padded with sum-neutral zero rows internally and
        sliced back — the result matches a plain flat psum."""
        from jax.sharding import PartitionSpec as P

        rng = np.random.default_rng(n0)
        x = rng.standard_normal((n0, 3)).astype(np.float32)

        def body(x_in):
            return hier24.hierarchical_allreduce(x_in)

        fn = jax.jit(hier24.shard_map(
            body, in_specs=P(None, None), out_specs=P(None, None),
        ))
        out = np.asarray(fn(jnp.asarray(x)))
        assert out.shape == x.shape
        np.testing.assert_allclose(out, 8.0 * x, rtol=1e-5)

    def test_divisible_path_unchanged(self, hier24):
        from jax.sharding import PartitionSpec as P

        x = np.arange(32, dtype=np.float32).reshape(8, 4)

        def body(x_in):
            return hier24.hierarchical_allreduce(x_in)

        fn = jax.jit(hier24.shard_map(
            body, in_specs=P(None, None), out_specs=P(None, None),
        ))
        np.testing.assert_allclose(np.asarray(fn(jnp.asarray(x))), 8.0 * x)
