"""Chaos suite for the resilience layer (raft_tpu/resilience/ +
raft_tpu/testing/faults.py) — every serving failure mode proven
end-to-end on the 8-device virtual CPU mesh, in tier-1:

* deadline-exceeded dispatch raises RaftTimeoutError; a retry succeeds
  WITHOUT recompiling (trace/dispatch counts audited);
* a fail_rank-masked shard yields a partial=True result whose valid
  entries exactly match a healthy search restricted to the surviving
  shards (parametrized over replication ∈ {1, 2});
* with R=2 replication and a FailoverPlan, a down rank's lists serve
  from their replica: coverage stays 1.0 and results are BIT-IDENTICAL
  to the healthy mesh, with zero retraces across the health flip;
* recover_rank restores a downed rank's slabs from a checkpoint and
  routing flips back — no rebuild;
* hedged dispatch beats an injected straggler deterministically;
  admission control sheds with RaftOverloadError, never collapses;
* a corrupt_bytes-damaged checkpoint raises CorruptIndexError naming
  the field, while an intact v1 (pre-manifest) file still loads;
* a batch with injected NaN rows returns finite top-k for all valid
  rows (and the empty answer, not garbage, for the poisoned ones);
* an index checkpoint restores onto a DIFFERENT mesh size via the
  place_index re-shard path with identical search results.

The failure-model rationale lives in docs/robustness.md.
"""

import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import errors
from raft_tpu.comms import (
    build_comms,
    mnmg_ivf_flat_build,
    mnmg_ivf_flat_search,
    mnmg_ivf_pq_build,
    mnmg_ivf_pq_search,
    place_index,
    recover_rank,
    replicate_index,
    reshard_index,
)
from raft_tpu.resilience import (
    AdmissionController,
    Deadline,
    FailoverPlan,
    HedgePolicy,
    PartialSearchResult,
    ReplicaPlacement,
    RetryPolicy,
    ShardHealth,
    dispatch_hedged,
    dispatch_with_deadline,
    health_check,
)
from raft_tpu.resilience.health import HealthProbe, HealthReport
from raft_tpu.spatial.ann import (
    IVFFlatParams,
    IVFPQParams,
    ivf_flat_build,
    load_index,
    save_index,
)
from raft_tpu.testing import faults
from tests.oracles import np_knn_ids


# ---------------------------------------------------------------------------
# Deadline / RetryPolicy primitives (no mesh)
# ---------------------------------------------------------------------------


class TestDeadline:
    def test_after_and_remaining(self):
        d = Deadline.after(30.0)
        assert d.bounded
        assert 0.0 < d.remaining() <= 30.0
        assert not d.expired()

    def test_unbounded(self):
        d = Deadline.unbounded()
        assert not d.bounded
        assert d.remaining() == float("inf")
        assert not d.expired()
        assert Deadline.after(None).remaining() == float("inf")

    def test_expired(self):
        d = Deadline.after(1e-6)
        import time

        time.sleep(0.01)
        assert d.expired() and d.remaining() == 0.0

    def test_nonpositive_rejected(self):
        with pytest.raises(ValueError):
            Deadline.after(0.0)


class TestRetryPolicy:
    def test_backoff_deterministic_and_bounded(self):
        p = RetryPolicy(
            base_delay_s=0.1, multiplier=2.0, max_delay_s=0.5,
            jitter_frac=0.25, seed=11,
        )
        q = RetryPolicy(
            base_delay_s=0.1, multiplier=2.0, max_delay_s=0.5,
            jitter_frac=0.25, seed=11,
        )
        for a in range(1, 8):
            assert p.backoff_s(a) == q.backoff_s(a)  # replayable
            # exponential base, clipped, +-25% jitter
            base = min(0.5, 0.1 * 2.0 ** (a - 1))
            assert 0.75 * base <= p.backoff_s(a) <= 1.25 * base

    def test_seed_decorrelates(self):
        a = RetryPolicy(seed=1).backoff_s(1)
        b = RetryPolicy(seed=2).backoff_s(1)
        assert a != b  # two replicas de-synchronize their retries

    def test_classification(self):
        p = RetryPolicy()
        assert p.is_retryable(errors.RaftTimeoutError("t"))
        assert p.is_retryable(TimeoutError())
        assert not p.is_retryable(errors.RaftLogicError("bad arg"))
        assert not p.is_retryable(RuntimeError("boom"))


# ---------------------------------------------------------------------------
# dispatch_with_deadline + inject_delay (the straggler scenario)
# ---------------------------------------------------------------------------


class TestDispatchWithDeadline:
    def test_timeout_raises(self):
        fn, audit = faults.inject_delay(5.0)
        with pytest.raises(errors.RaftTimeoutError):
            dispatch_with_deadline(fn, jnp.arange(4.0), timeout_s=0.1)
        assert audit.calls == 1  # no retry without a policy

    def test_timeout_not_a_valueerror(self):
        """The serving loop's `except ValueError` (bad request) handler
        must never swallow a deadline."""
        fn, _ = faults.inject_delay(5.0)
        with pytest.raises(errors.RaftTimeoutError):
            try:
                dispatch_with_deadline(fn, jnp.arange(4.0), timeout_s=0.1)
            except ValueError:  # pragma: no cover - the bug being tested
                pytest.fail("RaftTimeoutError was caught as ValueError")

    def test_retry_succeeds_without_recompile(self):
        """THE acceptance audit: attempt 1 times out, the retry
        re-dispatches the already-compiled program (one trace, two
        executions) and returns the right answer."""
        fn, audit = faults.inject_delay(5.0, first_n=1)
        x = jnp.arange(8.0)
        seen = []
        out = dispatch_with_deadline(
            fn, x, timeout_s=0.25,
            retry=RetryPolicy(max_attempts=3, base_delay_s=0.01),
            on_retry=lambda a, e, s: seen.append((a, type(e).__name__)),
        )
        np.testing.assert_allclose(np.asarray(out), np.arange(8.0))
        assert audit.traces == 1, "retry must reuse the compiled program"
        assert audit.dispatches == 2, "retry must actually re-execute"
        assert audit.calls == 2
        assert seen == [(1, "RaftTimeoutError")]

    def test_non_retryable_propagates_immediately(self):
        calls = []

        def bad(_x):
            calls.append(1)
            raise errors.RaftLogicError("malformed batch")

        with pytest.raises(ValueError, match="malformed batch"):
            dispatch_with_deadline(
                bad, jnp.arange(4.0), timeout_s=1.0,
                retry=RetryPolicy(max_attempts=5, base_delay_s=0.01),
            )
        assert len(calls) == 1  # classification stopped the retries

    def test_overall_deadline_caps_retries(self):
        fn, audit = faults.inject_delay(5.0)
        with pytest.raises(errors.RaftTimeoutError):
            dispatch_with_deadline(
                fn, jnp.arange(4.0), timeout_s=0.1,
                deadline=Deadline.after(0.3),
                retry=RetryPolicy(max_attempts=100, base_delay_s=0.01),
            )
        assert audit.calls < 100  # the budget, not max_attempts, stopped it


# ---------------------------------------------------------------------------
# ShardHealth + health_check
# ---------------------------------------------------------------------------


class TestShardHealth:
    def test_mark_and_mask(self):
        h = ShardHealth(4)
        assert h.all_up and h.n_up == 4
        h.mark_down(2)
        h.mark_down(2)  # idempotent
        assert not h.all_up and h.n_up == 3 and not h.is_up(2)
        np.testing.assert_array_equal(h.mask(), [1, 1, 0, 1])
        h.mark_up(2)
        assert h.all_up
        assert "down=none" in repr(h)

    def test_bad_rank_rejected(self):
        h = ShardHealth(2)
        with pytest.raises(ValueError):
            h.mark_down(2)
        with pytest.raises(ValueError):
            ShardHealth(0)

    def test_fail_rank_helper(self):
        h = faults.fail_rank(8, 1, 5)
        np.testing.assert_array_equal(h.mask(), [1, 0, 1, 1, 1, 0, 1, 1])
        h2 = faults.fail_rank(h, 0)
        assert h2 is h and not h.is_up(0)


def test_health_check_timed_sweep(comms8):
    report = health_check(comms8)
    assert report.ok and report.failed == []
    assert len(report.probes) == 10  # the full self-test registry
    assert all(p.seconds >= 0 for p in report.probes.values())
    assert report.total_seconds > 0


def test_health_check_failure_marks_all_down(comms8, monkeypatch):
    from raft_tpu.comms import self_test as st

    def torn_mesh(_comms):
        raise RuntimeError("simulated torn mesh")

    monkeypatch.setitem(st.SELF_TESTS, "allreduce", torn_mesh)
    h = ShardHealth(8)
    report = health_check(comms8, health=h)
    assert not report.ok and report.failed == ["allreduce"]
    assert h.n_up == 0  # a torn fabric serves no shard
    with pytest.raises(errors.RaftException, match="allreduce"):
        health_check(comms8, raise_on_failure=True)


# ---------------------------------------------------------------------------
# Degraded sharded search (both engines) on the 8-device mesh
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def comms8():
    return build_comms(jax.devices()[:8])


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(7)
    x = rng.standard_normal((512, 16)).astype(np.float32)
    q = x[::37][:12] + 0.05 * rng.standard_normal((12, 16)).astype(
        np.float32
    )
    return x, q


FLAT_PARAMS = IVFFlatParams(n_lists=8, kmeans_n_iters=4, seed=3)
K = 5


@pytest.fixture(scope="module")
def flat_index(comms8, dataset):
    x, _ = dataset
    return mnmg_ivf_flat_build(comms8, x, FLAT_PARAMS)


@pytest.fixture(scope="module", params=[
    ("flat_probe", 1), ("two_level_probe", 1),
    ("flat_probe", 2), ("two_level_probe", 2),
], ids=lambda p: f"{p[0]}-r{p[1]}")
def probed_index(request, comms8, flat_index):
    """The degraded-search suite runs under BOTH coarse probes (flat
    centroid scan vs two-level CoarseIndex) AND under replication ∈
    {1, 2}: the PartialSearchResult semantics (shard_mask with a down
    rank, owner=-1 probe-set extras, NaN query rows) must be identical
    in all four layouts — an unrouted replicated index serves primaries
    exactly like the unreplicated one."""
    probe, replication = request.param
    idx = flat_index
    if replication > 1:
        idx = place_index(comms8, idx, replication=replication)
    if probe == "two_level_probe":
        from raft_tpu.comms import attach_coarse_index

        idx = attach_coarse_index(idx, seed=0)
    return idx


@pytest.fixture(scope="module")
def replicated_flat(comms8, flat_index):
    """The R=2 striped replica layout of the flat suite's index."""
    return place_index(comms8, flat_index, replication=2)


def _rank_row_ids(index, rank):
    """GLOBAL row ids whose PRIMARY owner is ``rank`` (host-side, from
    the slab layout: the primary segment is the first nl_pad/R lists,
    so its rows are [0, list_offsets[rank, nl_pad/R]))."""
    offs = np.asarray(index.list_offsets)
    sids = np.asarray(index.sorted_ids)
    nlp_base = index.nl_pad // int(getattr(index, "replication", 1) or 1)
    return sids[rank, : offs[rank, nlp_base]]


def test_all_up_mask_matches_healthy_search(comms8, dataset, probed_index):
    x, q = dataset
    v0, i0 = mnmg_ivf_flat_search(
        comms8, probed_index, q, K, n_probes=8, qcap=q.shape[0]
    )
    res = mnmg_ivf_flat_search(
        comms8, probed_index, q, K, n_probes=8, qcap=q.shape[0],
        shard_mask=True,
    )
    assert isinstance(res, PartialSearchResult)
    assert res.partial is False
    np.testing.assert_array_equal(np.asarray(res.coverage), 1.0)
    assert np.asarray(res.row_valid).all()
    np.testing.assert_allclose(
        np.asarray(res.distances), np.asarray(v0), rtol=1e-6
    )
    np.testing.assert_array_equal(np.asarray(res.ids), np.asarray(i0))


def test_fail_rank_matches_surviving_shard_search(
    comms8, dataset, probed_index
):
    """THE degraded-search acceptance: with rank r down and every list
    probed, the partial result's valid entries exactly equal the exact
    top-k over the rows the SURVIVING shards own."""
    x, q = dataset
    # pick a rank that owns rows (they all do under LPT balance)
    dead = 2
    dead_ids = set(_rank_row_ids(probed_index, dead).tolist())
    assert dead_ids, "test premise: the dead rank owns rows"
    health = faults.fail_rank(ShardHealth(8), dead)
    res = mnmg_ivf_flat_search(
        comms8, probed_index, q, K, n_probes=8, qcap=q.shape[0],
        shard_mask=health,
    )
    assert res.partial is True
    cov = np.asarray(res.coverage)
    assert (cov < 1.0).any() and (cov >= 0.0).all()
    # oracle: exact search restricted to surviving rows (probe-everything
    # IVF-Flat == brute force over the surviving shards' union)
    alive_ids = np.array(
        sorted(set(range(x.shape[0])) - dead_ids), np.int64
    )
    want = alive_ids[np_knn_ids(x[alive_ids], q, K)]
    got_d = np.asarray(res.distances)
    got_i = np.asarray(res.ids)
    assert np.isfinite(got_d).all()  # >= K survivors everywhere
    np.testing.assert_array_equal(got_i, want)
    assert not (set(got_i.ravel().tolist()) & dead_ids)


def test_all_ranks_down_degrades_not_raises(comms8, dataset, probed_index):
    _, q = dataset
    res = mnmg_ivf_flat_search(
        comms8, probed_index, q, K, n_probes=8, qcap=q.shape[0],
        shard_mask=np.zeros(8, np.int32),
    )
    assert res.partial is True and res.min_coverage == 0.0
    assert np.isinf(np.asarray(res.distances)).all()
    assert (np.asarray(res.ids) == -1).all()


def test_nan_rows_neutralized(comms8, dataset, probed_index):
    """THE bad-input acceptance: poisoned rows cannot contaminate their
    batchmates — valid rows return the finite healthy answer, poisoned
    rows return the empty answer."""
    _, q = dataset
    bad_rows = [1, 4]
    qbad = faults.inject_nonfinite(q, bad_rows, kind="nan")
    qbad = faults.inject_nonfinite(qbad, [7], kind="inf")
    res = mnmg_ivf_flat_search(
        comms8, probed_index, qbad, K, n_probes=8, qcap=q.shape[0],
        shard_mask=True,
    )
    rv = np.asarray(res.row_valid)
    want_valid = np.ones(q.shape[0], bool)
    want_valid[[1, 4, 7]] = False
    np.testing.assert_array_equal(rv, want_valid)
    assert res.partial is True
    d, i = np.asarray(res.distances), np.asarray(res.ids)
    assert np.isfinite(d[rv]).all()
    assert np.isinf(d[~rv]).all() and (i[~rv] == -1).all()
    np.testing.assert_array_equal(np.asarray(res.coverage)[~rv], 0.0)
    # valid rows exactly match the healthy search of the same rows
    v0, i0 = mnmg_ivf_flat_search(
        comms8, probed_index, q, K, n_probes=8, qcap=q.shape[0]
    )
    np.testing.assert_array_equal(i[rv], np.asarray(i0)[rv])


def test_degraded_pq_engine(comms8, dataset):
    """The PQ engine shares the degraded contract (mask, +inf, coverage,
    sanitize) — spot-check all-up parity and a down rank."""
    x, q = dataset
    idx = mnmg_ivf_pq_build(
        comms8, x,
        IVFPQParams(n_lists=8, pq_dim=4, kmeans_n_iters=3, seed=5),
    )
    v0, i0 = mnmg_ivf_pq_search(comms8, idx, q, K, n_probes=8,
                                qcap=q.shape[0])
    res = mnmg_ivf_pq_search(
        comms8, idx, q, K, n_probes=8, qcap=q.shape[0], shard_mask=True,
    )
    assert res.partial is False
    np.testing.assert_array_equal(np.asarray(res.ids), np.asarray(i0))
    health = faults.fail_rank(ShardHealth(8), 0)
    res2 = mnmg_ivf_pq_search(
        comms8, idx, q, K, n_probes=8, qcap=q.shape[0], shard_mask=health,
    )
    assert res2.partial is True
    dead_ids = set(_rank_row_ids(idx, 0).tolist())
    live = np.asarray(res2.ids)[np.asarray(res2.ids) >= 0]
    assert not (set(live.ravel().tolist()) & dead_ids)


def test_warmup_resilient_variant(comms8, dataset, probed_index):
    _, q = dataset
    qc = probed_index.warmup(
        comms8, q.shape[0], k=K, n_probes=8, shard_mask=True
    )
    assert isinstance(qc, int) and qc >= 1


def test_probe_set_extras_identical_partial_semantics(
    comms8, dataset, probed_index
):
    """owner=-1 probe-set extras under a down rank: the degraded result
    (distances, ids, coverage, row_valid) must be IDENTICAL with the
    extras attached — unowned far-away centroids never enter any
    query's top probes — and identical under the two-level vs flat
    probe (expand_probe_set rebuilds an attached coarse index over the
    expanded set)."""
    from raft_tpu.comms import expand_probe_set

    _, q = dataset
    rng = np.random.default_rng(17)
    far = (1e4 + rng.standard_normal((64, 16))).astype(np.float32)
    eidx = expand_probe_set(probed_index, far)
    # the coarse index (when present) must cover the expanded set
    assert (eidx.coarse is not None) == (probed_index.coarse is not None)
    if eidx.coarse is not None:
        assert eidx.coarse.n_cents == int(eidx.owner.shape[0])
    health = faults.fail_rank(ShardHealth(8), 3)
    base = mnmg_ivf_flat_search(
        comms8, probed_index, q, K, n_probes=8, qcap=q.shape[0],
        shard_mask=health,
    )
    res = mnmg_ivf_flat_search(
        comms8, eidx, q, K, n_probes=8, qcap=q.shape[0],
        shard_mask=health,
    )
    assert isinstance(res, PartialSearchResult)
    np.testing.assert_array_equal(
        np.asarray(res.ids), np.asarray(base.ids)
    )
    np.testing.assert_allclose(
        np.asarray(res.distances), np.asarray(base.distances), rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(res.coverage), np.asarray(base.coverage)
    )
    np.testing.assert_array_equal(
        np.asarray(res.row_valid), np.asarray(base.row_valid)
    )


def test_two_level_probe_health_flip_zero_retrace(
    comms8, dataset, flat_index, monkeypatch
):
    """The recompile-hazard regression (trace/dispatch audit): with the
    two-level probe engaged, flipping ``shard_mask`` values at runtime
    triggers ZERO retraces of the compiled serving program, while
    flipping ``overprobe`` is a trace-time static (a DIFFERENT program,
    itself compiled once and reused across mask flips)."""
    from raft_tpu.comms import attach_coarse_index
    from raft_tpu.comms import mnmg_ivf_flat as mod

    _, q = dataset
    idx = attach_coarse_index(flat_index, seed=0)
    created = []
    orig = mod._cached_search

    def recording(*a, **k):
        fn = orig(*a, **k)
        created.append(fn)
        return fn

    monkeypatch.setattr(mod, "_cached_search", recording)
    kw = dict(n_probes=8, qcap=q.shape[0])
    m_up = np.ones(8, np.int32)
    m_one = m_up.copy()
    m_one[3] = 0
    m_two = m_up.copy()
    m_two[1] = m_two[6] = 0
    mod.mnmg_ivf_flat_search(comms8, idx, q, K, shard_mask=m_up, **kw)
    fn = created[0]
    size0 = fn._cache_size()
    for mask in (m_one, m_two, m_up):
        mod.mnmg_ivf_flat_search(comms8, idx, q, K, shard_mask=mask, **kw)
    assert all(f is fn for f in created), \
        "health flips must reuse the cached program object"
    assert fn._cache_size() == size0, \
        "health flips must not retrace the compiled program"
    # overprobe flips at TRACE time: a distinct program...
    mod.mnmg_ivf_flat_search(
        comms8, idx, q, K, shard_mask=m_up, overprobe=3.0, **kw
    )
    fn2 = created[-1]
    assert fn2 is not fn
    size2 = fn2._cache_size()
    # ...that mask flips then reuse without retracing
    mod.mnmg_ivf_flat_search(
        comms8, idx, q, K, shard_mask=m_one, overprobe=3.0, **kw
    )
    assert created[-1] is fn2 and fn2._cache_size() == size2


# ---------------------------------------------------------------------------
# R-way replication + failover (resilience/replica.py)
# ---------------------------------------------------------------------------


class TestReplicaPlacement:
    def test_striped_holders_and_segments(self):
        p = ReplicaPlacement.striped(8, 2)     # default offset P//R = 4
        assert p.offset == 4
        assert p.holders(1) == (1, 5)
        assert p.segments(5) == (5, 1)
        assert p.memory_factor == 2
        p3 = ReplicaPlacement.striped(8, 3, offset=1)
        assert p3.holders(6) == (6, 7, 0)

    def test_colliding_offset_rejected(self):
        with pytest.raises(ValueError, match="collides"):
            ReplicaPlacement.striped(8, 2, offset=8)
        with pytest.raises(ValueError, match="collides"):
            ReplicaPlacement.striped(8, 3, offset=4)  # 2*4 % 8 == 0

    def test_replication_bounds(self):
        with pytest.raises(ValueError, match="replication"):
            ReplicaPlacement.striped(4, 5)
        with pytest.raises(ValueError, match="replication"):
            ReplicaPlacement.striped(4, 0)


class TestFailoverPlan:
    def test_healthy_routes_primaries(self):
        p = ReplicaPlacement.striped(8, 2)
        plan = FailoverPlan.from_health(p, True)
        np.testing.assert_array_equal(plan.route, np.zeros(8))
        assert plan.fully_covered
        np.testing.assert_array_equal(plan.serving_load(), np.ones(8))

    def test_single_failure_routes_to_replica(self):
        p = ReplicaPlacement.striped(8, 2)
        plan = FailoverPlan.from_health(p, faults.fail_rank(8, 2))
        assert plan.fully_covered
        assert plan.route[2] == 1 and plan.serving_rank(2) == 6
        assert (plan.route[np.arange(8) != 2] == 0).all()
        load = plan.serving_load()
        assert load[2] == 0 and load[6] == 2  # rank 6 carries both

    def test_whole_group_dead_unserved(self):
        p = ReplicaPlacement.striped(8, 2)
        plan = FailoverPlan.from_health(p, faults.fail_rank(8, 3, 7))
        # shards 3 and 7 share holders {3, 7}: both groups are dead
        assert not plan.fully_covered
        assert plan.unserved_shards == [3, 7]
        assert plan.serving_rank(3) == -1


def test_replicated_layout_geometry(flat_index, replicated_flat):
    base, rep = flat_index, replicated_flat
    assert rep.replication == 2 and rep.replica_offset == 4
    assert rep.nl_pad == 2 * base.nl_pad
    # primary segment 0 is byte-identical to the base layout (healthy
    # serving reads it with unchanged local ids/offsets), segment 1 is
    # the replica partner's primary
    szs_b = np.asarray(base.list_sizes)
    szs_r = np.asarray(rep.list_sizes)
    for r in range(8):
        np.testing.assert_array_equal(szs_r[r, : base.nl_pad], szs_b[r])
        np.testing.assert_array_equal(
            szs_r[r, base.nl_pad:], szs_b[(r - 4) % 8]
        )
    # every rank's replica segment carries its partner's primary rows
    for r in range(8):
        partner = (r - 4) % 8
        prim = np.sort(_rank_row_ids(rep, r))
        np.testing.assert_array_equal(
            prim, np.sort(_rank_row_ids(flat_index, r))
        )
        offs = np.asarray(rep.list_offsets)
        sids = np.asarray(rep.sorted_ids)
        seg1 = sids[r, offs[r, base.nl_pad]: offs[r, -1]]
        np.testing.assert_array_equal(
            np.sort(seg1), np.sort(_rank_row_ids(flat_index, partner))
        )


def test_failover_full_coverage_bit_identical(
    comms8, dataset, replicated_flat
):
    """THE tentpole acceptance: R=2, any single rank down, failover
    routed — coverage 1.0 everywhere and results BIT-IDENTICAL to the
    healthy mesh."""
    _, q = dataset
    v0, i0 = mnmg_ivf_flat_search(
        comms8, replicated_flat, q, K, n_probes=8, qcap=q.shape[0]
    )
    placement = ReplicaPlacement.of_index(replicated_flat)
    for dead in range(8):
        health = faults.fail_rank(ShardHealth(8), dead)
        plan = FailoverPlan.from_health(placement, health)
        assert plan.fully_covered
        res = mnmg_ivf_flat_search(
            comms8, replicated_flat, q, K, n_probes=8, qcap=q.shape[0],
            shard_mask=health, failover=plan,
        )
        assert isinstance(res, PartialSearchResult)
        assert res.partial is False
        np.testing.assert_array_equal(np.asarray(res.coverage), 1.0)
        np.testing.assert_array_equal(np.asarray(res.ids), np.asarray(i0))
        np.testing.assert_array_equal(
            np.asarray(res.distances), np.asarray(v0)
        )


def test_failover_pq_engine_bit_identical(comms8, dataset):
    x, q = dataset
    idx = mnmg_ivf_pq_build(
        comms8, x,
        IVFPQParams(n_lists=8, pq_dim=4, kmeans_n_iters=3, seed=5),
    )
    v0, i0 = mnmg_ivf_pq_search(comms8, idx, q, K, n_probes=8,
                                qcap=q.shape[0])
    ridx = place_index(comms8, idx, replication=2)
    health = faults.fail_rank(ShardHealth(8), 5)
    plan = FailoverPlan.from_health(
        ReplicaPlacement.of_index(ridx), health
    )
    res = mnmg_ivf_pq_search(
        comms8, ridx, q, K, n_probes=8, qcap=q.shape[0],
        shard_mask=health, failover=plan,
    )
    assert res.partial is False and res.min_coverage == 1.0
    np.testing.assert_array_equal(np.asarray(res.ids), np.asarray(i0))
    np.testing.assert_array_equal(
        np.asarray(res.distances), np.asarray(v0)
    )


def test_whole_group_dead_degrades_partial(comms8, dataset,
                                           replicated_flat):
    """Both replicas of one group down: the plan routes -1 and the
    search degrades to the PR 3 partial path for exactly those lists."""
    _, q = dataset
    health = faults.fail_rank(ShardHealth(8), 1, 5)  # group {1, 5}
    plan = FailoverPlan.from_health(
        ReplicaPlacement.of_index(replicated_flat), health
    )
    assert plan.unserved_shards == [1, 5]
    res = mnmg_ivf_flat_search(
        comms8, replicated_flat, q, K, n_probes=8, qcap=q.shape[0],
        shard_mask=health, failover=plan,
    )
    assert res.partial is True
    cov = np.asarray(res.coverage)
    assert (cov < 1.0).any()
    dead_ids = set(_rank_row_ids(replicated_flat, 1).tolist()) | set(
        _rank_row_ids(replicated_flat, 5).tolist()
    )
    live = np.asarray(res.ids)[np.asarray(res.ids) >= 0]
    assert not (set(live.ravel().tolist()) & dead_ids)


def test_failover_flip_zero_retrace(comms8, dataset, replicated_flat,
                                    monkeypatch):
    """THE zero-retrace acceptance across failover flips: health down →
    replica serves → health up, all against ONE compiled program (route
    and mask are runtime inputs)."""
    from raft_tpu.comms import mnmg_ivf_flat as mod

    _, q = dataset
    created = []
    orig = mod._cached_search

    def recording(*a, **k):
        fn = orig(*a, **k)
        created.append(fn)
        return fn

    monkeypatch.setattr(mod, "_cached_search", recording)
    placement = ReplicaPlacement.of_index(replicated_flat)
    kw = dict(n_probes=8, qcap=q.shape[0])
    health = ShardHealth(8)
    plan_up = FailoverPlan.from_health(placement, health)
    r0 = mod.mnmg_ivf_flat_search(
        comms8, replicated_flat, q, K, shard_mask=health,
        failover=plan_up, **kw,
    )
    fn = created[0]
    size0 = fn._cache_size()
    # rank 3 dies; its shard serves from the replica on rank 7
    health.mark_down(3)
    plan_down = FailoverPlan.from_health(placement, health)
    r1 = mod.mnmg_ivf_flat_search(
        comms8, replicated_flat, q, K, shard_mask=health,
        failover=plan_down, **kw,
    )
    # rank 3 heals; route flips back to the primary
    health.mark_up(3)
    r2 = mod.mnmg_ivf_flat_search(
        comms8, replicated_flat, q, K, shard_mask=health,
        failover=FailoverPlan.from_health(placement, health), **kw,
    )
    assert all(f is fn for f in created), \
        "failover flips must reuse the cached program object"
    assert fn._cache_size() == size0, \
        "failover flips must not retrace the compiled program"
    for r in (r1, r2):
        assert r.partial is False
        np.testing.assert_array_equal(
            np.asarray(r.ids), np.asarray(r0.ids)
        )
        np.testing.assert_array_equal(
            np.asarray(r.distances), np.asarray(r0.distances)
        )


def test_open_loop_executor_failover_chaos(comms8, dataset,
                                           replicated_flat, monkeypatch,
                                           tmp_path):
    """ISSUE 8 chaos acceptance: ONE open-loop executor serves a
    request stream through a mid-stream rank failure with R=2 — the
    hedge covers the straggling batches, the FailoverPlan route flows
    in as a runtime input, every answer stays bit-identical to the
    healthy mesh at coverage 1.0, and the compiled program never
    retraces.

    ISSUE 13 extension: a FlightRecorder rides the same executor, and
    its dump must tell the postmortem story — the straggling batch the
    hedge covered, the backup winning the race, and the failover flip's
    route — while the live retrace census reads the same program count
    the trace audit pins."""
    from raft_tpu.comms import mnmg_ivf_flat as mod
    from raft_tpu.obs import FlightRecorder, program_census
    from raft_tpu.serving import ServingExecutor

    _, q = dataset                                   # (12, 16) queries
    qcap = q.shape[0]
    buckets = (4, 8)
    created = []
    orig = mod._cached_search

    def recording(*a, **k):
        fn = orig(*a, **k)
        created.append(fn)
        return fn

    monkeypatch.setattr(mod, "_cached_search", recording)
    placement = ReplicaPlacement.of_index(replicated_flat)
    health = ShardHealth(8)

    def run(qq, shard_mask=None, failover=None):
        return mod.mnmg_ivf_flat_search(
            comms8, replicated_flat, qq, K, n_probes=8, qcap=qcap,
            shard_mask=shard_mask if shard_mask is not None
            else np.ones(8, np.int32),
            failover=failover,
        )

    # healthy reference + warm both bucket shapes BEFORE the audit mark
    plan0 = FailoverPlan.from_health(placement, health)
    ref = run(jnp.asarray(q), shard_mask=health.mask(), failover=plan0)
    vref, iref = np.asarray(ref.distances), np.asarray(ref.ids)
    for b in buckets:
        jax.block_until_ready(run(
            jnp.zeros((b, q.shape[1]), jnp.float32),
            shard_mask=health.mask(), failover=plan0,
        ))
    fn = created[0]
    size0 = fn._cache_size()

    straggler_s = 1.0
    primary, audit = faults.inject_straggler(run, every=3,
                                             seconds=straggler_s)
    recorder = FlightRecorder(1024, dump_dir=str(tmp_path),
                              name="chaos")
    ex = ServingExecutor(
        primary, buckets, dim=q.shape[1], flush_age_s=0.0,
        max_in_flight=2, hedge=0.02, backup_dispatch=run,
        runtime_inputs={"shard_mask": health.mask(), "failover": plan0},
        flight=recorder,
    )
    lat_ms = []
    results = []

    def drain(futs):
        for rows, fut, t0 in futs:
            res = fut.result(timeout=60)
            lat_ms.append((time.monotonic() - t0) * 1e3)
            results.append((rows, res))

    def submit_wave():
        futs = []
        for start, m in ((0, 3), (3, 2), (5, 3), (8, 4), (0, 8), (8, 2)):
            futs.append((
                list(range(start, start + m)),
                ex.submit(q[start:start + m]),
                time.monotonic(),
            ))
        return futs

    drain(submit_wave())                              # healthy traffic
    # rank 3 dies MID-STREAM: route its shard to the replica via the
    # executor's runtime inputs — later dispatches pick it up, nothing
    # recompiles
    faults.fail_rank(health, 3)
    plan = FailoverPlan.from_health(placement, health)
    assert plan.fully_covered
    ex.set_runtime(shard_mask=health.mask(), failover=plan)
    drain(submit_wave())                              # degraded traffic
    # rank 3 heals; primary routing resumes
    health.mark_up(3)
    ex.set_runtime(shard_mask=health.mask(),
                   failover=FailoverPlan.from_health(placement, health))
    drain(submit_wave())
    st = ex.stats()
    ex.close()

    assert st.completed == len(results) and st.failed == 0
    # hedge engaged on the injected stragglers (every 3rd batch)
    assert st.hedged_batches >= 1 and st.backup_wins >= 1
    # bounded tail THROUGH the failure: the straggling batches resolve
    # via the backup at ~hedge_delay + service, well under the 1 s
    # straggle the unhedged path would eat
    assert max(lat_ms) < 0.9 * straggler_s * 1e3, max(lat_ms)
    # every answer bit-identical to the healthy mesh at coverage 1.0
    for rows, res in results:
        np.testing.assert_array_equal(np.asarray(res.coverage), 1.0)
        assert bool(np.asarray(res.row_valid).all())
        np.testing.assert_array_equal(res.ids, iref[rows])
        np.testing.assert_array_equal(res.distances, vref[rows])
    # zero retraces across warm → fail → failover → heal, incl. hedges
    assert all(f is fn for f in created), \
        "the open-loop stream must reuse the cached program object"
    assert fn._cache_size() == size0, \
        "health/failover flips through the executor must not retrace"
    # the LIVE retrace gauge reads the same program count the trace
    # audit just pinned — the zero-retrace contract as a runtime metric
    census = program_census({"mnmg_ivf_flat._cached_search": fn})
    assert census["mnmg_ivf_flat._cached_search"] == size0

    # -- the flight-recorder postmortem (ISSUE 13 acceptance) ---------
    # the dump must NAME (a) the straggling batch the hedge covered,
    # (b) the hedge winner, (c) the failover flip's route
    hedges = recorder.events(event="hedge")
    assert hedges, "the injected stragglers must appear as hedge events"
    straggler_batch = hedges[0]["batch_id"]
    assert hedges[0]["age_ms"] >= 0.02 * 1e3 * 0.5
    wins = [e for e in recorder.events(event="demux")
            if e["winner"] == "backup"]
    assert wins, "a backup win must be attributed in the recorder"
    flips = [e for e in recorder.events(event="runtime_update")
             if "failover_route" in e]
    # the mid-stream flip routes rank 3's shard to replica copy 1
    # (and the heal routes it back to 0)
    assert any(e["failover_route"][3] == 1 for e in flips)
    assert any(e["failover_route"][3] == 0 for e in flips)
    path = recorder.dump("chaos-postmortem")
    lines = [json.loads(ln) for ln in open(path)]
    assert lines[0]["reason"] == "chaos-postmortem"
    dumped = {ln.get("event") for ln in lines[1:]}
    assert {"hedge", "demux", "runtime_update"} <= dumped
    assert any(ln.get("event") == "dispatch"
               and ln.get("batch_id") == straggler_batch
               for ln in lines[1:]), \
        "the dump must show the straggling batch's dispatch"


def test_failover_requires_shard_mask(comms8, dataset, replicated_flat):
    _, q = dataset
    plan = FailoverPlan.from_health(
        ReplicaPlacement.of_index(replicated_flat), True
    )
    with pytest.raises(ValueError, match="shard_mask"):
        mnmg_ivf_flat_search(
            comms8, replicated_flat, q, K, n_probes=8, qcap=q.shape[0],
            failover=plan,
        )


def test_failover_plan_geometry_mismatch_rejected(
    comms8, dataset, replicated_flat
):
    _, q = dataset
    bad = FailoverPlan.from_health(ReplicaPlacement.striped(8, 2, 1), True)
    with pytest.raises(ValueError, match="does not match"):
        mnmg_ivf_flat_search(
            comms8, replicated_flat, q, K, n_probes=8, qcap=q.shape[0],
            shard_mask=True, failover=bad,
        )


def test_replicated_checkpoint_roundtrip_and_reshard(
    comms8, dataset, replicated_flat, tmp_path
):
    """A replicated checkpoint round-trips (layout statics preserved)
    and restores onto a smaller mesh with replication re-applied."""
    _, q = dataset
    v0, i0 = mnmg_ivf_flat_search(
        comms8, replicated_flat, q, K, n_probes=8, qcap=q.shape[0]
    )
    p = tmp_path / "replicated.npz"
    save_index(replicated_flat, p)
    back = load_index(p, comms=comms8)
    assert back.replication == 2 and back.nl_pad == replicated_flat.nl_pad
    v1, i1 = mnmg_ivf_flat_search(
        comms8, back, q, K, n_probes=8, qcap=q.shape[0]
    )
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    comms4 = build_comms(jax.devices()[:4])
    idx4 = place_index(comms4, back, replication=2)
    assert idx4.sorted_ids.shape[0] == 4 and idx4.replication == 2
    plan4 = FailoverPlan.from_health(
        ReplicaPlacement.of_index(idx4), faults.fail_rank(4, 0)
    )
    res4 = mnmg_ivf_flat_search(
        comms4, idx4, q, K, n_probes=8, qcap=q.shape[0],
        shard_mask=faults.fail_rank(4, 0), failover=plan4,
    )
    assert res4.partial is False
    np.testing.assert_array_equal(np.asarray(res4.ids), np.asarray(i0))


def test_recover_rank_full_cycle(comms8, dataset, replicated_flat,
                                 tmp_path):
    """The heal path end-to-end: rank dies → failover serves (identical
    results) → replacement rank restores its slabs from the checkpoint
    (recover_rank) → health up, route back → healthy serving, all
    results identical throughout."""
    import dataclasses as dc

    _, q = dataset
    v0, i0 = mnmg_ivf_flat_search(
        comms8, replicated_flat, q, K, n_probes=8, qcap=q.shape[0]
    )
    p = tmp_path / "ckpt.npz"
    save_index(replicated_flat, p)
    placement = ReplicaPlacement.of_index(replicated_flat)
    dead = 6
    health = faults.fail_rank(ShardHealth(8), dead)
    # the dead rank's slab content is LOST (zeroed) — only the replica
    # and the checkpoint still hold its lists
    wrecked = dc.replace(
        replicated_flat,
        vectors_sorted=jnp.zeros_like(
            jnp.asarray(replicated_flat.vectors_sorted)
        ).at[np.arange(8) != dead].set(
            jnp.asarray(replicated_flat.vectors_sorted)[
                np.arange(8) != dead
            ]
        ),
        sorted_ids=jnp.asarray(replicated_flat.sorted_ids)
        .at[dead].set(0),
    )
    plan = FailoverPlan.from_health(placement, health)
    res = mnmg_ivf_flat_search(
        comms8, wrecked, q, K, n_probes=8, qcap=q.shape[0],
        shard_mask=health, failover=plan,
    )
    assert res.partial is False
    np.testing.assert_array_equal(np.asarray(res.ids), np.asarray(i0))
    # replacement chip joins: restore the slabs, flip health + route
    healed = recover_rank(comms8, wrecked, p, dead)
    np.testing.assert_array_equal(
        np.asarray(healed.sorted_ids)[dead],
        np.asarray(replicated_flat.sorted_ids)[dead],
    )
    health.mark_up(dead)
    plan_back = FailoverPlan.from_health(placement, health)
    np.testing.assert_array_equal(plan_back.route, np.zeros(8))
    res2 = mnmg_ivf_flat_search(
        comms8, healed, q, K, n_probes=8, qcap=q.shape[0],
        shard_mask=health, failover=plan_back,
    )
    assert res2.partial is False
    np.testing.assert_array_equal(np.asarray(res2.ids), np.asarray(i0))
    np.testing.assert_array_equal(
        np.asarray(res2.distances), np.asarray(v0)
    )


def test_recover_rank_layout_mismatch_rejected(
    comms8, flat_index, replicated_flat, tmp_path
):
    p = tmp_path / "base.npz"
    save_index(flat_index, p)      # unreplicated checkpoint
    with pytest.raises(ValueError, match="not a checkpoint of this build"):
        recover_rank(comms8, replicated_flat, p, 0)


def test_replicate_index_rejects_replicated_input(replicated_flat):
    with pytest.raises(ValueError, match="already"):
        replicate_index(replicated_flat, 2)


# ---------------------------------------------------------------------------
# Hedged dispatch (resilience/deadline.py) — the straggler tail
# ---------------------------------------------------------------------------


class TestDispatchHedged:
    def test_backup_wins_on_straggler_without_recompile(self):
        """Primary straggles past the hedge delay → the backup is
        dispatched from the SAME compiled program and wins,
        deterministically."""
        fn, audit = faults.inject_delay(5.0, first_n=1)
        pol = HedgePolicy(default_delay_s=0.05, min_samples=100)
        out = dispatch_hedged(fn, jnp.arange(8.0), hedge=pol)
        np.testing.assert_allclose(np.asarray(out), np.arange(8.0))
        assert audit.traces == 1, "hedge must reuse the compiled program"
        assert audit.calls == 2 and audit.dispatches == 2
        assert pol.hedges == 1 and pol.backup_wins == 1
        assert pol.primary_wins == 0 and pol.unhedged == 0

    def test_fast_primary_never_hedges(self):
        fn, audit = faults.inject_delay(0.0)
        pol = HedgePolicy(default_delay_s=0.25, min_samples=100)
        out = dispatch_hedged(fn, jnp.arange(4.0), hedge=pol)
        np.testing.assert_allclose(np.asarray(out), np.arange(4.0))
        assert audit.calls == 1 and pol.hedges == 0
        assert pol.unhedged == 1 and pol.n_samples == 1

    def test_backup_fn_used_for_the_hedge(self):
        slow, _ = faults.inject_delay(5.0)
        fast_calls = []

        def fast(x):
            fast_calls.append(1)
            return jnp.asarray(x) * 1.0

        out = dispatch_hedged(slow, jnp.arange(4.0), hedge=0.02,
                              backup_fn=fast)
        np.testing.assert_allclose(np.asarray(out), np.arange(4.0))
        assert fast_calls == [1]

    def test_deadline_bounds_both_dispatches(self):
        fn, audit = faults.inject_delay(5.0)   # every call straggles
        with pytest.raises(errors.RaftTimeoutError):
            dispatch_hedged(fn, jnp.arange(4.0), hedge=0.02,
                            timeout_s=0.15)
        assert audit.calls == 2                # it DID hedge, then gave up

    def test_policy_percentile_adapts(self):
        pol = HedgePolicy(percentile=50.0, min_samples=2,
                          min_delay_s=0.0, max_delay_s=9.0)
        assert pol.hedge_delay_s() == pol.default_delay_s  # cold
        for s in (0.1, 0.2, 0.3):
            pol.record(s)
        assert abs(pol.hedge_delay_s() - 0.2) < 1e-9
        clamped = HedgePolicy(percentile=50.0, min_samples=1,
                              min_delay_s=0.5, max_delay_s=1.0)
        clamped.record(0.01)
        assert clamped.hedge_delay_s() == 0.5

    def test_policy_validation(self):
        with pytest.raises(ValueError):
            HedgePolicy(percentile=0.0)
        with pytest.raises(ValueError):
            HedgePolicy(min_delay_s=2.0, max_delay_s=1.0)

    def test_inject_straggler_schedule(self):
        calls = []

        def f(x):
            calls.append(x)
            return x

        wrapped, audit = faults.inject_straggler(f, every=3, seconds=0.01)
        outs = [wrapped(i) for i in range(6)]
        assert audit.calls == 6 and audit.dispatches == 2
        assert isinstance(outs[2], faults.DelayedReady)
        assert isinstance(outs[5], faults.DelayedReady)
        assert not isinstance(outs[0], faults.DelayedReady)


# ---------------------------------------------------------------------------
# Admission control (resilience/admission.py) — shed, never collapse
# ---------------------------------------------------------------------------


class TestAdmissionControl:
    def test_sheds_when_queue_full(self):
        ctrl = AdmissionController(max_concurrent=1, max_queue=0,
                                   retry_after_s=0.5)
        with ctrl.admit():
            with pytest.raises(errors.RaftOverloadError) as ei:
                with ctrl.admit():
                    pass  # pragma: no cover
            assert ei.value.retry_after_s == 0.5
            assert not isinstance(ei.value, ValueError)  # loud, typed
        st = ctrl.stats()
        assert st.admitted == 1 and st.shed_queue == 1
        assert st.shed == 1 and st.offered == 2
        assert abs(st.shed_fraction - 0.5) < 1e-9

    def test_queued_request_admitted_when_slot_frees(self):
        ctrl = AdmissionController(max_concurrent=1, max_queue=2)
        release = threading.Event()
        admitted = threading.Event()

        def holder():
            with ctrl.admit():
                admitted.set()
                release.wait(5.0)

        th = threading.Thread(target=holder)
        th.start()
        admitted.wait(5.0)
        got = []

        def waiter():
            with ctrl.admit(timeout_s=5.0):
                got.append(1)

        tw = threading.Thread(target=waiter)
        tw.start()
        time.sleep(0.05)
        assert ctrl.queue_depth == 1 and not got
        release.set()
        tw.join(5.0)
        th.join(5.0)
        assert got == [1]
        st = ctrl.stats()
        assert st.admitted == 2 and st.shed == 0
        assert st.peak_queue_depth == 1 and st.queue_depth == 0

    def test_unbounded_deadline_waits_instead_of_overflowing(self):
        """Deadline.unbounded()/after(None) through admit() must mean
        'wait forever', not Condition.wait(inf) -> OverflowError."""
        ctrl = AdmissionController(max_concurrent=1, max_queue=2)
        release = threading.Event()
        admitted = threading.Event()

        def holder():
            with ctrl.admit():
                admitted.set()
                release.wait(5.0)

        th = threading.Thread(target=holder)
        th.start()
        admitted.wait(5.0)
        got = []

        def waiter():
            with ctrl.admit(deadline=Deadline.unbounded()):
                got.append(1)

        tw = threading.Thread(target=waiter)
        tw.start()
        time.sleep(0.05)
        assert not got and ctrl.queue_depth == 1  # queued, not crashed
        release.set()
        tw.join(5.0)
        th.join(5.0)
        assert got == [1]

    def test_timeout_while_queued_is_timeout_not_overload(self):
        ctrl = AdmissionController(max_concurrent=1, max_queue=2)
        release = threading.Event()

        def holder():
            with ctrl.admit():
                release.wait(5.0)

        th = threading.Thread(target=holder)
        th.start()
        time.sleep(0.05)
        with pytest.raises(errors.RaftTimeoutError):
            with ctrl.admit(timeout_s=0.05):
                pass  # pragma: no cover
        release.set()
        th.join(5.0)
        assert ctrl.stats().timed_out == 1

    def test_token_limiter_deterministic_clock(self):
        t = [0.0]
        ctrl = AdmissionController(max_concurrent=4, max_queue=4,
                                   rate=2.0, burst=2, clock=lambda: t[0])
        with ctrl.admit():
            pass
        with ctrl.admit():
            pass
        with pytest.raises(errors.RaftOverloadError) as ei:
            with ctrl.admit():
                pass  # pragma: no cover
        assert 0.0 < ei.value.retry_after_s <= 0.5  # next token at rate 2/s
        t[0] = 0.6                                  # refill > 1 token
        with ctrl.admit():
            pass
        st = ctrl.stats()
        assert st.shed_rate == 1 and st.admitted == 3

    def test_retry_after_priced_from_measured_service(self):
        ctrl = AdmissionController(max_concurrent=1, max_queue=0)
        with ctrl.admit():
            time.sleep(0.05)             # measurable service time
        with ctrl.admit():               # in flight again
            with pytest.raises(errors.RaftOverloadError) as ei:
                with ctrl.admit():
                    pass  # pragma: no cover
        assert ei.value.retry_after_s is not None
        assert ei.value.retry_after_s > 0.0

    def test_retry_after_occupancy_floors_stale_ewma(self):
        """ISSUE 8 satellite regression: the service-time EWMA only
        moves on COMPLETIONS, so a burst after an idle stretch used to
        price retry_after_s from stale history while the in-flight
        occupancy already showed service had slowed. The age of the
        oldest in-flight request must floor the estimate (injectable
        clock, fully deterministic)."""
        t = [0.0]
        ctrl = AdmissionController(max_concurrent=1, max_queue=1,
                                   clock=lambda: t[0])
        # one fast completion seeds a tiny (soon stale) EWMA
        with ctrl.admit():
            t[0] += 0.001
        # a request enters service... and runs for 10 s (the regression
        # scenario: service slowed, nothing has completed since)
        ctrl.enqueue()
        ticket = ctrl.begin_service()
        t[0] += 10.0
        ctrl.enqueue()                        # fills the queue (1/1)
        with pytest.raises(errors.RaftOverloadError) as ei:
            ctrl.enqueue()                    # burst arrival: shed
        # priced from the 10 s occupancy evidence, NOT the 1 ms EWMA:
        # (1 waiter + 1 in flight) * max(ewma, oldest in-flight age)
        assert ei.value.retry_after_s == pytest.approx(20.0)
        # completion folds the observed slow service into the EWMA
        ctrl.finish_service(ticket)
        assert ctrl._service_ewma_s == pytest.approx(
            0.8 * 0.001 + 0.2 * 10.0
        )
        st = ctrl.stats()
        assert st.in_flight == 0 and st.queue_depth == 1
        ctrl.cancel_queued()
        assert ctrl.stats().queue_depth == 0

    def test_async_triple_counters_and_shed(self):
        """The executor's non-blocking path: enqueue never waits,
        begin/finish move the gauges, sheds beyond the TOTAL capacity
        (queued + in service vs max_queue + max_concurrent)."""
        ctrl = AdmissionController(max_concurrent=2, max_queue=0)
        ctrl.enqueue()
        ctrl.enqueue()
        with pytest.raises(errors.RaftOverloadError):
            ctrl.enqueue()                    # 2 outstanding == capacity
        tk = ctrl.begin_service(2)            # one micro-batch of 2
        st = ctrl.stats()
        assert st.queue_depth == 0 and st.in_flight == 2
        assert st.admitted == 2 and st.shed_queue == 1
        with pytest.raises(errors.RaftOverloadError):
            ctrl.enqueue()                    # in-service still counts
        ctrl.finish_service(tk)
        ctrl.enqueue()                        # capacity freed
        ctrl.cancel_queued()
        st = ctrl.stats()
        assert st.in_flight == 0 and st.completed == 2
        with pytest.raises(ValueError):
            ctrl.begin_service(1)             # nothing queued

    def test_enqueue_idle_default_controller_admits(self):
        """A default controller (max_concurrent=1, max_queue=0) on an
        IDLE server must admit the async path's first request — the
        bound is total capacity, not raw queue depth (a free slot would
        have absorbed the request immediately in the blocking world)."""
        ctrl = AdmissionController()
        ctrl.enqueue()                        # no shed
        tk = ctrl.begin_service()
        with pytest.raises(errors.RaftOverloadError):
            ctrl.enqueue()                    # now genuinely full
        ctrl.finish_service(tk)
        ctrl.enqueue()                        # and free again
        ctrl.cancel_queued()

    def test_occupancy_floor_amortized_over_batch_ticket(self):
        """A service ticket covers a whole micro-batch: the occupancy
        floor must price PER REQUEST (batch age / n), not charge every
        queued request the full batch age (injectable clock)."""
        t = [0.0]
        ctrl = AdmissionController(max_concurrent=8, max_queue=8,
                                   clock=lambda: t[0])
        for _ in range(4):
            ctrl.enqueue()
        ctrl.begin_service(4)                 # one batch of 4
        t[0] += 0.08                          # in service 80 ms
        for _ in range(12):
            ctrl.enqueue()                    # fills capacity (16)
        with pytest.raises(errors.RaftOverloadError) as ei:
            ctrl.enqueue()
        # (12 waiters + 4 in flight) * (0.08 / 4) per request — NOT
        # * 0.08, which would price a ~0.16 s backlog at 1.28 s
        assert ei.value.retry_after_s == pytest.approx(16 * 0.02)

    def test_abort_service_frees_slot_without_ewma_or_completed(self):
        """A crashed dispatch releases its slot but is NOT service
        evidence: the near-zero held time must not drag the EWMA toward
        0 (underpricing every later shed) and its failed requests must
        not count as completed (injectable clock)."""
        t = [0.0]
        ctrl = AdmissionController(max_concurrent=2, max_queue=4,
                                   clock=lambda: t[0])
        # a real completion seeds the EWMA at 2 s
        ctrl.enqueue()
        tk = ctrl.begin_service()
        t[0] += 2.0
        ctrl.finish_service(tk)
        assert ctrl._service_ewma_s == pytest.approx(2.0)
        # a dispatch that fails immediately aborts its ticket
        ctrl.enqueue()
        tk2 = ctrl.begin_service()
        ctrl.abort_service(tk2)
        st = ctrl.stats()
        assert st.in_flight == 0 and st.completed == 1
        assert ctrl._service_ewma_s == pytest.approx(2.0)  # untouched

    def test_validation(self):
        with pytest.raises(ValueError):
            AdmissionController(max_concurrent=0)
        with pytest.raises(ValueError):
            AdmissionController(max_queue=-1)
        with pytest.raises(ValueError):
            AdmissionController(rate=0.0)


# ---------------------------------------------------------------------------
# HealthReport → ShardHealth pipeline (apply_report)
# ---------------------------------------------------------------------------


class TestApplyReport:
    def test_rank_attributed_failures_down_exactly_those(self):
        h = ShardHealth(8)
        report = HealthReport(probes={
            "heartbeat@2": HealthProbe(ok=False, seconds=0.1, ranks=(2,)),
            "heartbeat@5": HealthProbe(ok=False, seconds=0.1, ranks=(5,)),
            "allreduce": HealthProbe(ok=True, seconds=0.1),
        })
        out = h.apply_report(report)
        assert out is h                   # chainable: one-call pipeline
        np.testing.assert_array_equal(h.mask(), [1, 1, 0, 1, 1, 0, 1, 1])

    def test_unattributed_failure_downs_everything(self):
        h = ShardHealth(4)
        h.apply_report(HealthReport(probes={
            "allgather": HealthProbe(ok=False, seconds=0.1),
        }))
        assert h.n_up == 0

    def test_passing_report_marks_nothing(self):
        h = ShardHealth(4)
        h.mark_down(1)
        h.apply_report(HealthReport(probes={
            "allreduce": HealthProbe(ok=True, seconds=0.1),
        }))
        assert h.n_up == 3 and not h.is_up(1)  # no auto mark_up

    def test_resolve_shard_mask_accepts_report(self):
        from raft_tpu.resilience import resolve_shard_mask

        report = HealthReport(probes={
            "hb": HealthProbe(ok=False, seconds=0.0, ranks=(0, 3)),
        })
        np.testing.assert_array_equal(
            resolve_shard_mask(report, 4), [0, 1, 1, 0]
        )


# ---------------------------------------------------------------------------
# Checkpoint integrity (format v2) + mesh-size recovery
# ---------------------------------------------------------------------------


@pytest.fixture()
def small_index():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((256, 8)).astype(np.float32)
    return ivf_flat_build(
        x, IVFFlatParams(n_lists=4, kmeans_n_iters=3, seed=1)
    )


def test_roundtrip_carries_manifest(small_index, tmp_path):
    p = tmp_path / "idx.npz"
    save_index(small_index, p)
    with np.load(p) as npz:
        header = json.loads(bytes(npz["__header__"]).decode("utf-8"))
    # no coarse quantizer attached -> the writer stamps the LOWEST
    # version that represents the payload (older readers keep working)
    assert header["version"] == 2
    man = header["integrity"]
    assert "data_sorted" in man and "centroids" in man
    for entry in man.values():
        assert set(entry) == {"crc32", "shape", "dtype"}
    idx2 = load_index(p)
    np.testing.assert_allclose(
        np.asarray(idx2.centroids), np.asarray(small_index.centroids)
    )


def test_corrupt_bytes_names_the_field(small_index, tmp_path):
    """THE integrity acceptance: silent payload damage (container CRCs
    rewritten to match) is caught by the manifest and names the field."""
    p = tmp_path / "idx.npz"
    save_index(small_index, p)
    damaged = faults.corrupt_bytes(p, field="data_sorted", seed=3)
    assert damaged == "data_sorted"
    with pytest.raises(errors.CorruptIndexError, match="data_sorted") as ei:
        load_index(p)
    assert ei.value.field == "data_sorted"
    assert not isinstance(ei.value, ValueError)  # loud, not absorbable


def test_corrupt_bytes_random_field_deterministic(small_index, tmp_path):
    p = tmp_path / "idx.npz"
    save_index(small_index, p)
    damaged = faults.corrupt_bytes(p, seed=12)
    with pytest.raises(errors.CorruptIndexError) as ei:
        load_index(p)
    assert ei.value.field == damaged


def test_corrupt_header_caught(small_index, tmp_path):
    p = tmp_path / "idx.npz"
    save_index(small_index, p)
    raw = bytearray(p.read_bytes())
    raw[: len(raw) // 2] = os.urandom(len(raw) // 2)
    p.write_bytes(bytes(raw))
    with pytest.raises(errors.CorruptIndexError):
        load_index(p)


def test_v1_file_still_loads(small_index, tmp_path):
    """Read-compat: a pre-manifest (v1) checkpoint loads unverified."""
    from raft_tpu.spatial.ann import serialize

    arrays, static = {}, {}
    serialize._flatten(small_index, "", arrays, static)
    header = {"type": "ivf_flat", "version": 1, "static": static}
    p = tmp_path / "v1.npz"
    with open(p, "wb") as f:
        np.savez(
            f,
            __header__=np.frombuffer(
                json.dumps(header).encode("utf-8"), dtype=np.uint8
            ),
            **arrays,
        )
    idx = load_index(p)
    np.testing.assert_allclose(
        np.asarray(idx.centroids), np.asarray(small_index.centroids)
    )


def test_future_version_rejected(small_index, tmp_path):
    from raft_tpu.spatial.ann import serialize

    arrays, static = {}, {}
    serialize._flatten(small_index, "", arrays, static)
    header = {"type": "ivf_flat", "version": 99, "static": static}
    p = tmp_path / "v99.npz"
    with open(p, "wb") as f:
        np.savez(
            f,
            __header__=np.frombuffer(
                json.dumps(header).encode("utf-8"), dtype=np.uint8
            ),
            **arrays,
        )
    # ISSUE 7 satellite: a structured, version-NAMING rejection (a
    # CorruptIndexError, deliberately NOT a ValueError) — a rolled-back
    # reader must fail loudly instead of filling a newer checkpoint's
    # unknown fields from missing-key defaults
    with pytest.raises(errors.CorruptIndexError, match="99"):
        load_index(p)


def test_restore_onto_smaller_mesh(comms8, dataset, flat_index, tmp_path):
    """THE recovery acceptance: a sharded checkpoint built for 8 ranks
    restores onto a 4-rank mesh (a lost rank pair) through the
    place_index re-shard path with identical search results."""
    x, q = dataset
    v8, i8 = mnmg_ivf_flat_search(
        comms8, flat_index, q, K, n_probes=8, qcap=q.shape[0]
    )
    p = tmp_path / "sharded.npz"
    save_index(flat_index, p)
    comms4 = build_comms(jax.devices()[:4])
    idx4 = load_index(p, comms=comms4)  # mismatch -> host load + re-shard
    assert idx4.sorted_ids.shape[0] == 4
    v4, i4 = mnmg_ivf_flat_search(
        comms4, idx4, q, K, n_probes=8, qcap=q.shape[0]
    )
    np.testing.assert_allclose(np.asarray(v4), np.asarray(v8), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(i4), np.asarray(i8))
    # reshard preserves the content inventory exactly
    all8 = np.sort(
        np.concatenate([_rank_row_ids(flat_index, r) for r in range(8)])
    )
    all4 = np.sort(
        np.concatenate([_rank_row_ids(idx4, r) for r in range(4)])
    )
    np.testing.assert_array_equal(all8, all4)


def test_place_index_reshards_directly(comms8, dataset, flat_index):
    _, q = dataset
    comms2 = build_comms(jax.devices()[:2])
    idx2 = place_index(comms2, flat_index)
    assert idx2.sorted_ids.shape[0] == 2
    v8, i8 = mnmg_ivf_flat_search(
        comms8, flat_index, q, K, n_probes=8, qcap=q.shape[0]
    )
    v2, i2 = mnmg_ivf_flat_search(
        comms2, idx2, q, K, n_probes=8, qcap=q.shape[0]
    )
    np.testing.assert_array_equal(np.asarray(i2), np.asarray(i8))


def test_reshard_rejects_ownerless_index(comms8, flat_index):
    import dataclasses as dc

    bad = dc.replace(
        flat_index,
        owner=jnp.full_like(jnp.asarray(flat_index.owner), -1),
    )
    comms2 = build_comms(jax.devices()[:2])
    with pytest.raises(ValueError, match="owns no lists"):
        reshard_index(comms2, bad)
