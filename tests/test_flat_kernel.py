"""Pallas flat-scan engine (spatial/ann/flat_kernel) — tier-1 coverage.

The kernel body runs under ``interpret=True`` on the CPU test platform
(the tests/test_pq_kernel.py pattern), pinned bitwise against the
op-for-op lax mirror and a float oracle; the grouped flat searches'
``use_pallas=True`` path is then pinned against the legacy XLA scan.
Bit-identity between engines is asserted on INTEGER-EXACT inputs with a
SATURATED rerank pool: every f32 accumulation is then exact regardless
of order (the kernel's different rerank accumulation shape cannot
perturb values) and the pool covers every probed row (the bf16 scan
cannot perturb candidate selection), so ``(dists, ids)`` must match to
the bit — the contract flat_kernel's module docstring pins. Elsewhere
the sub-chunk cover argument guarantees recall non-inferiority only,
asserted separately. MNMG parity runs inside the fused one-dispatch
program with a zero-retrace health-flip audit, and the mutation tier's
tombstone ``row_mask`` is pinned at the kernel path's rerank tail.
"""

import dataclasses
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.spatial.ann import (
    IVFFlatParams, IVFSQParams, ivf_flat_build, ivf_sq_build,
)
from raft_tpu.spatial.ann import flat_kernel
from raft_tpu.spatial.ann.ivf_flat import (
    _resolve_scan_engine,
    ivf_flat_search_grouped,
)

K_NN = 5


def _rand_case(rng, lb, q, d, l_pad):
    # values on the bf16-exact integer grid: the mirror pin is bitwise,
    # but the oracle cross-check below wants operands the bf16 cast
    # cannot round
    qrows = jnp.asarray(
        rng.integers(-64, 64, (lb, q, d)), jnp.float32
    )
    slabs_t = jnp.asarray(
        rng.integers(-64, 64, (lb, d, l_pad)), jnp.float32
    )
    return qrows, slabs_t


def _oracle_subchunk_min(qrows, slabs_t, bounds):
    qv = np.asarray(qrows, np.float32)
    yv = np.asarray(slabs_t, np.float32)
    lb, q, d = qv.shape
    l_pad = yv.shape[2]
    out = np.empty((lb, q, l_pad), np.float32)
    for b in range(lb):
        qn = (qv[b] ** 2).sum(1)[:, None]
        yn = (yv[b] ** 2).sum(0)[None, :]
        out[b] = qn + yn - 2.0 * (qv[b] @ yv[b])
        lo, hi = int(bounds[b, 0]), int(bounds[b, 1])
        mask = np.zeros(l_pad, bool)
        mask[lo:hi] = True
        out[b] = np.where(mask[None, :], out[b], flat_kernel.BIG)
    sub = flat_kernel.SUBCHUNK
    return out.reshape(lb, q, l_pad // sub, sub).min(-1)


@pytest.mark.parametrize(
    "lb,q,d,l_pad,l_tile",
    [
        (3, 32, 16, 256, 128),   # two slab tiles per list
        (2, 16, 24, 128, 128),   # single tile, ragged d
        (1, 48, 8, 512, 256),    # wider tiles
    ],
)
def test_kernel_matches_lax_mirror_bitwise(rng_np, lb, q, d, l_pad,
                                           l_tile):
    """Interpret-mode kernel == lax mirror, bit for bit, masked rows
    included — the 'lax mirror pinned bitwise' acceptance pin."""
    qrows, slabs_t = _rand_case(rng_np, lb, q, d, l_pad)
    bounds = jnp.asarray(
        [[i, max(i, l_pad - 7 * i)] for i in range(lb)], jnp.int32
    )
    got = flat_kernel.flat_scan_subchunk_min(
        qrows, slabs_t, bounds, interpret=True, l_tile=l_tile
    )
    ref = flat_kernel.flat_scan_subchunk_min_lax(qrows, slabs_t, bounds)
    assert got.shape == (lb, q, l_pad // flat_kernel.SUBCHUNK)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    np.testing.assert_allclose(
        np.asarray(got), _oracle_subchunk_min(qrows, slabs_t, bounds),
        rtol=1e-6, atol=1e-4,
    )


def test_kernel_empty_and_full_ranges(rng_np):
    """lo == hi (empty list) -> every sub-chunk min is BIG; full range
    touches every row."""
    qrows, slabs_t = _rand_case(rng_np, 2, 16, 16, 256)
    bounds = jnp.asarray([[5, 5], [0, 256]], jnp.int32)
    got = np.asarray(flat_kernel.flat_scan_subchunk_min(
        qrows, slabs_t, bounds, interpret=True, l_tile=128
    ))
    assert (got[0] == flat_kernel.BIG).all()
    assert (got[1] < flat_kernel.BIG).all()


def test_plan_and_supported_predicates():
    assert flat_kernel.plan_l_tile(96, 48) is not None
    assert flat_kernel.flat_scan_supported(96, 48)
    # every planned tile is lane-aligned, even from a non-128-multiple
    # start and through budget-forced halvings (the pq_kernel review
    # regression, re-pinned here)
    for d in (8, 96, 4096):
        for start in (128, 384, 512):
            lt = flat_kernel.plan_l_tile(d, 64, l_tile=start)
            if lt is not None:
                assert lt % 128 == 0 and lt <= 512
    # absurd (d x qcap): one query block alone exceeds the VMEM budget
    assert not flat_kernel.flat_scan_supported(1 << 20, 512)
    assert not flat_kernel.flat_scan_supported(0, 8)
    with pytest.raises(ValueError, match="multiple"):
        flat_kernel.flat_scan_subchunk_min(
            jnp.zeros((1, 8, 16), jnp.float32),      # Q=8 not 16-aligned
            jnp.zeros((1, 16, 128), jnp.float32),
            jnp.zeros((1, 2), jnp.int32), interpret=True,
        )
    with pytest.raises(ValueError, match="dim"):
        flat_kernel.flat_scan_subchunk_min(
            jnp.zeros((1, 16, 16), jnp.float32),
            jnp.zeros((1, 24, 128), jnp.float32),    # slab dim mismatch
            jnp.zeros((1, 2), jnp.int32), interpret=True,
        )


# -- grouped search: engine equivalence --------------------------------------

def _int_dataset(seed, n=3000, d=16, nq=64):
    """Integer-exact clustered rows/queries (values on the bf16-exact
    grid, squared distances exact in f32 for ANY accumulation order) —
    what makes saturated-pool engine comparisons BIT-identical instead
    of last-ulp-identical (flat_kernel docstring)."""
    rng = np.random.default_rng(seed)
    centers = rng.integers(-60, 60, (8, d))
    x = (
        centers[rng.integers(0, 8, n)]
        + rng.integers(-6, 7, (n, d))
    ).astype(np.float32)
    q = (
        x[rng.integers(0, n, nq)] + rng.integers(-2, 3, (nq, d))
    ).astype(np.float32)
    return x, q


@pytest.fixture(scope="module")
def dataset():
    return _int_dataset(7)


@pytest.fixture(scope="module")
def flat_index(dataset):
    x, _ = dataset
    # n_lists > populated clusters on this data -> some lists are EMPTY,
    # so probes hit empty lists and padded tails (the masking edge cases)
    return ivf_flat_build(x, IVFFlatParams(
        n_lists=48, kmeans_n_iters=4, kmeans_init="random",
    ), metric="sqeuclidean")


def _saturating_ratio(index, p, k):
    """rerank_ratio that makes the kernel path's top-c sub-chunks cover
    every probed row: c*8 >= p*l_pad >= every row the scan saw."""
    l_tile = flat_kernel.plan_l_tile(
        index.centroids.shape[1], 64
    )
    l_pad = -(-index.storage.max_list // l_tile) * l_tile
    return float(p * l_pad // flat_kernel.SUBCHUNK) / k + 1.0


@pytest.mark.parametrize("stream", [None, True])
def test_saturated_pool_bit_identical_single_chip(dataset, flat_index,
                                                  stream):
    """With the rerank pool covering every probed row, BOTH engines
    exact-score the same candidate set in f32 — on integer-exact inputs
    the returned (dists, ids) must match to the bit."""
    x, q = dataset
    p = 4
    kw = dict(n_probes=p, qcap=64, stream_partials=stream,
              rerank_ratio=_saturating_ratio(flat_index, p, K_NN))
    d0, i0 = ivf_flat_search_grouped(flat_index, q, K_NN,
                                     use_pallas=False, **kw)
    d1, i1 = ivf_flat_search_grouped(flat_index, q, K_NN,
                                     use_pallas=True, **kw)
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))


def _assert_ids_equal_up_to_ties(dists, i0, i1):
    """ids bit-identical except inside equal-distance runs, where the
    two engines' selection machinery may order ties differently (the
    integer-exact fixtures that make dists bitwise also make exact
    ties common at k >> 5): each interior tie group must hold the same
    id SET; the group cut by the k-boundary is checked for distance
    only (any id at that distance is a correct k-th neighbor)."""
    d = np.asarray(dists)
    a, b = np.asarray(i0), np.asarray(i1)
    for r in range(d.shape[0]):
        start = 0
        k = d.shape[1]
        for end in range(1, k + 1):
            if end == k or d[r, end] != d[r, start]:
                if end < k or start == 0:
                    assert set(a[r, start:end].tolist()) == \
                        set(b[r, start:end].tolist()), f"query {r}"
                start = end


def _with_emptied_lists(x, base, emptied):
    """Rebuild ``base``'s storage with the rows of ``emptied`` lists
    remapped into list 0 — those lists keep their centroids (so probes
    still select them) but hold ZERO rows: the empty-probe edge case,
    constructed deterministically (the PQ-kernel fixture, flat flavor)."""
    from raft_tpu.spatial.ann.common import build_list_storage

    n = base.storage.n
    n_lists = base.centroids.shape[0]
    sid = np.asarray(base.storage.sorted_ids)
    sizes = np.asarray(base.storage.list_sizes)
    labels = np.empty(n, np.int64)
    labels[sid] = np.repeat(np.arange(n_lists), sizes)
    labels = np.where(np.isin(labels, list(emptied)), 0, labels)
    storage = build_list_storage(labels, n_lists)
    sid2 = np.asarray(storage.sorted_ids)
    data_sorted = jnp.concatenate([
        jnp.asarray(x[sid2]), jnp.zeros((1, x.shape[1]), jnp.float32)
    ])
    return dataclasses.replace(base, data_sorted=data_sorted,
                               storage=storage)


def test_emptied_lists_padded_tails_no_alien_rows(dataset, flat_index):
    """Empty lists are forced into the index (rows remapped away,
    centroids kept) so probes hit genuinely empty lists and padded
    tails; the kernel path must (a) stay bit-identical to the XLA
    engine at a saturated pool, and (b) never return rows outside the
    probed lists — sub-chunk windows overhang a list's tail into the
    NEXT list's slab rows, and the per-row validity mask must drop
    them."""
    x, q = dataset
    idx = _with_emptied_lists(x, flat_index, {1, 5, 9, 17})
    storage = idx.storage
    sizes = np.asarray(storage.list_sizes)
    assert (sizes == 0).any(), "fixture must include empty lists"
    p = 16
    kw = dict(n_probes=p, qcap=64,
              rerank_ratio=_saturating_ratio(idx, p, K_NN))
    ds0, is0 = ivf_flat_search_grouped(idx, q, K_NN, use_pallas=False,
                                       **kw)
    ds1, is1 = ivf_flat_search_grouped(idx, q, K_NN, use_pallas=True,
                                       **kw)
    np.testing.assert_array_equal(np.asarray(ds0), np.asarray(ds1))
    np.testing.assert_array_equal(np.asarray(is0), np.asarray(is1))

    from raft_tpu.spatial.ann.common import coarse_probe

    probes, _ = coarse_probe(
        jnp.asarray(q, jnp.float32),
        jnp.asarray(idx.centroids, jnp.float32), p,
    )
    probes = np.asarray(probes)
    sid = np.asarray(storage.sorted_ids)
    offs = np.asarray(storage.list_offsets)
    ids = np.asarray(is1)
    for qi in range(ids.shape[0]):
        allowed = set()
        for l in probes[qi]:
            allowed.update(sid[offs[l]:offs[l] + sizes[l]].tolist())
        got = set(t for t in ids[qi].tolist() if t >= 0)
        assert got <= allowed, f"query {qi} returned unprobed rows"


def test_kernel_recall_non_inferior(dataset, flat_index):
    """At a modest rerank_ratio the top-c sub-chunks cover the top-c
    rows of the bf16 scan (the 8-row cover argument), so kernel-path
    recall must not fall below the XLA engine's beyond bf16 boundary
    noise."""
    from tests.oracles import np_knn_ids

    x, q = dataset
    true = np_knn_ids(x, np.asarray(q), K_NN)

    def rec(ids):
        g = np.asarray(ids)
        return sum(
            len(set(a.tolist()) & set(b.tolist()))
            for a, b in zip(g, true)
        ) / true.size

    kw = dict(n_probes=4, qcap=64, rerank_ratio=4.0)
    r_pal = rec(ivf_flat_search_grouped(flat_index, q, K_NN,
                                        use_pallas=True, **kw)[1])
    r_xla = rec(ivf_flat_search_grouped(flat_index, q, K_NN,
                                        use_pallas=False, **kw)[1])
    assert r_pal >= r_xla - 0.01, (r_pal, r_xla)


def test_large_k_exceeding_subchunk_pool(dataset):
    """k > p * (l_pad/8) is legal whenever k <= max_list: the kernel
    path must clamp its sub-chunk selection to the pool width instead
    of asking top_k for more sub-chunks than exist — and the clamped
    pool (c*8 = p*l_pad rows) still covers k rows."""
    x, q = dataset
    # few lists -> max_list well above l_pad/8
    idx = ivf_flat_build(x, IVFFlatParams(
        n_lists=4, kmeans_n_iters=3, kmeans_init="random",
    ), metric="sqeuclidean")
    L = idx.storage.max_list
    p = 1
    l_tile = flat_kernel.plan_l_tile(x.shape[1], 64)
    l_pad = -(-L // l_tile) * l_tile
    width = l_pad // flat_kernel.SUBCHUNK
    k = min(L, p * width + 8)
    assert k > p * width, "fixture must exceed the sub-chunk pool"
    kw = dict(n_probes=p, qcap=64, rerank_ratio=1.0)
    d0, i0 = ivf_flat_search_grouped(idx, q, k, use_pallas=False, **kw)
    d1, i1 = ivf_flat_search_grouped(idx, q, k, use_pallas=True, **kw)
    assert d1.shape == d0.shape == (q.shape[0], k)
    # at c = full pool both engines exact-score every probed row;
    # a k this deep into dense integer clusters hits exact ties
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
    _assert_ids_equal_up_to_ties(d0, i0, i1)


def test_use_pallas_true_raises_naming_requirement(dataset, flat_index):
    """Explicit opt-in must not silently fall back: the resolver raises
    naming the unmet requirement (VMEM plan / per-query routing)."""
    x, q = dataset
    with pytest.raises(Exception, match="VMEM plan"):
        _resolve_scan_engine(True, 1 << 20, 512)
    # k > max_list routes to the per-query search (no kernel path)
    with pytest.raises(Exception, match="per-query"):
        ivf_flat_search_grouped(
            flat_index, q, flat_index.storage.max_list + 1,
            n_probes=4, use_pallas=True,
        )


def test_resolve_scan_engine_auto_off_tpu():
    """Auto (None) never selects the kernel off-TPU; explicit values
    resolve as given when supported."""
    assert jax.default_backend() != "tpu"
    assert _resolve_scan_engine(None, 96, 48) is False
    assert _resolve_scan_engine(True, 96, 48) is True
    assert _resolve_scan_engine(False, 96, 48) is False


def test_cpu_default_never_imports_kernel_module():
    """A fresh JAX_PLATFORMS=cpu process running default grouped flat
    searches (plus warmup) must not import (let alone compile) the
    Pallas kernel module."""
    prog = (
        "import os, sys\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "import numpy as np\n"
        "from raft_tpu.spatial.ann import IVFFlatParams, ivf_flat_build\n"
        "from raft_tpu.spatial.ann.ivf_flat import "
        "ivf_flat_search_grouped\n"
        "rng = np.random.default_rng(0)\n"
        "x = rng.standard_normal((400, 8)).astype(np.float32)\n"
        "idx = ivf_flat_build(x, IVFFlatParams(n_lists=8,\n"
        "    kmeans_n_iters=2, kmeans_init='random'))\n"
        "idx.warmup(8, k=3, n_probes=2)\n"
        "ivf_flat_search_grouped(idx, x[:8], 3, n_probes=2, qcap=8)\n"
        "assert 'raft_tpu.spatial.ann.flat_kernel' not in sys.modules, \\\n"
        "    'CPU default search imported the TPU kernel module'\n"
        "print('OK')\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", prog], capture_output=True, text=True,
        timeout=300,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "OK" in out.stdout


# -- IVF-SQ: kernel lives in the grouped search; loud-fail names it ----------

def test_ivf_sq_per_query_use_pallas_points_at_grouped(dataset):
    """Since ISSUE 11 the SQ engine HAS a kernel path — in the grouped
    search (tests/test_sq_kernel.py). The per-query search still has
    none (it never forms list slabs): ``use_pallas=True`` there must
    raise POINTING AT the grouped entry, and ``None``/``False`` must
    run the XLA path with identical results."""
    from raft_tpu.spatial.ann.ivf_sq import ivf_sq_search

    x, q = dataset
    idx = ivf_sq_build(x, IVFSQParams(n_lists=16, kmeans_n_iters=3))
    with pytest.raises(Exception, match="ivf_sq_search_grouped"):
        ivf_sq_search(idx, q, K_NN, n_probes=4, use_pallas=True)
    d_def, i_def = ivf_sq_search(idx, q, K_NN, n_probes=4)
    d_none, i_none = ivf_sq_search(idx, q, K_NN, n_probes=4,
                                   use_pallas=None)
    d_off, i_off = ivf_sq_search(idx, q, K_NN, n_probes=4,
                                 use_pallas=False)
    for dd, ii in ((d_none, i_none), (d_off, i_off)):
        np.testing.assert_array_equal(np.asarray(d_def), np.asarray(dd))
        np.testing.assert_array_equal(np.asarray(i_def), np.asarray(ii))


# -- mutation tier: tombstones at the rerank tail ----------------------------

def test_mutable_search_engine_parity_with_tombstones(dataset):
    """The kernel path folds the mutation tier's row_mask at its exact
    rerank tail: on a small-list index (the default rerank_ratio
    saturates the pool) both engines must return bit-identical
    (dists, ids) after upserts AND deletes, and no deleted id may ever
    surface."""
    from raft_tpu.spatial.ann.mutation import (
        delete, mutable_search, upsert, wrap_mutable,
    )

    x, q = dataset
    idx = ivf_flat_build(x, IVFFlatParams(
        n_lists=64, kmeans_n_iters=4, kmeans_init="random",
    ), metric="sqeuclidean")
    # default rerank_ratio=4.0, k=10 -> c*8 = 320 rows >= p*max_list
    p = 3
    assert 4 * 10 * flat_kernel.SUBCHUNK >= p * idx.storage.max_list, \
        "fixture must saturate the default rerank pool"
    m = wrap_mutable(idx, delta_cap=32)
    rng = np.random.default_rng(3)
    up_ids = jnp.asarray(rng.integers(0, x.shape[0], 8), jnp.int32)
    m, _ = upsert(m, jnp.asarray(x[np.asarray(up_ids)] + 1.0), up_ids)
    dead = jnp.asarray(rng.integers(0, x.shape[0], 40), jnp.int32)
    m, _ = delete(m, dead)
    kw = dict(n_probes=p, qcap=64)
    d0, i0 = mutable_search(m, q, 10, use_pallas=False, **kw)
    d1, i1 = mutable_search(m, q, 10, use_pallas=True, **kw)
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
    _assert_ids_equal_up_to_ties(d0, i0, i1)
    alive_dead = set(np.asarray(dead).tolist()) - \
        set(np.asarray(up_ids).tolist())
    got = set(np.asarray(i1).ravel().tolist())
    assert not (got & alive_dead), "deleted rows surfaced"


# -- MNMG: the fused one-dispatch program ------------------------------------

@pytest.fixture(scope="module")
def comms8():
    from raft_tpu.comms import build_comms

    return build_comms(jax.devices()[:8])


@pytest.fixture(scope="module")
def sharded_index(dataset, comms8):
    from raft_tpu.comms import mnmg_ivf_flat_build

    x, _ = dataset
    return mnmg_ivf_flat_build(comms8, x, IVFFlatParams(
        n_lists=32, kmeans_n_iters=4, kmeans_init="random",
    ), metric="sqeuclidean")


def test_mnmg_fused_program_engine_parity(dataset, comms8,
                                          sharded_index):
    """The Pallas path ACTIVE inside the MNMG fused one-dispatch
    program: saturated-pool results bit-identical to the XLA engine's
    (each probed list is scored shard-locally by the same grouped
    kernel, and the merge sees identical shard payloads)."""
    from raft_tpu.comms import mnmg_ivf_flat_search

    x, q = dataset
    p = 4
    l_tile = flat_kernel.plan_l_tile(x.shape[1], 64)
    l_pad = -(-int(sharded_index.max_list) // l_tile) * l_tile
    rr = float(p * l_pad // flat_kernel.SUBCHUNK) / K_NN + 1.0
    kw = dict(n_probes=p, qcap=q.shape[0], rerank_ratio=rr)
    d0, i0 = mnmg_ivf_flat_search(comms8, sharded_index, q, K_NN,
                                  use_pallas=False, **kw)
    d1, i1 = mnmg_ivf_flat_search(comms8, sharded_index, q, K_NN,
                                  use_pallas=True, **kw)
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))


def test_mnmg_pallas_health_flip_zero_retrace(
    dataset, comms8, sharded_index, monkeypatch
):
    """The acceptance trace-audit with the kernel engaged: use_pallas
    is a trace-time static, health stays a runtime input — shard_mask
    flips must reuse the ONE compiled fused program (zero retraces)."""
    from raft_tpu.comms import mnmg_ivf_flat as mod

    _, q = dataset
    created = []
    orig = mod._cached_search

    def recording(*a, **k):
        fn = orig(*a, **k)
        created.append(fn)
        return fn

    monkeypatch.setattr(mod, "_cached_search", recording)
    kw = dict(n_probes=4, qcap=q.shape[0], use_pallas=True)
    m_up = np.ones(8, np.int32)
    m_one = m_up.copy()
    m_one[3] = 0
    mod.mnmg_ivf_flat_search(comms8, sharded_index, q, K_NN,
                             shard_mask=m_up, **kw)
    fn = created[0]
    size0 = fn._cache_size()
    for mask in (m_one, m_up):
        res = mod.mnmg_ivf_flat_search(comms8, sharded_index, q, K_NN,
                                       shard_mask=mask, **kw)
    assert all(f is fn for f in created), \
        "health flips must reuse the cached program object"
    assert fn._cache_size() == size0, \
        "health flips must not retrace the compiled kernel program"
    assert float(jnp.min(res.coverage)) == 1.0
