"""k-means tests — cluster-recovery oracle on make_blobs, mirroring the
reference test strategy (cpp/test/cluster/kmeans.cu: fit on blobs, check
adjusted rand / score bounds)."""

import numpy as np

from raft_tpu.cluster import (
    KMeans,
    KMeansParams,
    kmeans_fit,
    kmeans_plus_plus_init,
    kmeans_predict,
    kmeans_transform,
)
from raft_tpu.random import make_blobs, RngState


def _blobs(n=1000, d=8, k=5, seed=7, std=0.4):
    X, y = make_blobs(
        n, d, n_clusters=k, cluster_std=std, state=RngState(seed),
        center_box=(-8.0, 8.0),
    )
    return np.asarray(X), np.asarray(y)


def purity(labels, truth, k):
    """Fraction of points in agreement under the best per-cluster majority."""
    total = 0
    for c in range(k):
        mask = labels == c
        if mask.sum() == 0:
            continue
        total += np.bincount(truth[mask]).max()
    return total / len(truth)


def test_kmeans_recovers_blobs():
    X, y = _blobs()
    out = kmeans_fit(X, KMeansParams(n_clusters=5, seed=3))
    labels = np.asarray(out.labels)
    assert purity(labels, y, 5) > 0.95
    assert int(out.n_iter) >= 1
    assert np.isfinite(float(out.inertia))


def test_kmeans_plus_plus_spreads_centroids():
    X, _ = _blobs(n=500, k=4)
    import jax

    cents = np.asarray(kmeans_plus_plus_init(X, 4, jax.random.PRNGKey(0)))
    # all 4 seeds distinct and drawn from the data
    dists = ((cents[:, None, :] - cents[None, :, :]) ** 2).sum(-1)
    np.fill_diagonal(dists, np.inf)
    assert dists.min() > 1.0  # well-separated blob centers


def test_kmeans_inertia_decreases_vs_random_init():
    X, _ = _blobs(n=600, k=4, std=1.0)
    good = kmeans_fit(X, KMeansParams(n_clusters=4, seed=0))
    one_iter = kmeans_fit(X, KMeansParams(n_clusters=4, seed=0, max_iter=1))
    assert float(good.inertia) <= float(one_iter.inertia) + 1e-3


def test_kmeans_predict_transform_consistent():
    X, _ = _blobs(n=400, k=3)
    out = kmeans_fit(X, KMeansParams(n_clusters=3, seed=1))
    labels = np.asarray(kmeans_predict(X, out.centroids))
    np.testing.assert_array_equal(labels, np.asarray(out.labels))
    T = np.asarray(kmeans_transform(X, out.centroids, sqrt=False))
    np.testing.assert_array_equal(T.argmin(1), labels)


def test_kmeans_handles_k_greater_than_clusters():
    # more centroids than natural clusters: empty-cluster reseeding must keep
    # all centroids populated (reference detail/kmeans.cuh:882-896)
    X, _ = _blobs(n=300, k=2, std=0.2)
    out = kmeans_fit(X, KMeansParams(n_clusters=8, seed=0))
    counts = np.bincount(np.asarray(out.labels), minlength=8)
    assert (counts > 0).sum() >= 6  # nearly all clusters used


def test_kmeans_estimator_facade():
    X, y = _blobs(n=500, k=4)
    km = KMeans(n_clusters=4, seed=2).fit(X)
    assert km.cluster_centers_.shape == (4, X.shape[1])
    assert purity(np.asarray(km.labels_), y, 4) > 0.9
