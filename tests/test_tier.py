"""Two-tier slab store suite (ISSUE 17, docs/tiering.md): the
popularity-tiered host-RAM cold tier — store correctness against the
full-resident grouped program, the promotion policy's hysteresis, the
async fetcher's bounded queue, mutation-epoch chaos (a write between a
demotion and its re-promotion never serves a pre-write slab — the
result-cache discipline of tests/test_result_cache.py applied to
slabs), the zero-retrace cache-size audit on membership flips, and the
capacity acceptance: an index >= 4x the hot "HBM" budget served on the
CPU host-sim at >= 0.95 of the hot-path recall. All tiny shapes, all
CPU — behavior, never QPS (the QPS claim lives in
bench/bench_serving.py's ``cold_tier_row``)."""

import time

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import errors
from raft_tpu.obs import metrics as obsm
from raft_tpu.obs.flight import FlightRecorder
from raft_tpu.resilience import measured_list_load
from raft_tpu.serving import ServingExecutor
from raft_tpu.spatial.ann import (
    IVFFlatParams,
    ivf_flat_build,
)
from raft_tpu.spatial.ann.ivf_flat import (
    _grouped_impl,
    ivf_flat_search_grouped,
)
from raft_tpu.spatial.ann.ivf_sq import IVFSQParams, ivf_sq_build
from raft_tpu.spatial.ann.mutation import (
    compact,
    delete as mut_delete,
    lists_changed_since,
    upsert as mut_upsert,
    wrap_mutable,
)
from raft_tpu.tier import PromotionPolicy, SlabFetcher, TieredListStore
from raft_tpu.tier.store import _install_rows

D = 16
K = 5
N_PROBES = 4
N_LISTS = 16
NQ = 8


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(7)
    return rng.standard_normal((2048, D)).astype(np.float32)


@pytest.fixture(scope="module")
def flat_index(dataset):
    return ivf_flat_build(dataset, IVFFlatParams(
        n_lists=N_LISTS, kmeans_n_iters=3, seed=1))


def make_store(index, n_slots=N_LISTS, **kw):
    kw.setdefault("registry", obsm.MetricRegistry())
    return TieredListStore(index, n_slots=n_slots, **kw)


def queries(dataset, nq=NQ, scale=1.001):
    return (dataset[:nq] * scale).astype(np.float32)


# ------------------------------------------------------------- the store
class TestStoreBasics:
    def test_budget_resolves_slots(self, flat_index):
        L = int(flat_index.storage.max_list)
        slab = L * D * 4                      # f32 slab bytes
        st = make_store(flat_index, n_slots=None,
                        hbm_budget_bytes=4 * slab)
        assert st.n_slots == 4
        # a budget past the whole index clamps to n_lists
        st2 = make_store(flat_index, n_slots=None,
                         hbm_budget_bytes=10 ** 12)
        assert st2.n_slots == N_LISTS
        with pytest.raises(errors.RaftLogicError):
            make_store(flat_index, n_slots=4, hbm_budget_bytes=slab)
        with pytest.raises(errors.RaftLogicError):
            make_store(flat_index, n_slots=None, hbm_budget_bytes=None)

    def test_membership_promote_demote(self, flat_index):
        st = make_store(flat_index, n_slots=4)
        assert st.hot_lists().tolist() == []
        assert st.promote([3, 1, 3]) == 2          # dup is a no-op
        assert st.hot_lists().tolist() == [1, 3]
        assert st.promote([0, 2, 5]) == 2          # stops when full
        assert st.stats().hot_lists == 4
        assert st.demote([3, 9]) == 1              # cold 9 is a no-op
        assert st.hot_lists().tolist() == [0, 1, 2]
        s = st.stats()
        assert s.fetches == 4 and s.demotions == 1
        assert "hot=3/16" in repr(st)

    def test_all_hot_matches_full_program(self, flat_index, dataset):
        st = make_store(flat_index)
        st.promote(range(N_LISTS))
        q = queries(dataset)
        vals, ids = st.search(q, K, n_probes=N_PROBES)
        ref_v, ref_i = ivf_flat_search_grouped(
            flat_index, jnp.asarray(q), K, n_probes=N_PROBES,
            qcap=NQ,
        )
        np.testing.assert_array_equal(np.asarray(ids),
                                      np.asarray(ref_i))
        np.testing.assert_allclose(np.asarray(vals),
                                   np.asarray(ref_v), atol=1e-5)
        assert st.measure_recall(q, K, n_probes=N_PROBES) == 1.0
        assert st.stats().hit_rate == 1.0

    def test_all_cold_serves_empty_and_counts_misses(self, flat_index,
                                                     dataset):
        st = make_store(flat_index, n_slots=2)
        q = queries(dataset)
        _, ids = st.search(q, K, n_probes=N_PROBES)
        assert np.all(np.asarray(ids) == -1)       # degraded, not wrong
        s = st.stats()
        assert s.probe_misses == NQ * N_PROBES and s.probe_hits == 0

    def test_validation(self, flat_index, dataset):
        st = make_store(flat_index, n_slots=2)
        with pytest.raises(errors.RaftLogicError):
            st.search(np.zeros((2, D + 1), np.float32), K)
        with pytest.raises(errors.RaftLogicError):
            st.search(queries(dataset), 10 ** 6)
        with pytest.raises(errors.RaftLogicError):
            st.promote([N_LISTS])

    def test_partial_hot_serves_from_hot_only(self, flat_index,
                                              dataset):
        """Probes landing cold contribute nothing; every id returned
        comes from a HOT list's rows (the graceful degraded answer)."""
        st = make_store(flat_index, n_slots=4)
        st.promote([0, 1, 2, 3])
        q = queries(dataset, nq=NQ, scale=1.01)
        _, ids = st.search(q, K, n_probes=N_PROBES)
        ids = np.asarray(ids)
        offs = np.asarray(flat_index.storage.list_offsets)
        szs = np.asarray(flat_index.storage.list_sizes)
        sids = np.asarray(flat_index.storage.sorted_ids)
        hot_ids = set()
        for lid in (0, 1, 2, 3):
            o = int(offs[lid])
            hot_ids |= set(sids[o:o + int(szs[lid])].tolist())
        for got in ids.ravel():
            assert got == -1 or int(got) in hot_ids

    def test_load_feed_records_per_list_series(self, flat_index,
                                               dataset):
        st = make_store(flat_index, n_slots=2, shard=91)
        st.search(queries(dataset), K, n_probes=N_PROBES)
        load = measured_list_load(N_LISTS, shard=91)
        assert load.sum() == NQ * N_PROBES
        # the decayed in-process touch signal ranks the same lists
        touch = st.measured_load()
        np.testing.assert_array_equal(touch > 0, load > 0)


# ---------------------------------------------- zero-retrace (acceptance)
class TestZeroRetrace:
    def test_membership_and_tombstone_flips_never_retrace(
            self, flat_index, dataset):
        """THE contract behind the ``ivf_flat_grouped_tiered`` program
        entry: offsets/sizes/ids/data/mask are runtime operands, so
        promote/demote/tombstone flips reuse the ONE warmed program."""
        st = make_store(flat_index, n_slots=4)
        q = queries(dataset)
        st.search(q, K, n_probes=N_PROBES)           # warm (cold view)
        warmed = _grouped_impl._cache_size()
        installs = _install_rows._cache_size()
        st.promote([0, 1, 2, 3])
        st.search(q, K, n_probes=N_PROBES)
        st.demote([1])
        st.promote([7])
        st.search(q, K, n_probes=N_PROBES)
        # a tombstone VALUE flip rides the same program too
        with st._install:
            st._mask_np = st._mask_np.copy()
            st._mask_np[5] = 0
            st._publish()
        st.search(q, K, n_probes=N_PROBES)
        assert _grouped_impl._cache_size() == warmed, \
            "a tier membership flip retraced the grouped program"
        # every install compiled exactly one slab-install program
        assert _install_rows._cache_size() == installs, \
            "slab installs retraced past the first slot"


# ------------------------------------------------------- promotion policy
class TestPromotionPolicy:
    def test_fills_free_slots_hottest_first(self):
        p = PromotionPolicy(min_touches=2.0, max_moves=4)
        load = np.array([0.0, 9.0, 1.0, 5.0, 3.0])
        moves = p.plan(load, np.full(5, -1, np.int32), n_slots=2)
        assert moves == [(1, None), (3, None)]   # 9 then 5; 1 < floor

    def test_hysteresis_blocks_marginal_swaps(self):
        p = PromotionPolicy(demote_margin=1.5, min_touches=1.0)
        slot_of = np.array([0, -1, 1, -1], np.int32)   # hot: 0, 2
        # candidate 1 at 1.4x of victim's load: blocked by the margin
        assert p.plan(np.array([10.0, 14.0, 20.0, 0.0]),
                      slot_of, n_slots=2) == []
        # at 2x it clears — the COLDEST hot list is the victim
        assert p.plan(np.array([10.0, 20.0, 30.0, 0.0]),
                      slot_of, n_slots=2) == [(1, 0)]

    def test_max_moves_caps_a_cycle(self):
        p = PromotionPolicy(min_touches=1.0, max_moves=2)
        load = np.arange(1.0, 7.0)
        moves = p.plan(load, np.full(6, -1, np.int32), n_slots=6)
        assert len(moves) == 2

    def test_pick_victim_honors_exclude_and_margin(self):
        p = PromotionPolicy(demote_margin=1.25, min_touches=1.0)
        load = np.array([2.0, 8.0, 4.0, 50.0])
        slot_of = np.array([0, 1, 2, -1], np.int32)
        assert p.pick_victim(load, slot_of, candidate_load=50.0) == 0
        assert p.pick_victim(load, slot_of, candidate_load=50.0,
                             exclude=[0]) == 2
        # below the margin of the coldest hot list: don't thrash
        assert p.pick_victim(load, slot_of,
                             candidate_load=2.2) is None
        assert p.pick_victim(load, slot_of,
                             candidate_load=0.5) is None
        with pytest.raises(errors.RaftLogicError):
            PromotionPolicy(demote_margin=0.5)


# ------------------------------------------------------- the async fetcher
class TestSlabFetcher:
    def test_misses_promote_asynchronously(self, flat_index, dataset):
        st = make_store(flat_index, n_slots=4)
        with SlabFetcher(st, window=2) as f:
            st.search(queries(dataset), K, n_probes=N_PROBES)
            assert f.drain(20.0)
            assert st.stats().hot_lists > 0
        # detached on close: a later miss queues nothing
        st.search(queries(dataset, scale=1.02), K, n_probes=N_PROBES)
        assert f.stats()["pending"] == 0

    def test_full_hot_set_sheds_without_policy(self, flat_index):
        st = make_store(flat_index, n_slots=2)
        st.promote([0, 1])
        with SlabFetcher(st, window=2) as f:
            f.request([4, 5, 6])
            assert f.drain(20.0)
        assert st.hot_lists().tolist() == [0, 1]   # nothing thrashed

    def test_policy_swaps_when_margin_cleared(self, flat_index):
        """A deterministic load injection: with hot {0, 1} idle and the
        margin at 1.25x, requests for loaded lists 5/6/7 must evict
        both idle lists, then 7 (20) must displace 5 (10) — and a
        re-request of 5 must bounce off the hysteresis."""
        st = make_store(flat_index, n_slots=2, touch_decay=1.0)
        st.promote([0, 1])
        with st._lock:
            st._touch[:] = 0.0
            st._touch[[5, 6, 7]] = [10.0, 40.0, 20.0]
        pol = PromotionPolicy(demote_margin=1.25, min_touches=1.0)
        with SlabFetcher(st, window=2, policy=pol,
                         max_pending=32) as f:
            f.request([5, 6, 7])
            assert f.drain(20.0)
            assert set(st.hot_lists().tolist()) == {6, 7}
            assert st.stats().demotions == 3   # 0, 1, then 5
            f.request([5])                     # 10 < 1.25 * 20: bounce
            assert f.drain(20.0)
        assert set(st.hot_lists().tolist()) == {6, 7}
        assert st.stats().demotions == 3

    def test_bounded_queue_drops_and_counts(self, flat_index):
        st = make_store(flat_index, n_slots=1)
        with SlabFetcher(st, window=1, max_pending=2) as f:
            # one locked enqueue: the dup dedups, the overflow drops
            assert f.request([9, 9, 10, 11, 12]) == 2
            assert f.stats()["dropped"] == 2
            assert f.drain(20.0)
            assert st.stats().hot_lists == 1   # full set sheds fills

    def test_overlap_stamp_via_busy_fn(self, flat_index):
        st = make_store(flat_index, n_slots=2)
        st.promote([0], busy=True)
        st.promote([1], busy=False)
        s = st.stats()
        assert s.overlapped_fetches == 1 and s.fetches == 2
        assert s.fetch_overlap_pct == 50.0

    def test_worker_crash_restarts_bounded_and_counted(self, flat_index):
        """ISSUE 18 satellite: a promotion-batch crash no longer kills
        the worker silently — the loop restarts (counted in
        tier_fetcher_restarts_total) and the next fill proceeds."""
        from raft_tpu.testing import chaos

        st = make_store(flat_index, n_slots=4, name="crashy-restart")
        restore = chaos.inject_worker_crash(st, times=1)
        c = obsm.default_registry().counter(
            "tier_fetcher_restarts_total", tier=st.name)
        v0 = c.value
        with SlabFetcher(st, window=1) as f:
            f.request([4])                      # this batch crashes
            deadline = time.monotonic() + 10
            while f.stats()["restarts"] < 1 and time.monotonic() < deadline:
                time.sleep(0.005)
            assert f.stats()["restarts"] == 1 and not f.gave_up
            assert c.value - v0 == 1
            restore()
            f.request([5])                      # the restarted loop fills
            assert f.drain(20.0)
            assert 5 in st.hot_lists().tolist()

    # the final give-up re-raise IS the point — silence pytest's
    # unhandled-thread-exception warning for it
    @pytest.mark.filterwarnings(
        "ignore::pytest.PytestUnhandledThreadExceptionWarning")
    def test_worker_gives_up_after_max_restarts(self, flat_index):
        """After max_restarts crashes the worker gives up DELIBERATELY:
        fill sink detached (the store serves from its hot set), a
        tier_fetcher_gave_up flight event, and the final exception
        surfaced through thread_uncaught_total."""
        from raft_tpu.testing import chaos

        prev_obs = obsm.set_enabled(True)
        try:
            recorder = FlightRecorder(256, name="crashy")
            st = make_store(flat_index, n_slots=4, name="crashy-giveup",
                            flight=recorder)
            chaos.inject_worker_crash(st, times=99)   # never recovers
            f = SlabFetcher(st, window=1, max_restarts=1,
                            name="crashy-giveup-fetch")
            try:
                f.request([4])
                deadline = time.monotonic() + 10
                while f._thread.is_alive() and time.monotonic() < deadline:
                    f.request([5])              # keep feeding batches
                    time.sleep(0.005)
                assert not f._thread.is_alive()
                assert f.gave_up
                assert f.stats()["restarts"] == 2   # crash 1 restarted,
                # crash 2 exhausted max_restarts=1 and gave up
                gave = recorder.events(event="tier_fetcher_gave_up")
                assert gave and gave[0]["tier"] == st.name
                assert gave[0]["max_restarts"] == 1
                snap = obsm.default_registry().snapshot()
                assert any(
                    row["labels"].get("thread") == "crashy-giveup-fetch"
                    for row in snap.get("thread_uncaught_total", [])
                ), "the final crash must surface in thread_uncaught_total"
                # degraded serve-from-hot: the sink is detached, so a
                # later request is a no-op (the producer API still works)
                assert f.request([6]) in (0, 1)
                assert st._fill_sink is None
            finally:
                f.close()
        finally:
            obsm.set_enabled(prev_obs)


# ------------------------------------- mutation-epoch chaos (acceptance)
class TestMutationEpochChaos:
    def test_journal_names_changed_lists(self, flat_index, dataset):
        m = wrap_mutable(flat_index, delta_cap=16)
        assert lists_changed_since(m, 0) == set()
        m1, acc = mut_upsert(m, dataset[:1] * 1.5,
                             np.array([9001], np.int32))
        assert bool(acc[0])
        ch = lists_changed_since(m1, 0)
        assert ch is not None and len(ch) >= 1
        # an up-to-date reader sees an empty set; compaction answers
        # None ("assume everything")
        assert lists_changed_since(m1, m1.epoch) == set()
        m2, _ = compact(m1)
        assert lists_changed_since(m2, m1.epoch) is None
        assert lists_changed_since(m2, 0) is None

    def test_journal_floor_answers_none(self, flat_index):
        m = wrap_mutable(flat_index, delta_cap=16)
        m.epoch = 5
        m._epoch_journal = [(5, frozenset({1}))]
        m._journal_floor = 4                      # epochs <= 4 fell off
        assert lists_changed_since(m, 4) == {1}
        assert lists_changed_since(m, 3) is None

    def test_delete_between_demotion_and_repromotion(self, flat_index,
                                                     dataset):
        """THE chaos acceptance: a delete lands while the victim's list
        is demoted — the re-promoted slab must serve the post-write
        truth, never the pre-write rows it was demoted with."""
        m = wrap_mutable(flat_index, delta_cap=16)
        st = make_store(flat_index)
        st.promote(range(N_LISTS))
        q = queries(dataset, nq=NQ, scale=1.0)      # exact rows
        _, ids0 = st.search(q, K, n_probes=N_PROBES)
        victim = int(np.asarray(ids0)[0, 0])
        # demote the victim's list(s), THEN delete the row
        m1, found = mut_delete(m, np.array([victim], np.int32))
        assert bool(found[0])
        changed = lists_changed_since(m1, 0)
        assert changed and changed is not None
        st.demote(sorted(changed))
        # sync pulls the journal: only the changed lists' masks update
        assert st.sync_mutations(m1) == changed
        st.promote(sorted(changed))                # re-promotion
        _, ids1 = st.search(q, K, n_probes=N_PROBES)
        assert victim not in np.asarray(ids1).ravel().tolist(), \
            "re-promoted slab served a pre-delete row"
        assert st.stats().epoch == m1.epoch

    def test_upsert_supersede_masks_main_copy(self, flat_index,
                                              dataset):
        """An upsert that SUPERSEDES a main-slab id must tombstone the
        old copy in the tier view (the fresh copy lives in the delta
        store, outside the frozen slab the tier serves)."""
        m = wrap_mutable(flat_index, delta_cap=16)
        st = make_store(flat_index)
        st.promote(range(N_LISTS))
        q = queries(dataset, nq=NQ, scale=1.0)
        _, ids0 = st.search(q, K, n_probes=N_PROBES)
        target = int(np.asarray(ids0)[0, 0])
        m1, acc = mut_upsert(m, (dataset[:1] + 100.0),
                             np.array([target], np.int32))
        assert bool(acc[0])
        assert st.sync_mutations(m1)              # names >= 1 list
        _, ids1 = st.search(q, K, n_probes=N_PROBES)
        assert target not in np.asarray(ids1).ravel().tolist(), \
            "tier served a superseded main-slab copy"

    def test_sync_is_idempotent_and_cheap_when_current(self,
                                                       flat_index):
        m = wrap_mutable(flat_index, delta_cap=16)
        st = make_store(flat_index, n_slots=4)
        v0 = st.runtime()["tier"].version
        assert st.sync_mutations(m) == set()
        assert st.runtime()["tier"].version == v0   # no republish

    def test_compaction_demands_a_rebuild_on_geometry_change(
            self, flat_index, dataset):
        """Compaction re-buckets the slab (max_list shrinks): the store
        must REFUSE to sync onto changed geometry — the documented
        statics-change rule — and the rebuild-with-epoch path serves
        the post-compaction truth with no spurious invalidation."""
        m = wrap_mutable(flat_index, delta_cap=16)
        st = make_store(flat_index)
        st.promote(range(N_LISTS))
        q = queries(dataset, nq=NQ, scale=1.0)
        _, ids0 = st.search(q, K, n_probes=N_PROBES)
        victim = int(np.asarray(ids0)[0, 0])
        m1, _ = mut_delete(m, np.array([victim], np.int32))
        m2, _ = compact(m1)
        new_L = int(m2.index.storage.max_list)
        if new_L == int(flat_index.storage.max_list):
            # geometry preserved: sync takes the full-refresh path
            assert st.sync_mutations(m2) is None
            st2 = st
        else:
            with pytest.raises(errors.RaftLogicError):
                st.sync_mutations(m2)
            st2 = make_store(m2.index, epoch=m2.epoch)
            st2.promote(range(N_LISTS))
            # seeded epoch: the first sync is a no-op, not a flush
            assert st2.sync_mutations(m2) == set()
        st2.promote(range(N_LISTS))
        _, ids2 = st2.search(q, K, n_probes=N_PROBES)
        assert victim not in np.asarray(ids2).ravel().tolist()

    def test_journal_overflow_refreshes_with_live_tombstones(
            self, flat_index, dataset):
        """A journal answer of None WITHOUT a compaction (the bounded
        journal overflowed) must full-refresh with the CURRENT
        row_mask riding along — live deletes survive the refresh."""
        m = wrap_mutable(flat_index, delta_cap=16)
        st = make_store(flat_index)
        st.promote(range(N_LISTS))
        q = queries(dataset, nq=NQ, scale=1.0)
        _, ids0 = st.search(q, K, n_probes=N_PROBES)
        victim = int(np.asarray(ids0)[0, 0])
        m1, found = mut_delete(m, np.array([victim], np.int32))
        assert bool(found[0])
        # simulate the cap: every entry fell off the journal
        m1._epoch_journal = []
        m1._journal_floor = m1.epoch
        assert st.sync_mutations(m1) is None
        assert st.stats().invalidations == N_LISTS
        st.promote(range(N_LISTS))
        _, ids1 = st.search(q, K, n_probes=N_PROBES)
        assert victim not in np.asarray(ids1).ravel().tolist(), \
            "journal-overflow refresh dropped a live tombstone"


# ------------------------------------------------------ recall guardrail
class TestRecallGuardrail:
    def test_breach_counts_and_flags_degraded(self, flat_index,
                                              dataset):
        reg = obsm.MetricRegistry()
        fr = FlightRecorder(64, name="tier-test")
        st = TieredListStore(flat_index, n_slots=N_LISTS,
                             min_recall=0.95, registry=reg, flight=fr)
        st.promote([0, 1])
        q = queries(dataset)
        r = st.measure_recall(q, K, n_probes=N_PROBES)
        assert r < 0.95 and st.degraded
        assert reg.counter("tier_recall_breaches_total",
                           tier="tier").value == 1
        assert reg.gauge("tier_recall", tier="tier").value == r
        assert fr.events(event="tier_recall_breach")
        # promoting the working set clears the guardrail
        st.promote(range(N_LISTS))
        assert st.measure_recall(q, K, n_probes=N_PROBES) == 1.0
        assert not st.degraded

    def test_recall_respects_tombstones_on_both_sides(self, flat_index,
                                                      dataset):
        """The reference arm of measure_recall carries the store's
        CURRENT mask — a tombstoned row missing from the tiered answer
        must not read as a recall loss."""
        m = wrap_mutable(flat_index, delta_cap=16)
        st = make_store(flat_index)
        st.promote(range(N_LISTS))
        q = queries(dataset, scale=1.0)
        _, ids0 = st.search(q, K, n_probes=N_PROBES)
        m1, _ = mut_delete(
            m, np.asarray(np.asarray(ids0)[0, :2], np.int32))
        st.sync_mutations(m1)
        assert st.measure_recall(q, K, n_probes=N_PROBES) == 1.0


# --------------------------------------------- capacity x4 (acceptance)
class TestCapacityAcceptance:
    def test_4x_capacity_at_hot_recall(self, dataset):
        """The ISSUE 17 acceptance on the CPU host-sim: the hot "HBM"
        budget is 1/4 of the cold slab's bytes (capacity_x >= 4), the
        traffic is a skewed working set whose probe footprint FITS that
        budget (the tier's premise — the Zipf head fits), the fetcher
        converges the hot set from misses alone — then >= 0.95 recall
        vs the full-resident program ON THAT TRAFFIC, hot-slab bytes
        audited against the budget."""
        idx = ivf_flat_build(dataset, IVFFlatParams(
            n_lists=32, kmeans_n_iters=3, seed=2))
        L = int(idx.storage.max_list)
        slab = L * D * 4
        budget = dataset.nbytes // 4
        st = TieredListStore(idx, hbm_budget_bytes=budget,
                             min_recall=0.95, touch_decay=1.0,
                             registry=obsm.MetricRegistry())
        assert st.n_slots == budget // slab
        capacity_x = dataset.nbytes / (st.n_slots * slab)
        assert capacity_x >= 4.0
        assert st.stats().hot_bytes <= budget + (D * 4)  # sentinel row
        # working set: replay the coarse probe for EVERY point (the
        # store's own accounting formula), pick the n_slots lists that
        # fully cover the most points, and query only covered points —
        # a skewed head whose probe footprint fits the hot budget
        P = 2       # probes per query — the working set must FIT the
        # hot budget, and a 4-probe footprint over 32 coarse lists
        # cannot fit 5 slots; capacity_x is a bytes claim, not a probes
        # claim
        cents = np.asarray(idx.centroids, np.float32)
        data = np.asarray(idx.data_sorted)[: dataset.shape[0]]
        d2 = (np.sum(cents ** 2, 1)[None, :]
              - 2.0 * (data.astype(np.float32) @ cents.T))
        probes = np.argpartition(d2, P - 1, 1)[:, :P]
        hist = np.bincount(probes.ravel(), minlength=32)
        S: set = set()
        covered = np.zeros(len(data), bool)
        for _ in range(st.n_slots):
            gain = [
                (int(((~covered)
                      & np.isin(probes, sorted(S | {c})).all(1)).sum()),
                 hist[c], c)
                for c in range(32) if c not in S
            ]
            _, _, best = max(gain)
            S.add(int(best))
            covered |= np.isin(probes, sorted(S)).all(1)
        pts = np.nonzero(covered)[0]
        assert pts.size >= NQ, "cover construction found no head"
        qs = data[pts[np.arange(64) % pts.size]].astype(np.float32)
        pol = PromotionPolicy(demote_margin=1.25, min_touches=2.0,
                              max_moves=8)
        rounds = 6
        with SlabFetcher(st, window=4, policy=pol,
                         max_pending=64) as f:
            for _ in range(rounds):
                for b in range(0, 64, NQ):
                    st.search(qs[b:b + NQ], K, n_probes=P)
                f.drain(30.0)
        recalls = [st.measure_recall(qs[b:b + NQ], K, n_probes=P)
                   for b in range(0, 64, NQ)]
        recall = float(np.mean(recalls))
        assert recall >= 0.95, \
            f"tiered recall {recall} < 0.95 of the hot path at " \
            f"{capacity_x:.1f}x capacity"
        assert not st.degraded
        s = st.stats()
        # misses converged the hot set onto the working set's lists
        assert set(st.hot_lists().tolist()) <= S
        assert s.hit_rate >= (rounds - 1.5) / rounds


# ------------------------------------------- executor runtime_provider
class TestExecutorIntegration:
    def test_provider_hands_dispatch_the_current_snapshot(
            self, flat_index, dataset):
        """The serving integration (docs/tiering.md "Serving through
        the executor"): the tier rides ``runtime_provider`` — each
        batch dispatches against the snapshot CURRENT at staging time,
        and a promotion between two submits flips the answer with zero
        retraces and zero ``set_runtime`` calls."""
        st = make_store(flat_index)
        qcap = NQ

        def dispatch(batch, tier=None, **_rt):
            return _grouped_impl(
                tier.view, batch, K, N_PROBES, qcap, 8,
                row_mask=tier.row_mask, use_pallas=False,
                pallas_interpret=False, dequant=tier.dequant,
            )

        q = queries(dataset)
        with ServingExecutor(
            dispatch, (NQ,), dim=D, flush_age_s=0.0,
            runtime_provider=st.runtime,
        ) as ex:
            _, ids_cold = ex.submit(q).result(timeout=60)
            assert np.all(np.asarray(ids_cold) == -1)
            warmed = _grouped_impl._cache_size()
            st.promote(range(N_LISTS))
            _, ids_hot = ex.submit(q).result(timeout=60)
        ref = ivf_flat_search_grouped(
            flat_index, jnp.asarray(q), K, n_probes=N_PROBES,
            qcap=qcap,
        )[1]
        np.testing.assert_array_equal(np.asarray(ids_hot),
                                      np.asarray(ref))
        assert _grouped_impl._cache_size() == warmed, \
            "the cold->hot flip retraced the serving program"


# ----------------------------------------------------------- int8 SQ tier
class TestSQTier:
    def test_sq_codes_tier_as_int8(self, dataset):
        from raft_tpu.spatial.ann.ivf_sq import ivf_sq_search_grouped

        sq = ivf_sq_build(dataset, IVFSQParams(
            n_lists=N_LISTS, kmeans_n_iters=3, seed=1))
        st = make_store(sq)
        st.promote(range(N_LISTS))
        # the hot slab holds CODES: one byte per element, so the HBM
        # budget stretches 4x further than the f32 tier's
        assert st._hot_data.dtype == jnp.int8
        assert st.stats().hot_bytes == st._hot_data.shape[0] * D
        q = queries(dataset)
        _, ids = st.search(q, K, n_probes=N_PROBES)
        _, ref = ivf_sq_search_grouped(sq, jnp.asarray(q), K,
                                       n_probes=N_PROBES, qcap=NQ)
        np.testing.assert_array_equal(np.asarray(ids), np.asarray(ref))
        assert st.measure_recall(q, K, n_probes=N_PROBES) == 1.0


# --------------------------------------------------- bench-row smoke
class TestColdTierRowSmoke:
    def test_cold_tier_row_tiny_config(self, dataset, flat_index):
        """The ISSUE-17 bench row end to end at a tiny CPU config (the
        smoke ci/run.sh's tier stage runs): the row must stamp the
        acceptance evidence — capacity_x, tier hit rate, recall vs the
        fully-resident program — without erroring, on an index a few
        slots can't fully hold."""
        from bench.bench_serving import cold_tier_row

        row = cold_tier_row(
            flat_index, dataset[:64], k=K, n_probes=2,
            capacity_x=4.0, buckets=(8, 16), request_size=4,
            n_templates=8, n_requests=24, chain=(1, 3), escalate=0,
            min_duration_s=0.05, max_requests=200, fracs=(0.8,),
            seed=5,
        )
        assert row["scenario"] == "cold_tier"
        assert "error" not in row
        # the budget really is a fraction of the cold slab
        assert 1 <= row["n_slots"] < N_LISTS
        assert row["capacity_x"] > 1.0
        # both arms measured, recall measured on the template traffic
        assert row["hot_qps"] > 0 and row["tiered_qps"] > 0
        assert 0.0 <= row["recall_vs_hot"] <= 1.0
        if "tier_hit_rate" in row:
            assert 0.0 <= row["tier_hit_rate"] <= 1.0
        assert isinstance(row["tier_degraded"], bool)
