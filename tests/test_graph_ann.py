"""Graph-ANN index tests (ISSUE 19): build determinism + structural
invariants, beam-search recall vs the exact oracle, bit-identity of the
exact rerank tail, tombstone mutation parity, zero-retrace audits
(single-chip and placed), serialization round-trip + corruption, and
the CPU never-imports-the-kernel guarantee. The Pallas beam-scan kernel
itself runs here in interpret mode against its lax mirror (bitwise, on
an integer grid); compiled-TPU parity rides the same helpers on
hardware."""

import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import errors
from raft_tpu.spatial.ann import (
    GraphParams,
    graph_build,
    graph_delete,
    graph_live_mask,
    graph_restore,
    graph_search,
    load_index,
    save_index,
)
from raft_tpu.spatial.ann.graph import _beam_impl
from tests.oracles import np_knn_ids


def recall(ids, oracle):
    hits = sum(
        len(set(a[a >= 0]) & set(b)) for a, b in zip(ids, oracle)
    )
    return hits / oracle.size


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(7)
    x = rng.standard_normal((400, 16)).astype(np.float32)
    q = x[::37][:8] + 0.05 * rng.standard_normal((8, 16)).astype(
        np.float32
    )
    return x, q


@pytest.fixture(scope="module")
def gindex(dataset):
    return graph_build(dataset[0], GraphParams(degree=8, seed=0))


# -- construction ------------------------------------------------------------


def test_build_deterministic(dataset):
    x, _ = dataset
    a = graph_build(x, GraphParams(degree=8, seed=0))
    b = graph_build(x, GraphParams(degree=8, seed=0))
    np.testing.assert_array_equal(np.asarray(a.storage.adjacency),
                                  np.asarray(b.storage.adjacency))
    np.testing.assert_array_equal(np.asarray(a.storage.entries),
                                  np.asarray(b.storage.entries))
    np.testing.assert_array_equal(np.asarray(a.data_padded),
                                  np.asarray(b.data_padded))
    c = graph_build(x, GraphParams(degree=8, seed=1))
    assert not np.array_equal(np.asarray(a.storage.entries),
                              np.asarray(c.storage.entries))


def test_adjacency_invariants(dataset, gindex):
    x, _ = dataset
    n = x.shape[0]
    adj = np.asarray(gindex.storage.adjacency)
    assert adj.shape == (n + 1, 8) and adj.dtype == np.int32
    # sentinel row: all invalid (the padded node expands to nothing)
    assert (adj[n] == -1).all()
    body = adj[:n]
    assert ((body >= -1) & (body < n)).all()
    # no self edges
    assert (body != np.arange(n)[:, None]).all()
    # no duplicate ids within a row (beyond -1 padding)
    for r in range(n):
        real = body[r][body[r] >= 0]
        assert len(real) == len(set(real.tolist()))
    # n >> degree and symmetrize gives every node >= degree candidates,
    # so the fixed-degree back-fill leaves no -1 in real rows here
    assert (body >= 0).all()
    # entries: sorted, unique, in range
    e = np.asarray(gindex.storage.entries)
    assert (np.diff(e) > 0).all() and e[0] >= 0 and e[-1] < n
    # padded data row is the sentinel fill
    dp = np.asarray(gindex.data_padded)
    assert dp.shape == (n + 1, x.shape[1])
    assert (dp[n] == np.float32(1e15)).all()
    np.testing.assert_array_equal(dp[:n], x)


def test_graph_connected_from_entries(dataset, gindex):
    """Every row must be reachable from the seeded entries (else it can
    never be returned at any beam width) — the symmetrized + back-filled
    build keeps this small-world graph one component."""
    n = dataset[0].shape[0]
    adj = np.asarray(gindex.storage.adjacency)[:n]
    seen = np.zeros(n, bool)
    frontier = list(np.asarray(gindex.storage.entries))
    seen[frontier] = True
    while frontier:
        nxt = adj[frontier].ravel()
        nxt = nxt[(nxt >= 0) & ~seen[nxt]]
        seen[nxt] = True
        frontier = list(np.unique(nxt))
    assert seen.all(), f"{(~seen).sum()} rows unreachable from entries"


def test_tiny_n_clamps_degree():
    rng = np.random.default_rng(3)
    x = rng.standard_normal((3, 4)).astype(np.float32)
    idx = graph_build(x, GraphParams(degree=16, seed=0))
    assert idx.storage.degree == 2          # clamped to n - 1
    d, i = graph_search(idx, x[:2], 2, beam=2)
    # exact at this scale: nearest is the row itself
    assert (np.asarray(i)[:, 0] == np.arange(2)).all()
    assert np.asarray(d)[0, 0] == 0.0


# -- search ------------------------------------------------------------------


def test_beam_recall_vs_oracle(dataset, gindex):
    x, q = dataset
    oracle = np_knn_ids(x, q, 10)
    d, i = graph_search(gindex, q, 10, beam=32)
    assert recall(np.asarray(i), oracle) >= 0.95
    # distances are exact f32 L2 of the returned ids
    dn = np.asarray(d)
    ref = np.linalg.norm(
        q[:, None, :] - x[np.asarray(i)], axis=-1
    ).astype(np.float32)
    # gram-form f32 (||q||^2 + ||x||^2 - 2qx) vs float64 diff-norm:
    # cancellation leaves ~1e-5 absolute on the squared scale
    np.testing.assert_allclose(dn, ref, rtol=1e-4, atol=1e-3)
    # and sorted ascending per query
    assert (np.diff(dn, axis=1) >= -1e-6).all()


def test_rerank_tail_bit_identity_saturated_pool():
    """On an integer grid (exactly representable f32 arithmetic), the
    returned squared distances must be BITWISE what the shared rerank
    authority scores for those ids — the beam program's tail IS
    score_l2_candidates, not a reimplementation."""
    from raft_tpu.spatial.ann.common import score_l2_candidates

    rng = np.random.default_rng(11)
    x = rng.integers(-64, 64, size=(256, 8)).astype(np.float32)
    q = rng.integers(-64, 64, size=(6, 8)).astype(np.float32)
    idx = graph_build(x, GraphParams(degree=8, seed=0),
                      metric="sqeuclidean")
    d, i = graph_search(idx, q, 8, beam=16)
    ids = np.asarray(i)
    assert (ids >= 0).all()                  # saturated: full k found
    ref = np.asarray(score_l2_candidates(
        jnp.asarray(q), jnp.asarray(x[ids]),
        jnp.ones(ids.shape, bool),
    ))
    np.testing.assert_array_equal(np.asarray(d), ref)


def test_pallas_interpret_matches_lax_engine(dataset, gindex):
    """The kernel-engine search (interpret mode on CPU) must agree with
    the XLA engine — the sub-chunk-min select + exact-subset rerank is
    lossless w.r.t. the pool merge (the top-P cover argument)."""
    x, q = dataset
    kw = dict(k=10, beam=16, iters=12, hash_bits=14)
    d0, i0 = graph_search(gindex, q, use_pallas=False, **kw)
    d1, i1 = graph_search(gindex, q, use_pallas=True,
                          pallas_interpret=True, **kw)
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))


def test_beam_kernel_matches_lax_mirror_bitwise():
    """graph_kernel sub-chunk minima: interpret-mode Pallas vs the lax
    mirror, bitwise, on an integer grid (the flat/pq kernel discipline:
    both paths take the same bf16 casts, so exactness is checkable)."""
    from raft_tpu.spatial.ann import graph_kernel as gk

    rng = np.random.default_rng(5)
    nq, d, cp = 4, 16, 256
    qp = gk.pad_queries(1)
    qrows = np.zeros((nq, qp, d), np.float32)
    qrows[:, 0, :] = rng.integers(-8, 8, size=(nq, d))
    cands = rng.integers(-8, 8, size=(nq, d, cp)).astype(np.float32)
    bounds = np.broadcast_to(np.array([0, cp], np.int32), (nq, 2))
    a = gk.beam_scan_subchunk_min(
        jnp.asarray(qrows), jnp.asarray(cands), jnp.asarray(bounds),
        interpret=True,
    )
    b = gk.beam_scan_subchunk_min_lax(
        jnp.asarray(qrows), jnp.asarray(cands), jnp.asarray(bounds)
    )
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_search_arg_validation(dataset, gindex):
    x, q = dataset
    with pytest.raises(ValueError):
        graph_search(gindex, q, 0)
    with pytest.raises(ValueError):
        graph_search(gindex, q, x.shape[0] + 1)
    with pytest.raises(ValueError):
        graph_search(gindex, q, 5, beam=0)
    with pytest.raises(ValueError, match="dims differ"):
        graph_search(gindex, q[:, :4], 5)


# -- mutation (tombstones) ---------------------------------------------------


def test_tombstone_delete_restore_parity(dataset, gindex):
    x, q = dataset
    oracle = np_knn_ids(x, q, 10)
    dead = np.unique(oracle[:, 0])           # every query's top-1
    mask = graph_delete(graph_live_mask(gindex), dead)
    _, i_del = graph_search(gindex, q, 10, beam=32, row_mask=mask)
    ids = np.asarray(i_del)
    assert not (np.isin(ids, dead)).any(), \
        "tombstoned rows must never be returned"
    # parity vs the oracle over the LIVE rows only
    live_rows = np.setdiff1d(np.arange(x.shape[0]), dead)
    o_live = live_rows[np_knn_ids(x[live_rows], q, 10)]
    assert recall(ids, o_live) >= 0.95
    # restore: back to the unmasked answer
    mask = graph_restore(mask, dead)
    d_r, i_r = graph_search(gindex, q, 10, beam=32, row_mask=mask)
    d_0, i_0 = graph_search(gindex, q, 10, beam=32)
    np.testing.assert_array_equal(np.asarray(i_r), np.asarray(i_0))
    np.testing.assert_array_equal(np.asarray(d_r), np.asarray(d_0))


def test_mask_flips_zero_retrace(dataset, gindex):
    """The graph_beam contract's claim, re-proven in-process: tombstone
    VALUE flips reuse the warmed program (the mask is a runtime
    operand); only the None <-> array signature change is a second
    program, and warmup covers each."""
    x, q = dataset
    it = gindex.warmup(q.shape[0], k=10, beam=16, with_mask=True)
    size0 = _beam_impl._cache_size()
    mask = graph_live_mask(gindex)
    for dead in ((3,), (3, 5), ()):
        m = graph_delete(mask, np.asarray(dead, np.int64)) \
            if dead else mask
        graph_search(gindex, q, 10, beam=16, iters=it, row_mask=m)
    assert _beam_impl._cache_size() == size0, \
        "tombstone flips must not retrace the beam program"


def test_warmup_audit_passes(dataset, gindex):
    _, q = dataset
    it = gindex.warmup(q.shape[0], k=10, beam=16, audit=True)
    assert isinstance(it, int) and it >= 4
    size0 = _beam_impl._cache_size()
    graph_search(gindex, q, 10, beam=16, iters=it)
    assert _beam_impl._cache_size() == size0


# -- serving placement -------------------------------------------------------


def test_place_index_replicates_whole(dataset, gindex):
    from raft_tpu.comms import build_comms
    from raft_tpu.comms.mnmg_ivf import place_index

    comms = build_comms(jax.devices()[:8])
    placed = place_index(comms, gindex)
    # no sharded fields: the whole index replicates, searches bitwise
    x, q = dataset
    d0, i0 = graph_search(gindex, q, 10, beam=16, iters=10)
    d1, i1 = graph_search(placed, q, 10, beam=16, iters=10)
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
    with pytest.raises(ValueError, match="replicates whole"):
        place_index(comms, gindex, replication=2)


# -- serialization -----------------------------------------------------------


def test_serialize_roundtrip_bitwise(tmp_path, dataset, gindex):
    import json

    x, q = dataset
    p = tmp_path / "graph.npz"
    save_index(gindex, p)
    with np.load(p) as npz:
        header = json.loads(bytes(npz["__header__"]).decode("utf-8"))
    assert header["type"] == "graph" and header["version"] == 5
    loaded = load_index(p)
    assert loaded.metric == gindex.metric
    np.testing.assert_array_equal(np.asarray(loaded.storage.adjacency),
                                  np.asarray(gindex.storage.adjacency))
    np.testing.assert_array_equal(np.asarray(loaded.storage.entries),
                                  np.asarray(gindex.storage.entries))
    np.testing.assert_array_equal(np.asarray(loaded.data_padded),
                                  np.asarray(gindex.data_padded))
    d0, i0 = graph_search(gindex, q, 5, beam=16, iters=10)
    d1, i1 = graph_search(loaded, q, 5, beam=16, iters=10)
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))


def test_serialize_corruption_names_field(tmp_path, gindex):
    from raft_tpu.testing import faults

    p = tmp_path / "graph.npz"
    save_index(gindex, p)
    damaged = faults.corrupt_bytes(p, field="storage.adjacency", seed=2)
    assert damaged == "storage.adjacency"
    with pytest.raises(errors.CorruptIndexError,
                       match="storage.adjacency") as ei:
        load_index(p)
    assert ei.value.field == "storage.adjacency"


# -- platform discipline -----------------------------------------------------


def test_cpu_default_never_imports_kernel_modules():
    """A fresh JAX_PLATFORMS=cpu process building + searching a graph
    index on defaults must not import the beam kernel module (nor drag
    in scan_core through it) — the kernel is an explicit opt-in."""
    prog = (
        "import os, sys\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "import numpy as np\n"
        "from raft_tpu.spatial.ann import GraphParams, graph_build, "
        "graph_search\n"
        "rng = np.random.default_rng(0)\n"
        "x = rng.standard_normal((300, 8)).astype(np.float32)\n"
        "idx = graph_build(x, GraphParams(degree=8, seed=0))\n"
        "it = idx.warmup(8, k=3, beam=8)\n"
        "graph_search(idx, x[:8], 3, beam=8, iters=it)\n"
        "for mod in ('raft_tpu.spatial.ann.graph_kernel',\n"
        "            'raft_tpu.spatial.ann.scan_core'):\n"
        "    assert mod not in sys.modules, \\\n"
        "        f'CPU default graph search imported {mod}'\n"
        "print('OK')\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", prog], capture_output=True, text=True,
        timeout=300,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "OK" in out.stdout
