"""Sharded multi-chip IVF-PQ (comms/mnmg_ivf.py) on the 8-device virtual
CPU mesh — recall parity with the single-device grouped search on the
same data (the reference's 100M-scale FAISS role,
ann_quantized_faiss.cuh:115-206 + knn_merge_parts merge)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.comms import build_comms, mnmg_ivf_pq_build, mnmg_ivf_pq_search
from raft_tpu.random import make_blobs
from raft_tpu.random.rng import RngState
from raft_tpu.spatial.ann import IVFPQParams, ivf_pq_build
from raft_tpu.spatial.ann.ivf_pq import ivf_pq_search_grouped
from tests.oracles import np_knn_ids


def recall(got, true):
    return sum(
        len(set(g.tolist()) & set(t.tolist())) for g, t in zip(got, true)
    ) / true.size


@pytest.fixture(scope="module")
def dataset():
    # sized for CI wall time (VERDICT r4 next-9): the distributed build's
    # CPU-mesh cost is dominated by bf16-emulated kmeans matmuls, which
    # scale with n*d*n_lists — 8k x 24 exercises every code path (split,
    # LPT, exchange rounds, refinement) at ~1/5 the 20k x 32 cost
    x, _ = make_blobs(8_000, 24, n_clusters=40, cluster_std=1.0,
                      state=RngState(11))
    key = jax.random.PRNGKey(5)
    q = jnp.take(
        x, jax.random.randint(key, (192,), 0, x.shape[0]), axis=0
    ) + 0.2 * jax.random.normal(
        jax.random.fold_in(key, 1), (192, 24), jnp.float32
    )
    bi = np_knn_ids(x, q, 10)
    return np.asarray(x), np.asarray(q), bi


@pytest.fixture(scope="module")
def comms():
    return build_comms(jax.devices()[:8])


PARAMS = IVFPQParams(
    n_lists=48, pq_dim=8, kmeans_n_iters=6, seed=3, max_list_cap=512
)


@pytest.fixture(scope="module")
def sharded_index(dataset, comms):
    x, _, _ = dataset
    return mnmg_ivf_pq_build(comms, x, PARAMS)


def test_recall_parity_with_single_device(dataset, comms, sharded_index):
    x, q, bi = dataset
    # single-device oracle: same params, same training path
    single = ivf_pq_build(x, PARAMS)
    _, i1 = ivf_pq_search_grouped(
        single, q, 10, n_probes=16, refine_ratio=4.0, qcap=q.shape[0]
    )
    r_single = recall(np.asarray(i1), bi)

    idx = sharded_index
    d2, i2 = mnmg_ivf_pq_search(
        comms, idx, q, 10, n_probes=16, refine_ratio=4.0, qcap=q.shape[0]
    )
    r_mnmg = recall(np.asarray(i2), bi)
    # each probed list is searched by exactly one chip with the same
    # kernel; per-chip refinement pools are supersets -> parity
    assert r_mnmg >= r_single - 0.02, (r_single, r_mnmg)
    assert r_mnmg > 0.85, r_mnmg
    # merged distances are exact refined L2 and sorted best-first
    d2 = np.asarray(d2)
    assert (np.diff(d2, axis=1) >= -1e-5).all()
    # ids are global row ids
    i2 = np.asarray(i2)
    assert ((i2 >= 0) & (i2 < x.shape[0])).all()


def test_merged_distances_match_exact(dataset, comms, sharded_index):
    """Refined distances must equal the true squared L2 to the returned
    global row id (the refinement is exact f32)."""
    x, q, bi = dataset
    idx = sharded_index
    d2, ids = mnmg_ivf_pq_search(
        comms, idx, q, 10, n_probes=16, refine_ratio=4.0, qcap=q.shape[0]
    )
    d2, ids = np.asarray(d2), np.asarray(ids)
    true = ((q[:, None, :] - x[ids]) ** 2).sum(-1)
    np.testing.assert_allclose(d2, true, rtol=1e-3, atol=1e-2)


def test_rows_cover_all_shards(dataset, comms, sharded_index):
    """Every dataset row lands on exactly one shard; global ids cover n."""
    x, _, _ = dataset
    idx = sharded_index
    sids = np.asarray(idx.sorted_ids)
    szs = np.asarray(idx.list_sizes)
    got = []
    for r in range(comms.size):
        got.append(sids[r, : szs[r].sum()])
    got = np.concatenate(got)
    assert got.shape[0] == x.shape[0]
    assert np.array_equal(np.sort(got), np.arange(x.shape[0]))


def test_codes_only_unrefined(comms):
    """store_raw=False shards search unrefined (ADC distances). Small
    standalone dataset: this only checks the no-raw-slab path, so it
    must not pay a second full-size build (CI wall time, VERDICT r4
    next-9)."""
    x, _ = make_blobs(2_500, 16, n_clusters=10, state=RngState(9))
    x = np.asarray(x)
    q = x[:64]
    bi = np_knn_ids(x, q, 10)
    idx = mnmg_ivf_pq_build(
        comms, x,
        IVFPQParams(n_lists=16, pq_dim=4, kmeans_n_iters=4, seed=3,
                    store_raw=False),
    )
    assert idx.vectors_sorted is None
    _, ids = mnmg_ivf_pq_search(
        comms, idx, q, 10, n_probes=8, refine_ratio=4.0, qcap=q.shape[0]
    )
    assert recall(np.asarray(ids), bi) > 0.5


def test_sharded_index_serialization_roundtrip(tmp_path, dataset, comms,
                                               sharded_index):
    """save/load/place round-trip: identical search results after reload
    (beyond-reference persistence extended to the sharded index)."""
    from raft_tpu.comms.mnmg_ivf import place_index
    from raft_tpu.spatial.ann import load_index, save_index

    x, q, _ = dataset
    p = tmp_path / "mnmg.npz"
    save_index(sharded_index, p)
    d1, i1 = mnmg_ivf_pq_search(
        comms, sharded_index, q, 10, n_probes=16, refine_ratio=4.0,
        qcap=q.shape[0]
    )
    # two load paths: default-device + place_index, and direct-to-mesh
    # streaming (the 100M path where slabs exceed one device)
    for loaded in (place_index(comms, load_index(p)),
                   load_index(p, comms=comms)):
        assert "ranks" in str(loaded.codes_sorted.sharding)
        d2, i2 = mnmg_ivf_pq_search(
            comms, loaded, q, 10, n_probes=16, refine_ratio=4.0,
            qcap=q.shape[0]
        )
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_allclose(
            np.asarray(d1), np.asarray(d2), rtol=1e-6
        )


def test_distributed_build_per_rank_rows(dataset, comms, sharded_index):
    """The per-rank entry point fed ONLY local row shards (ragged last
    shard) must produce the same index as the one-host wrapper — the
    wrapper IS the distributed pipeline, so results are identical, and
    no host-side full-dataset assembly exists anywhere in the path
    (VERDICT r4 item 1)."""
    import jax.sharding

    from raft_tpu.comms.mnmg_ivf import mnmg_ivf_pq_build_distributed

    x, q, bi = dataset
    n, d = x.shape
    Pn = comms.size
    # ragged shards: last rank gets fewer rows (exercises n_valid)
    nloc = -(-n // Pn)
    sh = jax.sharding.NamedSharding(
        comms.mesh, jax.sharding.PartitionSpec(comms.axis, None, None)
    )
    parts = []
    n_valid = []
    for r, dev in enumerate(comms.mesh.devices.flat):
        blk = x[r * nloc:min(n, (r + 1) * nloc)]
        n_valid.append(blk.shape[0])
        if blk.shape[0] < nloc:
            blk = np.pad(blk, ((0, nloc - blk.shape[0]), (0, 0)))
        parts.append(jax.device_put(blk[None], dev))
    xg = jax.make_array_from_single_device_arrays((Pn, nloc, d), sh, parts)
    idx = mnmg_ivf_pq_build_distributed(
        comms, xg, PARAMS, n_valid=np.asarray(n_valid, np.int32)
    )
    d2, i2 = mnmg_ivf_pq_search(
        comms, idx, q, 10, n_probes=16, refine_ratio=4.0, qcap=q.shape[0]
    )
    dw, iw = mnmg_ivf_pq_search(
        comms, sharded_index, q, 10, n_probes=16, refine_ratio=4.0,
        qcap=q.shape[0]
    )
    np.testing.assert_array_equal(np.asarray(i2), np.asarray(iw))
    np.testing.assert_allclose(np.asarray(d2), np.asarray(dw), rtol=1e-6)
    assert recall(np.asarray(i2), bi) > 0.85


def test_distributed_build_ragged_coverage(comms):
    """Genuinely ragged per-rank shards (different valid counts per rank,
    including an empty one) still cover every row exactly once with the
    contiguous global-id convention."""
    import jax.sharding

    from raft_tpu.comms.mnmg_ivf import mnmg_ivf_pq_build_distributed

    rng = np.random.default_rng(3)
    Pn = comms.size
    n_valid = np.array([300, 250, 0, 300, 120, 300, 280, 50][:Pn], np.int32)
    n = int(n_valid.sum())
    d, nloc = 16, 300
    x = rng.standard_normal((n, d)).astype(np.float32)
    sh = jax.sharding.NamedSharding(
        comms.mesh, jax.sharding.PartitionSpec(comms.axis, None, None)
    )
    starts = np.concatenate([[0], np.cumsum(n_valid)[:-1]])
    parts = []
    for r, dev in enumerate(comms.mesh.devices.flat):
        blk = x[starts[r]:starts[r] + n_valid[r]]
        blk = np.pad(blk, ((0, nloc - blk.shape[0]), (0, 0)))
        parts.append(jax.device_put(blk[None], dev))
    xg = jax.make_array_from_single_device_arrays((Pn, nloc, d), sh, parts)
    idx = mnmg_ivf_pq_build_distributed(
        comms, xg,
        IVFPQParams(n_lists=16, pq_dim=4, kmeans_n_iters=6, seed=1,
                    max_list_cap=256),
        n_valid=n_valid,
    )
    sids = np.asarray(idx.sorted_ids)
    szs = np.asarray(idx.list_sizes)
    got = np.concatenate([
        sids[r, : szs[r].sum()] for r in range(comms.size)
    ])
    assert got.shape[0] == n
    assert np.array_equal(np.sort(got), np.arange(n))
    # searching for perturbed dataset rows finds them
    q = x[::7][:64] + 0.01 * rng.standard_normal((64, d)).astype(np.float32)
    _, ids = mnmg_ivf_pq_search(
        comms, idx, q, 1, n_probes=16, refine_ratio=4.0, qcap=64
    )
    hit = (np.asarray(ids)[:, 0] == np.arange(n)[::7][:64]).mean()
    assert hit > 0.9, hit


def test_fewer_lists_than_ranks(comms):
    """Ranks owning zero lists contribute inf and merge out."""
    x, _ = make_blobs(2_000, 16, n_clusters=4, state=RngState(2))
    x = np.asarray(x)
    q = x[:32]
    bi = np_knn_ids(x, q, 5)
    idx = mnmg_ivf_pq_build(
        comms, x,
        IVFPQParams(n_lists=4, pq_dim=4, kmeans_n_iters=6, seed=0,
                    max_list_cap=0),
    )
    _, ids = mnmg_ivf_pq_search(
        comms, idx, q, 5, n_probes=4, refine_ratio=4.0, qcap=q.shape[0]
    )
    r = recall(np.asarray(ids), bi)
    assert r > 0.9, r
