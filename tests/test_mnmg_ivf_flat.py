"""Sharded multi-chip IVF-Flat (comms/mnmg_ivf_flat.py) on the 8-device
virtual CPU mesh — recall parity with the single-chip grouped search and
full-probe exactness (the reference's FAISS IVF-Flat role,
ann_quantized_faiss.cuh:115-142, at the 10-60M multi-chip regime)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.comms import (
    build_comms,
    mnmg_ivf_flat_build,
    mnmg_ivf_flat_search,
)
from raft_tpu.random import make_blobs
from raft_tpu.random.rng import RngState
from raft_tpu.spatial.ann import IVFFlatParams, ivf_flat_build
from raft_tpu.spatial.ann.ivf_flat import ivf_flat_search_grouped
from tests.oracles import np_knn_ids


def recall(got, true):
    return sum(
        len(set(g.tolist()) & set(t.tolist())) for g, t in zip(got, true)
    ) / true.size


@pytest.fixture(scope="module")
def dataset():
    x, _ = make_blobs(12_000, 24, n_clusters=32, cluster_std=1.0,
                      state=RngState(13))
    key = jax.random.PRNGKey(6)
    q = jnp.take(
        x, jax.random.randint(key, (128,), 0, x.shape[0]), axis=0
    ) + 0.2 * jax.random.normal(
        jax.random.fold_in(key, 1), (128, 24), jnp.float32
    )
    bi = np_knn_ids(x, q, 10)
    return np.asarray(x), np.asarray(q), np.asarray(bi)


@pytest.fixture(scope="module")
def comms():
    return build_comms(jax.devices()[:8])


PARAMS = IVFFlatParams(n_lists=48, kmeans_n_iters=8, seed=4)


@pytest.fixture(scope="module")
def sharded_index(dataset, comms):
    x, _, _ = dataset
    return mnmg_ivf_flat_build(comms, x, PARAMS, metric="sqeuclidean")


def test_recall_parity_with_single_chip(dataset, comms, sharded_index):
    x, q, bi = dataset
    single = ivf_flat_build(x, PARAMS, metric="sqeuclidean")
    _, i1 = ivf_flat_search_grouped(
        single, q, 10, n_probes=12, qcap=q.shape[0]
    )
    r_single = recall(np.asarray(i1), bi)

    d2, i2 = mnmg_ivf_flat_search(
        comms, sharded_index, q, 10, n_probes=12, qcap=q.shape[0]
    )
    r_mnmg = recall(np.asarray(i2), bi)
    # each probed list is scored exactly by one chip -> parity (the
    # quantizers differ only via the training subsample draw)
    assert r_mnmg >= r_single - 0.02, (r_single, r_mnmg)
    assert r_mnmg > 0.9, r_mnmg
    d2 = np.asarray(d2)
    assert (np.diff(d2, axis=1) >= -1e-5).all()
    i2 = np.asarray(i2)
    assert ((i2 >= 0) & (i2 < x.shape[0])).all()


def test_full_probe_is_exact(dataset, comms, sharded_index):
    """Probing every list = exact brute force: recall 1.0 and true
    squared distances (the recall-1.0 engine claim, measured)."""
    x, q, bi = dataset
    nl = int(np.asarray(sharded_index.centroids).shape[0])
    d2, ids = mnmg_ivf_flat_search(
        comms, sharded_index, q, 10, n_probes=nl, qcap=q.shape[0]
    )
    assert recall(np.asarray(ids), bi) == 1.0
    true = ((q[:, None, :] - x[np.asarray(ids)]) ** 2).sum(-1)
    np.testing.assert_allclose(np.asarray(d2), true, rtol=1e-4, atol=1e-2)


def test_rows_cover_all_shards(dataset, comms, sharded_index):
    x, _, _ = dataset
    sids = np.asarray(sharded_index.sorted_ids)
    szs = np.asarray(sharded_index.list_sizes)
    got = np.concatenate([
        sids[r, : szs[r].sum()] for r in range(comms.size)
    ])
    assert got.shape[0] == x.shape[0]
    assert np.array_equal(np.sort(got), np.arange(x.shape[0]))


def test_l2_metric_sqrt(dataset, comms):
    x, q, _ = dataset
    idx = mnmg_ivf_flat_build(comms, x, PARAMS, metric="l2")
    d_l2, i_l2 = mnmg_ivf_flat_search(
        comms, idx, q, 5, n_probes=12, qcap=q.shape[0]
    )
    true = np.sqrt(((q[:, None, :] - x[np.asarray(i_l2)]) ** 2).sum(-1))
    np.testing.assert_allclose(np.asarray(d_l2), true, rtol=1e-4,
                               atol=1e-2)


def test_serialization_roundtrip(tmp_path, dataset, comms, sharded_index):
    from raft_tpu.spatial.ann import load_index, save_index

    _, q, _ = dataset
    p = tmp_path / "mnmg_flat.npz"
    save_index(sharded_index, p)
    d1, i1 = mnmg_ivf_flat_search(
        comms, sharded_index, q, 10, n_probes=12, qcap=q.shape[0]
    )
    loaded = load_index(p, comms=comms)  # direct-to-mesh streaming
    assert "ranks" in str(loaded.vectors_sorted.sharding)
    d2, i2 = mnmg_ivf_flat_search(
        comms, loaded, q, 10, n_probes=12, qcap=q.shape[0]
    )
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-6)


def test_distributed_build_ragged_coverage(comms):
    """Per-rank entry with genuinely ragged shards (one empty) covers
    every row exactly once and finds perturbed rows — same contract as
    the PQ sibling (shared pipeline, exact scoring)."""
    import jax.sharding

    from raft_tpu.comms import mnmg_ivf_flat_build_distributed

    rng = np.random.default_rng(8)
    Pn = comms.size
    n_valid = np.array([220, 180, 0, 240, 90, 200, 260, 40][:Pn], np.int32)
    n = int(n_valid.sum())
    d, nloc = 16, 260
    x = rng.standard_normal((n, d)).astype(np.float32)
    sh = jax.sharding.NamedSharding(
        comms.mesh, jax.sharding.PartitionSpec(comms.axis, None, None)
    )
    starts = np.concatenate([[0], np.cumsum(n_valid)[:-1]])
    parts = []
    for r, dev in enumerate(comms.mesh.devices.flat):
        blk = x[starts[r]:starts[r] + n_valid[r]]
        blk = np.pad(blk, ((0, nloc - blk.shape[0]), (0, 0)))
        parts.append(jax.device_put(blk[None], dev))
    xg = jax.make_array_from_single_device_arrays((Pn, nloc, d), sh, parts)
    idx = mnmg_ivf_flat_build_distributed(
        comms, xg,
        IVFFlatParams(n_lists=12, kmeans_n_iters=5, seed=2,
                      max_list_cap=256),
        n_valid=n_valid, metric="sqeuclidean",
    )
    sids = np.asarray(idx.sorted_ids)
    szs = np.asarray(idx.list_sizes)
    got = np.concatenate([
        sids[r, : szs[r].sum()] for r in range(comms.size)
    ])
    assert got.shape[0] == n
    assert np.array_equal(np.sort(got), np.arange(n))
    q = x[::5][:64]
    _, ids = mnmg_ivf_flat_search(
        comms, idx, q, 1, n_probes=12, qcap=64
    )
    assert (np.asarray(ids)[:, 0] == np.arange(n)[::5][:64]).all()
