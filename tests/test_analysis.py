"""jaxlint (raft_tpu.analysis) unit tests — fixture snippets per rule.

Pure AST work: nothing here executes JAX, so the whole file runs in tier-1
with no mesh/TPU. Each rule gets a true positive, a true negative, a
suppression check; the engine gets baseline, JSON output, and CLI checks.
The final test is the self-gate: the repo's own source must lint clean,
and a seeded jax.shard_map fixture must be flagged (the acceptance
criterion for the seed breakage class this subsystem exists to prevent).
"""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from raft_tpu.analysis import Baseline, lint_paths, lint_source
from raft_tpu.analysis.rules import ALL_RULES

REPO = Path(__file__).resolve().parent.parent


def findings(src, rule=None):
    out = lint_source(textwrap.dedent(src))
    if rule is not None:
        out = [f for f in out if f.rule == rule]
    return out


def rules_hit(src):
    return {f.rule for f in findings(src)}


# -- api-compat --------------------------------------------------------------

def test_api_compat_flags_direct_shard_map():
    out = findings("""
        import jax
        f = jax.shard_map(lambda x: x, mesh=m, in_specs=s, out_specs=s)
    """, "api-compat")
    assert len(out) == 1
    assert "jax.shard_map" in out[0].message
    assert "raft_tpu.compat.shard_map" in out[0].message


def test_api_compat_flags_experimental_import_form():
    out = findings("""
        from jax.experimental.shard_map import shard_map
    """, "api-compat")
    assert len(out) == 1


def test_api_compat_flags_aliased_root():
    # alias resolution: `import jax as j` must not hide the hazard
    out = findings("""
        import jax as j
        f = j.tree_map(lambda x: x, t)
    """, "api-compat")
    assert len(out) == 1


def test_api_compat_true_negative_compat_usage():
    out = findings("""
        from raft_tpu import compat
        f = compat.shard_map(lambda x: x, mesh=m, in_specs=s, out_specs=s)
        g = compat.tree_map(lambda x: x, t)
    """, "api-compat")
    assert out == []


def test_api_compat_one_finding_per_use_not_per_attribute_level():
    out = findings("""
        import jax
        f = jax.experimental.shard_map.shard_map(g, mesh=m, in_specs=s,
                                                 out_specs=s)
    """, "api-compat")
    assert len(out) == 1


def test_api_compat_suppression_honored():
    out = findings("""
        import jax
        f = jax.shard_map(g, mesh=m, in_specs=s, out_specs=s)  # jaxlint: disable=api-compat
    """)
    assert [f for f in out if f.rule == "api-compat"] == []


# -- tracer-safety -----------------------------------------------------------

def test_tracer_safety_flags_np_asarray_in_jit():
    out = findings("""
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            return np.asarray(x) + 1
    """, "tracer-safety")
    assert len(out) == 1
    assert "materializes" in out[0].message


def test_tracer_safety_flags_coercion_and_item():
    out = findings("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            a = float(x)
            b = jnp.sum(x).item()
            return a + b
    """, "tracer-safety")
    assert len(out) == 2


def test_tracer_safety_flags_python_if_on_traced_param():
    out = findings("""
        import jax

        @jax.jit
        def f(x):
            if x > 0:
                return x
            return -x
    """, "tracer-safety")
    assert len(out) == 1
    assert "lax.cond" in out[0].message


def test_tracer_safety_callable_passed_to_shard_map_call():
    # traced via call form, not decorator: comms.shard_map(body, ...)
    out = findings("""
        import numpy as np

        def body(x):
            return np.asarray(x)

        sm = comms.shard_map(body, in_specs=s, out_specs=s)
    """, "tracer-safety")
    assert len(out) == 1


def test_tracer_safety_true_negatives():
    out = findings("""
        import jax
        import numpy as np

        @jax.jit
        def f(x, tiled):
            if x.shape[0] > 4:          # static metadata: fine
                y = x * 2
            else:
                y = x
            return y

        def host(x):
            return np.asarray(x)        # host code: numpy is fine

        @jax.jit
        def g(x, n=None):
            if n is None:               # identity check: host-side
                n = x.shape[0]
            return x[:n]
    """, "tracer-safety")
    assert out == []


def test_tracer_safety_static_argnames_param_may_branch():
    out = findings("""
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("mode",))
        def f(x, mode):
            if mode == "fast":
                return x
            return x * 2
    """, "tracer-safety")
    assert out == []


def test_tracer_safety_builtin_map_is_not_a_transform():
    # Python's map() must not mark its callable as traced (lax.map does)
    out = findings("""
        import numpy as np

        def convert(x):
            return np.asarray(x)

        rows2 = list(map(convert, rows))
    """, "tracer-safety")
    assert out == []
    out2 = findings("""
        import numpy as np
        from jax import lax

        def convert(x):
            return np.asarray(x)

        rows2 = lax.map(convert, rows)
    """, "tracer-safety")
    assert len(out2) == 1


def test_facts_decorator_factory_assigned_before_use():
    """ISSUE 12 satellite (facts.py edge case): a transform factory
    bound by ASSIGNMENT — ``jit_k = partial(jax.jit, static_argnames=
    ("mode",))`` — marks every ``@jit_k`` function as traced, with the
    factory call's statics honored (``mode`` may branch; the traced
    param may not)."""
    out = findings("""
        import jax
        from functools import partial

        jit_k = partial(jax.jit, static_argnames=("mode",))

        @jit_k
        def f(x, mode):
            if mode == "fast":          # static via the factory: fine
                return x
            if x > 0:                   # traced param: flagged
                return x
            return -x
    """, "tracer-safety")
    assert len(out) == 1
    assert out[0].line != 0


def test_facts_factory_call_form_and_partial_alias():
    """The factory works in CALL form too (``jit_k(body)``), through an
    aliased ``partial`` import (``from functools import partial as P``)
    — and a factory over a NON-transform never marks anything."""
    out = findings("""
        import numpy as np
        import jax
        from functools import partial as P

        jit_k = P(jax.jit, static_argnames=("mode",))

        def body(x, mode):
            return np.asarray(x)

        g = jit_k(body)
    """, "tracer-safety")
    assert len(out) == 1
    out2 = findings("""
        import numpy as np
        from functools import partial

        runner = partial(sorted, reverse=True)

        @runner
        def h(x):
            return np.asarray(x)        # not traced: numpy is fine
    """, "tracer-safety")
    assert out2 == []


def test_facts_plain_transform_rebinding_alias():
    """``jit2 = jax.jit`` rebinding: both the decorator and call form
    resolve through the assignment alias."""
    out = findings("""
        import numpy as np
        import jax

        jit2 = jax.jit

        @jit2
        def f(x):
            return np.asarray(x)
    """, "tracer-safety")
    assert len(out) == 1
    out2 = findings("""
        import numpy as np
        import jax

        jit2 = jax.jit

        def body(x):
            return np.asarray(x)

        g = jit2(body)
    """, "tracer-safety")
    assert len(out2) == 1


def test_facts_import_alias_chains():
    """Aliasing through ``from x import y as z`` chains: the origin
    path resolves through the rename, so the hazard cannot hide behind
    an alias — and an unrelated local name shadowing a transform tail
    stays clean."""
    out = findings("""
        import numpy as np
        from jax import lax as looper

        def convert(x):
            return np.asarray(x)

        rows = looper.map(convert, batch)
    """, "tracer-safety")
    assert len(out) == 1
    # the same alias passing its callable to a NON-transform attribute
    # marks nothing (origin tracked, tail still decides)
    out2 = findings("""
        import numpy as np
        from jax import lax as looper

        def convert(x):
            return np.asarray(x)

        rows = looper.stop_gradient(convert)
    """, "tracer-safety")
    assert out2 == []


def test_facts_factory_self_rebinding_terminates():
    """``j = partial(j, ...)`` rebinding must not cycle the resolver
    (depth-bounded factory chains)."""
    out = findings("""
        import jax
        from functools import partial

        j = partial(jax.jit, static_argnames=("k",))
        j = partial(j, static_argnames=("k",))

        @j
        def f(x, k):
            return x
    """, "tracer-safety")
    assert out == []


# -- recompile-hazard --------------------------------------------------------

def test_recompile_hazard_dynamic_static_spec():
    out = findings("""
        import jax
        spec = compute_spec()
        f = jax.jit(g, static_argnums=spec)
    """, "recompile-hazard")
    assert len(out) == 1
    assert "static_argnums" in out[0].message


def test_recompile_hazard_literal_spec_ok():
    out = findings("""
        import jax
        f = jax.jit(g, static_argnums=(0, 1))
        h = jax.jit(g, static_argnames=("k",))
    """, "recompile-hazard")
    assert out == []


def test_recompile_hazard_mutable_default():
    out = findings("""
        import jax

        @jax.jit
        def f(x, opts={}):
            return x
    """, "recompile-hazard")
    assert len(out) == 1
    assert "mutable default" in out[0].message


def test_recompile_hazard_fstring_in_traced_body():
    out = findings("""
        import jax

        @jax.jit
        def f(x):
            key = f"shape={x.shape}"
            return cache[key] * x
    """, "recompile-hazard")
    assert len(out) == 1


def test_recompile_hazard_mutated_closure_capture():
    out = findings("""
        import jax

        def outer(xs):
            step = 0
            def body(x):
                return x + step
            for x in xs:
                step += 1
                run(jax.jit(body), x)
    """, "recompile-hazard")
    assert len(out) == 1
    assert "varies per call" in out[0].message


def test_recompile_hazard_fstring_on_host_ok():
    out = findings("""
        import jax

        def host(x):
            label = f"n={x.shape[0]}"   # host-side formatting: fine
            return label
    """, "recompile-hazard")
    assert out == []


# -- x64-hygiene -------------------------------------------------------------

def test_x64_flags_unguarded_jnp_float64():
    out = findings("""
        import jax.numpy as jnp
        y = x.astype(jnp.float64)
    """, "x64-hygiene")
    assert len(out) == 1


def test_x64_guarded_use_is_exempt():
    out = findings("""
        import jax
        import jax.numpy as jnp
        d = jnp.float64 if jax.config.read("jax_enable_x64") else jnp.float32
    """, "x64-hygiene")
    assert out == []


def test_x64_flags_wide_dtype_kwarg_at_jnp_boundary():
    out = findings("""
        import jax.numpy as jnp
        import numpy as np
        a = jnp.zeros(8, dtype=np.float64)
        b = jnp.arange(8, dtype="int64")
        c = jnp.asarray(x, dtype=float)
    """, "x64-hygiene")
    assert len(out) == 3


def test_x64_host_numpy_not_flagged():
    out = findings("""
        import numpy as np
        a = np.zeros(8, dtype=np.float64)   # host numpy: allowed
    """, "x64-hygiene")
    assert out == []


def test_x64_disabling_or_unrelated_store_is_not_exempt():
    # storing a FALSY value (or into an unrelated dict) must not silence
    # the rule — only an actual enable is the harness pattern
    out = findings("""
        import os
        import jax.numpy as jnp
        os.environ["JAX_ENABLE_X64"] = "0"
        a = jnp.zeros(8, dtype=jnp.float64)
    """, "x64-hygiene")
    assert len(out) == 1
    out2 = findings("""
        import jax.numpy as jnp
        cfg = {}
        cfg["JAX_ENABLE_X64"] = "1"
        a = jnp.zeros(8, dtype=jnp.float64)
    """, "x64-hygiene")
    assert len(out2) == 1


def test_x64_env_enable_is_exempt():
    out = findings("""
        import os
        import jax.numpy as jnp
        os.environ["JAX_ENABLE_X64"] = "1"
        a = jnp.zeros(8, dtype=jnp.float64)
    """, "x64-hygiene")
    assert out == []


def test_x64_harness_module_exempt_wholesale():
    out = findings("""
        import jax
        import jax.numpy as jnp
        jax.config.update("jax_enable_x64", True)
        a = jnp.zeros(8, dtype=jnp.float64)
    """, "x64-hygiene")
    assert out == []


# -- prng-discipline ---------------------------------------------------------

def test_prng_flags_key_reuse():
    out = findings("""
        import jax

        def f():
            key = jax.random.PRNGKey(0)
            a = jax.random.normal(key, (4,))
            b = jax.random.uniform(key, (4,))
            return a + b
    """, "prng-discipline")
    assert len(out) == 1
    assert "replay the same stream" in out[0].message


def test_prng_split_and_fold_in_are_clean():
    out = findings("""
        import jax

        def f():
            key = jax.random.PRNGKey(0)
            k1, k2 = jax.random.split(key)
            a = jax.random.normal(k1, (4,))
            b = jax.random.uniform(k2, (4,))
            c = jax.random.normal(jax.random.fold_in(key, 7), (4,))
            return a + b + c
    """, "prng-discipline")
    assert out == []


def test_prng_reassignment_refreshes():
    out = findings("""
        import jax

        def f():
            key = jax.random.PRNGKey(0)
            a = jax.random.normal(key, (4,))
            key = jax.random.fold_in(key, 1)
            b = jax.random.normal(key, (4,))
            return a + b
    """, "prng-discipline")
    assert out == []


def test_prng_exclusive_branches_not_flagged():
    # if/else arms are mutually exclusive — one draw each is fine; but a
    # draw AFTER the branches still sees the key as consumed
    out = findings("""
        import jax

        def f(cond):
            key = jax.random.PRNGKey(0)
            if cond:
                a = jax.random.normal(key, (4,))
            else:
                a = jax.random.uniform(key, (4,))
            return a
    """, "prng-discipline")
    assert out == []
    out2 = findings("""
        import jax

        def f(cond):
            key = jax.random.PRNGKey(0)
            if cond:
                a = jax.random.normal(key, (4,))
            else:
                a = jax.random.uniform(key, (4,))
            return a + jax.random.normal(key, (4,))
    """, "prng-discipline")
    assert len(out2) == 1


def test_prng_suppression_honored():
    out = findings("""
        import jax

        def f():
            key = jax.random.PRNGKey(0)
            a = jax.random.normal(key, (4,))
            b = jax.random.uniform(key, (4,))  # jaxlint: disable=prng-discipline
            return a + b
    """)
    assert [f for f in out if f.rule == "prng-discipline"] == []


# -- adc-gather --------------------------------------------------------------

def test_adc_gather_flags_trailing_axis_lut_gather():
    out = findings("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def scan(lut_t, codes):
            return jnp.take_along_axis(lut_t, codes, axis=2)
    """, rule="adc-gather")
    assert len(out) == 1
    assert "take_along_axis axis=2" in out[0].message


def test_adc_gather_low_axis_and_host_gather_clean():
    out = findings("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def remap(vals, order):
            return jnp.take_along_axis(vals, order, axis=1)

        def offline(lut_t, codes):   # not traced: offline build path
            return jnp.take_along_axis(lut_t, codes, axis=2)
    """, rule="adc-gather")
    assert out == []


def test_adc_gather_flags_onehot_contraction_via_name():
    out = findings("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def adc(lut, codes, K):
            onehot = (codes[..., None] == jnp.arange(K, dtype=jnp.uint8))
            return jax.lax.dot_general(
                lut, onehot.reshape(8, 512, -1).astype(jnp.bfloat16),
                (((2,), (2,)), ((0,), (0,))),
            )
    """, rule="adc-gather")
    assert len(out) == 1
    assert "one-hot contraction" in out[0].message


def test_adc_gather_two_arg_arange_and_broadcasted_iota():
    """Width resolution must see through arange(start, stop) and
    broadcasted_iota(dtype, shape, dimension) — both escaped the first
    cut of the rule (review-caught)."""
    out = findings("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def adc(lut, codes):
            onehot = (codes[..., None] == jnp.arange(0, 256))
            return jnp.einsum("qk,lk->ql", lut, onehot.astype(jnp.float32))
    """, rule="adc-gather")
    assert len(out) == 1
    out = findings("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def adc(lut, codes):
            onehot = (
                codes[..., None]
                == jax.lax.broadcasted_iota(jnp.int32, (8, 512, 256), 2)
            ).astype(jnp.bfloat16)
            return jax.lax.dot_general(
                lut, onehot.reshape(8, 512, -1),
                (((2,), (2,)), ((0,), (0,))),
            )
    """, rule="adc-gather")
    assert len(out) == 1
    # narrow 2-arg arange still clean
    out = findings("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def mask_dot(lut, codes):
            onehot = (codes[..., None] == jnp.arange(0, 16))
            return jnp.einsum("qk,lk->ql", lut, onehot.astype(jnp.float32))
    """, rule="adc-gather")
    assert out == []


def test_adc_gather_narrow_onehot_clean():
    # a probe-mask / small-codebook compare (literal width < 128) feeding
    # a contraction is cheap and stays unflagged
    out = findings("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def mask_dot(lut, codes):
            onehot = (codes[..., None] == jnp.arange(16)).astype(jnp.float32)
            return jnp.einsum("qk,lk->ql", lut, onehot)
    """, rule="adc-gather")
    assert out == []


def test_adc_gather_inline_onehot_operand_flagged():
    out = findings("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def adc(lut, codes):
            return jnp.einsum(
                "qk,lk->ql", lut,
                (codes[:, None] == jnp.arange(256)).astype(jnp.float32),
            )
    """, rule="adc-gather")
    assert len(out) == 1


def test_adc_gather_suppression_honored():
    out = findings("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def remap(lut_t, codes):
            return jnp.take_along_axis(lut_t, codes, axis=2)  # jaxlint: disable=adc-gather
    """, rule="adc-gather")
    assert out == []


# -- wide-distance-materialize -----------------------------------------------

def test_wide_distance_flags_einsum_tile_into_top_k():
    # the exact legacy grouped-flat shape: a (LB, qcap, L) einsum tile
    # massaged through arithmetic + where, then selected over
    out = findings("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def block_fn(qv, mv, qn, mn, invalid, k):
            dots = jnp.einsum("bqd,bld->bql", qv, mv)
            d2 = qn[:, :, None] + mn[:, None, :] - 2.0 * dots
            d2 = jnp.where(invalid, jnp.inf, d2)
            vals, sel = jax.lax.top_k(-d2, k)
            return vals
    """, rule="wide-distance-materialize")
    assert len(out) == 1
    assert "einsum distance tile feeds top_k" in out[0].message


def test_wide_distance_flags_inline_and_method_chain():
    # taint through .reshape/.astype chains and straight into approx_min_k
    out = findings("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def scan(lut, onehot, k):
            d2 = jnp.einsum("bqk,blk->bql", lut, onehot)
            return jax.lax.approx_min_k(
                d2.reshape(8, 64, -1).astype(jnp.float32), k
            )
    """, rule="wide-distance-materialize")
    assert len(out) == 1


def test_wide_distance_chains_on_call_results(  # review regression
):
    """Method chains rooted at a module-alias CALL must re-evaluate the
    inner call instead of bailing on the module root: taint flows
    through `einsum(...).astype(...)` and `where(...).reshape(...)`,
    while `jnp.sum(d2).reshape(...)` still launders."""
    out = findings("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def chained_einsum(qv, mv, k):
            d2 = jnp.einsum("bqd,bld->bql", qv, mv).astype(jnp.float32)
            return jax.lax.top_k(-d2, k)
    """, rule="wide-distance-materialize")
    assert len(out) == 1
    out = findings("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def chained_where(qv, mv, m, k):
            dots = jnp.einsum("bqd,bld->bql", qv, mv)
            d2 = jnp.where(m, jnp.inf, dots).reshape(8, 64, -1)
            return jax.lax.top_k(-d2, k)
    """, rule="wide-distance-materialize")
    assert len(out) == 1
    out = findings("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def laundered(qv, mv, k):
            d2 = jnp.einsum("bqd,bld->bql", qv, mv)
            mins = jnp.min(d2, axis=2).reshape(8, -1)
            return jax.lax.top_k(-mins, k)

        @jax.jit
        def laundered_method(qv, mv, k):
            d2 = jnp.einsum("bqd,bld->bql", qv, mv)
            return jax.lax.top_k(-d2.min(axis=2), k)
    """, rule="wide-distance-materialize")
    assert out == []


def test_wide_distance_order_free_taint_fixpoint():
    """Assignment chains resolve regardless of statement order (the
    fixpoint, not a single forward pass)."""
    out = findings("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def scan(qv, mv, mask, k):
            d3 = jnp.where(mask, jnp.inf, d2)
            d2 = jnp.einsum("bqd,bld->bql", qv, mv)
            return jax.lax.top_k(-d3, k)
    """, rule="wide-distance-materialize")
    assert len(out) == 1


def test_wide_distance_narrow_and_reduced_clean():
    # 2-d scoring einsum (score_l2_candidates shape), a tile consumed by
    # a reduction, and an untraced body: all clean
    out = findings("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def score(cand, qf, k):
            dots = jnp.einsum("qcd,qd->qc", cand, qf)
            return jax.lax.top_k(-dots, k)

        @jax.jit
        def reduced(qv, mv, k):
            d2 = jnp.einsum("bqd,bld->bql", qv, mv)
            mins = jnp.min(d2, axis=2)         # reduction launders
            return jax.lax.top_k(-mins, k)

        def offline(qv, mv, k):                # not traced
            d2 = jnp.einsum("bqd,bld->bql", qv, mv)
            return jax.lax.top_k(-d2, k)
    """, rule="wide-distance-materialize")
    assert out == []


def test_wide_distance_suppression_honored():
    out = findings("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def legacy(qv, mv, k):
            d2 = jnp.einsum("bqd,bld->bql", qv, mv)
            return jax.lax.top_k(-d2, k)  # jaxlint: disable=wide-distance-materialize
    """, rule="wide-distance-materialize")
    assert out == []


def test_wide_distance_legacy_flat_scan_inline_suppressed():
    """ISSUE 11 baseline burn-down: the one intentional legacy caller —
    the XLA grouped flat scan kept as the use_pallas=False bit-stable
    engine — is now INLINE-suppressed at its fixed spelling, so the
    rule raises no finding over the ANN tree and the baseline no longer
    grandfathers it (the coarse probe, the other wide-tile producer,
    is kernelized through scan_core)."""
    result = lint_paths([REPO / "raft_tpu" / "spatial" / "ann"],
                        root=REPO)
    flagged = [f for f in result.findings
               if f.rule == "wide-distance-materialize"]
    assert flagged == []
    base = Baseline.load(REPO / "ci" / "checks" / "jaxlint_baseline.json")
    assert not any(
        "wide-distance-materialize" in key for key in base.counts
    ), "the burned-down baseline entry must not come back"


def test_baseline_entries_match_live_findings_no_drift():
    """The stale-baseline drift check (ISSUE 11 satellite, scope widened
    r12): every entry the committed baseline still grandfathers must
    match a LIVE finding at its exact budgeted count — a baselined line
    that was since fixed (or inline-suppressed) must be REMOVED from the
    baseline, or the burn-down ratchet silently loosens. Conversely no
    live finding may exceed its budget (the repo lints clean — CI's hard
    gate, re-asserted here next to the drift direction it cannot see).
    The lint scope is the FULL gated target set (raft_tpu + tests +
    bench + ci + the top-level scripts, exactly ci/run.sh's list), so a
    future baseline entry under tests/ or bench/ is drift-checked too."""
    base = Baseline.load(REPO / "ci" / "checks" / "jaxlint_baseline.json")
    targets = ["raft_tpu", "tests", "bench", "ci",
               "bench.py", "__graft_entry__.py"]
    result = lint_paths([REPO / t for t in targets], root=REPO)
    live: dict = {}
    for f in result.findings:
        live[f.baseline_key] = live.get(f.baseline_key, 0) + 1
    # no un-baselined findings (the CI gate) ...
    new, old = base.filter(result.findings)
    assert new == [], [f.baseline_key for f in new]
    # ... and no STALE baseline budget: each entry fully consumed
    for key, budget in base.counts.items():
        assert live.get(key, 0) == budget, (
            f"baseline entry no longer matches a live finding "
            f"(live {live.get(key, 0)} != budget {budget}): {key}"
        )


def test_adc_gather_baseline_burned_down_to_inline_proofs():
    """ISSUE 12 satellite: the last two grandfathered ``adc-gather``
    findings (the per-query LUT gather and the grouped one-hot engine,
    both in spatial/ann/ivf_pq.py) are re-verified at the PROGRAM level
    — `ivf_pq_per_query` and `ivf_pq_grouped_onehot` in
    ci/checks/program_contracts.json pin their materialization — and
    carry inline suppressions naming that proof, so the baseline is now
    EMPTY: any new adc-gather spelling anywhere fails CI immediately,
    with no grandfather budget left to absorb it."""
    base = Baseline.load(REPO / "ci" / "checks" / "jaxlint_baseline.json")
    assert base.counts == {}, base.counts
    # the inline proofs exist and name the contract entries
    src = (REPO / "raft_tpu" / "spatial" / "ann" / "ivf_pq.py").read_text()
    assert src.count("jaxlint: disable=adc-gather") >= 3  # 2 proofs + remap
    assert "ivf_pq_per_query" in src
    assert "ivf_pq_grouped_onehot" in src
    contracts = json.loads(
        (REPO / "ci" / "checks" / "program_contracts.json").read_text()
    )["programs"]
    assert "ivf_pq_per_query" in contracts
    assert "ivf_pq_grouped_onehot" in contracts
    # the rule still fires on fresh spellings (no silent weakening)
    out = findings("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def scan(lut_t, codes):
            return jnp.take_along_axis(lut_t, codes, axis=2)
    """, rule="adc-gather")
    assert len(out) == 1


# -- mutation-retrace --------------------------------------------------------

def test_mutation_retrace_flags_int_coercion():
    out = findings("""
        import jax

        @jax.jit
        def bad(delta_counts, l):
            return int(delta_counts[l])
    """, rule="mutation-retrace")
    assert len(out) == 1
    assert "int(delta_counts)" in out[0].message


def test_mutation_retrace_flags_if_and_while_on_state():
    out = findings("""
        import jax

        @jax.jit
        def bad(tombstones, n_dead, x):
            if tombstones.any():
                x = -x
            while n_dead > 0:
                x = x + 1
            return x
    """, rule="mutation-retrace")
    assert len(out) == 2


def test_mutation_retrace_flags_range_and_item_dotted():
    out = findings("""
        import jax

        @jax.jit
        def bad(delta, row_mask, x):
            for i in range(delta.counts[0]):
                x = x + 1
            return x + row_mask.item()
    """, rule="mutation-retrace")
    assert len(out) == 2
    assert any("range(delta_counts)" in f.message for f in out)
    assert any("row_mask.item()" in f.message for f in out)


def test_mutation_retrace_presence_test_and_runtime_use_clean():
    # `is None` presence checks are pytree structure (legitimate
    # statics); jnp.where on the runtime value is THE intended pattern
    out = findings("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def good(x, row_mask=None):
            if row_mask is not None:
                x = jnp.where(row_mask > 0, x, jnp.inf)
            return x
    """, rule="mutation-retrace")
    assert out == []


def test_mutation_retrace_host_side_clean():
    out = findings("""
        def compaction_stats(delta_counts, tombstones):
            return int(delta_counts.sum()), bool(tombstones.any())
    """, rule="mutation-retrace")
    assert out == []


def test_mutation_retrace_unrelated_names_clean():
    out = findings("""
        import jax

        @jax.jit
        def good(alive, delta_cap, x):
            if alive is None:
                return x
            return x[:delta_cap] + int(x.shape[0])
    """, rule="mutation-retrace")
    assert out == []


def test_mutation_retrace_suppression_honored():
    out = findings("""
        import jax

        @jax.jit
        def pinned(delta_counts, x):
            return x[:int(delta_counts)]  # jaxlint: disable=mutation-retrace
    """, rule="mutation-retrace")
    assert out == []


# -- sync-in-hot-path --------------------------------------------------------

def serving_findings(src, rel="raft_tpu/serving/executor.py"):
    out = lint_source(textwrap.dedent(src), rel=rel)
    return [f for f in out if f.rule == "sync-in-hot-path"]


def test_sync_in_hot_path_flags_loop_body_syncs():
    out = serving_findings("""
        import numpy as np
        import jax

        def _drain_loop(self):
            while True:
                out = self.queue.get()
                host = np.asarray(out)
                v = out.item()
                jax.block_until_ready(out)
                out.block_until_ready()
    """)
    assert len(out) == 4
    msgs = " ".join(f.message for f in out)
    assert "np.asarray()" in msgs and ".item()" in msgs
    assert "jax.block_until_ready()" in msgs
    assert all("_drain_loop" in f.message for f in out)


def test_sync_in_hot_path_outside_loop_clean():
    # the intended pattern: sync AFTER readiness, outside the loop
    # (setup/demux), is not a finding even in a serving module
    out = serving_findings("""
        import numpy as np

        def _finish(self, winner):
            host = np.asarray(winner)
            return host

        def warm(self, q0):
            self.dispatch(q0).block_until_ready()
    """)
    assert out == []


def test_sync_in_hot_path_loop_named_function_any_module():
    # a *_loop / serve* function is a hot path wherever it lives
    out = serving_findings("""
        import numpy as np

        def serve_forever(q):
            for batch in q:
                np.asarray(batch)
    """, rel="raft_tpu/comms/frontend.py")
    assert len(out) == 1 and "serve_forever" in out[0].message


def test_sync_in_hot_path_plain_module_function_clean():
    # same shape, non-serving module, unremarkable name: not a hot path
    out = serving_findings("""
        import numpy as np

        def gather(parts):
            outs = []
            for p in parts:
                outs.append(np.asarray(p))
            return outs
    """, rel="raft_tpu/spatial/knn.py")
    assert out == []


def test_sync_in_hot_path_numpy_alias_and_while_test():
    # alias resolution (import numpy as xp) and a sync in the WHILE
    # TEST itself (runs every iteration) are both caught
    out = serving_findings("""
        import numpy as xp

        def _batch_loop(self):
            while self.flag.item():
                x = xp.array(self.next())
    """)
    assert len(out) == 2


def test_sync_in_hot_path_suppression_honored():
    out = serving_findings("""
        import numpy as np

        def _drain_loop(self):
            for fl in self.inflight:
                host = np.asarray(fl.out)  # jaxlint: disable=sync-in-hot-path
    """)
    assert out == []


# -- dcn-wide-collective -----------------------------------------------------

def dcn_findings(src, rel="raft_tpu/comms/frontend.py"):
    out = lint_source(textwrap.dedent(src), rel=rel)
    return [f for f in out if f.rule == "dcn-wide-collective"]


def test_dcn_wide_collective_flags_both_level_collectives():
    # the one-collective-erases-the-win shape: full per-chip payloads
    # over BOTH mesh levels at once, inside a traced body
    out = dcn_findings("""
        import jax
        from jax import lax

        @jax.jit
        def body(vals, gids):
            pd = lax.all_gather(vals, ("dcn", "ici"))
            s = lax.psum(gids, ("dcn", "ici"))
            return pd, s
    """)
    assert len(out) == 2
    msgs = " ".join(f.message for f in out)
    assert "lax.all_gather" in msgs and "lax.psum" in msgs
    assert "'dcn'" in msgs and "hierarchical_merge_select_k" in msgs


def test_dcn_wide_collective_single_axis_stages_clean():
    # the hierarchy's own stages — inner-only and dcn-only collectives —
    # are the FIX, not the hazard
    out = dcn_findings("""
        import jax
        from jax import lax

        @jax.jit
        def hier_tail(vals):
            s = lax.psum_scatter(vals, "ici", tiled=True)
            s = lax.psum(s, "dcn")
            return lax.all_gather(s, "ici", tiled=True)
    """)
    assert out == []


def test_dcn_wide_collective_untraced_body_clean():
    # host-side composition (no tracer) is not a serving-path dispatch
    out = dcn_findings("""
        from jax import lax

        def host_side(vals):
            return lax.all_gather(vals, ("dcn", "ici"))
    """)
    assert out == []


def test_dcn_wide_collective_axis_name_kw_and_outer_spelling():
    out = dcn_findings("""
        import jax
        from jax import lax

        @jax.jit
        def body(x):
            return lax.psum(x, axis_name=("outer", "inner"))
    """)
    assert len(out) == 1 and "'outer'" in out[0].message


def test_dcn_wide_collective_pmean_flagged():
    # pmean moves the same per-chip payload bytes as psum — a mean over
    # both levels must not evade the rule
    out = dcn_findings("""
        import jax
        from jax import lax

        @jax.jit
        def body(x):
            return lax.pmean(x, ("dcn", "ici"))
    """)
    assert len(out) == 1 and "lax.pmean" in out[0].message


def test_dcn_wide_collective_inner_only_tuple_clean():
    # a tuple of ici-level axes crosses no host boundary
    out = dcn_findings("""
        import jax
        from jax import lax

        @jax.jit
        def body(x):
            return lax.psum(x, ("ici_x", "ici_y"))
    """)
    assert out == []


def test_dcn_wide_collective_dynamic_axis_unflagged():
    # variable axes are beyond a lexical linter — no false positive
    out = dcn_findings("""
        import jax
        from jax import lax

        @jax.jit
        def body(x, axes):
            return lax.psum(x, axes)
    """)
    assert out == []


def test_dcn_wide_collective_suppression_honored():
    out = dcn_findings("""
        import jax
        import jax.numpy as jnp
        from jax import lax

        @jax.jit
        def barrier(x):
            return lax.psum(jnp.zeros(()), ("dcn", "ici"))  # jaxlint: disable=dcn-wide-collective
    """)
    assert out == []


# -- metrics-in-traced-body --------------------------------------------------

def metric_findings(src):
    return findings(src, "metrics-in-traced-body")


def test_metrics_in_traced_body_flags_recorder_calls():
    # the trace-time flatline: .inc()/.observe() under a tracer fires
    # once at trace time and never per dispatch
    out = metric_findings("""
        import jax

        @jax.jit
        def body(x, c, h):
            c.inc()
            h.observe(1.0)
            return x + 1
    """)
    assert len(out) == 2
    msgs = " ".join(f.message for f in out)
    assert "trace time" in msgs and "c.inc()" in msgs


def test_metrics_in_traced_body_clock_feeding_recorder():
    # a perf_counter read feeding an observe — through a name and as a
    # direct argument — is a trace-time constant, flagged alongside the
    # recorder call itself
    out = metric_findings("""
        import jax
        import time

        @jax.jit
        def body(x, h):
            t0 = time.perf_counter()
            y = x + 1
            h.observe(time.perf_counter() - t0)
            return y
    """)
    assert len(out) == 3        # the observe + both clock reads
    msgs = " ".join(f.message for f in out)
    assert "TRACE-TIME" in msgs and "perf_counter" in msgs


def test_metrics_in_traced_body_array_at_set_unflagged():
    # `.set` fires only on metric-shaped receivers: the tombstone
    # mask's `arr.at[i].set(0)` (a Subscript receiver) and ordinary
    # setters must never match
    out = metric_findings("""
        import jax

        @jax.jit
        def body(mask, i, cfg):
            cfg.set(True)
            return mask.at[i].set(0)
    """)
    assert out == []


def test_metrics_in_traced_body_gauge_set_flagged():
    out = metric_findings("""
        import jax

        @jax.jit
        def body(x, fill_gauge, reg):
            fill_gauge.set(0.5)
            reg.gauge("depth").set(1)
            return x
    """)
    assert len(out) == 2


def test_metrics_in_traced_body_g_handle_convention_flagged():
    # the repo's own gauge-handle spelling (`self._g_*` / `_G_*`) must
    # not evade the rule the same PR ships (review-caught r13)
    out = metric_findings("""
        import jax

        @jax.jit
        def body(self, x):
            self._g_coverage.set(1.0)
            return x
    """)
    assert len(out) == 1


def test_metrics_in_traced_body_host_path_clean():
    # the intended pattern — stamps AROUND the dispatch on the host —
    # is exactly what the executor does; nothing traced, nothing
    # flagged (bare clock reads in a traced body without a recorder
    # are recompile-hazard territory, not this rule's)
    out = metric_findings("""
        import time

        def serve(h, fn, x):
            t0 = time.perf_counter()
            out = fn(x)
            h.observe((time.perf_counter() - t0) * 1e3)
            return out
    """)
    assert out == []


def test_metrics_in_traced_body_bare_clock_unflagged():
    out = metric_findings("""
        import jax
        import time

        @jax.jit
        def body(x):
            t = time.time()
            return x
    """)
    assert out == []


def test_metrics_in_traced_body_suppression_honored():
    out = metric_findings("""
        import jax

        @jax.jit
        def body(x, c):
            c.inc()  # jaxlint: disable=metrics-in-traced-body
            return x
    """)
    assert out == []


# -- host-fetch-in-traced-body ------------------------------------------------

def hostfetch_findings(src):
    return findings(src, "host-fetch-in-traced-body")


def test_host_fetch_flags_device_put_in_traced_body():
    # the constant-bake: device_put at trace time freezes the slab
    # into the executable — every promotion after it is invisible
    out = hostfetch_findings("""
        import jax

        @jax.jit
        def body(x, slab):
            dev = jax.device_put(slab)
            return x + dev
    """)
    assert len(out) == 1
    assert "COMPILE-TIME constant" in out[0].message


def test_host_fetch_flags_device_put_import_form():
    out = hostfetch_findings("""
        from jax import device_put
        import jax

        @jax.jit
        def body(x, slab):
            return x + device_put(slab)
    """)
    assert len(out) == 1


def test_host_fetch_flags_tier_store_calls():
    # fetch_slab fires on ANY receiver; membership methods only on a
    # tier-shaped one
    out = hostfetch_findings("""
        import jax

        @jax.jit
        def body(x, store, tier_store):
            slab, ids, pos = store.fetch_slab(3)
            tier_store.promote([3])
            tier_store.sync_mutations(None)
            return x
    """)
    assert len(out) == 3
    msgs = " ".join(f.message for f in out)
    assert "trace time" in msgs


def test_host_fetch_generic_promote_unflagged():
    # `plan.promote()` on a non-tier-shaped receiver must not match —
    # promote/request are ordinary verbs elsewhere
    out = hostfetch_findings("""
        import jax

        @jax.jit
        def body(x, plan, session):
            plan.promote([1])
            session.request([2])
            return x
    """)
    assert out == []


def test_host_fetch_flags_pinned_slab_read():
    # the repo's host-mirror convention (`self._data_np`) and the
    # generic host/pinned/cold tokens — a subscript READ traces to a
    # baked-in constant
    out = hostfetch_findings("""
        import jax

        @jax.jit
        def body(self, x, host_slab, i):
            a = self._data_np[3:7]
            b = host_slab[i]
            return x + a.sum() + b
    """)
    assert len(out) == 2
    assert "constant operand" in out[0].message


def test_host_fetch_device_subscript_unflagged():
    # ordinary device-array indexing inside a traced body is the
    # normal pattern — only host-shaped receivers match
    out = hostfetch_findings("""
        import jax

        @jax.jit
        def body(x, offsets, i):
            return x + offsets[i]
    """)
    assert out == []


def test_host_fetch_host_path_clean():
    # the intended pattern — the fetcher thread stages on the host and
    # the traced body sees only runtime operands — is exactly
    # TieredListStore._install_list; nothing traced, nothing flagged
    out = hostfetch_findings("""
        import jax

        def install(store, slot, lid):
            slab, ids, pos = store.fetch_slab(lid)
            dev = jax.device_put(slab)
            return dev
    """)
    assert out == []


def test_host_fetch_suppression_honored():
    out = hostfetch_findings("""
        import jax

        @jax.jit
        def body(x, slab):
            dev = jax.device_put(slab)  # jaxlint: disable=host-fetch-in-traced-body
            return x + dev
    """)
    assert out == []


# -- engine: baseline, CLI, self-gate ---------------------------------------

FIXTURE_BAD = textwrap.dedent("""
    import jax
    f = jax.shard_map(lambda x: x, mesh=m, in_specs=s, out_specs=s)
""")


def test_stale_epoch_read_flags_missing_epoch():
    out = findings("""
        def serve(result_cache, rows):
            return result_cache.lookup(rows)
    """, rule="stale-epoch-read")
    assert len(out) == 1
    assert "threads no mutation epoch" in out[0].message


def test_stale_epoch_read_flags_literal_epoch():
    out = findings("""
        def serve(self, rows):
            a = self._rcache.lookup(rows, epoch=0)
            b = self._rcache.lookup(rows, epoch=None)
    """, rule="stale-epoch-read")
    assert len(out) == 2
    assert all("pins the mutation epoch" in f.message for f in out)


def test_stale_epoch_read_threaded_epoch_clean():
    # a name, an attribute chain, or an epoch-returning call all count
    # as threading a live epoch
    out = findings("""
        def serve(self, cache, rows, epoch):
            a = cache.lookup(rows, epoch=epoch)
            b = cache.lookup(rows, epoch=self._rt_epoch)
            c = cache.lookup(rows, epoch=mindex.epoch)
            d = cache.lookup(rows, epoch=epoch_fn())
            e = cache.lookup(rows, int(current_epoch))
    """, rule="stale-epoch-read")
    assert out == []


def test_stale_epoch_read_epochish_receiver_still_flagged():
    # the receiver's own name never counts as threading an epoch —
    # `epoch_cache.lookup(rows)` is exactly the bypass
    out = findings("""
        def serve(epoch_cache, rows):
            return epoch_cache.lookup(rows)
    """, rule="stale-epoch-read")
    assert len(out) == 1


def test_stale_epoch_read_non_cache_receiver_clean():
    # only cache-shaped receivers are result-cache lookups
    out = findings("""
        def resolve(registry, dns, name):
            a = registry.lookup(name)
            b = dns.lookup(name)
    """, rule="stale-epoch-read")
    assert out == []


def test_stale_epoch_read_suppression_honored():
    out = findings("""
        def serve(frozen_cache, rows):
            return frozen_cache.lookup(rows, epoch=0)  # jaxlint: disable=stale-epoch-read
    """, rule="stale-epoch-read")
    assert out == []


# -- data-dependent-loop-bound -----------------------------------------------

def loopbound_findings(src):
    return findings(src, "data-dependent-loop-bound")


def test_loop_bound_flags_range_of_coerced_operand():
    # the beam-search hazard: a Python loop bound read off a traced
    # value — bakes this batch's trip count into the program
    out = loopbound_findings("""
        import jax

        @jax.jit
        def search(q, n_active):
            acc = q
            for _ in range(int(n_active)):
                acc = acc + 1
            return acc
    """)
    assert len(out) == 1
    assert "range bound int(...n_active...)" in out[0].message
    assert "lax.while_loop" in out[0].message


def test_loop_bound_flags_while_on_item():
    out = loopbound_findings("""
        import jax

        @jax.jit
        def converge(frontier, x):
            while frontier.item() > 0:
                x = x * 2
            return x
    """)
    assert len(out) == 1
    assert "frontier.item()" in out[0].message


def test_loop_bound_flags_fori_and_scan_length():
    out = loopbound_findings("""
        import jax
        from jax import lax

        @jax.jit
        def hop(x, hops):
            y = lax.fori_loop(0, int(hops), lambda i, s: s + 1, x)
            z, _ = lax.scan(lambda c, _: (c, c), y, None,
                            length=int(hops))
            return z
    """)
    assert len(out) == 2
    msgs = " ".join(f.message for f in out)
    assert "fori_loop bound" in msgs and "scan length" in msgs


def test_loop_bound_shape_derived_and_static_clean():
    # shapes are trace-time statics however traced their base is, and
    # declared static params are statics by definition — the intended
    # `iters` discipline must never be flagged
    out = loopbound_findings("""
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("iters",))
        def search(q, iters):
            for _ in range(int(iters)):
                q = q + 1
            for _ in range(int(q.shape[0])):
                q = q * 2
            for _ in range(len(q)):
                q = q - 1
            while int(q.ndim) > 3:
                q = q[0]
            return q
    """)
    assert out == []


def test_loop_bound_host_loop_clean():
    # host orchestration loops over runtime values freely — only
    # traced bodies are in scope
    out = loopbound_findings("""
        def drive(batches, fn):
            for b in range(int(batches)):
                fn(b)
            return None
    """)
    assert out == []


def test_loop_bound_suppression_honored():
    out = loopbound_findings("""
        import jax

        @jax.jit
        def search(q, n_const):
            for _ in range(int(n_const)):  # jaxlint: disable=data-dependent-loop-bound
                q = q + 1
            return q
    """)
    assert out == []


def test_baseline_respected_and_counted(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(FIXTURE_BAD)
    result = lint_paths([bad], root=tmp_path)
    assert len(result.findings) == 1

    bl_path = tmp_path / "baseline.json"
    Baseline().save(bl_path, result.findings)
    bl = Baseline.load(bl_path)

    result2 = lint_paths([bad], root=tmp_path, baseline=bl)
    assert result2.findings == []          # grandfathered
    assert result2.baselined == 1
    assert result2.clean

    # a SECOND identical finding exceeds the baselined count -> new
    bad.write_text(FIXTURE_BAD + "g = jax.shard_map(h, mesh=m, "
                   "in_specs=s, out_specs=s)\n")
    result3 = lint_paths([bad], root=tmp_path, baseline=bl)
    assert len(result3.findings) == 1
    assert not result3.clean


def test_parse_error_is_reported_not_crash(tmp_path):
    bad = tmp_path / "syn.py"
    bad.write_text("def broken(:\n")
    result = lint_paths([bad], root=tmp_path)
    assert len(result.parse_errors) == 1
    assert not result.clean


def test_cli_json_format_and_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(FIXTURE_BAD)
    proc = subprocess.run(
        [sys.executable, "-m", "raft_tpu.analysis", "--format", "json",
         "--no-baseline", str(bad)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload["checked_files"] == 1
    assert len(payload["findings"]) == 1
    assert payload["findings"][0]["rule"] == "api-compat"

    good = tmp_path / "good.py"
    good.write_text("from raft_tpu import compat\n"
                    "f = compat.tree_map(abs, [1])\n")
    proc2 = subprocess.run(
        [sys.executable, "-m", "raft_tpu.analysis", "--format", "json",
         "--no-baseline", str(good)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc2.returncode == 0, proc2.stdout + proc2.stderr


def test_cli_rule_filter_and_list(tmp_path):
    proc = subprocess.run(
        [sys.executable, "-m", "raft_tpu.analysis", "--list-rules"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0
    for rule in ALL_RULES:
        assert rule.name in proc.stdout

    bad = tmp_path / "bad.py"
    bad.write_text(FIXTURE_BAD)
    proc2 = subprocess.run(
        [sys.executable, "-m", "raft_tpu.analysis", "--rules",
         "prng-discipline", "--no-baseline", str(bad)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc2.returncode == 0  # api-compat finding filtered out


@pytest.mark.parametrize("rule", [r.name for r in ALL_RULES])
def test_every_rule_has_description(rule):
    r = next(r for r in ALL_RULES if r.name == rule)
    assert r.description


def test_rule_docs_and_cli_parity():
    """ISSUE 12 satellite: a rule cannot land undocumented. Every rule
    id registered in raft_tpu/analysis/rules/__init__.py must have a
    ``### `rule-id` `` heading in docs/static_analysis.md AND print from
    ``--list-rules`` — and the program-auditor passes (the second tier)
    are held to the same bar against their own docs section and
    ``--list-programs`` is exercised by tests/test_program_audit.py."""
    docs = (REPO / "docs" / "static_analysis.md").read_text()
    for r in ALL_RULES:
        assert f"### `{r.name}`" in docs, (
            f"rule {r.name} has no '### `{r.name}`' heading in "
            "docs/static_analysis.md"
        )
    proc = subprocess.run(
        [sys.executable, "-m", "raft_tpu.analysis", "--list-rules"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0
    for r in ALL_RULES:
        assert f"{r.name}:" in proc.stdout, r.name
    # the program tier's passes are documented in the same file
    from raft_tpu.analysis.program.passes import ALL_PASSES

    for p in ALL_PASSES:
        assert f"### `{p.name}`" in docs, (
            f"program pass {p.name} has no '### `{p.name}`' heading in "
            "docs/static_analysis.md"
        )
        assert p.description
    assert "### `program-contract`" in docs  # the drift rule too
    # the thread tier's rules (ISSUE 16) are held to the same bar:
    # docs heading + their own --threads --list-rules output
    from raft_tpu.analysis.threads.rules import THREAD_RULES

    proc3 = subprocess.run(
        [sys.executable, "-m", "raft_tpu.analysis", "--threads",
         "--list-rules"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc3.returncode == 0
    for r in THREAD_RULES:
        assert f"### `{r.name}`" in docs, (
            f"thread rule {r.name} has no '### `{r.name}`' heading in "
            "docs/static_analysis.md"
        )
        assert r.description
        assert f"{r.name}:" in proc3.stdout, r.name
    for graph_rule in ("lock-order-drift", "lock-order-cycle"):
        assert f"### `{graph_rule}`" in docs
        assert f"{graph_rule}:" in proc3.stdout


def test_repo_lints_clean():
    """The CI gate, as a test: the repo's own source has no new findings."""
    targets = ["raft_tpu", "tests", "bench", "ci",
               "bench.py", "__graft_entry__.py"]
    baseline_path = REPO / "ci" / "checks" / "jaxlint_baseline.json"
    baseline = Baseline.load(baseline_path) if baseline_path.exists() \
        else None
    result = lint_paths([REPO / t for t in targets], root=REPO,
                        baseline=baseline)
    msgs = [f.render() for f in result.parse_errors + result.findings]
    assert result.clean, "\n".join(msgs)
