"""Shared numpy oracles for tests (plain module, not conftest: importing
conftest as `tests.conftest` would load it twice — once by pytest as
top-level `conftest`, once as a package module — duplicating any
module-level state)."""

import numpy as np


def np_knn_ids(x, q, k):
    """Exact numpy kNN oracle (squared-L2 ids) for small test shapes.

    Pure-oracle call sites (ids discarded into recall thresholds) use
    this instead of brute_force_knn so they don't each pay a CPU-mesh
    jit compile for their unique shape (CI wall time; brute_force_knn
    itself is covered by tests/test_knn.py).
    """
    x = np.asarray(x, np.float32)
    q = np.asarray(q, np.float32)
    d2 = (
        (q * q).sum(1)[:, None] + (x * x).sum(1)[None, :]
        - 2.0 * (q @ x.T)
    )
    idx = np.argpartition(d2, k - 1, axis=1)[:, :k]
    vals = np.take_along_axis(d2, idx, axis=1)
    o = np.argsort(vals, axis=1, kind="stable")
    return np.take_along_axis(idx, o, axis=1)
