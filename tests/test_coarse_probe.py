"""Two-level coarse probe (spatial/ann/common.CoarseIndex) + the
in-program cross-shard merge width (``merge_ways=``) — the r6 serving
tentpole:

* build invariants: member blocks PARTITION the centroid set, no empty
  super clusters, the member cap bounds ``max_members``;
* exact degeneration: when every super cluster is scanned the two-level
  probe selects exactly the flat scan's probe set;
* the FLOP acceptance: >= 4x fewer centroid-scoring FLOPs than the flat
  scan at the deployment-scale ~65k-centroid geometry (shape
  accounting), with probe recall within the guardrail on clustered data;
* ``merge_ways`` pads the in-program allgather+select_k merge to
  deployment width with IDENTICAL results (absent peers contribute
  +inf/-1);
* serialize format v3 carries the coarse index (CRC-manifested,
  v2-shaped archives still load with ``coarse=None``).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.spatial.ann import common as ann_common
from raft_tpu.spatial.ann.common import (
    CoarseIndex,
    build_coarse_index,
    coarse_probe,
    coarse_probe_recall,
    default_coarse_geometry,
    n_super_probes,
    probe_flop_accounting,
    two_level_probe,
)


@pytest.fixture(scope="module")
def centroid_set():
    rng = np.random.default_rng(11)
    return rng.standard_normal((300, 16)).astype(np.float32)


@pytest.fixture(scope="module")
def coarse(centroid_set):
    return build_coarse_index(centroid_set, seed=0)


class TestBuild:
    def test_members_partition_the_centroids(self, coarse, centroid_set):
        n = centroid_set.shape[0]
        m = np.asarray(coarse.member_ids)
        real = m[m < n]
        assert sorted(real.tolist()) == list(range(n))
        # padding is exactly the sentinel
        assert (m[m >= n] == n).all()
        assert coarse.n_cents == n

    def test_no_empty_super_clusters(self, coarse, centroid_set):
        n = centroid_set.shape[0]
        m = np.asarray(coarse.member_ids)
        assert ((m < n).sum(axis=1) >= 1).all()

    def test_padded_blocks_carry_member_rows(self, coarse, centroid_set):
        n = centroid_set.shape[0]
        m = np.asarray(coarse.member_ids)
        cpad = np.asarray(coarse.cents_padded)
        valid = m < n
        np.testing.assert_allclose(
            cpad[valid], centroid_set[m[valid]], rtol=1e-6
        )

    def test_member_cap_bounds_max_members(self, centroid_set):
        ci = build_coarse_index(centroid_set, member_cap=16, seed=0)
        assert ci.max_members <= 16
        # still a partition after splitting
        m = np.asarray(ci.member_ids)
        real = m[m < 300]
        assert sorted(real.tolist()) == list(range(300))

    def test_geometry_defaults(self):
        ns, cap = default_coarse_geometry(65792)
        assert ns == 256
        mean = -(-65792 // ns)
        assert cap == -(-3 * mean // 2)

    def test_overprobe_below_one_rejected(self):
        with pytest.raises(ValueError):
            n_super_probes(8, 64, overprobe=0.5)


class TestProbe:
    def test_full_cover_matches_flat_scan(self, coarse, centroid_set):
        """S = n_super reranks every centroid — the probe set must equal
        the flat scan's exactly (the small-index degeneration that makes
        two-level a safe default at any scale)."""
        rng = np.random.default_rng(3)
        q = rng.standard_normal((32, 16)).astype(np.float32)
        flat, _ = coarse_probe(jnp.asarray(q), jnp.asarray(centroid_set), 8)
        two, d2 = two_level_probe(
            q, coarse.super_cents, coarse.member_ids, coarse.cents_padded,
            coarse.n_cents, 8, coarse.n_super,
        )
        np.testing.assert_array_equal(
            np.sort(np.asarray(flat), axis=1),
            np.sort(np.asarray(two), axis=1),
        )
        assert np.isfinite(np.asarray(d2)).all()

    def test_probe_respects_query_blocking(self, coarse, centroid_set):
        """block_q smaller than nq must not change the probe set."""
        rng = np.random.default_rng(4)
        q = rng.standard_normal((21, 16)).astype(np.float32)
        args = (coarse.super_cents, coarse.member_ids,
                coarse.cents_padded, coarse.n_cents, 6, coarse.n_super)
        a, _ = two_level_probe(q, *args, 256)
        b, _ = two_level_probe(q, *args, 4)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_recall_guardrail_on_clustered_data(self):
        """Clustered centroids (the bench regime): two-level probe recall
        vs the flat scan stays high at the default overprobe."""
        rng = np.random.default_rng(9)
        hubs = 8.0 * rng.standard_normal((64, 12)).astype(np.float32)
        cents = (
            np.repeat(hubs, 32, axis=0)
            + rng.standard_normal((2048, 12)).astype(np.float32)
        )
        ci = build_coarse_index(cents, seed=1)
        assert ci.n_super > n_super_probes(8, ci.n_super), \
            "test premise: the probe must actually be sub-linear here"
        q = cents[::97][:20] + 0.1 * rng.standard_normal(
            (20, 12)
        ).astype(np.float32)
        rec = coarse_probe_recall(q, cents, ci, 8)
        assert rec >= 0.95

    def test_flop_acceptance_at_deployment_geometry(self):
        """THE acceptance: >= 4x fewer centroid-scoring FLOPs than the
        flat scan at n_gcents ~ 65k, by shape accounting — even at the
        worst-case geometry the defaults allow (member blocks full to
        the cap, super count inflated by every possible cap split)."""
        n_cents, d, n_probes = 65792, 96, 16
        ns, cap = default_coarse_geometry(n_cents)
        # cap splitting can only ADD ceil(n/cap) supers beyond the base
        worst_ns = ns + -(-n_cents // cap)
        worst = CoarseIndex(
            super_cents=jnp.zeros((worst_ns, d), jnp.float32),
            member_ids=jnp.zeros((worst_ns, cap), jnp.int32),
            cents_padded=jnp.zeros((worst_ns, cap, d), jnp.float32),
            n_cents=n_cents, n_super=worst_ns, max_members=cap,
        )
        acc = probe_flop_accounting(worst, n_probes)
        assert acc["ratio"] >= 4.0, acc

    def test_flop_accounting_matches_built_geometry(self, coarse):
        acc = probe_flop_accounting(coarse, 8, overprobe=2.0)
        d = coarse.super_cents.shape[1]
        S = n_super_probes(8, coarse.n_super, 2.0)
        assert acc["flat"] == 2.0 * coarse.n_cents * d
        assert acc["two_level"] == 2.0 * (
            coarse.n_super + S * coarse.max_members
        ) * d


# ---------------------------------------------------------------------------
# Sharded engines: fused two-level probe + deployment-width merge
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def comms8():
    from raft_tpu.comms import build_comms

    return build_comms(jax.devices()[:8])


@pytest.fixture(scope="module")
def sharded_data():
    rng = np.random.default_rng(21)
    x = rng.standard_normal((640, 16)).astype(np.float32)
    q = x[::41][:10] + 0.05 * rng.standard_normal((10, 16)).astype(
        np.float32
    )
    return x, q


@pytest.fixture(scope="module")
def sharded_flat(comms8, sharded_data):
    from raft_tpu.comms import mnmg_ivf_flat_build
    from raft_tpu.spatial.ann import IVFFlatParams

    return mnmg_ivf_flat_build(
        comms8, sharded_data[0],
        IVFFlatParams(n_lists=8, kmeans_n_iters=4, seed=3),
    )


class TestShardedCoarseProbe:
    def test_attach_and_search_parity(self, comms8, sharded_data,
                                      sharded_flat):
        from raft_tpu.comms import attach_coarse_index, mnmg_ivf_flat_search

        _, q = sharded_data
        cidx = attach_coarse_index(sharded_flat)
        assert cidx.coarse is not None
        v0, i0 = mnmg_ivf_flat_search(
            comms8, sharded_flat, q, 5, n_probes=8, qcap=q.shape[0]
        )
        v1, i1 = mnmg_ivf_flat_search(
            comms8, cidx, q, 5, n_probes=8, qcap=q.shape[0]
        )
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_allclose(np.asarray(v0), np.asarray(v1),
                                   rtol=1e-5)

    def test_stale_coarse_index_rejected(self, comms8, sharded_data,
                                         sharded_flat):
        import dataclasses

        from raft_tpu.comms import attach_coarse_index, mnmg_ivf_flat_search

        _, q = sharded_data
        cidx = attach_coarse_index(sharded_flat)
        # manually widening the probe set WITHOUT rebuilding the coarse
        # index must fail loudly, not probe a stale subset
        bad = dataclasses.replace(
            cidx,
            centroids=jnp.concatenate(
                [jnp.asarray(cidx.centroids),
                 jnp.zeros((4, 16), jnp.float32)]
            ),
            owner=jnp.concatenate(
                [jnp.asarray(cidx.owner),
                 jnp.full((4,), -1, jnp.int32)]
            ),
            local_id=jnp.concatenate(
                [jnp.asarray(cidx.local_id), jnp.zeros((4,), jnp.int32)]
            ),
        )
        with pytest.raises(ValueError, match="coarse index"):
            mnmg_ivf_flat_search(comms8, bad, q, 5, n_probes=8,
                                 qcap=q.shape[0])

    def test_merge_ways_identical_results(self, comms8, sharded_data,
                                          sharded_flat):
        """The in-program merge at deployment width: absent peers pad
        the allgathered payload with +inf/-1, so the 16-way select_k
        returns exactly the 8-way answer."""
        from raft_tpu.comms import mnmg_ivf_flat_search

        _, q = sharded_data
        v0, i0 = mnmg_ivf_flat_search(
            comms8, sharded_flat, q, 5, n_probes=8, qcap=q.shape[0]
        )
        v1, i1 = mnmg_ivf_flat_search(
            comms8, sharded_flat, q, 5, n_probes=8, qcap=q.shape[0],
            merge_ways=16,
        )
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_allclose(np.asarray(v0), np.asarray(v1),
                                   rtol=1e-6)

    def test_merge_ways_narrower_than_mesh_rejected(self, comms8,
                                                    sharded_data,
                                                    sharded_flat):
        from raft_tpu.comms import mnmg_ivf_flat_search

        _, q = sharded_data
        with pytest.raises(ValueError, match="merge_ways"):
            mnmg_ivf_flat_search(
                comms8, sharded_flat, q, 5, n_probes=8, qcap=q.shape[0],
                merge_ways=4,
            )

    def test_merge_ways_pq_engine(self, comms8, sharded_data):
        from raft_tpu.comms import (
            attach_coarse_index, mnmg_ivf_pq_build, mnmg_ivf_pq_search,
        )
        from raft_tpu.spatial.ann import IVFPQParams

        x, q = sharded_data
        idx = mnmg_ivf_pq_build(
            comms8, x,
            IVFPQParams(n_lists=8, pq_dim=4, kmeans_n_iters=3, seed=5),
        )
        v0, i0 = mnmg_ivf_pq_search(comms8, idx, q, 5, n_probes=8,
                                    qcap=q.shape[0])
        cidx = attach_coarse_index(idx)
        v1, i1 = mnmg_ivf_pq_search(
            comms8, cidx, q, 5, n_probes=8, qcap=q.shape[0],
            merge_ways=16,
        )
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))

    def test_warmup_covers_coarse_and_merge_ways(self, comms8,
                                                 sharded_data,
                                                 sharded_flat):
        from raft_tpu.comms import attach_coarse_index

        _, q = sharded_data
        cidx = attach_coarse_index(sharded_flat)
        qc = cidx.warmup(
            comms8, q.shape[0], k=5, n_probes=8, merge_ways=16
        )
        assert isinstance(qc, int) and qc >= 1


# ---------------------------------------------------------------------------
# Serialize format v3
# ---------------------------------------------------------------------------


class TestSerializeV3:
    def test_roundtrip_carries_coarse_with_manifest(
        self, comms8, sharded_data, sharded_flat, tmp_path
    ):
        from raft_tpu.comms import attach_coarse_index, mnmg_ivf_flat_search
        from raft_tpu.spatial.ann import load_index, save_index

        _, q = sharded_data
        cidx = attach_coarse_index(sharded_flat)
        p = tmp_path / "v3.npz"
        save_index(cidx, p)
        with np.load(p) as npz:
            header = json.loads(bytes(npz["__header__"]).decode("utf-8"))
        assert header["version"] == 3
        assert header["static"]["coarse"] == {"__nested__": "CoarseIndex"}
        # the coarse arrays are CRC-manifested like every other array
        for key in ("coarse.super_cents", "coarse.member_ids",
                    "coarse.cents_padded"):
            assert key in header["integrity"]
        loaded = load_index(p, comms=comms8)
        assert loaded.coarse is not None
        assert loaded.coarse.n_super == cidx.coarse.n_super
        v0, i0 = mnmg_ivf_flat_search(
            comms8, cidx, q, 5, n_probes=8, qcap=q.shape[0]
        )
        v1, i1 = mnmg_ivf_flat_search(
            comms8, loaded, q, 5, n_probes=8, qcap=q.shape[0]
        )
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))

    def test_corrupt_coarse_array_names_the_field(
        self, sharded_flat, tmp_path
    ):
        from raft_tpu import errors
        from raft_tpu.comms import attach_coarse_index
        from raft_tpu.spatial.ann import load_index, save_index
        from raft_tpu.testing import faults

        cidx = attach_coarse_index(sharded_flat)
        p = tmp_path / "v3.npz"
        save_index(cidx, p)
        damaged = faults.corrupt_bytes(
            p, field="coarse.super_cents", seed=2
        )
        assert damaged == "coarse.super_cents"
        with pytest.raises(
            errors.CorruptIndexError, match="coarse.super_cents"
        ) as ei:
            load_index(p)
        assert ei.value.field == "coarse.super_cents"

    def test_v2_shaped_archive_loads_without_coarse(
        self, sharded_flat, tmp_path
    ):
        """Read-compat: an archive written before the coarse quantizer
        existed (version 2, no coarse.* keys) loads with coarse=None."""
        from raft_tpu.spatial.ann import load_index, serialize

        arrays, static = {}, {}
        serialize._flatten(sharded_flat, "", arrays, static)
        assert sharded_flat.coarse is None and static["coarse"] is None
        static.pop("coarse")          # a v2 writer never knew the field
        integrity = {
            key: {
                "crc32": serialize._array_crc(arr),
                "shape": list(arr.shape),
                "dtype": str(arr.dtype),
            }
            for key, arr in arrays.items()
        }
        header = {"type": "mnmg_ivf_flat", "version": 2,
                  "static": static, "integrity": integrity}
        p = tmp_path / "v2.npz"
        with open(p, "wb") as f:
            np.savez(
                f,
                __header__=np.frombuffer(
                    json.dumps(header).encode("utf-8"), dtype=np.uint8
                ),
                **arrays,
            )
        idx = load_index(p)
        assert idx.coarse is None
        np.testing.assert_allclose(
            np.asarray(idx.centroids), np.asarray(sharded_flat.centroids)
        )

    def test_reshard_preserves_coarse(self, comms8, sharded_flat):
        from raft_tpu.comms import attach_coarse_index, place_index
        from raft_tpu.comms import build_comms

        cidx = attach_coarse_index(sharded_flat)
        comms4 = build_comms(jax.devices()[:4])
        idx4 = place_index(comms4, cidx)
        assert idx4.sorted_ids.shape[0] == 4
        assert idx4.coarse is not None
        np.testing.assert_allclose(
            np.asarray(idx4.coarse.super_cents),
            np.asarray(cidx.coarse.super_cents),
        )


# ---------------------------------------------------------------------------
# Registry hygiene for the audit helpers used above
# ---------------------------------------------------------------------------


def test_expand_probe_set_replays_coarse_build_args(comms8, sharded_flat):
    """Rebuilding over the expanded probe set must replay the user's
    attach_coarse_index tuning (recorded in CoarseIndex.build_args), not
    silently revert to defaults."""
    from raft_tpu.comms import attach_coarse_index, expand_probe_set

    cidx = attach_coarse_index(
        sharded_flat, member_cap=2, kmeans_n_iters=5, seed=9
    )
    assert cidx.coarse.build_args == (None, 2, 5, 9)
    assert cidx.coarse.max_members <= 2
    far = (1e4 + np.arange(64)[:, None] * np.ones((64, 16))).astype(
        np.float32
    )
    eidx = expand_probe_set(cidx, far)
    assert eidx.coarse.build_args == (None, 2, 5, 9)
    assert eidx.coarse.max_members <= 2
    assert eidx.coarse.n_cents == 8 + 64


def test_auto_qcap_routes_through_two_level_probe(centroid_set, coarse,
                                                  monkeypatch):
    """The qcap=None auto path must not reintroduce the flat centroid
    scan the coarse index removes: with ``coarse`` supplied, every eager
    probe matmul runs against the SUPER set only."""
    seen = []
    orig = ann_common.coarse_probe

    def recording(qf, cents, n_probes, precision=None):
        seen.append(int(cents.shape[0]))
        return orig(qf, cents, n_probes, precision)

    monkeypatch.setattr(ann_common, "coarse_probe", recording)
    rng = np.random.default_rng(6)
    q = rng.standard_normal((16, 16)).astype(np.float32)
    qc, probes = ann_common.resolve_qcap_arg(
        None, q, jnp.asarray(centroid_set), 300, 4, coarse=coarse
    )
    assert isinstance(qc, int) and qc >= 1
    assert seen and all(s == coarse.n_super for s in seen), seen


def test_two_level_probe_plays_with_throughput_audit(centroid_set):
    """resolve_qcap_arg's eager audit keeps using the flat probe for
    drop-fraction sizing — a coarse-equipped index must not break it."""
    rng = np.random.default_rng(5)
    q = rng.standard_normal((16, 16)).astype(np.float32)
    qc, probes = ann_common.resolve_qcap_arg(
        "throughput", q, jnp.asarray(centroid_set), 300, 4
    )
    assert isinstance(qc, int) and qc >= 1 and probes is None


class TestKernelizedProbe:
    """ISSUE 11: the two-level probe routed through the shared
    scan-kernel core — the super scan as a one-slab sub-chunk-min
    kernel, the member rerank as the mini-flat grouped body — pinned
    against the legacy probe and, fused, against the XLA engines."""

    def test_kernel_probe_matches_legacy(self, coarse, centroid_set):
        from raft_tpu.spatial.ann.common import (
            two_level_probe_kernel_supported,
        )

        rng = np.random.default_rng(5)
        q = rng.standard_normal((130, 16)).astype(np.float32)
        S = n_super_probes(8, coarse.n_super, 2.0)
        assert two_level_probe_kernel_supported(
            16, 130, 8, coarse.n_super, coarse.max_members, S
        )
        args = (coarse.super_cents, coarse.member_ids,
                coarse.cents_padded, coarse.n_cents, 8, S)
        p0, d0 = two_level_probe(q, *args)
        p1, d1 = two_level_probe(q, *args, use_pallas=True,
                                 pallas_interpret=True)
        np.testing.assert_array_equal(np.asarray(p0), np.asarray(p1))
        np.testing.assert_allclose(np.asarray(d0), np.asarray(d1),
                                   rtol=1e-5, atol=1e-4)

    def test_kernel_probe_full_cover_degeneration(self, coarse,
                                                  centroid_set):
        """S = n_super through the kernel path still reranks every
        centroid — probe set equals the flat scan's."""
        rng = np.random.default_rng(6)
        q = rng.standard_normal((32, 16)).astype(np.float32)
        flat, _ = coarse_probe(jnp.asarray(q), jnp.asarray(centroid_set),
                               8)
        two, d2 = two_level_probe(
            q, coarse.super_cents, coarse.member_ids, coarse.cents_padded,
            coarse.n_cents, 8, coarse.n_super, use_pallas=True,
            pallas_interpret=True,
        )
        np.testing.assert_array_equal(
            np.sort(np.asarray(flat), axis=1),
            np.sort(np.asarray(two), axis=1),
        )
        assert np.isfinite(np.asarray(d2)).all()

    def test_unsupported_geometry_degrades_to_legacy(self, coarse):
        """use_pallas=True with a probe geometry the shared planner
        rejects serves the legacy path silently — the probe is an
        internal stage, never a loud-fail surface."""
        from raft_tpu.spatial.ann.common import (
            two_level_probe_kernel_supported,
        )

        assert not two_level_probe_kernel_supported(
            1 << 20, 32, 8, coarse.n_super, coarse.max_members, 16
        )
        rng = np.random.default_rng(8)
        q = rng.standard_normal((16, 16)).astype(np.float32)
        S = n_super_probes(4, coarse.n_super, 2.0)
        args = (coarse.super_cents, coarse.member_ids,
                coarse.cents_padded, coarse.n_cents, 4, S)
        # per-row pool too small for n_probes -> predicate rejects and
        # the kernel flag must not change results
        p0, _ = two_level_probe(q, *args)
        p1, _ = two_level_probe(
            q, *args, use_pallas=True, pallas_interpret=True,
        )
        np.testing.assert_array_equal(np.asarray(p0), np.asarray(p1))

    def test_fused_flat_search_with_kernel_probe_bit_identical(
        self, comms8
    ):
        """The kernelized probe ACTIVE inside the fused one-dispatch
        flat program (use_pallas=True engages scan kernel AND probe
        kernel): saturated-pool results bit-identical to the
        legacy-probe XLA-engine dispatch on an INTEGER-EXACT fixture
        (every f32 accumulation exact regardless of order — the same
        discipline as the engines' own bit-identity pins) — the
        ISSUE 11 acceptance pin."""
        from raft_tpu.comms import (
            attach_coarse_index, mnmg_ivf_flat_build,
            mnmg_ivf_flat_search,
        )
        from raft_tpu.spatial.ann import IVFFlatParams, flat_kernel

        rng = np.random.default_rng(13)
        x = rng.integers(-60, 60, (3000, 16)).astype(np.float32)
        q = (x[:48] + rng.integers(-2, 3, (48, 16))).astype(np.float32)
        idx = mnmg_ivf_flat_build(comms8, x, IVFFlatParams(
            n_lists=32, kmeans_n_iters=4, kmeans_init="random",
        ), metric="sqeuclidean")
        cidx = attach_coarse_index(idx)
        l_tile = flat_kernel.plan_l_tile(16, q.shape[0])
        l_pad = -(-int(cidx.max_list) // l_tile) * l_tile
        rr = float(8 * l_pad // flat_kernel.SUBCHUNK) / 5 + 1.0
        kw = dict(n_probes=8, qcap=q.shape[0], rerank_ratio=rr)
        v0, i0 = mnmg_ivf_flat_search(comms8, cidx, q, 5,
                                      use_pallas=False, **kw)
        v1, i1 = mnmg_ivf_flat_search(comms8, cidx, q, 5,
                                      use_pallas=True, **kw)
        np.testing.assert_array_equal(np.asarray(v0), np.asarray(v1))
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))

    def test_fused_kernel_probe_health_flip_zero_retrace(
        self, comms8, sharded_data, sharded_flat, monkeypatch
    ):
        """Health flips with the kernelized probe engaged reuse the one
        compiled program — the probe's kernel/legacy choice is a
        trace-time static, never a runtime branch."""
        from raft_tpu.comms import attach_coarse_index
        from raft_tpu.comms import mnmg_ivf_flat as mod

        _, q = sharded_data
        cidx = attach_coarse_index(sharded_flat)
        created = []
        orig = mod._cached_search

        def recording(*a, **k):
            fn = orig(*a, **k)
            created.append(fn)
            return fn

        monkeypatch.setattr(mod, "_cached_search", recording)
        kw = dict(n_probes=8, qcap=q.shape[0], use_pallas=True)
        m_up = np.ones(8, np.int32)
        m_one = m_up.copy()
        m_one[5] = 0
        mod.mnmg_ivf_flat_search(comms8, cidx, q, 5, shard_mask=m_up,
                                 **kw)
        fn = created[0]
        size0 = fn._cache_size()
        for mask in (m_one, m_up):
            res = mod.mnmg_ivf_flat_search(comms8, cidx, q, 5,
                                           shard_mask=mask, **kw)
        assert all(f is fn for f in created)
        assert fn._cache_size() == size0, \
            "health flips must not retrace the kernel-probe program"
        assert float(jnp.min(res.coverage)) == 1.0

    def test_recall_audit_covers_kernelized_probe(self, coarse,
                                                  centroid_set):
        """coarse_probe_recall(use_pallas=True) audits the KERNELIZED
        probe — the pre-rollout check for query-skewed workloads, where
        the probe's shape-only qcap can drop marginal (query, super)
        pairs. On this fixture (occupancy under the 4x-mean cap) both
        probe engines must audit ~identically."""
        rng = np.random.default_rng(17)
        q = rng.standard_normal((96, 16)).astype(np.float32)
        r_legacy = coarse_probe_recall(q, centroid_set, coarse, 8)
        r_kernel = coarse_probe_recall(q, centroid_set, coarse, 8,
                                       use_pallas=True)
        assert abs(r_kernel - r_legacy) <= 0.01, (r_kernel, r_legacy)
