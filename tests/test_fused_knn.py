"""Fused Pallas distance+select kNN vs naive oracle — the reference's
fused-kernel test niche (cpp/test/spatial/fused_l2_knn.cu pattern: optimized
kernel vs naive distance + sort). Runs the Pallas kernel in interpret mode
on the CPU test platform."""

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu.distance.distance_type import DistanceType
from raft_tpu.spatial.fused_knn import fused_l2_knn, fused_knn_supported
from raft_tpu.spatial.knn import brute_force_knn


def _oracle(q, x, k):
    q64 = q.astype(np.float64)
    x64 = x.astype(np.float64)
    d2 = (
        (q64 * q64).sum(1)[:, None]
        + (x64 * x64).sum(1)[None, :]
        - 2.0 * q64 @ x64.T
    )
    full = np.sqrt(np.maximum(d2, 0))
    oi = np.argsort(full, axis=1)[:, :k]
    return full, np.take_along_axis(full, oi, axis=1)


@pytest.mark.parametrize(
    "m,n,d,k",
    [
        (37, 8192, 19, 7),       # ragged everything
        (128, 5000, 64, 10),     # n not a multiple of the chunk width
        (10, 4109, 96, 3),       # prime-ish n
        (200, 16384, 128, 32),   # larger k
    ],
)
def test_fused_l2_knn_exact(m, n, d, k, rng_np):
    q = rng_np.standard_normal((m, d)).astype(np.float32)
    x = rng_np.standard_normal((n, d)).astype(np.float32)
    dists, idxs = fused_l2_knn(q, x, k, metric=DistanceType.L2SqrtExpanded)
    full, ov = _oracle(q, x, k)
    dv = np.take_along_axis(full, np.asarray(idxs), axis=1)
    np.testing.assert_allclose(dv, ov, atol=1e-6)       # right neighbors
    np.testing.assert_allclose(np.asarray(dists), ov, atol=1e-2)


def test_fused_metric_variants(rng_np):
    q = rng_np.standard_normal((16, 32)).astype(np.float32)
    x = rng_np.standard_normal((6000, 32)).astype(np.float32)
    ds, _ = fused_l2_knn(q, x, 4, metric=DistanceType.L2SqrtExpanded)
    dsq, _ = fused_l2_knn(q, x, 4, metric=DistanceType.L2Expanded)
    dun, _ = fused_l2_knn(q, x, 4, metric=DistanceType.L2Unexpanded)
    np.testing.assert_allclose(np.asarray(ds) ** 2, np.asarray(dsq), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(dsq), np.asarray(dun), rtol=1e-6)


def test_fused_bf16_recall(rng_np):
    """bf16 phase-1 with a wide margin stays near-exact (rescore is f32)."""
    q = rng_np.standard_normal((64, 64)).astype(np.float32)
    x = rng_np.standard_normal((20000, 64)).astype(np.float32)
    k = 10
    _, idxs = fused_l2_knn(
        q, x, k, metric=DistanceType.L2SqrtExpanded,
        compute_dtype=jnp.bfloat16, extra_chunks=32,
    )
    full, ov = _oracle(q, x, k)
    oi = np.argsort(full, axis=1)[:, :k]
    recall = np.mean([
        len(set(np.asarray(idxs)[r]) & set(oi[r])) / k
        for r in range(q.shape[0])
    ])
    assert recall >= 0.99, recall


def test_supported_predicate():
    L2 = DistanceType.L2SqrtExpanded
    assert fused_knn_supported(L2, 10, 100_000, 128, 10)
    assert not fused_knn_supported(L2, 10, 1000, 128, 10)   # too few chunks
    assert not fused_knn_supported(DistanceType.L1, 10, 100_000, 128, 10)
    assert not fused_knn_supported(L2, 10, 100_000, 128, 200)  # k too big


def test_brute_force_knn_use_fused_matches(rng_np):
    q = rng_np.standard_normal((32, 48)).astype(np.float32)
    x = rng_np.standard_normal((8192, 48)).astype(np.float32)
    d1, i1 = brute_force_knn(x, q, 5)
    d2, i2 = brute_force_knn(x, q, 5, use_fused=True)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), atol=1e-4)


def test_brute_force_knn_use_fused_unsupported_raises(rng_np):
    q = rng_np.standard_normal((8, 16)).astype(np.float32)
    x = rng_np.standard_normal((256, 16)).astype(np.float32)
    with pytest.raises(ValueError):
        brute_force_knn(x, q, 3, use_fused=True)  # n too small for cover


def test_fused_knn_row_gather_matches_chunk_gather(rng_np):
    """The big-index phase-2 row-gather branch (taken automatically above
    2 GB, forced here) must agree exactly with the chunk-gather branch."""
    q = rng_np.standard_normal((37, 24)).astype(np.float32)
    y = rng_np.standard_normal((4096 + 57, 24)).astype(np.float32)
    d1, i1 = fused_l2_knn(q, y, 7, gather_rows=False)
    d2, i2 = fused_l2_knn(q, y, 7, gather_rows=True)
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


def test_fused_knn_aligned_index_no_pad(rng_np):
    """Exact-multiple index rows skip the pad copy (big-index regime);
    results must still match the brute-force oracle."""
    q = rng_np.standard_normal((16, 32)).astype(np.float32)
    y = rng_np.standard_normal((8192, 32)).astype(np.float32)
    d1, i1 = fused_l2_knn(q, y, 5, bn=2048)
    full = ((q[:, None, :] - y[None, :, :]) ** 2).sum(-1)
    want_i = np.argsort(full, 1, kind="stable")[:, :5]
    want_d = np.sqrt(np.take_along_axis(full, want_i, 1))
    np.testing.assert_allclose(np.asarray(d1), want_d, rtol=1e-4, atol=1e-4)


def test_fused_knn_index_norms_matches(rng_np):
    """Caller-precomputed index norms (the stored-norms search mode,
    reference knn_brute_force_faiss.cuh:318-330) must be bit-identical to
    the self-computed path, and wrong shapes must raise."""
    q = rng_np.standard_normal((19, 32)).astype(np.float32)
    y = rng_np.standard_normal((12000, 32)).astype(np.float32)
    norms = (y.astype(np.float32) ** 2).sum(1)
    d1, i1 = fused_l2_knn(q, y, 5)
    d2, i2 = fused_l2_knn(q, y, 5, index_norms=norms)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), atol=1e-6)
    with pytest.raises(ValueError):
        fused_l2_knn(q, y, 5, index_norms=norms[:-1])
    # threaded through the partitioned entry point
    d3, i3 = brute_force_knn(
        [y[:6000], y[6000:]], q, 5, use_fused=True,
        index_norms=[norms[:6000], norms[6000:]],
    )
    d4, i4 = brute_force_knn([y[:6000], y[6000:]], q, 5, use_fused=True)
    np.testing.assert_array_equal(np.asarray(i3), np.asarray(i4))


def test_fused_knn_warm_start(rng_np):
    """Warm-starting partition B's search with partition A's (translated)
    results equals one search over A + B (the reference's previous-top-k
    warm path, fused_l2_knn.cuh:947)."""
    q = rng_np.standard_normal((23, 16)).astype(np.float32)
    a = rng_np.standard_normal((4096, 16)).astype(np.float32)
    b = rng_np.standard_normal((4096, 16)).astype(np.float32)
    k = 6
    da, ia = fused_l2_knn(q, a, k)
    db, ib = fused_l2_knn(q, b, k, init=(da, ia + 0))  # a-ids are global
    dfull, ifull = fused_l2_knn(q, np.concatenate([b, a]), k)
    # translate: b ids 0..4095 stay, a ids offset by 4096 in the concat
    got = np.sort(np.asarray(db), axis=1)
    want = np.sort(np.asarray(dfull), axis=1)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_fused_knn_rescore_tiles_beyond_grid_limit(rng_np):
    """Query batches whose padded row count exceeds the per-call grid
    budget must keep the DMA rescore path by tiling into <= grid_limit
    kernel calls (not silently fall back to the XLA gather)."""
    from raft_tpu.spatial.fused_knn import _fused_l2_knn_impl

    q = rng_np.standard_normal((40, 128)).astype(np.float32)
    y = rng_np.standard_normal((4096, 128)).astype(np.float32)
    dt, it = _fused_l2_knn_impl(
        q, y, 5, DistanceType.L2SqrtExpanded, bm=1024, bn=2048, bq2=40,
        extra_chunks=8, compute_dtype=jnp.dtype(jnp.float32),
        interpret=True, grid_limit=16,    # forces ceil(40/16)=3 tiles
    )
    dref, iref = fused_l2_knn(q, y, 5)
    np.testing.assert_array_equal(np.asarray(it), np.asarray(iref))
    np.testing.assert_allclose(
        np.asarray(dt), np.asarray(dref), rtol=1e-5, atol=1e-5
    )
