"""MST / connect_components / single-linkage tests — golden-fixture +
invariant patterns (reference cpp/test/mst.cu, cpp/test/sparse/
connect_components.cu, cpp/test/sparse/linkage.cu)."""

import numpy as np


from raft_tpu.sparse import coo_from_dense
from raft_tpu.sparse.mst import boruvka_mst
from raft_tpu.sparse.connect import connect_components, get_n_components
from raft_tpu.sparse.hierarchy import (
    build_sorted_mst,
    extract_flattened_clusters,
    single_linkage,
)
from raft_tpu.sparse.knn_graph import knn_graph


def naive_mst_weight(dense):
    """Prim's algorithm on a dense adjacency (0 = no edge)."""
    n = dense.shape[0]
    adj = np.where(dense > 0, dense, np.inf)
    visited = np.zeros(n, bool)
    visited[0] = True
    total = 0.0
    for _ in range(n - 1):
        best = np.inf
        bi = bj = -1
        for i in range(n):
            if visited[i]:
                for j in range(n):
                    if not visited[j] and adj[i, j] < best:
                        best, bi, bj = adj[i, j], i, j
        if bj < 0:
            break
        visited[bj] = True
        total += best
    return total, visited.sum()


def random_graph(rng, n, p=0.4):
    dense = rng.random((n, n)).astype(np.float32)
    dense = np.where(rng.random((n, n)) < p, dense, 0)
    dense = np.triu(dense, 1)
    dense = dense + dense.T
    return dense


def test_mst_matches_prim(rng_np):
    for trial in range(3):
        dense = random_graph(rng_np, 20)
        want_w, n_reach = naive_mst_weight(dense)
        if n_reach < 20:
            continue
        mst = boruvka_mst(coo_from_dense(dense))
        k = int(mst.n_edges)
        assert k == 19
        got_w = float(np.asarray(mst.weight)[:k].sum())
        np.testing.assert_allclose(got_w, want_w, rtol=1e-5)
        # connected: one color
        assert int(get_n_components(mst.color)) == 1


def test_mst_forest_on_disconnected():
    # two triangles, no bridge
    dense = np.zeros((6, 6), np.float32)
    for a, b, w in [(0, 1, 1), (1, 2, 2), (0, 2, 3), (3, 4, 1), (4, 5, 2), (3, 5, 3)]:
        dense[a, b] = dense[b, a] = w
    mst = boruvka_mst(coo_from_dense(dense))
    assert int(mst.n_edges) == 4  # 2 edges per triangle
    assert int(get_n_components(mst.color)) == 2
    np.testing.assert_allclose(
        sorted(np.asarray(mst.weight)[:4]), [1, 1, 2, 2]
    )


def test_mst_tie_breaking_deterministic():
    # all weights equal: still a valid spanning tree
    dense = np.ones((8, 8), np.float32) - np.eye(8, dtype=np.float32)
    mst = boruvka_mst(coo_from_dense(dense))
    assert int(mst.n_edges) == 7
    assert int(get_n_components(mst.color)) == 1


def test_connect_components(rng_np):
    # two distant blobs with colors from blob id
    a = rng_np.standard_normal((10, 3)).astype(np.float32)
    b = rng_np.standard_normal((10, 3)).astype(np.float32) + 50
    x = np.concatenate([a, b])
    color = np.repeat([0, 1], 10).astype(np.int32)
    extra = connect_components(x, color)
    nnz = int(extra.nnz)
    assert nnz == 2  # one best edge per component
    rows = np.asarray(extra.rows)[:nnz]
    cols = np.asarray(extra.cols)[:nnz]
    # edges cross the components
    assert all(color[r] != color[c] for r, c in zip(rows, cols))
    # and pick the globally closest cross pair
    d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
    want = d2.min()
    vals = np.asarray(extra.vals)[:nnz]
    np.testing.assert_allclose(vals.min(), want, rtol=1e-4)


def test_build_sorted_mst_stitches(rng_np):
    # kNN graph of two far blobs is disconnected; build_sorted_mst must
    # return a full spanning tree anyway (reference detail/mst.cuh fixup)
    a = rng_np.standard_normal((15, 4)).astype(np.float32)
    b = rng_np.standard_normal((15, 4)).astype(np.float32) + 30
    x = np.concatenate([a, b])
    g = knn_graph(x, 3)
    src, dst, w = build_sorted_mst(x, g)
    assert len(src) == 29
    assert (np.diff(w) >= 0).all()


def test_dendrogram_and_flatten():
    # golden chain: 4 points on a line at 0, 1, 3, 7
    x = np.array([[0.0], [1.0], [3.0], [7.0]], np.float32)
    res = single_linkage(x, n_clusters=2, k=3)
    labels = np.asarray(res.labels)
    # the 2-cluster cut splits at the largest merge (distance 4)
    assert labels[0] == labels[1] == labels[2]
    assert labels[3] != labels[0]
    np.testing.assert_allclose(sorted(res.deltas), [1.0, 2.0, 4.0], rtol=1e-5)


def test_single_linkage_blobs(rng_np):
    from raft_tpu.random import make_blobs, RngState

    X, y = make_blobs(200, 5, n_clusters=3, cluster_std=0.3,
                      state=RngState(11), center_box=(-10.0, 10.0))
    X = np.asarray(X)
    y = np.asarray(y)
    res = single_linkage(X, n_clusters=3, k=8)
    labels = np.asarray(res.labels)
    assert len(np.unique(labels)) == 3
    purity = sum(
        np.bincount(y[labels == c]).max() for c in np.unique(labels)
    ) / len(y)
    assert purity > 0.95


def test_extract_flattened_monotonic():
    children = np.array([[0, 1], [2, 3], [4, 5]])  # n=4: merges -> 4,5,6
    labels = extract_flattened_clusters(children, 4, 2)
    # first-occurrence monotonic labels
    assert labels[0] == 0
    assert labels.max() == 1
