"""Distance engine tests — naive-oracle pattern mirroring the reference
(cpp/test/distance/distance_base.cuh:33-57 naiveDistanceKernel + CompareApprox;
cpp/test/distance/fused_l2_nn.cu)."""

import numpy as np
import pytest

from raft_tpu.distance import (
    DistanceType,
    pairwise_distance,
    fused_l2_nn,
    fused_l2_nn_argmin,
    haversine_distance,
)


# ---------------------------------------------------------------------------
# numpy oracles (deliberately naive, like the reference's naive kernels)
# ---------------------------------------------------------------------------


def naive_pairwise(x, y, metric, p=2.0):
    m, d = x.shape
    n = y.shape[0]
    out = np.zeros((m, n), np.float64)
    x = x.astype(np.float64)
    y = y.astype(np.float64)
    for i in range(m):
        for j in range(n):
            a, b = x[i], y[j]
            if metric in (DistanceType.L2Expanded, DistanceType.L2Unexpanded):
                out[i, j] = np.sum((a - b) ** 2)
            elif metric in (DistanceType.L2SqrtExpanded, DistanceType.L2SqrtUnexpanded):
                out[i, j] = np.sqrt(np.sum((a - b) ** 2))
            elif metric == DistanceType.CosineExpanded:
                out[i, j] = 1 - a @ b / (np.linalg.norm(a) * np.linalg.norm(b))
            elif metric == DistanceType.InnerProduct:
                out[i, j] = a @ b
            elif metric == DistanceType.CorrelationExpanded:
                ac, bc = a - a.mean(), b - b.mean()
                out[i, j] = 1 - ac @ bc / (np.linalg.norm(ac) * np.linalg.norm(bc))
            elif metric == DistanceType.L1:
                out[i, j] = np.sum(np.abs(a - b))
            elif metric == DistanceType.Linf:
                out[i, j] = np.max(np.abs(a - b))
            elif metric == DistanceType.Canberra:
                den = np.abs(a) + np.abs(b)
                t = np.where(den == 0, 0.0, np.abs(a - b) / np.where(den == 0, 1, den))
                out[i, j] = np.sum(t)
            elif metric == DistanceType.LpUnexpanded:
                out[i, j] = np.sum(np.abs(a - b) ** p) ** (1 / p)
            elif metric == DistanceType.HellingerExpanded:
                out[i, j] = np.sqrt(max(0.0, 1 - np.sum(np.sqrt(a * b))))
            elif metric == DistanceType.HammingUnexpanded:
                out[i, j] = np.mean(a != b)
            elif metric == DistanceType.KLDivergence:
                mask = a > 0
                out[i, j] = np.sum(a[mask] * np.log(a[mask] / b[mask]))
            elif metric == DistanceType.JensenShannon:
                mm = 0.5 * (a + b)
                t1 = np.where(a > 0, a * np.log(np.where(a > 0, a, 1) / mm), 0)
                t2 = np.where(b > 0, b * np.log(np.where(b > 0, b, 1) / mm), 0)
                out[i, j] = np.sqrt(max(0.0, 0.5 * np.sum(t1 + t2)))
            elif metric == DistanceType.BrayCurtis:
                out[i, j] = np.sum(np.abs(a - b)) / np.sum(np.abs(a + b))
            elif metric == DistanceType.RusselRaoExpanded:
                out[i, j] = (d - a @ b) / d
            elif metric == DistanceType.JaccardExpanded:
                inter = a @ b
                out[i, j] = 1 - inter / (a.sum() + b.sum() - inter)
            elif metric == DistanceType.DiceExpanded:
                out[i, j] = 1 - 2 * (a @ b) / (a.sum() + b.sum())
            else:
                raise NotImplementedError(metric)
    return out


GENERAL_METRICS = [
    DistanceType.L2Expanded,
    DistanceType.L2SqrtExpanded,
    DistanceType.CosineExpanded,
    DistanceType.InnerProduct,
    DistanceType.CorrelationExpanded,
    DistanceType.L1,
    DistanceType.L2Unexpanded,
    DistanceType.L2SqrtUnexpanded,
    DistanceType.Linf,
    DistanceType.Canberra,
    DistanceType.LpUnexpanded,
    DistanceType.HammingUnexpanded,
    DistanceType.BrayCurtis,
]

PROB_METRICS = [  # require probability-simplex rows
    DistanceType.HellingerExpanded,
    DistanceType.KLDivergence,
    DistanceType.JensenShannon,
]

BOOL_METRICS = [
    DistanceType.RusselRaoExpanded,
    DistanceType.JaccardExpanded,
    DistanceType.DiceExpanded,
]


@pytest.mark.parametrize("metric", GENERAL_METRICS)
@pytest.mark.parametrize("shape", [(33, 17, 5), (64, 128, 32)])
def test_pairwise_general(metric, shape, rng_np):
    m, n, d = shape
    x = rng_np.standard_normal((m, d)).astype(np.float32)
    y = rng_np.standard_normal((n, d)).astype(np.float32)
    got = np.asarray(pairwise_distance(x, y, metric, p=3.0))
    want = naive_pairwise(x, y, metric, p=3.0)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("metric", PROB_METRICS)
def test_pairwise_prob(metric, rng_np):
    m, n, d = 20, 30, 16
    x = rng_np.random((m, d)).astype(np.float32) + 0.01
    y = rng_np.random((n, d)).astype(np.float32) + 0.01
    x /= x.sum(1, keepdims=True)
    y /= y.sum(1, keepdims=True)
    got = np.asarray(pairwise_distance(x, y, metric))
    want = naive_pairwise(x, y, metric)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("metric", BOOL_METRICS)
def test_pairwise_bool(metric, rng_np):
    m, n, d = 25, 18, 40
    x = (rng_np.random((m, d)) > 0.5).astype(np.float32)
    y = (rng_np.random((n, d)) > 0.5).astype(np.float32)
    got = np.asarray(pairwise_distance(x, y, metric))
    want = naive_pairwise(x, y, metric)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_haversine(rng_np):
    x = np.stack(
        [rng_np.uniform(-np.pi / 2, np.pi / 2, 10), rng_np.uniform(-np.pi, np.pi, 10)], 1
    ).astype(np.float32)
    y = np.stack(
        [rng_np.uniform(-np.pi / 2, np.pi / 2, 7), rng_np.uniform(-np.pi, np.pi, 7)], 1
    ).astype(np.float32)
    got = np.asarray(haversine_distance(x, y))
    for i in range(10):
        for j in range(7):
            la1, lo1 = x[i]
            la2, lo2 = y[j]
            a = (
                np.sin((la1 - la2) / 2) ** 2
                + np.cos(la1) * np.cos(la2) * np.sin((lo1 - lo2) / 2) ** 2
            )
            want = 2 * np.arcsin(np.sqrt(a))
            np.testing.assert_allclose(got[i, j], want, rtol=1e-4, atol=1e-5)


def test_metric_string_aliases(rng_np):
    x = rng_np.standard_normal((8, 4)).astype(np.float32)
    a = np.asarray(pairwise_distance(x, x, "euclidean"))
    b = np.asarray(pairwise_distance(x, x, DistanceType.L2SqrtUnexpanded))
    np.testing.assert_allclose(a, b)


def test_fin_op_fused(rng_np):
    # epsilon-neighborhood style fused threshold
    x = rng_np.standard_normal((16, 8)).astype(np.float32)
    got = np.asarray(
        pairwise_distance(x, x, DistanceType.L2Unexpanded, fin_op=lambda d: d < 1.0)
    )
    want = naive_pairwise(x, x, DistanceType.L2Unexpanded) < 1.0
    assert got.dtype == np.bool_
    np.testing.assert_array_equal(got, want)


def test_blocked_matches_unblocked(rng_np):
    x = rng_np.standard_normal((37, 9)).astype(np.float32)
    y = rng_np.standard_normal((21, 9)).astype(np.float32)
    a = np.asarray(pairwise_distance(x, y, DistanceType.L1))
    b = np.asarray(pairwise_distance(x, y, DistanceType.L1, block_m=16))
    np.testing.assert_allclose(a, b, rtol=1e-6)


# ---------------------------------------------------------------------------
# fused L2 NN (reference cpp/test/distance/fused_l2_nn.cu)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("shape", [(57, 13, 8), (128, 300, 32)])
@pytest.mark.parametrize("sqrt", [False, True])
def test_fused_l2_nn(shape, sqrt, rng_np):
    m, n, d = shape
    x = rng_np.standard_normal((m, d)).astype(np.float32)
    y = rng_np.standard_normal((n, d)).astype(np.float32)
    minv, mini = fused_l2_nn(x, y, sqrt=sqrt, block_n=64)
    d2 = naive_pairwise(x, y, DistanceType.L2Unexpanded)
    if sqrt:
        d2 = np.sqrt(d2)
    np.testing.assert_array_equal(np.asarray(mini), d2.argmin(1))
    np.testing.assert_allclose(np.asarray(minv), d2.min(1), rtol=1e-4, atol=1e-4)


def test_fused_l2_nn_masked(rng_np):
    # connect_components-style exclusion: mask out same-color pairs
    m, n, d = 40, 40, 4
    x = rng_np.standard_normal((m, d)).astype(np.float32)
    colors = rng_np.integers(0, 3, m)
    import jax.numpy as jnp

    cj = jnp.asarray(colors)

    def mask_op(rows, cols):
        return cj[rows] != cj[cols]

    minv, mini = fused_l2_nn(x, x, mask_op=mask_op, block_n=16)
    d2 = naive_pairwise(x, x, DistanceType.L2Unexpanded)
    d2[colors[:, None] == colors[None, :]] = np.inf
    np.testing.assert_array_equal(np.asarray(mini), d2.argmin(1))


def test_fused_l2_nn_argmin_matches(rng_np):
    x = rng_np.standard_normal((31, 6)).astype(np.float32)
    y = rng_np.standard_normal((17, 6)).astype(np.float32)
    idx = np.asarray(fused_l2_nn_argmin(x, y))
    d2 = naive_pairwise(x, y, DistanceType.L2Unexpanded)
    np.testing.assert_array_equal(idx, d2.argmin(1))
