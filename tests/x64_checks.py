"""float64 validation pass — run in its OWN process with x64 enabled
(x64 is process-global config, so it cannot share the main test
process). Exercises the places double precision matters in the
reference (solvers, stats, LAP: double instantiations throughout
cpp/src/): each check must beat tolerances unreachable in f32.

Run: JAX_ENABLE_X64=1 JAX_PLATFORMS=cpu python -m tests.x64_checks
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["JAX_ENABLE_X64"] = "1"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def check_decomp():
    from raft_tpu import linalg

    rng = np.random.default_rng(0)
    a = rng.standard_normal((60, 60))
    sym = jnp.asarray((a + a.T) / 2, jnp.float64)
    v, w = linalg.eig_dc(sym)    # (vectors, ascending values)
    w_np = np.linalg.eigvalsh(np.asarray(sym))
    assert np.allclose(np.asarray(w), w_np, atol=1e-12), "eig_dc f64"
    r = np.asarray(sym @ v[:, 0] - w[0] * v[:, 0])
    assert np.linalg.norm(r) < 1e-11, f"eig residual {np.linalg.norm(r)}"

    b = jnp.asarray(rng.standard_normal((80, 20)), jnp.float64)
    u, s, vt = linalg.svd_qr(b)
    s_np = np.linalg.svd(np.asarray(b), compute_uv=False)
    assert np.allclose(np.asarray(s), s_np, atol=1e-12), "svd f64"

    y = jnp.asarray(rng.standard_normal((80,)), jnp.float64)
    for solver in (linalg.lstsq_svd_qr, linalg.lstsq_eig, linalg.lstsq_qr):
        wfit = solver(b, y)
        ref = np.linalg.lstsq(np.asarray(b), np.asarray(y), rcond=None)[0]
        assert np.allclose(np.asarray(wfit), ref, atol=1e-9), solver.__name__
    print("decomp f64 ok")


def check_lanczos():
    from raft_tpu.linalg.lanczos import lanczos_solver

    rng = np.random.default_rng(1)
    a = rng.standard_normal((400, 400))
    sym = (a + a.T) / 2
    mv = lambda v: jnp.asarray(sym) @ v
    w, vecs, res, it = lanczos_solver(
        mv, 400, 3, ncv=40, tol=1e-12, dtype=jnp.float64, return_info=True
    )
    w_np = np.linalg.eigvalsh(sym)[:3]
    # f64 + restarts: accuracy far beyond the f32 floor
    assert np.allclose(np.asarray(w), w_np, atol=1e-10), (w, w_np)
    print("lanczos f64 ok (restarts:", int(it), ")")


def check_stats():
    from raft_tpu.stats import summary

    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((5000, 8)) * 1e6 + 3e8, jnp.float64)
    mu = summary.mean(x)
    sd = summary.stddev(x)
    c = summary.cov(x)
    x_np = np.asarray(x)
    assert np.allclose(np.asarray(mu), x_np.mean(0), rtol=1e-12)
    assert np.allclose(np.asarray(sd), x_np.std(0, ddof=1), rtol=1e-9)
    assert np.allclose(np.asarray(c), np.cov(x_np.T), rtol=1e-8), "cov f64"
    print("stats f64 ok")


def check_lap():
    from raft_tpu.lap import solve_lap
    import itertools

    rng = np.random.default_rng(3)
    cost = jnp.asarray(rng.random((7, 7)), jnp.float64)
    assign, obj = solve_lap(cost)   # (row_assignment, total objective)
    got = float(np.asarray(cost)[np.arange(7), np.asarray(assign)].sum())
    assert abs(float(obj) - got) < 1e-12, "objective computed in f64"
    best = min(
        sum(np.asarray(cost)[i, p[i]] for i in range(7))
        for p in itertools.permutations(range(7))
    )
    assert abs(got - best) < 1e-12, (got, best)
    print("lap f64 ok")


def check_rng():
    from raft_tpu.random.rng import RngState, normal

    v = normal(RngState(5), (200_000,), dtype=jnp.float64, mu=2.0, sigma=3.0)
    assert v.dtype == jnp.float64
    assert abs(float(jnp.mean(v)) - 2.0) < 0.05
    assert abs(float(jnp.std(v)) - 3.0) < 0.05
    print("rng f64 ok")


def main():
    check_decomp()
    check_lanczos()
    check_stats()
    check_lap()
    check_rng()
    print("X64-PASS")


if __name__ == "__main__":
    main()
