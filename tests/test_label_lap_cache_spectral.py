"""Tests for label utils, LAP, vector cache, spectral methods
(reference cpp/test/label/label.cu, cpp/test/lap/lap.cu,
cpp/test/cluster_solvers.cu / eigen_solvers.cu / spectral_matrix.cu)."""

import itertools

import numpy as np
import pytest


from raft_tpu.label import (
    get_unique_labels,
    make_monotonic,
    get_ovr_labels,
    merge_labels,
)
from raft_tpu.lap import solve_lap, solve_lap_batched, LinearAssignmentProblem
from raft_tpu.cache import VectorCache


# -- label -------------------------------------------------------------------


def test_unique_labels():
    labels = np.array([5, 2, 9, 2, 5, 5], np.int32)
    uniq, n = get_unique_labels(labels, capacity=6)
    assert int(n) == 3
    np.testing.assert_array_equal(np.asarray(uniq)[:3], [2, 5, 9])


def test_make_monotonic():
    labels = np.array([10, 3, 10, 99, 3], np.int32)
    out = np.asarray(make_monotonic(labels))
    # ranks by sorted value: 3->0, 10->1, 99->2
    np.testing.assert_array_equal(out, [1, 0, 1, 2, 0])


def test_ovr_labels():
    labels = np.array([0, 1, 2, 1], np.int32)
    out = np.asarray(get_ovr_labels(labels, 1))
    np.testing.assert_array_equal(out, [-1, 1, -1, 1])


def test_merge_labels():
    # a: {0,1} {2,3}; b: {1,2} {0} {3} -> all connected via 1-2 bridge
    a = np.array([0, 0, 2, 2], np.int32)
    b = np.array([0, 1, 1, 3], np.int32)
    out = np.asarray(merge_labels(a, b))
    assert len(np.unique(out)) == 1
    # disjoint labelings stay split
    a = np.array([0, 0, 2, 2], np.int32)
    b = np.array([0, 0, 2, 2], np.int32)
    out = np.asarray(merge_labels(a, b))
    assert len(np.unique(out)) == 2


def test_merge_labels_mask():
    # mask stops the b-induced bridge
    a = np.array([0, 0, 2, 2], np.int32)
    b = np.array([0, 1, 1, 3], np.int32)
    mask = np.array([True, False, False, True])
    out = np.asarray(merge_labels(a, b, mask))
    assert len(np.unique(out)) == 2


# -- LAP ---------------------------------------------------------------------


def brute_force_lap(cost):
    n = cost.shape[0]
    best, best_perm = np.inf, None
    for perm in itertools.permutations(range(n)):
        v = cost[np.arange(n), perm].sum()
        if v < best:
            best, best_perm = v, perm
    return best, np.array(best_perm)


@pytest.mark.parametrize("n", [3, 5, 7])
def test_lap_optimal_small(n, rng_np):
    for _ in range(3):
        cost = rng_np.random((n, n)).astype(np.float32)
        assign, total = solve_lap(cost)
        assign = np.asarray(assign)
        # valid permutation
        assert sorted(assign) == list(range(n))
        want, _ = brute_force_lap(cost)
        np.testing.assert_allclose(float(total), want, rtol=1e-3, atol=1e-3)


def test_lap_maximize(rng_np):
    cost = rng_np.random((6, 6)).astype(np.float32)
    assign, total = solve_lap(cost, maximize=True)
    want, _ = brute_force_lap(-cost)
    np.testing.assert_allclose(float(total), -want, rtol=1e-3, atol=1e-3)


def test_lap_batched(rng_np):
    costs = rng_np.random((4, 5, 5)).astype(np.float32)
    rows, objs = solve_lap_batched(costs)
    for b in range(4):
        want, _ = brute_force_lap(costs[b])
        np.testing.assert_allclose(float(objs[b]), want, rtol=1e-3, atol=1e-3)
    lapobj = LinearAssignmentProblem(5, 4)
    rows2, objs2 = lapobj.solve(costs)
    np.testing.assert_allclose(np.asarray(objs), np.asarray(objs2))


def test_lap_identity():
    # diagonal much cheaper than off-diagonal
    cost = np.ones((8, 8), np.float32) * 10 - 9 * np.eye(8, dtype=np.float32)
    assign, total = solve_lap(cost)
    np.testing.assert_array_equal(np.asarray(assign), np.arange(8))
    np.testing.assert_allclose(float(total), 8.0, rtol=1e-4)


# -- cache -------------------------------------------------------------------


def test_cache_roundtrip(rng_np):
    cache = VectorCache(dim=4, n_sets=8, associativity=2)
    keys = np.arange(10, dtype=np.int32)
    vecs = rng_np.standard_normal((10, 4)).astype(np.float32)
    cache.store_vecs(keys, vecs)
    got, found = cache.get_vecs(keys)
    found = np.asarray(found)
    got = np.asarray(got)
    assert found.sum() >= 8  # some sets may have collided (2-way, 8 sets)
    for i in np.nonzero(found)[0]:
        np.testing.assert_allclose(got[i], vecs[i])
    # misses report not-found
    _, found2 = cache.get_vecs(np.array([1000, 2000], np.int32))
    assert not np.asarray(found2).any()


def test_cache_lru_eviction(rng_np):
    cache = VectorCache(dim=2, n_sets=1, associativity=2)
    v = rng_np.standard_normal((3, 2)).astype(np.float32)
    cache.store_vecs(np.array([0], np.int32), v[:1])
    cache.store_vecs(np.array([1], np.int32), v[1:2])
    cache.get_vecs(np.array([0], np.int32))       # touch 0 -> 1 becomes LRU
    cache.store_vecs(np.array([2], np.int32), v[2:])
    _, f0 = cache.get_vecs(np.array([0], np.int32))
    _, f1 = cache.get_vecs(np.array([1], np.int32))
    _, f2 = cache.get_vecs(np.array([2], np.int32))
    assert bool(np.asarray(f0)[0]) and bool(np.asarray(f2)[0])
    assert not bool(np.asarray(f1)[0])


# -- spectral ----------------------------------------------------------------


def two_clique_graph(n_per=8, bridge_w=0.01):
    n = 2 * n_per
    dense = np.zeros((n, n), np.float32)
    for grp in (range(n_per), range(n_per, n)):
        for i in grp:
            for j in grp:
                if i != j:
                    dense[i, j] = 1.0
    dense[n_per - 1, n_per] = dense[n_per, n_per - 1] = bridge_w
    return dense


def test_spectral_partition():
    from raft_tpu.sparse import coo_from_dense, csr_from_coo
    from raft_tpu.spectral import (
        EigenSolverConfig,
        ClusterSolverConfig,
        partition,
        analyze_partition,
    )

    dense = two_clique_graph()
    csr = csr_from_coo(coo_from_dense(dense))
    res = partition(
        csr, EigenSolverConfig(n_eig_vecs=2), ClusterSolverConfig(n_clusters=2)
    )
    labels = np.asarray(res.labels)
    assert len(np.unique(labels)) == 2
    # the cut must split the two cliques (bridge is the only cross edge)
    assert len(np.unique(labels[:8])) == 1
    assert len(np.unique(labels[8:])) == 1
    edge_cut, cost = analyze_partition(csr, res.labels, 2)
    np.testing.assert_allclose(float(edge_cut), 0.01, atol=1e-4)


def test_modularity_maximization():
    from raft_tpu.sparse import coo_from_dense, csr_from_coo
    from raft_tpu.spectral import (
        EigenSolverConfig,
        ClusterSolverConfig,
        modularity_maximization,
        analyze_modularity,
    )

    dense = two_clique_graph(bridge_w=0.5)
    csr = csr_from_coo(coo_from_dense(dense))
    res = modularity_maximization(
        csr, EigenSolverConfig(n_eig_vecs=2), ClusterSolverConfig(n_clusters=2)
    )
    labels = np.asarray(res.labels)
    q = float(analyze_modularity(csr, res.labels))
    # good community structure: Q close to 0.5 for two equal cliques
    assert q > 0.3
    assert len(np.unique(labels[:8])) == 1
    assert len(np.unique(labels[8:])) == 1
