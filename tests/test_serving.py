"""Serving-path surface (ISSUE r6): warmup pre-compilation, the fused
deployment-view probe set (``expand_probe_set``), the persistent
compilation cache wiring on ``Resources``, the weakref-keyed
throughput-qcap audit registry, chunk-min tie semantics, and the bench
artifact compaction helpers."""

import gc
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.spatial.ann import (
    IVFFlatParams,
    IVFPQParams,
    ivf_flat_build,
    ivf_pq_build,
)
from raft_tpu.spatial.ann import common as ann_common
from raft_tpu.spatial.ann.ivf_flat import (
    _grouped_impl,
    ivf_flat_search_grouped,
)
from raft_tpu.spatial.ann.ivf_pq import (
    _pq_grouped_impl,
    ivf_pq_search_grouped,
)

FLAT_PARAMS = IVFFlatParams(n_lists=16, kmeans_n_iters=4, seed=1)


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(3)
    x = rng.standard_normal((4000, 16)).astype(np.float32)
    q = rng.standard_normal((32, 16)).astype(np.float32)
    return x, q


@pytest.fixture(scope="module")
def flat_index(data):
    return ivf_flat_build(data[0], FLAT_PARAMS)


@pytest.fixture(scope="module")
def comms():
    from raft_tpu.comms import build_comms

    return build_comms(jax.devices()[:8])


@pytest.fixture(scope="module")
def sharded_flat(data, comms):
    from raft_tpu.comms import mnmg_ivf_flat_build

    return mnmg_ivf_flat_build(
        comms, data[0], FLAT_PARAMS, metric="sqeuclidean"
    )


# ---------------------------------------------------------------- warmup
class TestWarmup:
    def test_static_qcap_is_shape_only(self):
        assert ann_common.static_qcap(None, 64, 8, 16) == \
            ann_common.default_qcap(64, 8, 16)
        assert ann_common.static_qcap("throughput", 64, 8, 16) == \
            ann_common.throughput_qcap(64, 8, 16)
        assert ann_common.static_qcap(12, 64, 8, 16) == 12
        with pytest.raises(Exception):
            ann_common.static_qcap(1.5, 64, 8, 16)
        with pytest.raises(Exception):
            ann_common.static_qcap(True, 64, 8, 16)

    def test_flat_warmup_precompiles_serving_program(self, flat_index,
                                                     data):
        qc = flat_index.warmup(32, k=5, n_probes=4)
        assert qc == ann_common.static_qcap(None, 32, 4, 16)
        warmed = _grouped_impl._cache_size()
        v, i = ivf_flat_search_grouped(
            flat_index, data[1], 5, n_probes=4, qcap=qc
        )
        # the warmed program IS the serving program: the real batch must
        # not trace or compile anything new
        assert _grouped_impl._cache_size() == warmed
        assert v.shape == (32, 5) and i.shape == (32, 5)

    def test_pq_warmup_precompiles_serving_program(self, data):
        pq = ivf_pq_build(data[0], IVFPQParams(
            n_lists=16, pq_dim=4, kmeans_n_iters=4, seed=1,
        ))
        qc = pq.warmup(32, k=5, n_probes=4, refine_ratio=2.0)
        warmed = _pq_grouped_impl._cache_size()
        v, i = ivf_pq_search_grouped(
            pq, data[1], 5, n_probes=4, qcap=qc, refine_ratio=2.0,
        )
        assert _pq_grouped_impl._cache_size() == warmed
        assert v.shape == (32, 5)

    def test_mnmg_flat_warmup_then_serve(self, comms, sharded_flat, data):
        from raft_tpu.comms import mnmg_ivf_flat_search

        qc = sharded_flat.warmup(comms, 32, k=5, n_probes=4)
        v, i = mnmg_ivf_flat_search(
            comms, sharded_flat, data[1], 5, n_probes=4, qcap=qc
        )
        assert v.shape == (32, 5)
        assert bool(jnp.all(i >= 0))


# ------------------------------------------- fused deployment-view probe
class TestExpandProbeSet:
    def test_far_extra_centroids_do_not_change_results(self, comms,
                                                       sharded_flat,
                                                       data):
        from raft_tpu.comms import expand_probe_set, mnmg_ivf_flat_search

        _, q = data
        rng = np.random.default_rng(11)
        far = (1e4 + rng.standard_normal((64, 16))).astype(np.float32)
        eidx = expand_probe_set(sharded_flat, far)
        assert eidx.centroids.shape[0] == \
            sharded_flat.centroids.shape[0] + 64
        assert int(eidx.owner[-1]) == -1
        v0, i0 = mnmg_ivf_flat_search(
            comms, sharded_flat, q, 5, n_probes=4, qcap=8
        )
        # the fused program probes the deployment-scale set; far-away
        # unowned centroids are never in any query's top probes, so the
        # shard's answers are unchanged
        v1, i1 = mnmg_ivf_flat_search(comms, eidx, q, 5, n_probes=4,
                                      qcap=8)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_allclose(np.asarray(v0), np.asarray(v1),
                                   rtol=1e-6)

    def test_donated_queries_dispatch(self, comms, sharded_flat, data):
        from raft_tpu.comms import mnmg_ivf_flat_search

        _, q = data
        v0, i0 = mnmg_ivf_flat_search(
            comms, sharded_flat, q, 5, n_probes=4, qcap=8
        )
        # serving mode: fresh buffer per dispatch, donated to the runtime
        v1, i1 = mnmg_ivf_flat_search(
            comms, sharded_flat, jnp.asarray(q), 5, n_probes=4, qcap=8,
            donate_queries=True,
        )
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))

    def test_dimension_mismatch_rejected(self, sharded_flat):
        from raft_tpu.comms import expand_probe_set

        with pytest.raises(Exception):
            expand_probe_set(sharded_flat, np.zeros((4, 7), np.float32))


# ------------------------------------------- persistent compilation cache
class TestCompilationCache:
    def test_resources_arg_enables_and_populates(self, tmp_path):
        from raft_tpu import compat
        from raft_tpu.core import (
            Resources,
            compilation_cache_dir,
            enable_compilation_cache,
        )
        from raft_tpu.core import resources as resources_mod

        cache = str(tmp_path / "xla_cache")
        # the cache is process-global config: capture the pre-test state
        # (CI runs the suite with its own cache dir exported) so teardown
        # RESTORES it — hardcoding None here would silently disable the
        # persistent cache for every later test in this process
        prior = {
            "jax_compilation_cache_dir":
                jax.config.jax_compilation_cache_dir,
            "jax_persistent_cache_min_compile_time_secs":
                jax.config.jax_persistent_cache_min_compile_time_secs,
            "jax_persistent_cache_min_entry_size_bytes":
                jax.config.jax_persistent_cache_min_entry_size_bytes,
        }
        prior_enabled = resources_mod._cache_dir_enabled
        try:
            Resources(compilation_cache_dir=cache)
            assert compilation_cache_dir() == cache

            @jax.jit
            def f(x):
                return x * 2.0 + 1.0

            f(jnp.arange(128.0)).block_until_ready()
            n_files = sum(len(fs) for _, _, fs in os.walk(cache))
            assert n_files > 0
            # idempotent re-enable (the serving bootstrap path calls it
            # once per Resources construction)
            enable_compilation_cache(cache)
            assert compilation_cache_dir() == cache
        finally:
            for name, val in prior.items():
                jax.config.update(name, val)
            compat.compilation_cache_reset()
            resources_mod._cache_dir_enabled = prior_enabled


# -------------------------------------- weakref-keyed throughput audit
class TestThroughputAuditRegistry:
    def test_registry_weakref_evicts_dead_entries(self):
        reg = ann_common._AuditRegistry()
        a = jnp.arange(8.0)
        sig = (16, 4, 8, 64)
        reg.add(a, sig)
        assert reg.seen(a, sig)
        assert not reg.seen(a, (1, 1, 1, 1))
        del a
        gc.collect()
        assert not reg._by_id

    def test_rebuilt_same_shape_index_is_reaudited(self, data,
                                                   monkeypatch):
        x, q = data
        calls = []
        orig = ann_common.probe_drop_stats

        def counting(*a, **k):
            calls.append(1)
            return orig(*a, **k)

        monkeypatch.setattr(ann_common, "probe_drop_stats", counting)

        def build_and_search():
            idx = ivf_flat_build(x, FLAT_PARAMS)
            ivf_flat_search_grouped(idx, q, 5, n_probes=4,
                                    qcap="throughput")
            return idx

        idx = build_and_search()
        n_first = len(calls)
        assert n_first >= 1
        # second search on the SAME index: audited once per process
        ivf_flat_search_grouped(idx, q, 5, n_probes=4, qcap="throughput")
        assert len(calls) == n_first
        # free the index, rebuild at the identical shape: the audit must
        # fire again — an id()-keyed registry can silently skip it when
        # the new centroids array lands on the recycled id
        del idx
        gc.collect()
        build_and_search()
        assert len(calls) == 2 * n_first


# --------------------------------------------- chunk-min tie semantics
class TestChunkMinTies:
    def test_duplicated_centroid_rows_value_multiset_matches_topk(self):
        # duplicated centroid rows (what max_list_cap splitting creates)
        # make exact distance ties; chunk-min may order ties differently
        # than lax.top_k's lowest-index tiebreak, but the selected VALUE
        # multiset must match exactly (docs/ivf_scale.md)
        rng = np.random.default_rng(5)
        base = rng.standard_normal((256, 8)).astype(np.float32)
        cents = np.repeat(base, 8, axis=0)                 # (2048, 8)
        q = rng.standard_normal((6, 8)).astype(np.float32)
        d2 = (
            (q ** 2).sum(1)[:, None] + (cents ** 2).sum(1)[None, :]
            - 2.0 * q @ cents.T
        ).astype(np.float32)
        k = 10
        from raft_tpu.spatial.selection import chunk_min_select_k

        # the chunk path must actually engage (not the top_k fallback)
        assert d2.shape[1] % 128 == 0 and d2.shape[1] // 128 >= k
        v, i = chunk_min_select_k(jnp.asarray(d2), k)
        tv, _ = jax.lax.top_k(-jnp.asarray(d2), k)
        v, i, tv = np.asarray(v), np.asarray(i), -np.asarray(tv)
        np.testing.assert_array_equal(np.sort(v, axis=1),
                                      np.sort(tv, axis=1))
        # returned indices address the returned values
        np.testing.assert_array_equal(
            np.take_along_axis(d2, i, axis=1), v
        )


# --------------------------------------------- bench artifact compaction
class TestBenchArtifact:
    @pytest.fixture(scope="class")
    def benchtop(self):
        import importlib.util

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "benchtop", os.path.join(root, "bench.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_compact_drops_prose_and_rounds(self, benchtop):
        row = {
            "metric": "m", "value": 123.456, "unit": "QPS",
            "spread": 0.2, "note": "prose", "qcap": "throughput (=24)",
            "vs_prev_qcap8_qps": 1.01,
            "extras": [{
                "metric": "e", "value": 10.12345, "bf16_note": "x",
                "rows": [{"engine": "fused_knn", "nq": 1,
                          "p50_ms": 0.123456, "qcap": 8}],
            }],
        }
        c = benchtop._compact(row)
        assert "note" not in c and "qcap" not in c
        assert c["value"] == 123.5
        assert c["vs_prev_qcap8_qps"] == 1.01
        sub = c["extras"][0]
        assert "bf16_note" not in sub
        assert sub["rows"][0] == {"engine": "fused_knn", "nq": 1,
                                  "p50_ms": 0.1235, "qcap": 8}
        # the whole compact line stays printable well under the driver cap
        import json

        assert len(json.dumps(c)) < 1800

    def test_fit_line_roundtrips_and_fits_cap(self, benchtop):
        """The r5 parsed=null regression: an over-long doc must be
        trimmed key-by-key until the printed line json.loads-round-trips
        under the driver cap, preserving every row's primary value."""
        import json

        doc = {
            "metric": "pairwise", "value": 101.5, "unit": "GFLOPS",
            "spread": 0.01, "repeats": 3,
            "extras": [
                {
                    "metric": f"extra_{i}", "value": 1000.0 + i,
                    "unit": "QPS", "spread": 0.02, "repeats": 7,
                    "recall_at_10": 0.95, "build_s": 100.0,
                    "build_warm_s": 2.0, "qcap8_qps": 9e4,
                    "measured_chip_qps": 1.2e4, "sharded_e2e_qps": 1.1e4,
                    "brute_force_same_shape_qps": 1.5e5,
                    "vs_prev": 1.01, "vs_prev_qcap8_qps": 0.99,
                    "vs_prev_build_warm_s": 1.0,
                }
                for i in range(14)
            ],
        }
        line = benchtop._fit_line(doc)
        parsed = json.loads(line)                 # round-trips
        assert len(line) <= 1800
        assert parsed["value"] == 101.5
        vals = [e["value"] for e in parsed["extras"]]
        assert vals == [1000.0 + i for i in range(14)]
        # trimming never touches the primary regression fields
        assert all("vs_prev" in e for e in parsed["extras"])

    def test_fit_line_small_doc_untrimmed(self, benchtop):
        import json

        doc = {"metric": "m", "value": 1.0, "unit": "QPS",
               "spread": 0.1, "repeats": 3}
        line = benchtop._fit_line(doc)
        assert json.loads(line) == benchtop._compact(doc)

    def test_vs_prev_significance_stamp(self, benchtop):
        prev = {"m": {"value": 112.0}}
        noisy = benchtop._stamp_vs_prev(
            {"metric": "m", "value": 118.0, "spread": 0.2}, prev
        )
        assert noisy["vs_prev_significant"] is False
        clear = benchtop._stamp_vs_prev(
            {"metric": "m", "value": 150.0, "spread": 0.05}, prev
        )
        assert "vs_prev_significant" not in clear


# ------------------------------------------------- latency sweep surface
def test_serving_latency_rows_tiny_config():
    from bench.bench_serving import serving_latency_rows

    out = serving_latency_rows(
        n=8192, d=8, k=4, n_probes=4, n_lists=8, nqs=(1, 4),
        engines=("ivf_flat",), chain=(1, 3), escalate=0,
        hedged=False, overload=False, mixed=False, open_loop=False,
        zipf=False,       # the zipf_hot_traffic row has its own smoke
        cold_tier=False,  # (tests/test_result_cache.py); the cold_tier
        self_heal=False,  # row's smoke lives in tests/test_tier.py, the
        graph=False,      # self_heal row's in tests/test_chaos.py, the
        durable=False,    # graph_ann + durable_ingest rows' below
    )
    assert out["unit"] == "ms"
    assert [r["nq"] for r in out["rows"]] == [1, 4]
    for r in out["rows"]:
        assert r["engine"] == "ivf_flat"
        assert ("p50_ms" in r) or ("error" in r)
        assert "qcap" in r


def test_graph_ann_row_tiny_config():
    """The graph-ANN row on a tiny CPU config (docs/graph_ann.md
    "Bench"): both arms must produce p50 + recall stamps, the served
    beam/degree/iters must be stamped, and the beam sweep must land
    recall within the 0.01 acceptance band of the in-row IVF baseline
    (p50 ordering itself is hardware territory — the CPU drive proves
    the measurement, not the win)."""
    from bench.bench_serving import graph_ann_row

    rng = np.random.default_rng(9)
    x = rng.standard_normal((4096, 8)).astype(np.float32)
    q = x[::17][:64] + 0.05 * rng.standard_normal((64, 8)).astype(
        np.float32
    )
    idx = ivf_flat_build(x, IVFFlatParams(n_lists=8, kmeans_n_iters=3,
                                          seed=2))
    row = graph_ann_row(x, q, idx, k=4, n_probes=4, degree=8,
                        beams=(8, 16, 32), n_recall_q=32,
                        chain=(1, 3), escalate=0)
    assert row["scenario"] == "graph_ann" and row["engine"] == "graph"
    assert row["nq"] == 1
    assert row["degree"] == 8 and row["beam"] in (8, 16, 32)
    assert isinstance(row["iters"], int) and row["iters"] >= 4
    assert ("p50_ms" in row) or ("error" in row)
    assert "ivf_recall_at_10" in row and "recall_at_10" in row
    assert row["recall_at_10"] >= row["ivf_recall_at_10"] - 0.01


def test_durable_ingest_row_tiny_config():
    """The durable-WAL ingest row on a tiny CPU config
    (docs/robustness.md "Durability"): both arms must stamp acked QPS,
    the ratio must be a positive quotient of them, the fsync sweep must
    carry one point per swept interval with real fsyncs counted, and
    the WAL throughput stamp must be positive (the ratio's 0.8
    acceptance is hardware territory — the CPU drive proves the
    measurement, not the win)."""
    from bench.bench_serving import durable_ingest_row

    rng = np.random.default_rng(13)
    x = rng.standard_normal((4096, 8)).astype(np.float32)
    q = x[::31][:32] + 0.05 * rng.standard_normal((32, 8)).astype(
        np.float32
    )
    idx = ivf_flat_build(x, IVFFlatParams(n_lists=8, kmeans_n_iters=3,
                                          seed=4))
    row = durable_ingest_row(idx, q, ingest_batch=16, n_batches=6,
                             delta_cap=32,
                             fsync_intervals_ms=(0.0, 1.0))
    assert row["scenario"] == "durable_ingest"
    assert row["engine"] == "ivf_flat"
    assert row["durable_qps"] > 0 and row["nondurable_qps"] > 0
    assert row["durability_ratio"] == pytest.approx(
        row["durable_qps"] / row["nondurable_qps"], rel=1e-2
    )
    assert row["fsync_interval_ms"] in (0.0, 1.0)
    assert row["fsync_p50_ms"] >= 0.0 and row["wal_mb_per_s"] > 0
    assert len(row["fsync_sweep"]) == 2
    for pt in row["fsync_sweep"]:
        assert pt["n_fsyncs"] >= 1          # every ack rode an fsync


def test_serving_resilience_rows_tiny_config():
    """The hedged-straggler and 2x-overload rows on a tiny CPU config:
    the hedge must cut the injected straggler's p99 (acceptance), and
    overload must SHED (RaftOverloadError accounting) with the queue
    bounded rather than collapsing."""
    import jax as _jax

    from bench.bench_serving import hedged_straggler_row, overload_row
    from raft_tpu.spatial.ann.ivf_flat import ivf_flat_search_grouped

    rng = np.random.default_rng(5)
    x = rng.standard_normal((4096, 8)).astype(np.float32)
    idx = ivf_flat_build(x, IVFFlatParams(n_lists=8, kmeans_n_iters=3,
                                          seed=2))
    nq = 8
    qcap = idx.warmup(nq, k=4, n_probes=4)
    qb = jnp.asarray(
        rng.standard_normal((nq, 8)).astype(np.float32)
    )

    def run(qq):
        return ivf_flat_search_grouped(idx, qq, 4, n_probes=4, qcap=qcap)

    _jax.block_until_ready(run(qb))
    hrow = hedged_straggler_row(run, qb, straggler_every=4,
                                n_requests=24)
    assert hrow["scenario"] == "hedged_straggler"
    assert hrow["p99_ms"] > 0 and hrow["hedged_p99_ms"] > 0
    # the injected straggler dominates the unhedged tail; the hedge
    # must cut it (generous margin — CI hosts are noisy)
    assert hrow["hedged_p99_ms"] < hrow["p99_ms"]

    orow = overload_row(run, qb, over_factor=2.0, n_requests=48,
                        max_queue=2)
    assert orow["scenario"] == "overload_2x"
    assert orow["shed_rate"] > 0.0          # it shed rather than queued
    assert orow["queue_peak"] <= 2 + 1      # bounded, never collapsed
    assert orow["timed_out"] == 0


def test_round6_bench_line_parses(benchtop_module=None):
    """ISSUE 5 satellite (extended for the r6 PQ-kernel round): the
    current artifact shape — the r5 extras, the serving resilience
    rows, plus this round's ``escalations``/``adc_engine`` stamps —
    must print as a line that json.loads-round-trips under the
    1800-char driver cap (r5 shipped parsed=null; the _fit_line
    self-check is asserted HERE, not left for the driver to
    discover)."""
    import importlib.util
    import json

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "benchtop_r6", os.path.join(root, "bench.py")
    )
    benchtop = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(benchtop)

    serving_rows = [
        {"engine": e, "nq": nq, "p50_ms": 1.2345, "spread": 0.08,
         "repeats": 5, "qcap": 24}
        for e in ("fused_knn", "ivf_flat", "ivf_pq")
        for nq in (1, 128, 1024)
    ] + [
        {"engine": "ivf_flat", "scenario": "hedged_straggler", "nq": 128,
         "p50_ms": 1.9, "p99_ms": 31.4, "hedged_p99_ms": 6.2,
         "hedge_delay_ms": 3.1, "straggler_every": 8,
         "straggler_ms": 25.0, "n_requests": 64},
        {"engine": "ivf_flat", "scenario": "overload_2x", "nq": 128,
         "p50_ms": 2.0, "offered_x": 2.0, "shed_rate": 0.47,
         "max_queue": 4, "n_requests": 96, "queue_peak": 5,
         "timed_out": 0, "p99_ms": 22.7},
    ]
    extras = [
        {"metric": f"extra_{i}", "value": 10000.0 + i, "unit": "QPS",
         "spread": 0.05, "repeats": 7, "escalations": 1,
         "adc_engine": "pallas", "recall_at_10": 0.95,
         "build_s": 150.0, "build_warm_s": 2.0, "qcap8_qps": 1.2e5,
         "measured_chip_qps": 1.1e4, "sharded_e2e_qps": 1.05e4,
         "probe_recall_vs_flat": 0.997, "probe_flop_ratio": 5.2,
         "brute_force_same_shape_qps": 1.5e5, "vs_prev": 1.01,
         "vs_prev_qcap8_qps": 0.99, "vs_prev_build_warm_s": 1.0,
         "note": "prose that must be dropped from the printed line"}
        for i in range(8)
    ] + [
        {"metric": "serving_p50_500000x96_k10_p16", "unit": "ms",
         "rows": serving_rows},
        {"metric": "warm_start_build_500000x96", "unit": "s",
         "value": 3.1, "cold_cache_build_s": 140.0, "build_warm_s": 1.9,
         "within_2x_warm": True},
    ]
    doc = {
        "metric": "pairwise_l2_expanded_8192x8192x512_f32",
        "value": 101000.5, "unit": "GFLOPS", "spread": 0.01,
        "repeats": 3, "f32_highest_gflops": 55000.2,
        "vs_baseline": 10.1, "vs_prev": 1.0,
        "extras": extras,
    }
    line = benchtop._fit_line(doc)
    parsed = json.loads(line)               # round-trips
    assert len(line) <= 1800
    assert isinstance(parsed, dict)
    assert parsed["value"] == 101000.5
    # every extra's primary value survives the trim
    vals = [e.get("value") for e in parsed["extras"]
            if "value" in e]
    assert vals[:8] == [10000.0 + i for i in range(8)]


def test_retired_shard_keys_never_print(benchtop_module=None):
    """ISSUE 8 satellite: the modeled-projection keys retired in PR 4
    (``probe_global_ms`` / ``projected_100m_qps`` / ``merge8_ms``) were
    still showing in BENCH_r05's shard rows. They must be stripped from
    every printed row — and from prior-round rows before vs_prev
    stamping — so a stale artifact can never resurrect them."""
    import importlib.util

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "benchtop_retired", os.path.join(root, "bench.py")
    )
    benchtop = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(benchtop)

    row = {
        "metric": "mnmg_ivf_flat_shard_12500000x96_q16384_k10_p16",
        "value": 50620.9, "unit": "QPS", "spread": 0.014,
        "merge8_ms": 0.45, "probe_global_ms": 50.45,
        "projected_100m_qps": 93002.5, "qcap8_qps": 130789.3,
        "vs_prev_projected_100m_qps": 1.01,
        "extras": [{"metric": "e", "value": 1.0,
                    "probe_global_ms": 50.19}],
    }
    c = benchtop._compact(row)
    for key in ("probe_global_ms", "projected_100m_qps", "merge8_ms",
                "vs_prev_projected_100m_qps"):
        assert key not in c, key
    assert "probe_global_ms" not in c["extras"][0]
    assert c["qcap8_qps"] == 130789.3          # measured keys survive
    # the retired keys are not in the print whitelist either
    for key in benchtop._RETIRED_KEYS:
        assert key not in benchtop._PRINT_KEYS


def test_round8_bench_line_parses_with_open_loop():
    """ISSUE 8 satellite (the _fit_line parse/cap test extended): the
    round-8 artifact shape — every prior row PLUS the open-loop
    executor row — must print as a line that json.loads-round-trips
    under the 1800-char driver cap, with the open-loop acceptance keys
    (saturation vs program ratio, p99 at 80/95% of saturation)
    surviving every trim stage."""
    import importlib.util
    import json

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "benchtop_r8", os.path.join(root, "bench.py")
    )
    benchtop = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(benchtop)

    serving_rows = [
        {"engine": e, "nq": nq, "p50_ms": 1.2345, "spread": 0.08,
         "repeats": 5, "qcap": 24}
        for e in ("fused_knn", "ivf_flat", "ivf_pq")
        for nq in (1, 128, 1024)
    ] + [
        {"engine": "ivf_flat", "scenario": "hedged_straggler", "nq": 128,
         "p50_ms": 1.9, "p99_ms": 31.4, "hedged_p99_ms": 6.2,
         "n_requests": 64},
        {"engine": "ivf_flat", "scenario": "overload_2x", "nq": 128,
         "p50_ms": 2.0, "shed_rate": 0.47, "p99_ms": 22.7},
        {"engine": "ivf_flat", "scenario": "mixed_ingest", "nq": 128,
         "ingest_batch": 256, "qcap": 24, "frozen_qps": 52000.0,
         "ingest_qps": 310000.0, "mixed_search_qps": 45000.0,
         "spread": 0.06, "repeats": 5, "escalations": 1,
         "qps_ratio_vs_frozen": 0.865, "upsert_visible_ms": 4.2,
         "delete_masked_ms": 2.9},
        {"engine": "ivf_flat", "scenario": "open_loop", "nq": 1024,
         "program_qps": 610000.0, "saturation_qps": 512000.0,
         "qps_ratio_vs_program": 0.839, "spread": 0.04, "repeats": 5,
         "p50_ms_50": 2.4, "p99_ms_50": 5.1, "p50_ms_80": 3.0,
         "p99_ms_80": 7.9, "p50_ms_95": 4.2, "p99_ms_95": 14.6,
         "shed_rate_95": 0.012, "max_in_flight": 4,
         "request_size": 16, "n_requests": 256},
    ]
    extras = [
        {"metric": f"extra_{i}", "value": 10000.0 + i, "unit": "QPS",
         "spread": 0.05, "repeats": 7, "escalations": 1,
         "adc_engine": "pallas", "recall_at_10": 0.95,
         "build_s": 150.0, "build_warm_s": 2.0, "qcap8_qps": 1.2e5,
         "measured_chip_qps": 1.1e4, "sharded_e2e_qps": 1.05e4,
         "probe_recall_vs_flat": 0.997, "probe_flop_ratio": 5.2,
         "brute_force_same_shape_qps": 1.5e5, "vs_prev": 1.01,
         "vs_prev_qcap8_qps": 0.99, "vs_prev_build_warm_s": 1.0}
        for i in range(8)
    ] + [
        {"metric": "serving_p50_500000x96_k10_p16", "unit": "ms",
         "rows": serving_rows},
        {"metric": "warm_start_build_500000x96", "unit": "s",
         "value": 3.1, "build_warm_s": 1.9, "within_2x_warm": True},
    ]
    doc = {
        "metric": "pairwise_l2_expanded_8192x8192x512_f32",
        "value": 101000.5, "unit": "GFLOPS", "spread": 0.01,
        "repeats": 3, "f32_highest_gflops": 55000.2,
        "vs_baseline": 10.1, "vs_prev": 1.0,
        "extras": extras,
    }
    line = benchtop._fit_line(doc)
    parsed = json.loads(line)               # round-trips
    assert len(line) <= 1800
    assert isinstance(parsed, dict)
    # the open-loop acceptance keys survive whatever trimming was
    # needed — they are not in _TRIM_ORDER, and only fall with "rows"
    if any("rows" in e for e in parsed.get("extras", [])):
        srv = next(e for e in parsed["extras"] if "rows" in e)
        orow = next(r for r in srv["rows"]
                    if r.get("scenario") == "open_loop")
        assert orow["qps_ratio_vs_program"] == 0.839
        assert orow["p99_ms_95"] == 14.6 and orow["p99_ms_80"] == 7.9
        assert "saturation_qps" in orow and "program_qps" in orow


def test_round9_bench_line_parses_with_cross_host():
    """ISSUE 9 satellite (the _fit_line parse/cap test extended,
    following the r05-r08 pattern): the round-9 artifact shape — every
    prior row PLUS the cross-host serving row — must print as a line
    that json.loads-round-trips under the 1800-char driver cap, with
    the cross-host acceptance keys (e2e QPS, dcn_bytes_ratio, the
    zero-retrace host-flip audit) surviving every trim stage."""
    import importlib.util
    import json

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "benchtop_r9", os.path.join(root, "bench.py")
    )
    benchtop = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(benchtop)

    serving_rows = [
        {"engine": e, "nq": nq, "p50_ms": 1.2345, "spread": 0.08,
         "repeats": 5, "qcap": 24}
        for e in ("fused_knn", "ivf_flat", "ivf_pq")
        for nq in (1, 128, 1024)
    ] + [
        {"engine": "ivf_flat", "scenario": "hedged_straggler", "nq": 128,
         "p50_ms": 1.9, "p99_ms": 31.4, "hedged_p99_ms": 6.2,
         "n_requests": 64},
        {"engine": "ivf_flat", "scenario": "overload_2x", "nq": 128,
         "p50_ms": 2.0, "shed_rate": 0.47, "p99_ms": 22.7},
        {"engine": "ivf_flat", "scenario": "mixed_ingest", "nq": 128,
         "frozen_qps": 52000.0, "ingest_qps": 310000.0,
         "mixed_search_qps": 45000.0, "spread": 0.06, "repeats": 5,
         "qps_ratio_vs_frozen": 0.865, "upsert_visible_ms": 4.2,
         "delete_masked_ms": 2.9},
        {"engine": "ivf_flat", "scenario": "open_loop", "nq": 1024,
         "program_qps": 610000.0, "saturation_qps": 512000.0,
         "qps_ratio_vs_program": 0.839, "spread": 0.04, "repeats": 5,
         "p50_ms_50": 2.4, "p99_ms_50": 5.1, "p50_ms_80": 3.0,
         "p99_ms_80": 7.9, "p50_ms_95": 4.2, "p99_ms_95": 14.6,
         "shed_rate_95": 0.012},
    ]
    extras = [
        {"metric": f"extra_{i}", "value": 10000.0 + i, "unit": "QPS",
         "spread": 0.05, "repeats": 7, "escalations": 1,
         "adc_engine": "pallas", "recall_at_10": 0.95,
         "build_s": 150.0, "build_warm_s": 2.0, "qcap8_qps": 1.2e5,
         "measured_chip_qps": 1.1e4, "sharded_e2e_qps": 1.05e4,
         "probe_recall_vs_flat": 0.997, "probe_flop_ratio": 5.2,
         "brute_force_same_shape_qps": 1.5e5, "vs_prev": 1.01,
         "vs_prev_qcap8_qps": 0.99, "vs_prev_build_warm_s": 1.0}
        for i in range(8)
    ] + [
        # the round-9 cross-host row, every key cross_host_row emits
        {"metric": "mnmg_cross_host_131072x64_q512_k10_hostsim_2x4",
         "value": 48123.4, "unit": "QPS", "spread": 0.07, "repeats": 5,
         "escalations": 1, "flat_e2e_qps": 50620.9,
         "qps_ratio_vs_flat": 0.951, "wire": "bf16",
         "dcn_bytes_per_query": 100.0,
         "flat_dcn_bytes_per_query": 320.0, "dcn_bytes_ratio": 3.2,
         "merge_ms_hier": 0.42, "merge_ms_flat": 0.31,
         "health_flip_retraces": 0, "coverage_host_down": 1.0,
         "host_down_bitident": True, "vs_prev": 1.0,
         "vs_prev_flat_e2e_qps": 1.0},
        {"metric": "serving_p50_500000x96_k10_p16", "unit": "ms",
         "rows": serving_rows},
        {"metric": "warm_start_build_500000x96", "unit": "s",
         "value": 3.1, "build_warm_s": 1.9, "within_2x_warm": True},
    ]
    doc = {
        "metric": "pairwise_l2_expanded_8192x8192x512_f32",
        "value": 101000.5, "unit": "GFLOPS", "spread": 0.01,
        "repeats": 3, "f32_highest_gflops": 55000.2,
        "vs_baseline": 10.1, "vs_prev": 1.0,
        "extras": extras,
    }
    line = benchtop._fit_line(doc)
    parsed = json.loads(line)               # round-trips
    assert len(line) <= 1800
    assert isinstance(parsed, dict)
    xrow = next((e for e in parsed["extras"]
                 if str(e.get("metric", "")).startswith(
                     "mnmg_cross_host")), None)
    assert xrow is not None
    assert xrow["value"] == 48123.4         # primary survives any trim
    # the acceptance keys are not in _TRIM_ORDER and print whitelisted,
    # so they only fall at the last-resort _core_projection
    if "dcn_bytes_ratio" in xrow:           # not core-projected
        assert xrow["dcn_bytes_ratio"] == 3.2
        assert xrow["qps_ratio_vs_flat"] == 0.951
        assert xrow["health_flip_retraces"] == 0
        assert xrow["coverage_host_down"] == 1.0
        assert xrow["host_down_bitident"] is True
    for key in ("dcn_bytes_ratio", "qps_ratio_vs_flat",
                "health_flip_retraces", "coverage_host_down",
                "host_down_bitident"):
        assert key not in benchtop._TRIM_ORDER
        assert key in benchtop._PRINT_KEYS
    # ... and the row's _compact projection always carries them (the
    # full-row pre-trim shape, the retired-keys test's sibling check)
    c = benchtop._compact(extras[8])
    for key in ("value", "dcn_bytes_ratio", "qps_ratio_vs_flat",
                "health_flip_retraces", "coverage_host_down",
                "host_down_bitident", "wire"):
        assert key in c, key


def test_mixed_ingest_row_tiny_config():
    """ISSUE 7: the mixed read/write row on a tiny CPU config — frozen
    vs under-ingest search QPS (ratio stamped), sustained ingest QPS,
    and the upsert→visible / delete→masked latencies, all through
    chained_dispatch_stats (escalations stamped)."""
    from bench.bench_serving import mixed_ingest_row

    rng = np.random.default_rng(9)
    x = rng.standard_normal((4096, 8)).astype(np.float32)
    idx = ivf_flat_build(
        x, IVFFlatParams(n_lists=8, kmeans_n_iters=3, seed=2),
        metric="sqeuclidean",
    )
    qb = jnp.asarray(x[:8] + 0.01)
    row = mixed_ingest_row(idx, qb, k=4, n_probes=4, ingest_batch=16,
                           chain=(1, 3), escalate=0)
    assert row["scenario"] == "mixed_ingest"
    assert row["ingest_batch"] == 16
    assert "error" not in row
    for key in ("frozen_qps", "mixed_search_qps", "qps_ratio_vs_frozen",
                "ingest_qps", "escalations", "spread",
                "upsert_visible_ms", "delete_masked_ms"):
        assert key in row, key
    assert row["mixed_search_qps"] > 0 and row["frozen_qps"] > 0
    assert row["upsert_visible_ms"] > 0
    assert row["delete_masked_ms"] > 0


def test_round7_bench_line_parses_with_mixed_ingest():
    """ISSUE 7 satellite (the _fit_line parse/cap test extended): the
    round-7 artifact shape — every prior row PLUS the mixed_ingest
    serving row — must print as a line that json.loads-round-trips
    under the 1800-char driver cap, with the mutation row's headline
    ratio surviving every trim stage."""
    import importlib.util
    import json

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "benchtop_r7", os.path.join(root, "bench.py")
    )
    benchtop = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(benchtop)

    serving_rows = [
        {"engine": e, "nq": nq, "p50_ms": 1.2345, "spread": 0.08,
         "repeats": 5, "qcap": 24}
        for e in ("fused_knn", "ivf_flat", "ivf_pq")
        for nq in (1, 128, 1024)
    ] + [
        {"engine": "ivf_flat", "scenario": "hedged_straggler", "nq": 128,
         "p50_ms": 1.9, "p99_ms": 31.4, "hedged_p99_ms": 6.2,
         "n_requests": 64},
        {"engine": "ivf_flat", "scenario": "overload_2x", "nq": 128,
         "p50_ms": 2.0, "shed_rate": 0.47, "p99_ms": 22.7},
        {"engine": "ivf_flat", "scenario": "mixed_ingest", "nq": 128,
         "ingest_batch": 256, "qcap": 24, "frozen_qps": 52000.0,
         "ingest_qps": 310000.0, "mixed_search_qps": 45000.0,
         "spread": 0.06, "repeats": 5, "escalations": 1,
         "qps_ratio_vs_frozen": 0.865, "upsert_visible_ms": 4.2,
         "delete_masked_ms": 2.9},
    ]
    extras = [
        {"metric": f"extra_{i}", "value": 10000.0 + i, "unit": "QPS",
         "spread": 0.05, "repeats": 7, "escalations": 1,
         "adc_engine": "pallas", "recall_at_10": 0.95,
         "build_s": 150.0, "build_warm_s": 2.0, "qcap8_qps": 1.2e5,
         "measured_chip_qps": 1.1e4, "sharded_e2e_qps": 1.05e4,
         "probe_recall_vs_flat": 0.997, "probe_flop_ratio": 5.2,
         "brute_force_same_shape_qps": 1.5e5, "vs_prev": 1.01,
         "vs_prev_qcap8_qps": 0.99, "vs_prev_build_warm_s": 1.0}
        for i in range(8)
    ] + [
        {"metric": "serving_p50_500000x96_k10_p16", "unit": "ms",
         "rows": serving_rows},
        {"metric": "warm_start_build_500000x96", "unit": "s",
         "value": 3.1, "build_warm_s": 1.9, "within_2x_warm": True},
    ]
    doc = {
        "metric": "pairwise_l2_expanded_8192x8192x512_f32",
        "value": 101000.5, "unit": "GFLOPS", "spread": 0.01,
        "repeats": 3, "f32_highest_gflops": 55000.2,
        "vs_baseline": 10.1, "vs_prev": 1.0,
        "extras": extras,
    }
    line = benchtop._fit_line(doc)
    parsed = json.loads(line)               # round-trips
    assert len(line) <= 1800
    assert isinstance(parsed, dict)
    # the headline ratio survives whatever trimming was needed — it is
    # not in _TRIM_ORDER, and mixed_search_qps only falls with "rows"
    if any("rows" in e for e in parsed.get("extras", [])):
        srv = next(e for e in parsed["extras"] if "rows" in e)
        mrow = next(r for r in srv["rows"]
                    if r.get("scenario") == "mixed_ingest")
        assert mrow["qps_ratio_vs_frozen"] == 0.865
        assert "mixed_search_qps" in mrow


def test_round10_bench_line_parses_with_flat_scan_kernel():
    """ISSUE 10 satellite (the _fit_line parse/cap test extended,
    following the r05-r09 pattern): the round-10 artifact shape — every
    prior row PLUS the flat_scan_kernel acceptance row and the
    ``scan_engine`` stamp on the flat shard row — must print as a line
    that json.loads-round-trips under the 1800-char driver cap, with
    the acceptance keys (kernel-vs-XLA speedup, the engine stamp,
    recall at both engines' operating point) surviving every trim
    stage short of the last-resort core projection."""
    import importlib.util
    import json

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "benchtop_r10", os.path.join(root, "bench.py")
    )
    benchtop = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(benchtop)

    serving_rows = [
        {"engine": e, "nq": nq, "p50_ms": 1.2345, "spread": 0.08,
         "repeats": 5, "qcap": 24}
        for e in ("fused_knn", "ivf_flat", "ivf_pq")
        for nq in (1, 128, 1024)
    ] + [
        {"engine": "ivf_flat", "scenario": "mixed_ingest", "nq": 128,
         "frozen_qps": 52000.0, "ingest_qps": 310000.0,
         "mixed_search_qps": 45000.0, "spread": 0.06, "repeats": 5,
         "qps_ratio_vs_frozen": 0.865, "upsert_visible_ms": 4.2,
         "delete_masked_ms": 2.9},
        {"engine": "ivf_flat", "scenario": "open_loop", "nq": 1024,
         "program_qps": 610000.0, "saturation_qps": 512000.0,
         "qps_ratio_vs_program": 0.839, "spread": 0.04, "repeats": 5,
         "p50_ms_95": 4.2, "p99_ms_95": 14.6, "shed_rate_95": 0.012},
    ]
    extras = [
        {"metric": f"extra_{i}", "value": 10000.0 + i, "unit": "QPS",
         "spread": 0.05, "repeats": 7, "escalations": 1,
         "adc_engine": "pallas", "recall_at_10": 0.95,
         "build_s": 150.0, "build_warm_s": 2.0, "qcap8_qps": 1.2e5,
         "measured_chip_qps": 1.1e4, "sharded_e2e_qps": 1.05e4,
         "probe_recall_vs_flat": 0.997, "probe_flop_ratio": 5.2,
         "brute_force_same_shape_qps": 1.5e5, "vs_prev": 1.01,
         "vs_prev_qcap8_qps": 0.99, "vs_prev_build_warm_s": 1.0}
        for i in range(7)
    ] + [
        # the round-10 acceptance row, every key extra_flat_scan_kernel
        # emits
        {"metric": "flat_scan_kernel_500000x96_q4096_k10_p16",
         "value": 104321.5, "unit": "QPS", "spread": 0.04, "repeats": 7,
         "escalations": 1, "scan_engine": "pallas",
         "recall_at_10": 0.9994, "xla_qps": 50620.9,
         "xla_recall_at_10": 0.9994, "xla_spread": 0.05,
         "speedup": 2.06, "vs_prev": 1.0, "vs_prev_xla_qps": 1.0},
        # the flat 100M-shard row now stamps its scan engine
        {"metric": "mnmg_ivf_flat_shard_12500000x96_q16384_k10_p16",
         "value": 50620.9, "unit": "QPS", "spread": 0.014, "repeats": 7,
         "escalations": 1, "scan_engine": "pallas",
         "recall_at_10_vs_shard": 0.9994, "build_s": 180.0,
         "qcap8_qps": 130789.3, "measured_chip_qps": 1.2e5,
         "sharded_e2e_qps": 1.1e5, "probe_recall_vs_flat": 0.997,
         "probe_flop_ratio": 5.2, "vs_prev": 1.05},
        {"metric": "mnmg_cross_host_131072x64_q512_k10_hostsim_2x4",
         "value": 48123.4, "unit": "QPS", "spread": 0.07,
         "flat_e2e_qps": 50620.9, "qps_ratio_vs_flat": 0.951,
         "wire": "bf16", "dcn_bytes_ratio": 3.2,
         "health_flip_retraces": 0, "coverage_host_down": 1.0,
         "host_down_bitident": True},
        {"metric": "serving_p50_500000x96_k10_p16", "unit": "ms",
         "rows": serving_rows},
        {"metric": "warm_start_build_500000x96", "unit": "s",
         "value": 3.1, "build_warm_s": 1.9, "within_2x_warm": True},
    ]
    doc = {
        "metric": "pairwise_l2_expanded_8192x8192x512_f32",
        "value": 101000.5, "unit": "GFLOPS", "spread": 0.01,
        "repeats": 3, "f32_highest_gflops": 55000.2,
        "vs_baseline": 10.1, "vs_prev": 1.0,
        "extras": extras,
    }
    line = benchtop._fit_line(doc)
    parsed = json.loads(line)               # round-trips
    assert len(line) <= 1800
    assert isinstance(parsed, dict)
    krow = next((e for e in parsed["extras"]
                 if str(e.get("metric", "")).startswith(
                     "flat_scan_kernel")), None)
    assert krow is not None
    assert krow["value"] == 104321.5        # primary survives any trim
    # the acceptance keys are not in _TRIM_ORDER and print whitelisted,
    # so they only fall at the last-resort _core_projection
    if "speedup" in krow:                   # not core-projected
        assert krow["speedup"] == 2.06
        assert krow["scan_engine"] == "pallas"
        assert krow["recall_at_10"] == 0.9994
    for key in ("speedup", "scan_engine", "recall_at_10"):
        assert key not in benchtop._TRIM_ORDER
        assert key in benchtop._PRINT_KEYS
    # xla_qps IS trimmable (speedup carries the acceptance signal), and
    # it is companion-tracked round-over-round
    assert "xla_qps" in benchtop._TRIM_ORDER
    assert "xla_qps" in benchtop._COMPANIONS
    # the rows' _compact projections always carry the stamps pre-trim
    c = benchtop._compact(extras[7])
    for key in ("value", "scan_engine", "speedup", "xla_qps",
                "xla_recall_at_10"):
        assert key in c, key
    assert benchtop._compact(extras[8])["scan_engine"] == "pallas"


def test_round11_bench_line_parses_with_sq_scan_kernel():
    """ISSUE 11 satellite (the _fit_line parse/cap test extended,
    following the r05-r10 pattern): the round-11 artifact shape — every
    prior row PLUS the sq_scan_kernel acceptance row (the int8
    dequant+scan engine vs its XLA dequant path) and the
    ``probe_kernel`` stamp on both shard rows — must print as a line
    that json.loads-round-trips under the 1800-char driver cap, with
    the acceptance keys (kernel-vs-XLA speedup, the scan_engine stamp,
    recall at both engines' operating point) surviving every trim
    stage short of the last-resort core projection. ``probe_kernel``
    is deliberately TRIMMABLE (a secondary stamp — the speedup rows
    carry the acceptance signal) but prints whitelisted."""
    import importlib.util
    import json

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "benchtop_r11", os.path.join(root, "bench.py")
    )
    benchtop = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(benchtop)

    serving_rows = [
        {"engine": e, "nq": nq, "p50_ms": 1.2345, "spread": 0.08,
         "repeats": 5, "qcap": 24}
        for e in ("fused_knn", "ivf_flat", "ivf_pq")
        for nq in (1, 128, 1024)
    ] + [
        {"engine": "ivf_flat", "scenario": "open_loop", "nq": 1024,
         "program_qps": 610000.0, "saturation_qps": 512000.0,
         "qps_ratio_vs_program": 0.839, "spread": 0.04, "repeats": 5,
         "p50_ms_95": 4.2, "p99_ms_95": 14.6, "shed_rate_95": 0.012},
    ]
    extras = [
        {"metric": f"extra_{i}", "value": 10000.0 + i, "unit": "QPS",
         "spread": 0.05, "repeats": 7, "escalations": 1,
         "adc_engine": "pallas", "recall_at_10": 0.95,
         "build_s": 150.0, "build_warm_s": 2.0, "qcap8_qps": 1.2e5,
         "measured_chip_qps": 1.1e4, "sharded_e2e_qps": 1.05e4,
         "probe_recall_vs_flat": 0.997, "probe_flop_ratio": 5.2,
         "brute_force_same_shape_qps": 1.5e5, "vs_prev": 1.01,
         "vs_prev_qcap8_qps": 0.99, "vs_prev_build_warm_s": 1.0}
        for i in range(6)
    ] + [
        # the round-10 flat acceptance row, unchanged
        {"metric": "flat_scan_kernel_500000x96_q4096_k10_p16",
         "value": 104321.5, "unit": "QPS", "spread": 0.04, "repeats": 7,
         "escalations": 1, "scan_engine": "pallas",
         "recall_at_10": 0.9994, "xla_qps": 50620.9,
         "xla_recall_at_10": 0.9994, "speedup": 2.06, "vs_prev": 1.0},
        # the round-11 acceptance row, every key extra_sq_scan_kernel
        # emits
        {"metric": "sq_scan_kernel_500000x96_q4096_k10_p16",
         "value": 98765.4, "unit": "QPS", "spread": 0.04, "repeats": 7,
         "escalations": 1, "scan_engine": "pallas",
         "recall_at_10": 0.9987, "xla_qps": 31234.5,
         "xla_recall_at_10": 0.9988, "xla_spread": 0.05,
         "speedup": 3.16, "index_gb": 0.05},
        # both shard rows now stamp the probe engine too
        {"metric": "mnmg_ivf_flat_shard_12500000x96_q16384_k10_p16",
         "value": 50620.9, "unit": "QPS", "spread": 0.014, "repeats": 7,
         "escalations": 1, "scan_engine": "pallas",
         "probe_kernel": "pallas",
         "recall_at_10_vs_shard": 0.9994, "build_s": 180.0,
         "qcap8_qps": 130789.3, "measured_chip_qps": 1.2e5,
         "sharded_e2e_qps": 1.1e5, "probe_recall_vs_flat": 0.997,
         "probe_flop_ratio": 5.2, "vs_prev": 1.05},
        {"metric": "mnmg_ivf_pq_shard_12500000x96_q16384_k10_p16",
         "value": 11900.0, "unit": "QPS", "spread": 0.02, "repeats": 7,
         "adc_engine": "pallas", "probe_kernel": "pallas",
         "recall_at_10_vs_shard": 0.9575, "qcap8_qps": 15500.0,
         "measured_chip_qps": 1.0e4, "sharded_e2e_qps": 0.95e4,
         "probe_recall_vs_flat": 0.997, "vs_prev": 1.0},
        {"metric": "serving_p50_500000x96_k10_p16", "unit": "ms",
         "rows": serving_rows},
        {"metric": "warm_start_build_500000x96", "unit": "s",
         "value": 3.1, "build_warm_s": 1.9, "within_2x_warm": True},
    ]
    doc = {
        "metric": "pairwise_l2_expanded_8192x8192x512_f32",
        "value": 101000.5, "unit": "GFLOPS", "spread": 0.01,
        "repeats": 3, "f32_highest_gflops": 55000.2,
        "vs_baseline": 10.1, "vs_prev": 1.0,
        "extras": extras,
    }
    line = benchtop._fit_line(doc)
    parsed = json.loads(line)               # round-trips
    assert len(line) <= 1800
    assert isinstance(parsed, dict)
    krow = next((e for e in parsed["extras"]
                 if str(e.get("metric", "")).startswith(
                     "sq_scan_kernel")), None)
    assert krow is not None
    assert krow["value"] == 98765.4         # primary survives any trim
    if "speedup" in krow:                   # not core-projected
        assert krow["speedup"] == 3.16
        assert krow["scan_engine"] == "pallas"
        assert krow["recall_at_10"] == 0.9987
    for key in ("speedup", "scan_engine", "recall_at_10"):
        assert key not in benchtop._TRIM_ORDER
        assert key in benchtop._PRINT_KEYS
    # probe_kernel prints whitelisted but IS trimmable under cap
    # pressure (the acceptance signal lives in the speedup rows)
    assert "probe_kernel" in benchtop._PRINT_KEYS
    assert "probe_kernel" in benchtop._TRIM_ORDER

def test_round12_bench_line_parses_with_program_audit_stamp():
    """ISSUE 12 satellite (the _fit_line parse/cap test extended,
    following the r05-r11 pattern): the round-12 artifact shape — every
    prior row PLUS the ``program_audit_ms`` stamp on the headline doc
    (the jaxpr-level contract gate's wall time, docs/static_analysis.md
    "Two tiers") — must print as a line that json.loads-round-trips
    under the 1800-char driver cap. ``program_audit_ms`` is
    deliberately TRIMMABLE (a secondary stamp: the gate's pass/fail
    lives in ci/run.sh programs, not the bench line) but prints
    whitelisted, and a red audit's ``program_audit_error`` string
    survives the _compact string filter so failures are visible on the
    driver line."""
    import importlib.util
    import json

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "benchtop_r12", os.path.join(root, "bench.py")
    )
    benchtop = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(benchtop)

    extras = [
        {"metric": f"extra_{i}", "value": 10000.0 + i, "unit": "QPS",
         "spread": 0.05, "repeats": 7, "escalations": 1,
         "adc_engine": "pallas", "recall_at_10": 0.95,
         "build_s": 150.0, "build_warm_s": 2.0, "qcap8_qps": 1.2e5,
         "measured_chip_qps": 1.1e4, "sharded_e2e_qps": 1.05e4,
         "probe_recall_vs_flat": 0.997, "probe_flop_ratio": 5.2,
         "brute_force_same_shape_qps": 1.5e5, "vs_prev": 1.01}
        for i in range(8)
    ] + [
        {"metric": "sq_scan_kernel_500000x96_q4096_k10_p16",
         "value": 98765.4, "unit": "QPS", "spread": 0.04, "repeats": 7,
         "escalations": 1, "scan_engine": "pallas",
         "recall_at_10": 0.9987, "xla_qps": 31234.5,
         "xla_recall_at_10": 0.9988, "speedup": 3.16},
        {"metric": "mnmg_ivf_flat_shard_12500000x96_q16384_k10_p16",
         "value": 50620.9, "unit": "QPS", "spread": 0.014, "repeats": 7,
         "scan_engine": "pallas", "probe_kernel": "pallas",
         "recall_at_10_vs_shard": 0.9994, "qcap8_qps": 130789.3,
         "measured_chip_qps": 1.2e5, "sharded_e2e_qps": 1.1e5,
         "vs_prev": 1.05},
    ]
    doc = {
        "metric": "pairwise_l2_expanded_8192x8192x512_f32",
        "value": 101000.5, "unit": "GFLOPS", "spread": 0.01,
        "repeats": 3, "f32_highest_gflops": 55000.2,
        # the round-12 stamp under test
        "program_audit_ms": 34193.2,
        "vs_baseline": 10.1, "vs_prev": 1.0,
        "extras": extras,
    }
    line = benchtop._fit_line(doc)
    parsed = json.loads(line)               # round-trips
    assert len(line) <= 1800
    assert isinstance(parsed, dict)
    # the stamp prints when the line has room...
    small = benchtop._fit_line({
        "metric": "pairwise_l2_expanded_8192x8192x512_f32",
        "value": 101000.5, "unit": "GFLOPS",
        "program_audit_ms": 34193.2, "extras": [],
    })
    assert json.loads(small)["program_audit_ms"] == 34193.2
    # ...is whitelisted-but-trimmable (the r11 acceptance keys are not)
    assert "program_audit_ms" in benchtop._PRINT_KEYS
    assert "program_audit_ms" in benchtop._TRIM_ORDER
    for key in ("speedup", "scan_engine", "recall_at_10"):
        assert key not in benchtop._TRIM_ORDER
        assert key in benchtop._PRINT_KEYS
    # a red audit's error string survives the _compact string filter
    err = benchtop._compact({
        "metric": "m", "program_audit_error": "exit 1: drift",
    })
    assert err["program_audit_error"] == "exit 1: drift"
    # and the stamp helper exists with the subprocess contract
    assert callable(benchtop._program_audit_stamp)


def test_round13_bench_line_parses_with_obs_overhead():
    """ISSUE 13 satellite (the _fit_line parse/cap test extended,
    following the r05-r12 pattern): the round-13 artifact shape — every
    prior row PLUS the open-loop row's ``obs_overhead_pct`` stamp
    (saturation QPS with the metric registry enabled vs
    ``RAFT_TPU_OBS=off``, docs/observability.md; acceptance <= ~2%) —
    must print as a line that json.loads-round-trips under the
    1800-char driver cap. The stamp is whitelisted-but-trimmable: the
    open-loop row's saturation/ratio acceptance keys outrank it when
    the line is tight."""
    import importlib.util
    import json

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "benchtop_r13", os.path.join(root, "bench.py")
    )
    benchtop = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(benchtop)

    extras = [
        {"metric": f"extra_{i}", "value": 10000.0 + i, "unit": "QPS",
         "spread": 0.05, "repeats": 7, "escalations": 1,
         "adc_engine": "pallas", "recall_at_10": 0.95,
         "build_s": 150.0, "build_warm_s": 2.0, "qcap8_qps": 1.2e5,
         "measured_chip_qps": 1.1e4, "sharded_e2e_qps": 1.05e4,
         "probe_recall_vs_flat": 0.997, "probe_flop_ratio": 5.2,
         "brute_force_same_shape_qps": 1.5e5, "vs_prev": 1.01}
        for i in range(8)
    ] + [
        # the round-13 open-loop row shape under test
        {"metric": "open_loop_ivf_flat_500000x96", "unit": "QPS",
         "scenario": "open_loop", "engine": "ivf_flat", "nq": 1024,
         "program_qps": 1.8e5, "saturation_qps": 1.5e5,
         "qps_ratio_vs_program": 0.83, "obs_overhead_pct": 1.4,
         "spread": 0.03, "repeats": 5,
         "p50_ms_50": 3.1, "p99_ms_50": 8.5, "p50_ms_80": 4.2,
         "p99_ms_80": 14.9, "p50_ms_95": 6.8, "p99_ms_95": 31.0,
         "shed_rate_95": 0.02, "vs_prev": 1.0},
    ]
    doc = {
        "metric": "pairwise_l2_expanded_8192x8192x512_f32",
        "value": 101000.5, "unit": "GFLOPS", "spread": 0.01,
        "repeats": 3, "f32_highest_gflops": 55000.2,
        "program_audit_ms": 34193.2,
        "vs_baseline": 10.1, "vs_prev": 1.0,
        "extras": extras,
    }
    line = benchtop._fit_line(doc)
    parsed = json.loads(line)               # round-trips
    assert len(line) <= 1800
    assert isinstance(parsed, dict)
    # the stamp prints when the line has room...
    small = benchtop._fit_line({
        "metric": "open_loop_ivf_flat_500000x96", "unit": "QPS",
        "saturation_qps": 1.5e5, "obs_overhead_pct": 1.4,
        "extras": [],
    })
    assert json.loads(small)["obs_overhead_pct"] == 1.4
    # ...is whitelisted-but-trimmable; the open-loop acceptance keys
    # it annotates are not trimmable
    assert "obs_overhead_pct" in benchtop._PRINT_KEYS
    assert "obs_overhead_pct" in benchtop._TRIM_ORDER
    for key in ("saturation_qps", "qps_ratio_vs_program"):
        assert key in benchtop._PRINT_KEYS
        assert key not in benchtop._TRIM_ORDER


def test_round15_bench_line_parses_with_zipf_hot_traffic():
    """ISSUE 15 satellite (the _fit_line parse/cap test extended,
    following the r05-r13 pattern): the round-15 artifact shape — every
    prior row PLUS the ``zipf_hot_traffic`` row (cache+coalescing
    saturation vs the uncached path under a Zipf(s≈1.1) mix,
    docs/serving.md "Hot traffic") — must print as a line that
    json.loads-round-trips under the 1800-char driver cap, with the
    acceptance keys (``qps_uplift``, ``cache_hit_rate``,
    ``cached_qps``, ``p99_ms_cached``) untrimmable."""
    import importlib.util
    import json

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "benchtop_r15", os.path.join(root, "bench.py")
    )
    benchtop = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(benchtop)

    extras = [
        {"metric": f"extra_{i}", "value": 10000.0 + i, "unit": "QPS",
         "spread": 0.05, "repeats": 7, "escalations": 1,
         "adc_engine": "pallas", "recall_at_10": 0.95,
         "build_s": 150.0, "build_warm_s": 2.0, "qcap8_qps": 1.2e5,
         "measured_chip_qps": 1.1e4, "sharded_e2e_qps": 1.05e4,
         "probe_recall_vs_flat": 0.997, "probe_flop_ratio": 5.2,
         "brute_force_same_shape_qps": 1.5e5, "vs_prev": 1.01}
        for i in range(8)
    ] + [
        # the round-13 open-loop row, unchanged
        {"metric": "open_loop_ivf_flat_500000x96", "unit": "QPS",
         "scenario": "open_loop", "engine": "ivf_flat", "nq": 1024,
         "program_qps": 1.8e5, "saturation_qps": 1.5e5,
         "qps_ratio_vs_program": 0.83, "obs_overhead_pct": 1.4,
         "spread": 0.03, "repeats": 5,
         "p50_ms_80": 4.2, "p99_ms_80": 14.9, "vs_prev": 1.0},
        # the round-15 hot-traffic row under test
        {"metric": "zipf_hot_traffic_ivf_flat_500000x96",
         "unit": "QPS", "scenario": "zipf_hot_traffic",
         "engine": "ivf_flat", "nq": 1024, "zipf_s": 1.1,
         "n_templates": 64, "program_qps": 1.8e5,
         "uncached_qps": 1.5e5, "cached_qps": 3.4e5,
         "qps_uplift": 2.27, "cache_hit_rate": 0.61,
         "coalesce_rate": 0.07, "p99_ms_uncached": 14.9,
         "p99_ms_cached": 9.1, "cached_identical": True,
         "spread": 0.03, "repeats": 5, "vs_prev": 1.0},
    ]
    doc = {
        "metric": "pairwise_l2_expanded_8192x8192x512_f32",
        "value": 101000.5, "unit": "GFLOPS", "spread": 0.01,
        "repeats": 3, "f32_highest_gflops": 55000.2,
        "program_audit_ms": 34193.2,
        "vs_baseline": 10.1, "vs_prev": 1.0,
        "extras": extras,
    }
    line = benchtop._fit_line(doc)
    parsed = json.loads(line)               # round-trips
    assert len(line) <= 1800
    assert isinstance(parsed, dict)
    # on a roomy line the row prints whole, acceptance keys included
    small = benchtop._fit_line({
        "metric": "zipf_hot_traffic_ivf_flat_500000x96", "unit": "QPS",
        "cached_qps": 3.4e5, "uncached_qps": 1.5e5,
        "qps_uplift": 2.27, "cache_hit_rate": 0.61,
        "coalesce_rate": 0.07, "cached_identical": True,
        "extras": [],
    })
    small_parsed = json.loads(small)
    assert small_parsed["qps_uplift"] == 2.27
    assert small_parsed["cache_hit_rate"] == 0.61
    assert small_parsed["cached_identical"] is True
    # the acceptance evidence is untrimmable; the secondaries trim
    for key in ("cached_qps", "qps_uplift", "cache_hit_rate",
                "p99_ms_cached"):
        assert key in benchtop._PRINT_KEYS
        assert key not in benchtop._TRIM_ORDER
    for key in ("zipf_s", "n_templates", "cached_identical",
                "coalesce_rate", "p99_ms_uncached", "uncached_qps"):
        assert key in benchtop._PRINT_KEYS
        assert key in benchtop._TRIM_ORDER


def test_round17_bench_line_parses_with_cold_tier():
    """ISSUE 17 satellite (the _fit_line parse/cap test extended,
    following the r05-r15 pattern): the round-17 artifact shape — every
    prior row PLUS the ``cold_tier`` row (same index served at
    1/capacity_x the HBM budget through the popularity tier,
    docs/tiering.md "Reading the bench row") — must print as a line
    that json.loads-round-trips under the 1800-char driver cap, with
    the acceptance keys (``capacity_x``, ``recall_vs_hot``,
    ``tier_hit_rate``, ``tiered_qps``, ``qps_ratio_vs_hot``,
    ``fetch_overlap_pct``, ``tier_hit_rate_95``) untrimmable."""
    import importlib.util
    import json

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "benchtop_r17", os.path.join(root, "bench.py")
    )
    benchtop = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(benchtop)

    extras = [
        {"metric": f"extra_{i}", "value": 10000.0 + i, "unit": "QPS",
         "spread": 0.05, "repeats": 7, "escalations": 1,
         "adc_engine": "pallas", "recall_at_10": 0.95,
         "build_s": 150.0, "build_warm_s": 2.0, "qcap8_qps": 1.2e5,
         "measured_chip_qps": 1.1e4, "sharded_e2e_qps": 1.05e4,
         "probe_recall_vs_flat": 0.997, "probe_flop_ratio": 5.2,
         "brute_force_same_shape_qps": 1.5e5, "vs_prev": 1.01}
        for i in range(8)
    ] + [
        # the round-15 hot-traffic row, unchanged
        {"metric": "zipf_hot_traffic_ivf_flat_500000x96",
         "unit": "QPS", "scenario": "zipf_hot_traffic",
         "engine": "ivf_flat", "nq": 1024, "zipf_s": 1.1,
         "n_templates": 64, "program_qps": 1.8e5,
         "uncached_qps": 1.5e5, "cached_qps": 3.4e5,
         "qps_uplift": 2.27, "cache_hit_rate": 0.61,
         "coalesce_rate": 0.07, "p99_ms_uncached": 14.9,
         "p99_ms_cached": 9.1, "cached_identical": True,
         "spread": 0.03, "repeats": 5, "vs_prev": 1.0},
        # the round-17 cold-tier row under test
        {"metric": "cold_tier_ivf_flat_500000x96", "unit": "QPS",
         "scenario": "cold_tier", "engine": "ivf_flat", "nq": 1024,
         "zipf_s": 1.1, "n_templates": 64, "n_slots": 512,
         "capacity_x": 4.0, "program_qps": 1.8e5,
         "hot_qps": 1.6e5, "tiered_qps": 1.4e5,
         "qps_ratio_vs_hot": 0.875, "tier_hit_rate": 0.93,
         "tier_hit_rate_50": 0.96, "tier_hit_rate_80": 0.94,
         "tier_hit_rate_95": 0.91, "p99_ms_50": 6.1,
         "p99_ms_80": 9.8, "p99_ms_95": 15.2,
         "fetch_overlap_pct": 71.4, "tier_fetches": 812,
         "recall_vs_hot": 0.982, "tier_degraded": False,
         "spread": 0.03, "repeats": 5, "vs_prev": 1.0},
    ]
    doc = {
        "metric": "pairwise_l2_expanded_8192x8192x512_f32",
        "value": 101000.5, "unit": "GFLOPS", "spread": 0.01,
        "repeats": 3, "f32_highest_gflops": 55000.2,
        "program_audit_ms": 34193.2,
        "vs_baseline": 10.1, "vs_prev": 1.0,
        "extras": extras,
    }
    line = benchtop._fit_line(doc)
    parsed = json.loads(line)               # round-trips
    assert len(line) <= 1800
    assert isinstance(parsed, dict)
    # on a roomy line the row prints whole, acceptance keys included
    small = benchtop._fit_line({
        "metric": "cold_tier_ivf_flat_500000x96", "unit": "QPS",
        "capacity_x": 4.0, "tiered_qps": 1.4e5, "hot_qps": 1.6e5,
        "qps_ratio_vs_hot": 0.875, "tier_hit_rate": 0.93,
        "fetch_overlap_pct": 71.4, "recall_vs_hot": 0.982,
        "tier_degraded": False,
        "extras": [],
    })
    small_parsed = json.loads(small)
    assert small_parsed["capacity_x"] == 4.0
    assert small_parsed["recall_vs_hot"] == 0.982
    assert small_parsed["tier_hit_rate"] == 0.93
    assert small_parsed["tier_degraded"] is False
    # the acceptance evidence is untrimmable; the secondaries trim
    for key in ("capacity_x", "recall_vs_hot", "tier_hit_rate",
                "tiered_qps", "qps_ratio_vs_hot", "fetch_overlap_pct",
                "tier_hit_rate_95"):
        assert key in benchtop._PRINT_KEYS
        assert key not in benchtop._TRIM_ORDER
    for key in ("n_slots", "tier_fetches", "tier_degraded",
                "tier_hit_rate_50", "tier_hit_rate_80", "hot_qps"):
        assert key in benchtop._PRINT_KEYS
        assert key in benchtop._TRIM_ORDER


def test_round18_bench_line_parses_with_self_heal():
    """ISSUE 18 satellite (the _fit_line parse/cap test extended,
    following the r05-r17 pattern): the round-18 artifact shape — every
    prior row PLUS the ``self_heal`` row (scripted kill→reroute→heal→
    reintegrate under open-loop Zipf, docs/robustness.md
    "Self-healing") — must print as a line that json.loads-round-trips
    under the 1800-char driver cap, with the acceptance stamps
    (``detection_ms``, ``route_convergence_ms``, ``reintegration_ms``,
    ``healed_p99_x``, ``p99_ms_degraded``) untrimmable."""
    import importlib.util
    import json

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "benchtop_r18", os.path.join(root, "bench.py")
    )
    benchtop = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(benchtop)

    extras = [
        {"metric": f"extra_{i}", "value": 10000.0 + i, "unit": "QPS",
         "spread": 0.05, "repeats": 7, "escalations": 1,
         "adc_engine": "pallas", "recall_at_10": 0.95,
         "build_s": 150.0, "build_warm_s": 2.0, "qcap8_qps": 1.2e5,
         "measured_chip_qps": 1.1e4, "sharded_e2e_qps": 1.05e4,
         "probe_recall_vs_flat": 0.997, "probe_flop_ratio": 5.2,
         "brute_force_same_shape_qps": 1.5e5, "vs_prev": 1.01}
        for i in range(8)
    ] + [
        # the round-17 cold-tier row, unchanged
        {"metric": "cold_tier_ivf_flat_500000x96", "unit": "QPS",
         "scenario": "cold_tier", "engine": "ivf_flat", "nq": 1024,
         "zipf_s": 1.1, "n_templates": 64, "n_slots": 512,
         "capacity_x": 4.0, "program_qps": 1.8e5,
         "hot_qps": 1.6e5, "tiered_qps": 1.4e5,
         "qps_ratio_vs_hot": 0.875, "tier_hit_rate": 0.93,
         "tier_hit_rate_50": 0.96, "tier_hit_rate_80": 0.94,
         "tier_hit_rate_95": 0.91, "p99_ms_50": 6.1,
         "p99_ms_80": 9.8, "p99_ms_95": 15.2,
         "fetch_overlap_pct": 71.4, "tier_fetches": 812,
         "recall_vs_hot": 0.982, "tier_degraded": False,
         "spread": 0.03, "repeats": 5, "vs_prev": 1.0},
        # the round-18 self-heal row under test
        {"metric": "self_heal_ivf_flat_500000x96", "unit": "ms",
         "scenario": "self_heal", "engine": "ivf_flat", "nq": 8,
         "request_size": 8, "zipf_s": 1.1, "n_templates": 32,
         "replication": 2, "n_ranks": 8, "rate_rps": 210.0,
         "detection_ms": 112.4, "route_convergence_ms": 113.0,
         "reintegration_ms": 41.7, "p99_ms_healthy": 9.8,
         "p99_ms_degraded": 14.2, "p99_ms_healed": 10.1,
         "healed_p99_x": 1.03, "route_pushes": 3, "heals_ok": 1,
         "transitions": 2, "all_serving": True, "gen_lag_ms": 4.4,
         "spread": 0.03, "repeats": 5, "vs_prev": 1.0},
    ]
    doc = {
        "metric": "pairwise_l2_expanded_8192x8192x512_f32",
        "value": 101000.5, "unit": "GFLOPS", "spread": 0.01,
        "repeats": 3, "f32_highest_gflops": 55000.2,
        "program_audit_ms": 34193.2,
        "vs_baseline": 10.1, "vs_prev": 1.0,
        "extras": extras,
    }
    line = benchtop._fit_line(doc)
    parsed = json.loads(line)               # round-trips
    assert len(line) <= 1800
    assert isinstance(parsed, dict)
    # on a roomy line the row prints whole, acceptance stamps included
    small = benchtop._fit_line({
        "metric": "self_heal_ivf_flat_500000x96", "unit": "ms",
        "detection_ms": 112.4, "route_convergence_ms": 113.0,
        "reintegration_ms": 41.7, "healed_p99_x": 1.03,
        "p99_ms_degraded": 14.2, "all_serving": True,
        "extras": [],
    })
    small_parsed = json.loads(small)
    assert small_parsed["detection_ms"] == 112.4
    assert small_parsed["route_convergence_ms"] == 113.0
    assert small_parsed["reintegration_ms"] == 41.7
    assert small_parsed["healed_p99_x"] == 1.03
    # the acceptance evidence is untrimmable; the secondaries trim
    for key in ("detection_ms", "route_convergence_ms",
                "reintegration_ms", "healed_p99_x", "p99_ms_degraded"):
        assert key in benchtop._PRINT_KEYS
        assert key not in benchtop._TRIM_ORDER
    for key in ("route_pushes", "heals_ok", "transitions",
                "all_serving", "rate_rps", "gen_lag_ms",
                "p99_ms_healthy", "p99_ms_healed"):
        assert key in benchtop._PRINT_KEYS
        assert key in benchtop._TRIM_ORDER


def test_round19_bench_line_parses_with_graph_ann():
    """ISSUE 19 satellite (the _fit_line parse/cap test extended,
    following the r05-r18 pattern): the round-19 artifact shape — every
    prior row PLUS the ``graph_ann`` row (one-dispatch beam search vs
    the in-row IVF-Flat qcap-1 baseline, docs/graph_ann.md) — must
    print as a line that json.loads-round-trips under the 1800-char
    driver cap, with the acceptance stamps (``p50_ms``,
    ``recall_at_10``, ``ivf_p50_ms``, ``ivf_recall_at_10``, ``beam``,
    ``degree``, ``iters``) untrimmable."""
    import importlib.util
    import json

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "benchtop_r19", os.path.join(root, "bench.py")
    )
    benchtop = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(benchtop)

    extras = [
        {"metric": f"extra_{i}", "value": 10000.0 + i, "unit": "QPS",
         "spread": 0.05, "repeats": 7, "escalations": 1,
         "adc_engine": "pallas", "recall_at_10": 0.95,
         "build_s": 150.0, "build_warm_s": 2.0, "qcap8_qps": 1.2e5,
         "measured_chip_qps": 1.1e4, "sharded_e2e_qps": 1.05e4,
         "probe_recall_vs_flat": 0.997, "probe_flop_ratio": 5.2,
         "brute_force_same_shape_qps": 1.5e5, "vs_prev": 1.01}
        for i in range(8)
    ] + [
        # the round-18 self-heal row, unchanged
        {"metric": "self_heal_ivf_flat_500000x96", "unit": "ms",
         "scenario": "self_heal", "engine": "ivf_flat", "nq": 8,
         "rate_rps": 210.0, "detection_ms": 112.4,
         "route_convergence_ms": 113.0, "reintegration_ms": 41.7,
         "p99_ms_healthy": 9.8, "p99_ms_degraded": 14.2,
         "p99_ms_healed": 10.1, "healed_p99_x": 1.03,
         "route_pushes": 3, "heals_ok": 1, "transitions": 2,
         "all_serving": True, "gen_lag_ms": 4.4,
         "spread": 0.03, "repeats": 5, "vs_prev": 1.0},
        # the round-19 graph-ANN row under test
        {"metric": "graph_ann_500000x96", "unit": "ms",
         "scenario": "graph_ann", "engine": "graph", "nq": 1,
         "degree": 16, "beam": 32, "iters": 23,
         "p50_ms": 0.41, "recall_at_10": 0.961, "spread": 0.04,
         "repeats": 5, "ivf_p50_ms": 1.38, "ivf_recall_at_10": 0.958,
         "ivf_qcap": 8, "ivf_spread": 0.05, "vs_prev": 1.0},
    ]
    doc = {
        "metric": "pairwise_l2_expanded_8192x8192x512_f32",
        "value": 101000.5, "unit": "GFLOPS", "spread": 0.01,
        "repeats": 3, "f32_highest_gflops": 55000.2,
        "program_audit_ms": 34193.2,
        "vs_baseline": 10.1, "vs_prev": 1.0,
        "extras": extras,
    }
    line = benchtop._fit_line(doc)
    parsed = json.loads(line)               # round-trips
    assert len(line) <= 1800
    assert isinstance(parsed, dict)
    # on a roomy line the row prints whole, acceptance stamps included
    small = benchtop._fit_line({
        "metric": "graph_ann_500000x96", "unit": "ms",
        "p50_ms": 0.41, "recall_at_10": 0.961, "ivf_p50_ms": 1.38,
        "ivf_recall_at_10": 0.958, "beam": 32, "degree": 16,
        "iters": 23, "extras": [],
    })
    small_parsed = json.loads(small)
    assert small_parsed["p50_ms"] == 0.41
    assert small_parsed["ivf_p50_ms"] == 1.38
    assert small_parsed["beam"] == 32
    assert small_parsed["iters"] == 23
    # the acceptance evidence is untrimmable; the secondaries trim
    for key in ("p50_ms", "recall_at_10", "ivf_p50_ms",
                "ivf_recall_at_10", "beam", "degree", "iters"):
        assert key in benchtop._PRINT_KEYS
        assert key not in benchtop._TRIM_ORDER
    for key in ("ivf_qcap", "ivf_spread"):
        assert key in benchtop._PRINT_KEYS
        assert key in benchtop._TRIM_ORDER


def test_round20_bench_line_parses_with_durable_ingest():
    """ISSUE 20 satellite (the _fit_line parse/cap test extended,
    following the r05-r19 pattern): the round-20 artifact shape — every
    prior row PLUS the ``durable_ingest`` row (fsync-durable acked QPS
    vs the non-durable apply, docs/robustness.md "Durability") — must
    print as a line that json.loads-round-trips under the 1800-char
    driver cap, with the acceptance stamps (``durable_qps``,
    ``nondurable_qps``, ``durability_ratio``) untrimmable."""
    import importlib.util
    import json

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "benchtop_r20", os.path.join(root, "bench.py")
    )
    benchtop = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(benchtop)

    extras = [
        {"metric": f"extra_{i}", "value": 10000.0 + i, "unit": "QPS",
         "spread": 0.05, "repeats": 7, "escalations": 1,
         "adc_engine": "pallas", "recall_at_10": 0.95,
         "build_s": 150.0, "build_warm_s": 2.0, "qcap8_qps": 1.2e5,
         "measured_chip_qps": 1.1e4, "sharded_e2e_qps": 1.05e4,
         "probe_recall_vs_flat": 0.997, "probe_flop_ratio": 5.2,
         "brute_force_same_shape_qps": 1.5e5, "vs_prev": 1.01}
        for i in range(8)
    ] + [
        # the round-19 graph-ANN row, unchanged
        {"metric": "graph_ann_500000x96", "unit": "ms",
         "scenario": "graph_ann", "engine": "graph", "nq": 1,
         "degree": 16, "beam": 32, "iters": 23,
         "p50_ms": 0.41, "recall_at_10": 0.961, "spread": 0.04,
         "repeats": 5, "ivf_p50_ms": 1.38, "ivf_recall_at_10": 0.958,
         "ivf_qcap": 8, "ivf_spread": 0.05, "vs_prev": 1.0},
        # the round-20 durable-ingest row under test
        {"metric": "durable_ingest_500000x96", "unit": "QPS",
         "scenario": "durable_ingest", "engine": "ivf_flat",
         "durable_qps": 38500.0, "nondurable_qps": 41200.0,
         "durability_ratio": 0.934, "fsync_interval_ms": 0.0,
         "fsync_p50_ms": 0.071, "wal_mb_per_s": 18.4,
         "spread": 0.03, "repeats": 5, "vs_prev": 1.0},
    ]
    doc = {
        "metric": "pairwise_l2_expanded_8192x8192x512_f32",
        "value": 101000.5, "unit": "GFLOPS", "spread": 0.01,
        "repeats": 3, "f32_highest_gflops": 55000.2,
        "program_audit_ms": 34193.2,
        "vs_baseline": 10.1, "vs_prev": 1.0,
        "extras": extras,
    }
    line = benchtop._fit_line(doc)
    parsed = json.loads(line)               # round-trips
    assert len(line) <= 1800
    assert isinstance(parsed, dict)
    # on a roomy line the row prints whole, acceptance stamps included
    small = benchtop._fit_line({
        "metric": "durable_ingest_500000x96", "unit": "QPS",
        "durable_qps": 38500.0, "nondurable_qps": 41200.0,
        "durability_ratio": 0.934, "fsync_interval_ms": 0.0,
        "fsync_p50_ms": 0.071, "wal_mb_per_s": 18.4, "extras": [],
    })
    small_parsed = json.loads(small)
    assert small_parsed["durable_qps"] == 38500.0
    assert small_parsed["nondurable_qps"] == 41200.0
    assert small_parsed["durability_ratio"] == 0.934
    # the acceptance evidence is untrimmable; the secondaries trim
    for key in ("durable_qps", "nondurable_qps", "durability_ratio"):
        assert key in benchtop._PRINT_KEYS
        assert key not in benchtop._TRIM_ORDER
    for key in ("fsync_interval_ms", "fsync_p50_ms", "wal_mb_per_s"):
        assert key in benchtop._PRINT_KEYS
        assert key in benchtop._TRIM_ORDER
