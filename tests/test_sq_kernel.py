"""int8 IVF-SQ Pallas dequant+scan engine (spatial/ann/sq_kernel) and
the shared scan-kernel core (spatial/ann/scan_core) — tier-1 coverage
(ISSUE 11).

The kernel runs under ``interpret=True`` on the CPU test platform,
pinned bitwise against its op-for-op lax mirror; the grouped SQ search's
``use_pallas=True`` path is then pinned against the XLA dequant scan.
Bit-identity between engines is asserted on DYADIC-EXACT fixtures:
``vscale`` a power of two (here exactly 1) and integer ``vmin`` make
every dequantized value a bf16-exact integer, so with a SATURATED rerank
pool both engines exact-score the same candidate set in f32 and
``(dists, ids)`` must match to the bit — the contract the sq_kernel
module docstring pins. The shared-planner property tests cover the ONE
``scan_core.plan_l_tile`` all three engines hand their byte models to
(the ISSUE 11 acceptance: one planner, duplicated copies deleted), the
``pad_queries`` rounding authority, and the ``tile_profile`` latency
plan. MNMG parity and the zero-retrace health-flip audit run inside the
fused one-dispatch program with the SQ kernel engaged.
"""

import dataclasses
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.spatial.ann import (
    IVFFlatParams, IVFSQParams, ivf_flat_build,
)
from raft_tpu.spatial.ann import flat_kernel, pq_kernel, scan_core, sq_kernel
from raft_tpu.spatial.ann.ivf_sq import (
    IVFSQIndex,
    _resolve_sq_engine,
    ivf_sq_search,
    ivf_sq_search_grouped,
)

K_NN = 5


# -- the shared planner: one authority, three byte models --------------------

def test_plan_l_tile_shrinks_monotone_under_budget():
    """The 512->128 halving ladder: as the query block grows, the ONE
    shared planner's tile only ever SHRINKS, through lane-aligned steps,
    to None once even a 128-row tile exceeds the VMEM budget — for every
    engine's byte model (the planner is shared; the byte models are what
    differ)."""
    plans = {
        "flat": lambda qp: flat_kernel.plan_l_tile(96, qp),
        "sq": lambda qp: sq_kernel.plan_l_tile(96, qp),
        "pq": lambda qp: pq_kernel.plan_l_tile(12 * 256, qp),
    }
    for name, plan in plans.items():
        prev = 513
        for q_pad in (16, 64, 256, 1024, 4096, 1 << 15, 1 << 18, 1 << 21):
            lt = plan(q_pad)
            if lt is None:
                # None is terminal: every larger block must also fail
                assert plan(2 * q_pad) is None, name
                break
            assert lt % scan_core.LANE == 0, name
            assert lt <= min(prev, 512), (name, q_pad, lt, prev)
            prev = lt
        else:
            pytest.fail(f"{name}: planner never exhausted its budget")
    # the int8 model is leaner than the bf16 flat model at equal
    # geometry (that IS the footprint win): its plan is never narrower
    for q_pad in (16, 256, 4096):
        f, s = plans["flat"](q_pad), plans["sq"](q_pad)
        if f is not None:
            assert s is not None and s >= f


def test_pad_queries_is_the_one_rounding_authority():
    """Every engine re-exports scan_core.pad_queries — the bf16-sublane
    rounding a resolver approves and a serving plan then replays."""
    assert flat_kernel.pad_queries is scan_core.pad_queries
    assert sq_kernel.pad_queries is scan_core.pad_queries
    for qcap, want in ((1, 16), (8, 16), (16, 16), (17, 32), (48, 48)):
        assert scan_core.pad_queries(qcap) == want
        assert scan_core.pad_queries(qcap) % scan_core.Q_GRANULE == 0


def test_supported_predicates_agree_with_their_plans():
    """Each engine's *_supported predicate must equal "the shared
    planner approves this geometry under the profile the grouped path
    would auto-select" — approval and plan can never round differently
    (they share pad_queries/tile_profile calls by construction)."""
    for d, qcap in ((8, 1), (96, 8), (96, 48), (768, 512), (1 << 20, 64)):
        prof = scan_core.tile_profile(qcap)
        qp = scan_core.pad_queries(qcap)
        assert flat_kernel.flat_scan_supported(d, qcap) == (
            flat_kernel.plan_l_tile(d, qp, profile=prof) is not None
        )
        assert sq_kernel.sq_scan_supported(d, qcap) == (
            sq_kernel.plan_l_tile(d, qp, profile=prof) is not None
        )
    for bits in (4, 8):
        for qcap in (8, 48, 512):
            mk = 12 * (1 << bits)
            prof = scan_core.tile_profile(qcap)
            assert pq_kernel.pq_adc_supported(12, bits, qcap) == (
                pq_kernel.plan_l_tile(
                    mk, scan_core.pad_queries(qcap), profile=prof
                ) is not None
            )
    assert not sq_kernel.sq_scan_supported(0, 8)


def test_latency_profile_widens_small_qcap_tiles():
    """tile_profile: qcap <= 8 (the open-loop serving buckets) selects
    the latency plan, whose start width is 1024 — a tiny query block
    leaves the budget nearly untouched, so the plan holds the doubled
    tile and the grid-step count halves exactly where p99 lives."""
    assert scan_core.tile_profile(1) == "latency"
    assert scan_core.tile_profile(8) == "latency"
    assert scan_core.tile_profile(9) == "throughput"
    lt_thr = sq_kernel.plan_l_tile(96, 16, profile="throughput")
    lt_lat = sq_kernel.plan_l_tile(96, 16, profile="latency")
    assert lt_thr == 512 and lt_lat == 1024
    assert flat_kernel.plan_l_tile(96, 16, profile="latency") == 1024
    # the latency plan still shrinks under pressure — profile changes
    # the START, never the budget
    wide = sq_kernel.plan_l_tile(1 << 14, 16, profile="latency")
    assert wide is None or wide <= 1024


# -- the SQ kernel vs its lax mirror -----------------------------------------

def _sq_case(rng, lb, q, d, l_pad, dyadic=True):
    qrows = jnp.asarray(
        rng.integers(-64, 64, (lb, q, d)), jnp.float32
    )
    codes_t = jnp.asarray(
        rng.integers(-128, 128, (lb, d, l_pad)), jnp.int8
    )
    if dyadic:
        vmin = jnp.asarray(rng.integers(-8, 8, (d,)), jnp.float32)
        vscale = jnp.full((d,), 0.5, jnp.float32)
    else:
        vmin = jnp.asarray(rng.standard_normal(d), jnp.float32)
        vscale = jnp.asarray(
            np.abs(rng.standard_normal(d)) / 255.0 + 1e-3, jnp.float32
        )
    return qrows, codes_t, vmin, vscale


@pytest.mark.parametrize(
    "lb,q,d,l_pad,l_tile,dyadic",
    [
        (3, 32, 16, 256, 128, True),    # two slab tiles per list
        (2, 16, 24, 128, 128, False),   # generic affine stats
        (1, 48, 8, 512, 256, True),     # wider tiles
    ],
)
def test_sq_kernel_matches_lax_mirror_bitwise(rng_np, lb, q, d, l_pad,
                                              l_tile, dyadic):
    """Interpret-mode kernel == lax mirror, bit for bit, masked rows
    included — generic (non-dyadic) affine stats too: the mirror shares
    the kernel's exact dequant spelling (_dequant_tile), so the pin
    holds regardless of rounding."""
    qrows, codes_t, vmin, vscale = _sq_case(rng_np, lb, q, d, l_pad,
                                            dyadic)
    bounds = jnp.asarray(
        [[i, max(i, l_pad - 7 * i)] for i in range(lb)], jnp.int32
    )
    got = sq_kernel.sq_scan_subchunk_min(
        qrows, codes_t, bounds, vmin, vscale,
        interpret=True, l_tile=l_tile,
    )
    ref = sq_kernel.sq_scan_subchunk_min_lax(
        qrows, codes_t, bounds, vmin, vscale
    )
    assert got.shape == (lb, q, l_pad // scan_core.SUBCHUNK)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_sq_kernel_oracle_and_masking(rng_np):
    """Dequantized-vector distances against a float oracle on a
    bf16-EXACT fixture (y = code/2 — every value fits bf16's 8
    significand bits, so the in-kernel rounding is the identity and
    the oracle comparison is exact); lo == hi (empty list) -> every
    sub-chunk min is BIG."""
    qrows, codes_t, _, _ = _sq_case(rng_np, 2, 16, 16, 256)
    vmin = jnp.full((16,), -64.0, jnp.float32)
    vscale = jnp.full((16,), 0.5, jnp.float32)
    bounds = jnp.asarray([[5, 5], [0, 256]], jnp.int32)
    got = np.asarray(sq_kernel.sq_scan_subchunk_min(
        qrows, codes_t, bounds, vmin, vscale, interpret=True, l_tile=128
    ))
    assert (got[0] == scan_core.BIG).all()
    assert (got[1] < scan_core.BIG).all()
    # oracle over list 1 (full range): dyadic dequant is exact in f32
    y = (np.asarray(codes_t[1], np.float32) + 128.0) \
        * np.asarray(vscale)[:, None] + np.asarray(vmin)[:, None]
    qv = np.asarray(qrows[1], np.float32)
    d2 = (qv ** 2).sum(1)[:, None] + (y ** 2).sum(0)[None, :] \
        - 2.0 * (qv @ y)
    ref = d2.reshape(16, -1, scan_core.SUBCHUNK).min(-1)
    np.testing.assert_allclose(got[1], ref, rtol=1e-5, atol=1e-3)


def test_sq_kernel_validates_shapes_and_dtype():
    with pytest.raises(ValueError, match="int8"):
        sq_kernel.sq_scan_subchunk_min(
            jnp.zeros((1, 16, 16), jnp.float32),
            jnp.zeros((1, 16, 128), jnp.uint8),     # wrong dtype
            jnp.zeros((1, 2), jnp.int32),
            jnp.zeros((16,)), jnp.ones((16,)), interpret=True,
        )
    with pytest.raises(ValueError, match="dim"):
        sq_kernel.sq_scan_subchunk_min(
            jnp.zeros((1, 16, 16), jnp.float32),
            jnp.zeros((1, 24, 128), jnp.int8),      # slab dim mismatch
            jnp.zeros((1, 2), jnp.int32),
            jnp.zeros((24,)), jnp.ones((24,)), interpret=True,
        )
    with pytest.raises(ValueError, match="multiple"):
        sq_kernel.sq_scan_subchunk_min(
            jnp.zeros((1, 8, 16), jnp.float32),     # Q=8 not 16-aligned
            jnp.zeros((1, 16, 128), jnp.int8),
            jnp.zeros((1, 2), jnp.int32),
            jnp.zeros((16,)), jnp.ones((16,)), interpret=True,
        )


# -- grouped search: engine equivalence --------------------------------------

def _int_sq_index(x_int, n_lists=48):
    """A dyadic-exact SQ index: codes ARE the integer rows (vmin=-128,
    vscale=1 -> y = code), so every dequantized value is a bf16-exact
    integer and saturated-pool engine comparisons are BIT-identical
    (the sq_kernel docstring contract)."""
    d = x_int.shape[1]
    base = ivf_flat_build(x_int, IVFFlatParams(
        n_lists=n_lists, kmeans_n_iters=4, kmeans_init="random",
    ), metric="sqeuclidean")
    return IVFSQIndex(
        centroids=base.centroids,
        codes_sorted=base.data_sorted.astype(jnp.int8),
        vmin=jnp.full((d,), -128.0, jnp.float32),
        vscale=jnp.ones((d,), jnp.float32),
        storage=base.storage,
    )


def _int_dataset(seed, n=3000, d=16, nq=64):
    rng = np.random.default_rng(seed)
    centers = rng.integers(-60, 60, (8, d))
    x = (
        centers[rng.integers(0, 8, n)]
        + rng.integers(-6, 7, (n, d))
    ).clip(-127, 127).astype(np.float32)
    q = (
        x[rng.integers(0, n, nq)] + rng.integers(-2, 3, (nq, d))
    ).astype(np.float32)
    return x, q


@pytest.fixture(scope="module")
def dataset():
    return _int_dataset(7)


@pytest.fixture(scope="module")
def sq_index(dataset):
    x, _ = dataset
    return _int_sq_index(x)


def _saturating_ratio(index, p, k):
    l_tile = sq_kernel.plan_l_tile(index.centroids.shape[1], 64)
    l_pad = -(-index.storage.max_list // l_tile) * l_tile
    return float(p * l_pad // scan_core.SUBCHUNK) / k + 1.0


@pytest.mark.parametrize("stream", [None, True])
def test_sq_saturated_pool_bit_identical_single_chip(dataset, sq_index,
                                                     stream):
    """With the rerank pool covering every probed row, BOTH engines
    exact-score the same f32-dequantized candidate set — on the dyadic
    fixture the returned (dists, ids) must match to the bit."""
    x, q = dataset
    p = 4
    kw = dict(n_probes=p, qcap=64, stream_partials=stream,
              rerank_ratio=_saturating_ratio(sq_index, p, K_NN))
    d0, i0 = ivf_sq_search_grouped(sq_index, q, K_NN,
                                   use_pallas=False, **kw)
    d1, i1 = ivf_sq_search_grouped(sq_index, q, K_NN,
                                   use_pallas=True, **kw)
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))


def test_sq_grouped_matches_per_query_search(dataset, sq_index):
    """The grouped SQ search (XLA and kernel engines) agrees with the
    per-query SQ search at full probe width on the dyadic fixture —
    three spellings of one exact computation."""
    x, q = dataset
    nl = sq_index.centroids.shape[0]
    d0, i0 = ivf_sq_search(sq_index, q, K_NN, n_probes=nl)
    kw = dict(n_probes=nl, qcap=q.shape[0],
              rerank_ratio=_saturating_ratio(sq_index, nl, K_NN))
    for up in (False, True):
        d1, i1 = ivf_sq_search_grouped(sq_index, q, K_NN,
                                       use_pallas=up, **kw)
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))


def test_sq_kernel_recall_non_inferior(dataset, sq_index):
    """At a modest rerank_ratio the top-c sub-chunks cover the top-c
    rows of the bf16 scan (the 8-row cover argument over dequantized
    values), so kernel-path recall must not fall below the XLA dequant
    engine's beyond bf16 boundary noise."""
    from tests.oracles import np_knn_ids

    x, q = dataset
    true = np_knn_ids(x, np.asarray(q), K_NN)

    def rec(ids):
        g = np.asarray(ids)
        return sum(
            len(set(a.tolist()) & set(b.tolist()))
            for a, b in zip(g, true)
        ) / true.size

    kw = dict(n_probes=4, qcap=64, rerank_ratio=4.0)
    r_pal = rec(ivf_sq_search_grouped(sq_index, q, K_NN,
                                      use_pallas=True, **kw)[1])
    r_xla = rec(ivf_sq_search_grouped(sq_index, q, K_NN,
                                      use_pallas=False, **kw)[1])
    assert r_pal >= r_xla - 0.01, (r_pal, r_xla)


def test_sq_use_pallas_true_names_planner_requirement(dataset, sq_index):
    """Explicit opt-in must not silently fall back — and the message
    now names the unmet PLANNER requirement (the ISSUE 11 satellite:
    the pre-r11 message claimed no kernel path exists at all)."""
    x, q = dataset
    with pytest.raises(Exception) as ei:
        _resolve_sq_engine(True, 1 << 20, 512)
    msg = str(ei.value)
    assert "sq_scan_supported" in msg and "plan_l_tile" in msg
    assert "VMEM" in msg
    # k > max_list routes to the per-query search (no kernel path)
    with pytest.raises(Exception, match="per-query"):
        ivf_sq_search_grouped(
            sq_index, q, sq_index.storage.max_list + 1,
            n_probes=4, use_pallas=True,
        )


def test_resolve_sq_engine_auto_off_tpu():
    assert jax.default_backend() != "tpu"
    assert _resolve_sq_engine(None, 96, 48) is False
    assert _resolve_sq_engine(True, 96, 48) is True
    assert _resolve_sq_engine(False, 96, 48) is False


def test_cpu_default_never_imports_sq_kernel_module():
    """A fresh JAX_PLATFORMS=cpu process running default grouped SQ
    searches (plus warmup) must not import (let alone compile) the
    Pallas kernel modules — scan_core included."""
    prog = (
        "import os, sys\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "import numpy as np\n"
        "from raft_tpu.spatial.ann import IVFSQParams, ivf_sq_build\n"
        "from raft_tpu.spatial.ann.ivf_sq import "
        "ivf_sq_search_grouped\n"
        "rng = np.random.default_rng(0)\n"
        "x = rng.standard_normal((400, 8)).astype(np.float32)\n"
        "idx = ivf_sq_build(x, IVFSQParams(n_lists=8,\n"
        "    kmeans_n_iters=2))\n"
        "idx.warmup(8, k=3, n_probes=2)\n"
        "ivf_sq_search_grouped(idx, x[:8], 3, n_probes=2, qcap=8)\n"
        "for mod in ('sq_kernel', 'flat_kernel', 'scan_core'):\n"
        "    full = 'raft_tpu.spatial.ann.' + mod\n"
        "    assert full not in sys.modules, full + ' imported'\n"
        "print('OK')\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", prog], capture_output=True, text=True,
        timeout=300,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "OK" in out.stdout


# -- mutation tier: tombstones at the rerank tail ----------------------------

def test_sq_mutable_search_engine_parity_with_tombstones(dataset):
    """The SQ kernel path folds the mutation tier's row_mask at its
    exact rerank tail: on a small-list dyadic index both engines must
    return identical ids after upserts AND deletes, and no deleted id
    may ever surface."""
    from raft_tpu.spatial.ann.mutation import (
        delete, mutable_search, upsert, wrap_mutable,
    )

    x, q = dataset
    idx = _int_sq_index(x, n_lists=64)
    p = 3
    assert 4 * 10 * scan_core.SUBCHUNK >= p * idx.storage.max_list, \
        "fixture must saturate the default rerank pool"
    m = wrap_mutable(idx, delta_cap=32)
    assert m.engine == "sq"
    rng = np.random.default_rng(3)
    up_ids = jnp.asarray(rng.integers(0, x.shape[0], 8), jnp.int32)
    m, acc = upsert(m, jnp.asarray(x[np.asarray(up_ids)] + 1.0), up_ids)
    assert acc.all()
    dead = jnp.asarray(rng.integers(0, x.shape[0], 40), jnp.int32)
    m, _ = delete(m, dead)
    kw = dict(n_probes=p, qcap=64)
    d0, i0 = mutable_search(m, q, 10, use_pallas=False, **kw)
    d1, i1 = mutable_search(m, q, 10, use_pallas=True, **kw)
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
    alive_dead = set(np.asarray(dead).tolist()) - \
        set(np.asarray(up_ids).tolist())
    for ids in (i0, i1):
        got = set(np.asarray(ids).ravel().tolist())
        assert not (got & alive_dead), "deleted rows surfaced"


def test_sq_compact_requantizes_against_kept_stats(dataset):
    """Compaction folds deltas + tombstones into fresh int8 slabs
    against the KEPT affine stats (the PQ-codebook rule): surviving
    main rows round-trip losslessly, and the compacted state keeps
    serving through the same engine."""
    from raft_tpu.spatial.ann.mutation import (
        compact, mutable_search, upsert, wrap_mutable,
    )

    x, q = dataset
    idx = _int_sq_index(x, n_lists=32)
    m = wrap_mutable(idx, delta_cap=16)
    ids = jnp.asarray([1, 2, 3], jnp.int32)
    m, acc = upsert(m, jnp.asarray(x[1:4] + 2.0), ids)
    assert acc.all()
    m2, stats = compact(m)
    assert m2.engine == "sq"
    assert isinstance(m2.index, IVFSQIndex)
    assert m2.index.codes_sorted.dtype == jnp.int8
    d0, i0 = mutable_search(m, q, K_NN, n_probes=8, qcap=64)
    d1, i1 = mutable_search(m2, q, K_NN, n_probes=8, qcap=64)
    # dyadic integers survive the re-quantization round trip exactly,
    # so pre- and post-compaction searches agree on the dyadic fixture
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))


# -- MNMG: the fused one-dispatch program ------------------------------------

@pytest.fixture(scope="module")
def comms8():
    from raft_tpu.comms import build_comms

    return build_comms(jax.devices()[:8])


@pytest.fixture(scope="module")
def sharded_sq_index(dataset, comms8):
    from raft_tpu.comms import mnmg_ivf_sq_build

    x, _ = dataset
    idx = mnmg_ivf_sq_build(comms8, x, IVFSQParams(
        n_lists=32, kmeans_n_iters=4,
    ))
    # pin the dyadic contract on the sharded fixture too: codes stay,
    # the affine map becomes the identity-on-integers one
    d = x.shape[1]
    return dataclasses.replace(
        idx,
        vmin=jnp.full((d,), -128.0, jnp.float32),
        vscale=jnp.ones((d,), jnp.float32),
    )


def test_mnmg_sq_fused_program_engine_parity(dataset, comms8,
                                             sharded_sq_index):
    """The SQ kernel ACTIVE inside the MNMG fused one-dispatch program:
    saturated-pool results bit-identical to the XLA dequant engine's."""
    from raft_tpu.comms import mnmg_ivf_sq_search

    x, q = dataset
    p = 4
    l_tile = sq_kernel.plan_l_tile(x.shape[1], 64)
    l_pad = -(-int(sharded_sq_index.max_list) // l_tile) * l_tile
    rr = float(p * l_pad // scan_core.SUBCHUNK) / K_NN + 1.0
    kw = dict(n_probes=p, qcap=q.shape[0], rerank_ratio=rr)
    d0, i0 = mnmg_ivf_sq_search(comms8, sharded_sq_index, q, K_NN,
                                use_pallas=False, **kw)
    d1, i1 = mnmg_ivf_sq_search(comms8, sharded_sq_index, q, K_NN,
                                use_pallas=True, **kw)
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))


def test_mnmg_sq_health_flip_zero_retrace(
    dataset, comms8, sharded_sq_index, monkeypatch
):
    """The ISSUE 11 acceptance trace-audit with the SQ kernel engaged:
    use_pallas is a trace-time static, health/failover stay runtime
    inputs — shard_mask flips must reuse the ONE compiled fused
    program (zero retraces)."""
    from raft_tpu.comms import mnmg_ivf_flat as mod

    _, q = dataset
    created = []
    orig = mod._cached_search

    def recording(*a, **k):
        fn = orig(*a, **k)
        created.append(fn)
        return fn

    monkeypatch.setattr(mod, "_cached_search", recording)
    kw = dict(n_probes=4, qcap=q.shape[0], use_pallas=True)
    m_up = np.ones(8, np.int32)
    m_one = m_up.copy()
    m_one[3] = 0
    mod.mnmg_ivf_sq_search(comms8, sharded_sq_index, q, K_NN,
                           shard_mask=m_up, **kw)
    fn = created[0]
    size0 = fn._cache_size()
    for mask in (m_one, m_up):
        res = mod.mnmg_ivf_sq_search(comms8, sharded_sq_index, q, K_NN,
                                     shard_mask=mask, **kw)
    assert all(f is fn for f in created), \
        "health flips must reuse the cached program object"
    assert fn._cache_size() == size0, \
        "health flips must not retrace the compiled kernel program"
    assert float(jnp.min(res.coverage)) == 1.0


def test_mnmg_sq_index_places_and_serializes(dataset, comms8,
                                             sharded_sq_index, tmp_path):
    """The SQ index rides the shared placement/serialization machinery:
    save -> load -> place round-trips with identical search results."""
    from raft_tpu.comms import mnmg_ivf_sq_search, place_index
    from raft_tpu.spatial.ann import load_index, save_index

    x, q = dataset
    path = tmp_path / "sq.idx"
    save_index(sharded_sq_index, path)
    loaded = place_index(comms8, load_index(path))
    d0, i0 = mnmg_ivf_sq_search(comms8, sharded_sq_index, q, K_NN,
                                n_probes=4, qcap=48)
    d1, i1 = mnmg_ivf_sq_search(comms8, loaded, q, K_NN,
                                n_probes=4, qcap=48)
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))


def test_sq_compact_survivor_codes_verbatim_extreme_stats(dataset):
    """Survivor codes must ride compaction VERBATIM — a decode->
    re-encode round trip drifts a code unit once |vmin| dwarfs the
    dimension's range (f32 add/subtract of the offset is inexact), so
    the fold is pinned on adversarial stats: huge offset, tiny scale."""
    from raft_tpu.spatial.ann.mutation import compact, wrap_mutable

    x, _ = dataset
    d = x.shape[1]
    base = _int_sq_index(x, n_lists=32)
    idx = dataclasses.replace(
        base,
        vmin=jnp.full((d,), 2.0 ** 20, jnp.float32),
        vscale=jnp.full((d,), 2.0 ** -10, jnp.float32),
    )
    m = wrap_mutable(idx, delta_cap=8)
    m2, _ = compact(m)

    def by_id(index):
        sid = np.asarray(index.storage.sorted_ids)
        codes = np.asarray(index.codes_sorted)
        return {
            int(i): codes[pos].tobytes()
            for pos, i in enumerate(sid.tolist()) if i >= 0
        }

    assert by_id(m2.index) == by_id(idx), \
        "compaction rewrote untouched survivor codes"


def test_mnmg_sq_mutable_search_routes_to_sq_engine(dataset, comms8,
                                                    sharded_sq_index):
    """mnmg_mutable_search must dispatch an MnmgIVFSQIndex to the SQ
    fused program (the flat route would feed its None vectors_sorted
    into shard_map): upsert -> delete -> search through the mutation-
    tier variant, fresh row visible, deleted row never surfaces."""
    from raft_tpu.comms.mnmg_mutation import (
        mnmg_delete, mnmg_mutable_search, mnmg_upsert, wrap_mnmg_mutable,
    )

    x, q = dataset
    m = wrap_mnmg_mutable(comms8, sharded_sq_index, delta_cap=8)
    fresh = jnp.asarray(q[:1] * 0 + 3.0)          # a distinctive row
    m, acc = mnmg_upsert(comms8, m, fresh, jnp.asarray([7], jnp.int32))
    assert acc.all()
    m, found = mnmg_delete(comms8, m, jnp.asarray([11], jnp.int32))
    assert found.all()
    dv, iv = mnmg_mutable_search(
        comms8, m, fresh, 5, n_probes=4, qcap=8, use_pallas=True,
    )
    ids0 = np.asarray(iv)[0]
    assert 7 in ids0.tolist(), "upserted row must be visible"
    dall, iall = mnmg_mutable_search(
        comms8, m, jnp.asarray(q), 10, n_probes=8, qcap=q.shape[0],
    )
    assert 11 not in set(np.asarray(iall).ravel().tolist()), \
        "deleted row surfaced"
