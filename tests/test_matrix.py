"""matrix utils tests (analog of reference cpp/test/matrix/*)."""

import numpy as np
import pytest

from raft_tpu import matrix


@pytest.fixture
def m(rng_np):
    return rng_np.standard_normal((8, 6)).astype(np.float32)


def test_copy_rows(m):
    idx = np.array([3, 0, 5])
    np.testing.assert_array_equal(matrix.copy_rows(m, idx), m[idx])


def test_slice(m):
    np.testing.assert_array_equal(matrix.slice_matrix(m, 1, 2, 5, 4), m[1:5, 2:4])


def test_reverse(m):
    np.testing.assert_array_equal(matrix.col_reverse(m), m[:, ::-1])
    np.testing.assert_array_equal(matrix.row_reverse(m), m[::-1, :])


def test_diagonal(m):
    sq = m[:6, :6]
    np.testing.assert_array_equal(matrix.get_diagonal(sq), np.diagonal(sq))
    newdiag = np.arange(6, dtype=np.float32)
    got = np.asarray(matrix.set_diagonal(sq, newdiag))
    np.testing.assert_array_equal(np.diagonal(got), newdiag)
    inv = np.asarray(matrix.invert_diagonal(sq))
    np.testing.assert_allclose(np.diagonal(inv), 1.0 / np.diagonal(sq), rtol=1e-5)


def test_argmax_argmin(m):
    np.testing.assert_array_equal(matrix.argmax(m, axis=1), m.argmax(1))
    np.testing.assert_array_equal(matrix.argmin(m, axis=0), m.argmin(0))


def test_ratio(m):
    x = np.abs(m) + 0.1
    np.testing.assert_allclose(matrix.ratio(x), x / x.sum(), rtol=1e-5)


def test_seq_root():
    x = np.array([4.0, -1.0, 9.0], np.float32)
    np.testing.assert_allclose(matrix.seq_root(x, set_neg_zero=True), [2.0, 0.0, 3.0])


def test_zero_small_values():
    x = np.array([1e-20, 0.5, -1e-18], np.float32)
    got = np.asarray(matrix.zero_small_values(x))
    np.testing.assert_array_equal(got, [0.0, 0.5, 0.0])


def test_sort_cols_per_row(m):
    vals, idx = matrix.sort_cols_per_row(m, ascending=True)
    np.testing.assert_allclose(np.asarray(vals), np.sort(m, axis=1), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(idx), np.argsort(m, axis=1, kind="stable"))
    vals_d, _ = matrix.sort_cols_per_row(m, ascending=False)
    np.testing.assert_allclose(np.asarray(vals_d), -np.sort(-m, axis=1), rtol=1e-6)
