"""Sparse suite tests — scipy-free numpy oracles (reference
cpp/test/sparse/{sort,filter,convert_coo,convert_csr,norm,symmetrize,
add,dist_coo_spmv,knn,knn_graph}.cu patterns)."""

import numpy as np

import jax.numpy as jnp

from raft_tpu.sparse import (
    COO,
    coo_from_dense,
    csr_from_coo,
    coo_from_csr,
    op,
    linalg as slinalg,
    sparse_pairwise_distance,
    sparse_brute_force_knn,
    knn_graph,
)


def random_sparse(rng, m, n, density=0.2, cap_extra=5):
    dense = rng.random((m, n)).astype(np.float32)
    dense[dense > density] = 0.0
    return dense, coo_from_dense(dense, capacity=int((dense != 0).sum()) + cap_extra)


def test_coo_roundtrip(rng_np):
    dense, coo = random_sparse(rng_np, 10, 8)
    np.testing.assert_allclose(np.asarray(coo.to_dense()), dense)
    csr = csr_from_coo(coo)
    np.testing.assert_allclose(np.asarray(csr.to_dense()), dense)
    back = coo_from_csr(csr)
    np.testing.assert_allclose(np.asarray(back.to_dense()), dense)


def test_coo_sort(rng_np):
    dense, coo = random_sparse(rng_np, 12, 9)
    # shuffle the VALID entries then sort (padding must stay at the tail —
    # the container invariant)
    nnz0 = int(coo.nnz)
    perm = np.concatenate(
        [rng_np.permutation(nnz0), np.arange(nnz0, coo.capacity)]
    )
    shuffled = COO(coo.rows[perm], coo.cols[perm], coo.vals[perm], coo.nnz, coo.shape)
    s = op.coo_sort(shuffled)
    nnz = int(s.nnz)
    r = np.asarray(s.rows)[:nnz]
    c = np.asarray(s.cols)[:nnz]
    keys = r.astype(np.int64) * s.shape[1] + c
    assert (np.diff(keys) >= 0).all()
    np.testing.assert_allclose(np.asarray(s.to_dense()), dense)


def test_coo_remove_scalar(rng_np):
    dense = np.array([[1, 0, 2], [2, 2, 0], [0, 3, 1]], np.float32)
    coo = coo_from_dense(dense, capacity=8)
    out = op.coo_remove_scalar(coo, 2.0)
    want = dense.copy()
    want[want == 2] = 0
    np.testing.assert_allclose(np.asarray(out.to_dense()), want)
    assert int(out.nnz) == (want != 0).sum()


def test_max_duplicates():
    rows = jnp.array([0, 0, 1, 1, 1, 0], jnp.int32)
    cols = jnp.array([1, 1, 2, 2, 3, 0], jnp.int32)
    vals = jnp.array([3.0, 5.0, 1.0, 7.0, 2.0, 4.0], jnp.float32)
    coo = COO(rows, cols, vals, jnp.int32(6), (2, 4))
    out = op.max_duplicates(coo)
    dense = np.asarray(out.to_dense())
    want = np.zeros((2, 4), np.float32)
    want[0, 1] = 5.0
    want[1, 2] = 7.0
    want[1, 3] = 2.0
    want[0, 0] = 4.0
    np.testing.assert_allclose(dense, want)
    assert int(out.nnz) == 4


def test_csr_row_slice(rng_np):
    dense, coo = random_sparse(rng_np, 10, 6)
    csr = csr_from_coo(coo)
    sl = op.csr_row_slice(csr, 3, 8)
    np.testing.assert_allclose(np.asarray(sl.to_dense()), dense[3:8])


def test_csr_row_op(rng_np):
    dense, coo = random_sparse(rng_np, 6, 5)
    csr = csr_from_coo(coo)
    out = op.csr_row_op(csr, lambda rows, data: data * (rows + 1))
    want = dense * (np.arange(6)[:, None] + 1)
    np.testing.assert_allclose(np.asarray(out.to_dense()), want, rtol=1e-6)


def test_degree_norms(rng_np):
    dense, coo = random_sparse(rng_np, 8, 7)
    np.testing.assert_array_equal(
        np.asarray(slinalg.coo_degree(coo)), (dense != 0).sum(1)
    )
    csr = csr_from_coo(coo)
    np.testing.assert_allclose(
        np.asarray(slinalg.rows_norm(csr, "l1")), np.abs(dense).sum(1), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(slinalg.rows_norm(csr, "l2")),
        np.sqrt((dense**2).sum(1)),
        rtol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(slinalg.rows_norm(csr, "linf")), np.abs(dense).max(1), rtol=1e-5
    )


def test_row_normalize(rng_np):
    dense, coo = random_sparse(rng_np, 8, 7)
    csr = csr_from_coo(coo)
    out = np.asarray(slinalg.csr_row_normalize_l1(csr).to_dense())
    sums = np.abs(dense).sum(1, keepdims=True)
    want = np.where(sums > 0, dense / np.where(sums == 0, 1, sums), 0)
    np.testing.assert_allclose(out, want, rtol=1e-5)


def test_transpose(rng_np):
    dense, coo = random_sparse(rng_np, 9, 5)
    t = slinalg.transpose(coo)
    np.testing.assert_allclose(np.asarray(t.to_dense()), dense.T)


def test_symmetrize(rng_np):
    dense, coo = random_sparse(rng_np, 7, 7)
    s = slinalg.coo_symmetrize(coo, combine="sum")
    np.testing.assert_allclose(
        np.asarray(s.to_dense()), dense + dense.T, rtol=1e-5
    )
    smax = slinalg.coo_symmetrize(coo, combine="max")
    np.testing.assert_allclose(
        np.asarray(smax.to_dense()), np.maximum(dense, dense.T), rtol=1e-5
    )


def test_csr_add(rng_np):
    da, ca = random_sparse(rng_np, 6, 6)
    db, cb = random_sparse(rng_np, 6, 6)
    out = slinalg.csr_add(csr_from_coo(ca), csr_from_coo(cb))
    np.testing.assert_allclose(np.asarray(out.to_dense()), da + db, rtol=1e-5)


def test_spmv_spmm(rng_np):
    dense, coo = random_sparse(rng_np, 10, 8)
    csr = csr_from_coo(coo)
    x = rng_np.standard_normal(8).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(slinalg.spmv(csr, x)), dense @ x, rtol=1e-4, atol=1e-5
    )
    X = rng_np.standard_normal((8, 3)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(slinalg.spmm(csr, X)), dense @ X, rtol=1e-4, atol=1e-5
    )


def test_sparse_pairwise_distance(rng_np):
    da, ca = random_sparse(rng_np, 15, 12, density=0.4)
    db, cb = random_sparse(rng_np, 11, 12, density=0.4)
    got = np.asarray(
        sparse_pairwise_distance(
            csr_from_coo(ca), csr_from_coo(cb), "sqeuclidean", block_m=4
        )
    )
    want = ((da[:, None, :] - db[None, :, :]) ** 2).sum(-1)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_sparse_knn_matches_dense(rng_np):
    da, ca = random_sparse(rng_np, 40, 10, density=0.5)
    db, cb = random_sparse(rng_np, 25, 10, density=0.5)
    d, i = sparse_brute_force_knn(
        csr_from_coo(ca), csr_from_coo(cb), 5,
        metric="sqeuclidean", block_q=8, block_n=16,
    )
    full = ((db[:, None, :] - da[None, :, :]) ** 2).sum(-1)
    want_i = np.argsort(full, 1)[:, :5]
    want_d = np.take_along_axis(full, want_i, 1)
    np.testing.assert_allclose(np.asarray(d), want_d, rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(i), want_i)


def test_knn_graph(rng_np):
    x = rng_np.standard_normal((30, 4)).astype(np.float32)
    g = knn_graph(x, 3)
    dense = np.asarray(g.to_dense())
    # symmetric, zero diagonal, each row has >= 3 edges
    np.testing.assert_allclose(dense, dense.T, rtol=1e-5)
    assert (np.diag(dense) == 0).all()
    assert ((dense > 0).sum(1) >= 3).all()


def test_fit_embedding_separates_components(rng_np):
    # two disconnected cliques: the Fiedler-style embedding separates them
    n = 12
    dense = np.zeros((n, n), np.float32)
    for grp in (range(6), range(6, 12)):
        for i in grp:
            for j in grp:
                if i != j:
                    dense[i, j] = 1.0
    csr = csr_from_coo(coo_from_dense(dense))
    emb = np.asarray(slinalg.fit_embedding(csr, 2, seed=0))
    assert emb.shape == (12, 2)


# ---------------------------------------------------------------------------
# colblock strategy (high-d, non-densifying — VERDICT r1 item 6; reference
# hash strategy, sparse/distance/detail/coo_spmv_strategies/hash_strategy.cuh)
# ---------------------------------------------------------------------------

from raft_tpu.sparse import csr_from_scipy  # noqa: E402


def _scipy_rand(rng, m, d, nnz_per_row):
    import scipy.sparse as ss

    density = nnz_per_row / d
    return ss.random(
        m, d, density=density, format="csr", dtype=np.float32,
        random_state=rng, data_rvs=lambda k: rng.random(k).astype(np.float32),
    )


def test_sparse_colblock_matches_dense_all_metrics(rng_np):
    """Strategy equivalence on every metric family the dense path serves."""
    da, ca = random_sparse(rng_np, 17, 40, density=0.3)
    db, cb = random_sparse(rng_np, 13, 40, density=0.3)
    A, B = csr_from_coo(ca), csr_from_coo(cb)
    for metric in (
        "sqeuclidean", "euclidean", "cosine", "correlation", "inner_product",
        "hellinger", "l1", "chebyshev", "canberra", "braycurtis", "hamming",
    ):
        dense = np.asarray(
            sparse_pairwise_distance(A, B, metric, strategy="dense")
        )
        colb = np.asarray(
            sparse_pairwise_distance(
                A, B, metric, strategy="colblock", col_block=16, block_n=8
            )
        )
        np.testing.assert_allclose(colb, dense, rtol=1e-4, atol=1e-4,
                                   err_msg=metric)


def test_sparse_highdim_knn_vs_scipy(rng_np):
    """d = 120k kNN through the non-densifying path, scipy.sparse oracle
    (20-newsgroups-like shape scaled for the CPU test harness; the full
    n~20k shape runs in bench/bench_sparse.py on TPU)."""
    d = 120_000
    idx_sp = _scipy_rand(rng_np, 400, d, 30)
    qry_sp = _scipy_rand(rng_np, 120, d, 30)
    index, queries = csr_from_scipy(idx_sp), csr_from_scipy(qry_sp)

    k = 7
    dist, ids = sparse_brute_force_knn(
        index, queries, k, metric="sqeuclidean",
        strategy="colblock", col_block=8192, block_n=256,
    )
    dist, ids = np.asarray(dist), np.asarray(ids)

    # scipy oracle: ||q||^2 + ||x||^2 - 2 q.x^T (exact on sparse data)
    g = (qry_sp @ idx_sp.T).toarray()
    qn = np.asarray(qry_sp.multiply(qry_sp).sum(1)).ravel()
    xn = np.asarray(idx_sp.multiply(idx_sp).sum(1)).ravel()
    full = np.maximum(qn[:, None] + xn[None, :] - 2.0 * g, 0.0)
    want_i = np.argsort(full, 1, kind="stable")[:, :k]
    want_d = np.take_along_axis(full, want_i, 1)

    np.testing.assert_allclose(dist, want_d, rtol=1e-4, atol=1e-4)
    # indices may differ on ties; distances of chosen ids must match
    got_d = np.take_along_axis(full, ids, 1)
    np.testing.assert_allclose(got_d, want_d, rtol=1e-4, atol=1e-4)


def test_sparse_highdim_pairwise_cosine_vs_scipy(rng_np):
    d = 60_000
    a_sp = _scipy_rand(rng_np, 150, d, 25)
    b_sp = _scipy_rand(rng_np, 90, d, 25)
    got = np.asarray(
        sparse_pairwise_distance(
            csr_from_scipy(a_sp), csr_from_scipy(b_sp), "cosine",
            strategy="colblock", col_block=8192, block_n=64,
        )
    )
    g = (a_sp @ b_sp.T).toarray()
    an = np.sqrt(np.asarray(a_sp.multiply(a_sp).sum(1))).ravel()
    bn = np.sqrt(np.asarray(b_sp.multiply(b_sp).sum(1))).ravel()
    denom = an[:, None] * bn[None, :]
    want = 1.0 - g / np.where(denom == 0, 1.0, denom)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_sparse_auto_picks_colblock_highdim(rng_np):
    """auto must route a high-d problem through colblock (no (n, d) dense)
    and still agree with the dense answer computed at the same small n."""
    d = 500_000  # a dense index block would be 4 GB — auto must not densify
    idx_sp = _scipy_rand(rng_np, 2000, d, 10)
    qry_sp = _scipy_rand(rng_np, 20, d, 10)
    dist, ids = sparse_brute_force_knn(
        csr_from_scipy(idx_sp), csr_from_scipy(qry_sp), 3,
        metric="sqeuclidean", col_block=65_536,
    )
    g = (qry_sp @ idx_sp.T).toarray()
    qn = np.asarray(qry_sp.multiply(qry_sp).sum(1)).ravel()
    xn = np.asarray(idx_sp.multiply(idx_sp).sum(1)).ravel()
    full = np.maximum(qn[:, None] + xn[None, :] - 2.0 * g, 0.0)
    want_i = np.argsort(full, 1, kind="stable")[:, :3]
    np.testing.assert_allclose(
        np.asarray(dist), np.take_along_axis(full, want_i, 1),
        rtol=1e-4, atol=1e-4,
    )


def test_sparse_prebuilt_colblock_index(rng_np):
    """Prebuilt index path == CSR colblock path == scipy oracle, across
    expanded + unexpanded metrics."""
    from raft_tpu.sparse import sparse_colblock_index_build

    d = 50_000
    idx_sp = _scipy_rand(rng_np, 300, d, 40)
    qry_sp = _scipy_rand(rng_np, 80, d, 40)
    queries = csr_from_scipy(qry_sp)
    layout = sparse_colblock_index_build(idx_sp, col_block=8192)

    for metric in ("sqeuclidean", "cosine", "l1"):
        dl, il = sparse_brute_force_knn(layout, queries, 5, metric=metric)
        dc, ic = sparse_brute_force_knn(
            csr_from_scipy(idx_sp), queries, 5, metric=metric,
            strategy="colblock", col_block=8192,
        )
        np.testing.assert_allclose(
            np.asarray(dl), np.asarray(dc), rtol=1e-4, atol=1e-4,
            err_msg=metric,
        )
    # scipy oracle on sqeuclidean
    g = (qry_sp @ idx_sp.T).toarray()
    qn = np.asarray(qry_sp.multiply(qry_sp).sum(1)).ravel()
    xn = np.asarray(idx_sp.multiply(idx_sp).sum(1)).ravel()
    full = np.maximum(qn[:, None] + xn[None, :] - 2.0 * g, 0.0)
    want_i = np.argsort(full, 1, kind="stable")[:, :5]
    dl, il = sparse_brute_force_knn(layout, queries, 5, metric="sqeuclidean")
    np.testing.assert_allclose(
        np.asarray(dl), np.take_along_axis(full, want_i, 1),
        rtol=1e-4, atol=1e-4,
    )
    # pairwise facade accepts the layout too
    pd = sparse_pairwise_distance(queries, layout, "sqeuclidean")
    np.testing.assert_allclose(np.asarray(pd), full, rtol=1e-4, atol=1e-3)


def test_sparse_prebuilt_rowblocked_streaming(rng_np):
    """row_block < n forces the index-row streaming path (the
    O(rows x col_block) memory bound for build-once/search-many); results
    must match the single-block layout exactly, per metric."""
    from raft_tpu.sparse import sparse_colblock_index_build

    d = 20_000
    idx_sp = _scipy_rand(rng_np, 300, d, 30)
    qry_sp = _scipy_rand(rng_np, 60, d, 30)
    queries = csr_from_scipy(qry_sp)
    one = sparse_colblock_index_build(idx_sp, col_block=4096)
    blk = sparse_colblock_index_build(idx_sp, col_block=4096, row_block=64)
    assert blk.rb_off.shape[1] - 1 == 5  # 5 streamed row blocks

    for metric in ("sqeuclidean", "cosine", "l1", "hellinger"):
        d1, i1 = sparse_brute_force_knn(one, queries, 7, metric=metric)
        d2, i2 = sparse_brute_force_knn(blk, queries, 7, metric=metric)
        np.testing.assert_allclose(
            np.asarray(d1), np.asarray(d2), rtol=1e-5, atol=1e-5,
            err_msg=metric,
        )
        np.testing.assert_array_equal(
            np.asarray(i1), np.asarray(i2), err_msg=metric
        )
        p1 = sparse_pairwise_distance(queries, one, metric)
        p2 = sparse_pairwise_distance(queries, blk, metric)
        np.testing.assert_allclose(
            np.asarray(p1), np.asarray(p2), rtol=1e-5, atol=1e-5,
            err_msg=metric,
        )


def test_sparse_colblock_index_build_from_csr(rng_np):
    from raft_tpu.sparse import sparse_colblock_index_build

    dense, coo = random_sparse(rng_np, 20, 30, density=0.3)
    layout = sparse_colblock_index_build(csr_from_coo(coo), col_block=8)
    qd, qcoo = random_sparse(rng_np, 10, 30, density=0.3)
    got = np.asarray(
        sparse_pairwise_distance(csr_from_coo(qcoo), layout, "sqeuclidean")
    )
    want = ((qd[:, None, :] - dense[None, :, :]) ** 2).sum(-1)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
