"""Bad-input validation tests — the analog of the reference's
``RAFT_EXPECTS`` contracts (cpp/include/raft/error.hpp:151-158) exercised
at the top public entry points.

Every raise is a RaftLogicError, which subclasses ValueError, so these
assert ValueError throughout (the weaker, stable contract).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import errors
from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit
from raft_tpu.distance.fused_l2_nn import fused_l2_nn
from raft_tpu.distance.pairwise import pairwise_distance
from raft_tpu.lap.lap import solve_lap
from raft_tpu.linalg.decomp import eig_jacobi, svd_jacobi
from raft_tpu.random.make_blobs import make_blobs
from raft_tpu.sparse.hierarchy import single_linkage
from raft_tpu.spatial.ann.ivf_flat import IVFFlatParams, ivf_flat_build
from raft_tpu.spatial.ann.ivf_pq import IVFPQParams, ivf_pq_build
from raft_tpu.spatial.knn import brute_force_knn
from raft_tpu.spatial.selection import select_k


X = np.random.default_rng(0).standard_normal((20, 8)).astype(np.float32)


# -- the primitive layer ----------------------------------------------------


class TestExpects:
    def test_pass(self):
        errors.expects(True, "never")
        errors.expects(1 == 1, "never")

    def test_fail_message(self):
        with pytest.raises(ValueError, match="RAFT failure at .*k=3 too big"):
            errors.expects(False, "k=%d too big", 3)

    def test_fail_is_raft_exception(self):
        with pytest.raises(errors.RaftException):
            errors.fail("boom")

    def test_traced_condition_rejected(self):
        @jax.jit
        def f(x):
            errors.expects(jnp.all(x > 0), "positive")
            return x

        with pytest.raises(TypeError, match="traced value"):
            f(jnp.ones((3,)))

    def test_expect_finite_host(self):
        errors.expect_finite(np.ones(4), "ok")
        with pytest.raises(ValueError, match="non-finite"):
            errors.expect_finite(np.array([1.0, np.nan]), "bad")

    def test_expect_finite_traced_noop(self):
        @jax.jit
        def f(x):
            errors.expect_finite(x)  # silently skipped under trace
            return x * 2

        np.testing.assert_allclose(f(jnp.ones(2)), 2.0)


# -- public entry points ----------------------------------------------------


class TestPairwiseDistance:
    def test_feature_mismatch(self):
        with pytest.raises(ValueError, match="feature dims differ"):
            pairwise_distance(X, X[:, :4])

    def test_rank(self):
        with pytest.raises(ValueError, match="2D"):
            pairwise_distance(X[0], X)

    def test_complex_dtype(self):
        with pytest.raises(ValueError, match="dtype"):
            pairwise_distance(X.astype(np.complex64), X.astype(np.complex64))

    def test_bad_p(self):
        with pytest.raises(ValueError, match="p > 0"):
            pairwise_distance(X, X, "minkowski", p=0.0)


class TestBruteForceKnn:
    def test_k_too_big(self):
        with pytest.raises(ValueError, match="out of range"):
            brute_force_knn(X, X, k=21)

    def test_k_zero(self):
        with pytest.raises(ValueError, match="out of range"):
            brute_force_knn(X, X, k=0)

    def test_dim_mismatch(self):
        with pytest.raises(ValueError, match="feature dims differ"):
            brute_force_knn(X, X[:, :4], k=3)

    def test_empty_partition_list(self):
        with pytest.raises(ValueError, match="at least one partition"):
            brute_force_knn([], X, k=1)

    def test_translations_length(self):
        with pytest.raises(ValueError, match="translations"):
            brute_force_knn([X, X], X, k=3, translations=[0])


class TestSelectK:
    def test_k_too_big(self):
        with pytest.raises(ValueError, match="out of range"):
            select_k(X, k=9)

    def test_indices_shape(self):
        with pytest.raises(ValueError, match="indices"):
            select_k(X, k=2, indices=jnp.zeros((3, 3), jnp.int32))


class TestKmeans:
    def test_too_many_clusters(self):
        with pytest.raises(ValueError, match="out of range"):
            kmeans_fit(X, KMeansParams(n_clusters=50))

    def test_bad_max_iter(self):
        with pytest.raises(ValueError, match="max_iter"):
            kmeans_fit(X, KMeansParams(n_clusters=2, max_iter=0))

    def test_centroid_shape(self):
        with pytest.raises(ValueError, match="centroids"):
            kmeans_fit(
                X, KMeansParams(n_clusters=3), centroids=np.zeros((2, 8))
            )


class TestFusedL2NN:
    def test_dim_mismatch(self):
        with pytest.raises(ValueError, match="feature dims differ"):
            fused_l2_nn(X, X[:, :4])


class TestANN:
    def test_ivf_flat_too_many_lists(self):
        with pytest.raises(ValueError, match="out of range"):
            ivf_flat_build(X, IVFFlatParams(n_lists=100))

    def test_ivf_pq_indivisible(self):
        with pytest.raises(ValueError, match="not divisible"):
            ivf_pq_build(X, IVFPQParams(n_lists=2, pq_dim=3))

    def test_ivf_pq_bits(self):
        with pytest.raises(ValueError, match="pq_bits"):
            ivf_pq_build(X, IVFPQParams(n_lists=2, pq_dim=4, pq_bits=12))


class TestLap:
    def test_non_square(self):
        with pytest.raises(ValueError, match="square"):
            solve_lap(np.zeros((3, 4), np.float32))


class TestLinkage:
    def test_too_many_clusters(self):
        with pytest.raises(ValueError, match="out of range"):
            single_linkage(X, n_clusters=25)


class TestMakeBlobs:
    def test_zero_samples(self):
        with pytest.raises(ValueError, match="n_samples"):
            make_blobs(0, 4)


class TestDecompParity:
    def test_eig_jacobi_bad_tol(self):
        with pytest.raises(ValueError, match="tol"):
            eig_jacobi(np.eye(4, dtype=np.float32), tol=0.0)

    def test_svd_jacobi_bad_sweeps(self):
        with pytest.raises(ValueError, match="sweeps"):
            svd_jacobi(X, sweeps=0)


# -- structured operational errors (ISSUE 3) --------------------------------


class TestOperationalErrors:
    """RaftTimeoutError / CorruptIndexError: same raise-site framing as
    every RaftException, but deliberately NOT ValueErrors — existing
    `except ValueError` handlers (the bad-argument contract above) must
    be unaffected by operational failures."""

    def test_exported(self):
        assert "RaftTimeoutError" in errors.__all__
        assert "CorruptIndexError" in errors.__all__
        assert "RaftOverloadError" in errors.__all__

    def test_overload_hierarchy_and_retry_after(self):
        e = errors.RaftOverloadError("queue full", retry_after_s=0.25)
        assert isinstance(e, errors.RaftException)
        assert not isinstance(e, ValueError)   # PR 3 pattern: loud, typed
        assert not isinstance(e, TimeoutError)  # overload != deadline
        assert e.retry_after_s == 0.25
        assert "RAFT failure at" in str(e) and "queue full" in str(e)
        assert errors.RaftOverloadError("no estimate").retry_after_s is None

    def test_timeout_hierarchy(self):
        e = errors.RaftTimeoutError("deadline blown")
        assert isinstance(e, errors.RaftException)
        assert isinstance(e, TimeoutError)  # generic deadline plumbing
        assert not isinstance(e, ValueError)
        assert "RAFT failure at" in str(e) and "deadline blown" in str(e)

    def test_corrupt_index_hierarchy_and_field(self):
        e = errors.CorruptIndexError("bad crc", field="sorted_ids")
        assert isinstance(e, errors.RaftException)
        assert not isinstance(e, ValueError)
        assert e.field == "sorted_ids"
        assert "RAFT failure at" in str(e)
        assert errors.CorruptIndexError("no field").field is None

    def test_value_error_handlers_unaffected(self):
        """A handler written for the validation contract must not absorb
        operational errors — and must still catch RaftLogicError."""
        def classify(exc):
            try:
                raise exc
            except ValueError:
                return "bad-argument"
            except errors.RaftException:
                return "operational"

        assert classify(errors.RaftLogicError("k too big")) == "bad-argument"
        assert classify(errors.RaftTimeoutError("slow")) == "operational"
        assert classify(errors.CorruptIndexError("crc")) == "operational"
        assert classify(errors.RaftOverloadError("full")) == "operational"
