"""Open-loop serving surface (ISSUE 8): shape-bucketed micro-batching,
the async pipelined executor (coalescing, padding, deadline flush,
completion demux, shedding), and the deterministic Poisson load
generator — all on CPU with a tiny index, asserting BEHAVIOR (batching
and demux correctness, zero recompiles, shed accounting), never QPS.
The chaos path (mid-stream rank failure + hedge + failover through one
executor) lives in tests/test_resilience.py next to its fixtures."""

import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import errors
from raft_tpu.resilience import AdmissionController, HedgePolicy
from raft_tpu.serving import (
    BucketSet,
    ServingExecutor,
    pack_requests,
)
from raft_tpu.serving.batching import PendingRequest
from raft_tpu.spatial.ann import IVFFlatParams, ivf_flat_build
from raft_tpu.spatial.ann.ivf_flat import (
    _grouped_impl,
    ivf_flat_search_grouped,
)
from raft_tpu.testing import faults, load

D = 8
K = 4
N_PROBES = 4
BUCKETS = (4, 8)


# ----------------------------------------------------------- bucket set
class TestBucketSet:
    def test_select_smallest_fitting(self):
        b = BucketSet.of([8, 4, 16])
        assert b.sizes == (4, 8, 16)
        assert b.select(1) == 4
        assert b.select(4) == 4
        assert b.select(5) == 8
        assert b.select(16) == 16
        # beyond the largest: the largest (caller packs what fits)
        assert b.select(100) == 16

    def test_validation(self):
        with pytest.raises(ValueError):
            BucketSet(())
        with pytest.raises(ValueError):
            BucketSet((4, 4))
        with pytest.raises(ValueError):
            BucketSet((8, 4))
        with pytest.raises(ValueError):
            BucketSet((0,))
        with pytest.raises(ValueError):
            BucketSet.of([])
        with pytest.raises(ValueError):
            BucketSet((True,))

    def test_pack_whole_requests_only(self):
        """A request never splits across batches: 3+3 rows into bucket 4
        packs ONE request (padded), the second stays pending — the
        bucket-straddling arrival becomes two warmed-shape batches."""
        buckets = BucketSet.of(BUCKETS)

        def req(m):
            return PendingRequest(
                queries=np.ones((m, D), np.float32),
                future=None, t_arrival=0.0,
            )

        pending = [req(3), req(3), req(3)]
        batch, rest = pack_requests(pending, buckets, D)
        # 9 total rows -> bucket 8 -> two whole requests fit (6 rows)
        assert batch.bucket == 8 and batch.n_valid == 6
        assert batch.n_requests == 2 and len(rest) == 1
        batch2, rest2 = pack_requests(rest, buckets, D)
        assert batch2.bucket == 4 and batch2.n_valid == 3
        assert batch2.n_padded == 1 and not rest2
        # padded rows are zeros
        np.testing.assert_array_equal(batch2.queries[3], 0.0)


# ------------------------------------------------------- load generator
class TestPoissonLoad:
    def test_deterministic_and_rate(self):
        a = load.poisson_arrivals(100.0, 500, seed=7)
        b = load.poisson_arrivals(100.0, 500, seed=7)
        np.testing.assert_array_equal(a.times_s, b.times_s)
        c = load.poisson_arrivals(100.0, 500, seed=8)
        assert not np.array_equal(a.times_s, c.times_s)
        # mean gap ~ 1/rate (law of large numbers, generous band)
        gaps = np.diff(a.times_s)
        assert 0.5 / 100.0 < gaps.mean() < 2.0 / 100.0
        assert a.n_requests == 500 and a.n_rows == 500

    def test_size_mix_deterministic(self):
        s = load.poisson_arrivals(10.0, 200, seed=3, sizes=(1, 8),
                                  size_weights=(0.75, 0.25))
        assert set(np.unique(s.sizes)) <= {1, 8}
        assert s.n_rows == int(s.sizes.sum())
        s2 = load.poisson_arrivals(10.0, 200, seed=3, sizes=(1, 8),
                                   size_weights=(0.75, 0.25))
        np.testing.assert_array_equal(s.sizes, s2.sizes)

    def test_zipf_template_mix_deterministic_and_skewed(self):
        """ISSUE 15 satellite: the Zipf repeated-query mix — seeded
        template ids over a pool, head-heavy at s=1.1, and adding the
        mix never perturbs the schedule's times or sizes."""
        s = load.poisson_arrivals(10.0, 400, seed=3, zipf_s=1.1,
                                  n_templates=16)
        s2 = load.poisson_arrivals(10.0, 400, seed=3, zipf_s=1.1,
                                   n_templates=16)
        np.testing.assert_array_equal(s.template_ids, s2.template_ids)
        assert s.template_ids.min() >= 0
        assert s.template_ids.max() < 16
        # the same seed without a mix gives the identical arrivals
        base = load.poisson_arrivals(10.0, 400, seed=3)
        np.testing.assert_array_equal(s.times_s, base.times_s)
        np.testing.assert_array_equal(s.sizes, base.sizes)
        assert base.template_ids is None
        # Zipf(1.1) over 16 templates: rank-0 carries the head (~29%
        # expected; generous band for the 400-draw sample)
        share0 = float((s.template_ids == 0).mean())
        assert share0 > 2.0 / 16
        # weights are the normalized power law, monotone decreasing
        w = load.zipf_template_weights(16, 1.1)
        assert w.shape == (16,) and w.sum() == pytest.approx(1.0)
        assert (np.diff(w) < 0).all()

    def test_zipf_mix_validation(self):
        with pytest.raises(ValueError):
            load.poisson_arrivals(1.0, 4, seed=0, zipf_s=1.1)
        with pytest.raises(ValueError):
            load.zipf_template_weights(0, 1.1)
        with pytest.raises(ValueError):
            load.ArrivalSchedule(
                times_s=np.zeros(2), sizes=np.ones(2, np.int64),
                template_ids=np.zeros(3, np.int64),
            )
        with pytest.raises(ValueError):
            load.ArrivalSchedule(
                times_s=np.zeros(2), sizes=np.ones(2, np.int64),
                template_ids=np.array([0, -1]),
            )

    def test_replay_open_loop_never_waits_on_results(self):
        """Replay with a virtual clock: each submit fires at its
        scheduled instant; a slow submit makes the NEXT one fire
        immediately (lag recorded), never re-shapes the offered load."""
        sched = load.ArrivalSchedule(
            times_s=np.array([0.0, 0.01, 0.02, 0.03]),
            sizes=np.ones(4, np.int64),
        )
        t = [0.0]
        calls = []

        def clock():
            return t[0]

        def sleep(s):
            t[0] += s

        def submit(i, size):
            calls.append((i, t[0]))
            if i == 1:
                t[0] += 0.05          # submit path stalls past schedule
            return i

        results, stamps, max_lag = load.replay(
            sched, submit, clock=clock, sleep=sleep
        )
        assert [c[0] for c in calls] == [0, 1, 2, 3]
        assert calls[1][1] == pytest.approx(0.01)
        assert calls[2][1] == pytest.approx(0.06)   # fired immediately
        assert max_lag == pytest.approx(0.04)
        assert results == [0, 1, 2, 3]

    def test_replay_records_sheds_as_data(self):
        sched = load.ArrivalSchedule(
            times_s=np.zeros(3), sizes=np.ones(3, np.int64),
        )

        def submit(i, size):
            if i == 1:
                raise errors.RaftOverloadError("full", retry_after_s=0.1)
            return i

        results, _, _ = load.replay(
            sched, submit, clock=lambda: 0.0, sleep=lambda s: None
        )
        assert results[0] == 0 and results[2] == 2
        assert isinstance(results[1], errors.RaftOverloadError)

    def test_validation(self):
        with pytest.raises(ValueError):
            load.poisson_arrivals(0.0, 10, seed=0)
        with pytest.raises(ValueError):
            load.poisson_arrivals(1.0, 0, seed=0)
        with pytest.raises(ValueError):
            load.ArrivalSchedule(
                times_s=np.array([1.0, 0.5]),
                sizes=np.ones(2, np.int64),
            )


# --------------------------------------------------------- the executor
@pytest.fixture(scope="module")
def tiny_serving():
    """A tiny warmed IVF-Flat serving setup: per-bucket closures at ONE
    shared qcap (so per-row results are batch-composition-independent)
    plus the healthy full-batch reference."""
    rng = np.random.default_rng(17)
    x = rng.standard_normal((2048, D)).astype(np.float32)
    idx = ivf_flat_build(x, IVFFlatParams(n_lists=8, kmeans_n_iters=3,
                                          seed=2))
    qcap = 32                     # >= nq of every shape: no probe drops,
    # so per-row results are batch-composition-independent
    for b in BUCKETS:
        idx.warmup(b, k=K, n_probes=N_PROBES, qcap=qcap)

    def dispatch(batch, **_rt):
        return ivf_flat_search_grouped(
            idx, batch, K, n_probes=N_PROBES, qcap=qcap,
        )

    q = rng.standard_normal((32, D)).astype(np.float32)
    vref, iref = (np.asarray(a) for a in dispatch(jnp.asarray(
        np.concatenate([q, np.zeros((0, D), np.float32)])[:32]
    )))
    # per-row reference at the same qcap, computed bucket-shaped so it
    # matches whatever batch composition the executor chooses
    refs = {}
    for start in range(0, 32):
        refs[start] = (vref[start], iref[start])
    return idx, dispatch, q, refs


def _check_request(req_rows, result, q, refs):
    v, i = result
    assert v.shape == (len(req_rows), K)
    for out_row, src in enumerate(req_rows):
        np.testing.assert_array_equal(i[out_row], refs[src][1])
        np.testing.assert_allclose(v[out_row], refs[src][0], rtol=1e-6)


class TestExecutorDemux:
    def test_mixed_sizes_demux_and_zero_recompiles(self, tiny_serving):
        """Requests of mixed sizes coalesce into warmed buckets; every
        caller gets exactly its own rows back; steady state compiles
        NOTHING new (the cache-size audit — the zero-retrace
        discipline)."""
        idx, dispatch, q, refs = tiny_serving
        warmed = _grouped_impl._cache_size()
        ex = ServingExecutor(dispatch, BUCKETS, dim=D,
                             flush_age_s=0.002, max_in_flight=3)
        reqs = []       # (row indices, future)
        cursor = 0
        for m in (1, 3, 2, 4, 1, 1, 8, 2, 3, 1, 4, 2):
            rows = list(range(cursor, cursor + m))
            cursor += m
            if cursor > 32:
                break
            reqs.append((rows, ex.submit(q[rows[0]:rows[-1] + 1])))
        for rows, fut in reqs:
            _check_request(rows, fut.result(timeout=30), q, refs)
        st = ex.stats()
        ex.close()
        assert st.completed == len(reqs) and st.failed == 0
        assert st.batches >= 2
        assert _grouped_impl._cache_size() == warmed, \
            "open-loop serving must dispatch only warmed bucket shapes"

    def test_smaller_than_smallest_bucket_pads(self, tiny_serving):
        """A lone 2-row request: padded to the smallest bucket, pad rows
        dispatched but never surfaced, no new compile."""
        idx, dispatch, q, refs = tiny_serving
        warmed = _grouped_impl._cache_size()
        ex = ServingExecutor(dispatch, BUCKETS, dim=D,
                             flush_age_s=0.0)        # flush immediately
        fut = ex.submit(q[5:7])
        _check_request([5, 6], fut.result(timeout=30), q, refs)
        st = ex.stats()
        ex.close()
        assert st.batches == 1 and st.padded_rows == BUCKETS[0] - 2
        assert st.flushes_deadline == 1 and st.flushes_full == 0
        assert _grouped_impl._cache_size() == warmed

    def test_straddling_requests_two_warmed_batches(self, tiny_serving):
        """Arrivals straddling the largest bucket (3+3+3 rows vs bucket
        8) become TWO warmed-shape dispatches — whole requests only,
        zero recompiles."""
        idx, dispatch, q, refs = tiny_serving
        warmed = _grouped_impl._cache_size()
        gate = threading.Event()

        def gated(batch, **rt):
            gate.wait(10.0)
            return dispatch(batch)

        ex = ServingExecutor(gated, BUCKETS, dim=D, flush_age_s=0.0)
        futs = [ex.submit(q[s:s + 3]) for s in (0, 3, 6)]
        gate.set()
        for s, fut in zip((0, 3, 6), futs):
            _check_request([s, s + 1, s + 2], fut.result(timeout=30),
                           q, refs)
        st = ex.stats()
        ex.close()
        assert st.batches == 2                       # 8-batch + 4-batch
        assert st.valid_rows == 9 and st.padded_rows == 3
        assert _grouped_impl._cache_size() == warmed

    def test_deadline_flush_partial_batch(self, tiny_serving):
        """With a long coalescing window and sub-bucket arrivals, the
        flush-on-deadline path dispatches a padded partial batch after
        ``flush_age_s`` — latency stays bounded at light load."""
        idx, dispatch, q, refs = tiny_serving
        warmed = _grouped_impl._cache_size()
        ex = ServingExecutor(dispatch, BUCKETS, dim=D,
                             flush_age_s=0.05)
        t0 = time.monotonic()
        fut = ex.submit(q[9:10])
        result = fut.result(timeout=30)
        waited = time.monotonic() - t0
        _check_request([9], result, q, refs)
        st = ex.stats()
        ex.close()
        assert st.flushes_deadline == 1
        assert waited >= 0.04            # it DID coalesce-wait first
        assert _grouped_impl._cache_size() == warmed

    def test_oversized_request_rejected_loudly(self, tiny_serving):
        idx, dispatch, q, refs = tiny_serving
        ex = ServingExecutor(dispatch, BUCKETS, dim=D)
        with pytest.raises(ValueError, match="largest warmed bucket"):
            ex.submit(np.zeros((BUCKETS[-1] + 1, D), np.float32))
        with pytest.raises(ValueError, match="expected"):
            ex.submit(np.zeros((2, D + 1), np.float32))
        ex.close()
        with pytest.raises(ValueError, match="closed"):
            ex.submit(q[:1])

    def test_runtime_inputs_snapshot_per_dispatch(self, tiny_serving):
        """set_runtime values flow into every LATER dispatch as keyword
        operands (the failover/health path's transport)."""
        idx, dispatch, q, refs = tiny_serving
        seen = []

        def spying(batch, **rt):
            seen.append(dict(rt))
            return dispatch(batch)

        ex = ServingExecutor(spying, BUCKETS, dim=D, flush_age_s=0.0,
                             runtime_inputs={"tag": 1})
        ex.submit(q[:1]).result(timeout=30)
        ex.set_runtime(tag=2)
        ex.submit(q[:1]).result(timeout=30)
        ex.set_runtime(tag=None)                      # removal
        ex.submit(q[:1]).result(timeout=30)
        ex.close()
        assert seen == [{"tag": 1}, {"tag": 2}, {}]


class TestExecutorShedding:
    def test_queue_bound_sheds_not_collapses(self, tiny_serving):
        """With dispatch stalled, arrivals beyond the admission queue
        shed with RaftOverloadError (occupancy-priced retry_after);
        everything admitted completes once the stall clears."""
        idx, dispatch, q, refs = tiny_serving
        gate = threading.Event()

        def gated(batch, **rt):
            gate.wait(10.0)
            return dispatch(batch)

        ctrl = AdmissionController(max_concurrent=2, max_queue=4)
        ex = ServingExecutor(gated, BUCKETS, dim=D, flush_age_s=0.0,
                             max_in_flight=1, admission=ctrl)
        futs, sheds = [], 0
        for i in range(16):
            try:
                futs.append((i % 32, ex.submit(q[i % 32:i % 32 + 1])))
            except errors.RaftOverloadError as e:
                sheds += 1
                assert e.retry_after_s is None or e.retry_after_s >= 0
        gate.set()
        for src, fut in futs:
            _check_request([src], fut.result(timeout=30), q, refs)
        st = ctrl.stats()
        ex.close()
        assert sheds > 0 and st.shed_queue == sheds
        assert st.completed == len(futs)
        assert st.queue_depth == 0 and st.in_flight == 0

    def test_caller_cancelled_future_does_not_wedge_drain(self,
                                                          tiny_serving):
        """A caller cancelling its future (client-side timeout) must
        not kill the drain thread: the batch demuxes around the
        cancelled entry and later requests still complete."""
        idx, dispatch, q, refs = tiny_serving
        gate = threading.Event()

        def gated(batch, **rt):
            gate.wait(10.0)
            return dispatch(batch)

        ex = ServingExecutor(gated, BUCKETS, dim=D, flush_age_s=0.0)
        f1 = ex.submit(q[:1])
        f2 = ex.submit(q[1:2])
        assert f1.cancel()               # still pending: cancel wins
        gate.set()
        _check_request([1], f2.result(timeout=30), q, refs)
        f3 = ex.submit(q[2:3])           # the drain thread survived
        _check_request([2], f3.result(timeout=30), q, refs)
        st = ex.stats()
        ex.close()
        assert ex._drainer is not None and not ex._drainer.is_alive()
        assert st.completed == 2 and st.failed == 0

    def test_dispatch_failure_fails_only_its_batch(self, tiny_serving):
        idx, dispatch, q, refs = tiny_serving
        calls = []

        def flaky(batch, **rt):
            calls.append(batch.shape[0])
            if len(calls) == 1:
                raise RuntimeError("injected dispatch failure")
            return dispatch(batch)

        ex = ServingExecutor(flaky, BUCKETS, dim=D, flush_age_s=0.0)
        f1 = ex.submit(q[:2])
        with pytest.raises(RuntimeError, match="injected"):
            f1.result(timeout=30)
        f2 = ex.submit(q[3:4])
        _check_request([3], f2.result(timeout=30), q, refs)
        st = ex.stats()
        ex.close()
        assert st.failed == 1 and st.completed == 1


class TestExecutorHedge:
    def test_straggling_batch_hedged_to_backup(self, tiny_serving):
        """A batch whose primary polls not-ready past the hedge delay is
        re-dispatched from its HOST copy through the backup closure; the
        first ready answer is demuxed (identical results)."""
        idx, dispatch, q, refs = tiny_serving
        wrapped, audit = faults.inject_straggler(
            dispatch, every=2, seconds=30.0,
        )
        pol = HedgePolicy(default_delay_s=0.01, min_samples=10 ** 6)
        ex = ServingExecutor(
            wrapped, BUCKETS, dim=D, flush_age_s=0.0,
            hedge=pol, backup_dispatch=dispatch,
        )
        f1 = ex.submit(q[:2])                 # call 1: fast
        _check_request([0, 1], f1.result(timeout=30), q, refs)
        f2 = ex.submit(q[4:6])                # call 2: straggles 30 s
        _check_request([4, 5], f2.result(timeout=30), q, refs)
        st = ex.stats()
        ex.close()
        assert st.hedged_batches == 1 and st.backup_wins == 1
        assert pol.hedges == 1 and pol.backup_wins == 1

    def test_backup_requires_hedge_policy(self, tiny_serving):
        idx, dispatch, q, refs = tiny_serving
        with pytest.raises(ValueError, match="hedge="):
            ServingExecutor(dispatch, BUCKETS, dim=D,
                            backup_dispatch=dispatch)


# ----------------------------------------------- open-loop smoke (bench)
def test_open_loop_row_tiny_config():
    """The CI-safe open-loop smoke (ISSUE 8 satellite): the bench row's
    full pipeline — Poisson schedule, executor, saturation probe,
    offered-load sweep — on a tiny CPU config, asserting SHAPE and
    accounting, never QPS."""
    from bench.bench_serving import open_loop_row

    rng = np.random.default_rng(5)
    x = rng.standard_normal((2048, D)).astype(np.float32)
    idx = ivf_flat_build(x, IVFFlatParams(n_lists=8, kmeans_n_iters=3,
                                          seed=2))
    q = rng.standard_normal((32, D)).astype(np.float32)

    def make_run(bucket):
        qcap = idx.warmup(bucket, k=K, n_probes=N_PROBES)

        def run(qq, qcap=qcap):
            return ivf_flat_search_grouped(idx, qq, K,
                                           n_probes=N_PROBES, qcap=qcap)
        return run

    row = open_loop_row(make_run, q, buckets=BUCKETS, request_size=2,
                        n_requests=24, chain=(1, 3), escalate=0,
                        flush_age_s=0.001, fracs=(0.5, 0.95),
                        min_duration_s=0.0)   # tiny fixed count on CI
    assert row["scenario"] == "open_loop"
    assert "error" not in row, row
    assert row["buckets"] == list(BUCKETS)
    assert row["program_qps"] > 0 and row["saturation_qps"] > 0
    assert row["qps_ratio_vs_program"] > 0
    for tag in ("50", "95"):
        assert row[f"p50_ms_{tag}"] > 0
        assert row[f"p99_ms_{tag}"] >= row[f"p50_ms_{tag}"]
