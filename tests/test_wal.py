"""ISSUE 20 durability suite: the mutation WAL
(raft_tpu/durability/wal.py) + its MNMG, supervisor, and chaos legs.

Contracts under test (docs/robustness.md "Durability"):

* frame format: CRC32-framed records round-trip exactly; a flipped
  byte is caught; a FUTURE format version raises CorruptIndexError
  instead of being truncated away as damage;
* torn-tail fuzz: a raw tear at EVERY byte offset of a real log
  (faults.inject_partial_write at_byte) recovers exactly the frames
  wholly before the cut — never a partial frame, never past an acked
  one;
* replay is idempotent: monotone-LSN dedupe makes duplicated segments
  and duplicated record streams replay once;
* group commit: an ack NEVER resolves before its batch's fsync
  returned (injectable fsync/clock prove the ordering without a
  disk); a flusher IO failure latches and fails later appends loudly;
* rotation + retention: prune removes only segments fully behind the
  checkpoint watermark, never the active one; reopen REPAIRS any torn
  tail first and starts a FRESH segment at the repaired frontier+1
  (never appends past an unrepaired tear, never truncates a segment
  holding records);
* recovery = checkpoint + WAL tail replay is bit-identical to the
  live state, including under a live-ingest vs checkpoint race;
* MNMG: per-rank WALs, quorum acks (a rank with a dead WAL stops
  holding quorum), and mnmg_recover reconciling lagging per-rank
  frontiers from the union of the logs;
* the supervisor drives QUARANTINED -> RECOVERING -> RESYNCING ->
  WARMING -> SERVING unassisted, with a REAL WAL replay as the
  replay_wal heal action;
* kill -9 chaos: a real subprocess SIGKILLed mid-ingest at seeded
  points loses ZERO acked records and applies ZERO torn frames
  (fast leg in tier-1, the >=10-point gate in `ci/run.sh wal`);
* the whole WAL path compiles nothing (cache-size audit).
"""

import os
import shutil
import struct
import threading

import numpy as np
import pytest

import jax

from raft_tpu import errors
from raft_tpu.comms import (
    MnmgDurableIngest,
    build_comms,
    mnmg_ivf_flat_build,
    mnmg_mutable_search,
    mnmg_recover,
    place_index,
    wrap_mnmg_mutable,
)
from raft_tpu.comms.mnmg_mutation import _row_holders
from raft_tpu.durability import wal
from raft_tpu.obs import FlightRecorder
from raft_tpu.resilience import (
    STATE_QUARANTINED,
    STATE_RECOVERING,
    STATE_RESYNCING,
    STATE_SERVING,
    STATE_WARMING,
    HealActions,
    HealthMonitor,
    ReplicaPlacement,
    ServingSupervisor,
    ShardHealth,
)
from raft_tpu.spatial.ann import (
    IVFFlatParams,
    ivf_flat_build,
    mutable_search,
    wrap_mutable,
)
from raft_tpu.spatial.ann import mutation as mut_mod
from raft_tpu.testing import chaos, faults

K = 5
D = 16


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(11)
    x = rng.standard_normal((1200, D)).astype(np.float32)
    q = x[::113][:8] + 0.05 * rng.standard_normal((8, D)).astype(
        np.float32
    )
    return x, q


@pytest.fixture(scope="module")
def flat_index(dataset):
    x, _ = dataset
    return ivf_flat_build(
        x, IVFFlatParams(n_lists=12, kmeans_n_iters=4,
                         kmeans_init="random", seed=3),
        metric="sqeuclidean",
    )


def _search_ids(mw, q, **kw):
    kw.setdefault("n_probes", 6)
    kw.setdefault("qcap", q.shape[0])
    return np.asarray(mutable_search(mw, q, K, **kw)[1])


def _write_log(path, n=6, d=4, seed=7, **kw):
    """A small real log written through the writer; returns the
    (vectors, ids) streams so tests can check exact recovery."""
    rng = np.random.default_rng(seed)
    vecs = rng.standard_normal((n, 1, d)).astype(np.float32)
    ids = np.arange(100, 100 + n, dtype=np.int32)
    w = wal.WalWriter(path, flush_interval_s=0.0005, **kw)
    for k in range(n):
        ack = w.append(wal.OP_UPSERT,
                       wal.encode_upsert(vecs[k], ids[k:k + 1]),
                       epoch=k)
        assert ack.wait(10.0)
    w.close()
    return vecs, ids


# ------------------------------------------------------------ frame format
class TestFrame:
    def test_record_roundtrip_exact(self, tmp_path):
        d = str(tmp_path / "w")
        vecs, ids = _write_log(d, n=5, d=3)
        records, frontier = wal.read_records(d)
        assert frontier == 5 and len(records) == 5
        for k, r in enumerate(records):
            assert r.lsn == k + 1 and r.epoch == k
            assert r.op == wal.OP_UPSERT
            v, i = wal.decode_upsert(r.payload)
            assert np.array_equal(v, vecs[k])
            assert np.array_equal(i, ids[k:k + 1])

    def test_delete_codec_roundtrip(self):
        ids = np.array([3, -1, 2 ** 31 - 1], np.int32)
        assert np.array_equal(wal.decode_delete(wal.encode_delete(ids)),
                              ids)

    def test_flipped_byte_is_caught(self, tmp_path):
        d = str(tmp_path / "w")
        _write_log(d, n=4, d=3)
        seg = wal.segment_paths(d)[0]
        data = bytearray(open(seg, "rb").read())
        data[-3] ^= 0x40                      # inside the last payload
        open(seg, "wb").write(bytes(data))
        records, good_end, damage = wal.scan_segment(seg)
        assert damage == "crc-mismatch" and len(records) == 3

    def test_future_version_refuses_to_scan(self, tmp_path):
        d = tmp_path / "w"
        d.mkdir()
        seg = d / "wal-00000000000000000001.log"
        seg.write_bytes(b"RWAL" + struct.pack("<HH", 99, 0))
        with pytest.raises(errors.CorruptIndexError) as ei:
            wal.scan_segment(str(seg))
        assert "v99" in str(ei.value)
        # ... and repair must NOT treat it as damage to truncate
        with pytest.raises(errors.CorruptIndexError):
            wal.repair_wal(str(d))
        assert seg.exists()


# ------------------------------------------------------- torn-tail fuzz
class TestTornTail:
    def test_fuzz_every_byte_offset(self, tmp_path):
        """The satellite gate: recovery is exact at EVERY cut point."""
        src = str(tmp_path / "src")
        _write_log(src, n=6, d=4)
        seg = wal.segment_paths(src)[0]
        clean = open(seg, "rb").read()
        # frame end offsets in the clean segment
        recs, end, damage = wal.scan_segment(seg)
        assert damage is None and end == len(clean)
        ends = [8]                            # file header
        off = 8
        for r in recs:
            off += 25 + len(r.payload)        # _FRAME_OVERHEAD
            ends.append(off)
        for cut in range(len(clean) + 1):
            d = str(tmp_path / f"cut{cut}")
            os.makedirs(d)
            dst = os.path.join(d, os.path.basename(seg))
            shutil.copyfile(seg, dst)
            faults.inject_partial_write(dst, at_byte=cut)
            records, frontier = wal.repair_wal(d, name="fuzz")
            want = sum(1 for e in ends[1:] if e <= cut)
            assert len(records) == want, f"cut={cut}"
            assert frontier == want
            if cut < 8:                       # header torn: removed whole
                assert wal.segment_paths(d) == []
            else:                             # truncated to last intact
                assert os.path.getsize(dst) == max(
                    [e for e in ends if e <= cut])
            # repair is idempotent
            records2, frontier2 = wal.repair_wal(d, name="fuzz")
            assert frontier2 == frontier and len(records2) == want

    def test_at_byte_validation(self, tmp_path):
        p = tmp_path / "f.bin"
        p.write_bytes(b"x" * 10)
        with pytest.raises(errors.RaftLogicError):
            faults.inject_partial_write(str(p), at_byte=11)
        with pytest.raises(errors.RaftLogicError):
            faults.inject_partial_write(str(p), at_byte=-1)
        with pytest.raises(errors.RaftLogicError):
            faults.inject_partial_write(str(p), mode="corrupt",
                                        at_byte=3)

    def test_segments_past_tear_are_dropped(self, tmp_path):
        d = str(tmp_path / "w")
        _write_log(d, n=8, d=4, segment_bytes=120)  # forces rotation
        segs = wal.segment_paths(d)
        assert len(segs) >= 3
        # tear the SECOND segment mid-frame: everything after goes too
        faults.inject_partial_write(
            segs[1], at_byte=os.path.getsize(segs[1]) - 1)
        records, frontier = wal.repair_wal(d, name="tear-mid")
        assert frontier < 8
        assert [r.lsn for r in records] == list(range(1, frontier + 1))
        left = wal.segment_paths(d)
        assert left and left[-1].endswith(os.path.basename(segs[1]))

    def test_torn_counter_and_flight_event(self, tmp_path):
        d = str(tmp_path / "w")
        _write_log(d, n=4, d=4)
        seg = wal.segment_paths(d)[0]
        faults.inject_partial_write(
            seg, at_byte=os.path.getsize(seg) - 2)
        fl = FlightRecorder()
        before = wal.series("torn-tel")["torn"].value
        wal.repair_wal(d, name="torn-tel", flight=fl)
        assert wal.series("torn-tel")["torn"].value == before + 1
        evs = [e for e in fl.events() if e["event"] == "wal_torn_tail"]
        assert len(evs) == 1
        assert evs[0]["reason"] in ("short-frame", "short-payload",
                                    "crc-mismatch")


# -------------------------------------------------- replay idempotence
class TestReplayIdempotence:
    def test_duplicated_segment_replays_once(self, tmp_path, flat_index):
        d = str(tmp_path / "w")
        vecs, ids = _write_log(d, n=5, d=D)
        seg = wal.segment_paths(d)[0]
        # a duplicated segment (same frames, later name) — backup
        # restore gone wrong; monotone dedupe must absorb it
        shutil.copyfile(seg, os.path.join(
            d, "wal-00000000000000000002.log"))
        records, frontier = wal.read_records(d)
        assert frontier == 5 and len(records) == 5
        mw = wrap_mutable(flat_index, delta_cap=8)
        mw1, last, n = wal.replay_into(mw, records, name="dup")
        assert (last, n) == (5, 5)
        # the duplicated RECORD STREAM also replays once
        mw2, last2, n2 = wal.replay_into(mw, records + records,
                                         name="dup")
        assert (last2, n2) == (5, 5)
        assert np.array_equal(np.asarray(mw1.delta.ids),
                              np.asarray(mw2.delta.ids))

    def test_replay_skips_at_or_below_watermark(self, tmp_path,
                                                flat_index):
        d = str(tmp_path / "w")
        _write_log(d, n=6, d=D)
        records, _ = wal.read_records(d)
        mw = wrap_mutable(flat_index, delta_cap=8)
        _, last, n = wal.replay_into(mw, records, start_lsn=4,
                                     name="wm")
        assert (last, n) == (6, 2)

    def test_replay_counts_metric(self, tmp_path, flat_index):
        d = str(tmp_path / "w")
        _write_log(d, n=3, d=D)
        records, _ = wal.read_records(d)
        before = wal.series("replay-tel")["replayed"].value
        wal.replay_into(wrap_mutable(flat_index, delta_cap=8), records,
                        name="replay-tel")
        assert wal.series("replay-tel")["replayed"].value == before + 3


# ------------------------------------------------ group-commit ordering
class TestGroupCommit:
    def test_ack_never_precedes_fsync(self, tmp_path):
        """The ordering contract, proven with an instrumented fsync:
        at every fsync entry the writer's published durable LSN still
        excludes the frames in flight."""
        seen = []
        cell = {"w": None}

        def probing_fsync(fd):
            w = cell["w"]
            if w is not None:                 # skip header fsyncs
                seen.append((w.durable_lsn, w.last_lsn))
            os.fsync(fd)

        w = wal.WalWriter(str(tmp_path / "w"), flush_interval_s=0.0005,
                          fsync=probing_fsync)
        cell["w"] = w
        for k in range(10):
            ack = w.append(wal.OP_DELETE,
                           wal.encode_delete(np.array([k], np.int32)))
            assert ack.wait(10.0) and ack.durable
            assert w.durable_lsn >= ack.lsn
        w.close()
        # every fsync with frames pending entered BEFORE the durable
        # LSN covered them — the published frontier trails the sync
        assert seen and all(dur <= last for dur, last in seen)
        assert any(dur < last for dur, last in seen)

    def test_gated_fsync_blocks_ack(self, tmp_path):
        armed = threading.Event()
        release = threading.Event()
        entered = threading.Event()

        def gated_fsync(fd):
            if armed.is_set():
                entered.set()
                assert release.wait(10.0)
            os.fsync(fd)

        w = wal.WalWriter(str(tmp_path / "w"), flush_interval_s=0.0005,
                          fsync=gated_fsync)
        armed.set()
        ack = w.append(wal.OP_DELETE,
                       wal.encode_delete(np.array([1], np.int32)))
        assert entered.wait(10.0)
        assert not ack.durable
        assert ack.wait(0.05) is False        # parked behind the disk
        release.set()
        assert ack.wait(10.0) and ack.durable
        armed.clear()
        w.close()

    def test_io_error_latches_and_fails_acks(self, tmp_path):
        boom = threading.Event()

        def failing_fsync(fd):
            if boom.is_set():
                raise OSError(5, "injected EIO")
            os.fsync(fd)

        w = wal.WalWriter(str(tmp_path / "w"), flush_interval_s=0.0005,
                          fsync=failing_fsync)
        ok = w.append(wal.OP_DELETE,
                      wal.encode_delete(np.array([1], np.int32)))
        assert ok.wait(10.0)
        boom.set()
        ack = w.append(wal.OP_DELETE,
                       wal.encode_delete(np.array([2], np.int32)))
        with pytest.raises(OSError):          # the latched EIO
            ack.wait(10.0)
        with pytest.raises(errors.RaftLogicError):
            w.append(wal.OP_DELETE,           # writer is dead now
                     wal.encode_delete(np.array([3], np.int32)))

    def test_batch_ack_fairness_with_fake_clock(self, tmp_path):
        """Many appends racing one flusher batch: every ack resolves,
        LSNs are dense, and the log holds each frame exactly once."""
        w = wal.WalWriter(str(tmp_path / "w"), flush_interval_s=0.0,
                          flush_bytes=64)
        acks = []
        threads = [
            threading.Thread(target=lambda k=k: acks.append(
                w.append(wal.OP_DELETE,
                         wal.encode_delete(
                             np.array([k], np.int32)))))
            for k in range(32)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(a.wait(10.0) for a in acks)
        w.close()
        records, frontier = wal.read_records(str(tmp_path / "w"))
        assert frontier == 32
        assert sorted(r.lsn for r in records) == list(range(1, 33))


# --------------------------------------------------- rotation/retention
class TestRotationRetention:
    def test_prune_honours_watermark_and_active(self, tmp_path):
        d = str(tmp_path / "w")
        w = wal.WalWriter(d, segment_bytes=120, flush_interval_s=0.0005)
        for k in range(10):
            assert w.append(
                wal.OP_DELETE,
                wal.encode_delete(np.array([k], np.int32))).wait(10.0)
        segs = wal.segment_paths(d)
        assert len(segs) >= 3
        # watermark mid-segment: the covering segment must SURVIVE
        assert w.prune(2) == []
        first_lsns = [int(os.path.basename(s)[4:-4]) for s in segs]
        wm = first_lsns[1] - 1                # first segment now covered
        removed = w.prune(wm)
        assert removed == [segs[0]]
        # every record past the watermark is still readable
        records, frontier = wal.read_records(d)
        assert frontier == 10
        assert [r.lsn for r in records] == list(
            range(first_lsns[1], 11))
        # watermark=everything: the ACTIVE segment still survives
        w.prune(10)
        assert len(wal.segment_paths(d)) >= 1
        assert w.append(
            wal.OP_DELETE,
            wal.encode_delete(np.array([99], np.int32))).wait(10.0)
        w.close()
        assert removed and wal.wal_frontier(d) == 11

    def test_reopen_continues_after_frontier(self, tmp_path):
        d = str(tmp_path / "w")
        _write_log(d, n=4, d=4)
        n_segs = len(wal.segment_paths(d))
        w = wal.WalWriter(d, flush_interval_s=0.0005)
        assert w.durable_lsn == 4
        ack = w.append(wal.OP_DELETE,
                       wal.encode_delete(np.array([7], np.int32)))
        assert ack.wait(10.0) and ack.lsn == 5
        w.close()
        # a fresh segment, never an append into the old one
        assert len(wal.segment_paths(d)) == n_segs + 1
        assert wal.wal_frontier(d) == 5

    def test_reopen_over_torn_tail_repairs_first(self, tmp_path):
        """REVIEW fix: a writer opened over a torn directory repairs
        it BEFORE computing the frontier — appending at an unrepaired
        scan-frontier puts acked frames past the tear, where a later
        repair_wal would classify them past-tear and DELETE them."""
        d = str(tmp_path / "w")
        _write_log(d, n=3, d=4)
        seg = wal.segment_paths(d)[0]
        faults.inject_partial_write(
            seg, at_byte=os.path.getsize(seg) - 2)
        w = wal.WalWriter(d, flush_interval_s=0.0005)
        assert w.durable_lsn == 2             # frame 3 was torn away
        ack = w.append(wal.OP_DELETE,
                       wal.encode_delete(np.array([9], np.int32)))
        assert ack.wait(10.0) and ack.lsn == 3
        w.close()
        # the acked frame SURVIVES a later repair: it is a clean tail,
        # not past-tear garbage
        records, frontier = wal.repair_wal(d, name="reopen-tear")
        assert frontier == 3
        assert [r.lsn for r in records] == [1, 2, 3]
        assert records[-1].op == wal.OP_DELETE

    def test_reopen_refuses_segment_holding_records(self, tmp_path):
        """REVIEW fix: the constructor never opens an existing segment
        with records in 'wb' mode — a colliding segment whose LSNs the
        scan deduped away (a copied directory) raises instead of being
        silently truncated."""
        d = str(tmp_path / "w")
        _write_log(d, n=3, d=4)
        seg = wal.segment_paths(d)[0]
        # duplicated segment named frontier+1: its records dedupe to
        # nothing, so a naive reopen would truncate it
        shutil.copyfile(seg, os.path.join(
            d, "wal-00000000000000000004.log"))
        with pytest.raises(errors.CorruptIndexError):
            wal.WalWriter(d, flush_interval_s=0.0005)
        # nothing was scribbled on: the log still reads back whole
        records, frontier = wal.read_records(d)
        assert frontier == 3 and len(records) == 3


# ------------------------------------------------- single-chip recovery
class TestDurableIngestRecovery:
    def test_checkpoint_plus_tail_is_bit_identical(self, tmp_path,
                                                   flat_index, dataset):
        x, q = dataset
        d = str(tmp_path / "w")
        ckpt = str(tmp_path / "delta.ckpt")
        w = wal.WalWriter(d, flush_interval_s=0.0005)
        ing = wal.DurableIngest(wrap_mutable(flat_index, delta_cap=8),
                                w)
        ids = np.arange(9000, 9008, dtype=np.int32)
        assert ing.upsert(q[:4], ids[:4]).all()
        assert ing.delete(ids[:2]).all()
        wm = ing.checkpoint(ckpt)
        assert wm == 2 and \
            mut_mod.delta_checkpoint_watermark(ckpt) == wm
        assert ing.upsert(q[4:8], ids[4:8]).all()
        live = ing.mindex
        ing.close()
        fresh = wrap_mutable(flat_index, delta_cap=8)
        rec, frontier, n = wal.recover_mutable(
            fresh, d, checkpoint_path=ckpt, name="rec")
        assert frontier == 3 and n == 1       # only the tail replayed
        for f in ("ids", "vecs", "live", "counts"):
            assert np.array_equal(np.asarray(getattr(rec.delta, f)),
                                  np.asarray(getattr(live.delta, f))), f
        assert np.array_equal(np.asarray(rec.row_mask),
                              np.asarray(live.row_mask))
        assert np.array_equal(_search_ids(rec, q), _search_ids(live, q))

    def test_recovery_without_checkpoint_replays_all(self, tmp_path,
                                                     flat_index,
                                                     dataset):
        _, q = dataset
        d = str(tmp_path / "w")
        w = wal.WalWriter(d, flush_interval_s=0.0005)
        ing = wal.DurableIngest(wrap_mutable(flat_index, delta_cap=8),
                                w)
        ids = np.arange(9100, 9104, dtype=np.int32)
        assert ing.upsert(q[:4], ids).all()
        live = ing.mindex
        ing.close()
        rec, frontier, n = wal.recover_mutable(
            wrap_mutable(flat_index, delta_cap=8), d, name="rec0")
        assert (frontier, n) == (1, 1)
        assert np.array_equal(np.asarray(rec.delta.ids),
                              np.asarray(live.delta.ids))

    def test_recovery_races_live_checkpoints(self, tmp_path, flat_index,
                                             dataset):
        """Background acked ingest racing a checkpoint loop: whatever
        checkpoint wins, checkpoint + tail reconstructs the final
        state exactly."""
        _, q = dataset
        d = str(tmp_path / "w")
        ckpt = str(tmp_path / "delta.ckpt")
        w = wal.WalWriter(d, flush_interval_s=0.0005)
        ing = wal.DurableIngest(wrap_mutable(flat_index, delta_cap=64),
                                w)
        stop = threading.Event()
        rng = np.random.default_rng(5)

        def ingest():
            k = 0
            while not stop.is_set() and k < 40:
                v = rng.standard_normal((1, D)).astype(np.float32)
                ing.upsert(v, np.array([9500 + k], np.int32))
                k += 1

        t = threading.Thread(target=ingest)
        t.start()
        for _ in range(5):
            ing.checkpoint(ckpt)
        stop.set()
        t.join()
        ing.checkpoint(ckpt, prune=False)     # one quiesced checkpoint
        live = ing.mindex
        final_lsn = ing.applied_lsn
        ing.close()
        rec, frontier, _ = wal.recover_mutable(
            wrap_mutable(flat_index, delta_cap=64), d,
            checkpoint_path=ckpt, name="race")
        assert frontier == final_lsn
        for f in ("ids", "live", "counts"):
            assert np.array_equal(np.asarray(getattr(rec.delta, f)),
                                  np.asarray(getattr(live.delta, f))), f
        assert np.array_equal(np.asarray(rec.row_mask),
                              np.asarray(live.row_mask))

    def test_durability_failure_latches_front_end(self, tmp_path,
                                                  flat_index, dataset):
        """REVIEW fix: once an ack fails, the in-memory state is ahead
        of the durable log — the front end must stop serving it
        instead of exposing rows that vanish on restart."""
        _, q = dataset
        boom = threading.Event()

        def failing_fsync(fd):
            if boom.is_set():
                raise OSError(5, "injected EIO")
            os.fsync(fd)

        d = str(tmp_path / "w")
        w = wal.WalWriter(d, flush_interval_s=0.0005,
                          fsync=failing_fsync)
        ing = wal.DurableIngest(wrap_mutable(flat_index, delta_cap=8),
                                w)
        ids = np.arange(9600, 9604, dtype=np.int32)
        assert ing.upsert(q[:4], ids).all()
        boom.set()
        with pytest.raises(OSError):          # the latched EIO
            ing.delete(ids[:2])
        # the applied-but-never-durable state is no longer served
        with pytest.raises(errors.CorruptIndexError):
            _ = ing.mindex
        with pytest.raises(errors.CorruptIndexError):
            ing.upsert(q[:1], ids[:1])
        with pytest.raises(errors.CorruptIndexError):
            ing.checkpoint(str(tmp_path / "c.ckpt"))
        ing.close()
        # the acked frame is still recoverable from the log
        records, frontier = wal.repair_wal(d, name="latch")
        assert frontier >= 1 and records[0].lsn == 1
        assert records[0].op == wal.OP_UPSERT

    def test_wal_path_compiles_nothing(self, tmp_path, flat_index,
                                       dataset):
        """Zero-retrace audit: journal + repair + replay + recovery add
        NOTHING to the mutation jit caches beyond what the identical
        plain mutations already compiled."""
        _, q = dataset
        warm = wrap_mutable(flat_index, delta_cap=8)
        ids = np.arange(9300, 9304, dtype=np.int32)
        warm, _ = mut_mod.upsert(warm, q[:4], ids)       # warm caches
        mut_mod.delete(warm, ids[:2])
        _search_ids(warm, q)
        s0 = mut_mod._mut_search_impl._cache_size()
        u0 = mut_mod._upsert_impl._cache_size()
        d0 = mut_mod._delete_impl._cache_size()
        d = str(tmp_path / "w")
        w = wal.WalWriter(d, flush_interval_s=0.0005)
        ing = wal.DurableIngest(wrap_mutable(flat_index, delta_cap=8),
                                w)
        assert ing.upsert(q[:4], ids).all()
        assert ing.delete(ids[:2]).all()
        ing.checkpoint(str(tmp_path / "c.ckpt"))
        ing.close()
        wal.recover_mutable(wrap_mutable(flat_index, delta_cap=8), d,
                            checkpoint_path=str(tmp_path / "c.ckpt"),
                            name="audit")
        assert mut_mod._upsert_impl._cache_size() == u0
        assert mut_mod._delete_impl._cache_size() == d0
        assert mut_mod._mut_search_impl._cache_size() == s0


# --------------------------------------------------------------- MNMG
@pytest.fixture(scope="module")
def comms8():
    return build_comms(jax.devices()[:8])


@pytest.fixture(scope="module")
def sharded_flat_r2(comms8, dataset):
    x, _ = dataset
    idx = mnmg_ivf_flat_build(
        comms8, x, IVFFlatParams(n_lists=16, kmeans_n_iters=4,
                                 kmeans_init="random", seed=2),
        metric="sqeuclidean",
    )
    return place_index(comms8, idx, replication=2)


class TestMnmgDurable:
    def test_quorum_ack_and_frontier_reconcile(self, comms8,
                                               sharded_flat_r2,
                                               dataset, tmp_path):
        _, q = dataset
        root = str(tmp_path / "mnmg")
        mw = wrap_mnmg_mutable(comms8, sharded_flat_r2, delta_cap=8)
        ing = MnmgDurableIngest(comms8, mw, root,
                                flush_interval_s=0.0005)
        ids = np.arange(8200, 8206, dtype=np.int32)
        acked = ing.upsert(q[:6], ids)
        assert acked.all()
        fr = ing.frontiers()
        assert max(fr.values()) == 1          # one global LSN
        # per-rank logs are SPARSE: only holder ranks journaled
        holders = _row_holders(mw.index, mw.placement, q[:6])
        involved = {int(r) for r in np.unique(holders) if r >= 0}
        for r, f in fr.items():
            assert f == (1 if r in involved else 0)
        # kill one involved rank's WAL: rows it holds lose quorum
        # (R=2, quorum=1 -> BOTH holders must be durable)
        dead = sorted(involved)[0]
        ing._wals[dead].close()
        acked2 = ing.upsert(q[:6] + 0.001, ids)
        h2 = _row_holders(ing.mindex.index, ing.mindex.placement,
                          np.asarray(q[:6] + 0.001, np.float32))
        for i in range(6):
            hs = {int(r) for r in h2[i] if r >= 0}
            assert acked2[i] == (dead not in hs), (i, hs)
        # a mesh-wide delete still reaches quorum off the 7 healthy
        # logs — one dead WAL is a degraded shard, not an outage
        assert ing.delete(ids[:1]).all()
        live = ing.mindex
        fr2 = ing.frontiers()
        assert fr2[dead] < max(fr2.values())  # the lagging frontier
        ing.close()
        # recovery heals the lagging rank from the union of the logs:
        # every APPLIED batch (acked or not) was journaled on some
        # healthy holder, so replay reconstructs the live state exactly
        fresh = wrap_mnmg_mutable(comms8, sharded_flat_r2, delta_cap=8)
        rec, frontiers, n = mnmg_recover(comms8, fresh, root)
        assert frontiers[dead] < max(frontiers.values())
        assert n == max(frontiers.values())
        for f in ("delta_ids", "delta_counts", "row_mask"):
            assert np.array_equal(np.asarray(getattr(rec.state, f)),
                                  np.asarray(getattr(live.state, f))), f
        kw = dict(n_probes=6, qcap=q.shape[0])
        _, il = mnmg_mutable_search(comms8, live, q, K, **kw)
        _, ir = mnmg_mutable_search(comms8, rec, q, K, **kw)
        assert np.array_equal(np.asarray(il), np.asarray(ir))

    def test_delete_below_quorum_acks_nothing(self, comms8,
                                              sharded_flat_r2, dataset,
                                              tmp_path):
        """A delete whose only live journal rank has a dead WAL cannot
        claim durability: found comes back all-False (caller retries),
        even though the tombstone applied in memory."""
        _, q = dataset
        mw = wrap_mnmg_mutable(comms8, sharded_flat_r2, delta_cap=8)
        ing = MnmgDurableIngest(comms8, mw, str(tmp_path / "m"),
                                flush_interval_s=0.0005)
        ids = np.arange(8300, 8302, dtype=np.int32)
        assert ing.upsert(q[:2], ids).all()
        ing._wals[3].close()
        alive = np.zeros(comms8.size, bool)
        alive[3] = True
        assert not ing.delete(ids, alive=alive).any()
        ing.close()

    def test_quorum_validation(self, comms8, sharded_flat_r2,
                               tmp_path):
        mw = wrap_mnmg_mutable(comms8, sharded_flat_r2, delta_cap=8)
        with pytest.raises(errors.RaftLogicError):
            MnmgDurableIngest(comms8, mw, str(tmp_path / "x"),
                              quorum=5)


# ------------------------------------------------- supervisor recovery
class TestSupervisorRecovering:
    def test_heal_drives_recovering_pipeline(self, tmp_path, flat_index,
                                             dataset):
        """The acceptance leg: a quarantined rank walks RECOVERING ->
        RESYNCING -> WARMING -> SERVING unassisted, with replay_wal
        doing a REAL recover_mutable as the first step."""
        _, q = dataset
        d = str(tmp_path / "w")
        w = wal.WalWriter(d, flush_interval_s=0.0005)
        ing = wal.DurableIngest(wrap_mutable(flat_index, delta_cap=8),
                                w)
        ids = np.arange(9400, 9404, dtype=np.int32)
        assert ing.upsert(q[:4], ids).all()
        live = ing.mindex
        ing.close()

        t = {"now": 0.0}

        def clock():
            return t["now"]

        def sleep(dt):
            t["now"] += dt

        cell = {}
        steps = []
        fl = FlightRecorder()

        def replay_wal(rank):
            cell["mw"], _, _ = wal.recover_mutable(
                wrap_mutable(flat_index, delta_cap=8), d,
                name="sup-rec")
            steps.append(("replay_wal", sup.state(rank)))

        def resync(rank):
            steps.append(("resync", sup.state(rank)))

        def warm(rank):
            steps.append(("warm", sup.state(rank)))

        scripted = chaos.ScriptedHealth(4)
        health = ShardHealth(4, telemetry=False)
        monitor = HealthMonitor(4, consecutive=1, cooldown_s=0.0,
                                clock=clock, telemetry=False)
        sup = ServingSupervisor(
            health, ReplicaPlacement.striped(4, 2), scripted.probe,
            heal=HealActions(replay_wal=replay_wal, resync=resync,
                             warm=warm),
            monitor=monitor, clock=clock, sleep=sleep, flight=fl,
        )
        scripted.set(1, False)
        sup.step()
        assert sup.state(1) == STATE_QUARANTINED
        scripted.set(1, True)
        for _ in range(4):
            sup.step()
            sleep(0.05)
            if sup.state(1) == STATE_SERVING:
                break
        assert sup.state(1) == STATE_SERVING
        assert steps == [("replay_wal", STATE_RECOVERING),
                         ("resync", STATE_RESYNCING),
                         ("warm", STATE_WARMING)]
        # and the replayed state really is the durable one
        assert np.array_equal(_search_ids(cell["mw"], q),
                              _search_ids(live, q))
        trans = [e["state"] for e in
                 fl.events(event="supervisor_transition")
                 if e.get("rank") == 1]
        assert trans == [STATE_QUARANTINED, STATE_RECOVERING,
                         STATE_RESYNCING, STATE_WARMING, STATE_SERVING]


# ------------------------------------------------------- kill -9 chaos
def _assert_crash_cycle(r):
    assert set(r["acked"]) <= set(r["recovered"]), \
        "acked write lost"                    # the durability contract
    assert len(r["recovered"]) <= r["submitted"]
    lsns = [l for l, _ in r["recovered"]]
    assert lsns == list(range(1, len(lsns) + 1))  # dense, no torn tail
    gids = [g for _, g in r["recovered"]]
    assert gids == [100000 + k for k in range(len(gids))]


class TestKill9:
    def test_fast_leg_seeded_points(self, tmp_path):
        """Tier-1 leg: three seeded kill points; the >=10-point gate
        runs in `ci/run.sh wal` (the slow test below)."""
        for i, after in enumerate((1, 5, 17)):
            r = chaos.run_crash_ingest_cycle(
                str(tmp_path / f"w{i}"), kill_after_acks=after,
                n_records=40, d=8, seed=20 + i)
            assert r["returncode"] == -9
            assert len(r["acked"]) == after
            _assert_crash_cycle(r)

    def test_completion_leg_no_kill(self, tmp_path):
        r = chaos.run_crash_ingest_cycle(
            str(tmp_path / "w"), kill_after_acks=999, n_records=12,
            d=8, seed=9)
        assert r["returncode"] == 0
        assert r["frontier"] == 12 and len(r["recovered"]) == 12
        _assert_crash_cycle(r)

    @pytest.mark.slow
    def test_gate_ten_seeded_points(self, tmp_path):
        """The ISSUE 20 acceptance gate: >=10 seeded kill points, zero
        acked records lost, zero torn frames applied at every one."""
        points = (1, 2, 3, 5, 8, 12, 17, 23, 29, 34)
        for i, after in enumerate(points):
            r = chaos.run_crash_ingest_cycle(
                str(tmp_path / f"g{i}"), kill_after_acks=after,
                n_records=48, d=8, seed=40 + i)
            assert r["returncode"] == -9, after
            assert len(r["acked"]) == after
            _assert_crash_cycle(r)
