"""Test config: run all tests on CPU with 8 virtual devices.

Mirrors the reference's test strategy (SURVEY.md §4): multi-device tests run
against a virtual mesh the way pyraft's Dask tests use a multi-process
single-node cluster (python/raft/raft/test/conftest.py in the reference).
Env vars must be set before jax initializes.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

# The suite's wall time is dominated by jit compiles that are identical run
# to run; share ci/run.sh's workspace compile cache so bare pytest
# invocations (the tier-1 verify command) stay inside their time budget.
# Same knobs and disable convention as ci/run.sh (set the dir empty to
# disable); must be set before jax initializes.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", os.path.join(_REPO_ROOT, ".jax_cache")
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")

import jax  # noqa: E402

# The axon TPU plugin ignores JAX_PLATFORMS from the environment; the config
# knob is authoritative.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def _map_count() -> int:
    try:
        with open("/proc/self/maps", "rb") as f:
            return sum(chunk.count(b"\n")
                       for chunk in iter(lambda: f.read(1 << 20), b""))
    except OSError:          # non-Linux: no /proc, no map-count ceiling
        return 0


def pytest_runtest_teardown(item):
    # Every loaded XLA executable mmaps its code pages (~3 regions
    # each) and the kernel caps a process at vm.max_map_count (65530
    # by default). The full suite compiles/loads ~5k programs in one
    # process, crosses the ceiling around 92% in, and the next
    # compile or cache-deserialize segfaults inside XLA when mmap
    # fails — any subset passes, only the whole run dies. Dropping
    # the executable caches under pressure stays below the ceiling;
    # the persistent compile cache keeps the re-compiles cheap.
    if _map_count() > 45_000:
        import gc

        jax.clear_caches()
        gc.collect()


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


@pytest.fixture(scope="session")
def mesh8():
    import jax.sharding

    devs = np.array(jax.devices()[:8])
    return jax.sharding.Mesh(devs, ("x",))


@pytest.fixture()
def rng_np():
    return np.random.default_rng(42)
