"""Program auditor (raft_tpu.analysis.program) — ISSUE 12 acceptance.

Two speed tiers:

* **Fast** (default): walker recursion through every staging primitive,
  and a positive + negative unit test per pass over hand-built jitted
  fixtures — tracing only, no index builds, no device dispatch.
* **Slow** (``@pytest.mark.slow``, run by ``ci/run.sh test``; the gate
  itself runs as ``ci/run.sh programs``): the full registry audit over
  the toy world — every committed ``program_contracts.json`` entry
  pinned to a live program (stale entries fail, the jaxlint-baseline
  ratchet), the seeded regressions (DCN merge forced onto an f32
  allgather; a serving dispatch with donation dropped) flipping the
  gate red, and the CLI's JSON schema parity with the jaxlint CLI.
"""

import json
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu import compat
from raft_tpu.analysis.program import (
    ProgramRecord,
    aval_bytes,
    run_passes,
    walk_jaxpr,
)
from raft_tpu.analysis.program.contracts import (
    check_drift,
    load_contracts,
)
from raft_tpu.analysis.program.passes import (
    ALL_PASSES,
    collective_census,
    donation_check,
    dtype_flow,
    materialization_model,
    program_count,
)
from raft_tpu.analysis.program.registry import (
    donated_leaves,
    flip_census,
    record_from_traced,
)

REPO = Path(__file__).resolve().parent.parent
CONTRACTS = REPO / "ci" / "checks" / "program_contracts.json"


def record_of(fn, *args, meta=None, donated=None, count=None, name="t"):
    """Trace a plain function under jit into a ProgramRecord."""
    traced = jax.jit(fn).trace(*args)
    return ProgramRecord(
        name=name, jaxpr=traced.jaxpr, meta=meta or {},
        donated=donated, program_count=count,
    )


def rules_of(findings):
    return sorted({f.rule for f in findings})


# -- walker ------------------------------------------------------------------


def test_walker_recurses_scan_cond_and_marks_loop_context():
    def f(xs, p):
        def step(c, x):
            return c + jnp.sum(x @ x.T), None
        tot, _ = lax.scan(step, 0.0, xs)
        return lax.cond(p, lambda y: y * 2, lambda y: y + 1, tot)

    rec = record_of(f, jnp.ones((4, 8, 8)), True)
    sites = list(walk_jaxpr(rec.jaxpr))
    prims = {s.prim for s in sites}
    assert "scan" in prims and "cond" in prims
    # the matmul inside the scan body is visited, with loop context
    dots = [s for s in sites if s.prim == "dot_general"]
    assert dots and all(s.in_scan for s in dots)
    # the cond branches are walked but are NOT loop bodies
    branch_ops = [s for s in sites if "cond" in s.path]
    assert branch_ops and not any(s.in_scan for s in branch_ops)


def test_walker_recurses_shard_map_and_while(mesh8):
    del mesh8  # devices provisioned by conftest
    mesh = jax.sharding.Mesh(
        np.array(jax.devices()[:8]).reshape(2, 4), ("dcn", "ici")
    )
    from jax.sharding import PartitionSpec as P

    def body(x):
        y = lax.psum(x, "ici")

        def w_cond(c):
            return jnp.sum(c) < 100.0

        def w_body(c):
            return c * 2.0

        return lax.while_loop(w_cond, w_body, y)

    sm = compat.shard_map(body, mesh=mesh, in_specs=P("dcn"),
                          out_specs=P("dcn"), check_vma=False)
    rec = record_of(sm, jnp.ones((8, 4)))
    sites = list(walk_jaxpr(rec.jaxpr))
    prims = {s.prim for s in sites}
    assert "shard_map" in prims and "psum" in prims and "while" in prims
    mults = [s for s in sites if s.prim == "mul"]
    assert mults and all(s.in_scan for s in mults)  # while == loop body


def test_aval_bytes():
    def f(x):
        return x.astype(jnp.bfloat16)

    rec = record_of(f, jnp.ones((4, 8), jnp.float32))
    (site,) = [s for s in walk_jaxpr(rec.jaxpr)
               if s.prim == "convert_element_type"]
    assert aval_bytes(site.eqn.outvars[0].aval) == 4 * 8 * 2
    assert aval_bytes(site.eqn.invars[0].aval) == 4 * 8 * 4


# -- collective-census -------------------------------------------------------


def _dcn_mesh():
    return jax.sharding.Mesh(
        np.array(jax.devices()[:8]).reshape(2, 4), ("dcn", "ici")
    )


def _sm_record(body, meta, in_spec=None, x=None):
    from jax.sharding import PartitionSpec as P

    mesh = _dcn_mesh()
    sm = compat.shard_map(
        body, mesh=mesh, in_specs=in_spec or P("dcn"),
        out_specs=P(None), check_vma=False,
    )
    x = jnp.ones((8, 256)) if x is None else x
    return record_of(sm, x, meta=meta)


def test_collective_census_flags_wide_inner_outer_collective():
    def body(x):
        return lax.psum(x, ("dcn", "ici"))

    rec = _sm_record(body, {"dcn_axes": ("dcn",)})
    contract, findings = collective_census(rec)
    assert rules_of(findings) == ["collective-census"]
    assert "deployment width" in findings[0].message
    (entry,) = contract["collectives"]
    assert entry["prim"] == "psum" and sorted(entry["axes"]) == \
        ["dcn", "ici"]


def test_collective_census_flags_f32_dcn_allgather_on_bf16_wire():
    def body(x):
        inner = lax.psum(x, "ici")                  # inner stage: fine
        return jnp.sum(lax.all_gather(inner, "dcn"), axis=0)

    rec = _sm_record(body, {"dcn_axes": ("dcn",), "dcn_wire": "bf16"})
    contract, findings = collective_census(rec)
    assert rules_of(findings) == ["collective-census"]
    assert "float32 payload" in findings[0].message
    assert "float32" in contract["dcn_wire_dtypes"]


def test_collective_census_compressed_wire_and_hier_stages_clean():
    def body(x):
        inner = lax.psum(x, "ici")
        wire = lax.all_gather(inner.astype(jnp.bfloat16), "dcn")
        exact = lax.psum(inner, "dcn")              # f32 rerank psum: ok
        return jnp.sum(wire.astype(jnp.float32), axis=0) + exact

    rec = _sm_record(body, {"dcn_axes": ("dcn",), "dcn_wire": "bf16"})
    contract, findings = collective_census(rec)
    assert findings == []
    assert contract["dcn_wire_dtypes"] == ["bfloat16"]


# -- materialization-model ---------------------------------------------------


def _tile_scan(q, slabs):
    """The legacy grouped-scan shape: a (1, qcap, L) f32 einsum tile
    materialized inside a lax.map body."""
    def blk(mb):
        d2 = jnp.einsum("bqd,bld->bql", q[None], mb[None])
        return jnp.min(d2, axis=2)[0]

    return lax.map(blk, slabs)


def test_materialization_flags_qcap_maxlist_f32_tile_in_scan():
    q = jnp.ones((8, 4))
    slabs = jnp.ones((3, 32, 4))
    rec = record_of(_tile_scan, q, slabs,
                    meta={"qcap": 8, "max_list": 32})
    contract, findings = materialization_model(rec)
    assert rules_of(findings) == ["materialization-model"]
    assert "(1, 8, 32)" in findings[0].message
    assert contract["scan_wide_f32_tiles"] >= 1


def test_materialization_allow_wide_tile_pins_without_finding():
    q = jnp.ones((8, 4))
    slabs = jnp.ones((3, 32, 4))
    rec = record_of(_tile_scan, q, slabs,
                    meta={"qcap": 8, "max_list": 32,
                          "allow_wide_tile": True})
    contract, findings = materialization_model(rec)
    assert findings == []
    assert contract["scan_wide_f32_tiles"] >= 1   # census still pinned
    assert contract["peak_eqn_bytes"] >= 8 * 32 * 4


def test_materialization_negative_outside_scan_and_other_shapes():
    # the same tile OUTSIDE a scan, and non-(qcap, L) shapes inside one
    def flat(q, m):
        return jnp.min(jnp.einsum("bqd,bld->bql", q, m), axis=2)

    rec = record_of(flat, jnp.ones((1, 8, 4)), jnp.ones((1, 32, 4)),
                    meta={"qcap": 8, "max_list": 32})
    _, findings = materialization_model(rec)
    assert findings == []

    def narrow_scan(q, slabs):
        def blk(mb):
            return q @ mb.T                      # (qcap, L) 2-d: clean

        return lax.map(blk, slabs)

    rec2 = record_of(narrow_scan, jnp.ones((8, 4)), jnp.ones((3, 32, 4)),
                     meta={"qcap": 8, "max_list": 32})
    contract2, findings2 = materialization_model(rec2)
    assert findings2 == [] and contract2["scan_wide_f32_tiles"] == 0


# -- dtype-flow --------------------------------------------------------------


def test_dtype_flow_census_and_upcast_budget():
    def f(x):
        y = x.astype(jnp.bfloat16)
        return y.astype(jnp.float32) + x

    rec = record_of(f, jnp.ones((4,)),
                    meta={"max_bf16_to_f32": 0})
    contract, findings = dtype_flow(rec)
    assert contract["casts"]["bfloat16->float32"] == 1
    assert contract["casts"]["float32->bfloat16"] == 1
    assert rules_of(findings) == ["dtype-flow"]
    assert "sanctions at most 0" in findings[0].message

    rec2 = record_of(f, jnp.ones((4,)),
                     meta={"max_bf16_to_f32": 1})
    _, findings2 = dtype_flow(rec2)
    assert findings2 == []
    assert contract["dtypes_64bit"] == []


def test_dtype_flow_flags_64bit():
    # x64 is process-global; build the 64-bit aval via a synthetic
    # record instead of enabling it (the x64 harness owns that process)
    import dataclasses as dc

    def f(x):
        return x + 1

    rec = record_of(f, jnp.ones((4,)))
    real = [s for s in walk_jaxpr(rec.jaxpr)][0]
    fake_aval = jax.core.ShapedArray((4,), jnp.dtype("float64"))

    class FakeVar:
        aval = fake_aval

    fake_eqn = real.eqn.replace(outvars=[FakeVar()])
    fake_jaxpr = rec.jaxpr.jaxpr.replace(eqns=[fake_eqn])
    rec64 = dc.replace(rec, jaxpr=jax.core.ClosedJaxpr(fake_jaxpr, []))
    contract, findings = dtype_flow(rec64)
    assert rules_of(findings) == ["dtype-flow"]
    assert "float64" in findings[0].message
    assert contract["dtypes_64bit"] == ["float64"]


# -- donation-check ----------------------------------------------------------


def test_donation_check_positive_and_negative():
    import functools

    @functools.partial(jax.jit, donate_argnums=(0,))
    def donating(q, w):
        return q * w

    traced = donating.trace(jnp.ones((4,)), jnp.ones((4,)))
    assert donated_leaves(traced) == [0]
    rec = record_from_traced(
        "ok", traced, {"expect_donated_queries": True}
    )
    _, findings = donation_check(rec)
    assert findings == []

    @jax.jit
    def not_donating(q, w):
        return q * w

    traced2 = not_donating.trace(jnp.ones((4,)), jnp.ones((4,)))
    rec2 = record_from_traced(
        "bad", traced2, {"expect_donated_queries": True}
    )
    contract2, findings2 = donation_check(rec2)
    assert rules_of(findings2) == ["donation-check"]
    assert "donates NO input buffer" in findings2[0].message
    assert contract2["donated"] == []


# -- program-count -----------------------------------------------------------


def test_program_count_pass_and_flip_census():
    rec = ProgramRecord("ok", None, program_count=1)
    contract, findings = program_count(rec)
    assert findings == [] and contract["program_count"] == 1

    rec2 = ProgramRecord("bad", None, program_count=3)
    _, findings2 = program_count(rec2)
    assert rules_of(findings2) == ["program-count"]
    assert "zero-retrace" in findings2[0].message

    # the census itself: a prepare whose STATICS leak a runtime value
    # resolves to two distinct programs; a clean prepare to one
    @jax.jit
    def serve(x):
        return x * 2

    @jax.jit
    def serve_retraced(x):
        return x * 3

    q = jnp.ones((4,))

    def prep_clean(alive):
        return serve, (q,), False

    def prep_leaky(alive):
        # the mutation-retrace hazard: a static derived from the mask
        fn = serve if int(np.asarray(alive).sum()) == 8 else serve_retraced
        return fn, (q,), False

    flips = [{"alive": np.ones(8)}, {"alive": np.r_[np.zeros(1),
                                                   np.ones(7)]}]
    assert flip_census(prep_clean, flips) == 1
    assert flip_census(prep_leaky, flips) == 2


# -- contract drift mechanics ------------------------------------------------


def test_check_drift_both_directions_and_field_diffs():
    live = {"a": {"x": 1, "nested": {"y": 2}}, "b": {"x": 1}}
    ok = check_drift(live, {"a": {"x": 1, "nested": {"y": 2}},
                            "b": {"x": 1}})
    assert ok == []
    # changed field
    fs = check_drift(live, {"a": {"x": 1, "nested": {"y": 3}},
                            "b": {"x": 1}})
    assert len(fs) == 1 and "nested.y" in fs[0].message
    assert fs[0].rule == "program-contract"
    # stale snapshot entry (program removed)
    fs2 = check_drift({"a": live["a"]}, {"a": live["a"], "b": {"x": 1}})
    assert len(fs2) == 1 and "no longer exists" in fs2[0].message
    # unpinned live program
    fs3 = check_drift(live, {"a": live["a"]})
    assert len(fs3) == 1 and "no committed contract" in fs3[0].message


def test_run_passes_merges_all_passes_and_meta():
    def f(x):
        return x * 2

    rec = record_of(f, jnp.ones((4,)),
                    meta={"qcap": 8, "note_obj": object()})
    contract, findings = run_passes(rec)
    assert findings == []
    for key in ("meta", "collectives", "peak_eqn_bytes", "casts",
                "donated", "program_count"):
        assert key in contract
    assert contract["meta"] == {"qcap": 8}   # non-JSON meta dropped
    assert [p.name for p in ALL_PASSES] == [
        "collective-census", "materialization-model", "dtype-flow",
        "donation-check", "program-count",
    ]


# -- the full registry (slow tier: toy-world builds) -------------------------


@pytest.fixture(scope="module")
def live_audit():
    from raft_tpu.analysis.program.contracts import audit_programs

    return audit_programs(count=True)


@pytest.mark.slow
def test_registry_covers_entry_points_and_audits_clean(live_audit):
    live, findings = live_audit
    assert findings == [], [f.render() for f in findings]
    assert len(live) >= 8
    # the serving surface is covered: every engine family, the probe,
    # both mnmg variants incl. failover+mutation, and the hier merge
    for name in (
        "ivf_flat_grouped_pallas", "ivf_pq_grouped_pallas",
        "ivf_sq_grouped_pallas", "two_level_probe_kernel",
        "mnmg_pq_fused", "mnmg_pq_fused_failover_mutation",
        "mnmg_flat_fused_failover_mutation", "mnmg_pq_hier_merge",
    ):
        assert name in live, name
    # physics pinned: kernel engines materialize no wide tile, legacy
    # engines do (and say so), serving queries donate, flips retrace
    # nothing, the DCN wire is compressed
    assert live["ivf_flat_grouped_pallas"]["scan_wide_f32_tiles"] == 0
    assert live["ivf_pq_grouped_pallas"]["scan_wide_f32_tiles"] == 0
    assert live["ivf_flat_grouped_xla"]["scan_wide_f32_tiles"] > 0
    assert live["ivf_pq_grouped_onehot"]["scan_wide_f32_tiles"] > 0
    assert live["mnmg_pq_fused"]["donated"] != []
    assert live["mnmg_pq_fused_failover_mutation"]["program_count"] == 1
    assert live["mnmg_flat_fused_failover_mutation"]["program_count"] == 1
    assert live["mnmg_pq_hier_merge"]["dcn_wire_dtypes"] == [
        "bfloat16", "int32",
    ]


@pytest.mark.slow
def test_committed_contracts_pin_live_programs_no_drift(live_audit):
    """The drift-check ratchet (the jaxlint-baseline discipline): every
    committed snapshot entry must match a LIVE program exactly — stale
    entries fail, unpinned live programs fail, changed fields fail."""
    live, _ = live_audit
    committed = load_contracts(CONTRACTS)
    assert len(committed) >= 8
    drift = check_drift(live, committed)
    assert drift == [], [f.render() for f in drift]
    # stale-entry direction actually fails
    import copy

    doctored = copy.deepcopy(committed)
    doctored["ghost_program"] = {"peak_eqn_bytes": 1}
    assert any(
        "no longer exists" in f.message
        for f in check_drift(live, doctored)
    )
    # and a field-level regression (the f32-wire shape) actually fails
    doctored2 = copy.deepcopy(committed)
    doctored2["mnmg_pq_hier_merge"]["dcn_wire_dtypes"] = [
        "float32", "int32",
    ]
    fs = check_drift(live, doctored2)
    assert any("dcn_wire_dtypes" in f.message for f in fs)


@pytest.mark.slow
def test_seeded_regression_f32_dcn_wire_flips_red():
    """ISSUE 12 acceptance: forcing the DCN merge onto the uncompressed
    f32 allgather — a change every bit-identity test is blind to —
    produces a hard collective-census finding against the REAL fused
    program, prepared through the serving entry's own front half."""
    from raft_tpu.analysis.program.registry import _World
    from raft_tpu.comms.mnmg_ivf import _prepare_pq_search
    from raft_tpu.comms.multihost import hier_axes

    w = _World.get()
    comms = w.hier_comms
    h = hier_axes(comms.mesh, comms.axis)
    fn, args, _ = _prepare_pq_search(
        comms, w.mnmg_pq, w.q, 4, n_probes=4, qcap=8, refine_ratio=2.0,
        use_pallas=True, wire="f32",
    )
    rec = record_from_traced(
        "seeded_f32_wire", fn.trace(*args),
        {"dcn_axes": (h[0],), "dcn_wire": "bf16"},
    )
    _, findings = run_passes(rec)
    assert "collective-census" in rules_of(findings)
    assert any("float32 payload" in f.message for f in findings)


@pytest.mark.slow
def test_seeded_regression_undonated_queries_flips_red():
    """ISSUE 12 acceptance: un-donating the serving queries produces a
    hard donation-check finding against the real fused program."""
    from raft_tpu.analysis.program.registry import _World
    from raft_tpu.comms.mnmg_ivf import _prepare_pq_search

    w = _World.get()
    fn, args, _ = _prepare_pq_search(
        w.comms, w.mnmg_pq, w.q, 4, n_probes=4, qcap=8,
        refine_ratio=2.0, use_pallas=True, donate_queries=False,
    )
    rec = record_from_traced(
        "seeded_undonated", fn.trace(*args),
        {"expect_donated_queries": True},
    )
    _, findings = run_passes(rec)
    assert rules_of(findings) == ["donation-check"]


@pytest.mark.slow
def test_cli_json_schema_matches_jaxlint(tmp_path):
    """ISSUE 12 satellite: ``--programs --format json`` emits the SAME
    top-level schema as the lint CLI, so the one consumer script parses
    both tiers — and a doctored contracts file flips the exit code."""
    env = dict(
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
        PATH="/usr/bin:/bin:/usr/local/bin",
    )
    import os

    env = {**os.environ, **env}
    proc = subprocess.run(
        [sys.executable, "-m", "raft_tpu.analysis", "--programs",
         "--format", "json"],
        capture_output=True, text=True, cwd=REPO, env=env,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    lint = subprocess.run(
        [sys.executable, "-m", "raft_tpu.analysis", "--format", "json",
         "--no-baseline", "ci/checks/style.py"],
        capture_output=True, text=True, cwd=REPO, env=env,
    )
    assert set(payload) == set(json.loads(lint.stdout))
    assert payload["checked_files"] >= 8
    assert payload["findings"] == []
    assert "collective-census" in payload["rules"]
    # doctored snapshot -> findings + exit 1 (the gate goes red)
    doctored = json.loads(CONTRACTS.read_text())
    doctored["programs"]["mnmg_pq_hier_merge"]["dcn_wire_dtypes"] = [
        "float32", "int32",
    ]
    alt = tmp_path / "contracts.json"
    alt.write_text(json.dumps(doctored))
    proc2 = subprocess.run(
        [sys.executable, "-m", "raft_tpu.analysis", "--programs",
         "--format", "json", "--contracts", str(alt)],
        capture_output=True, text=True, cwd=REPO, env=env,
    )
    assert proc2.returncode == 1
    out2 = json.loads(proc2.stdout)
    assert any(f["rule"] == "program-contract" for f in out2["findings"])


@pytest.mark.slow
def test_warmup_audit_spot_check(live_audit):
    """``warmup(audit=True)`` accepts the healthy single-chip engines
    (both modes) and the registry world's caches keep it cheap."""
    del live_audit  # ordering: reuse the already-built world
    from raft_tpu.analysis.program.registry import _World

    w = _World.get()
    assert w.flat_index.warmup(16, k=4, n_probes=4, use_pallas=True,
                               audit=True) == 8
    assert w.flat_index.warmup(16, k=4, n_probes=4, use_pallas=False,
                               audit=True) == 8


def test_list_programs_cli():
    proc = subprocess.run(
        [sys.executable, "-m", "raft_tpu.analysis", "--list-programs"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0
    from raft_tpu.analysis.program.registry import SPECS

    assert len(SPECS) >= 8
    for s in SPECS:
        assert f"{s.name}:" in proc.stdout
