"""ISSUE 18 chaos suite: the self-healing supervisor proven by the
scripted chaos-schedule harness (raft_tpu/resilience/supervisor.py +
raft_tpu/testing/chaos.py) — no manual recovery calls anywhere:

* HealthMonitor debounce: N-consecutive confirm, cooldown hysteresis
  (injectable clock), kept streaks across a suppressed window, report
  folding, force() re-arm;
* flap invariant: an oscillating probe produces ZERO route pushes, and
  a confirmed transition exactly one (deterministic, fake clock);
* the resumable heal pipeline: per-step retry under RetryPolicy,
  partial-failure rollback back to QUARANTINED (monitor re-armed), and
  resume-from-cursor after a mid-heal supervisor crash;
* supervisor thread crash surfaced via thread_uncaught_total and
  restartable with start() (state, incl. heal progress, survives);
* the chaos-schedule engine itself (replay-order firing, fake-clock
  determinism, convergence checker deadlines);
* resync_rank racing live acked ingest loses no acked write, with the
  SUPERVISOR driving recover→resync (the write-exclusion edge lives in
  the heal action: health flips up inside resync's critical section);
* the acceptance schedule — rank kill mid-ingest → straggler burst →
  heal → oscillating probe — against a live open-loop executor, with
  coverage==1.0 / bit-identity / zero-acked-writes-lost /
  zero-retrace / bounded-route-convergence / no-flap all asserted by
  the declarative checker framework.

Runs in tier-1 on the virtual 8-device CPU mesh and again under
RAFT_TPU_LOCKCHECK=1 in the `ci/run.sh chaos` stage.
"""

import dataclasses
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import errors
from raft_tpu.comms import (
    build_comms,
    mnmg_ivf_flat_build,
    mnmg_mutable_search,
    mnmg_upsert,
    place_index,
    recover_rank,
    resync_rank,
    wrap_mnmg_mutable,
)
from raft_tpu.obs import FlightRecorder
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.resilience import (
    STATE_QUARANTINED,
    STATE_SERVING,
    FailoverPlan,
    HealActions,
    HealthMonitor,
    ReplicaPlacement,
    RetryPolicy,
    ServingSupervisor,
    ShardHealth,
)
from raft_tpu.resilience.health import HealthProbe, HealthReport
from raft_tpu.serving import ServingExecutor
from raft_tpu.spatial.ann import IVFFlatParams, save_index
from raft_tpu.testing import chaos

K = 5


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += float(dt)


class _RecordingExecutor:
    """set_runtime sink — counts the supervisor's route pushes."""

    def __init__(self):
        self.pushes = []

    def set_runtime(self, **updates):
        self.pushes.append(updates)


# ---------------------------------------------------------------------------
# HealthMonitor debounce (no mesh)
# ---------------------------------------------------------------------------


class TestHealthMonitor:
    def test_consecutive_confirm_and_streak_reset(self):
        m = HealthMonitor(4, consecutive=3, cooldown_s=0.0,
                          telemetry=False)
        assert m.observe(1, False) is None
        assert m.observe(1, False) is None
        # a contradiction broken by an agreeing observation resets
        assert m.observe(1, True) is None
        assert m.observe(1, False) is None
        assert m.observe(1, False) is None
        assert m.observe(1, False) == "down"
        assert not m.is_up(1) and m.is_up(0)
        assert m.transition_count == 1

    def test_cooldown_suppresses_then_defers_not_drops(self):
        clk = _FakeClock()
        m = HealthMonitor(2, consecutive=2, cooldown_s=1.0, clock=clk,
                          telemetry=False)
        assert m.observe(0, False) is None
        assert m.observe(0, False) == "down"
        # immediate recovery streak: confirmed but inside cooldown
        assert m.observe(0, True) is None
        assert m.observe(0, True) is None      # streak=2, suppressed
        assert m.observe(0, True) is None
        clk.advance(1.01)
        # streak was KEPT: first post-cooldown observation flips
        assert m.observe(0, True) == "up"
        assert m.transition_count == 2

    def test_oscillation_never_confirms(self):
        m = HealthMonitor(2, consecutive=2, cooldown_s=0.0,
                          telemetry=False)
        for i in range(40):
            assert m.observe(1, i % 2 == 0) is None
        assert m.is_up(1) and m.transition_count == 0

    def test_force_rearms_without_counting(self):
        clk = _FakeClock()
        m = HealthMonitor(2, consecutive=1, cooldown_s=0.5, clock=clk,
                          telemetry=False)
        assert m.observe(0, False) == "down"
        clk.advance(1.0)
        assert m.observe(0, True) == "up"
        m.force(0, up=False)                   # rollback re-arm
        assert not m.is_up(0)
        assert m.transition_count == 2          # force did not count
        # cooldown restarts at force time: an immediate up is deferred
        assert m.observe(0, True) is None
        clk.advance(0.51)
        assert m.observe(0, True) == "up"

    def test_observe_report_downs_implicated_ranks_only(self):
        m = HealthMonitor(4, consecutive=1, cooldown_s=0.0,
                          telemetry=False)
        rep = HealthReport(probes={
            "allreduce": HealthProbe(ok=True, seconds=0.01),
            "heartbeat": HealthProbe(ok=False, seconds=0.01, ranks=(2,)),
        })
        assert m.observe_report(rep) == {2: "down"}
        assert m.is_up(0) and not m.is_up(2)
        # unattributed failure implicates everyone
        rep2 = HealthReport(probes={
            "allreduce": HealthProbe(ok=False, seconds=0.01),
        })
        out = m.observe_report(rep2)
        assert set(out) == {0, 1, 3} and all(v == "down"
                                             for v in out.values())


# ---------------------------------------------------------------------------
# Supervisor state machine (no mesh — fake executors, fake clock)
# ---------------------------------------------------------------------------


def _mini_supervisor(clk, scripted, *, n=8, consecutive=3,
                     cooldown_s=10.0, heal=None, retry=None):
    health = ShardHealth(n, telemetry=False)
    monitor = HealthMonitor(n, consecutive=consecutive,
                            cooldown_s=cooldown_s, clock=clk,
                            telemetry=False)
    sup = ServingSupervisor(
        health, ReplicaPlacement.striped(n, 2), scripted.probe,
        heal=heal, monitor=monitor, retry=retry,
        clock=clk, sleep=clk.advance,
    )
    return sup, health, monitor


class TestSupervisorFlap:
    def test_oscillation_never_pushes_confirmed_pushes_once(self):
        """ISSUE 18 satellite: oscillating health reports never produce
        more than one route push per CONFIRMED transition — and an
        oscillation that never confirms produces none at all."""
        clk = _FakeClock()
        scripted = chaos.ScriptedHealth(8)
        sup, health, monitor = _mini_supervisor(clk, scripted)
        ex = _RecordingExecutor()
        sup.register(ex)
        base = len(ex.pushes)                   # the register sync push
        # a probe oscillating every tick: streak never reaches 3
        for i in range(30):
            scripted.set(2, i % 2 == 0)
            sup.step()
            clk.advance(0.05)
        assert len(ex.pushes) == base
        assert monitor.transition_count == 0 and health.is_up(2)
        # sustained death: exactly ONE push, on the confirming tick
        scripted.set(2, False)
        for _ in range(5):
            sup.step()
            clk.advance(0.05)
        assert monitor.transition_count == 1
        assert len(ex.pushes) == base + 1
        assert sup.state(2) == STATE_QUARANTINED and not health.is_up(2)
        # the pushed mask/plan routes around rank 2, coverage intact
        push = ex.pushes[-1]
        assert push["shard_mask"][2] == 0
        assert push["failover"].fully_covered
        # more oscillation inside the cooldown: still nothing
        for i in range(30):
            scripted.set(2, i % 2 == 0)
            sup.step()
            clk.advance(0.05)
        assert len(ex.pushes) == base + 1
        # sustained recovery past the cooldown: one heal, one push
        clk.advance(11.0)
        scripted.set(2, True)
        for _ in range(5):
            sup.step()
            clk.advance(0.05)
        assert monitor.transition_count == 2
        assert len(ex.pushes) == base + 2
        assert sup.state(2) == STATE_SERVING and health.is_up(2)
        # the flap invariant, as the checker spells it
        flap = chaos.BoundInvariant(
            "no-route-flap",
            lambda: (len(ex.pushes) - base) - monitor.transition_count,
            0,
        )
        flap.sample(clk.t)
        assert not flap.violations


class TestSupervisorHeal:
    def test_retry_backoff_then_success(self):
        clk = _FakeClock()
        scripted = chaos.ScriptedHealth(4)
        calls = {"resync": 0}

        def flaky_resync(rank):
            calls["resync"] += 1
            if calls["resync"] < 3:
                raise errors.RaftTimeoutError("transient splice timeout")

        sup, health, monitor = _mini_supervisor(
            clk, scripted, n=4, consecutive=1, cooldown_s=0.0,
            heal=HealActions(resync=flaky_resync),
            retry=RetryPolicy(max_attempts=3, base_delay_s=0.01),
        )
        scripted.set(1, False)
        sup.step()
        assert sup.state(1) == STATE_QUARANTINED
        scripted.set(1, True)
        sup.step()
        assert calls["resync"] == 3             # two retries then success
        assert sup.state(1) == STATE_SERVING and health.is_up(1)
        assert sup.stats().heals_ok == 1
        assert sup.stats().heals_rolled_back == 0

    def test_nonretryable_failure_rolls_back_and_rearms(self):
        clk = _FakeClock()
        scripted = chaos.ScriptedHealth(4)
        calls = {"recover": 0, "rollback": 0, "broken": True}

        def recover(rank):
            calls["recover"] += 1
            if calls["broken"]:
                raise errors.CorruptIndexError("torn checkpoint")

        def rollback(rank):
            calls["rollback"] += 1

        sup, health, monitor = _mini_supervisor(
            clk, scripted, n=4, consecutive=1, cooldown_s=0.0,
            heal=HealActions(recover=recover, rollback=rollback),
            retry=RetryPolicy(max_attempts=3, base_delay_s=0.01),
        )
        ex = _RecordingExecutor()
        sup.register(ex)
        base = len(ex.pushes)
        scripted.set(2, False)
        sup.step()
        assert len(ex.pushes) == base + 1
        scripted.set(2, True)
        sup.step()
        # CorruptIndexError is not retryable: ONE attempt, rollback,
        # back to QUARANTINED, the routed-around plan keeps serving —
        # and NO route push for the failed heal
        assert calls["recover"] == 1 and calls["rollback"] == 1
        assert sup.state(2) == STATE_QUARANTINED and not health.is_up(2)
        assert sup.stats().heals_rolled_back == 1
        assert len(ex.pushes) == base + 1
        # monitor was re-armed to confirmed-down: the still-up probe
        # re-confirms on the next tick and the (now fixed) heal runs
        calls["broken"] = False
        sup.step()
        assert sup.state(2) == STATE_SERVING and health.is_up(2)
        assert sup.stats().heals_ok == 1
        assert len(ex.pushes) == base + 2

    def test_mid_heal_crash_resumes_from_cursor(self):
        """The pipeline is RESUMABLE: a supervisor crash between steps
        (anything that unwinds step() — here a BaseException from the
        resync actuator) leaves the per-rank cursor on the object, and
        the next step() resumes AFTER the completed recover step
        instead of replaying the side-effectful splice."""

        class _Crash(BaseException):
            pass

        clk = _FakeClock()
        scripted = chaos.ScriptedHealth(4)
        calls = {"recover": 0, "resync": 0, "crash": True}

        def recover(rank):
            calls["recover"] += 1

        def resync(rank):
            calls["resync"] += 1
            if calls["crash"]:
                calls["crash"] = False
                raise _Crash()

        sup, health, monitor = _mini_supervisor(
            clk, scripted, n=4, consecutive=1, cooldown_s=0.0,
            heal=HealActions(recover=recover, resync=resync),
        )
        scripted.set(3, False)
        sup.step()
        scripted.set(3, True)
        with pytest.raises(_Crash):
            sup.step()                          # dies mid-pipeline
        assert calls["recover"] == 1 and calls["resync"] == 1
        assert sup.state(3) != STATE_SERVING
        sup.step()                              # "restart": resumes
        assert calls["recover"] == 1            # NOT replayed
        assert calls["resync"] == 2
        assert sup.state(3) == STATE_SERVING and health.is_up(3)

    # the injected crash IS the point — silence pytest's
    # unhandled-thread-exception warning for it
    @pytest.mark.filterwarnings(
        "ignore::pytest.PytestUnhandledThreadExceptionWarning")
    def test_thread_crash_surfaced_and_restartable(self):
        """ISSUE 18 satellite: a supervisor thread crash is caught by
        the crash excepthook chain (thread_uncaught_total names the
        thread), and start() simply restarts the loop from the
        object's state."""
        prev_obs = obs_metrics.set_enabled(True)
        try:
            boom = {"on": False}

            def probe():
                if boom["on"]:
                    raise RuntimeError("injected supervisor crash")
                return {r: True for r in range(4)}

            sup = ServingSupervisor(
                ShardHealth(4, telemetry=False),
                ReplicaPlacement.striped(4, 2), probe,
                interval_s=0.003, name="chaos18-sup-crash",
            )
            sup.start()
            deadline = time.monotonic() + 10
            while sup.stats().ticks < 2 and time.monotonic() < deadline:
                time.sleep(0.005)
            assert sup.stats().ticks >= 2
            boom["on"] = True
            while sup._thread.is_alive() and time.monotonic() < deadline:
                time.sleep(0.005)
            assert not sup._thread.is_alive()
            snap = obs_metrics.default_registry().snapshot()
            assert any(
                row["labels"].get("thread") == "chaos18-sup-crash"
                for row in snap.get("thread_uncaught_total", [])
            ), "the crash must surface in thread_uncaught_total"
            # restart: same object, fresh thread, loop resumes
            boom["on"] = False
            ticks0 = sup.stats().ticks
            sup.start()
            while (sup.stats().ticks <= ticks0
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            assert sup.stats().ticks > ticks0
            sup.close()
        finally:
            obs_metrics.set_enabled(prev_obs)


# ---------------------------------------------------------------------------
# The schedule engine (no mesh)
# ---------------------------------------------------------------------------


class TestChaosEngine:
    def test_events_fire_in_order_fake_clock(self):
        clk = _FakeClock()
        fired = []
        sched = (
            chaos.ChaosSchedule(seed=1)
            .at(0.03, "b", lambda: fired.append("b"))
            .at(0.01, "a", lambda: fired.append("a"))
        )
        inv = chaos.BoundInvariant("at-most-two", lambda: len(fired), 2)
        report = chaos.run_schedule(
            sched, duration_s=0.05, invariants=[inv],
            check_interval_s=0.005, clock=clk, sleep=clk.advance,
        )
        assert report.ok, report.summary()
        assert [n for _, n in report.fired] == ["a", "b"]
        assert fired == ["a", "b"]

    def test_oscillate_composer_ends_up(self):
        clk = _FakeClock()
        scripted = chaos.ScriptedHealth(4)
        seen = []
        sched = chaos.ChaosSchedule(scripted=scripted, seed=0)
        sched.oscillate(0.01, 2, period_s=0.01, duration_s=0.04)
        chaos.run_schedule(
            sched, duration_s=0.08,
            tick=lambda t: seen.append(scripted.probe()[2]),
            check_interval_s=0.002, clock=clk, sleep=clk.advance,
        )
        assert False in seen and True in seen   # it really flapped
        assert scripted.probe()[2] is True      # and ended up

    def test_convergence_invariant_deadline(self):
        trig = [0]
        done = [0]
        inv = chaos.ConvergenceInvariant("conv", lambda: trig[0],
                                         lambda: done[0], 0.5)
        inv.sample(0.0)
        trig[0] = 1
        inv.sample(0.1)                         # trigger seen at 0.1
        inv.sample(0.5)                         # within deadline
        assert not inv.violations
        inv.sample(0.7)                         # 0.6 s > 0.5 s late
        assert len(inv.violations) == 1
        trig[0] = 2
        inv.sample(0.8)
        done[0] = 2                             # answered in time
        inv.sample(0.9)
        inv.finish(1.0)
        assert len(inv.violations) == 1

    def test_final_invariant_only_checks_at_finish(self):
        state = {"ok": False}
        inv = chaos.FinalInvariant("final", lambda: state["ok"])
        inv.sample(0.1)
        assert not inv.violations
        state["ok"] = True
        inv.finish(0.2)
        assert not inv.violations

    def test_straggler_gate_toggles(self):
        calls = []

        def fn(x):
            calls.append(x)
            return x

        gate = chaos.StragglerGate(fn, every=1, seconds=0.0)
        assert gate(1) == 1
        gate.enable()
        gate(2)
        gate.disable()
        assert gate(3) == 3
        assert gate.audit.calls >= 1            # the window was audited

    def test_inject_worker_crash_arms_and_restores(self):
        class _Store:
            def __init__(self):
                self.applied = []

            def apply_moves(self, moves, **kw):
                self.applied.append(moves)

        store = _Store()
        restore = chaos.inject_worker_crash(store, times=2)
        with pytest.raises(RuntimeError, match="injected fetcher"):
            store.apply_moves([(1, None)])
        with pytest.raises(RuntimeError):
            store.apply_moves([(2, None)])
        store.apply_moves([(3, None)])          # fault exhausted
        assert store.applied == [[(3, None)]]
        restore()
        assert store.apply_moves.__self__ is store  # original bound back


# ---------------------------------------------------------------------------
# MNMG: supervisor-driven heal on the live mesh
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def comms8():
    return build_comms(jax.devices()[:8])


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(7)
    x = rng.standard_normal((512, 16)).astype(np.float32)
    q = rng.standard_normal((12, 16)).astype(np.float32)
    return x, q


@pytest.fixture(scope="module")
def replicated_r2(comms8, dataset):
    x, _ = dataset
    idx = mnmg_ivf_flat_build(
        comms8, x,
        IVFFlatParams(n_lists=8, kmeans_n_iters=3,
                      kmeans_init="random", seed=2),
        metric="sqeuclidean",
    )
    return place_index(comms8, idx, replication=2)


def _heal_actions(comms, cell, lock, health, ckpt):
    """The real reintegration actuators over a shared mutable-index
    cell. The WRITE-EXCLUSION EDGE: ``resync`` flips health up INSIDE
    its critical section, after the swapped-in state already carries
    the donor's delta — so a writer that snapshots ``health.mask()``
    under the same lock can never ack a write that misses the healed
    copy (the resync-vs-live-ingest race)."""

    def recover(rank):
        with lock:
            mw = cell["mw"]
            rec = recover_rank(comms, mw.index, ckpt, rank)
            mw2 = dataclasses.replace(mw, index=rec)
            mw2._id_loc = None
            cell["mw"] = mw2

    def resync(rank):
        with lock:
            cell["mw"] = resync_rank(comms, cell["mw"], rank)
            health.mark_up(rank)

    return HealActions(recover=recover, resync=resync)


def test_resync_racing_live_ingest_supervisor_driven(
    comms8, dataset, replicated_r2, tmp_path
):
    """ISSUE 18 satellite: resync_rank racing live acked upsert traffic
    loses no acked write — and unlike the hand-scripted ISSUE 7 test,
    the SUPERVISOR drives the whole recover→resync pipeline while a
    background writer keeps acking with ``alive=health.mask()``."""
    x, _ = dataset
    ckpt = tmp_path / "base.npz"
    save_index(replicated_r2, ckpt)
    cell = {"mw": wrap_mnmg_mutable(comms8, replicated_r2, delta_cap=64)}
    lock = threading.Lock()
    health = ShardHealth(8, telemetry=False)
    scripted = chaos.ScriptedHealth(8)
    sup = ServingSupervisor(
        health, ReplicaPlacement.of_index(replicated_r2),
        scripted.probe,
        heal=_heal_actions(comms8, cell, lock, health, ckpt),
        monitor=HealthMonitor(8, consecutive=1, cooldown_s=0.0,
                              telemetry=False),
        step_deadline_s=120.0, name="chaos18-race",
    )
    dead = 2
    far = (30.0 * x[:160]).astype(np.float32)
    acked = []
    stop = threading.Event()

    def ingest():
        for i in range(40):
            if stop.is_set():
                break
            ids = np.arange(21000 + 4 * i, 21004 + 4 * i, dtype=np.int64)
            ids = ids.astype(np.int32)
            with lock:
                mw2, acc = mnmg_upsert(
                    comms8, cell["mw"], far[4 * i:4 * i + 4], ids,
                    alive=health.mask(),
                )
                cell["mw"] = mw2
            acked.extend(int(v) for v in ids[np.asarray(acc)])
            time.sleep(0.002)

    writer = threading.Thread(target=ingest, daemon=True)
    writer.start()

    def settle(rank, state, timeout=180.0):
        deadline = time.monotonic() + timeout
        while sup.state(rank) != state and time.monotonic() < deadline:
            sup.step()
            time.sleep(0.002)
        assert sup.state(rank) == state

    sup.step()                                  # healthy baseline tick
    scripted.set(dead, False)                   # kill mid-ingest
    settle(dead, STATE_QUARANTINED)
    time.sleep(0.05)                            # degraded-acked writes
    scripted.set(dead, True)                    # heal mid-ingest
    settle(dead, STATE_SERVING)                 # recover+resync race
    writer.join(timeout=60)
    stop.set()
    assert not writer.is_alive()
    assert len(acked) >= 8, "the run must actually ack writes"
    assert health.all_up and sup.stats().heals_ok == 1

    # EVERY acked write serves from the healthy mesh, coverage 1.0 —
    # each upserted vector is its own query (distance 0 → top-1)
    with lock:
        mw = cell["mw"]
    ids_arr = np.array(sorted(set(acked)), dtype=np.int64)
    rows = far[ids_arr - 21000]
    for s in range(0, len(ids_arr), 12):
        chunk, idc = rows[s:s + 12], ids_arr[s:s + 12]
        pad = np.zeros((12 - chunk.shape[0], chunk.shape[1]), np.float32)
        res = mnmg_mutable_search(
            comms8, mw, np.concatenate([chunk, pad], axis=0), K,
            n_probes=8, qcap=12, shard_mask=health.mask(),
        )
        assert float(np.asarray(res.coverage).min()) == 1.0
        np.testing.assert_array_equal(
            np.asarray(res.ids)[:chunk.shape[0], 0], idc
        )


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_scripted_chaos_schedule_end_to_end(
    comms8, dataset, replicated_r2, tmp_path, monkeypatch
):
    """ISSUE 18 acceptance: the scripted schedule — rank kill
    mid-ingest → straggler burst → heal → oscillating probe — runs
    end-to-end against ONE live open-loop executor with NO manual
    recovery calls: the supervisor detects through the debounced
    monitor, converges the route within its deadline, drives
    recover→resync→reintegrate itself, and every invariant is asserted
    by the checker framework: coverage 1.0 and bit-identity vs the
    healthy mesh whenever the control loop has converged on the
    scripted truth (the detection window is bounded by the convergence
    checker — no system can be correct about a failure it has not yet
    been allowed to detect), zero acked writes lost, zero retraces
    (cache-size audited), route pushes never exceed confirmed
    transitions, and every rank is back to SERVING at drain."""
    from raft_tpu.comms import mnmg_ivf_flat as mod

    x, q = dataset
    qcap = q.shape[0]
    ckpt = tmp_path / "base.npz"
    save_index(replicated_r2, ckpt)
    cell = {"mw": wrap_mnmg_mutable(comms8, replicated_r2, delta_cap=64)}
    lock = threading.Lock()
    health = ShardHealth(8, telemetry=False)
    placement = ReplicaPlacement.of_index(replicated_r2)
    monitor = HealthMonitor(8, consecutive=2, cooldown_s=0.25,
                            telemetry=False)
    scripted = chaos.ScriptedHealth(8)

    created = []
    orig = mod._cached_search

    def recording(*a, **kw):
        fn = orig(*a, **kw)
        created.append(fn)
        return fn

    monkeypatch.setattr(mod, "_cached_search", recording)

    def run(qq, shard_mask=None, failover=None):
        with lock:
            mw = cell["mw"]
        return mnmg_mutable_search(
            comms8, mw, qq, K, n_probes=8, qcap=qcap,
            shard_mask=(shard_mask if shard_mask is not None
                        else np.ones(8, np.int32)),
            failover=failover,
        )

    # healthy reference + warm both bucket shapes BEFORE the audit
    # mark; ingest vectors are pushed 30x out of the data cloud, so
    # the reference answer for q never changes as ingest proceeds
    plan0 = FailoverPlan.load_balanced(placement, health)
    ref = run(jnp.asarray(q), shard_mask=health.mask(), failover=plan0)
    iref, vref = np.asarray(ref.ids), np.asarray(ref.distances)
    for b in (4, qcap):
        jax.block_until_ready(run(
            jnp.zeros((b, q.shape[1]), jnp.float32),
            shard_mask=health.mask(), failover=plan0,
        ))
    fn = created[0]
    size0 = fn._cache_size()

    gate = chaos.StragglerGate(run, every=2, seconds=0.02)
    recorder = FlightRecorder(2048, name="chaos18")
    ex = ServingExecutor(
        gate, (4, qcap), dim=q.shape[1], flush_age_s=0.0,
        max_in_flight=2,
        runtime_inputs={"shard_mask": health.mask(), "failover": plan0},
        flight=recorder,
    )
    sup = ServingSupervisor(
        health, placement, scripted.probe,
        heal=_heal_actions(comms8, cell, lock, health, ckpt),
        monitor=monitor, interval_s=0.004, step_deadline_s=120.0,
        flight=recorder, name="chaos18-e2e",
    )
    sup.register(ex)
    pushes0 = sup.stats().route_pushes          # the register sync push

    dead = 3

    def wreck():
        # the dead rank's slab content is LOST at the kill instant —
        # only the replica and the checkpoint still hold its lists, so
        # bit-identity PROVES the reroute
        with lock:
            mw = cell["mw"]
            wrecked = dataclasses.replace(
                mw.index,
                vectors_sorted=jnp.asarray(mw.index.vectors_sorted)
                .at[dead].set(0),
                sorted_ids=jnp.asarray(mw.index.sorted_ids)
                .at[dead].set(0),
            )
            mw2 = dataclasses.replace(mw, index=wrecked)
            mw2._id_loc = None
            cell["mw"] = mw2

    sched = chaos.ChaosSchedule(scripted=scripted, seed=18)
    sched.kill_rank(0.25, dead, wreck=wreck)
    sched.straggler_window(0.45, gate, duration_s=0.2)
    sched.heal_rank(0.9, dead)
    sched.oscillate(1.6, 5, period_s=0.05, duration_s=0.25)

    far = (30.0 * x[:160]).astype(np.float32)
    acked = []
    results = []
    state = {"i": 0, "tick": 0}

    def ingest_batch():
        i = state["i"]
        if 4 * (i + 1) > far.shape[0]:
            return
        state["i"] = i + 1
        ids = np.arange(20000 + 4 * i, 20004 + 4 * i).astype(np.int32)
        with lock:
            mw2, acc = mnmg_upsert(
                comms8, cell["mw"], far[4 * i:4 * i + 4], ids,
                alive=health.mask(),
            )
            cell["mw"] = mw2
        acked.extend(int(v) for v in ids[np.asarray(acc)])

    def tick(t_s):
        state["tick"] += 1
        sup.step()
        if state["tick"] % 4 == 0:
            ingest_batch()                      # kill lands MID-ingest
        truth = scripted.probe()
        converged = all(monitor.is_up(r) == truth[r] for r in range(8))
        res = ex.submit(q).result(timeout=120)
        results.append((converged, res))

    # -- the declarative invariants (the assertion framework) ---------
    def check_results():
        while results:
            converged, res = results.pop(0)
            if not converged:
                continue
            if float(np.asarray(res.coverage).min()) != 1.0:
                return False
            if not np.array_equal(np.asarray(res.ids), iref):
                return False
            if not np.array_equal(np.asarray(res.distances), vref):
                return False
        return True

    def n_down_confirms():
        return sum(1 for _, e, _ in sup.timeline()
                   if e == "confirmed_down")

    def n_pushes():
        return sup.stats().route_pushes - pushes0

    def no_acked_lost():
        with lock:
            mw = cell["mw"]
        ids_arr = np.array(sorted(set(acked)), dtype=np.int64)
        rows = far[ids_arr - 20000]
        plan = FailoverPlan.load_balanced(placement, health)
        for s in range(0, len(ids_arr), qcap):
            chunk, idc = rows[s:s + qcap], ids_arr[s:s + qcap]
            pad = np.zeros((qcap - chunk.shape[0], chunk.shape[1]),
                           np.float32)
            res = run(jnp.asarray(np.concatenate([chunk, pad], axis=0)),
                      shard_mask=health.mask(), failover=plan)
            if float(np.asarray(res.coverage).min()) != 1.0:
                return False
            if not np.array_equal(
                np.asarray(res.ids)[:chunk.shape[0], 0], idc
            ):
                return False
        return True

    invariants = [
        chaos.AlwaysInvariant(
            "coverage-1-and-bit-identity-when-converged", check_results,
        ),
        chaos.ConvergenceInvariant(
            "route-converges-within-deadline",
            n_down_confirms, n_pushes, deadline_s=1.0,
        ),
        chaos.BoundInvariant(
            "route-pushes-bounded-by-confirmed-transitions",
            lambda: n_pushes() - monitor.transition_count, 0,
        ),
        chaos.BoundInvariant(
            "zero-retraces", lambda: fn._cache_size() - size0, 0,
        ),
        chaos.FinalInvariant("zero-acked-writes-lost", no_acked_lost),
        chaos.FinalInvariant(
            "all-ranks-back-to-serving",
            lambda: health.all_up and all(
                s == STATE_SERVING for s in sup.stats().states.values()
            ),
        ),
    ]
    report = chaos.run_schedule(
        sched, duration_s=4.0, invariants=invariants, tick=tick,
        check_interval_s=0.002,
    )
    ex.close()
    sup.close()
    assert report.ok, report.summary()
    # the schedule really exercised the loop
    assert n_down_confirms() >= 1, "the kill must confirm"
    assert sup.stats().heals_ok >= 1, "the supervisor must reintegrate"
    assert len(acked) >= 8, "ingest must have acked mid-chaos"
    assert state["tick"] >= 10 and gate.audit.calls >= 1
    # zero retraces: the whole run reused the one warmed program object
    assert all(f is fn for f in created), \
        "every dispatch must reuse the cached program object"
    # the postmortem names the supervisor's actions
    assert recorder.events(event="supervisor_route_push")
    assert recorder.events(event="supervisor_heal_step")


# --------------------------------------------------- bench-row smoke
class TestSelfHealRowSmoke:
    def test_self_heal_row_tiny_config(self, dataset):
        """The ISSUE-18 bench row end to end at a tiny CPU config: the
        supervisor-driven kill→reroute→heal cycle under open-loop Zipf
        load must stamp the acceptance evidence — detection_ms,
        route_convergence_ms, reintegration_ms, per-phase p99s — with
        every rank back to SERVING, without erroring."""
        from bench.bench_serving import self_heal_row

        x, q = dataset
        row = self_heal_row(
            np.asarray(x), np.asarray(q), k=K, n_probes=8,
            n_lists=8, request_size=4,
            kill_at_s=0.4, heal_at_s=1.2, duration_s=2.5,
        )
        assert row["scenario"] == "self_heal"
        assert "error" not in row, row.get("error")
        # the acceptance stamps are present and sane
        for key in ("detection_ms", "route_convergence_ms",
                    "reintegration_ms"):
            assert row[key] >= 0.0, (key, row[key])
        # detection precedes (or equals) route convergence by contract
        assert row["route_convergence_ms"] >= row["detection_ms"]
        # the loop really ran: a confirmed down+up, at least one push
        # per confirmed transition but never more
        assert row["transitions"] >= 2
        assert 1 <= row["route_pushes"] <= row["transitions"] + 1
        assert row["heals_ok"] >= 1
        assert row["all_serving"] is True
