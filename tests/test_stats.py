"""Stats tests — host-reference oracle pattern (reference cpp/test/stats/*:
CPU/closed-form expected values + tolerance matchers)."""

import numpy as np
import pytest

from raft_tpu import stats
from raft_tpu.stats import CriterionType


def test_mean_stddev_meanvar(rng_np):
    x = rng_np.standard_normal((200, 7)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(stats.mean(x)), x.mean(0), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(stats.stddev(x)), x.std(0, ddof=1), rtol=1e-4, atol=1e-5
    )
    mu, var = stats.meanvar(x)
    np.testing.assert_allclose(np.asarray(var), x.var(0, ddof=1), rtol=1e-4, atol=1e-5)


def test_minmax_sum(rng_np):
    x = rng_np.standard_normal((50, 4)).astype(np.float32)
    mn, mx = stats.minmax(x)
    np.testing.assert_array_equal(np.asarray(mn), x.min(0))
    np.testing.assert_array_equal(np.asarray(mx), x.max(0))
    np.testing.assert_allclose(np.asarray(stats.sum_(x)), x.sum(0), rtol=1e-5)


@pytest.mark.parametrize("stable", [True, False])
def test_cov(stable, rng_np):
    x = rng_np.standard_normal((300, 5)).astype(np.float32)
    got = np.asarray(stats.cov(x, stable=stable))
    want = np.cov(x, rowvar=False)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


def test_histogram(rng_np):
    x = rng_np.random((1000, 3)).astype(np.float32)
    h = np.asarray(stats.histogram(x, 10, lower=0.0, upper=1.0))
    assert h.shape == (10, 3)
    np.testing.assert_array_equal(h.sum(0), [1000, 1000, 1000])
    for c in range(3):
        want, _ = np.histogram(x[:, c], bins=10, range=(0, 1))
        np.testing.assert_array_equal(h[:, c], want)


def test_weighted_mean(rng_np):
    x = rng_np.standard_normal((40, 6)).astype(np.float32)
    w = rng_np.random(40).astype(np.float32)
    got = np.asarray(stats.col_weighted_mean(x, w))
    want = (x * w[:, None]).sum(0) / w.sum()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    wr = rng_np.random(6).astype(np.float32)
    got = np.asarray(stats.row_weighted_mean(x, wr))
    np.testing.assert_allclose(got, (x * wr[None, :]).sum(1) / wr.sum(), rtol=1e-4, atol=1e-5)


# -- clustering metrics ------------------------------------------------------


def test_contingency_matrix():
    yt = np.array([0, 0, 1, 1, 2, 2])
    yp = np.array([0, 0, 1, 2, 2, 2])
    c = np.asarray(stats.contingency_matrix(yt, yp, 3))
    want = np.array([[2, 0, 0], [0, 1, 1], [0, 0, 2]])
    np.testing.assert_array_equal(c, want)


def naive_ari(yt, yp):
    classes_t = np.unique(yt)
    classes_p = np.unique(yp)
    c = np.array([[(np.logical_and(yt == i, yp == j)).sum() for j in classes_p]
                  for i in classes_t], float)
    comb = lambda x: x * (x - 1) / 2
    sum_c = comb(c).sum()
    a = comb(c.sum(1)).sum()
    b = comb(c.sum(0)).sum()
    n = comb(len(yt))
    exp = a * b / n
    return (sum_c - exp) / ((a + b) / 2 - exp)


def test_adjusted_rand_index(rng_np):
    yt = rng_np.integers(0, 4, 100)
    yp = rng_np.integers(0, 4, 100)
    got = float(stats.adjusted_rand_index(yt, yp, 4))
    np.testing.assert_allclose(got, naive_ari(yt, yp), rtol=1e-4, atol=1e-5)
    # perfect agreement
    np.testing.assert_allclose(float(stats.adjusted_rand_index(yt, yt, 4)), 1.0, atol=1e-5)


def test_rand_index(rng_np):
    yt = rng_np.integers(0, 3, 40)
    yp = rng_np.integers(0, 3, 40)
    got = float(stats.rand_index(yt, yp))
    n = len(yt)
    agree = 0
    for i in range(n):
        for j in range(i + 1, n):
            agree += (yt[i] == yt[j]) == (yp[i] == yp[j])
    np.testing.assert_allclose(got, agree / (n * (n - 1) / 2), rtol=1e-5)


def test_entropy_uniform():
    labels = np.repeat(np.arange(4), 25)
    np.testing.assert_allclose(float(stats.entropy(labels, 4)), np.log(4), rtol=1e-5)


def test_mutual_info_and_vmeasure(rng_np):
    yt = rng_np.integers(0, 3, 200)
    # identical labelings: MI = H, homogeneity = completeness = v = 1
    mi = float(stats.mutual_info_score(yt, yt, 3))
    h = float(stats.entropy(yt, 3))
    np.testing.assert_allclose(mi, h, rtol=1e-4)
    np.testing.assert_allclose(float(stats.v_measure(yt, yt, 3)), 1.0, atol=1e-5)
    np.testing.assert_allclose(float(stats.homogeneity_score(yt, yt, 3)), 1.0, atol=1e-5)
    # independent labelings have low v-measure
    yp = rng_np.integers(0, 3, 200)
    assert float(stats.v_measure(yt, yp, 3)) < 0.2


def naive_silhouette(x, labels):
    n = len(x)
    d = np.sqrt(((x[:, None, :] - x[None, :, :]) ** 2).sum(-1))
    s = np.zeros(n)
    for i in range(n):
        own = labels == labels[i]
        if own.sum() > 1:
            a = d[i][own & (np.arange(n) != i)].mean()
        else:
            s[i] = 0.0
            continue
        b = np.inf
        for c in np.unique(labels):
            if c == labels[i]:
                continue
            mask = labels == c
            if mask.any():
                b = min(b, d[i][mask].mean())
        s[i] = (b - a) / max(a, b)
    return s


def test_silhouette(rng_np):
    x = np.concatenate(
        [rng_np.standard_normal((30, 4)) + 5, rng_np.standard_normal((30, 4)) - 5]
    ).astype(np.float32)
    labels = np.repeat([0, 1], 30)
    got = np.asarray(stats.silhouette_samples(x, labels, 2))
    want = naive_silhouette(x, labels)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)
    score = float(stats.silhouette_score(x, labels, 2))
    np.testing.assert_allclose(score, want.mean(), rtol=1e-3)
    batched = float(stats.batched_silhouette_score(x, labels, 2, batch_size=16))
    np.testing.assert_allclose(batched, want.mean(), rtol=1e-3)


def test_dispersion(rng_np):
    cents = rng_np.standard_normal((4, 3)).astype(np.float32)
    sizes = np.array([10, 20, 30, 40], np.int32)
    disp, gc = stats.dispersion(cents, sizes)
    mu = (cents * sizes[:, None]).sum(0) / sizes.sum()
    want = np.sqrt((sizes * ((cents - mu) ** 2).sum(1)).sum())
    np.testing.assert_allclose(float(disp), want, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gc), mu, rtol=1e-5)


def test_kl_divergence():
    p = np.array([0.5, 0.3, 0.2], np.float32)
    q = np.array([0.4, 0.4, 0.2], np.float32)
    want = (p * np.log(p / q)).sum()
    np.testing.assert_allclose(float(stats.kl_divergence(p, q)), want, rtol=1e-5)


# -- regression / IC ---------------------------------------------------------


def test_accuracy_r2(rng_np):
    a = rng_np.integers(0, 2, 100)
    np.testing.assert_allclose(float(stats.accuracy(a, a)), 1.0)
    y = rng_np.standard_normal(100).astype(np.float32)
    yh = y + 0.1 * rng_np.standard_normal(100).astype(np.float32)
    got = float(stats.r2_score(y, yh))
    want = 1 - ((y - yh) ** 2).sum() / ((y - y.mean()) ** 2).sum()
    np.testing.assert_allclose(got, want, rtol=1e-3)


def test_regression_metrics(rng_np):
    p = rng_np.standard_normal(50).astype(np.float32)
    r = rng_np.standard_normal(50).astype(np.float32)
    m = stats.regression_metrics(p, r)
    np.testing.assert_allclose(float(m.mean_abs_error), np.abs(p - r).mean(), rtol=1e-5)
    np.testing.assert_allclose(float(m.mean_squared_error), ((p - r) ** 2).mean(), rtol=1e-5)
    np.testing.assert_allclose(float(m.median_abs_error), np.median(np.abs(p - r)), rtol=1e-5)


def test_information_criterion():
    ll = np.array([-100.0, -50.0], np.float32)
    aic = np.asarray(stats.information_criterion(ll, CriterionType.AIC, 3, 1000))
    np.testing.assert_allclose(aic, -2 * ll + 6)
    bic = np.asarray(stats.information_criterion(ll, CriterionType.BIC, 3, 1000))
    np.testing.assert_allclose(bic, -2 * ll + 3 * np.log(1000), rtol=1e-6)


def test_trustworthiness_perfect_embedding(rng_np):
    x = rng_np.standard_normal((60, 8)).astype(np.float32)
    t = float(stats.trustworthiness_score(x, x, n_neighbors=5))
    np.testing.assert_allclose(t, 1.0, atol=1e-5)
    # random embedding scores lower
    bad = rng_np.standard_normal((60, 2)).astype(np.float32)
    assert float(stats.trustworthiness_score(x, bad, n_neighbors=5)) < 0.95


def test_mean_center_and_add(rng_np):
    from raft_tpu.stats import mean_center, mean_add, mean

    x = rng_np.standard_normal((20, 7)).astype(np.float32)
    c = np.asarray(mean_center(x))
    np.testing.assert_allclose(c.mean(axis=0), 0.0, atol=1e-5)
    np.testing.assert_allclose(c, x - x.mean(0, keepdims=True), rtol=1e-5)
    back = np.asarray(mean_add(c, mean(x, axis=0)))
    np.testing.assert_allclose(back, x, rtol=1e-5, atol=1e-6)
    # row centering (bcastAlongRows=False analog)
    cr = np.asarray(mean_center(x, axis=1))
    np.testing.assert_allclose(cr.mean(axis=1), 0.0, atol=1e-5)


def test_mean_center_3d(rng_np):
    from raft_tpu.stats import mean_center

    x = rng_np.standard_normal((2, 3, 4)).astype(np.float32)
    for axis in (0, 1, 2):
        c = np.asarray(mean_center(x, axis=axis))
        np.testing.assert_allclose(c.mean(axis=axis), 0.0, atol=1e-5)
