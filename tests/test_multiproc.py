"""Real multi-process distributed bring-up test — the analog of the
reference's Dask/NCCL cluster test (python/raft/raft/test/test_comms.py:
200-336 over a LocalCUDACluster): spawn separate OS processes, rendezvous
through ``jax.distributed`` (the NCCL-uniqueId analog), run the
communicator self-tests and a distributed k-means on every rank, and
assert all ranks agree.

Each worker process owns 2 virtual CPU devices, so collectives cross a REAL
process boundary (gloo), not just a single-process virtual mesh — this is
the coverage the in-process tests in test_comms.py cannot provide.
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
WORKER = os.path.join(HERE, "multiproc_worker.py")
N_PROCS = 2
TIMEOUT_S = 420


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def worker_reports():
    # one retry on a fresh port: the gloo/coordination-service bring-up
    # can flake on a loaded 1-core host (heartbeat timeout while a worker
    # is stuck in a long XLA compile) — a real failure fails both rounds
    # and surfaces both workers' stderr
    try:
        return _spawn_and_collect()
    except AssertionError as first:
        try:
            return _spawn_and_collect()
        except AssertionError as second:
            raise AssertionError(
                f"bring-up failed twice.\n-- first attempt --\n{first}\n"
                f"-- second attempt --\n{second}"
            ) from second


def _spawn_and_collect():
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
    }
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, coord, str(N_PROCS), str(r)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )
        for r in range(N_PROCS)
    ]
    # Supervise ALL workers against ONE shared deadline (ISSUE 3): the
    # old per-rank communicate(timeout=420) serialized the budgets — a
    # worker hanging after its sibling finished late could strand the
    # fixture for up to N x 420 s — and a fast nonzero exit left the
    # survivor blocking inside a collective until ITS timeout. Now the
    # first failure (nonzero exit or deadline) kills every survivor
    # immediately. Every failure mode must still surface worker stderr
    # in the assertion: a bare TimeoutExpired/IndexError here cost a
    # triage round-trip when the shard_map AttributeError first broke
    # the workers.
    #
    # Pipes are drained CONCURRENTLY by reader threads: a worker whose
    # XLA/jax warnings exceed the OS pipe buffer would otherwise block
    # in write() and be falsely reported as hung.
    chunks = {(r, s): [] for r in range(N_PROCS) for s in ("out", "err")}

    def _drain(rank, stream_name, stream):
        chunks[(rank, stream_name)].append(stream.read())

    readers = [
        threading.Thread(
            target=_drain, args=(r, name, stream), daemon=True
        )
        for r, p in enumerate(procs)
        for name, stream in (("out", p.stdout), ("err", p.stderr))
    ]
    for t in readers:
        t.start()
    deadline = time.monotonic() + TIMEOUT_S
    failed_rank = None
    timed_out = []
    try:
        pending = set(range(N_PROCS))
        while pending:
            for rank in sorted(pending):
                if procs[rank].poll() is not None:
                    pending.discard(rank)
                    if procs[rank].returncode != 0 and failed_rank is None:
                        failed_rank = rank
                        # a dead rank wedges its peers inside the next
                        # collective — kill them NOW, not at the deadline
                        for q in procs:
                            if q.poll() is None:
                                q.kill()
            if pending and time.monotonic() > deadline:
                timed_out = sorted(pending)
                for q in procs:
                    if q.poll() is None:
                        q.kill()
                break
            time.sleep(0.05)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for t in readers:
        t.join(timeout=30)  # EOF follows process death
    for p in procs:
        p.wait()
    outs = [
        (
            "".join(chunks[(r, "out")]),
            "".join(chunks[(r, "err")]),
        )
        for r in range(N_PROCS)
    ]

    def tails(rank):
        out, err = outs[rank]
        return (
            f"stderr:\n{err[-4000:]}\nstdout tail:\n{out[-1000:]}"
        )

    if timed_out:
        raise AssertionError(
            f"workers {timed_out} timed out after {TIMEOUT_S}s "
            f"(survivors killed);\n"
            + "\n".join(f"-- worker {r} --\n{tails(r)}" for r in timed_out)
        )
    if failed_rank is not None:
        raise AssertionError(
            f"worker {failed_rank} exited "
            f"{procs[failed_rank].returncode} (survivors killed);\n"
            f"{tails(failed_rank)}"
        )
    for rank, p in enumerate(procs):
        assert p.returncode == 0, (
            f"worker {rank} exited {p.returncode}; {tails(rank)}"
        )
    reports = []
    for rank, (out, err) in enumerate(outs):
        json_lines = [ln for ln in out.splitlines() if ln.startswith("{")]
        assert json_lines, (
            f"worker {rank} exited 0 but emitted no JSON report; stdout:\n"
            f"{out[-2000:]}\nstderr tail:\n{err[-2000:]}"
        )
        reports.append(json.loads(json_lines[-1]))
    return sorted(reports, key=lambda r: r["rank"])


def test_cluster_bringup(worker_reports):
    assert [r["rank"] for r in worker_reports] == list(range(N_PROCS))
    for r in worker_reports:
        assert r["process_count"] == N_PROCS
        assert r["global_devices"] == 2 * N_PROCS


def test_collective_self_tests_pass_on_all_ranks(worker_reports):
    for r in worker_reports:
        failed = [name for name, ok in r["self_tests"].items() if not ok]
        assert not failed, f"rank {r['rank']} failed: {failed}"


def test_mnmg_kmeans_agrees_across_processes(worker_reports):
    inertias = [r["inertia"] for r in worker_reports]
    sums = [r["centroid_sum"] for r in worker_reports]
    iters = [r["n_iter"] for r in worker_reports]
    assert max(inertias) - min(inertias) < 1e-3 * max(abs(inertias[0]), 1.0)
    assert max(sums) - min(sums) < 1e-3 * max(abs(sums[0]), 1.0)
    assert len(set(iters)) == 1
    # sanity: 4 well-separated blobs -> inertia far below total variance
    assert inertias[0] > 0.0


def test_mnmg_ivf_pq_across_processes(worker_reports):
    """Sharded IVF-PQ under real multi-process jax.distributed: every
    rank must return exact self-neighbors and the identical merged ids
    (replicated outputs agree across the process boundary)."""
    for r in worker_reports:
        assert r["ivf_self_recall"] is True, r
    id_sums = {r["ivf_ids_sum"] for r in worker_reports}
    assert len(id_sums) == 1, id_sums


def test_distributed_build_per_rank_rows_across_processes(worker_reports):
    """Each process feeds ONLY its own devices' row shards to
    mnmg_ivf_pq_build_distributed; the index must search identically to
    the one-host wrapper build (VERDICT r4 item 1 'done' criterion)."""
    for r in worker_reports:
        assert r["ivf_dist_build_matches"] is True, r


def test_mnmg_ivf_flat_across_processes(worker_reports):
    """Sharded IVF-Flat under real multi-process jax.distributed: exact
    scoring returns exact self-neighbors on every rank."""
    for r in worker_reports:
        assert r["ivf_flat_self_exact"] is True, r


def test_hierarchical_merge_across_processes(worker_reports):
    """ISSUE 9 satellite: the 2-level HierarchicalComms carries a real
    workload across the REAL process boundary — the worker builds the
    (num_procs, 2) mesh whose dcn axis is the process split, runs the
    two-stage hierarchical merge end-to-end, and its (dists, ids) must
    be bit-identical to the single-host flat-merge program on the same
    data, with all ranks agreeing on the merged ids."""
    for r in worker_reports:
        assert r["hier_merge_matches_flat"] is True, r
    assert len({r["hier_merge_ids_sum"] for r in worker_reports}) == 1


def test_hierarchical_allreduce_pad_across_processes(worker_reports):
    """The pad-and-slice hierarchical_allreduce fix holds over real DCN:
    an odd leading dim reduces to the plain psum result on every rank."""
    for r in worker_reports:
        assert r["hier_allreduce_pad_ok"] is True, r
