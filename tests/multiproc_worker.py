"""Worker entry for the real multi-process distributed test — the analog of
a pyraft Dask worker in the reference's MNMG test
(python/raft/raft/test/test_comms.py:200-336: every worker runs
``perform_test_comms_*`` and the driver asserts all ranks return True).

Invoked as: python multiproc_worker.py <coordinator> <num_procs> <rank>

Forces the virtual CPU platform (2 local devices per process) and the gloo
cross-process collectives backend BEFORE jax initializes, bootstraps the
cluster via ``Comms.initialize_distributed`` (the Dask/NCCL-uniqueId
rendezvous analog, reference comms.py:171-218 + nccl.pyx:52-57), then:

  1. runs every communicator round-trip self-test (comms/detail/test.hpp
     analog) on the 2x2-device global mesh;
  2. fits a small distributed k-means on a shared deterministic dataset;
  3. builds + searches a list-sharded IVF-PQ index across the processes
     (the DEEP-100M layout of comms/mnmg_ivf.py under REAL multi-host
     jax.distributed, not just the single-process virtual mesh);

and prints one JSON line with the results. The pytest driver
(test_multiproc.py) spawns N of these and asserts cross-process agreement.
"""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=2"
)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    coordinator, num_procs, rank = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    )

    from raft_tpu.comms import Comms, build_comms, mnmg_kmeans_fit
    from raft_tpu.comms.self_test import run_all_self_tests

    Comms.initialize_distributed(coordinator, num_procs, rank)
    assert jax.process_count() == num_procs

    comms = build_comms()  # all global devices: num_procs x 2
    self_tests = {k: bool(v) for k, v in run_all_self_tests(comms).items()}

    # identical dataset on every rank (the reference's Dask test scatters
    # from the client; here the shared seed plays that role)
    rng = np.random.default_rng(7)
    x = (
        rng.standard_normal((512, 8)).astype(np.float32)
        + 8.0 * rng.integers(0, 4, (512, 1)).astype(np.float32)
    )
    out = mnmg_kmeans_fit(comms, x, n_clusters=4, max_iter=20, seed=3)

    # sharded IVF-PQ across the REAL process boundary: every rank holds
    # the same host dataset (shared seed = the Dask client-scatter role);
    # device_put scatters each rank's slab shards to its local devices
    from raft_tpu.comms import mnmg_ivf_pq_build, mnmg_ivf_pq_search
    from raft_tpu.spatial.ann import IVFPQParams

    ivf_params = IVFPQParams(
        n_lists=8, pq_dim=4, pq_bits=6, kmeans_n_iters=4, seed=0,
    )
    idx = mnmg_ivf_pq_build(comms, x, ivf_params)
    dq, iq = mnmg_ivf_pq_search(
        comms, idx, x[:16], 3, n_probes=8, refine_ratio=4.0, qcap=16,
    )
    iq_np = np.asarray(iq)
    ivf_self = bool((iq_np[:, 0] == np.arange(16)).all())

    # the per-rank build path under REAL process boundaries: each process
    # device_puts ONLY the row shards of its own devices (the true
    # distributed data model — no process ever assembles the full
    # dataset), and the resulting index must search identically to the
    # one-host wrapper build above (same pipeline, same global ids)
    from raft_tpu.comms.mnmg_ivf import (
        mnmg_ivf_pq_build_distributed, shard_rows,
    )

    # shard_rows device_puts ONLY this process's devices' shards — each
    # process transfers its local rows and nothing else crosses the host
    xg, n_valid = shard_rows(comms, x)
    idx2 = mnmg_ivf_pq_build_distributed(
        comms, xg, ivf_params, n_valid=n_valid
    )
    dq2, iq2 = mnmg_ivf_pq_search(
        comms, idx2, x[:16], 3, n_probes=8, refine_ratio=4.0, qcap=16,
    )
    dist_matches_wrapper = bool(
        (np.asarray(iq2) == iq_np).all()
        and np.allclose(np.asarray(dq2), np.asarray(dq), rtol=1e-5)
    )

    # sharded IVF-Flat across the same process boundary: exact scoring,
    # so full-probe self-search must return exact self-neighbors
    from raft_tpu.comms import mnmg_ivf_flat_build, mnmg_ivf_flat_search
    from raft_tpu.spatial.ann import IVFFlatParams

    fidx = mnmg_ivf_flat_build(
        comms, x, IVFFlatParams(n_lists=8, kmeans_n_iters=4, seed=0),
        metric="sqeuclidean",
    )
    df, jf = mnmg_ivf_flat_search(
        comms, fidx, x[:16], 3, n_probes=8, qcap=16,
    )
    flat_self = bool(
        (np.asarray(jf)[:, 0] == np.arange(16)).all()
        and float(np.asarray(df)[:, 0].max()) < 1e-2
    )

    # the cross-host serving tier under REAL process boundaries
    # (ISSUE 9): the 2-level mesh's outer (dcn) axis IS the process
    # boundary here — global devices order process-major, so
    # mesh_shape=(num_procs, 2) puts each process's 2 local devices in
    # one slice and the hierarchical merge's DCN stage crosses gloo, not
    # just a virtual in-process mesh. Same index, same queries: the
    # two-stage merge with the uncompressed wire must return the flat
    # program's (dists, ids) bit-identically on every rank.
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from raft_tpu.comms import build_comms_hierarchical, place_index

    hier = build_comms_hierarchical(mesh_shape=(num_procs, 2))
    hidx = place_index(hier, fidx)
    dh, jh = mnmg_ivf_flat_search(
        hier, hidx, x[:16], 3, n_probes=8, qcap=16, wire="f32",
    )
    hier_matches = bool(
        (np.asarray(jh) == np.asarray(jf)).all()
        and (np.asarray(dh) == np.asarray(df)).all()
    )

    # the padded hierarchical_allreduce (ISSUE 9 satellite) across the
    # same real DCN boundary: odd leading dim, every device agrees on
    # the plain psum result
    def _allred(v):
        return hier.hierarchical_allreduce(v)

    fn = jax.jit(hier.shard_map(
        _allred, in_specs=P(None, None), out_specs=P(None, None),
    ))
    v = np.arange(7 * 3, dtype=np.float32).reshape(7, 3)
    width = float(len(jax.devices()))
    hier_allreduce_ok = bool(np.allclose(
        np.asarray(fn(jnp.asarray(v))), width * v, rtol=1e-5,
    ))

    print(json.dumps({
        "rank": rank,
        "process_count": jax.process_count(),
        "global_devices": len(jax.devices()),
        "self_tests": self_tests,
        "inertia": float(out.inertia),
        "n_iter": int(out.n_iter),
        "centroid_sum": float(np.asarray(out.centroids, np.float64).sum()),
        "ivf_self_recall": ivf_self,
        "ivf_ids_sum": int(iq_np.sum()),
        "ivf_dist_build_matches": dist_matches_wrapper,
        "ivf_flat_self_exact": flat_self,
        "hier_merge_matches_flat": hier_matches,
        "hier_merge_ids_sum": int(np.asarray(jh).sum()),
        "hier_allreduce_pad_ok": hier_allreduce_ok,
    }), flush=True)


if __name__ == "__main__":
    main()
