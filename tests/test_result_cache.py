"""Hot-traffic shaping suite (ISSUE 15, docs/serving.md "Hot traffic"):
the semantic result cache, request coalescing, mutation-epoch
invalidation, and popularity-aware replication — all on CPU with tiny
indexes, asserting BEHAVIOR (a stale entry can never serve, a coalesced
caller gets exactly its rows, route flips stay runtime values), never
QPS. Also the direct :class:`raft_tpu.cache.VectorCache` coverage the
cache had been missing (it was only exercised through
test_label_lap_cache_spectral.py). Runs fail-fast in ci/run.sh next to
the obs smoke: the cache fronts every serving dispatch, so a
correctness bug here poisons every later serving measurement."""

import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.cache import VectorCache
from raft_tpu.resilience import (
    FailoverPlan,
    HedgePolicy,
    ReplicaPlacement,
    measured_shard_load,
    popularity_replication,
    record_shard_load,
)
from raft_tpu.obs import metrics as obsm
from raft_tpu.obs.flight import FlightRecorder
from raft_tpu.serving import (
    CentroidSigner,
    ExecutorStats,
    ResultCache,
    ServingExecutor,
    semantic_recall,
)
from raft_tpu.serving.result_cache import exact_signatures
from raft_tpu.spatial.ann import IVFFlatParams, ivf_flat_build
from raft_tpu.spatial.ann.ivf_flat import (
    _grouped_impl,
    ivf_flat_search_grouped,
)
from raft_tpu.spatial.ann.mutation import (
    compact,
    delete as mut_delete,
    mutable_search,
    mutable_warmup,
    upsert as mut_upsert,
    wrap_mutable,
)
from raft_tpu.testing import faults

D = 8
K = 4
N_PROBES = 4


# ------------------------------------------------- VectorCache, directly
class TestVectorCache:
    def test_round_trip_and_found_mask(self):
        c = VectorCache(3, n_sets=4, associativity=2)
        c.store_vecs([1, 2], np.array([[1., 2., 3.], [4., 5., 6.]],
                                      np.float32))
        vecs, found = c.get_vecs([1, 2, 9])
        assert np.asarray(found).tolist() == [True, True, False]
        np.testing.assert_array_equal(np.asarray(vecs)[0], [1., 2., 3.])
        np.testing.assert_array_equal(np.asarray(vecs)[2], 0.0)
        assert c.n_cached == 2

    def test_associativity_collision_evicts_lru(self):
        """Three keys in ONE set of a 2-way cache: the least-recently
        USED lane is the victim (a get touches its entry's clock)."""
        c = VectorCache(1, n_sets=2, associativity=2)
        c.store_vecs([0], np.array([[10.0]], np.float32))   # set 0
        c.store_vecs([2], np.array([[12.0]], np.float32))   # set 0
        _ = c.get_vecs([0])       # touch key 0 -> key 2 is now LRU
        c.store_vecs([4], np.array([[14.0]], np.float32))   # evicts 2
        _, found = c.get_vecs([0, 2, 4])
        assert np.asarray(found).tolist() == [True, False, True]

    def test_insertion_order_eviction_without_touch(self):
        c = VectorCache(1, n_sets=2, associativity=2)
        c.store_vecs([0], np.array([[10.0]], np.float32))
        c.store_vecs([2], np.array([[12.0]], np.float32))
        c.store_vecs([4], np.array([[14.0]], np.float32))   # evicts 0
        _, found = c.get_vecs([0, 2, 4])
        assert np.asarray(found).tolist() == [False, True, True]

    def test_same_set_distinct_keys_one_call_all_stored(self):
        """Distinct keys colliding on one SET within a single
        store_vecs call claim distinct LRU lanes (the reference
        assign_cache_idx contract) — the old same-victim overwrite
        silently dropped a row, which made a colliding request
        permanently uncacheable in the result cache."""
        c = VectorCache(1, n_sets=2, associativity=4)
        keys = np.array([0, 2, 4, 6])              # all map to set 0
        c.store_vecs(keys, np.arange(4, dtype=np.float32)[:, None])
        vecs, found = c.get_vecs(keys)
        assert np.asarray(found).all()
        np.testing.assert_array_equal(
            np.asarray(vecs).ravel(), [0.0, 1.0, 2.0, 3.0])
        # beyond the associativity the ranks wrap (still a cache, no
        # crash; the overflowed rows overwrite from the LRU end)
        c2 = VectorCache(1, n_sets=2, associativity=2)
        c2.store_vecs(np.array([0, 2, 4]),
                      np.arange(3, dtype=np.float32)[:, None])
        _, f2 = c2.get_vecs(np.array([0, 2, 4]))
        assert np.asarray(f2).sum() == 2

    def test_evict_absent_key_is_noop(self):
        c = VectorCache(2, n_sets=4, associativity=2)
        c.store_vecs([3], np.array([[1.0, 2.0]], np.float32))
        c.evict([7])               # same set as 3, absent
        c.evict([100])             # different set, absent
        vecs, found = c.get_vecs([3])
        assert bool(np.asarray(found)[0])
        np.testing.assert_array_equal(np.asarray(vecs)[0], [1.0, 2.0])
        c.evict([3])
        _, found = c.get_vecs([3])
        assert not bool(np.asarray(found)[0])
        assert c.n_cached == 0

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.int32,
                                       jnp.bfloat16])
    def test_dtype_round_trip(self, dtype):
        c = VectorCache(4, n_sets=4, associativity=2, dtype=dtype)
        if dtype == jnp.int32:
            v = np.array([[-(2 ** 31) + 5, -1, 0, 2 ** 31 - 1]],
                         np.int32)
        elif dtype == jnp.bfloat16:
            v = np.array([[1.0, -2.0, 0.5, 128.0]], np.float32)
        else:
            v = np.array([[1e-38, -np.inf, 3.5, 1e38]], np.float32)
        c.store_vecs([5], jnp.asarray(v, dtype))
        out, found = c.get_vecs([5])
        assert bool(np.asarray(found)[0])
        assert out.dtype == jnp.dtype(dtype)
        np.testing.assert_array_equal(
            np.asarray(out, np.float64 if dtype != jnp.int32 else None),
            np.asarray(jnp.asarray(v, dtype), out.dtype),
        )

    def test_shape_round_trip_and_update_in_place(self):
        c = VectorCache(2, n_sets=2, associativity=2)
        c.store_vecs([1], np.array([[1.0, 2.0]], np.float32))
        c.store_vecs([1], np.array([[9.0, 8.0]], np.float32))  # update
        vecs, _ = c.get_vecs([1])
        assert np.asarray(vecs).shape == (1, 2)
        np.testing.assert_array_equal(np.asarray(vecs)[0], [9.0, 8.0])
        assert c.n_cached == 1     # updated the slot, not a second one


# --------------------------------------------------------- signatures
class TestSignatures:
    def test_exact_signature_content_keyed(self):
        rng = np.random.default_rng(0)
        q = rng.standard_normal((4, D)).astype(np.float32)
        s1 = exact_signatures(q)
        s2 = exact_signatures(q.copy())
        np.testing.assert_array_equal(s1, s2)
        s3 = exact_signatures(q + 1e-7)       # any bit flip re-keys
        assert not np.array_equal(s1, s3)
        assert not np.array_equal(exact_signatures(q, b"k4"),
                                  exact_signatures(q, b"k8"))

    def test_centroid_signer_sorted_and_stable(self):
        rng = np.random.default_rng(1)
        sc = rng.standard_normal((16, D)).astype(np.float32)
        signer = CentroidSigner(sc, n_probes=3)
        q = rng.standard_normal((5, D)).astype(np.float32)
        ids = signer.super_ids(q)
        assert ids.shape == (5, 3)
        assert (np.diff(ids, axis=1) > 0).all()     # sorted, distinct
        np.testing.assert_array_equal(ids, signer.super_ids(q.copy()))
        # a tiny perturbation keeps the semantic signature
        np.testing.assert_array_equal(
            signer(q), signer(q + 1e-6))

    def test_signer_n_probes_clamped(self):
        sc = np.eye(3, D, dtype=np.float32)
        signer = CentroidSigner(sc, n_probes=10)
        assert signer.super_ids(np.zeros((1, D), np.float32)).shape == \
            (1, 3)


# ------------------------------------------------------- ResultCache unit
def _mk_results(m, k=K, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((m, k)).astype(np.float32),
            rng.integers(0, 10 ** 6, (m, k)).astype(np.int32))


class TestResultCache:
    def test_insert_lookup_exact_round_trip(self):
        rc = ResultCache(K, n_sets=32, name="t_rt",
                         registry=obsm.MetricRegistry())
        rng = np.random.default_rng(2)
        q = rng.standard_normal((3, D)).astype(np.float32)
        d, i = _mk_results(3)
        d[0, 0] = np.inf           # distance BITS round-trip exactly
        d[1, 1] = 1e-38
        assert rc.lookup(q, epoch=0) is None
        rc.insert(q, d, i, epoch=0)
        out = rc.lookup(q, epoch=0)
        assert out is not None
        np.testing.assert_array_equal(out[0], d)
        np.testing.assert_array_equal(out[1], i)
        st = rc.stats()
        assert st.hits == 3 and st.misses == 3 and st.inserts == 3
        assert st.hit_rate == pytest.approx(0.5)

    def test_epoch_mismatch_is_stale_then_evicted(self):
        rc = ResultCache(K, n_sets=32, name="t_epoch",
                         registry=obsm.MetricRegistry())
        q = np.ones((2, D), np.float32)
        d, i = _mk_results(2)
        rc.insert(q, d, i, epoch=3)
        assert rc.lookup(q, epoch=3) is not None
        assert rc.lookup(q, epoch=4) is None        # stale
        st = rc.stats()
        assert st.stale == 2
        # the stale entries died: a second epoch-4 lookup is a clean
        # miss (no second stale count), and re-inserting at 4 serves
        assert rc.lookup(q, epoch=4) is None
        assert rc.stats().stale == 2
        rc.insert(q, d, i, epoch=4)
        assert rc.lookup(q, epoch=4) is not None

    def test_partial_hit_is_a_miss(self):
        rc = ResultCache(K, n_sets=32, name="t_part",
                         registry=obsm.MetricRegistry())
        rng = np.random.default_rng(3)
        q = rng.standard_normal((2, D)).astype(np.float32)
        d, i = _mk_results(2)
        rc.insert(q[:1], d[:1], i[:1], epoch=0)
        assert rc.lookup(q, epoch=0) is None       # row 1 missing

    def test_insert_shape_validated(self):
        rc = ResultCache(K, name="t_shape",
                         registry=obsm.MetricRegistry())
        q = np.ones((2, D), np.float32)
        d, i = _mk_results(2, k=K + 1)
        with pytest.raises(ValueError):
            rc.insert(q, d, i, epoch=0)

    def test_semantic_tier_gated_and_served(self):
        rng = np.random.default_rng(4)
        sc = rng.standard_normal((8, D)).astype(np.float32)
        signer = CentroidSigner(sc, n_probes=2)
        rc = ResultCache(K, n_sets=32, signer=signer, name="t_sem",
                         registry=obsm.MetricRegistry())
        q = rng.standard_normal((2, D)).astype(np.float32)
        near = q + 1e-5            # same super ids, different bytes
        assert np.array_equal(signer(q), signer(near))
        d, i = _mk_results(2)
        rc.insert(q, d, i, epoch=0)
        # disabled by default: near-duplicate misses
        assert not rc.semantic_enabled
        assert rc.lookup(near, epoch=0) is None
        rc.semantic_enabled = True
        out = rc.lookup(near, epoch=0)
        assert out is not None
        np.testing.assert_array_equal(out[1], i)
        assert rc.stats().semantic_hits == 2
        # epoch invalidation applies to the semantic tier too
        assert rc.lookup(near, epoch=1) is None

    def test_calibrate_semantic_guardrail(self):
        rng = np.random.default_rng(5)
        sc = rng.standard_normal((4, D)).astype(np.float32)
        signer = CentroidSigner(sc, n_probes=1)

        def search_same(rows):
            m = rows.shape[0]
            ids = np.tile(np.arange(K, dtype=np.int32), (m, 1))
            return np.zeros((m, K), np.float32), ids

        rc = ResultCache(K, signer=signer, name="t_cal",
                         registry=obsm.MetricRegistry())
        # colliding queries whose fresh results agree -> recall 1.0
        base = rng.standard_normal((1, D)).astype(np.float32)
        sample = np.concatenate([base + 1e-5 * j for j in range(4)])
        assert rc.calibrate_semantic(sample, search_same) is True
        assert rc.measured_semantic_recall == pytest.approx(1.0)
        assert rc.semantic_enabled

        def search_disjoint(rows):
            m = rows.shape[0]
            ids = (np.arange(m, dtype=np.int32)[:, None] * K
                   + np.arange(K, dtype=np.int32)[None, :])
            return np.zeros((m, K), np.float32), ids

        rc2 = ResultCache(K, signer=signer, name="t_cal2",
                          registry=obsm.MetricRegistry())
        assert rc2.calibrate_semantic(sample, search_disjoint) is False
        assert rc2.measured_semantic_recall == pytest.approx(0.0)
        assert not rc2.semantic_enabled
        # no colliding pair in the sample: recall unmeasurable, OFF
        spread = np.asarray(sc) * 100.0
        rc3 = ResultCache(K, signer=signer, name="t_cal3",
                          registry=obsm.MetricRegistry())
        assert rc3.calibrate_semantic(spread, search_same) is False
        assert rc3.measured_semantic_recall is None

    def test_semantic_recall_helper_counts_pairs(self):
        sc = np.eye(2, D, dtype=np.float32)
        signer = CentroidSigner(sc, n_probes=1)
        q = np.stack([sc[0], sc[0] * 1.001, sc[1]]).astype(np.float32)

        def search(rows):
            m = rows.shape[0]
            return (np.zeros((m, K), np.float32),
                    np.tile(np.arange(K, dtype=np.int32), (m, 1)))

        r = semantic_recall(q, search, signer, K)
        assert r == pytest.approx(1.0)

    def test_counters_land_in_registry(self):
        reg = obsm.MetricRegistry()
        rc = ResultCache(K, name="t_reg", registry=reg)
        q = np.ones((1, D), np.float32)
        d, i = _mk_results(1)
        rc.lookup(q, epoch=0)
        rc.insert(q, d, i, epoch=0)
        rc.lookup(q, epoch=0)
        vals = {
            tuple(sorted(s.labels.items())): s.value
            for s in reg.series("serving_result_cache_total")
        }
        assert vals[(("cache", "t_reg"), ("result", "hit"))] == 1
        assert vals[(("cache", "t_reg"), ("result", "miss"))] == 1


# --------------------------------------------- executor: cache + coalesce
@pytest.fixture(scope="module")
def tiny_serving():
    """A tiny warmed IVF-Flat serving setup at one shared qcap (the
    test_open_loop fixture recipe, rebuilt here so this suite stays
    importable fail-fast on its own)."""
    rng = np.random.default_rng(17)
    x = rng.standard_normal((2048, D)).astype(np.float32)
    idx = ivf_flat_build(x, IVFFlatParams(n_lists=8, kmeans_n_iters=3,
                                          seed=2))
    qcap = 32
    for b in (4, 8):
        idx.warmup(b, k=K, n_probes=N_PROBES, qcap=qcap)

    def dispatch(batch, **_rt):
        return ivf_flat_search_grouped(
            idx, batch, K, n_probes=N_PROBES, qcap=qcap,
        )

    q = rng.standard_normal((32, D)).astype(np.float32)
    return idx, dispatch, q


def _wait(pred, timeout_s=10.0):
    t0 = time.monotonic()
    while not pred():
        assert time.monotonic() - t0 < timeout_s, "timed out"
        time.sleep(0.002)


class TestExecutorResultCache:
    def test_repeat_query_served_from_cache_zero_retrace(self,
                                                         tiny_serving):
        """The hot-query path: an identical re-submit is answered from
        the cache with the bitwise result of the first dispatch, no new
        batch, no new compile (cache on/off touches no program)."""
        idx, dispatch, q, = tiny_serving
        warmed = _grouped_impl._cache_size()
        rc = ResultCache(K, name="ex_hit", registry=obsm.MetricRegistry())
        ex = ServingExecutor(dispatch, (4, 8), dim=D, flush_age_s=0.0,
                             result_cache=rc)
        r1 = ex.submit(q[:2]).result(timeout=30)
        _wait(lambda: rc.stats().inserts >= 2)
        r2 = ex.submit(q[:2]).result(timeout=30)
        np.testing.assert_array_equal(np.asarray(r1[0]), r2[0])
        np.testing.assert_array_equal(np.asarray(r1[1]), r2[1])
        ex.close()
        st = ex.stats()
        assert st.cache_hits == 1 and st.batches == 1
        assert st.completed == 2
        assert _grouped_impl._cache_size() == warmed, \
            "the result cache must never touch the compiled programs"

    def test_cache_hit_and_coalesce_flight_events(self, tiny_serving):
        idx, dispatch, q = tiny_serving
        gate = threading.Event()

        def gated(batch, **rt):
            gate.wait(10.0)
            return dispatch(batch)

        fl = FlightRecorder(capacity=256)
        rc = ResultCache(K, name="ex_fl", registry=obsm.MetricRegistry())
        ex = ServingExecutor(gated, (4, 8), dim=D, flush_age_s=0.0,
                             result_cache=rc, flight=fl)
        lead = ex.submit(q[:2])
        _wait(lambda: len(ex._pending) == 0)   # packed (gate holds it)
        follow = ex.submit(q[:2])          # identical -> coalesce
        gate.set()
        lead.result(timeout=30)
        follow.result(timeout=30)
        _wait(lambda: rc.stats().inserts >= 2)
        hit = ex.submit(q[:2])
        hit.result(timeout=30)
        ex.close()
        assert len(fl.events(event="coalesce")) == 1
        assert len(fl.events(event="cache_hit")) == 1
        st = ex.stats()
        assert st.coalesced_requests == 1 and st.cache_hits == 1

    def test_coalesced_rows_correct_and_no_extra_batch(self,
                                                      tiny_serving):
        idx, dispatch, q = tiny_serving
        gate = threading.Event()

        def gated(batch, **rt):
            gate.wait(10.0)
            return dispatch(batch)

        ex = ServingExecutor(gated, (4, 8), dim=D, flush_age_s=0.0,
                             result_cache=ResultCache(
                                 K, name="ex_co",
                                 registry=obsm.MetricRegistry()))
        lead = ex.submit(q[4:6])
        _wait(lambda: len(ex._pending) == 0)   # packed (gate holds it)
        f1 = ex.submit(q[4:6])
        f2 = ex.submit(q[4:6])
        gate.set()
        ref = lead.result(timeout=30)
        for f in (f1, f2):
            out = f.result(timeout=30)
            np.testing.assert_array_equal(np.asarray(ref[1]), out[1])
        ex.close()
        st = ex.stats()
        assert st.batches == 1 and st.coalesced_requests == 2
        assert st.completed == 3

    def test_coalesce_requires_same_rows_and_epoch(self, tiny_serving):
        """A different query, a different row count, or a bumped epoch
        must NOT coalesce onto the in-flight leader."""
        idx, dispatch, q = tiny_serving
        gate = threading.Event()
        epoch = [0]

        def gated(batch, **rt):
            gate.wait(10.0)
            return dispatch(batch)

        ex = ServingExecutor(gated, (4, 8), dim=D, flush_age_s=0.0,
                             coalesce=True, epoch_fn=lambda: epoch[0])
        lead = ex.submit(q[:2])
        _wait(lambda: len(ex._pending) == 0)   # packed (gate holds it)
        other = ex.submit(q[2:4])          # different bytes
        epoch[0] = 1
        post_write = ex.submit(q[:2])      # same bytes, NEWER epoch
        gate.set()
        for f in (lead, other, post_write):
            f.result(timeout=30)
        ex.close()
        st = ex.stats()
        assert st.coalesced_requests == 0
        assert st.batches >= 2

    def test_follower_survives_leader_cancellation(self, tiny_serving):
        """A caller cancelling the LEADER's future cancels only
        itself: followers are resolved from the demuxed batch rows,
        not from the leader's future."""
        idx, dispatch, q = tiny_serving
        gate = threading.Event()

        def gated(batch, **rt):
            gate.wait(10.0)
            return dispatch(batch)

        ex = ServingExecutor(gated, (4, 8), dim=D, flush_age_s=0.0,
                             coalesce=True)
        ref = np.asarray(dispatch(jnp.asarray(
            np.vstack([q[:2], np.zeros((2, D), np.float32)])))[1])[:2]
        lead = ex.submit(q[:2])
        _wait(lambda: len(ex._pending) == 0)   # packed (gate holds it)
        follow = ex.submit(q[:2])
        assert lead.cancel()
        gate.set()
        out = follow.result(timeout=30)
        ex.close()
        np.testing.assert_array_equal(np.asarray(out[1]), ref)
        st = ex.stats()
        assert st.coalesced_requests == 1
        assert st.completed == 1 and st.failed == 0

    def test_coalesced_follower_gets_leader_failure(self, tiny_serving):
        idx, dispatch, q = tiny_serving
        gate = threading.Event()

        def doomed(batch, **rt):
            gate.wait(10.0)
            raise RuntimeError("boom")

        ex = ServingExecutor(doomed, (4, 8), dim=D, flush_age_s=0.0,
                             coalesce=True)
        lead = ex.submit(q[:2])
        _wait(lambda: len(ex._pending) == 0)
        follow = ex.submit(q[:2])
        gate.set()
        with pytest.raises(RuntimeError, match="boom"):
            lead.result(timeout=30)
        with pytest.raises(RuntimeError, match="boom"):
            follow.result(timeout=30)
        ex.close()
        st = ex.stats()
        assert st.failed == 2

    def test_executor_stats_byte_compatible(self):
        """Pre-r15 positional constructions (12 args, then the r13
        stage dicts) still work; the new fields default to 0."""
        st = ExecutorStats(1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12)
        assert st.submitted == 1 and st.in_flight == 12
        assert st.coalesced_requests == 0
        assert st.cache_hits == 0 and st.cache_stale == 0
        st2 = ExecutorStats(1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12,
                            {}, {})
        assert st2.stage_p50_ms == {}


# --------------------------------------- mutation-epoch chaos (acceptance)
@pytest.fixture()
def mutable_serving():
    rng = np.random.default_rng(23)
    x = rng.standard_normal((512, D)).astype(np.float32)
    idx = ivf_flat_build(x, IVFFlatParams(n_lists=4, kmeans_n_iters=3,
                                          seed=3))
    mw = wrap_mutable(idx, delta_cap=8)
    qcap = 8
    for b in (4,):
        mutable_warmup(mw, b, k=K, n_probes=N_PROBES, qcap=qcap)
    cell = {"m": mw}

    def dispatch(batch, **_rt):
        return mutable_search(cell["m"], batch, K, n_probes=N_PROBES,
                              qcap=qcap)

    return cell, dispatch, x


class TestMutationEpochChaos:
    def test_epoch_bumps_on_applied_mutations_only(self, mutable_serving):
        cell, dispatch, x = mutable_serving
        m0 = cell["m"]
        assert m0.epoch == 0
        m1, acc = mut_upsert(m0, x[:1] * 1.5, np.array([900], np.int32))
        assert bool(acc[0]) and m1.epoch == 1
        # a no-op delete (missing id) does not bump
        m2, found = mut_delete(m1, np.array([123456], np.int32))
        assert not bool(found[0]) and m2.epoch == 1
        m3, found = mut_delete(m2, np.array([900], np.int32))
        assert bool(found[0]) and m3.epoch == 2
        # a rejected upsert (negative id) is a strict no-op
        m4, acc = mut_upsert(m3, x[:1], np.array([-1], np.int32))
        assert not bool(acc[0]) and m4.epoch == 2
        m5, _ = compact(m3)
        assert m5.epoch == 3       # continues the chain, never resets

    def test_write_between_identical_queries_never_serves_stale(
            self, mutable_serving):
        """THE chaos acceptance: an upsert (and later a delete) lands
        between two identical queries — the second query must see the
        post-write truth, through the cache, via delta-apply AND
        compaction."""
        cell, dispatch, x = mutable_serving
        rc = ResultCache(K, name="chaos",
                         registry=obsm.MetricRegistry())
        ex = ServingExecutor(
            dispatch, (4,), dim=D, flush_age_s=0.0,
            result_cache=rc, epoch_fn=lambda: cell["m"].epoch,
        )
        probe = (x[:1] * 1.01).astype(np.float32)
        r0 = ex.submit(probe).result(timeout=30)
        assert 777 not in np.asarray(r0[1]).tolist()[0]
        _wait(lambda: rc.stats().inserts >= 1)
        # warm hit proves the entry is live before the write
        ex.submit(probe).result(timeout=30)
        assert ex.stats().cache_hits == 1

        # -- delta-apply: upsert the probe itself under id 777
        cell["m"], acc = mut_upsert(cell["m"], probe,
                                    np.array([777], np.int32))
        assert bool(acc[0])
        ex.set_runtime()           # install: re-samples the epoch
        r1 = ex.submit(probe).result(timeout=30)
        assert int(np.asarray(r1[1])[0, 0]) == 777, \
            "post-upsert query served a pre-write cached result"
        assert ex.stats().cache_hits == 1       # NOT a cache hit
        assert rc.stats().stale >= 1

        # -- delete: the id must vanish from the next identical query
        _wait(lambda: rc.stats().inserts >= 2)
        cell["m"], found = mut_delete(cell["m"],
                                      np.array([777], np.int32))
        assert bool(found[0])
        ex.set_runtime()
        r2 = ex.submit(probe).result(timeout=30)
        assert 777 not in np.asarray(r2[1]).tolist()[0], \
            "post-delete query served a pre-write cached result"

        # -- compaction: also an epoch bump -> also invalidates
        _wait(lambda: rc.stats().inserts >= 3)
        hits_before = ex.stats().cache_hits
        cell["m"], _ = compact(cell["m"], list_bucket=4, row_bucket=64)
        mutable_warmup(cell["m"], 4, k=K, n_probes=N_PROBES, qcap=8)
        ex.set_runtime()
        r3 = ex.submit(probe).result(timeout=30)
        ex.close()
        assert ex.stats().cache_hits == hits_before, \
            "post-compaction query hit a pre-compaction cache entry"
        np.testing.assert_array_equal(np.asarray(r2[1]),
                                      np.asarray(r3[1]))

    def test_coalesced_under_straggler_and_hedge_all_complete(
            self, tiny_serving):
        """Coalesced requests + a straggling primary + a hedged backup:
        every caller (leader and followers) still gets its correct
        rows, exactly once."""
        idx, dispatch, q = tiny_serving
        wrapped, audit = faults.inject_straggler(
            dispatch, every=1, seconds=30.0,
        )
        pol = HedgePolicy(default_delay_s=0.02, min_samples=10 ** 6)
        rc = ResultCache(K, name="hedge_co",
                         registry=obsm.MetricRegistry())
        ex = ServingExecutor(
            wrapped, (4, 8), dim=D, flush_age_s=0.0,
            hedge=pol, backup_dispatch=dispatch, result_cache=rc,
        )
        ref = np.asarray(dispatch(jnp.asarray(
            np.vstack([q[:2], np.zeros((2, D), np.float32)])))[1])[:2]
        lead = ex.submit(q[:2])
        _wait(lambda: ex.stats().in_flight >= 1)
        f1 = ex.submit(q[:2])
        f2 = ex.submit(q[:2])
        outs = [f.result(timeout=30) for f in (lead, f1, f2)]
        ex.close()
        for out in outs:
            np.testing.assert_array_equal(np.asarray(out[1]), ref)
        st = ex.stats()
        assert st.hedged_batches == 1 and st.backup_wins == 1
        assert st.coalesced_requests == 2 and st.completed == 3


# ------------------------------------------- popularity-aware replication
class TestPopularityReplication:
    def test_vector_properties(self):
        load = np.array([100.0, 10.0, 1.0, 1.0])
        copies = popularity_replication(load, budget=8, r_min=1,
                                        r_max=4)
        assert copies.sum() == 8
        assert copies.min() >= 1 and copies.max() <= 4
        assert copies[0] == copies.max()     # the hot shard leads
        # uniform load degenerates to uniform replication
        np.testing.assert_array_equal(
            popularity_replication(np.ones(4), budget=8), [2, 2, 2, 2])
        # zero load (cold start) also degenerates
        np.testing.assert_array_equal(
            popularity_replication(np.zeros(4), budget=8), [2, 2, 2, 2])

    def test_vector_respects_r_max_strands_to_cold(self):
        copies = popularity_replication(
            np.array([1000.0, 1.0, 1.0, 1.0]), budget=10, r_min=1,
            r_max=3)
        assert copies.sum() == 10
        assert copies[0] == 3                # clamped
        assert copies.min() >= 2             # surplus spread to cold

    def test_vector_validation(self):
        with pytest.raises(ValueError):
            popularity_replication(np.ones(4), budget=3)   # < P*r_min
        with pytest.raises(ValueError):
            popularity_replication(np.ones(4), budget=20, r_max=2)

    def test_load_balanced_uniform_matches_primary_route(self):
        p = ReplicaPlacement.striped(8, 2)
        fp = FailoverPlan.load_balanced(p, True, np.ones(8))
        np.testing.assert_array_equal(
            fp.route, FailoverPlan.from_health(p, True).route)

    def test_load_balanced_avoids_the_hot_failover_rank(self):
        """A hot shard fails over onto its standby; from_health then
        STACKS the standby's own primary shard on the same rank, while
        the load-weighted route moves that shard to its free standby —
        strictly more even weighted load, same placement, same route
        shape/dtype (route VALUES only)."""
        p = ReplicaPlacement.striped(4, 2, offset=1)  # s -> (s, s+1)
        alive = np.array([0, 1, 1, 1])       # hot shard 0's rank dead
        load = np.array([50.0, 1.0, 1.0, 1.0])
        naive = FailoverPlan.from_health(p, alive)
        fp = FailoverPlan.load_balanced(p, alive, load)
        assert fp.fully_covered and naive.fully_covered
        assert fp.serving_rank(0) == 1       # forced failover
        assert naive.serving_rank(1) == 1    # first-live stacks rank 1
        assert fp.serving_rank(1) == 2       # weighted route moves off

        def weighted(plan):
            w = np.zeros(4)
            for s in range(4):
                w[plan.serving_rank(s)] += load[s]
            return w

        assert weighted(fp).max() < weighted(naive).max()
        assert fp.route.shape == naive.route.shape
        assert fp.route.dtype == naive.route.dtype

    def test_route_values_only_zero_retrace(self):
        """A popularity-driven re-route is VALUES of the same (P,)
        int32 runtime operand — one compiled program serves
        from_health and load_balanced routes (the ISSUE 15 audit)."""
        import jax

        p = ReplicaPlacement.striped(4, 2)

        @jax.jit
        def consume(x, route):
            return x + route.sum()

        x = jnp.zeros((2,), jnp.int32)
        from raft_tpu.resilience import resolve_route

        r1 = resolve_route(FailoverPlan.from_health(p, True), 4, 2, 2)
        consume(x, jnp.asarray(r1))
        warmed = consume._cache_size()
        load = np.array([9.0, 1.0, 1.0, 1.0])
        r2 = resolve_route(
            FailoverPlan.load_balanced(p, [1, 0, 1, 1], load), 4, 2, 2)
        consume(x, jnp.asarray(r2))
        r3 = resolve_route(
            FailoverPlan.load_balanced(p, True, load * 7), 4, 2, 2)
        consume(x, jnp.asarray(r3))
        assert consume._cache_size() == warmed

    def test_registry_glue_round_trip(self):
        reg = obsm.MetricRegistry()
        record_shard_load([4, 0, 2, 0], registry=reg)
        record_shard_load([1, 1, 0, 0], registry=reg)
        np.testing.assert_array_equal(
            measured_shard_load(4, registry=reg), [5, 1, 2, 0])
        # load_balanced can read straight from the registry
        p = ReplicaPlacement.striped(4, 2)
        fp = FailoverPlan.load_balanced(p, True, registry=reg)
        np.testing.assert_array_equal(
            fp.route, FailoverPlan.from_health(p, True).route)


# ------------------------------------------------- bench row (CI smoke)
def test_zipf_hot_traffic_row_tiny_config():
    """The CI-safe zipf_hot_traffic smoke (ISSUE 15 satellite): the
    bench row runs end-to-end on a tiny CPU config and stamps its
    acceptance keys — NO QPS assertions (CPU jitter), but the
    equal-recall spot check and a nonzero hit rate must hold: the Zipf
    mix guarantees repeats, and repeats must hit."""
    rng = np.random.default_rng(9)
    x = rng.standard_normal((2048, D)).astype(np.float32)
    idx = ivf_flat_build(x, IVFFlatParams(n_lists=8, kmeans_n_iters=3,
                                          seed=2))
    from bench.bench_serving import zipf_hot_traffic_row

    def make_run(bucket):
        qcap = idx.warmup(bucket, k=K, n_probes=N_PROBES)

        def run(qq, qcap=qcap):
            return ivf_flat_search_grouped(
                idx, qq, K, n_probes=N_PROBES, qcap=qcap,
            )
        return run

    row = zipf_hot_traffic_row(
        make_run, x[:256], k=K, buckets=(4, 8), request_size=2,
        n_templates=8, n_requests=48, chain=(1, 3), escalate=0,
        min_duration_s=0.0, max_requests=64,
    )
    assert row["scenario"] == "zipf_hot_traffic"
    if "error" in row:
        pytest.skip(f"jitter-dominated tiny config: {row['error']}")
    for key in ("program_qps", "uncached_qps", "cached_qps",
                "qps_uplift", "cache_hit_rate", "coalesce_rate",
                "zipf_s", "n_templates", "cached_identical"):
        assert key in row, key
    assert row["cache_hit_rate"] > 0.0
    assert row["cached_identical"] is True
