"""random subsystem tests — moment checks like reference cpp/test/random/rng.cu
and cluster-recovery like cpp/test/random/make_blobs.cu."""

import jax.numpy as jnp
import numpy as np

from raft_tpu import random as rrandom
from raft_tpu.random import RngState


N = 20000
TOL = 0.05


class TestDistributions:
    def test_uniform_moments(self):
        x = np.asarray(rrandom.uniform(RngState(1), (N,), low=-2.0, high=4.0))
        assert abs(x.mean() - 1.0) < TOL * 6
        assert x.min() >= -2 and x.max() < 4

    def test_normal_moments(self):
        x = np.asarray(rrandom.normal(RngState(2), (N,), mu=1.5, sigma=2.0))
        assert abs(x.mean() - 1.5) < 0.1
        assert abs(x.std() - 2.0) < 0.1

    def test_lognormal(self):
        x = np.asarray(rrandom.lognormal(RngState(3), (N,), mu=0.0, sigma=0.5))
        assert (x > 0).all()
        want_mean = np.exp(0.125)
        assert abs(x.mean() - want_mean) < 0.1

    def test_exponential(self):
        lam = 2.0
        x = np.asarray(rrandom.exponential(RngState(4), (N,), lam=lam))
        assert abs(x.mean() - 1 / lam) < 0.05

    def test_rayleigh(self):
        sigma = 1.5
        x = np.asarray(rrandom.rayleigh(RngState(5), (N,), sigma=sigma))
        want = sigma * np.sqrt(np.pi / 2)
        assert abs(x.mean() - want) < 0.1

    def test_laplace_gumbel_logistic(self):
        for fn, mean_tol in [(rrandom.laplace, 0.1), (rrandom.logistic, 0.1)]:
            x = np.asarray(fn(RngState(6), (N,), 0.5, 1.0))
            assert abs(x.mean() - 0.5) < mean_tol
        g = np.asarray(rrandom.gumbel(RngState(7), (N,), mu=0.0, beta=1.0))
        assert abs(g.mean() - 0.5772) < 0.1  # Euler-Mascheroni

    def test_bernoulli(self):
        x = np.asarray(rrandom.bernoulli(RngState(8), (N,), 0.3, dtype=jnp.float32))
        assert abs(x.mean() - 0.3) < 0.02

    def test_scaled_bernoulli(self):
        x = np.asarray(rrandom.scaled_bernoulli(RngState(9), (N,), 0.5, 2.0))
        assert set(np.unique(x)) == {-2.0, 2.0}

    def test_uniform_int(self):
        x = np.asarray(rrandom.uniform_int(RngState(10), (N,), 3, 9))
        assert x.min() >= 3 and x.max() < 9

    def test_normal_table(self):
        mu = np.array([0.0, 10.0, -5.0], np.float32)
        sigma = np.array([1.0, 2.0, 0.5], np.float32)
        x = np.asarray(rrandom.normal_table(RngState(11), N, mu, sigma))
        np.testing.assert_allclose(x.mean(0), mu, atol=0.15)
        np.testing.assert_allclose(x.std(0), sigma, atol=0.15)

    def test_fill(self):
        x = np.asarray(rrandom.fill(RngState(12), (5,), 7.0))
        np.testing.assert_array_equal(x, np.full(5, 7.0, np.float32))

    def test_discrete(self):
        probs = np.array([0.1, 0.6, 0.3])
        x = np.asarray(rrandom.discrete(RngState(13), (N,), probs))
        counts = np.bincount(x, minlength=3) / N
        np.testing.assert_allclose(counts, probs, atol=0.03)

    def test_custom_distribution(self):
        # inverse CDF of exponential(1)
        x = np.asarray(rrandom.custom_distribution(
            RngState(14), (N,), lambda u: -jnp.log1p(-u * (1 - 1e-7))))
        assert abs(x.mean() - 1.0) < 0.05

    def test_state_advance_determinism(self):
        s1 = RngState(42)
        a = np.asarray(rrandom.uniform(s1, (10,)))
        b = np.asarray(rrandom.uniform(s1, (10,)))
        assert not np.allclose(a, b)  # state advanced
        s2 = RngState(42)
        a2 = np.asarray(rrandom.uniform(s2, (10,)))
        np.testing.assert_array_equal(a, a2)  # reproducible


class TestSampling:
    def test_sample_without_replacement_unique(self):
        idx, _ = rrandom.sample_without_replacement(RngState(1), 50, 100)
        idx = np.asarray(idx)
        assert len(np.unique(idx)) == 50
        assert idx.min() >= 0 and idx.max() < 100

    def test_sample_weighted_bias(self):
        # heavily weighted item should virtually always be selected
        w = np.ones(100, np.float32)
        w[7] = 10000.0
        hits = 0
        for seed in range(20):
            idx, _ = rrandom.sample_without_replacement(RngState(seed), 10, 100, weights=w)
            hits += int(7 in np.asarray(idx))
        assert hits >= 19

    def test_permute(self, rng_np):
        x = rng_np.standard_normal((30, 4)).astype(np.float32)
        perm, out = rrandom.permute(RngState(3), 30, x)
        perm = np.asarray(perm)
        assert len(np.unique(perm)) == 30
        np.testing.assert_array_equal(np.asarray(out), x[perm])


class TestGenerators:
    def test_make_blobs_recovery(self):
        data, labels = rrandom.make_blobs(2000, 8, n_clusters=4,
                                          state=RngState(0), cluster_std=0.3)
        data, labels = np.asarray(data), np.asarray(labels)
        assert data.shape == (2000, 8) and labels.shape == (2000,)
        assert set(np.unique(labels)) == {0, 1, 2, 3}
        # within-cluster scatter should be tiny vs between-cluster distances
        centers = np.stack([data[labels == c].mean(0) for c in range(4)])
        for c in range(4):
            spread = np.linalg.norm(data[labels == c] - centers[c], axis=1).mean()
            assert spread < 0.3 * np.sqrt(8) * 2
        d01 = np.linalg.norm(centers[0] - centers[1])
        assert d01 > 1.0

    def test_make_blobs_given_centers(self):
        centers = np.array([[0.0, 0.0], [100.0, 100.0]], np.float32)
        data, labels = rrandom.make_blobs(200, 2, state=RngState(1),
                                          centers=centers, cluster_std=0.1,
                                          shuffle=False)
        data, labels = np.asarray(data), np.asarray(labels)
        np.testing.assert_allclose(data[labels == 1].mean(0), [100, 100], atol=0.2)

    def test_make_regression_exact(self):
        x, y, w = rrandom.make_regression(300, 10, n_informative=5,
                                          state=RngState(2), noise=0.0,
                                          shuffle=True, coef=True)
        x, y, w = np.asarray(x), np.asarray(y), np.asarray(w)
        np.testing.assert_allclose(y, x @ w, rtol=1e-3, atol=1e-2)
        assert (np.abs(w) > 1e-6).sum() == 5

    def test_make_regression_lowrank(self):
        x, y = rrandom.make_regression(100, 40, n_informative=10,
                                       state=RngState(3), effective_rank=5,
                                       tail_strength=0.1)
        s = np.linalg.svd(np.asarray(x), compute_uv=False)
        # effective rank ~5 -> fast spectral decay
        assert s[10] < 0.2 * s[0]

    def test_multi_variable_gaussian(self):
        cov = np.array([[2.0, 0.8], [0.8, 1.0]], np.float32)
        mu = np.array([1.0, -1.0], np.float32)
        x = np.asarray(rrandom.multi_variable_gaussian(RngState(4), 30000, mu, cov))
        assert x.shape == (2, 30000)
        np.testing.assert_allclose(x.mean(1), mu, atol=0.05)
        np.testing.assert_allclose(np.cov(x), cov, atol=0.1)
