"""Runtime telemetry (ISSUE 13): the metric registry (log2 histogram
quantiles, labels, thread-safety, the enable gate, exposition/JSONL),
per-stage executor timings driven by a `testing.load` virtual-clock
replay, the flight recorder's ring + automatic dump triggers, the
SLO-triggered profile capture, the annotate enable flag, and the live
retrace census. Everything host-side — the one jitted program here is
a 3-element add for the census — so the whole file stays cheap in
tier-1."""

import json
import threading
import time

import numpy as np
import pytest

import importlib

from raft_tpu import errors

# raft_tpu.core re-exports the `annotate` FUNCTION under the module's
# own name; fetch the module itself for the gate/state tests
annotate_mod = importlib.import_module("raft_tpu.core.annotate")
from raft_tpu.obs import FlightRecorder, MetricRegistry, program_census
from raft_tpu.obs import metrics as obsm
from raft_tpu.obs.capture import ProfileTrigger
from raft_tpu.serving import ServingExecutor
from raft_tpu.serving.executor import STAGES, ExecutorStats
from raft_tpu.testing import load

D = 4


@pytest.fixture
def reg():
    return MetricRegistry()


@pytest.fixture(autouse=True)
def _obs_on():
    """Every test in this file assumes recording is ON (the repo
    default); restore whatever state the suite had."""
    prev = obsm.set_enabled(True)
    yield
    obsm.set_enabled(prev)


# ------------------------------------------------------------ histograms
class TestHistogram:
    def test_bucket_geometry(self):
        # octave buckets tile [2^LO, 2^HI); edges round-trip
        assert obsm.bucket_index(0.0) == 0
        assert obsm.bucket_index(2.0 ** obsm.LOG2_LO) == 1
        assert obsm.bucket_index(2.0 ** obsm.LOG2_HI) == obsm.N_BUCKETS - 1
        for i in range(1, obsm.N_BUCKETS - 1):
            lo, hi = obsm.bucket_edges(i)
            assert obsm.bucket_index(lo) == i
            assert obsm.bucket_index(hi * (1 - 1e-9)) == i

    def test_quantiles_exact_for_constant_stream(self, reg):
        h = reg.histogram("lat_ms")
        for _ in range(100):
            h.observe(3.25)
        # min/max clamping collapses the bucket to the observed value
        assert h.p50 == pytest.approx(3.25)
        assert h.p99 == pytest.approx(3.25)
        assert h.count == 100 and h.sum == pytest.approx(325.0)
        assert h.mean == pytest.approx(3.25)

    def test_quantiles_within_log2_bucket_error(self, reg):
        h = reg.histogram("lat_ms", stage="x")
        vals = np.random.default_rng(0).lognormal(1.0, 1.0, 5000)
        for v in vals:
            h.observe(float(v))
        for q in (50.0, 95.0, 99.0):
            est = h.quantile(q)
            ref = float(np.percentile(vals, q))
            # a log2 bucket's worst-case relative error is 2x; linear
            # interpolation lands far closer in practice
            assert ref / 2.0 <= est <= ref * 2.0, (q, est, ref)

    def test_empty_histogram_returns_none(self, reg):
        h = reg.histogram("lat_ms", stage="empty")
        assert h.quantile(50.0) is None and h.p99 is None
        assert h.mean is None and h.count == 0

    def test_quantile_range_validated(self, reg):
        with pytest.raises(ValueError):
            obsm.quantile_from_counts([1], 101.0)

    def test_merged_quantile_pools_buckets(self, reg):
        a = reg.histogram("m", bucket=4)
        b = reg.histogram("m", bucket=8)
        for _ in range(100):
            a.observe(1.0)
        for _ in range(100):
            b.observe(64.0)
        pooled = obsm.merged_quantile([a, b], 50.0)
        # half the pooled mass sits at 1.0 — the p50 must stay at the
        # low mode, not the high series' value
        assert pooled is not None and pooled <= 2.0
        assert obsm.merged_quantile([a, b], 99.0) >= 32.0
        assert obsm.merged_quantile([], 50.0) is None


# -------------------------------------------------------------- registry
class TestRegistry:
    def test_labels_key_distinct_series(self, reg):
        a = reg.counter("reqs", bucket=4)
        b = reg.counter("reqs", bucket=8)
        assert a is not b
        a.inc(3)
        assert a.value == 3 and b.value == 0
        # same (name, labels) -> the SAME handle
        assert reg.counter("reqs", bucket=4) is a

    def test_kind_conflict_raises(self, reg):
        reg.counter("x")
        with pytest.raises(ValueError, match="counter"):
            reg.gauge("x")
        # the rule is per NAME, not per (name, labels): exposition
        # emits one `# TYPE` per name, so a labels-differing series
        # must not smuggle a second kind in (review-caught r13)
        reg.counter("y", a=1)
        with pytest.raises(ValueError, match="counter"):
            reg.histogram("y", b=2)

    def test_gauge_set_add(self, reg):
        g = reg.gauge("depth")
        g.set(7)
        g.add(-2.5)
        assert g.value == 4.5

    def test_enable_gate_no_ops_everything(self, reg):
        c = reg.counter("c")
        g = reg.gauge("g")
        h = reg.histogram("h")
        fr = FlightRecorder(8)
        prev = obsm.set_enabled(False)
        try:
            c.inc(100)
            g.set(5)
            h.observe(1.0)
            fr.record("submit", request_id=1)
        finally:
            obsm.set_enabled(prev)
        assert c.value == 0 and g.value == 0.0 and h.count == 0
        assert fr.events() == []

    def test_thread_safety_smoke(self, reg):
        c = reg.counter("hits")
        h = reg.histogram("lat")
        n_threads, n_each = 8, 500

        def work():
            for i in range(n_each):
                c.inc()
                h.observe(float(i % 7) + 0.5)

        ts = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert c.value == n_threads * n_each
        assert h.count == n_threads * n_each

    def test_snapshot_text_and_exposition(self, reg):
        reg.counter("reqs", outcome="ok").inc(2)
        h = reg.histogram("lat_ms", stage="e2e")
        h.observe(1.0)
        snap = reg.snapshot()
        assert snap["reqs"][0]["value"] == 2
        assert snap["lat_ms"][0]["count"] == 1
        assert "p50" in snap["lat_ms"][0]
        txt = reg.text_snapshot()
        assert 'reqs{outcome="ok"} 2' in txt
        expo = reg.exposition()
        assert "# TYPE reqs counter" in expo
        assert "# TYPE lat_ms histogram" in expo
        assert 'lat_ms_bucket{le="+Inf",stage="e2e"}' in expo
        assert 'lat_ms_count{stage="e2e"} 1' in expo

    def test_jsonl_emitter(self, tmp_path):
        reg = MetricRegistry(clock=lambda: 123.5)   # injectable stamp
        reg.counter("n").inc(4)
        path = tmp_path / "metrics.jsonl"
        em = reg.start_emitter(str(path), interval_s=0.01)
        time.sleep(0.05)
        em.stop()
        lines = [json.loads(x) for x in
                 path.read_text().strip().splitlines()]
        assert len(lines) >= 2            # periodic + final flush
        assert lines[0]["t"] == 123.5
        assert lines[0]["metrics"]["n"][0]["value"] == 4
        reg.stop_emitters()               # idempotent


# -------------------------------------------- executor per-stage timing
def _host_dispatch(batch, **_rt):
    """A pure-host dispatch: results are immediately 'ready' (numpy has
    no is_ready), so the executor pipeline runs at full speed with no
    device in the loop."""
    return (batch * 2.0, np.argsort(batch, axis=1).astype(np.int32))


class TestExecutorStageTiming:
    def test_stage_histograms_under_virtual_clock_replay(self):
        """The per-stage pin (ISSUE 13): drive the executor from a
        `testing.load` virtual-clock replay (all submits fire
        instantly) and assert every STAGE histogram filled with
        consistent counts — queue_wait/e2e once per request,
        batch_build/staging/dispatch_ready/demux once per batch."""
        reg = MetricRegistry()
        ex = ServingExecutor(_host_dispatch, (4, 8), dim=D,
                             flush_age_s=0.0, registry=reg,
                             name="stagetest")
        sched = load.poisson_arrivals(1000.0, 24, seed=5, sizes=2)
        futs, _, _ = load.replay(
            sched,
            lambda i, size: ex.submit(
                np.full((size, D), i, np.float32)),
            clock=lambda: 0.0, sleep=lambda s: None,
        )
        for f in futs:
            f.result(timeout=30)
        st = ex.stats()
        ex.close()
        assert st.completed == 24 and st.failed == 0
        for stage_name in STAGES:
            assert stage_name in st.stage_p50_ms, stage_name
            assert st.stage_p50_ms[stage_name] >= 0.0
            assert (st.stage_p99_ms[stage_name]
                    >= st.stage_p50_ms[stage_name])
        # count consistency: per-request vs per-batch stages
        def total(stage_name):
            return sum(
                h.count for (s, _b), h in ex._stage_hist.items()
                if s == stage_name
            )
        assert total("queue_wait") == 24 and total("e2e") == 24
        assert total("dispatch_ready") == st.batches
        assert total("batch_build") == st.batches
        assert total("staging") == st.batches
        assert total("demux") == st.batches
        # e2e contains dispatch_ready by construction
        assert (st.stage_p50_ms["e2e"]
                >= st.stage_p50_ms["dispatch_ready"])

    def test_executor_stats_positional_compat(self):
        """The pre-r13 12-field positional construction still works and
        the new stage fields default empty — byte-compatibility, the
        ISSUE 13 satellite contract."""
        st = ExecutorStats(1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12)
        assert st.submitted == 1 and st.in_flight == 12
        assert st.stage_p50_ms == {} and st.stage_p99_ms == {}
        assert st.pad_fraction == pytest.approx(8 / 15)

    def test_coverage_gauge_read_at_demux(self):
        """An mnmg-shaped result (PartialSearchResult pytree) feeds the
        coverage gauge from the ALREADY-converted host copy."""
        from raft_tpu.resilience.degraded import PartialSearchResult

        reg = MetricRegistry()

        def dispatch(batch, **_rt):
            b = batch.shape[0]
            return PartialSearchResult(
                distances=np.zeros((b, 2), np.float32),
                ids=np.zeros((b, 2), np.int32),
                coverage=np.full((b,), 0.75, np.float32),
                row_valid=np.ones((b,), bool),
            )

        ex = ServingExecutor(dispatch, (4,), dim=D, flush_age_s=0.0,
                             registry=reg, name="covtest")
        ex.submit(np.ones((2, D), np.float32)).result(timeout=30)
        ex.close()
        g = reg.gauge("serving_coverage_min", executor="covtest")
        assert g.value == pytest.approx(0.75)


# -------------------------------------------------------- flight recorder
class TestFlightRecorder:
    def test_span_events_join_by_request_id(self):
        fr = FlightRecorder(64, clock=lambda: 1.0)
        ex = ServingExecutor(_host_dispatch, (4,), dim=D,
                             flush_age_s=0.0, registry=MetricRegistry(),
                             flight=fr, name="fr")
        fut = ex.submit(np.ones((2, D), np.float32))
        fut.result(timeout=30)
        ex.close()
        evs = [e["event"] for e in fr.events(request_id=0)]
        assert evs[:2] == ["submit", "pack"]
        batch = fr.events(event="dispatch")
        assert batch and 0 in batch[0]["requests"]
        demux = fr.events(event="demux")
        assert demux and demux[0]["winner"] == "unhedged"
        assert demux[0]["delivered"] == 1

    def test_ring_bound_and_dropped(self):
        fr = FlightRecorder(4, clock=lambda: 0.0)
        for i in range(10):
            fr.record("submit", request_id=i)
        assert len(fr.events()) == 4
        assert fr.dropped == 6
        assert [e["request_id"] for e in fr.events()] == [6, 7, 8, 9]

    def test_dumps_header_and_schema(self):
        fr = FlightRecorder(8, name="s", clock=lambda: 2.5)
        fr.record("submit", request_id=3, rows=2)
        lines = [json.loads(x) for x in
                 fr.dumps("unit").strip().splitlines()]
        assert lines[0] == {"flight": "s", "reason": "unit", "t": 2.5,
                            "n_events": 1, "dropped": 0}
        assert lines[1]["event"] == "submit"
        assert lines[1]["request_id"] == 3 and lines[1]["rows"] == 2

    def test_dump_without_sink_is_noop(self):
        fr = FlightRecorder(8)
        fr.record("submit", request_id=0)
        assert fr.dump("no-sink") is None
        assert fr.events()               # ring untouched

    def test_auto_dump_on_batch_failure(self, tmp_path):
        """Trigger 1: a failing dispatch dumps the ring BEFORE failing
        the futures; trigger 3: close() with failures outstanding dumps
        again."""
        fr = FlightRecorder(64, dump_dir=str(tmp_path), name="boom")

        def bad_dispatch(batch, **_rt):
            raise errors.RaftTimeoutError("deadline tripped")

        ex = ServingExecutor(bad_dispatch, (4,), dim=D, flush_age_s=0.0,
                             registry=MetricRegistry(), flight=fr,
                             name="boom")
        fut = ex.submit(np.ones((1, D), np.float32))
        with pytest.raises(errors.RaftTimeoutError):
            fut.result(timeout=30)
        ex.close()
        assert len(fr.dumps_written) == 2
        first = [json.loads(x) for x in open(fr.dumps_written[0])]
        assert first[0]["reason"] == "batch-fail"
        fails = [e for e in first if e.get("event") == "batch_fail"]
        assert fails and fails[0]["error"] == "RaftTimeoutError"
        assert "deadline tripped" in fails[0]["message"]
        last = [json.loads(x) for x in open(fr.dumps_written[1])]
        assert last[0]["reason"] == "close-with-failures"
        assert any(e.get("event") == "close" and e.get("failed") == 1
                   for e in last)

    def test_broken_dump_sink_never_hangs_clients(self, tmp_path):
        """Review-caught r13: an OSError from the automatic dump (bad
        dir, disk full) must not escape _fail_batch — the futures
        still owe their callers the REAL dispatch exception, and an
        escape would kill the worker thread and hang every waiter."""
        fr = FlightRecorder(
            64, dump_dir=str(tmp_path / "missing" / "dir"), name="io",
        )

        def bad_dispatch(batch, **_rt):
            raise errors.RaftTimeoutError("the real failure")

        ex = ServingExecutor(bad_dispatch, (4,), dim=D, flush_age_s=0.0,
                             registry=MetricRegistry(), flight=fr,
                             name="io")
        fut = ex.submit(np.ones((1, D), np.float32))
        with pytest.raises(errors.RaftTimeoutError, match="real"):
            fut.result(timeout=30)       # resolved, not hung
        ex.close(timeout_s=10.0)         # completes despite the sink
        assert fr.dumps_written == []

    def test_shed_recorded(self):
        from raft_tpu.resilience import AdmissionController

        fr = FlightRecorder(16)
        ex = ServingExecutor(
            _host_dispatch, (4,), dim=D, flush_age_s=10.0,
            registry=MetricRegistry(), flight=fr, name="shed",
            admission=AdmissionController(max_concurrent=1, max_queue=0),
        )
        ex.submit(np.ones((1, D), np.float32))
        with pytest.raises(errors.RaftOverloadError):
            for _ in range(8):
                ex.submit(np.ones((1, D), np.float32))
        ex.close()
        assert fr.events(event="shed")


# ------------------------------------------------------- profile trigger
class _FakeTrace:
    def __init__(self):
        self.started = []
        self.stopped = 0

    def start(self, log_dir):
        self.started.append(log_dir)

    def stop(self):
        self.stopped += 1


class TestProfileTrigger:
    def _trigger(self, reg, fr=None, **kw):
        h = reg.histogram("e2e_ms")
        tr = _FakeTrace()
        slept = []
        trig = ProfileTrigger(
            h, threshold_ms=10.0, log_dir="/tmp/prof", consecutive=2,
            capture_s=0.25, max_captures=1, cooldown_s=60.0,
            registry=reg, recorder=fr, start=tr.start, stop=tr.stop,
            sleep=slept.append, clock=lambda: 100.0, **kw,
        )
        return h, tr, slept, trig

    def test_fires_after_consecutive_breaches_only(self, reg):
        fr = FlightRecorder(16)
        h, tr, slept, trig = self._trigger(reg, fr)
        # window 1: over threshold -> breach 1, no capture
        for _ in range(10):
            h.observe(50.0)
        assert trig.check() is None and tr.started == []
        # window 2: still over -> capture fires, bounded, path recorded
        for _ in range(10):
            h.observe(50.0)
        assert trig.check() == "/tmp/prof"
        assert tr.started == ["/tmp/prof"] and tr.stopped == 1
        assert slept == [0.25]
        assert trig.captures == 1
        c = reg.counter("profile_captures_total", trigger="e2e_ms")
        assert c.value == 1
        ev = fr.events(event="profile_capture")
        assert ev and ev[0]["path"] == "/tmp/prof"
        assert ev[0]["breached_ms"] > 10.0

    def test_windowed_not_lifetime_quantile(self, reg):
        h, tr, _, trig = self._trigger(reg)
        # a bad HISTORY must not trip the trigger once the current
        # window is healthy: lifetime p99 stays >10, window p99 is 1
        for _ in range(100):
            h.observe(50.0)
        assert trig.check() is None          # breach 1
        for _ in range(100):
            h.observe(1.0)
        assert trig.check() is None and tr.started == []
        # the healthy window also RESET the breach count
        for _ in range(10):
            h.observe(50.0)
        assert trig.check() is None          # breach 1 again, not 2

    def test_no_traffic_carries_no_evidence(self, reg):
        h, tr, _, trig = self._trigger(reg)
        for _ in range(10):
            h.observe(50.0)
        assert trig.check() is None          # breach 1
        assert trig.check() is None          # empty window: no advance
        for _ in range(10):
            h.observe(50.0)
        assert trig.check() == "/tmp/prof"   # breach 2 -> fires

    def test_failed_capture_rolls_back_the_budget(self, reg):
        """Review-caught r13: a refused start_trace (another capture
        already running) must not burn the one-capture budget — the
        trigger retries after the next full debounce instead of going
        dark for the process lifetime."""
        h = reg.histogram("e2e_ms", t="rollback")

        calls = []

        def refusing_start(_d):
            calls.append("start")
            raise RuntimeError("profiler already started")

        tr = _FakeTrace()
        trig = ProfileTrigger(
            h, threshold_ms=10.0, log_dir="/tmp/prof", consecutive=1,
            capture_s=0.1, max_captures=1, cooldown_s=60.0,
            registry=reg, start=refusing_start, stop=tr.stop,
            sleep=lambda s: None, clock=lambda: 100.0,
        )
        for _ in range(10):
            h.observe(50.0)
        with pytest.raises(RuntimeError):
            trig.check()
        assert trig.captures == 0            # budget intact
        # the profiler frees up; the next breach captures normally
        trig._start = tr.start
        for _ in range(10):
            h.observe(50.0)
        assert trig.check() == "/tmp/prof"
        assert trig.captures == 1

    def test_max_captures_bounds_the_storm(self, reg):
        h, tr, _, trig = self._trigger(reg)
        for round_ in range(4):
            for _ in range(10):
                h.observe(50.0)
            trig.check()
        assert len(tr.started) == 1          # max_captures=1

    def test_watch_thread_runs_and_stops(self, reg):
        h, tr, _, trig = self._trigger(reg)
        trig.watch(interval_s=0.01)
        for _ in range(10):
            h.observe(50.0)
        time.sleep(0.05)
        for _ in range(10):
            h.observe(50.0)
        deadline = time.monotonic() + 2.0
        while not tr.started and time.monotonic() < deadline:
            time.sleep(0.01)
        trig.stop()
        assert tr.started == ["/tmp/prof"]


# ------------------------------------------------- annotate enable flag
class TestAnnotateGate:
    def test_disabled_push_allocates_nothing(self, monkeypatch):
        """The 'near-zero cost' claim, pinned: with profiling off,
        push_range constructs NO profiler object and stacks NO
        ExitStack; annotate yields without touching jax.profiler."""
        constructed = []

        class Spy:
            def __init__(self, label):
                constructed.append(label)

            def __enter__(self):
                return self

            def __exit__(self, *exc):
                return False

        monkeypatch.setattr(annotate_mod.jax.profiler,
                            "TraceAnnotation", Spy)
        prev = annotate_mod.set_profiling(False)
        try:
            annotate_mod.push_range("hot %d", 1)
            assert annotate_mod._stack == []
            assert constructed == []
            with annotate_mod.annotate("hot"):
                pass
            assert constructed == []
            # pop on the empty stack: loud no-op, never an exception
            annotate_mod.pop_range()
        finally:
            annotate_mod.set_profiling(prev)

    def test_enabled_push_pop_balanced(self, monkeypatch):
        constructed = []

        class Spy:
            def __init__(self, label):
                constructed.append(label)

            def __enter__(self):
                return self

            def __exit__(self, *exc):
                return False

        monkeypatch.setattr(annotate_mod.jax.profiler,
                            "TraceAnnotation", Spy)
        prev = annotate_mod.set_profiling(True)
        try:
            annotate_mod.push_range("range %s", "a")
            assert len(annotate_mod._stack) == 1
            assert constructed == ["range a"]
            annotate_mod.pop_range()
            assert annotate_mod._stack == []
        finally:
            annotate_mod.set_profiling(prev)

    def test_trace_capture_flips_the_gate(self, monkeypatch):
        monkeypatch.setattr(annotate_mod.jax.profiler, "start_trace",
                            lambda d: None)
        monkeypatch.setattr(annotate_mod.jax.profiler, "stop_trace",
                            lambda: None)
        prev = annotate_mod.set_profiling(False)
        try:
            annotate_mod.start_trace("/tmp/t")
            assert annotate_mod.profiling_enabled()
            annotate_mod.stop_trace()
            assert not annotate_mod.profiling_enabled()
        finally:
            annotate_mod.set_profiling(prev)

    def test_failed_start_trace_leaks_nothing(self, monkeypatch):
        """Review-caught r13: a refused profiler start (capture already
        running) must leave the range gate AND its restore stack
        untouched — the old order enabled ranges forever."""
        def refuse(_d):
            raise RuntimeError("profiler already started")

        monkeypatch.setattr(annotate_mod.jax.profiler, "start_trace",
                            refuse)
        prev = annotate_mod.set_profiling(False)
        depth = len(annotate_mod._pre_trace)
        try:
            with pytest.raises(RuntimeError):
                annotate_mod.start_trace("/tmp/t")
            assert not annotate_mod.profiling_enabled()
            assert len(annotate_mod._pre_trace) == depth
        finally:
            annotate_mod.set_profiling(prev)

    def test_unbalanced_stop_restores_env_default(self, monkeypatch):
        """Review-caught r13: a stop_trace with no matching
        start_trace falls back to the env-derived default, not a hard
        False — a RAFT_TPU_PROFILE=1 process must not be silently
        disabled by one stray stop."""
        monkeypatch.setattr(annotate_mod.jax.profiler, "stop_trace",
                            lambda: None)
        monkeypatch.setattr(annotate_mod, "_ENV_DEFAULT", True)
        prev = annotate_mod.set_profiling(True)
        try:
            assert annotate_mod._pre_trace == []
            annotate_mod.stop_trace()            # unbalanced
            assert annotate_mod.profiling_enabled()
        finally:
            annotate_mod.set_profiling(prev)


# --------------------------------------------------- live retrace census
class TestProgramCensus:
    def test_census_reads_cache_sizes(self, reg):
        import jax

        @jax.jit
        def f(x):
            return x + 1

        f(np.ones(3, np.float32))
        out = program_census({"f": f, "not_jitted": len}, registry=reg)
        assert out == {"f": 1}              # non-jitted entries skipped
        assert reg.gauge("compiled_programs", entry="f").value == 1
        # steady state: same shape, same census — a retrace would move
        # the gauge, which is exactly what an alert watches
        f(np.ones(3, np.float32) * 2)
        assert program_census({"f": f}, registry=reg)["f"] == 1
        f(np.ones(5, np.float32))           # a NEW shape retraces
        assert program_census({"f": f}, registry=reg)["f"] == 2


# ------------------------------------------------- health gauge seeding
class TestHealthGauge:
    def test_fresh_tracker_seeds_ranks_up(self):
        """Review-caught r13: a scrape before the first flip must read
        the constructed tracker's all-up count, not the gauge's 0.0
        initial value (which an alert would read as total outage)."""
        from raft_tpu.resilience import ShardHealth

        ShardHealth(6)
        g = obsm.default_registry().gauge("health_ranks_up")
        assert g.value == 6.0

    def test_throwaway_trackers_do_not_pollute(self):
        """Review-caught r13: the per-call HealthReport normalization
        (resolve_shard_mask) builds a transient tracker — it must
        neither reset the gauge nor count fake flip transitions on
        every degraded search."""
        from raft_tpu.resilience import ShardHealth
        from raft_tpu.resilience.degraded import resolve_shard_mask
        from raft_tpu.resilience.health import HealthProbe, HealthReport

        reg = obsm.default_registry()
        ShardHealth(8).mark_down(2)      # the real tracker: 7 up
        flips = reg.counter("health_transitions_total",
                            direction="down").value
        report = HealthReport(probes={
            "allreduce": HealthProbe(ok=False, seconds=0.1, ranks=(3,)),
        })
        for _ in range(5):               # steady degraded traffic
            mask = resolve_shard_mask(report, 8)
        assert mask.tolist() == [1, 1, 1, 0, 1, 1, 1, 1]
        g = reg.gauge("health_ranks_up")
        assert g.value == 7.0            # the REAL tracker's count
        assert reg.counter("health_transitions_total",
                           direction="down").value == flips


# -------------------------------------------------- admission metrics
class TestAdmissionMetrics:
    def test_shed_and_occupancy_series(self):
        from raft_tpu.resilience import AdmissionController

        reg = MetricRegistry()
        ctrl = AdmissionController(max_concurrent=1, max_queue=1,
                                   registry=reg, name="t")
        ctrl.enqueue()
        ctrl.enqueue()
        with pytest.raises(errors.RaftOverloadError):
            ctrl.enqueue()
        assert reg.counter("admission_shed_total", controller="t",
                           reason="queue").value == 1
        assert reg.gauge("admission_queue_depth",
                         controller="t").value == 2.0
        ticket = ctrl.begin_service(2)
        assert reg.gauge("admission_in_flight",
                         controller="t").value == 2.0
        ctrl.finish_service(ticket)
        assert reg.gauge("admission_in_flight",
                         controller="t").value == 0.0
        assert reg.gauge("admission_service_ewma_ms",
                         controller="t").value >= 0.0


# ------------------------------------------------- per-list load feed
class TestListLoadMetrics:
    def test_round_trip_and_shard_filter(self):
        from raft_tpu.resilience import (
            measured_list_load, record_list_load,
        )

        reg = MetricRegistry()
        record_list_load([3, 0, 2, 0], shard=0, registry=reg)
        record_list_load([1, 1, 0, 0], shard=0, registry=reg)
        record_list_load([0, 7, 0, 0], shard=1, registry=reg)
        np.testing.assert_array_equal(
            measured_list_load(4, shard=0, registry=reg), [4, 1, 2, 0])
        np.testing.assert_array_equal(
            measured_list_load(4, registry=reg), [4, 8, 2, 0])

    def test_bounded_cardinality_folds_into_other(self):
        """The cardinality rule: a shard mints at most ``max_series``
        per-list series; the remainder folds into ``list="other"`` so
        traffic totals are conserved and the catalog stays bounded."""
        from raft_tpu.resilience import (
            measured_list_load, record_list_load,
        )

        reg = MetricRegistry()
        rows = np.arange(1, 9)          # 8 lists, loads 1..8
        record_list_load(rows, shard=0, registry=reg, max_series=3)
        per_list = [
            inst for inst in reg.series("serving_list_rows_total")
            if inst.labels.get("list") != "other"
        ]
        assert len(per_list) == 3
        other = [
            inst for inst in reg.series("serving_list_rows_total")
            if inst.labels.get("list") == "other"
        ]
        assert len(other) == 1
        total = sum(float(i.value)
                    for i in reg.series("serving_list_rows_total"))
        assert total == float(rows.sum())       # conserved
        # minted series keep recording; measured_ excludes "other"
        record_list_load(rows, shard=0, registry=reg, max_series=3)
        assert measured_list_load(8, registry=reg).sum() > 0

    def test_default_registry_emission(self):
        # record once into the process registry so the live-registry
        # side of the catalog-parity scan sees the dynamic name
        from raft_tpu.resilience import record_list_load

        record_list_load([1, 0], shard=7)
        names = obsm.default_registry().snapshot()
        assert "serving_list_rows_total" in names


# ------------------------------------- mutation journal telemetry
class TestMutationJournalTelemetry:
    def test_journal_overflow_counts_and_flight_marks(self):
        """ISSUE 20 satellite: an epoch-journal overflow silently
        downgrades stale readers to "refresh everything" — it must be
        attributable: mutation_journal_compacted_total counts the
        dropped entries and each overflow flight-marks the new floor.
        Driven through _journal_note directly (the real write path
        calls it per mutation) so the file stays host-side cheap."""
        import types

        from raft_tpu.spatial.ann import mutation as mut_mod

        fl = FlightRecorder()
        m = types.SimpleNamespace(
            _epoch_journal=[], _journal_floor=0, epoch=0,
            name="journal-tel", flight=fl,
        )
        counter = mut_mod._mseries("journal-tel")["journal_compacted"]
        before = counter.value
        overflow = 6
        for e in range(mut_mod._EPOCH_JOURNAL_CAP + overflow):
            m.epoch = e + 1
            mut_mod._journal_note(m, [e % 4])
        assert counter.value == before + overflow
        assert len(m._epoch_journal) == mut_mod._EPOCH_JOURNAL_CAP
        evs = fl.events(event="mutation_journal_compacted")
        assert len(evs) == overflow
        floors = [e["floor"] for e in evs]
        assert floors == sorted(floors) and floors[-1] == overflow
        assert all(e["index"] == "journal-tel" and e["dropped"] == 1
                   for e in evs)
        # below the floor the journal answers None = full refresh
        assert mut_mod.lists_changed_since(m, 0) is None

    def test_no_flight_recorder_is_fine(self):
        import types

        from raft_tpu.spatial.ann import mutation as mut_mod

        m = types.SimpleNamespace(
            _epoch_journal=[], _journal_floor=0, epoch=0,
            name="journal-tel2", flight=None,
        )
        for e in range(mut_mod._EPOCH_JOURNAL_CAP + 2):
            m.epoch = e + 1
            mut_mod._journal_note(m, None)


# -------------------------------------------- metric-catalog parity
class TestMetricCatalogParity:
    def test_every_emitted_series_has_a_catalog_row(self):
        """ISSUE 16 satellite: docs/observability.md's metric catalog
        cannot drift behind the code. Every literal series name passed
        to a ``.counter/.gauge/.histogram`` factory anywhere in
        raft_tpu/ — plus whatever the process registry actually holds
        by the time this file has run — must appear in a catalog row
        (same one-heading-per-rule bar as the static_analysis.md
        parity test)."""
        import ast
        from pathlib import Path

        repo = Path(__file__).resolve().parents[1]
        emitted: dict = {}
        for f in sorted((repo / "raft_tpu").rglob("*.py")):
            for node in ast.walk(ast.parse(f.read_text())):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in ("counter", "gauge",
                                               "histogram")
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    emitted.setdefault(node.args[0].value,
                                       f.relative_to(repo).as_posix())
        assert len(emitted) >= 20   # the scan itself must not go blind
        # series created dynamically (names built at runtime) surface
        # through the live registry this suite already exercised
        for name in obsm.default_registry().snapshot():
            emitted.setdefault(name, "<default_registry>")
        catalog = (repo / "docs" / "observability.md").read_text()
        start = catalog.index("## Metric catalog")
        end = catalog.find("\n## ", start + 1)
        section = catalog[start:end if end != -1 else None]
        missing = [f"{n} (from {src})" for n, src in sorted(emitted.items())
                   if f"`{n}`" not in section]
        assert not missing, (
            "series emitted but not in the docs/observability.md "
            "catalog:\n" + "\n".join(missing)
        )
