"""ANN index tests — recall-vs-brute-force oracles (the reference tests
ball cover against brute-force kNN, cpp/test/spatial/ball_cover.cu, and
relies on FAISS's own tests for IVF; here every index is native so each
gets a recall/exactness harness)."""

import numpy as np
import pytest

from raft_tpu.spatial import brute_force_knn
from raft_tpu.spatial.ann import (
    ivf_flat_build, ivf_flat_search, ivf_flat_search_grouped, IVFFlatParams,
    ivf_pq_build, ivf_pq_search, IVFPQParams,
    ivf_sq_build, ivf_sq_search, IVFSQParams,
    rbc_build_index, rbc_knn_query, rbc_all_knn_query,
)


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(3)
    # clustered data (ANN-friendly) + uniform noise
    centers = rng.standard_normal((20, 16)) * 5
    x = np.concatenate(
        [c + 0.5 * rng.standard_normal((100, 16)) for c in centers]
    ).astype(np.float32)
    queries = x[rng.choice(len(x), 50, replace=False)] + 0.05 * rng.standard_normal(
        (50, 16)
    ).astype(np.float32)
    return x.astype(np.float32), queries.astype(np.float32)


def recall(got_ids, want_ids):
    hits = 0
    for g, w in zip(got_ids, want_ids):
        hits += len(set(g.tolist()) & set(w.tolist()))
    return hits / want_ids.size


def test_ivf_flat_recall(dataset):
    x, q = dataset
    index = ivf_flat_build(x, IVFFlatParams(n_lists=32, seed=0))
    d, i = ivf_flat_search(index, q, 10, n_probes=8)
    bd, bi = brute_force_knn(x, q, 10, metric="l2")
    r = recall(np.asarray(i), np.asarray(bi))
    assert r > 0.95, r
    # distances are true L2 distances of the returned ids
    row = np.linalg.norm(x[np.asarray(i)[0, 0]] - q[0])
    np.testing.assert_allclose(np.asarray(d)[0, 0], row, rtol=1e-3, atol=1e-3)


def test_ivf_flat_full_probe_exact(dataset):
    x, q = dataset
    index = ivf_flat_build(x, IVFFlatParams(n_lists=16, seed=0))
    d, i = ivf_flat_search(index, q, 5, n_probes=16)  # all lists
    bd, bi = brute_force_knn(x, q, 5, metric="l2")
    assert recall(np.asarray(i), np.asarray(bi)) == 1.0
    np.testing.assert_allclose(np.asarray(d), np.asarray(bd), rtol=1e-3, atol=1e-3)


def test_ivf_pq_recall(dataset):
    x, q = dataset
    index = ivf_pq_build(x, IVFPQParams(n_lists=16, pq_dim=8, seed=0))
    # refined search (default refine_ratio=2): near-exact recall
    d, i = ivf_pq_search(index, q, 10, n_probes=8)
    bd, bi = brute_force_knn(x, q, 10, metric="l2")
    r = recall(np.asarray(i), np.asarray(bi))
    assert r > 0.9, r
    # refined distances are exact squared L2 of the returned ids
    row = np.linalg.norm(x[np.asarray(i)[0, 0]] - q[0]) ** 2
    np.testing.assert_allclose(np.asarray(d)[0, 0], row, rtol=1e-3, atol=1e-3)


def test_ivf_pq_unrefined_recall(dataset):
    x, q = dataset
    index = ivf_pq_build(
        x, IVFPQParams(n_lists=16, pq_dim=8, seed=0, store_raw=False)
    )
    assert index.vectors_sorted is None
    d, i = ivf_pq_search(index, q, 10, n_probes=8)  # no raw -> pure ADC
    _, bi = brute_force_knn(x, q, 10, metric="l2")
    r = recall(np.asarray(i), np.asarray(bi))
    assert r > 0.6, r  # quantized: lossy but far above chance (10/2000)


def test_ivf_pq_subsample_blocked_build(dataset):
    """Large-n build path (subsampled training + streaming blocked encode)
    must produce an index with recall comparable to the one-shot build."""
    x, q = dataset
    index = ivf_pq_build(
        x,
        IVFPQParams(
            n_lists=16, pq_dim=8, seed=0,
            train_size=600, encode_block=512,  # forces both paths
        ),
    )
    d, i = ivf_pq_search(index, q, 10, n_probes=8)
    _, bi = brute_force_knn(x, q, 10, metric="l2")
    r = recall(np.asarray(i), np.asarray(bi))
    assert r > 0.9, r
    # codes cover every row exactly once: sorted ids are a permutation
    ids = np.sort(np.asarray(index.storage.sorted_ids))
    np.testing.assert_array_equal(ids, np.arange(len(x)))


def test_ivf_pq_refine_dataset_external(dataset):
    """store_raw=False + refine_dataset must match the store_raw=True
    refined search (codes-only index memory, caller-held vectors)."""
    from raft_tpu.spatial.ann.ivf_pq import ivf_pq_search_grouped

    x, q = dataset
    p_raw = IVFPQParams(n_lists=16, pq_dim=8, seed=0, store_raw=True)
    p_codes = IVFPQParams(n_lists=16, pq_dim=8, seed=0, store_raw=False)
    idx_raw = ivf_pq_build(x, p_raw)
    idx_codes = ivf_pq_build(x, p_codes)
    d1, i1 = ivf_pq_search(idx_raw, q, 10, n_probes=8)
    d2, i2 = ivf_pq_search(idx_codes, q, 10, n_probes=8, refine_dataset=x)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2),
                               rtol=1e-5, atol=1e-5)
    # grouped path too
    g1, gi1 = ivf_pq_search_grouped(idx_raw, q, 10, n_probes=8, qcap=len(q))
    g2, gi2 = ivf_pq_search_grouped(
        idx_codes, q, 10, n_probes=8, qcap=len(q), refine_dataset=x
    )
    np.testing.assert_array_equal(np.asarray(gi1), np.asarray(gi2))


def test_grouped_qcap_drop_accounting(dataset):
    """Adversarially clustered queries (all probing the same lists) must
    (a) be measurable via probe_drop_stats under a small explicit qcap and
    (b) keep recall when qcap=None auto-sizes from the actual probe map."""
    from raft_tpu.spatial.ann.common import (
        coarse_probe, probe_drop_stats, resolve_qcap,
    )
    import jax.numpy as jnp

    x, _ = dataset
    # every query lands in the same blob -> one hot list
    rng = np.random.default_rng(11)
    hot = x[0] + 0.05 * rng.standard_normal((64, x.shape[1])).astype(
        np.float32
    )
    index = ivf_flat_build(x, IVFFlatParams(n_lists=32, seed=0))
    probes, _ = coarse_probe(
        jnp.asarray(hot, jnp.float32), index.centroids, 4
    )
    stats = probe_drop_stats(probes, 32, qcap=8)
    assert stats["dropped"] > 0 and stats["frac"] > 0.2, stats
    # auto qcap resolves high enough that almost nothing drops
    qcap = resolve_qcap(probes, 32, 64, 4)
    assert probe_drop_stats(probes, 32, qcap)["frac"] <= 0.02
    # and the auto-sized grouped search matches the per-query path
    _, i_pq = ivf_flat_search(index, hot, 10, n_probes=4)
    _, i_g = ivf_flat_search_grouped(index, hot, 10, n_probes=4)
    assert recall(np.asarray(i_g), np.asarray(i_pq)) > 0.98


def test_ivf_pq_refine_ratio_sweep(dataset):
    """Recall must be monotone-ish in refine_ratio and hit >=0.95 at 4x."""
    x, q = dataset
    index = ivf_pq_build(x, IVFPQParams(n_lists=16, pq_dim=8, seed=0))
    _, bi = brute_force_knn(x, q, 10, metric="l2")
    r4 = recall(
        np.asarray(ivf_pq_search(index, q, 10, n_probes=8, refine_ratio=4.0)[1]),
        np.asarray(bi),
    )
    assert r4 >= 0.95, r4


def test_ivf_flat_grouped_matches_per_query(dataset):
    """List-major (query-grouped) search returns exactly the per-query
    path's results when qcap can't truncate."""
    x, q = dataset
    index = ivf_flat_build(x, IVFFlatParams(n_lists=32, seed=0))
    d1, i1 = ivf_flat_search(index, q, 10, n_probes=6)
    d2, i2 = ivf_flat_search_grouped(index, q, 10, n_probes=6,
                                     qcap=len(q))
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    # values agree to f32 reduction-order noise (different matmul layouts)
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2),
                               rtol=2e-3, atol=1e-3)


def test_ivf_flat_grouped_default_qcap_recall(dataset):
    x, q = dataset
    index = ivf_flat_build(x, IVFFlatParams(n_lists=32, seed=0))
    _, i1 = ivf_flat_search(index, q, 10, n_probes=6)
    _, i3 = ivf_flat_search_grouped(index, q, 10, n_probes=6)
    assert recall(np.asarray(i3), np.asarray(i1)) > 0.95


def test_ivf_pq_codes_shapes(dataset):
    x, _ = dataset
    index = ivf_pq_build(x, IVFPQParams(n_lists=8, pq_dim=4, pq_bits=6))
    assert index.codebooks.shape == (4, 64, 4)
    assert index.codes_sorted.shape == (len(x) + 1, 4)
    assert int(np.asarray(index.codes_sorted).max()) < 64


def test_ivf_sq_recall(dataset):
    x, q = dataset
    index = ivf_sq_build(x, IVFSQParams(n_lists=16, seed=0))
    d, i = ivf_sq_search(index, q, 10, n_probes=16)  # all lists -> SQ error only
    _, bi = brute_force_knn(x, q, 10, metric="l2")
    r = recall(np.asarray(i), np.asarray(bi))
    assert r > 0.9, r


def test_ball_cover_certified_exact(dataset):
    x, q = dataset
    index = rbc_build_index(x, seed=0)
    d, i, exact = rbc_knn_query(index, q, 5, n_probes=20)
    bd, bi = brute_force_knn(x, q, 5, metric="l2")
    ex = np.asarray(exact)
    # certified-exact queries must match brute force exactly
    for qi in np.nonzero(ex)[0]:
        np.testing.assert_allclose(
            np.asarray(d)[qi], np.asarray(bd)[qi], rtol=1e-3, atol=1e-3
        )
    # and most queries should certify with 20 of ~45 balls probed
    assert ex.mean() > 0.7, ex.mean()
    assert recall(np.asarray(i), np.asarray(bi)) > 0.95


def test_ball_cover_all_probes_exact(dataset):
    x, q = dataset
    index = rbc_build_index(x, n_landmarks=12, seed=0)
    d, i, exact = rbc_knn_query(index, q, 5, n_probes=12)
    assert np.asarray(exact).all()
    _, bi = brute_force_knn(x, q, 5, metric="l2")
    assert recall(np.asarray(i), np.asarray(bi)) == 1.0


def test_ball_cover_all_knn(dataset):
    x, _ = dataset
    index = rbc_build_index(x, n_landmarks=10, seed=0)
    d, i, exact = rbc_all_knn_query(index, 4, n_probes=10)
    # each point's nearest neighbor is itself
    np.testing.assert_array_equal(np.asarray(i)[:, 0], np.arange(len(x)))


@pytest.fixture(scope="module")
def geo_dataset():
    """(lat, lon) radian pairs clustered around world cities."""
    rng = np.random.default_rng(4)
    hubs = np.deg2rad(
        rng.uniform([-60, -170], [70, 170], size=(25, 2))
    ).astype(np.float32)
    pts = hubs[rng.integers(0, 25, 3000)] + rng.normal(
        0, 0.02, (3000, 2)
    ).astype(np.float32)
    pts[:, 0] = np.clip(pts[:, 0], -np.pi / 2, np.pi / 2)
    q = pts[rng.integers(0, 3000, 200)] + rng.normal(
        0, 0.01, (200, 2)
    ).astype(np.float32)
    q[:, 0] = np.clip(q[:, 0], -np.pi / 2, np.pi / 2)
    return pts, q.astype(np.float32)


def test_ball_cover_haversine_oracle(geo_dataset):
    """Haversine ball cover vs the exact haversine_knn oracle — the
    reference's geospatial dispatch (ball_cover.cuh:38-39, 88-94)."""
    from raft_tpu.spatial.knn import haversine_knn

    x, q = geo_dataset
    index = rbc_build_index(x, n_landmarks=40, seed=1, metric="haversine")
    assert index.metric == "haversine"
    bd, bi = haversine_knn(x, q, 5)
    d, i, exact = rbc_knn_query(index, q, 5, n_probes=40)
    # full probing: exhaustively exact (the reference guarantee)
    assert np.asarray(exact).all()
    np.testing.assert_allclose(
        np.asarray(d), np.asarray(bd), rtol=1e-5, atol=1e-6
    )
    assert recall(np.asarray(i), np.asarray(bi)) == 1.0


def test_ball_cover_haversine_certificate(geo_dataset):
    """Partial probing: certified queries must match the oracle exactly."""
    from raft_tpu.spatial.knn import haversine_knn

    x, q = geo_dataset
    index = rbc_build_index(x, n_landmarks=40, seed=1, metric="haversine")
    _, bi = haversine_knn(x, q, 5)
    d, i, exact = rbc_knn_query(index, q, 5, n_probes=10)
    ex = np.asarray(exact)
    assert ex.mean() > 0.5, ex.mean()   # clustered geo data certifies fast
    got, want = np.asarray(i)[ex], np.asarray(bi)[ex]
    assert recall(got, want) == 1.0


def test_ivf_flat_sq_max_list_cap(dataset):
    """max_list_cap splits swollen lists for Flat and SQ (the padded-list
    tax fix, docs/ivf_scale.md); results stay exact for full probing."""
    x, q = dataset
    bd, bi = brute_force_knn(x, q, 5, metric="sqeuclidean")
    flat = ivf_flat_build(
        x, IVFFlatParams(n_lists=8, kmeans_n_iters=6, max_list_cap=64)
    )
    assert flat.storage.max_list <= 64
    nl = flat.centroids.shape[0]
    assert nl >= 8
    _, fi = ivf_flat_search(flat, q, 5, n_probes=nl)
    assert recall(np.asarray(fi), np.asarray(bi)) == 1.0
    # grouped path handles the prime-ish post-split list count
    from raft_tpu.spatial.ann import ivf_flat_search_grouped

    _, gi = ivf_flat_search_grouped(
        flat, q, 5, n_probes=nl, qcap=q.shape[0], list_block=32
    )
    assert recall(np.asarray(gi), np.asarray(bi)) == 1.0

    sq = ivf_sq_build(
        x, IVFSQParams(n_lists=8, kmeans_n_iters=6, max_list_cap=64)
    )
    assert sq.storage.max_list <= 64
    _, si = ivf_sq_search(sq, q, 5, n_probes=sq.centroids.shape[0])
    assert recall(np.asarray(si), np.asarray(bi)) > 0.9  # int8 rounding


def test_ball_cover_haversine_validation():
    with pytest.raises(Exception):
        rbc_build_index(np.zeros((10, 3), np.float32), metric="haversine")
    with pytest.raises(Exception):
        rbc_build_index(np.zeros((10, 2), np.float32), metric="cosine")


def test_ivf_pq_grouped_matches_per_query_recall(dataset):
    """List-major grouped PQ search (one-hot ADC matmul) must reach the
    per-query path's recall at the same n_probes/refine settings."""
    from raft_tpu.spatial.ann.ivf_pq import ivf_pq_search_grouped

    x, q = dataset
    pq = ivf_pq_build(x, IVFPQParams(n_lists=16, pq_dim=4, kmeans_n_iters=8))
    bd, bi = brute_force_knn(x, q, 10, metric="sqeuclidean")
    _, i1 = ivf_pq_search(pq, q, 10, n_probes=8, refine_ratio=4.0)
    _, i2 = ivf_pq_search_grouped(
        pq, q, 10, n_probes=8, refine_ratio=4.0, qcap=q.shape[0]
    )
    r1 = recall(np.asarray(i1), np.asarray(bi))
    r2 = recall(np.asarray(i2), np.asarray(bi))
    assert r2 >= r1 - 0.05, (r1, r2)
    assert r2 > 0.85, r2


def test_ivf_pq_grouped_exact_selection(dataset):
    """exact_selection=True restores exact lax.top_k candidate selection
    in the refined grouped path (the pre-approx_min_k behavior) without
    disabling refinement — recall must match or beat the approx mode."""
    from raft_tpu.spatial.ann.ivf_pq import ivf_pq_search_grouped

    x, q = dataset
    pq = ivf_pq_build(x, IVFPQParams(n_lists=16, pq_dim=4, kmeans_n_iters=8))
    bd, bi = brute_force_knn(x, q, 10, metric="sqeuclidean")
    _, ia = ivf_pq_search_grouped(
        pq, q, 10, n_probes=8, refine_ratio=4.0, qcap=q.shape[0]
    )
    de, ie = ivf_pq_search_grouped(
        pq, q, 10, n_probes=8, refine_ratio=4.0, qcap=q.shape[0],
        exact_selection=True,
    )
    ra = recall(np.asarray(ia), np.asarray(bi))
    re = recall(np.asarray(ie), np.asarray(bi))
    # approx_min_k's pool is not a strict subset of the exact pool, so
    # exact mode is not mathematically >= approx — compare with slack and
    # require an absolute floor like the neighboring tests
    assert re >= ra - 0.05, (ra, re)
    assert re > 0.85, re
    # refined distances are exact f32 regardless of selection mode
    assert np.all(np.isfinite(np.asarray(de)[:, 0]))


def test_grouped_throughput_qcap_mode(dataset):
    """qcap="throughput" resolves to ~0.75x mean occupancy on PQ and
    Flat grouped searches and keeps recall on clustered data."""
    from raft_tpu.spatial.ann.common import throughput_qcap
    from raft_tpu.spatial.ann.ivf_pq import ivf_pq_search_grouped
    from raft_tpu.spatial.ann import ivf_flat_search_grouped

    x, q = dataset
    assert throughput_qcap(4096, 16, 2048) == 24   # the measured knee
    bd, bi = brute_force_knn(x, q, 10, metric="sqeuclidean")
    pq = ivf_pq_build(x, IVFPQParams(n_lists=16, pq_dim=4, kmeans_n_iters=8))
    _, i1 = ivf_pq_search_grouped(
        pq, q, 10, n_probes=16, refine_ratio=4.0, qcap="throughput"
    )
    assert recall(np.asarray(i1), np.asarray(bi)) > 0.8
    flat = ivf_flat_build(x, IVFFlatParams(n_lists=16, kmeans_n_iters=8))
    _, i2 = ivf_flat_search_grouped(
        flat, q, 10, n_probes=16, qcap="throughput"
    )
    assert recall(np.asarray(i2), np.asarray(bi)) > 0.8
    # np integer caps are valid; bogus strings raise the typed error
    _, i3 = ivf_pq_search_grouped(
        pq, q, 10, n_probes=8, refine_ratio=4.0, qcap=np.int32(64)
    )
    assert recall(np.asarray(i3), np.asarray(bi)) > 0.8
    with pytest.raises(ValueError):
        ivf_pq_search_grouped(pq, q, 10, n_probes=8, qcap="bogus")


def test_ivf_pq_grouped_unrefined(dataset):
    from raft_tpu.spatial.ann.ivf_pq import ivf_pq_search_grouped

    x, q = dataset
    pq = ivf_pq_build(x, IVFPQParams(n_lists=16, pq_dim=4, kmeans_n_iters=8))
    bd, bi = brute_force_knn(x, q, 10, metric="sqeuclidean")
    _, ids = ivf_pq_search_grouped(
        pq, q, 10, n_probes=8, refine_ratio=0.0, qcap=q.shape[0]
    )
    assert recall(np.asarray(ids), np.asarray(bi)) > 0.5


def test_index_serialization_roundtrip(tmp_path, dataset):
    """save_index/load_index roundtrip for every index family: identical
    search results after reload (the reference keeps FAISS indexes
    memory-only; persistence is native here)."""
    from raft_tpu.spatial.ann import save_index, load_index
    from raft_tpu.spatial.ann.ivf_pq import ivf_pq_search_grouped

    import jax.numpy as jnp

    x, q = dataset
    pq = ivf_pq_build(x, IVFPQParams(n_lists=16, pq_dim=4, kmeans_n_iters=6))
    flat = ivf_flat_build(x, IVFFlatParams(n_lists=16, kmeans_n_iters=6))
    sq = ivf_sq_build(x, IVFSQParams(n_lists=16, kmeans_n_iters=6))
    # bf16 storage must round-trip too (ml_dtypes arrays need the bit-view
    # path — raw np.savez of bfloat16 stores void bytes that cannot load)
    flat16 = ivf_flat_build(
        x.astype(jnp.bfloat16), IVFFlatParams(n_lists=16, kmeans_n_iters=6)
    )
    for name, idx, search in [
        ("flat", flat, lambda i: ivf_flat_search(i, q, 5, n_probes=4)),
        ("flat_bf16", flat16, lambda i: ivf_flat_search(i, q, 5, n_probes=4)),
        ("sq", sq, lambda i: ivf_sq_search(i, q, 5, n_probes=4)),
        ("pq", pq, lambda i: ivf_pq_search(i, q, 5, n_probes=4)),
        ("pq_grouped", pq,
         lambda i: ivf_pq_search_grouped(i, q, 5, n_probes=4, qcap=64)),
    ]:
        path = tmp_path / f"{name}.npz"
        save_index(idx, path)
        loaded = load_index(path)
        d0, i0 = search(idx)
        d1, i1 = search(loaded)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1),
                                      err_msg=name)
        np.testing.assert_allclose(np.asarray(d0), np.asarray(d1),
                                   rtol=1e-6, err_msg=name)


def test_sparse_colblock_index_serialization(tmp_path, rng_np):
    from raft_tpu.spatial.ann import save_index, load_index
    from raft_tpu.sparse import csr_from_scipy, sparse_brute_force_knn
    from raft_tpu.sparse.distance import sparse_colblock_index_build
    from tests.test_sparse import _scipy_rand

    idx_sp = _scipy_rand(rng_np, 300, 20_000, 30)
    qry = csr_from_scipy(_scipy_rand(rng_np, 50, 20_000, 30))
    layout = sparse_colblock_index_build(idx_sp, 4096)
    path = tmp_path / "sparse.npz"
    save_index(layout, path)
    loaded = load_index(path)
    d0, i0 = sparse_brute_force_knn(layout, qry, 5, metric="sqeuclidean")
    d1, i1 = sparse_brute_force_knn(loaded, qry, 5, metric="sqeuclidean")
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    np.testing.assert_allclose(np.asarray(d0), np.asarray(d1), rtol=1e-6)


def test_approx_knn_generic_dispatch(dataset):
    """Generic build/search entry (reference approx_knn_build_index /
    approx_knn_search dynamic dispatch on the param type)."""
    from raft_tpu.spatial.ann import (
        approx_knn_build_index, approx_knn_search,
    )
    from raft_tpu import errors as err
    import pytest

    x, q = dataset
    bd, bi = brute_force_knn(x, q, 10, metric="sqeuclidean")
    for params in (
        IVFFlatParams(n_lists=16, kmeans_n_iters=6),
        IVFPQParams(n_lists=16, pq_dim=4, kmeans_n_iters=6),
        IVFSQParams(n_lists=16, kmeans_n_iters=6),
    ):
        idx = approx_knn_build_index(x, params)
        d, i = approx_knn_search(idx, q, 10, n_probes=8)
        r = recall(np.asarray(i), np.asarray(bi))
        assert r > 0.8, (type(params).__name__, r)
    with pytest.raises(err.RaftException):
        approx_knn_build_index(x, object())


def test_grouped_streamed_partials_match(dataset):
    """stream_partials=True (the bounded-HBM scan path, VERDICT r4
    weak-5) must return bit-identical results to the materialized
    regroup path for BOTH grouped engines — same block kernel, only the
    partials' route to the query-major pool differs."""
    from raft_tpu.spatial.ann.ivf_pq import ivf_pq_search_grouped

    x, q = dataset
    flat = ivf_flat_build(x, IVFFlatParams(n_lists=32, seed=0))
    kw = dict(n_probes=6, qcap=len(q))
    d1, i1 = ivf_flat_search_grouped(flat, q, 10, stream_partials=False,
                                     **kw)
    d2, i2 = ivf_flat_search_grouped(flat, q, 10, stream_partials=True,
                                     **kw)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-6)

    pq = ivf_pq_build(x, IVFPQParams(n_lists=32, pq_dim=4, seed=0))
    pkw = dict(n_probes=6, qcap=len(q), refine_ratio=4.0,
               exact_selection=True)
    d3, i3 = ivf_pq_search_grouped(pq, q, 10, stream_partials=False, **pkw)
    d4, i4 = ivf_pq_search_grouped(pq, q, 10, stream_partials=True, **pkw)
    np.testing.assert_array_equal(np.asarray(i3), np.asarray(i4))
    np.testing.assert_allclose(np.asarray(d3), np.asarray(d4), rtol=1e-6)

    # a qcap tight enough to drop pairs: drops must match too
    d5, i5 = ivf_pq_search_grouped(
        pq, q, 10, n_probes=6, qcap=8, refine_ratio=4.0,
        exact_selection=True, stream_partials=False,
    )
    d6, i6 = ivf_pq_search_grouped(
        pq, q, 10, n_probes=6, qcap=8, refine_ratio=4.0,
        exact_selection=True, stream_partials=True,
    )
    np.testing.assert_array_equal(np.asarray(i5), np.asarray(i6))


def test_throughput_qcap_guardrail(dataset):
    """qcap='throughput' on an adversarial (hot-list-concentrated) probe
    map must emit a visible drop warning through the library logger, and
    max_drop_frac must fall back to a drop-bounded auto qcap (VERDICT r4
    weak-4: the mode's silent 0.27-recall hazard)."""
    from raft_tpu.core import logger
    from raft_tpu.spatial.ann import common as ann_common

    x, _ = dataset
    index = ivf_flat_build(x, IVFFlatParams(n_lists=32, seed=0))
    # adversarial queries: tight copies of ONE dataset point — every
    # query's probes collapse onto the same few hot lists
    hot = np.repeat(x[:1], 96, axis=0) + 0.01 * np.random.default_rng(
        0
    ).standard_normal((96, x.shape[1])).astype(np.float32)

    records = []
    logger.set_callback(lambda lvl, msg: records.append(msg))
    try:
        ann_common._THROUGHPUT_AUDITED.clear()
        ivf_flat_search_grouped(index, hot, 5, n_probes=4,
                                qcap="throughput")
        assert any("qcap='throughput'" in m and "drops" in m
                   for m in records), records
        # audit is once-per-signature: a second identical call is silent
        n0 = len(records)
        ivf_flat_search_grouped(index, hot, 5, n_probes=4,
                                qcap="throughput")
        assert len(records) == n0

        # bounded mode: falls back to an auto qcap that respects the cap
        records.clear()
        ann_common._THROUGHPUT_AUDITED.clear()
        d, i = ivf_flat_search_grouped(
            index, hot, 5, n_probes=4, qcap="throughput",
            qcap_max_drop_frac=0.02,
        )
        assert any("falling back" in m for m in records), records
        # fallback result matches a generously-capped search
        _, i_ref = ivf_flat_search_grouped(index, hot, 5, n_probes=4,
                                           qcap=96)
        np.testing.assert_array_equal(np.asarray(i), np.asarray(i_ref))
    finally:
        logger.set_callback(None)


def test_coarse_probe_chunk_path_matches_topk():
    """coarse_probe routes wide centroid sets (nl % 128 == 0, nl/128 >=
    4*n_probes) through the exact chunk-min select — the 100M-scale
    probe's hot path. Its probes must equal the direct lax.top_k path's
    (chunk_min_select_k is value-exact; index equality additionally
    needs tie-free distances, which continuous random data gives with
    probability 1 — this pins the routing AND the primitive's index
    arithmetic at a genuinely-engaged shape, which no other test
    reaches)."""
    import jax
    import jax.numpy as jnp

    from raft_tpu.spatial.ann.common import coarse_probe
    from raft_tpu.spatial.selection import chunk_min_select_k

    rng = np.random.default_rng(11)
    q = jnp.asarray(rng.standard_normal((37, 24)).astype(np.float32))
    cents = jnp.asarray(rng.standard_normal((1024, 24)).astype(np.float32))
    probes, d2 = coarse_probe(q, cents, 2)        # 1024/128 = 8 >= 4*2
    _, want = jax.lax.top_k(-d2, 2)
    np.testing.assert_array_equal(np.asarray(probes), np.asarray(want))
    # the primitive itself, at a wide many-k shape (values AND indices)
    v1, i1 = chunk_min_select_k(d2, 7)
    nv, i2 = jax.lax.top_k(-d2, 7)
    np.testing.assert_allclose(np.asarray(v1), -np.asarray(nv), rtol=0)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
