"""linalg tests vs numpy oracles (analog of reference cpp/test/linalg/*)."""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import linalg
from raft_tpu.linalg import matrix_vector as mv


@pytest.fixture
def mats(rng_np):
    a = rng_np.standard_normal((17, 9)).astype(np.float32)
    b = rng_np.standard_normal((17, 9)).astype(np.float32)
    return a, b


class TestElementwise:
    def test_basic_ops(self, mats):
        a, b = mats
        np.testing.assert_allclose(linalg.add(a, b), a + b, rtol=1e-6)
        np.testing.assert_allclose(linalg.subtract(a, b), a - b, rtol=1e-6)
        np.testing.assert_allclose(linalg.eltwise_multiply(a, b), a * b, rtol=1e-6)
        np.testing.assert_allclose(linalg.add_scalar(a, 2.0), a + 2, rtol=1e-6)
        np.testing.assert_allclose(linalg.multiply_scalar(a, 3.0), a * 3, rtol=1e-6)

    def test_map_then_reduce(self, mats):
        a, b = mats
        got = linalg.map_then_reduce(lambda x, y: (x - y) ** 2, a, b)
        np.testing.assert_allclose(got, ((a - b) ** 2).sum(), rtol=1e-4)

    def test_axpy_dot(self, rng_np):
        x = rng_np.standard_normal(33).astype(np.float32)
        y = rng_np.standard_normal(33).astype(np.float32)
        np.testing.assert_allclose(linalg.axpy(2.0, x, y), y + 2 * x, rtol=1e-6)
        np.testing.assert_allclose(linalg.dot(x, y), np.dot(x, y), rtol=1e-5)

    def test_sign_flip(self, mats):
        a, _ = mats
        f = np.asarray(linalg.sign_flip(a))
        idx = np.abs(f).argmax(axis=0)
        assert (f[idx, np.arange(f.shape[1])] >= 0).all()

    def test_reciprocal_setzero(self):
        x = np.array([2.0, 0.0, 4.0], np.float32)
        got = np.asarray(linalg.reciprocal(x, scalar=1.0, setzero=True))
        np.testing.assert_allclose(got, [0.5, 0.0, 0.25])


class TestReduction:
    def test_norms(self, mats):
        a, _ = mats
        np.testing.assert_allclose(linalg.row_norm(a, linalg.L2Norm),
                                   (a ** 2).sum(1), rtol=1e-5)
        np.testing.assert_allclose(linalg.row_norm(a, linalg.L2Norm, do_sqrt=True),
                                   np.linalg.norm(a, axis=1), rtol=1e-5)
        np.testing.assert_allclose(linalg.col_norm(a, linalg.L1Norm),
                                   np.abs(a).sum(0), rtol=1e-5)
        np.testing.assert_allclose(linalg.row_norm(a, linalg.LinfNorm),
                                   np.abs(a).max(1), rtol=1e-6)

    def test_coalesced_strided(self, mats):
        a, _ = mats
        np.testing.assert_allclose(linalg.coalesced_reduction(a), a.sum(1), rtol=1e-4)
        np.testing.assert_allclose(linalg.strided_reduction(a), a.sum(0), rtol=1e-4)

    def test_reduce_rows_by_key(self, rng_np):
        x = rng_np.standard_normal((50, 7)).astype(np.float32)
        keys = rng_np.integers(0, 5, 50).astype(np.int32)
        got = np.asarray(linalg.reduce_rows_by_key(x, keys, 5))
        want = np.zeros((5, 7), np.float32)
        for i, k in enumerate(keys):
            want[k] += x[i]
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)

    def test_reduce_rows_by_key_weighted(self, rng_np):
        x = rng_np.standard_normal((30, 4)).astype(np.float32)
        keys = rng_np.integers(0, 3, 30).astype(np.int32)
        w = rng_np.random(30).astype(np.float32)
        got = np.asarray(linalg.reduce_rows_by_key(x, keys, 3, weights=w))
        want = np.zeros((3, 4), np.float32)
        for i, k in enumerate(keys):
            want[k] += w[i] * x[i]
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)

    def test_reduce_cols_by_key(self, rng_np):
        x = rng_np.standard_normal((6, 20)).astype(np.float32)
        keys = rng_np.integers(0, 4, 20).astype(np.int32)
        got = np.asarray(linalg.reduce_cols_by_key(x, keys, 4))
        want = np.zeros((6, 4), np.float32)
        for j, k in enumerate(keys):
            want[:, k] += x[:, j]
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)

    def test_mse_divide(self, mats):
        a, b = mats
        np.testing.assert_allclose(linalg.mean_squared_error(a, b),
                                   ((a - b) ** 2).mean(), rtol=1e-5)
        num = np.array([1.0, 2.0], np.float32)
        den = np.array([2.0, 0.0], np.float32)
        np.testing.assert_allclose(
            linalg.binary_div_skip_zero(num, den, return_zero=True), [0.5, 0.0])


class TestGemm:
    def test_gemm_variants(self, rng_np):
        a = rng_np.standard_normal((5, 7)).astype(np.float32)
        b = rng_np.standard_normal((7, 3)).astype(np.float32)
        c = rng_np.standard_normal((5, 3)).astype(np.float32)
        np.testing.assert_allclose(linalg.gemm(a, b), a @ b, rtol=1e-5)
        np.testing.assert_allclose(linalg.gemm(a.T, b, trans_a=True), a @ b, rtol=1e-5)
        np.testing.assert_allclose(linalg.gemm(a, b.T, trans_b=True), a @ b, rtol=1e-5)
        np.testing.assert_allclose(
            linalg.gemm(a, b, alpha=2.0, beta=0.5, c=c), 2 * a @ b + 0.5 * c, rtol=1e-5)

    def test_gemv(self, rng_np):
        a = rng_np.standard_normal((5, 7)).astype(np.float32)
        x = rng_np.standard_normal(7).astype(np.float32)
        np.testing.assert_allclose(linalg.gemv(a, x), a @ x, rtol=1e-5)


class TestMatrixVector:
    def test_along_rows_cols(self, mats):
        a, _ = mats
        v_row = np.arange(a.shape[1], dtype=np.float32)
        v_col = np.arange(a.shape[0], dtype=np.float32)
        np.testing.assert_allclose(
            mv.matrix_vector_add(a, v_row, along_rows=True), a + v_row[None, :], rtol=1e-6)
        np.testing.assert_allclose(
            mv.matrix_vector_mul(a, v_col, along_rows=False), a * v_col[:, None], rtol=1e-6)


class TestDecomp:
    def test_eig(self, rng_np):
        a = rng_np.standard_normal((12, 12)).astype(np.float32)
        sym = (a + a.T) / 2
        v, w = linalg.eig_dc(sym)
        np.testing.assert_allclose(np.asarray(v) @ np.diag(np.asarray(w)) @ np.asarray(v).T,
                                   sym, atol=1e-3)

    def test_eig_sel(self, rng_np):
        a = rng_np.standard_normal((10, 10)).astype(np.float32)
        sym = (a + a.T) / 2
        v, w = linalg.eig_sel_dc(sym, 3, largest=True)
        w_np = np.linalg.eigvalsh(sym)
        np.testing.assert_allclose(np.asarray(w), w_np[-3:], atol=1e-3)

    def test_svd_qr(self, rng_np):
        a = rng_np.standard_normal((15, 6)).astype(np.float32)
        u, s, v = linalg.svd_qr(a)
        rec = np.asarray(linalg.svd_reconstruction(u, s, v))
        np.testing.assert_allclose(rec, a, atol=1e-3)

    def test_svd_eig_tall(self, rng_np):
        a = rng_np.standard_normal((40, 5)).astype(np.float32)
        u, s, v = linalg.svd_eig(a)
        s_np = np.linalg.svd(a, compute_uv=False)
        np.testing.assert_allclose(np.asarray(s), s_np, rtol=1e-2, atol=1e-2)
        rec = np.asarray(linalg.svd_reconstruction(u, s, v))
        np.testing.assert_allclose(rec, a, atol=1e-2)

    def test_rsvd(self, rng_np):
        # low-rank matrix: rsvd should recover the spectrum
        u0 = rng_np.standard_normal((60, 5)).astype(np.float32)
        v0 = rng_np.standard_normal((5, 30)).astype(np.float32)
        a = u0 @ v0
        u, s, v = linalg.rsvd_fixed_rank(a, k=5, p=8, n_iters=3)
        s_np = np.linalg.svd(a, compute_uv=False)[:5]
        np.testing.assert_allclose(np.asarray(s), s_np, rtol=1e-2)

    def test_lstsq_variants(self, rng_np):
        a = rng_np.standard_normal((40, 6)).astype(np.float32)
        w_true = rng_np.standard_normal(6).astype(np.float32)
        b = a @ w_true
        for fn in (linalg.lstsq_svd_qr, linalg.lstsq_eig, linalg.lstsq_qr,
                   linalg.lstsq_svd_jacobi):
            w = np.asarray(fn(a, b))
            np.testing.assert_allclose(w, w_true, atol=2e-2), fn.__name__

    def test_cholesky_rank1(self, rng_np):
        a = rng_np.standard_normal((6, 6)).astype(np.float32)
        spd = a @ a.T + 6 * np.eye(6, dtype=np.float32)
        l_np = np.linalg.cholesky(spd)
        # grow the factor one row at a time
        l = jnp.zeros((6, 6), jnp.float32)
        for n in range(1, 7):
            l = l.at[n - 1, :n].set(spd[n - 1, :n])
            l = linalg.cholesky_rank1_update(l, n)
        np.testing.assert_allclose(np.asarray(l), l_np, atol=1e-3)


class TestLanczos:
    def test_restarted_convergence_large_laplacian(self, rng_np):
        """tol must actually control accuracy: thick-restart Lanczos with
        ncv << n on a 50k-node graph Laplacian, validated against
        scipy.sparse.linalg.eigsh — a single fixed-ncv pass at this
        ncv/n ratio does NOT converge (the round-2 VERDICT's missing
        item; reference restarted solver lanczos.cuh:745-1089)."""
        import scipy.sparse as sp
        import scipy.sparse.linalg as spla
        from raft_tpu.linalg.lanczos import lanczos_solver

        n = 50_000
        rng = np.random.default_rng(0)
        # ring + random chords: connected, irregular spectrum
        rows = np.arange(n)
        ring = np.stack([rows, (rows + 1) % n])
        chords = rng.integers(0, n, size=(2, n // 2))
        ij = np.concatenate([ring, chords], axis=1)
        a = sp.coo_matrix(
            (np.ones(ij.shape[1], np.float64), (ij[0], ij[1])), (n, n)
        )
        a = ((a + a.T) > 0).astype(np.float64)
        lap = sp.diags(np.asarray(a.sum(1)).ravel()) - a
        w_ref = spla.eigsh(lap, k=4, sigma=None, which="SM",
                           return_eigenvectors=False)[::-1]

        lap32 = lap.tocsr().astype(np.float32)
        data = jnp.asarray(lap32.data)
        indices = jnp.asarray(lap32.indices)
        indptr = jnp.asarray(lap32.indptr)

        import jax as _jax

        row_ids = jnp.searchsorted(
            indptr, jnp.arange(data.shape[0]), side="right") - 1

        def matvec(v):
            # simple CSR spmv via segment_sum (jit-compatible)
            return _jax.ops.segment_sum(
                data * v[indices], row_ids, num_segments=n)

        w, vecs, res, restarts = lanczos_solver(
            matvec, n, 4, ncv=48, tol=1e-6, return_info=True
        )
        assert int(restarts) >= 1  # the single pass was NOT enough
        np.testing.assert_allclose(np.asarray(w), w_ref, atol=5e-4)
        # residuals honor the tolerance contract: tol-relative with the
        # documented f32-eps * spectral-scale floor (Gershgorin bounds
        # the Laplacian spectrum by twice the max degree)
        lam_max_bound = 2.0 * float(np.asarray(a.sum(1)).max())
        floor = 10 * np.finfo(np.float32).eps * lam_max_bound
        thr = np.maximum(1e-6 * np.maximum(np.abs(np.asarray(w)), 1.0),
                         floor) * 1.5
        assert np.all(np.asarray(res) <= thr), (res, thr)

    def test_smallest_largest(self, rng_np):
        n = 60
        a = rng_np.standard_normal((n, n)).astype(np.float32)
        sym = ((a + a.T) / 2).astype(np.float32)
        w_np = np.linalg.eigvalsh(sym)
        matvec = lambda v: jnp.asarray(sym) @ v
        w_small, v_small = linalg.lanczos_smallest_eigenvectors(matvec, n, 3, ncv=40)
        np.testing.assert_allclose(np.asarray(w_small), w_np[:3], atol=1e-2)
        w_large, _ = linalg.lanczos_largest_eigenvectors(matvec, n, 3, ncv=40)
        np.testing.assert_allclose(np.asarray(w_large), w_np[-3:][::-1], atol=1e-2)
        # residual check ||A v - w v||
        for i in range(3):
            v = np.asarray(v_small[:, i])
            r = sym @ v - np.asarray(w_small)[i] * v
            # f32 + ncv=40 Krylov: residual ~3e-3 relative to ||A||~10
            assert np.linalg.norm(r) < 5e-2
