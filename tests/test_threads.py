"""The concurrency auditor (ISSUE 16): tier-3 static rules over seeded
positive/negative fixtures, the cross-module lock-order graph
(edges, cycles, drift vs ``ci/checks/lock_order.json``), the
``TracedLock`` runtime tracer, ``threading.excepthook`` crash routing,
``BackgroundCompactor.stop()`` crash propagation, and the executor
close-vs-submit race under ``RAFT_TPU_LOCKCHECK=1`` — ended with the
repo-wide self-gate (``ci/run.sh threads`` runs this file with every
lock traced, so the pinned order is asserted under real
interleavings)."""

import json
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from raft_tpu import errors
from raft_tpu.analysis.engine import lint_source
from raft_tpu.analysis.threads import runtime as lockcheck
from raft_tpu.analysis.threads.lock_order import (
    build_graph,
    drift_findings,
    load_order_file,
)
from raft_tpu.analysis.threads.rules import THREAD_RULES
from raft_tpu.obs import crash as obs_crash
from raft_tpu.obs import metrics as obsm
from raft_tpu.obs.flight import FlightRecorder

REPO = Path(__file__).resolve().parents[1]


def names(findings):
    return [f.rule for f in findings]


def tlint(src):
    return lint_source(src, rules=THREAD_RULES)


@pytest.fixture()
def lockcheck_on():
    """Tracing on with a clean slate; restores the prior gate and
    pinned order afterward (the env-driven CI run keeps its state)."""
    prev = lockcheck.set_enabled(True)
    prev_order = lockcheck.pinned_order()
    lockcheck.clear()
    yield
    lockcheck.clear()
    lockcheck.pin_order(prev_order)
    lockcheck.set_enabled(prev)


# ------------------------------------------------ static: shared state
class TestUnguardedSharedState:
    def test_unlocked_read_of_guarded_attr_flagged(self):
        src = """import threading
class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []
    def put(self, x):
        with self._lock:
            self._items.append(x)
    def peek(self):
        return self._items
"""
        fs = tlint(src)
        assert names(fs) == ["unguarded-shared-state"]
        assert "_items" in fs[0].message and "peek" in fs[0].message

    def test_all_access_under_lock_clean(self):
        src = """import threading
class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []
    def put(self, x):
        with self._lock:
            self._items.append(x)
    def peek(self):
        with self._lock:
            return list(self._items)
"""
        assert tlint(src) == []

    def test_init_only_attrs_not_guarded(self):
        """Immutable config read everywhere must not be census'd: the
        write-under-lock requirement is what keeps `self.dim` out."""
        src = """import threading
class Box:
    def __init__(self, dim):
        self._lock = threading.Lock()
        self.dim = dim
        self._n = 0
    def bump(self):
        with self._lock:
            self._n += 1
    def shape(self):
        return self.dim
"""
        assert tlint(src) == []

    def test_condition_canonicalizes_to_underlying_lock(self):
        """`with self._work:` IS `with self._lock:` for the census —
        the executor's two-conditions-one-lock idiom."""
        src = """import threading
class Ex:
    def __init__(self):
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._pending = []
    def put(self, x):
        with self._work:
            self._pending.append(x)
    def flush(self):
        with self._lock:
            out, self._pending = self._pending, []
        return out
"""
        assert tlint(src) == []

    def test_nested_def_resets_held_stack(self):
        """A thread-target closure runs on ANOTHER thread: the lexical
        lock around `Thread(target=work)` does not guard the body."""
        src = """import threading
class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._result = None
    def submit(self):
        with self._lock:
            def work():
                self._result = 1
            t = threading.Thread(target=work)
            t.start()
    def poll(self):
        with self._lock:
            self._result = None
"""
        fs = tlint(src)
        assert names(fs) == ["unguarded-shared-state"]

    def test_private_helper_inference(self):
        """A private method whose intra-class call sites ALL hold the
        lock executes under it — the documented 'under _lock' helper
        idiom (`_flush_wait_s`)."""
        src = """import threading
class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._pending = []
    def _oldest(self):
        return self._pending[0]
    def tick(self):
        with self._lock:
            self._pending.append(1)
            return self._oldest()
"""
        assert tlint(src) == []

    def test_suppression(self):
        src = """import threading
class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []
    def put(self, x):
        with self._lock:
            self._items.append(x)
    def peek(self):
        return len(self._items)  # jaxlint: disable=unguarded-shared-state
"""
        assert tlint(src) == []


# ------------------------------------------------ static: traced bodies
class TestLockInTracedBody:
    def test_module_lock_in_jitted_body_flagged(self):
        src = """import threading
import jax
_glock = threading.Lock()
@jax.jit
def f(x):
    with _glock:
        return x + 1
"""
        fs = tlint(src)
        assert "lock-in-traced-body" in names(fs)

    def test_lock_outside_traced_body_clean(self):
        src = """import threading
import jax
_glock = threading.Lock()
@jax.jit
def f(x):
    return x + 1
def g(x):
    with _glock:
        return f(x)
"""
        assert tlint(src) == []


# ------------------------------------------------ static: blocking calls
class TestBlockingCallUnderLock:
    def test_condition_wait_on_own_lock_clean(self):
        src = """import threading
class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._n = 0
    def bump(self):
        with self._cv:
            self._n += 1
    def park(self):
        with self._cv:
            while not self._n:
                self._cv.wait(0.1)
"""
        assert tlint(src) == []

    def test_event_wait_under_lock_flagged(self):
        src = """import threading
class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._n = 0
    def bump(self):
        with self._lock:
            self._n += 1
    def park(self):
        with self._lock:
            self._stop.wait(1.0)
"""
        assert "blocking-call-under-lock" in names(tlint(src))

    def test_future_result_under_lock_flagged(self):
        src = """import threading
class C:
    def __init__(self):
        self._lock = threading.Lock()
    def get(self, fut):
        with self._lock:
            return fut.result(1.0)
"""
        assert names(tlint(src)) == ["blocking-call-under-lock"]

    def test_thread_join_under_lock_flagged_incl_alias(self):
        src = """import threading
class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._t = threading.Thread(target=print)
    def bad_direct(self):
        with self._lock:
            self._t.join()
    def bad_alias(self):
        with self._lock:
            t = self._t
            t.join()
    def fine(self):
        with self._lock:
            t = self._t
        t.join()
"""
        fs = tlint(src)
        assert names(fs) == ["blocking-call-under-lock"] * 2

    def test_str_join_never_trips(self):
        src = """import threading
class C:
    def __init__(self):
        self._lock = threading.Lock()
    def fmt(self, parts):
        with self._lock:
            return ",".join(parts)
"""
        assert tlint(src) == []

    def test_wait_with_extra_lock_held_flagged(self):
        """`wait` releases only its OWN lock; an outer lock stays held
        while the thread parks."""
        src = """import threading
class C:
    def __init__(self):
        self._a = threading.Lock()
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
    def park(self):
        with self._a:
            with self._cv:
                self._cv.wait(0.1)
"""
        fs = tlint(src)
        assert names(fs) == ["blocking-call-under-lock"]
        assert "stays held" in fs[0].message

    def test_fsync_under_lock_flagged_outside_clean(self):
        """ISSUE 20 satellite: durable IO is a blocking call — the WAL
        group-commit contract is fsync OUTSIDE the lock, publish the
        durable LSN under it."""
        src = """import os
import threading
class W:
    def __init__(self):
        self._lock = threading.Lock()
        self._durable = 0
    def bad(self, fd):
        with self._lock:
            os.fsync(fd)
    def good(self, fd, lsn):
        os.fsync(fd)
        with self._lock:
            self._durable = lsn
"""
        fs = tlint(src)
        assert names(fs) == ["blocking-call-under-lock"]
        assert "os.fsync" in fs[0].message
        assert "outside the lock" in fs[0].message

    def test_flush_under_lock_flagged(self):
        src = """import threading
class W:
    def __init__(self):
        self._lock = threading.Lock()
        self._f = open("/dev/null", "wb")
    def bad(self):
        with self._lock:
            self._f.flush()
    def fine(self):
        self._f.flush()
"""
        fs = tlint(src)
        assert names(fs) == ["blocking-call-under-lock"]
        assert "parks behind" in fs[0].message

    def test_non_file_flush_under_lock_clean(self):
        """REVIEW fix: only FILE receivers trip the .flush() check — a
        buffer/queue/logger flush under a lock parks behind nothing
        and must not fail the gate."""
        src = """import threading
class Batcher:
    def __init__(self, sink):
        self._lock = threading.Lock()
        self._sink = sink
    def drain(self):
        with self._lock:
            self._sink.flush()
"""
        assert tlint(src) == []

    def test_file_alias_flush_under_lock_flagged(self):
        """A local bound to open() (or to a file attr) is still a file
        receiver for the .flush() check."""
        src = """import threading
class W:
    def __init__(self):
        self._lock = threading.Lock()
    def bad(self, path):
        f = open(path, "wb")
        with self._lock:
            f.flush()
"""
        fs = tlint(src)
        assert names(fs) == ["blocking-call-under-lock"]
        assert "parks behind" in fs[0].message


# ------------------------------------------------ static: sleep
class TestSleepUnderLock:
    def test_sleep_under_lock_flagged(self):
        src = """import threading
import time
class C:
    def __init__(self):
        self._lock = threading.Lock()
    def tick(self):
        with self._lock:
            time.sleep(0.01)
"""
        assert names(tlint(src)) == ["sleep-under-lock"]

    def test_sleep_outside_lock_clean(self):
        src = """import threading
import time
class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
    def tick(self):
        with self._lock:
            self._n += 1
        time.sleep(0.01)
"""
        assert tlint(src) == []


# ------------------------------------------------ the lock-order graph
GRAPH_A = """import threading
class Outer:
    def __init__(self, inner: "Inner"):
        self._lock = threading.Lock()
        self.inner = inner
        self._n = 0
    def tick(self):
        with self._lock:
            self._n += 1
            self.inner.bump()
"""
GRAPH_B = """import threading
class Inner:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
    def bump(self):
        with self._lock:
            self._n += 1
"""


class TestLockOrderGraph:
    def _write(self, tmp_path, **files):
        for name, src in files.items():
            (tmp_path / f"{name}.py").write_text(src)
        return tmp_path

    def test_cross_object_edge_via_annotation(self, tmp_path):
        self._write(tmp_path, outer=GRAPH_A, inner=GRAPH_B)
        g = build_graph([tmp_path], root=tmp_path)
        assert ("Outer._lock", "Inner._lock") in g.edge_list()
        assert g.cycles() == []

    def test_nested_with_and_module_lock_edges(self, tmp_path):
        src = """import threading
_mlock = threading.Lock()
class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
    def tick(self):
        with self._lock:
            self._n += 1
            with _mlock:
                pass
"""
        self._write(tmp_path, mod=src)
        g = build_graph([tmp_path], root=tmp_path)
        assert ("C._lock", "mod._mlock") in g.edge_list()

    def test_cycle_detected(self, tmp_path):
        src = """import threading
class A:
    def __init__(self, b: "B"):
        self._lock = threading.Lock()
        self.b = b
        self._n = 0
    def fwd(self):
        with self._lock:
            self._n += 1
            self.b.bump()
    def bump(self):
        with self._lock:
            self._n += 1
class B:
    def __init__(self, a: "A"):
        self._lock = threading.Lock()
        self.a = a
        self._n = 0
    def bump(self):
        with self._lock:
            self._n += 1
    def back(self):
        with self._lock:
            self._n += 1
            self.a.bump()
"""
        self._write(tmp_path, cyc=src)
        g = build_graph([tmp_path], root=tmp_path)
        cycles = g.cycles()
        assert len(cycles) == 1
        assert set(cycles[0]) == {"A._lock", "B._lock"}

    def test_drift_new_and_stale_edges(self, tmp_path):
        self._write(tmp_path, outer=GRAPH_A, inner=GRAPH_B)
        g = build_graph([tmp_path], root=tmp_path)
        op = tmp_path / "lock_order.json"
        # empty blessed order: the observed edge is NEW
        fs = drift_findings(g, {}, op)
        assert [f.rule for f in fs] == ["lock-order-drift"]
        assert "new acquired-while-held edge" in fs[0].message
        # blessed exactly: clean
        assert drift_findings(g, {"Outer._lock": ["Inner._lock"]}, op) == []
        # transitively implied: clean (matches the runtime tracer)
        order = {"Outer._lock": ["Mid._lock"], "Mid._lock": ["Inner._lock"]}
        assert not any("new" in f.message
                       for f in drift_findings(g, order, op))
        # a blessed edge with no observed path is STALE
        fs = drift_findings(g, {"Outer._lock": ["Inner._lock"],
                                "Ghost._lock": ["Inner._lock"]}, op)
        assert len(fs) == 1 and "stale blessed edge" in fs[0].message

    def test_cli_write_then_clean_then_drift(self, tmp_path):
        self._write(tmp_path, outer=GRAPH_A, inner=GRAPH_B)
        op = tmp_path / "lock_order.json"

        def run(*extra):
            return subprocess.run(
                [sys.executable, "-m", "raft_tpu.analysis", "--threads",
                 "--lock-order", str(op), str(tmp_path), *extra],
                capture_output=True, text=True, cwd=REPO,
            )

        # unblessed edge fails; --write-lock-order pins it; clean after
        assert run().returncode == 1
        w = run("--write-lock-order")
        assert w.returncode == 0, w.stdout + w.stderr
        data = json.loads(op.read_text())
        assert data["order"] == {"Outer._lock": ["Inner._lock"]}
        assert run().returncode == 0
        # a new nested acquisition drifts red again
        (tmp_path / "extra.py").write_text("""import threading
_zlock = threading.Lock()
class Z:
    def __init__(self, inner: "Inner"):
        self._lock = threading.Lock()
        self.inner = inner
        self._n = 0
    def tick(self):
        with self._lock:
            self._n += 1
            self.inner.bump()
""")
        p = run()
        assert p.returncode == 1 and "Z._lock -> Inner._lock" in p.stdout

    def test_cli_refuses_to_bless_cycles(self, tmp_path):
        (tmp_path / "cyc.py").write_text("""import threading
_a = threading.Lock()
_b = threading.Lock()
def fwd():
    with _a:
        with _b:
            pass
def back():
    with _b:
        with _a:
            pass
""")
        op = tmp_path / "lock_order.json"
        p = subprocess.run(
            [sys.executable, "-m", "raft_tpu.analysis", "--threads",
             "--lock-order", str(op), str(tmp_path),
             "--write-lock-order"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert p.returncode == 1
        assert "refusing to bless a cyclic order" in p.stderr
        assert not op.exists()

    def test_list_rules(self):
        p = subprocess.run(
            [sys.executable, "-m", "raft_tpu.analysis", "--threads",
             "--list-rules"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert p.returncode == 0
        for r in THREAD_RULES:
            assert f"{r.name}:" in p.stdout
        assert "lock-order-drift:" in p.stdout
        assert "lock-order-cycle:" in p.stdout


# ------------------------------------------------ the runtime tracer
class TestTracedLockRuntime:
    def test_blessed_direct_and_transitive_clean(self, lockcheck_on):
        lockcheck.pin_order({"A": ["B"], "B": ["C"]})
        A, B, C = (lockcheck.make_lock(n) for n in "ABC")
        with A:
            with B:
                pass
        with A:
            with C:            # A -> B -> C transitively blessed
                pass
        lockcheck.assert_clean()
        assert "A" in lockcheck.observed_edges()

    def test_inversion_and_unpinned_recorded(self, lockcheck_on):
        lockcheck.pin_order({"A": ["B"]})
        A, B, D = (lockcheck.make_lock(n) for n in "ABD")
        with B:
            with A:            # reverse of the blessed path
                pass
        with A:
            with D:            # edge the graph has never seen
                pass
        kinds = [v.kind for v in lockcheck.violations()]
        assert kinds == ["inversion", "unpinned"]
        with pytest.raises(AssertionError, match="inversion"):
            lockcheck.assert_clean()

    def test_self_reacquire_raises(self, lockcheck_on):
        lockcheck.pin_order({})
        A = lockcheck.make_lock("A")
        with pytest.raises(RuntimeError, match="re-acquiring"):
            with A:
                with A:
                    pass
        assert lockcheck.held_locks() == ()   # stack unwound cleanly

    def test_try_acquire_skips_order_check(self, lockcheck_on):
        lockcheck.pin_order({"A": ["B"]})
        A, B = lockcheck.make_lock("A"), lockcheck.make_lock("B")
        with B:
            assert A.acquire(blocking=False)   # try-lock cannot deadlock
            A.release()
        lockcheck.assert_clean()

    def test_condition_wait_keeps_stack_truthful(self, lockcheck_on):
        lockcheck.pin_order({})
        L = lockcheck.make_lock("CvLock")
        cv = lockcheck.make_condition(L)
        state = []

        def waiter():
            with cv:
                while not state:
                    cv.wait(0.5)
                state.append(lockcheck.held_locks())

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        with cv:
            state.append("go")
            cv.notify()
        t.join(2)
        assert not t.is_alive()
        assert state[-1] == ("CvLock",)   # re-acquired after wait
        lockcheck.assert_clean()

    def test_hold_outlier_and_histogram_feed(self, lockcheck_on,
                                             monkeypatch):
        import raft_tpu.analysis.threads.runtime as rt

        monkeypatch.setattr(rt, "HOLD_OUTLIER_MS", 5.0)
        lockcheck.pin_order({})
        prev_obs = obsm.set_enabled(True)
        try:
            A = lockcheck.make_lock("OutlierLock")
            with A:
                time.sleep(0.02)
            outs = lockcheck.hold_outliers()
            assert any(o.lock == "OutlierLock" and o.held_ms >= 5.0
                       for o in outs)
            snap = obsm.default_registry().snapshot()
            assert any(row["labels"].get("lock") == "OutlierLock"
                       for row in snap["lock_hold_ms"])
        finally:
            obsm.set_enabled(prev_obs)

    def test_violation_counter_feed(self, lockcheck_on):
        lockcheck.pin_order({"A": ["B"]})
        prev_obs = obsm.set_enabled(True)
        try:
            A, B = lockcheck.make_lock("A"), lockcheck.make_lock("B")
            with B:
                with A:
                    pass
            snap = obsm.default_registry().snapshot()
            assert any(
                row["labels"] == {"kind": "inversion"}
                for row in snap["lock_order_violations_total"]
            )
        finally:
            obsm.set_enabled(prev_obs)

    def test_note_dispatch(self, lockcheck_on):
        lockcheck.pin_order({})
        lockcheck.note_dispatch("x")          # nothing held: no-op
        lockcheck.assert_clean()
        A = lockcheck.make_lock("A")
        with A:
            lockcheck.note_dispatch("dev")
        vs = lockcheck.violations()
        assert [v.kind for v in vs] == ["hold-while-dispatch"]
        assert vs[0].acquiring == "dev"

    def test_disabled_hands_back_plain_lock(self):
        prev = lockcheck.set_enabled(False)
        try:
            L = lockcheck.make_lock("P")
            assert not isinstance(L, lockcheck.TracedLock)
        finally:
            lockcheck.set_enabled(prev)

    def test_pinned_order_loads_from_repo_file(self):
        order, baseline = load_order_file(
            REPO / "ci" / "checks" / "lock_order.json")
        assert "ServingExecutor._lock" in order
        assert lockcheck.load_pinned_order(
            REPO / "ci" / "checks" / "lock_order.json")


# ------------------------------------------------ excepthook (sat. 1)
class TestThreadCrashRouting:
    # the injected crash IS the point — pytest's threadexception
    # plugin would flag it as an unhandled thread exception
    @pytest.mark.filterwarnings(
        "ignore::pytest.PytestUnhandledThreadExceptionWarning")
    def test_uncaught_exception_counts_and_flight_event(self):
        obs_crash.install_excepthook()
        obs_crash.install_excepthook()   # idempotent
        fr = FlightRecorder(capacity=16, name="crash-test")
        prev_obs = obsm.set_enabled(True)
        obs_crash.set_flight_sink(fr)
        try:
            def boom():
                raise ValueError("injected crash")

            t = threading.Thread(target=boom, name="crashy-worker",
                                 daemon=True)
            t.start()
            t.join(5)
            snap = obsm.default_registry().snapshot()
            assert any(
                row["labels"].get("thread") == "crashy-worker"
                for row in snap.get("thread_uncaught_total", [])
            )
            evs = [e for e in fr.events()
                   if e["event"] == "thread_uncaught"]
            assert evs and evs[-1]["thread"] == "crashy-worker"
            assert evs[-1]["exc_type"] == "ValueError"
        finally:
            obs_crash.set_flight_sink(None)
            obsm.set_enabled(prev_obs)


# ------------------------------------------------ compactor (sat. 2)
class TestCompactorStop:
    def test_crash_then_stop_reraises(self, monkeypatch):
        from raft_tpu.spatial.ann import mutation as mut

        comp = mut.BackgroundCompactor()

        def exploding(mindex, **kw):
            raise RuntimeError("compaction exploded")

        monkeypatch.setattr(mut, "compact", exploding)
        assert comp.submit(object()) is True
        deadline = time.monotonic() + 5
        while comp.busy and time.monotonic() < deadline:
            time.sleep(0.005)
        with pytest.raises(RuntimeError, match="compaction exploded"):
            comp.stop(timeout_s=5.0)
        # the error is consumed exactly once: a second stop is quiet,
        # and the compactor accepts new work again
        comp.stop(timeout_s=5.0)
        assert comp.submit(object()) is True
        comp.join(5.0)

    def test_stop_without_worker_is_quiet(self):
        from raft_tpu.spatial.ann.mutation import BackgroundCompactor

        BackgroundCompactor().stop(timeout_s=0.1)


# ------------------------------------------------ executor race (sat. 3)
class TestExecutorCloseRace:
    def test_close_racing_submits_under_tracer(self, lockcheck_on):
        """Submits racing close() either resolve or raise cleanly;
        nothing wedges; the traced locks see zero order violations."""
        from raft_tpu.resilience import AdmissionController
        from raft_tpu.serving import ServingExecutor

        lockcheck.load_pinned_order(
            REPO / "ci" / "checks" / "lock_order.json")
        dim = 4

        def dispatch(batch, **_rt):
            return (batch,)

        ex = ServingExecutor(
            dispatch, (2, 4), dim=dim, flush_age_s=0.001,
            max_in_flight=2,
            admission=AdmissionController(max_concurrent=4, max_queue=64),
            flight=FlightRecorder(capacity=64, name="close-race"),
        )
        results = {"ok": 0, "closed": 0, "shed": 0}
        res_lock = threading.Lock()
        futures = []

        def submitter(seed):
            rng = np.random.default_rng(seed)
            for _ in range(40):
                try:
                    f = ex.submit(rng.standard_normal(
                        (2, dim)).astype(np.float32))
                except errors.RaftLogicError:
                    with res_lock:
                        results["closed"] += 1
                    return
                except errors.RaftOverloadError:
                    with res_lock:
                        results["shed"] += 1
                    continue
                with res_lock:
                    futures.append(f)

        threads = [threading.Thread(target=submitter, args=(s,))
                   for s in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.05)
        ex.close(timeout_s=30)
        for t in threads:
            t.join(10)
        assert not any(t.is_alive() for t in threads)
        # both loops actually exited — no wedged drain thread
        assert not ex._batcher.is_alive()
        assert not ex._drainer.is_alive()
        # every accepted in-flight future resolved (result or exception)
        for f in futures:
            assert f.done()
            if f.exception() is None:
                out = f.result()
                assert out[0].shape == (2, dim)
        # submits AFTER close raise cleanly
        with pytest.raises(errors.RaftLogicError, match="closed"):
            ex.submit(np.zeros((2, dim), np.float32))
        # the tracer saw the pinned production order and nothing else
        lockcheck.assert_clean()
        assert not any(v.kind == "hold-while-dispatch"
                       for v in lockcheck.violations())


# ------------------------------------------------ the repo self-gate
@pytest.mark.slow
def test_repo_threads_clean():
    """`python -m raft_tpu.analysis --threads` over the gated tree:
    zero findings, zero drift, cycle-free — the `ci/run.sh threads`
    gate as a test."""
    p = subprocess.run(
        [sys.executable, "-m", "raft_tpu.analysis", "--threads",
         "--lock-order", "ci/checks/lock_order.json",
         "raft_tpu", "tests", "bench", "ci", "bench.py",
         "__graft_entry__.py"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert p.returncode == 0, p.stdout + p.stderr
