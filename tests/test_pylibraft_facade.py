"""pylibraft-facade + utils tests (reference
python/pylibraft/pylibraft/test/test_distance.py patterns)."""

import numpy as np
import pytest

from raft_tpu.pylibraft import Handle, Stream, distance, cluster, neighbors
from raft_tpu.utils import Seive, Pow2, round_up_safe, div_rounding_up


def test_handle_stream():
    h = Handle(n_streams=4)
    assert h.n_lanes == 4
    s = Stream("work")
    s.sync()
    h.sync()


def test_pairwise_distance_facade(rng_np):
    X = rng_np.standard_normal((20, 8)).astype(np.float32)
    Y = rng_np.standard_normal((15, 8)).astype(np.float32)
    out = np.zeros((20, 15), np.float32)
    D = distance.pairwise_distance(X, Y, out, metric="euclidean")
    want = np.sqrt(((X[:, None] - Y[None]) ** 2).sum(-1))
    np.testing.assert_allclose(np.asarray(D), want, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-4)  # written back


def test_fused_argmin_facade(rng_np):
    X = rng_np.standard_normal((12, 6)).astype(np.float32)
    Y = rng_np.standard_normal((9, 6)).astype(np.float32)
    idx = np.asarray(distance.fused_l2_nn_argmin(X, Y))
    want = ((X[:, None] - Y[None]) ** 2).sum(-1).argmin(1)
    np.testing.assert_array_equal(idx, want)


def test_cluster_facade(rng_np):
    from raft_tpu.random import make_blobs, RngState

    X, _ = make_blobs(300, 6, n_clusters=3, cluster_std=0.3, state=RngState(2))
    cents, labels, inertia, n_iter = cluster.fit(X, 3, seed=1)
    assert cents.shape == (3, 6)
    pred = np.asarray(cluster.predict(X, cents))
    np.testing.assert_array_equal(pred, np.asarray(labels))
    assert float(cluster.cluster_cost(X, cents)) == pytest.approx(
        float(inertia), rel=1e-4
    )


def test_neighbors_facade(rng_np):
    X = rng_np.standard_normal((500, 16)).astype(np.float32)
    q = X[:10]
    d, i = neighbors.brute_force.knn(X, q, 5)
    np.testing.assert_array_equal(np.asarray(i)[:, 0], np.arange(10))
    index = neighbors.ivf_flat.build(X, neighbors.ivf_flat.IndexParams(n_lists=8))
    d2, i2 = neighbors.ivf_flat.search(index, q, 5, n_probes=8)
    np.testing.assert_array_equal(np.asarray(i2)[:, 0], np.arange(10))


def test_seive():
    s = Seive(100)
    assert s.is_prime(97)
    assert not s.is_prime(91)
    np.testing.assert_array_equal(s.primes()[:5], [2, 3, 5, 7, 11])


def test_pow2():
    p = Pow2(16)
    assert p.round_up(17) == 32
    assert p.round_down(17) == 16
    assert p.mod(19) == 3
    assert p.div(32) == 2
    assert p.is_aligned(48)
    with pytest.raises(ValueError):
        Pow2(12)
    assert round_up_safe(10, 3) == 12
    assert div_rounding_up(10, 3) == 4


def test_lazy_submodules():
    import raft_tpu

    assert raft_tpu.stats.r2_score is not None
    assert raft_tpu.lap.solve_lap is not None
    with pytest.raises(AttributeError):
        raft_tpu.nonexistent_module
