"""Packaging contract — the analog of the reference's install surface
(build.sh targets, python/pylibraft/setup.py, conda recipes) and its
include-test (python/raft/raft/test/test_raft.py importability check).

Asserts the distribution is installable: metadata parses, the package
imports from a clean subprocess, the native runtime's C++ source ships
with the package (the wheel is pure-Python; the .so builds lazily at
first use and is never version-controlled).
"""

import os
import subprocess
import sys

import raft_tpu

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_version_matches_pyproject():
    try:
        import tomllib
    except ImportError:  # py<3.11
        return
    with open(os.path.join(REPO, "pyproject.toml"), "rb") as f:
        meta = tomllib.load(f)
    assert meta["project"]["version"] == raft_tpu.__version__
    assert meta["project"]["name"] == "raft-tpu"


def test_native_source_ships_in_package_dir():
    src = os.path.join(
        os.path.dirname(raft_tpu.__file__), "native", "src", "host_algos.cpp"
    )
    assert os.path.exists(src), "native runtime source must ship with the package"


def test_no_binaries_in_tree():
    pkg = os.path.dirname(raft_tpu.__file__)
    committed = subprocess.run(
        ["git", "ls-files", "--", "*.so"], capture_output=True, text=True,
        cwd=REPO,
    )
    if committed.returncode == 0:  # inside a git checkout
        assert committed.stdout.strip() == "", (
            f"compiled binaries are version-controlled: {committed.stdout}"
        )
    del pkg


def test_clean_subprocess_import():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-c",
         "import raft_tpu; print(raft_tpu.__version__)"],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == raft_tpu.__version__
