"""Core runtime tests (analog of reference cpp/test/{handle.cpp,logger.cpp,
interruptible.cu,mdarray.cu})."""

import threading
import time

import jax
import numpy as np
import pytest

import raft_tpu
from raft_tpu.core import logger, mdarray
from raft_tpu.core.interruptible import Interruptible, InterruptedException
from raft_tpu.core.annotate import annotate, push_range, pop_range
from raft_tpu.core.resources import Resources


class TestResources:
    def test_default(self):
        res = Resources()
        assert res.device is not None
        assert not res.has_mesh

    def test_mesh_slot(self, mesh8):
        res = Resources()
        res.set_mesh(mesh8)
        assert res.get_mesh() is mesh8
        res.set_sub_mesh("sub", mesh8)
        assert res.get_sub_mesh("sub") is mesh8

    def test_no_mesh_raises(self):
        with pytest.raises(RuntimeError):
            Resources().get_mesh()

    def test_sync(self):
        Resources().sync()

    def test_default_singleton(self):
        assert raft_tpu.get_default_resources() is raft_tpu.get_default_resources()


class TestLogger:
    def test_levels_and_callback(self):
        captured = []
        logger.set_callback(lambda lvl, msg: captured.append(msg))
        logger.set_level(logger.INFO)
        logger.info("hello %d", 42)
        logger.debug("not captured")
        assert any("hello 42" in m for m in captured)
        assert not any("not captured" in m for m in captured)
        logger.set_level(logger.DEBUG)
        logger.debug("now captured")
        assert any("now captured" in m for m in captured)
        logger.set_callback(None)

    def test_should_log_for(self):
        logger.set_level(logger.WARN)
        assert logger.should_log_for(logger.ERROR)
        assert not logger.should_log_for(logger.INFO)
        logger.set_level(logger.INFO)

    def test_flush_callback(self):
        flushed = []
        logger.set_flush(lambda: flushed.append(1))
        logger.flush()
        assert flushed
        logger.set_flush(None)


class TestInterruptible:
    def test_yield_no_cancel(self):
        Interruptible.yield_now()  # should not raise

    def test_cancel_self(self):
        Interruptible.get_token().cancel()
        with pytest.raises(InterruptedException):
            Interruptible.yield_now()
        # token cleared after raising
        Interruptible.yield_now()

    def test_cancel_other_thread(self):
        errors = []
        started = threading.Event()
        tid_holder = []

        def worker():
            tid_holder.append(threading.get_ident())
            Interruptible.get_token()
            started.set()
            for _ in range(200):
                try:
                    Interruptible.yield_now()
                except InterruptedException:
                    errors.append("interrupted")
                    return
                time.sleep(0.005)

        t = threading.Thread(target=worker)
        t.start()
        started.wait()
        Interruptible.cancel_thread(tid_holder[0])
        t.join(timeout=5)
        assert errors == ["interrupted"]

    def test_synchronize(self):
        x = jax.numpy.ones((8,))
        Interruptible.synchronize(x)

    def test_registry_prunes_dead_threads(self):
        """Dead threads' tokens are dropped at the next get_token, so the
        registry stays bounded (the reference's weak-pointer registry
        property, interruptible.hpp:140-168)."""
        def hold_token():
            Interruptible.get_token()

        threads = [threading.Thread(target=hold_token) for _ in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        Interruptible.get_token()  # triggers the prune
        live = {t.ident for t in threading.enumerate()}
        with Interruptible._registry_lock:
            stale = [k for k in Interruptible._registry if k not in live]
        assert stale == []

    def test_synchronize_timeout_raises(self):
        """timeout_s bounds the wait on not-ready work with a
        RaftTimeoutError (the deadline primitive under
        resilience.dispatch_with_deadline)."""
        from raft_tpu import errors
        from raft_tpu.testing import faults

        fn, _ = faults.inject_delay(10.0)
        out = fn(jax.numpy.arange(4.0))
        t0 = time.perf_counter()
        with pytest.raises(errors.RaftTimeoutError):
            Interruptible.synchronize(out, timeout_s=0.15)
        assert time.perf_counter() - t0 < 5.0
        # ready work never times out, even with a tiny budget
        Interruptible.synchronize(jax.numpy.ones(3), timeout_s=1e-6)

    def test_cancel_beats_timeout(self):
        """Cancellation and deadline compose: whichever fires first wins.
        A cancel arriving well before a generous deadline must surface as
        InterruptedException, not be masked into a timeout."""
        from raft_tpu.testing import faults

        fn, _ = faults.inject_delay(10.0)
        out = fn(jax.numpy.arange(4.0))
        state = {}
        started = threading.Event()
        tid_holder = []

        def waiter():
            tid_holder.append(threading.get_ident())
            started.set()
            try:
                Interruptible.synchronize(out, timeout_s=30.0)
                state["result"] = "completed"
            except InterruptedException:
                state["result"] = "interrupted"
            except Exception as e:  # pragma: no cover
                state["result"] = type(e).__name__

        t = threading.Thread(target=waiter)
        t.start()
        started.wait()
        faults.cancel_after(0.1, thread_id=tid_holder[0])
        t.join(timeout=10)
        assert state.get("result") == "interrupted", state

    def test_timeout_beats_late_cancel(self):
        """The converse ordering: a deadline expiring before any cancel
        raises RaftTimeoutError — and the thread's token stays clean for
        later waits."""
        from raft_tpu import errors
        from raft_tpu.testing import faults

        fn, _ = faults.inject_delay(10.0)
        out = fn(jax.numpy.arange(4.0))
        timer = faults.cancel_after(30.0)  # armed far beyond the deadline
        try:
            with pytest.raises(errors.RaftTimeoutError):
                Interruptible.synchronize(out, timeout_s=0.1)
            Interruptible.yield_now()  # token untouched by the timeout
        finally:
            timer.cancel()

    def test_synchronize_interrupts_in_flight_wait(self):
        """cancel() from another thread must break a wait on still-running
        device work (the reference's polling-loop guarantee,
        interruptible.hpp:66-120) — not just a wait that hasn't started."""
        import threading
        import time as _time

        import jax.numpy as jnp
        from jax import lax

        @jax.jit
        def slow(a, n):
            def body(i, acc):
                return acc @ a / jnp.float32(1.0001)
            return lax.fori_loop(0, n, body, a)

        a = jnp.eye(400) * 1.001
        jax.block_until_ready(slow(a, 2))  # compile

        out = slow(a, 8_000)  # dispatched; runs for several seconds
        state = {}
        started = threading.Event()

        def waiter():
            started.set()
            t0 = _time.perf_counter()
            try:
                Interruptible.synchronize(out)
                state["result"] = "completed"
            except InterruptedException:
                state["result"] = "interrupted"
            state["elapsed"] = _time.perf_counter() - t0

        tid_holder = []

        def run():
            tid_holder.append(threading.get_ident())
            waiter()

        t = threading.Thread(target=run)
        t.start()
        started.wait()
        _time.sleep(0.3)  # let the wait become in-flight
        Interruptible.cancel_thread(tid_holder[0])
        t.join(timeout=10)
        assert state.get("result") == "interrupted", state
        assert state["elapsed"] < 8.0, state  # broke out of the wait
        # drain the still-running dispatch so it cannot outlive the test
        jax.block_until_ready(out)


class TestAnnotate:
    def test_context(self):
        with annotate("test %d", 1):
            pass

    def test_push_pop(self):
        push_range("r")
        pop_range()
        pop_range()  # extra pop is a no-op


class TestMdarray:
    def test_factories(self):
        m = mdarray.make_device_matrix(None, 4, 5)
        assert m.shape == (4, 5)
        v = mdarray.make_device_vector(None, 7, dtype=np.int32)
        assert v.shape == (7,) and v.dtype == np.int32
        s = mdarray.make_device_scalar(None, 3.5)
        assert float(s) == 3.5

    def test_round_trip(self):
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        d = mdarray.to_device(None, x)
        np.testing.assert_array_equal(mdarray.to_host(d), x)

    def test_validation(self):
        with pytest.raises(ValueError):
            mdarray.expect_matrix(np.zeros(3))
        with pytest.raises(ValueError):
            mdarray.expect_vector(np.zeros((3, 3)))
        with pytest.raises(TypeError):
            mdarray.expect_same_dtype(np.zeros(2, np.float32), np.zeros(2, np.float64))
