"""Native C++ host-algos tests: parity with the numpy fallbacks
(the reference's pattern of testing runtime-lib entry points against the
header implementations)."""

import numpy as np
import pytest

native = pytest.importorskip("raft_tpu.native")


def test_dendrogram_matches_numpy(rng_np):
    from raft_tpu.sparse import hierarchy as h

    n = 30
    # random spanning tree edges, weight-sorted
    src = np.arange(1, n, dtype=np.int32)
    dst = np.array([rng_np.integers(0, i) for i in range(1, n)], np.int32)
    w = np.sort(rng_np.random(n - 1).astype(np.float32))

    got = native.dendrogram(src, dst, w, n)

    # numpy reference: force the fallback path
    import unittest.mock as mock

    with mock.patch.dict("sys.modules", {"raft_tpu.native": None}):
        want = h.build_dendrogram_host(src, dst, w, n)

    np.testing.assert_array_equal(got[0], want[0])
    np.testing.assert_allclose(got[1], want[1], rtol=1e-6)
    np.testing.assert_array_equal(got[2], want[2])


def test_extract_flat_matches(rng_np):
    from raft_tpu.sparse.hierarchy import extract_flattened_clusters

    n = 20
    src = np.arange(1, n, dtype=np.int32)
    dst = np.array([rng_np.integers(0, i) for i in range(1, n)], np.int32)
    w = np.sort(rng_np.random(n - 1).astype(np.float32))
    children, _, _ = native.dendrogram(src, dst, w, n)
    import unittest.mock as mock

    for k in (2, 3, 5):
        got = native.extract_flat(children, n, k)
        with mock.patch.dict("sys.modules", {"raft_tpu.native": None}):
            want = extract_flattened_clusters(children, n, k)
        np.testing.assert_array_equal(got, want)
        assert len(np.unique(got)) == k


def test_make_monotonic():
    labels = np.array([7, 3, 7, 9, 3, 0], np.int32)
    out = native.make_monotonic(labels)
    np.testing.assert_array_equal(out, [0, 1, 0, 2, 1, 3])


def test_merge_topk(rng_np):
    P, m, k = 3, 5, 4
    d = np.sort(rng_np.random((P, m, k)).astype(np.float32), axis=2)
    i = rng_np.integers(0, 1000, (P, m, k)).astype(np.int32)
    out_d, out_i = native.merge_topk(d, i)
    flat = d.transpose(1, 0, 2).reshape(m, P * k)
    want = np.sort(flat, axis=1)[:, :k]
    np.testing.assert_allclose(out_d, want, rtol=1e-6)
    assert (np.diff(out_d, axis=1) >= 0).all()
