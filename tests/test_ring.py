"""Ring-dataflow distributed kNN / pairwise tests on the 8-device virtual
mesh (fully-sharded operands — the ring-attention-style dataflow of
SURVEY.md §5 — validated against single-device oracles)."""

import numpy as np
import pytest

import jax

from raft_tpu.comms import build_comms, ring_knn, ring_pairwise_distance
from raft_tpu.spatial import brute_force_knn
from raft_tpu.distance import pairwise_distance


@pytest.fixture(scope="module")
def comms():
    return build_comms(jax.devices()[:8])


def test_ring_knn_matches_single(comms, rng_np):
    index = rng_np.standard_normal((333, 12)).astype(np.float32)  # ragged/8
    queries = rng_np.standard_normal((41, 12)).astype(np.float32)
    d_r, i_r = ring_knn(comms, index, queries, 6, metric="sqeuclidean")
    d_s, i_s = brute_force_knn(index, queries, 6, metric="sqeuclidean")
    np.testing.assert_allclose(np.asarray(d_r), np.asarray(d_s), rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(i_r), np.asarray(i_s))


def test_ring_knn_l2_metric(comms, rng_np):
    index = rng_np.standard_normal((160, 8)).astype(np.float32)
    queries = index[:16]
    d_r, i_r = ring_knn(comms, index, queries, 3, metric="l2")
    np.testing.assert_array_equal(np.asarray(i_r)[:, 0], np.arange(16))
    np.testing.assert_allclose(np.asarray(d_r)[:, 0], 0.0, atol=1e-3)


def test_ring_pairwise_matches_single(comms, rng_np):
    x = rng_np.standard_normal((45, 10)).astype(np.float32)
    y = rng_np.standard_normal((29, 10)).astype(np.float32)
    got = np.asarray(ring_pairwise_distance(comms, x, y, metric="sqeuclidean"))
    want = np.asarray(pairwise_distance(x, y, "sqeuclidean"))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
