"""Mutation-tier tests (ISSUE 7): upsert / delete / streaming ingest
with background compaction, single-chip and sharded.

Contracts under test (docs/mutation.md):

* an ACKNOWLEDGED upsert is visible to the very next search; a delete
  masks the row everywhere (main slab, delta, every replica copy);
* upsert into a non-full delta segment, tombstone flips, and
  health/failover flips all run with ZERO retraces of the compiled
  programs (cache-size audits, Pallas ADC engine engaged on the PQ
  path under interpret);
* compaction folds deltas+tombstones back into main slabs with results
  preserved, warm-started centroid refresh bounded by the
  probe-overlap drift guardrail, and recall stays bounded across
  ingest+refresh cycles;
* checkpoint v4: full round-trip, the lowest-version writer rule, a
  FUTURE version rejected with a CorruptIndexError naming it, and
  dirty-list delta checkpoints that survive duplication and fail
  loudly on partial writes (faults.inject_partial_write);
* chaos: a mid-ingest rank failure + recover_rank/resync_rank cycle
  loses no acknowledged write.
"""

import dataclasses
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu import errors
from raft_tpu.spatial.ann import (
    BackgroundCompactor,
    CompactionPolicy,
    IVFFlatParams,
    IVFPQParams,
    apply_delta_checkpoint,
    compact,
    compaction_stats,
    delete,
    ivf_flat_build,
    ivf_pq_build,
    load_index,
    mutable_search,
    mutable_warmup,
    probe_overlap,
    save_delta_checkpoint,
    save_index,
    upsert,
    wrap_mutable,
)
from raft_tpu.spatial.ann import mutation as mut_mod
from raft_tpu.testing import faults
from tests.oracles import np_knn_ids

K = 5
D = 16


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(11)
    x = rng.standard_normal((1200, D)).astype(np.float32)
    q = x[::113][:8] + 0.05 * rng.standard_normal((8, D)).astype(
        np.float32
    )
    return x, q


@pytest.fixture(scope="module")
def flat_index(dataset):
    x, _ = dataset
    return ivf_flat_build(
        x, IVFFlatParams(n_lists=12, kmeans_n_iters=4,
                         kmeans_init="random", seed=3),
        metric="sqeuclidean",
    )


@pytest.fixture(scope="module")
def pq_index(dataset):
    x, _ = dataset
    return ivf_pq_build(x, IVFPQParams(
        n_lists=12, pq_dim=4, kmeans_n_iters=4, kmeans_init="random",
        seed=3,
    ))


def _search_ids(mw, q, **kw):
    kw.setdefault("n_probes", 6)
    kw.setdefault("qcap", q.shape[0])
    return np.asarray(mutable_search(mw, q, K, **kw)[1])


# ------------------------------------------------------- single-chip core
class TestUpsertDelete:
    def test_upsert_acked_then_visible_top1(self, flat_index, dataset):
        _, q = dataset
        mw = wrap_mutable(flat_index, delta_cap=8)
        new_ids = np.arange(7000, 7000 + q.shape[0]).astype(np.int32)
        mw2, acc = upsert(mw, q, new_ids)
        assert acc.all()
        ids = _search_ids(mw2, q)
        assert (ids[:, 0] == new_ids).all()
        # the original state is untouched (functional updates)
        assert not np.isin(_search_ids(mw, q), new_ids).any()

    def test_reupsert_supersedes_old_copy(self, flat_index, dataset):
        x, q = dataset
        mw = wrap_mutable(flat_index, delta_cap=8)
        # move an EXISTING main-slab row onto the first query
        victim = int(_search_ids(mw, q)[1, 0])
        mw2, acc = upsert(mw, q[:1], np.array([victim], np.int32))
        assert acc.all()
        ids = _search_ids(mw2, q)
        assert ids[0, 0] == victim
        # and re-upsert the DELTA copy again: still exactly one live copy
        mw3, _ = upsert(mw2, q[:1] + 0.001, np.array([victim], np.int32))
        ids3 = _search_ids(mw3, q)
        assert (ids3[0] == victim).sum() == 1
        live = (np.asarray(mw3.delta.live) > 0) & (
            np.asarray(mw3.delta.ids) == victim
        )
        assert live.sum() == 1

    def test_delete_masks_main_and_delta(self, flat_index, dataset):
        _, q = dataset
        mw = wrap_mutable(flat_index, delta_cap=8)
        new_ids = np.arange(7100, 7104).astype(np.int32)
        mw2, _ = upsert(mw, q[:4], new_ids)
        main_victims = _search_ids(mw2, q)[:, 1][:3].astype(np.int32)
        both = np.concatenate([new_ids, main_victims])
        mw3, found = delete(mw2, both)
        assert found.all()
        ids = _search_ids(mw3, q)
        assert not np.isin(ids, both).any()
        # deleting again: nothing live to find
        _, found2 = delete(mw3, both)
        assert not found2.any()

    def test_capacity_rejection_is_explicit(self, flat_index):
        mw = wrap_mutable(flat_index, delta_cap=2)
        # identical vectors land in one list: only cap=2 fit
        v = np.tile(np.asarray(flat_index.centroids)[0], (5, 1))
        mw2, acc = upsert(mw, v, np.arange(8000, 8005).astype(np.int32))
        assert acc.sum() == 2
        assert int(np.asarray(mw2.delta.counts).max()) == 2
        # rejected rows are NOT in the delta
        assert not np.isin(
            np.asarray(mw2.delta.ids), np.arange(8002, 8005)
        ).any()

    def test_rejected_upsert_is_a_strict_noop(self, flat_index,
                                              dataset):
        """Review regression: a capacity-rejected upsert must NOT
        tombstone the id's previous copy — False means "compact, then
        retry", and the old version keeps serving (main slab AND delta
        copies)."""
        _, q = dataset
        mw = wrap_mutable(flat_index, delta_cap=1)
        c0 = np.asarray(flat_index.centroids)[0:1]
        # fill list 0's one-slot segment
        mw, acc = upsert(mw, c0, np.array([8100], np.int32))
        assert acc.all()
        before = _search_ids(mw, q)
        # a MAIN-slab id re-upserted into the full list: rejected, and
        # its previous main copy keeps serving
        victim = int(before[0, 0])
        mw2, acc2 = upsert(mw, c0, np.array([victim], np.int32))
        assert not acc2.any()
        assert np.array_equal(_search_ids(mw2, q), before)
        # a DELTA id re-upserted into the full list: rejected, and the
        # previous delta copy stays live
        mw3, acc3 = upsert(mw2, c0 + 1e-4, np.array([8100], np.int32))
        assert not acc3.any()
        live = (np.asarray(mw3.delta.live) > 0) & (
            np.asarray(mw3.delta.ids) == 8100
        )
        assert live.sum() == 1

    def test_superseded_delta_copy_dirties_its_list(self, flat_index,
                                                    dataset, tmp_path):
        """Review regression: re-upserting an id whose delta copy lives
        in ANOTHER list must dirty that list too — otherwise replaying
        incremental checkpoints resurrects the stale copy."""
        _, q = dataset
        cents = np.asarray(flat_index.centroids)
        base = wrap_mutable(flat_index, delta_cap=4)
        mw, acc = upsert(base, cents[0:1], np.array([8200], np.int32))
        assert acc.all()
        p1 = tmp_path / "d1.npz"
        save_delta_checkpoint(mw, p1)
        # move the id to a different list
        mw, acc = upsert(mw, cents[5:6], np.array([8200], np.int32))
        assert acc.all()
        assert len(mw.dirty_lists) >= 2      # new list AND the old one
        p2 = tmp_path / "d2.npz"
        save_delta_checkpoint(mw, p2)
        fresh = wrap_mutable(flat_index, delta_cap=4)
        r = apply_delta_checkpoint(
            apply_delta_checkpoint(fresh, p1), p2
        )
        live = (np.asarray(r.delta.live) > 0) & (
            np.asarray(r.delta.ids) == 8200
        )
        assert live.sum() == 1               # exactly ONE live copy

    def test_sparse_id_space_rejected_loudly(self, flat_index):
        """The id→pos map is dense over [0, max_id]: wildly sparse ids
        must fail with a clear contract error, not a silent multi-GB
        allocation."""
        import dataclasses as dc

        huge = dc.replace(
            flat_index,
            storage=dc.replace(
                flat_index.storage,
                sorted_ids=jnp.asarray(
                    np.asarray(flat_index.storage.sorted_ids)
                    + (1 << 30)
                ),
            ),
        )
        with pytest.raises(ValueError, match="dense"):
            wrap_mutable(huge, delta_cap=4)

    def test_pq_engine_with_pallas_kernel_interpret(self, pq_index,
                                                    dataset):
        """The kernel-path tombstone contract: with the Pallas ADC
        engine engaged (interpret mode on CPU), upserts surface and
        deleted rows never do — the row mask is applied at the exact
        refine tail."""
        _, q = dataset
        mw = wrap_mutable(pq_index, delta_cap=8)
        kw = dict(n_probes=6, qcap=q.shape[0], refine_ratio=2.0,
                  use_pallas=True)
        new_ids = np.arange(7200, 7200 + q.shape[0]).astype(np.int32)
        mw2, acc = upsert(mw, q, new_ids)
        assert acc.all()
        ids = _search_ids(mw2, q, **kw)
        assert (ids[:, 0] == new_ids).all()
        victims = _search_ids(mw2, q, **kw)[:, 1][:4].astype(np.int32)
        mw3, found = delete(mw2, victims)
        assert found.all()
        ids3 = _search_ids(mw3, q, **kw)
        assert not np.isin(ids3, victims).any()

    def test_zero_retrace_upsert_tombstone_search(self, flat_index,
                                                  dataset):
        """THE zero-retrace acceptance: upsert into a non-full segment,
        tombstone flips, and repeated serving all reuse ONE compiled
        program per op (cache-size audit on the three jitted impls)."""
        _, q = dataset
        mw = wrap_mutable(flat_index, delta_cap=8)
        kw = dict(n_probes=6, qcap=q.shape[0])
        mutable_search(mw, q, K, **kw)
        s0 = mut_mod._mut_search_impl._cache_size()
        u0 = d0 = None
        for i in range(3):
            mw, acc = upsert(
                mw, q + 0.01 * i,
                np.arange(9000 + 10 * i, 9000 + 10 * i + q.shape[0],
                          dtype=np.int32),
            )
            assert acc.all()
            if u0 is None:
                u0 = mut_mod._upsert_impl._cache_size()
            mw, _ = delete(mw, np.array([9000 + 10 * i], np.int32))
            if d0 is None:
                d0 = mut_mod._delete_impl._cache_size()
            mutable_search(mw, q, K, **kw)
        assert mut_mod._mut_search_impl._cache_size() == s0, \
            "mutations must not retrace the serving program"
        assert mut_mod._upsert_impl._cache_size() == u0
        assert mut_mod._delete_impl._cache_size() == d0

    def test_warmup_consumes_nothing(self, flat_index):
        mw = wrap_mutable(flat_index, delta_cap=4)
        qc = mutable_warmup(mw, 4, k=K, n_probes=6, ingest_batch=8)
        assert isinstance(qc, int)
        assert int(np.asarray(mw.delta.counts).sum()) == 0
        assert int(np.asarray(mw.row_mask).min()) == 1


# --------------------------------------------------------- compaction
class TestCompaction:
    def test_compact_preserves_results(self, flat_index, dataset):
        x, q = dataset
        mw = wrap_mutable(flat_index, delta_cap=8)
        new_ids = np.arange(7300, 7306).astype(np.int32)
        mw, _ = upsert(mw, q[:6] * 1.01, new_ids)
        victims = _search_ids(mw, q)[:, 2][:4].astype(np.int32)
        mw, _ = delete(mw, victims)
        before = _search_ids(mw, q)
        mw2, stats = compact(mw)
        assert stats["survivors"] == 1200 + 6 - 4
        after = _search_ids(mw2, q)
        assert np.array_equal(before, after)
        # delta drained, mask all-live
        assert int(np.asarray(mw2.delta.counts).sum()) == 0
        assert compaction_stats(mw2)["tombstone_frac"] == 0.0

    def test_compact_statics_stable_across_cycles(self, flat_index,
                                                  dataset):
        _, q = dataset
        mw = wrap_mutable(flat_index, delta_cap=8)
        mw1, s1 = compact(mw)
        mw1, _ = upsert(mw1, q[:2], np.array([7400, 7401], np.int32))
        mw2, s2 = compact(mw1)
        # bucketed statics: a 2-row delta must not shift the program keys
        assert s1["max_list"] == s2["max_list"]
        assert s1["n_slab"] == s2["n_slab"]

    def test_refresh_drift_guardrail(self, flat_index, dataset):
        _, q = dataset
        mw = wrap_mutable(flat_index, delta_cap=8)
        # warm-started refresh on unchanged data: tiny drift, passes
        mw2, stats = compact(mw, refresh_centroids=True,
                             drift_queries=q, min_probe_overlap=0.5,
                             n_probes=6)
        assert stats["refreshed"] and stats["probe_overlap"] >= 0.5
        # an impossible bound trips the guardrail loudly
        with pytest.raises(ValueError, match="drift"):
            compact(mw, refresh_centroids=True, drift_queries=q,
                    min_probe_overlap=1.01, n_probes=6)

    def test_recall_bounded_across_ingest_refresh_cycles(self):
        """The drift-guardrail acceptance: recall vs a fresh exact
        oracle stays within bound across ingest + centroid-refresh
        cycles (clustered data, the regime IVF exists for)."""
        from raft_tpu.random import make_blobs
        from raft_tpu.random.rng import RngState

        x, _ = make_blobs(3000, D, n_clusters=24, cluster_std=0.6,
                          state=RngState(5))
        x = np.asarray(x, np.float32)
        idx = ivf_flat_build(
            x[:2400], IVFFlatParams(n_lists=16, kmeans_n_iters=5,
                                    kmeans_init="random", seed=1),
            metric="sqeuclidean",
        )
        mw = wrap_mutable(idx, delta_cap=64)
        rng = np.random.default_rng(2)
        q = x[rng.integers(0, 2400, 16)] + 0.05 * rng.standard_normal(
            (16, D)
        ).astype(np.float32)
        live = {i: x[i] for i in range(2400)}
        nxt = 2400
        for cycle in range(3):
            batch = np.arange(nxt, nxt + 200)
            mw, acc = upsert(mw, x[nxt:nxt + 200], batch.astype(np.int32))
            for i in batch[acc]:
                live[int(i)] = x[int(i)]
            nxt += 200
            dead = rng.choice(sorted(live), size=50, replace=False)
            mw, _ = delete(mw, dead.astype(np.int32))
            for i in dead:
                live.pop(int(i), None)
            mw, stats = compact(
                mw, refresh_centroids=True, drift_queries=q,
                min_probe_overlap=0.3, n_probes=8,
            )
            ids_live = np.array(sorted(live), np.int64)
            xs = np.stack([live[int(i)] for i in ids_live])
            true = ids_live[np_knn_ids(xs, q, K)]
            got = _search_ids(mw, q, n_probes=8)
            rec = np.mean([
                len(set(g.tolist()) & set(t.tolist())) / K
                for g, t in zip(got, true)
            ])
            assert rec >= 0.85, (cycle, rec)

    def test_background_compactor_lifecycle(self, flat_index, dataset):
        _, q = dataset
        mw = wrap_mutable(flat_index, delta_cap=4)
        bc = BackgroundCompactor(CompactionPolicy(max_fill_frac=0.25,
                                                  refresh_every=0))
        assert not bc.maybe_submit(mw)       # empty: nothing to do
        v = np.tile(np.asarray(flat_index.centroids)[0], (3, 1))
        mw, acc = upsert(mw, v, np.arange(7500, 7503).astype(np.int32))
        assert acc.all()
        assert bc.maybe_submit(mw)
        assert not bc.submit(mw)             # one in flight at a time
        bc.join(30.0)
        out = bc.poll()
        assert out is not None
        mw2, stats = out
        assert stats["survivors"] == 1200 + 3
        assert bc.poll() is None
        assert np.isin(
            np.asarray(mw2.index.storage.sorted_ids),
            np.arange(7500, 7503),
        ).sum() == 3

    def test_probe_overlap_bounds(self, flat_index, dataset):
        _, q = dataset
        c = np.asarray(flat_index.centroids)
        assert probe_overlap(c, c, q, 6) == 1.0
        rng = np.random.default_rng(0)
        # unrelated centroids: overlap collapses toward the random
        # expectation (n_probes / n_lists = 2/12)
        assert probe_overlap(
            c, rng.standard_normal(c.shape).astype(np.float32) * 10, q, 2
        ) < 0.75


# ------------------------------------------------- checkpointing (v4)
class TestCheckpointV4:
    def test_full_v4_roundtrip(self, flat_index, dataset, tmp_path):
        _, q = dataset
        mw = wrap_mutable(flat_index, delta_cap=8)
        mw, _ = upsert(mw, q[:4], np.arange(7600, 7604).astype(np.int32))
        mw, _ = delete(mw, _search_ids(mw, q)[:, 1][:2].astype(np.int32))
        p = tmp_path / "mut.npz"
        save_index(mw, p)
        hdr = json.loads(bytes(np.load(p)["__header__"]).decode())
        assert hdr["version"] == 4 and hdr["type"] == "mutable_ivf"
        back = load_index(p)
        assert np.array_equal(_search_ids(back, q), _search_ids(mw, q))

    def test_frozen_payload_keeps_lowest_version(self, flat_index,
                                                 tmp_path):
        p = tmp_path / "flat.npz"
        save_index(flat_index, p)
        hdr = json.loads(bytes(np.load(p)["__header__"]).decode())
        assert hdr["version"] == 2     # no coarse, no mutation payload

    def test_future_version_rejected_naming_it(self, flat_index,
                                               tmp_path):
        """ISSUE 7 satellite: a v3-era reader meeting a future-format
        header must raise a structured CorruptIndexError NAMING the
        version — never fall through to missing-key defaults."""
        p = tmp_path / "f.npz"
        save_index(flat_index, p)
        with np.load(p) as npz:
            hdr = json.loads(bytes(npz["__header__"]).decode())
            arrays = {k: npz[k] for k in npz.files if k != "__header__"}
        hdr["version"] = 9
        with open(p, "wb") as f:
            np.savez(f, __header__=np.frombuffer(
                json.dumps(hdr).encode(), dtype=np.uint8
            ), **arrays)
        with pytest.raises(errors.CorruptIndexError, match="9"):
            load_index(p)

    def test_delta_checkpoint_dirty_lists_and_idempotence(
        self, flat_index, dataset, tmp_path
    ):
        _, q = dataset
        base = wrap_mutable(flat_index, delta_cap=8)
        mw, _ = upsert(base, q[:4], np.arange(7700, 7704).astype(np.int32))
        dirty = set(mw.dirty_lists)
        assert dirty          # something got dirty
        p = tmp_path / "delta.npz"
        written = save_delta_checkpoint(mw, p)
        assert set(written) == dirty and not mw.dirty_lists
        fresh = wrap_mutable(flat_index, delta_cap=8)
        r1 = apply_delta_checkpoint(fresh, p)
        assert np.array_equal(_search_ids(r1, q), _search_ids(mw, q))
        # a duplicated flush re-applies to the same state
        r2 = apply_delta_checkpoint(r1, p)
        assert np.array_equal(_search_ids(r2, q), _search_ids(mw, q))

    @pytest.mark.parametrize("mode", ["truncate", "duplicate"])
    def test_partial_write_detected(self, flat_index, dataset, tmp_path,
                                    mode):
        """ISSUE 7 satellite: a torn or duplicated delta-segment flush
        must fail loudly at apply time (CorruptIndexError), never
        half-apply."""
        _, q = dataset
        mw = wrap_mutable(flat_index, delta_cap=8)
        mw, _ = upsert(mw, q, np.arange(7800, 7808).astype(np.int32))
        p = tmp_path / "delta.npz"
        save_delta_checkpoint(mw, p)
        damaged = faults.inject_partial_write(str(p), mode=mode,
                                              boundary=2)
        assert damaged
        fresh = wrap_mutable(flat_index, delta_cap=8)
        with pytest.raises(errors.CorruptIndexError):
            apply_delta_checkpoint(fresh, p)

    def test_geometry_mismatch_rejected(self, flat_index, dataset,
                                        tmp_path):
        _, q = dataset
        mw = wrap_mutable(flat_index, delta_cap=8)
        mw, _ = upsert(mw, q[:2], np.array([7900, 7901], np.int32))
        p = tmp_path / "delta.npz"
        save_delta_checkpoint(mw, p)
        other = wrap_mutable(flat_index, delta_cap=4)   # different cap
        with pytest.raises(errors.CorruptIndexError, match="geometry"):
            apply_delta_checkpoint(other, p)


# ------------------------------------------------------- sharded (MNMG)
from raft_tpu.comms import (  # noqa: E402 — mesh-dependent imports
    build_comms,
    mnmg_delete,
    mnmg_ivf_flat_build,
    mnmg_ivf_flat_search,
    mnmg_ivf_pq_build,
    mnmg_mutable_search,
    mnmg_upsert,
    place_index,
    recover_rank,
    resync_rank,
    wrap_mnmg_mutable,
)
from raft_tpu.resilience import FailoverPlan, ReplicaPlacement  # noqa: E402
from raft_tpu.resilience.health import ShardHealth  # noqa: E402


@pytest.fixture(scope="module")
def comms8():
    return build_comms(jax.devices()[:8])


@pytest.fixture(scope="module")
def sharded_flat_r2(comms8, dataset):
    x, _ = dataset
    idx = mnmg_ivf_flat_build(
        comms8, x, IVFFlatParams(n_lists=16, kmeans_n_iters=3,
                                 kmeans_init="random", seed=2),
        metric="sqeuclidean",
    )
    return place_index(comms8, idx, replication=2)


class TestMnmgMutation:
    def test_empty_state_parity_and_upsert_visible(self, comms8,
                                                   sharded_flat_r2,
                                                   dataset):
        _, q = dataset
        idx = sharded_flat_r2
        mw = wrap_mnmg_mutable(comms8, idx, delta_cap=8)
        kw = dict(n_probes=8, qcap=q.shape[0])
        v0, i0 = mnmg_mutable_search(comms8, mw, q, K, **kw)
        vp, ip = mnmg_ivf_flat_search(comms8, idx, q, K, **kw)
        assert np.array_equal(np.asarray(i0), np.asarray(ip))
        new_ids = np.arange(8800, 8800 + q.shape[0]).astype(np.int32)
        mw2, acc = mnmg_upsert(comms8, mw, q, new_ids)
        assert acc.all()
        _, i1 = mnmg_mutable_search(comms8, mw2, q, K, **kw)
        assert (np.asarray(i1)[:, 0] == new_ids).all()
        # the pre-upsert state is untouched (functional)
        _, i0b = mnmg_mutable_search(comms8, mw, q, K, **kw)
        assert np.array_equal(np.asarray(i0b), np.asarray(i0))

    def test_tombstone_vs_replica_bit_identical(self, comms8,
                                                sharded_flat_r2,
                                                dataset):
        """ISSUE 7 satellite: with R=2 and one rank down, a delete
        routed through the FailoverPlan masks the row on the SERVING
        REPLICA too — results bit-identical to the healthy mesh
        post-delete, coverage 1.0."""
        _, q = dataset
        idx = sharded_flat_r2
        mw = wrap_mnmg_mutable(comms8, idx, delta_cap=8)
        new_ids = np.arange(8900, 8904).astype(np.int32)
        mw, acc = mnmg_upsert(comms8, mw, q[:4], new_ids)
        assert acc.all()
        kw = dict(n_probes=8, qcap=q.shape[0])
        ids_now = np.asarray(
            mnmg_mutable_search(comms8, mw, q, K, **kw)[1]
        )
        victims = np.concatenate(
            [new_ids[:2], ids_now[:, 1][:3].astype(np.int32)]
        )
        h = faults.fail_rank(ShardHealth(8), 3)
        plan = FailoverPlan.from_health(
            ReplicaPlacement.of_index(idx), h
        )
        assert plan.fully_covered
        mw2, found = mnmg_delete(comms8, mw, victims)
        assert found.all()
        res_h = mnmg_mutable_search(comms8, mw2, q, K, shard_mask=True,
                                    **kw)
        res_d = mnmg_mutable_search(comms8, mw2, q, K, shard_mask=h,
                                    failover=plan, **kw)
        assert np.array_equal(np.asarray(res_h.ids),
                              np.asarray(res_d.ids))
        assert np.array_equal(np.asarray(res_h.distances),
                              np.asarray(res_d.distances))
        assert not np.isin(np.asarray(res_d.ids), victims).any()
        assert float(np.asarray(res_d.coverage).min()) == 1.0

    def test_mid_ingest_rank_failure_loses_no_acked_write(
        self, comms8, sharded_flat_r2, dataset, tmp_path
    ):
        """ISSUE 7 chaos acceptance: acked upserts before AND during a
        rank failure survive the fail_rank → recover_rank (main slabs
        from the CRC-verified checkpoint) → resync_rank (mutation slabs
        from the live replica) cycle; a TORN delta-segment flush is
        rejected loudly on the way (faults.inject_partial_write), so
        recovery routes through the replica instead of half-applying."""
        x, q = dataset
        idx = sharded_flat_r2
        ckpt = tmp_path / "base.npz"
        save_index(idx, ckpt)
        mw = wrap_mnmg_mutable(comms8, idx, delta_cap=8)
        kw = dict(n_probes=8, qcap=q.shape[0])
        ids1 = np.arange(9500, 9504).astype(np.int32)
        mw, acc1 = mnmg_upsert(comms8, mw, q[:4], ids1)
        assert acc1.all()
        # mid-ingest failure
        dead = 2
        h = faults.fail_rank(ShardHealth(8), dead)
        plan = FailoverPlan.from_health(
            ReplicaPlacement.of_index(idx), h
        )
        ids2 = np.arange(9600, 9604).astype(np.int32)
        mw, acc2 = mnmg_upsert(comms8, mw, q[4:8], ids2,
                               alive=h.mask())
        assert acc2.all()      # acked: recorded on every LIVE holder
        # every acked write serves through the failover route
        res = mnmg_mutable_search(comms8, mw, q, K, shard_mask=h,
                                  failover=plan, **kw)
        got = np.asarray(res.ids)
        assert (got[:4, 0] == ids1).all() and (got[4:8, 0] == ids2).all()
        # a torn delta-segment flush is detected, not half-applied
        side = ivf_flat_build(
            x[:400], IVFFlatParams(n_lists=4, kmeans_n_iters=2,
                                   kmeans_init="random"),
            metric="sqeuclidean",
        )
        smw = wrap_mutable(side, delta_cap=4)
        smw, _ = upsert(smw, x[:6], np.arange(100, 106).astype(np.int32))
        flush = tmp_path / "flush.npz"
        save_delta_checkpoint(smw, flush)
        faults.inject_partial_write(str(flush), mode="truncate",
                                    boundary=1)
        with pytest.raises(errors.CorruptIndexError):
            apply_delta_checkpoint(wrap_mutable(side, delta_cap=4), flush)
        # recovery: main slabs from the checkpoint, mutation slabs from
        # the surviving replica — then the healthy mesh serves every
        # acked write with primaries restored
        rec = recover_rank(comms8, mw.index, ckpt, dead)
        mw_rec = dataclasses.replace(mw, index=rec)
        mw_rec._id_loc = None
        mw_rec = resync_rank(comms8, mw_rec, dead)
        res2 = mnmg_mutable_search(comms8, mw_rec, q, K,
                                   shard_mask=True, **kw)
        got2 = np.asarray(res2.ids)
        assert (got2[:4, 0] == ids1).all()
        assert (got2[4:8, 0] == ids2).all()
        assert float(np.asarray(res2.coverage).min()) == 1.0

    def test_mnmg_rejected_upsert_is_a_strict_noop(self, comms8,
                                                   sharded_flat_r2,
                                                   dataset):
        """Review regression (MNMG): a capacity-rejected upsert leaves
        every replica copy of the id's previous version serving."""
        _, q = dataset
        idx = sharded_flat_r2
        mw = wrap_mnmg_mutable(comms8, idx, delta_cap=1)
        kw = dict(n_probes=8, qcap=q.shape[0])
        c = np.asarray(idx.centroids)[2:3]
        mw, acc = mnmg_upsert(comms8, mw, c, np.array([8300], np.int32))
        assert acc.all()                 # fills that list's one slot
        before = np.asarray(mnmg_mutable_search(comms8, mw, q, K, **kw)[1])
        victim = int(before[0, 0])
        mw2, acc2 = mnmg_upsert(comms8, mw, c,
                                np.array([victim], np.int32))
        assert not acc2.any()
        after = np.asarray(mnmg_mutable_search(comms8, mw2, q, K, **kw)[1])
        assert np.array_equal(before, after)

    def test_mutation_and_failover_flips_zero_retrace(
        self, comms8, sharded_flat_r2, dataset, monkeypatch
    ):
        """Upserts, tombstone flips, and health/failover flips all ride
        ONE compiled mutation-tier program (cache-size audit)."""
        from raft_tpu.comms import mnmg_ivf_flat as mod

        _, q = dataset
        idx = sharded_flat_r2
        mw = wrap_mnmg_mutable(comms8, idx, delta_cap=8)
        created = []
        orig = mod._cached_search

        def recording(*a, **k):
            fn = orig(*a, **k)
            created.append(fn)
            return fn

        monkeypatch.setattr(mod, "_cached_search", recording)
        kw = dict(n_probes=8, qcap=q.shape[0])
        h_up = np.ones(8, np.int32)
        h_dn = h_up.copy()
        h_dn[5] = 0
        plan = FailoverPlan.from_health(
            ReplicaPlacement.of_index(idx), h_dn
        )
        mnmg_mutable_search(comms8, mw, q, K, shard_mask=h_up, **kw)
        fn = created[0]
        size0 = fn._cache_size()
        for i in range(2):
            mw, acc = mnmg_upsert(
                comms8, mw, q + 0.01 * i,
                np.arange(9700 + 10 * i, 9700 + 10 * i + q.shape[0],
                          dtype=np.int32),
            )
            assert acc.all()
            mw, _ = mnmg_delete(
                comms8, mw, np.array([9700 + 10 * i], np.int32)
            )
            mnmg_mutable_search(comms8, mw, q, K, shard_mask=h_up, **kw)
            mnmg_mutable_search(comms8, mw, q, K, shard_mask=h_dn,
                                failover=plan, **kw)
        assert all(f is fn for f in created), \
            "mutation/health flips must reuse the cached program object"
        assert fn._cache_size() == size0, \
            "mutation/health flips must not retrace the program"

    def test_pq_mutation_with_pallas_kernel_engaged(self, comms8,
                                                    dataset,
                                                    monkeypatch):
        """The ISSUE 7 zero-retrace acceptance WITH the Pallas ADC
        engine engaged (interpret mode on CPU): upsert→visible,
        delete→masked, and no retrace across upsert + tombstone flips
        inside the fused PQ program running the kernel."""
        from raft_tpu.comms import mnmg_ivf as mod

        x, q = dataset
        idx = mnmg_ivf_pq_build(comms8, x, IVFPQParams(
            n_lists=8, pq_dim=4, kmeans_n_iters=3,
            kmeans_init="random", seed=4, store_raw=True,
        ))
        mw = wrap_mnmg_mutable(comms8, idx, delta_cap=8)
        kw = dict(n_probes=6, qcap=q.shape[0], refine_ratio=2.0,
                  use_pallas=True)
        created = []
        orig = mod._cached_search

        def recording(*a, **k):
            fn = orig(*a, **k)
            created.append(fn)
            return fn

        monkeypatch.setattr(mod, "_cached_search", recording)
        mnmg_mutable_search(comms8, mw, q, K, **kw)
        fn = created[0]
        size0 = fn._cache_size()
        new_ids = np.arange(9900, 9900 + q.shape[0]).astype(np.int32)
        mw2, acc = mnmg_upsert(comms8, mw, q, new_ids)
        assert acc.all()
        _, i1 = mnmg_mutable_search(comms8, mw2, q, K, **kw)
        assert (np.asarray(i1)[:, 0] == new_ids).all()
        victims = np.asarray(i1)[:, 1][:3].astype(np.int32)
        mw3, found = mnmg_delete(comms8, mw2, victims)
        assert found.all()
        _, i2 = mnmg_mutable_search(comms8, mw3, q, K, **kw)
        assert not np.isin(np.asarray(i2), victims).any()
        assert all(f is fn for f in created)
        assert fn._cache_size() == size0, \
            "mutations must not retrace the kernel-engaged program"
