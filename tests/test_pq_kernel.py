"""Pallas ADC engine (spatial/ann/pq_kernel) — tier-1 coverage.

The kernel body runs under ``interpret=True`` on the CPU test platform
(the same pattern tests/test_fused_knn.py uses), pinned bitwise against
the op-for-op lax mirror and a float oracle; the grouped searches'
``use_pallas=True`` path is then pinned against the one-hot engine:
identical candidate multisets after exact refinement wherever the refine
pools saturate (both engines then rescore every probed candidate in
exact f32 — the value-exactness contract, mirroring the ``fused_knn``
chunk-min value-exact / tie-order-may-differ contract), recall
non-inferiority elsewhere (the sub-chunk pool is a superset by the
cover argument), and MNMG parity inside the fused one-dispatch program
with zero retraces across health flips.
"""

import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.spatial.ann import IVFPQParams, ivf_pq_build
from raft_tpu.spatial.ann import pq_kernel
from raft_tpu.spatial.ann.ivf_pq import (
    _resolve_adc_engine,
    ivf_pq_search_grouped,
)

K_NN = 5


def _rand_case(rng, lb, q, m, k_codes, l_pad):
    luts = jnp.asarray(
        rng.standard_normal((lb, q, m * k_codes)), jnp.bfloat16
    )
    codes = jnp.asarray(
        rng.integers(0, k_codes, (lb, m, l_pad)), jnp.uint8
    )
    return luts, codes


def _oracle_subchunk_min(luts, codes, bounds):
    lut = np.asarray(luts, np.float32)
    c = np.asarray(codes).astype(np.int64)
    lb, q, mk = lut.shape
    m, l_pad = c.shape[1], c.shape[2]
    k_codes = mk // m
    d2 = np.zeros((lb, q, l_pad), np.float32)
    for b in range(lb):
        for mm in range(m):
            d2[b] += lut[b][:, mm * k_codes + c[b, mm]]
    for b in range(lb):
        lo, hi = int(bounds[b, 0]), int(bounds[b, 1])
        mask = np.zeros(l_pad, bool)
        mask[lo:hi] = True
        d2[b] = np.where(mask[None, :], d2[b], pq_kernel.BIG)
    sub = pq_kernel.SUBCHUNK
    return d2.reshape(lb, q, l_pad // sub, sub).min(-1)


@pytest.mark.parametrize(
    "lb,q,m,k_codes,l_pad,l_tile",
    [
        (3, 32, 4, 16, 256, 128),    # two code tiles per list
        (2, 16, 3, 256, 128, 128),   # full 8-bit codebook width
        (1, 48, 5, 32, 512, 256),    # ragged M, wider tiles
    ],
)
def test_kernel_matches_lax_mirror_bitwise(rng_np, lb, q, m, k_codes,
                                           l_pad, l_tile):
    """Interpret-mode kernel == lax mirror, bit for bit, masked rows
    included — the 'lax fallback bit-compatible' acceptance pin."""
    luts, codes = _rand_case(rng_np, lb, q, m, k_codes, l_pad)
    bounds = jnp.asarray(
        [[i, max(i, l_pad - 7 * i)] for i in range(lb)], jnp.int32
    )
    got = pq_kernel.pq_adc_subchunk_min(
        luts, codes, bounds, interpret=True, l_tile=l_tile
    )
    ref = pq_kernel.pq_adc_subchunk_min_lax(luts, codes, bounds)
    assert got.shape == (lb, q, l_pad // pq_kernel.SUBCHUNK)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    np.testing.assert_allclose(
        np.asarray(got), _oracle_subchunk_min(luts, codes, bounds),
        rtol=1e-5, atol=1e-4,
    )


def test_kernel_empty_and_full_ranges(rng_np):
    """lo == hi (empty list) -> every sub-chunk min is BIG; full range
    touches every row."""
    luts, codes = _rand_case(rng_np, 2, 16, 4, 16, 256)
    bounds = jnp.asarray([[5, 5], [0, 256]], jnp.int32)
    got = np.asarray(pq_kernel.pq_adc_subchunk_min(
        luts, codes, bounds, interpret=True, l_tile=128
    ))
    assert (got[0] == pq_kernel.BIG).all()
    assert (got[1] < pq_kernel.BIG).all()


def test_plan_and_supported_predicates():
    assert pq_kernel.plan_l_tile(24 * 256, 48) is not None
    assert pq_kernel.pq_adc_supported(24, 8, 48)
    # every planned tile is lane-aligned, even from a non-128-multiple
    # start and through budget-forced halvings (review regression)
    for mk in (64, 6144, 96 * 256):
        for start in (128, 384, 512):
            lt = pq_kernel.plan_l_tile(mk, 64, l_tile=start)
            if lt is not None:
                assert lt % 128 == 0 and lt <= 512
    # absurdly wide M*2^bits: one LUT block alone exceeds the budget
    assert not pq_kernel.pq_adc_supported(4096, 8, 512)
    with pytest.raises(ValueError):
        pq_kernel.pq_adc_subchunk_min(
            jnp.zeros((1, 8, 64), jnp.bfloat16),     # Q=8 not 16-aligned
            jnp.zeros((1, 4, 128), jnp.uint8),
            jnp.zeros((1, 2), jnp.int32), interpret=True,
        )


# -- grouped search: engine equivalence --------------------------------------

@pytest.fixture(scope="module")
def dataset():
    # clustered data (8 tight blobs): with n_lists=48, k-means leaves
    # EMPTY lists, so high-n_probes searches probe empty lists and
    # padded tails (the masking edge cases)
    from raft_tpu.random import make_blobs
    from raft_tpu.random.rng import RngState

    rng = np.random.default_rng(7)
    n, d = 3000, 16
    x, _ = make_blobs(n, d, n_clusters=8, cluster_std=0.5,
                      state=RngState(3))
    x = np.asarray(x, np.float32)
    q = x[rng.integers(0, n, 64)] + 0.1 * rng.standard_normal(
        (64, d)
    ).astype(np.float32)
    return x, q


@pytest.fixture(scope="module")
def pq_index(dataset):
    x, _ = dataset
    # n_lists > populated clusters on this data -> some lists are EMPTY,
    # so probes hit empty lists and padded tails (the masking edge cases)
    return ivf_pq_build(x, IVFPQParams(
        n_lists=48, pq_dim=4, pq_bits=4, kmeans_n_iters=4,
        kmeans_init="random",
    ))


@pytest.mark.parametrize("exact_selection", [True, False])
@pytest.mark.parametrize("stream", [None, True])
def test_saturated_pool_candidate_multiset_identical(
    dataset, pq_index, exact_selection, stream
):
    """With refine_ratio * k >= every probed candidate, BOTH engines
    exact-rescore the full probed pool — the returned (dists, ids) must
    match exactly (same candidate multiset after refine)."""
    x, q = dataset
    p = 4
    rr = float(p * pq_index.storage.max_list) / K_NN + 1.0
    kw = dict(n_probes=p, refine_ratio=rr, qcap=64,
              exact_selection=exact_selection, stream_partials=stream)
    d0, i0 = ivf_pq_search_grouped(pq_index, q, K_NN, use_pallas=False,
                                   **kw)
    d1, i1 = ivf_pq_search_grouped(pq_index, q, K_NN, use_pallas=True,
                                   **kw)
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))


def _with_emptied_lists(x, base, emptied):
    """Rebuild ``base``'s storage with the rows of ``emptied`` lists
    remapped into list 0 — those lists keep their centroids (so probes
    still select them) but hold ZERO rows: the empty-probe edge case,
    constructed deterministically."""
    import dataclasses

    from raft_tpu.spatial.ann.common import build_list_storage

    n = base.storage.n
    n_lists = base.centroids.shape[0]
    sid = np.asarray(base.storage.sorted_ids)
    sizes = np.asarray(base.storage.list_sizes)
    labels = np.empty(n, np.int64)
    labels[sid] = np.repeat(np.arange(n_lists), sizes)
    labels = np.where(np.isin(labels, list(emptied)), 0, labels)
    storage = build_list_storage(labels, n_lists)
    codes_unsorted = np.empty((n, base.pq_dim), np.uint8)
    codes_unsorted[sid] = np.asarray(base.codes_sorted)[:-1]
    sid2 = np.asarray(storage.sorted_ids)
    codes_sorted = jnp.concatenate([
        jnp.asarray(codes_unsorted[sid2]),
        jnp.zeros((1, base.pq_dim), jnp.uint8),
    ])
    vectors_sorted = jnp.concatenate([
        jnp.asarray(x[sid2]), jnp.zeros((1, x.shape[1]), jnp.float32)
    ])
    return dataclasses.replace(
        base, codes_sorted=codes_sorted, storage=storage,
        vectors_sorted=vectors_sorted,
    )


def test_padded_lists_and_empty_probes_no_alien_candidates(
    dataset, pq_index
):
    """Kernel-path results only ever contain rows of the probed lists:
    sub-chunk windows overhang list tails into neighboring lists' slab
    rows, and the per-row validity mask must drop them. Empty lists are
    forced into the index (rows remapped away, centroids kept) so
    probes hit genuinely empty lists."""
    x, q = dataset
    idx = _with_emptied_lists(x, pq_index, {1, 5, 9, 17})
    storage = idx.storage
    sizes = np.asarray(storage.list_sizes)
    assert (sizes == 0).any(), "fixture must include empty lists"
    p = 16
    kw = dict(n_probes=p, refine_ratio=3.0, qcap=64,
              exact_selection=True)
    d1, i1 = ivf_pq_search_grouped(idx, q, K_NN, use_pallas=True, **kw)
    # engine parity on the emptied index at a SATURATED refine pool
    rr = float(p * storage.max_list) / K_NN + 1.0
    kw_sat = dict(kw, refine_ratio=rr)
    ds0, is0 = ivf_pq_search_grouped(idx, q, K_NN, use_pallas=False,
                                     **kw_sat)
    ds1, is1 = ivf_pq_search_grouped(idx, q, K_NN, use_pallas=True,
                                     **kw_sat)
    np.testing.assert_array_equal(np.asarray(ds0), np.asarray(ds1))
    np.testing.assert_array_equal(np.asarray(is0), np.asarray(is1))
    from raft_tpu.spatial.ann.common import coarse_probe

    probes, _ = coarse_probe(
        jnp.asarray(q, jnp.float32),
        jnp.asarray(idx.centroids, jnp.float32), p,
    )
    probes = np.asarray(probes)
    sid = np.asarray(storage.sorted_ids)
    offs = np.asarray(storage.list_offsets)
    ids = np.asarray(i1)
    for qi in range(ids.shape[0]):
        allowed = set()
        for l in probes[qi]:
            allowed.update(
                sid[offs[l]:offs[l] + sizes[l]].tolist()
            )
        got = set(t for t in ids[qi].tolist() if t >= 0)
        assert got <= allowed, f"query {qi} returned unprobed rows"


def test_kernel_refine_pool_recall_non_inferior(dataset, pq_index):
    """At a modest refine_ratio the sub-chunk pool is a SUPERSET of the
    row pool (cover argument): kernel-path recall must not fall below
    the one-hot path's."""
    from tests.oracles import np_knn_ids

    x, q = dataset
    true = np_knn_ids(x, np.asarray(q), K_NN)

    def rec(ids):
        g = np.asarray(ids)
        return sum(
            len(set(a.tolist()) & set(b.tolist()))
            for a, b in zip(g, true)
        ) / true.size

    kw = dict(n_probes=4, refine_ratio=2.0, qcap=64, exact_selection=True)
    r_pal = rec(ivf_pq_search_grouped(pq_index, q, K_NN, use_pallas=True,
                                      **kw)[1])
    r_one = rec(ivf_pq_search_grouped(pq_index, q, K_NN, use_pallas=False,
                                      **kw)[1])
    assert r_pal >= r_one - 1e-9, (r_pal, r_one)


def test_large_k_exceeding_subchunk_pool(dataset, pq_index):
    """k > p * (l_pad/8) is legal whenever k <= p*max_list: the kernel
    path must clamp its sub-chunk selection to the pool width instead of
    asking top_k for more sub-chunks than exist (code-review regression:
    the clamp order made c = k blow past the pool)."""
    x, q = dataset
    L = pq_index.storage.max_list
    p = 2
    # l_pad rounds L up to the tile, so the pool has p * l_pad / 8
    # sub-chunks; pick k above that but within p * max_list
    import raft_tpu.spatial.ann.pq_kernel as pk

    l_tile = pk.plan_l_tile(4 * 16, 64)
    l_pad = -(-L // l_tile) * l_tile
    k = min(p * L, p * l_pad // pk.SUBCHUNK + 8)
    assert k <= p * L
    rr = float(p * L) / k + 1.0   # saturate BOTH engines' refine pools
    d0, i0 = ivf_pq_search_grouped(
        pq_index, q, k, n_probes=p, refine_ratio=rr, qcap=64,
        exact_selection=True, use_pallas=False,
    )
    d1, i1 = ivf_pq_search_grouped(
        pq_index, q, k, n_probes=p, refine_ratio=rr, qcap=64,
        exact_selection=True, use_pallas=True,
    )
    assert d1.shape == d0.shape == (q.shape[0], k)
    # at c = full pool both engines refine every probed candidate
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))


def test_use_pallas_requires_refine(dataset, pq_index):
    x, q = dataset
    with pytest.raises(Exception, match="refine"):
        ivf_pq_search_grouped(
            pq_index, q, K_NN, n_probes=4, refine_ratio=1.0, qcap=64,
            use_pallas=True,
        )


def test_resolve_adc_engine_auto_off_tpu():
    """Auto (None) never selects the kernel off-TPU — and never even
    imports it (the JAX_PLATFORMS=cpu eager-import acceptance)."""
    assert jax.default_backend() != "tpu"
    assert _resolve_adc_engine(None, True, 24, 8, 48) is False
    assert _resolve_adc_engine(True, True, 24, 8, 48) is True
    assert _resolve_adc_engine(False, True, 24, 8, 48) is False


def test_cpu_default_never_imports_kernel_module():
    """A fresh JAX_PLATFORMS=cpu process running a default grouped
    search must not import (let alone compile) the Pallas kernel
    module."""
    prog = (
        "import os, sys\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "import numpy as np\n"
        "from raft_tpu.spatial.ann import IVFPQParams, ivf_pq_build\n"
        "from raft_tpu.spatial.ann.ivf_pq import ivf_pq_search_grouped\n"
        "rng = np.random.default_rng(0)\n"
        "x = rng.standard_normal((400, 8)).astype(np.float32)\n"
        "pq = ivf_pq_build(x, IVFPQParams(n_lists=8, pq_dim=2,\n"
        "    pq_bits=4, kmeans_n_iters=2, kmeans_init='random'))\n"
        "ivf_pq_search_grouped(pq, x[:8], 3, n_probes=2, qcap=8)\n"
        "assert 'raft_tpu.spatial.ann.pq_kernel' not in sys.modules, \\\n"
        "    'CPU default search imported the TPU kernel module'\n"
        "print('OK')\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", prog], capture_output=True, text=True,
        timeout=300,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "OK" in out.stdout


# -- MNMG: the fused one-dispatch program ------------------------------------

@pytest.fixture(scope="module")
def comms8():
    from raft_tpu.comms import build_comms

    return build_comms(jax.devices()[:8])


@pytest.fixture(scope="module")
def sharded_index(dataset, comms8):
    from raft_tpu.comms import mnmg_ivf_pq_build

    x, _ = dataset
    return mnmg_ivf_pq_build(comms8, x, IVFPQParams(
        n_lists=32, pq_dim=4, pq_bits=4, kmeans_n_iters=4,
        kmeans_init="random",
    ))


def test_mnmg_fused_program_engine_parity(dataset, comms8, sharded_index):
    """The Pallas path ACTIVE inside the MNMG fused one-dispatch program:
    saturated-pool results identical to the one-hot engine's."""
    from raft_tpu.comms import mnmg_ivf_pq_search

    _, q = dataset
    p = 4
    rr = float(p * sharded_index.max_list) / K_NN + 1.0
    kw = dict(n_probes=p, refine_ratio=rr, qcap=q.shape[0])
    d0, i0 = mnmg_ivf_pq_search(comms8, sharded_index, q, K_NN,
                                use_pallas=False, **kw)
    d1, i1 = mnmg_ivf_pq_search(comms8, sharded_index, q, K_NN,
                                use_pallas=True, **kw)
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))


def test_mnmg_pallas_health_flip_zero_retrace(
    dataset, comms8, sharded_index, monkeypatch
):
    """The acceptance trace-audit with the kernel engaged: use_pallas is
    a trace-time static, alive/failover stay runtime inputs — health
    flips must reuse the ONE compiled fused program (zero retraces)."""
    from raft_tpu.comms import mnmg_ivf as mod

    _, q = dataset
    created = []
    orig = mod._cached_search

    def recording(*a, **k):
        fn = orig(*a, **k)
        created.append(fn)
        return fn

    monkeypatch.setattr(mod, "_cached_search", recording)
    kw = dict(n_probes=4, refine_ratio=3.0, qcap=q.shape[0],
              use_pallas=True)
    m_up = np.ones(8, np.int32)
    m_one = m_up.copy()
    m_one[2] = 0
    mod.mnmg_ivf_pq_search(comms8, sharded_index, q, K_NN,
                           shard_mask=m_up, **kw)
    fn = created[0]
    size0 = fn._cache_size()
    for mask in (m_one, m_up):
        res = mod.mnmg_ivf_pq_search(comms8, sharded_index, q, K_NN,
                                     shard_mask=mask, **kw)
    assert all(f is fn for f in created), \
        "health flips must reuse the cached program object"
    assert fn._cache_size() == size0, \
        "health flips must not retrace the compiled kernel program"
    assert float(jnp.min(res.coverage)) == 1.0
