"""Headline benchmark — pairwise L2 distance throughput on TPU.

Mirrors the reference's distance benchmark (cpp/bench/distance/distance_exp_l2.cu
via the shared harness cpp/bench/distance/distance_common.cuh): time the
expanded-L2 pairwise distance engine on a large square problem.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

vs_baseline is value / 10_000 GFLOPS — a RAFT-on-A100 estimate for the f32
pairwise-distance suite (the reference publishes no absolute numbers;
BASELINE.md records `"published": {}`), i.e. vs_baseline >= 1.0 means we beat
the A100 reference estimate.

Timing methodology: the repeat loop lives INSIDE one jit (lax.fori_loop) —
per-dispatch latency through the axon tunnel is ~10 ms, so host-side loops
measure the tunnel, not the chip.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.distance.pairwise import _expanded_impl
from raft_tpu.distance.distance_type import DistanceType


def main():
    m = n = 8192
    d = 512
    iters = 20

    rng = np.random.default_rng(42)
    # TPU-idiomatic: bf16 operands, f32 MXU accumulation (preferred_element_type)
    x = jax.device_put(rng.standard_normal((m, d)).astype(jnp.bfloat16))
    y = jax.device_put(rng.standard_normal((n, d)).astype(jnp.bfloat16))

    @jax.jit
    def loop(x, y):
        def body(i, acc):
            dmat = _expanded_impl(
                DistanceType.L2Expanded, x + i * 0.0, y, "default"
            )
            # full-matrix reduce pins the dependence on every output element;
            # a sliced read would let XLA narrow the dot to two rows and
            # overstate GFLOPS by orders of magnitude.
            return acc + jnp.sum(dmat)
        return lax.fori_loop(0, iters, body, jnp.float32(0.0))

    loop(x, y).block_until_ready()  # compile
    t0 = time.perf_counter()
    float(loop(x, y))
    dt = (time.perf_counter() - t0) / iters

    gflops = 2.0 * m * n * d / dt / 1e9
    print(
        json.dumps(
            {
                "metric": "pairwise_l2_expanded_8192x8192x512_bf16",
                "value": round(gflops, 1),
                "unit": "GFLOPS",
                "vs_baseline": round(gflops / 10_000.0, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
