"""Headline benchmark — the BASELINE.md north-star configs on one chip.

Emits ONE compact JSON line — the driver-facing artifact, whitelisted
numeric fields only (``_PRINT_KEYS``), kept under the driver's
~1,800-char parse cap — and writes the FULL rows (prose notes,
secondary diagnostics) to a local ``bench_full.json``. The primary
metric stays the pairwise expanded-L2 engine (reference
cpp/bench/distance/distance_exp_l2.cu shape family); ``extras`` carries
the other BASELINE.md targets so the artifact parses every north star
(VERDICT r1 item 3):

* brute-force kNN QPS at the largest single-chip-honest scale — the
  10M x 768 regime via bf16 index storage (~14 GB HBM-resident; the fused
  chunk-min kernel never materialises the m x n matrix and reads the index
  in its storage dtype, so no f32 copy exists),
* k-means seconds/iter at 1M x 128, k=1024,
* IVF-PQ search QPS with recall@10 on the same line (recall-qualified,
  exact-refined).

Methodology: loop-in-jit two-point-difference timing (bench/common.py)
cancels the ~100 ms axon-tunnel dispatch cost; k-means uses a
two-program difference quotient on fresh inputs instead (its while_loop
iteration count is data-dependent, and the axon runtime memoizes
executions with identical inputs). Large operands are generated on
device (jax.random) so the tunnel never transfers gigabytes.

vs_baseline is headline GFLOPS / 10_000 — the RAFT-on-A100 estimate whose
derivation (A100 fp32 CUDA-core peak x a favorable 50-65% efficiency
assumption, per metric) is written out in BASELINE.md "Comparison basis";
the kNN and kmeans extras carry their own `vs_est_a100` fields on the
same basis. The reference publishes no absolute numbers (BASELINE.json
records `"published": {}`); >= 1.0 beats the estimate.
"""

import contextlib
import io
import json
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from bench.common import bench_fn
from raft_tpu.distance.distance_type import DistanceType
from raft_tpu.distance.pairwise import _expanded_impl


def _quiet_bench(fn, *args, iters):
    with contextlib.redirect_stdout(io.StringIO()):
        return bench_fn(fn, *args, iters=iters, name="x")


def headline_pairwise(reps: int = 3):
    """Returns (default-mode GFLOPS, HIGHEST-mode GFLOPS, spread) at
    8192^2 x 512, each the median of ``reps`` independent harness runs
    (spread = (max-min)/median of the default-mode GFLOPS; VERDICT r4
    weak-1 repeated-measurement discipline).

    Default = bf16-rounded operands with f32 accumulation (XLA's default
    matmul precision, the fast MXU path). HIGHEST = exact f32 operands —
    the library default for f32 users (distance/pairwise.py) and the
    honest companion to the reference comparison (its CUDA kernels are
    exact-f32, pairwise_distance_base.cuh:76-379)."""
    m = n = 8192
    d = 512
    rng = np.random.default_rng(42)
    # f32 operands + default MXU precision: measured fastest on v5e (the
    # bf16-input path currently hits an XLA layout-conversion slowdown —
    # see bench/bench_distance.py for the full grid)
    x = jax.device_put(rng.standard_normal((m, d)).astype(np.float32))
    y = jax.device_put(rng.standard_normal((n, d)).astype(np.float32))
    flops = 2.0 * m * n * d
    ms = sorted(
        _quiet_bench(
            lambda a, b: _expanded_impl(
                DistanceType.L2Expanded, a, b, "default"
            ),
            x, y, iters=40,
        )
        for _ in range(reps)
    )
    ms_hi = sorted(
        _quiet_bench(
            lambda a, b: _expanded_impl(
                DistanceType.L2Expanded, a, b, "highest"
            ),
            x, y, iters=40,
        )
        for _ in range(reps)
    )
    med = ms[len(ms) // 2]
    spread = (ms[-1] - ms[0]) / med
    return (
        flops / (med / 1e3) / 1e9,
        flops / (ms_hi[len(ms_hi) // 2] / 1e3) / 1e9,
        round(spread, 3),
    )


def extra_big_knn():
    """kNN QPS at 9.2M x 768: bf16-resident index held as 3 partitions
    (each partition's Pallas grid stays under the compile-helper's
    per-program step limit; no monolithic copy ever exists), fused
    chunk-min per partition, knn_merge_parts across them — the reference's
    multi-partition search shape (knn_brute_force_faiss.cuh:289-368) at
    the BASELINE 10M x 768 regime.

    Timed by sequential async dispatches with one terminal sync (NOT the
    loop-in-jit harness: fusing the three Pallas calls into one looped
    program exceeds the per-program grid-step limit). Distinct query
    values per dispatch defeat the axon result memoization; the
    difference quotient T(n2) - T(n1) cancels the terminal round trip."""
    from raft_tpu.spatial.knn import brute_force_knn

    d, nq, k = 768, 1024, 10
    part_rows, n_parts = 3_072_000, 3
    n = part_rows * n_parts
    key = jax.random.PRNGKey(0)

    # synthetic index data from fused iota+sin: jax.random.normal would
    # materialize 9.4 GB of uint32 threefry bits per part next to the
    # already-resident parts (OOM); throughput here is data-independent
    @jax.jit
    def synth(seed):
        i = jax.lax.broadcasted_iota(jnp.float32, (part_rows, d), 0)
        j = jax.lax.broadcasted_iota(jnp.float32, (part_rows, d), 1)
        return jnp.sin(i * 1.13e-4 + j * 7.1e-2 + seed).astype(jnp.bfloat16)

    parts = [synth(float(s)) for s in range(n_parts)]
    # index norms precomputed once (index-build cost, as the reference
    # stores norms with the index): searches then never re-read the index
    # for norms
    norm = jax.jit(
        lambda p: jnp.einsum("nd,nd->n", p, p,
                             preferred_element_type=jnp.float32)
    )
    part_norms = [norm(p) for p in parts]

    def search(qq):
        return brute_force_knn(
            parts, qq, k, metric=DistanceType.L2Expanded,
            use_fused=True, compute_dtype=jnp.bfloat16, extra_chunks=16,
            index_norms=part_norms,
        )

    from bench.common import chained_dispatch_stats

    float(jnp.sum(search(jax.random.normal(key, (nq, d), jnp.float32))[0]))
    # chained dispatches: device-serialized by the data dependence, so
    # only ONE search's transients are live next to the 14 GB index;
    # median of 3 quotients (single quotients through the axon tunnel
    # measured a 2.5x run-to-run spread)
    st = chained_dispatch_stats(
        lambda salt: jax.random.normal(
            jax.random.fold_in(key, salt), (nq, d), jnp.float32
        ),
        search, escalate=1,
    )
    if st is None:
        return {"metric": f"knn_fused_bf16_{n}x{d}_q{nq}_k{k}",
                "error": "quotient jitter-dominated"}
    qps = nq / (st["ms"] / 1e3)
    return {
        "metric": f"knn_fused_bf16_{n}x{d}_q{nq}_k{k}",
        "value": round(qps, 1),
        "unit": "QPS",
        "spread": st["spread"],
        "repeats": st["repeats"],
        "index_gb": round(n * d * 2 / 1e9, 1),
        "partitions": n_parts,
        "extra_chunks": 16,
        # BASELINE.md "Comparison basis": A100 at 10 TFLOPS effective
        # on this batch's 14.5 TFLOP = ~706 QPS estimate
        "vs_est_a100": round(qps / 706.0, 2),
    }


def extra_kmeans():
    """BASELINE.md config: 1M x 128, k=1024 (two-program difference).

    BOTH precision modes are reported (VERDICT r3 weak-1): the library
    default updates centroids in exact input precision; the
    ``compute_dtype="bfloat16"`` opt-in (what quantizer builds use) runs
    the assign+update matmuls at the 2x MXU rate."""
    from raft_tpu.cluster import KMeansParams, kmeans_fit

    n, d, k = 1_000_000, 128, 1024
    x = jax.random.normal(jax.random.PRNGKey(1), (n, d), jnp.float32)

    def per_iter_s(compute_dtype):
        p5 = KMeansParams(n_clusters=k, max_iter=5, tol=0.0, seed=0,
                          compute_dtype=compute_dtype)
        p20 = KMeansParams(n_clusters=k, max_iter=20, tol=0.0, seed=0,
                           compute_dtype=compute_dtype)
        float(kmeans_fit(x, p5).inertia)      # compile both programs
        float(kmeans_fit(x, p20).inertia)

        def once(trial):
            # fresh values each trial: defeat the axon result memoization
            x2 = x * jnp.float32(1.0001 + 1e-5 * trial)
            t0 = time.perf_counter()
            out5 = kmeans_fit(x2, p5)
            float(out5.inertia)
            t5 = time.perf_counter() - t0
            t0 = time.perf_counter()
            out20 = kmeans_fit(x2, p20)
            float(out20.inertia)
            t20 = time.perf_counter() - t0
            return (t20 - t5) / (int(out20.n_iter) - int(out5.n_iter))

        # the two-point difference is unsigned under host-timing noise
        # (a contended dispatch can make t5 > t20 — observed once, BENCH
        # r4 dry run at -371 iters/s); retry and take the median of the
        # positive trials
        vals = sorted(v for v in (once(t) for t in range(3)) if v > 0)
        if not vals:
            raise RuntimeError("kmeans timing jitter-dominated")
        med = vals[len(vals) // 2]
        return med, round((vals[-1] - vals[0]) / med, 3), len(vals)

    exact, spread, reps = per_iter_s(None)
    bf16, bf16_spread, _ = per_iter_s("bfloat16")
    return {
        "metric": f"kmeans_{n}x{d}_k{k}",
        "value": round(1.0 / exact, 2),
        "unit": "iters_per_s",
        "spread": spread,
        "repeats": reps,
        "s_per_iter": round(exact, 4),
        "precision_mode": "exact input precision (library default)",
        # the 2x-MXU-rate opt-in mode, explicitly labeled (it is the mode
        # quantizer builds use and the r02 ~130 iters/s figure's mode)
        "bf16_iters_per_s": round(1.0 / bf16, 2),
        "bf16_spread": bf16_spread,
        # r02->r04 bf16 drop (133.6 -> ~101) bisected in r5 with the
        # worktree method (r02 library checked out and remeasured on the
        # r5 runtime): the r02 LIBRARY remeasures 93.8 iters/s vs 104.9
        # for r5 code — runtime drift, not a code regression (r5 code is
        # faster than r02 code on the same stack)
        "bf16_note": "r02 lib remeasured 93.8 vs r5 lib 104.9 on r5 "
                     "runtime — drift, not code",
        # BASELINE.md "Comparison basis": 262 GFLOP/iter at 10 TFLOPS
        # effective = ~38 iter/s A100 estimate
        "vs_est_a100": round(1.0 / exact / 38.0, 2),
    }


def _adc_engine(index, nq, n_probes, *, qcap, refine_ratio):
    """Which ADC engine the row's grouped/mnmg search resolves to —
    stamped so the driver can verify the Pallas path was actually
    active. Takes the row's REAL qcap and refine_ratio (the resolver
    depends on both: the VMEM plan scales with qcap, and an unrefined
    row always runs one-hot) so the stamp can never drift from the
    measured configuration. One helper for all four stamped rows."""
    from raft_tpu.spatial.ann.common import static_qcap
    from raft_tpu.spatial.ann.ivf_pq import _resolve_adc_engine

    return "pallas" if _resolve_adc_engine(
        None, refine_ratio > 1.0, index.pq_dim, index.pq_bits,
        static_qcap(qcap, nq, n_probes, index.centroids.shape[0]),
    ) else "onehot"


def extra_ivf_pq():
    """IVF-PQ refined search QPS with recall@10 vs an exact oracle.

    Data is clustered (make_blobs, 1000 centers) — the regime real
    embedding corpora live in and the one IVF exists for; on isotropic
    Gaussian data (no cluster structure, distance concentration at d=96)
    recall@10 measures ~0.19 at the same settings for ANY inverted-file
    method — that is a property of the adversarial dataset, not the
    index (measured, see bench/bench_ann.py)."""
    from raft_tpu.spatial.ann import (
        IVFPQParams, ivf_pq_build, ivf_pq_search_grouped,
    )
    from bench.common import ann_bench_dataset, recall_at_k

    n, d, nq, k = 500_000, 96, 4096, 10
    # shared config: queries are perturbed dataset points (realistic —
    # queries come from the corpus distribution); ground truth exact
    x, q, true_np = ann_bench_dataset(n, d, nq, k)

    # 2048 lists halve the worst-case padded list length on 1000-blob data;
    # pq_dim=24 (4 dims/subspace) sharpens ADC on the near-isotropic
    # intra-blob residuals: recall@10 0.95 at n_probes=16 (measured sweep).
    # max_list_cap=512 splits the one swollen list (uncapped max_list is
    # 1500 vs a 244 mean): grouped compute scales with n_lists * max_list,
    # and capping measured 10.9k vs 7.1k QPS at identical recall (r4
    # sweep; docs/ivf_scale.md "Padded-list tax")
    bparams = IVFPQParams(
        n_lists=2048, pq_dim=24, kmeans_n_iters=10, kmeans_init="random",
        max_list_cap=512,
    )

    def timed_build(xx):
        t0 = time.perf_counter()
        out = ivf_pq_build(xx, bparams)
        # fetch THROUGH the final artifact: the scalar depends on the whole
        # codes_sorted producer chain, so no cross-program ordering
        # assumption
        float(jnp.sum(out.codes_sorted[-1].astype(jnp.float32)))
        return out, time.perf_counter() - t0

    pq, build_s = timed_build(x)
    # warm rebuild on perturbed same-shape data: executables cached, so
    # this is the COMPUTE cost; build_s - build_warm_s is jit compile
    # (VERDICT r4 weak-6 / next-8: FAISS-comparable scope split,
    # reference cpp/bench/spatial/knn.cu:34-60 Scope::BUILD)
    _, build_warm_s = timed_build(x * jnp.float32(1.0001))

    n_probes, refine = 16, 4.0

    def search(qq):
        # list-major grouped search: ADC as a one-hot matmul on the MXU
        # (43x the per-query path at equal recall at this config).
        # qcap=24 ~ mean probe occupancy (32): block compute is linear in
        # qcap and the rank-aware slot filling makes the dropped pairs the
        # marginal last-rank probes — measured recall is FLAT at 0.9454
        # from qcap 256 down to 16 while QPS goes 11.2k -> 52.1k (r4
        # sweep; docs/ivf_scale.md "The qcap occupancy tax")
        return ivf_pq_search_grouped(
            index=pq, queries=qq, k=k, n_probes=n_probes,
            refine_ratio=refine, qcap="throughput",   # resolves to 24 here
        )

    # chained-dispatch two-point timing (same rationale as extra_big_knn:
    # the search program is too large for the loop-in-jit harness); shared
    # harness helper so every chained bench measures identically
    from bench.common import chained_dispatch_ms, chained_dispatch_stats

    float(jnp.sum(search(q)[0]))  # compile + warm
    st = chained_dispatch_stats(
        lambda salt: q * (1.0 + 1e-6 * salt), search, escalate=1,
    )
    if st is None:
        return {"metric": "ivf_pq", "error": "timing jitter-dominated"}

    # honest same-shape dense comparison (like the 10M row): at this
    # (n, d) the f32-exact fused scan measures ~3x the tuned ADC QPS —
    # the IVF-PQ value here is compression, not speed (docs/ivf_scale.md)
    from raft_tpu.spatial.fused_knn import fused_l2_knn

    norms = jnp.einsum("nd,nd->n", x, x, preferred_element_type=jnp.float32)

    def dense(qq):
        return fused_l2_knn(qq, x, k, metric=DistanceType.L2Expanded,
                            index_norms=norms)

    float(jnp.sum(dense(q)[0]))
    ms_dense = chained_dispatch_ms(
        lambda salt: q * (1.0 + 1e-6 * salt), dense,
    )
    out = {
        "metric": f"ivf_pq_grouped_refined_{n}x{d}_q{nq}_k{k}_p{n_probes}",
        "value": round(nq / (st["ms"] / 1e3), 1),
        "unit": "QPS",
        "spread": st["spread"],
        "repeats": st["repeats"],
        "escalations": st.get("escalations", 0),
        "adc_engine": _adc_engine(pq, nq, n_probes, qcap="throughput",
                                  refine_ratio=refine),
        "recall_at_10": round(recall_at_k(search(q)[1], true_np), 4),
        "build_s": round(build_s, 2),
        "build_warm_s": round(build_warm_s, 2),
        # r02->r03 bisect (r4): the 8660->7129 drop was runtime drift, not
        # code — the r02 library remeasures at 5982 QPS on the r4 runtime
        # vs 7140 for r03 code (docs/ivf_scale.md "Padded-list tax"); the
        # r4 gains are max_list_cap=512 + the occupancy-tuned qcap
        "note": "max_list_cap=512, qcap=24; r02 lib remeasured 5982 QPS "
                "on r4 runtime",
        # refinement ladder documented in docs/ivf_scale.md (one r5
        # sweep session: rr=8 costs ~24% QPS for recall 0.978, rr=16
        # ~50% for 0.989) — prose note, not a per-run measurement
    }
    if ms_dense is not None:
        out["brute_force_same_shape_qps"] = round(nq / (ms_dense / 1e3), 1)
    return out


def _scan_engine(index, nq, n_probes, *, qcap):
    """Which flat scan engine the row's grouped/mnmg search resolves to
    ("pallas" = the sub-chunk-min flat kernel, "xla" = the legacy
    scan) — the flat sibling of ``_adc_engine``, stamped so the driver
    can verify the kernel path was actually active. Takes the row's
    REAL qcap (the kernel's VMEM plan scales with it) so the stamp can
    never drift from the measured configuration."""
    from raft_tpu.spatial.ann.common import static_qcap
    from raft_tpu.spatial.ann.ivf_flat import _resolve_scan_engine

    return "pallas" if _resolve_scan_engine(
        None, index.centroids.shape[1],
        static_qcap(qcap, nq, n_probes, index.centroids.shape[0]),
    ) else "xla"


def _sq_scan_engine(index, nq, n_probes, *, qcap):
    """Which SQ scan engine the row's grouped search resolves to
    ("pallas" = the int8 in-kernel dequant+scan, "xla" = the dequant
    scan) — the SQ sibling of ``_scan_engine``, same real-qcap
    discipline."""
    from raft_tpu.spatial.ann.common import static_qcap
    from raft_tpu.spatial.ann.ivf_sq import _resolve_sq_engine

    return "pallas" if _resolve_sq_engine(
        None, index.centroids.shape[1],
        static_qcap(qcap, nq, n_probes, index.centroids.shape[0]),
    ) else "xla"


def _probe_kernel(index, nq, n_probes, engine_stamp, *,
                  overprobe: float = 2.0):
    """Whether the fused serving rows' two-level coarse probe runs
    through the shared scan-kernel core ("pallas") or the legacy tile
    path ("xla") — stamped on the shard rows so the driver can verify
    the probe-kernelization (ISSUE 11) was actually active. The probe
    kernel rides the engines' use_pallas static, so it engages exactly
    when the engine stamp says "pallas" AND the probe geometry fits
    the shared planner."""
    from raft_tpu.spatial.ann.common import (
        n_super_probes, two_level_probe_kernel_supported,
    )

    c = getattr(index, "coarse", None)
    if engine_stamp != "pallas" or c is None:
        return "xla"
    S = n_super_probes(n_probes, c.n_super, overprobe)
    return "pallas" if two_level_probe_kernel_supported(
        index.centroids.shape[1], nq, n_probes, c.n_super,
        c.max_members, S,
    ) else "xla"


def extra_sq_scan_kernel():
    """Single-chip grouped IVF-SQ: the XLA dequant scan vs the int8
    Pallas dequant+scan kernel (spatial/ann/sq_kernel) at the shared
    500k x 96 config — the ISSUE 11 acceptance row (>= 3x at equal
    recall on this geometry). ``value`` is the auto-engine QPS (the
    kernel on TPU), ``xla_qps`` the pinned ``use_pallas=False`` dequant
    engine on the SAME index and queries, ``speedup`` their ratio;
    recall@10 for BOTH engines against the exact oracle so "equal
    recall" is measured, not assumed. On a non-TPU backend auto
    resolves to the XLA engine and the row degenerates to speedup ~1."""
    from raft_tpu.spatial.ann import IVFSQParams, ivf_sq_build
    from raft_tpu.spatial.ann.ivf_sq import ivf_sq_search_grouped
    from bench.common import (
        ann_bench_dataset, chained_dispatch_stats, recall_at_k,
    )

    n, d, nq, k = 500_000, 96, 4096, 10
    x, q, true_np = ann_bench_dataset(n, d, nq, k)
    # same capped list geometry as the flat acceptance row so the two
    # engines' rows read side-by-side (docs/ivf_scale.md)
    idx = ivf_sq_build(x, IVFSQParams(
        n_lists=2048, kmeans_n_iters=10, max_list_cap=512,
    ))
    float(jnp.sum(idx.centroids))
    n_probes = 16

    def make(up):
        def search(qq):
            return ivf_sq_search_grouped(
                idx, qq, k, n_probes=n_probes, qcap="throughput",
                use_pallas=up,
            )
        return search

    stats = {}
    for label, up in (("auto", None), ("xla", False)):
        fn = make(up)
        float(jnp.sum(fn(q)[0]))            # compile + warm
        st = chained_dispatch_stats(
            lambda salt: q * (1.0 + 1e-6 * salt), fn, escalate=1,
        )
        if st is None:
            return {"metric": "sq_scan_kernel",
                    "error": f"{label} timing jitter-dominated"}
        stats[label] = (st, recall_at_k(fn(q)[1], true_np))
    st, rec = stats["auto"]
    st_x, rec_x = stats["xla"]
    qps = nq / (st["ms"] / 1e3)
    xla_qps = nq / (st_x["ms"] / 1e3)
    return {
        "metric": f"sq_scan_kernel_{n}x{d}_q{nq}_k{k}_p{n_probes}",
        "value": round(qps, 1),
        "unit": "QPS",
        "spread": st["spread"],
        "repeats": st["repeats"],
        "escalations": st.get("escalations", 0),
        "scan_engine": _sq_scan_engine(idx, nq, n_probes,
                                       qcap="throughput"),
        "recall_at_10": round(rec, 4),
        "xla_qps": round(xla_qps, 1),
        "xla_recall_at_10": round(rec_x, 4),
        "xla_spread": st_x["spread"],
        "speedup": round(qps / xla_qps, 2),
        "index_gb": round(idx.codes_sorted.nbytes / 1e9, 2),
    }


def extra_flat_scan_kernel():
    """Single-chip grouped IVF-Flat: the XLA scan vs the Pallas
    sub-chunk-min flat kernel (spatial/ann/flat_kernel) at the shared
    500k x 96 config — the ISSUE 10 acceptance row (>= 2x at equal
    recall). ``value`` is the auto-engine QPS (the kernel on TPU),
    ``xla_qps`` the pinned ``use_pallas=False`` engine on the SAME
    index and queries, ``speedup`` their ratio; recall@10 is reported
    for BOTH engines against the exact oracle so "equal recall" is
    measured, not assumed. On a non-TPU backend auto resolves to the
    XLA engine and the row degenerates to speedup ~1 (the kernel is
    TPU-only by auto-select)."""
    from raft_tpu.spatial.ann import IVFFlatParams, ivf_flat_build
    from raft_tpu.spatial.ann.ivf_flat import ivf_flat_search_grouped
    from bench.common import (
        ann_bench_dataset, chained_dispatch_stats, recall_at_k,
    )

    n, d, nq, k = 500_000, 96, 4096, 10
    x, q, true_np = ann_bench_dataset(n, d, nq, k)
    # same list geometry as the tuned PQ row (docs/ivf_scale.md
    # "Padded-list tax"): 2048 capped lists keep the padded slab short
    idx = ivf_flat_build(x, IVFFlatParams(
        n_lists=2048, kmeans_n_iters=10, kmeans_init="random",
        max_list_cap=512,
    ), metric="sqeuclidean")
    float(jnp.sum(idx.centroids))
    n_probes = 16

    def make(up):
        def search(qq):
            return ivf_flat_search_grouped(
                idx, qq, k, n_probes=n_probes, qcap="throughput",
                use_pallas=up,
            )
        return search

    stats = {}
    for label, up in (("auto", None), ("xla", False)):
        fn = make(up)
        float(jnp.sum(fn(q)[0]))            # compile + warm
        st = chained_dispatch_stats(
            lambda salt: q * (1.0 + 1e-6 * salt), fn, escalate=1,
        )
        if st is None:
            return {"metric": "flat_scan_kernel",
                    "error": f"{label} timing jitter-dominated"}
        stats[label] = (st, recall_at_k(fn(q)[1], true_np))
    st, rec = stats["auto"]
    st_x, rec_x = stats["xla"]
    qps = nq / (st["ms"] / 1e3)
    xla_qps = nq / (st_x["ms"] / 1e3)
    return {
        "metric": f"flat_scan_kernel_{n}x{d}_q{nq}_k{k}_p{n_probes}",
        "value": round(qps, 1),
        "unit": "QPS",
        "spread": st["spread"],
        "repeats": st["repeats"],
        "escalations": st.get("escalations", 0),
        "scan_engine": _scan_engine(idx, nq, n_probes,
                                    qcap="throughput"),
        "recall_at_10": round(rec, 4),
        "xla_qps": round(xla_qps, 1),
        "xla_recall_at_10": round(rec_x, 4),
        "xla_spread": st_x["spread"],
        "speedup": round(qps / xla_qps, 2),
    }


def extra_ivf_pq_10m():
    """IVF-PQ at 10M x 96 — the BASELINE DEEP-100M config family scaled
    to one chip (subsample-trained, block-encoded, codes-only index with
    caller-held-dataset exact refinement). Reports the honest same-shape
    brute-force number alongside: at d=96 the MXU makes the dense fused
    scan faster per query; the IVF-PQ index's single-chip win is memory
    (codes ~M bytes/row, 10x compression) and it is the only engine left
    once raw vectors outgrow HBM (the true 100M regime; the multi-chip
    sharding story is in docs/ivf_scale.md)."""
    from raft_tpu.spatial.ann import IVFPQParams, ivf_pq_build
    from raft_tpu.spatial.ann.ivf_pq import ivf_pq_search_grouped
    from raft_tpu.spatial.knn import brute_force_knn

    n, d, nq, k = 10_000_000, 96, 16_384, 10
    n_blobs = 1000
    key = jax.random.PRNGKey(7)
    centers = jax.random.normal(key, (n_blobs, d), jnp.float32) * 6.0

    @jax.jit
    def synth_block(seed, start):
        B = 1_000_000
        rows = start + jnp.arange(B)
        noise = jax.random.normal(jax.random.fold_in(key, seed), (B, d))
        return centers[rows % n_blobs] + noise

    x = jnp.concatenate([synth_block(i, i * 1_000_000) for i in range(10)])
    kq = jax.random.fold_in(key, 99)
    q = jnp.take(x, jax.random.randint(kq, (nq,), 0, n), axis=0) + \
        0.3 * jax.random.normal(jax.random.fold_in(kq, 1), (nq, d),
                                jnp.float32)
    jax.block_until_ready(q)

    bparams = IVFPQParams(
        n_lists=4096, pq_dim=24, kmeans_n_iters=10, kmeans_init="random",
        store_raw=False, train_size=1 << 20, encode_block=1 << 20,
    )
    t0 = time.perf_counter()
    pq = ivf_pq_build(x, bparams)
    float(jnp.sum(pq.codes_sorted[-1].astype(jnp.float32)))  # final-artifact sync
    build_s = time.perf_counter() - t0
    # warm rebuild: executables cached (the blocked encode is a
    # module-level jit), so this is compute; build_s - warm = compile
    t0 = time.perf_counter()
    pq2 = ivf_pq_build(x, bparams)
    float(jnp.sum(pq2.codes_sorted[-1].astype(jnp.float32)))
    build_warm_s = time.perf_counter() - t0
    del pq2

    # qcap=48 < the 64 mean occupancy: recall measured FLAT at 0.9668
    # for qcap 48..120 while QPS goes 7.6k -> 12.7k (r4 sweep;
    # docs/ivf_scale.md "The qcap occupancy tax")
    n_probes, refine, qcap = 16, 8.0, "throughput"   # resolves to 48 here

    def search(qq):
        return ivf_pq_search_grouped(
            index=pq, queries=qq, k=k, n_probes=n_probes,
            refine_ratio=refine, qcap=qcap, refine_dataset=x,
        )

    from bench.common import chained_dispatch_stats

    def chain_stats(f, qb, escalate=1):
        float(jnp.sum(f(qb)[0]))  # compile + warm
        return chained_dispatch_stats(
            lambda salt: qb * (1.0 + 1e-6 * salt), f, escalate=escalate,
        )

    # escalate=2: the r05 row shipped spread 0.268 — this row gets two
    # chain-length growths, each re-laddered, and stamps how many it used
    st = chain_stats(search, q, escalate=2)
    if st is None:
        return {"metric": "ivf_pq_10m", "error": "timing jitter-dominated"}

    # recall vs exact oracle on a 1024-query subset — sliced from the
    # FULL 16k-query run so it is measured at the TIMED configuration
    # (a subset-only search would re-resolve qcap='throughput' from the
    # small batch's occupancy and barely drop any probe pairs,
    # overstating the throughput config's recall)
    qs = q[:1024]
    _, true_ids = brute_force_knn(
        x, qs, k, metric=DistanceType.L2Expanded, use_fused=False)
    true_np = np.asarray(true_ids)
    got = np.asarray(search(q)[1][:1024])
    hits = sum(len(set(g.tolist()) & set(t.tolist()))
               for g, t in zip(got, true_np))

    # honest same-shape dense comparison: fused f32 over 4 partitions
    parts = [x[i * 2_500_000:(i + 1) * 2_500_000] for i in range(4)]
    brute = lambda qq: (brute_force_knn(
        parts, qq, k, metric=DistanceType.L2Expanded, use_fused=True
    )[0], None)
    st_brute = chain_stats(lambda qq: brute(qq), q[:4096])

    out = {
        "metric": f"ivf_pq_10m_{n}x{d}_q{nq}_k{k}_p{n_probes}",
        "value": round(nq / (st["ms"] / 1e3), 1),
        "unit": "QPS",
        "spread": st["spread"],
        "repeats": st["repeats"],
        "escalations": st.get("escalations", 0),
        "adc_engine": _adc_engine(pq, nq, n_probes, qcap=qcap,
                                  refine_ratio=refine),
        "recall_at_10": round(hits / true_np.size, 4),
        "build_s": round(build_s, 2),
        "build_warm_s": round(build_warm_s, 2),
        "index_gb": round(pq.codes_sorted.nbytes / 1e9, 2),
    }
    if st_brute is not None:
        out["brute_force_same_shape_qps"] = round(
            4096 / (st_brute["ms"] / 1e3), 1
        )
        out["brute_force_spread"] = st_brute["spread"]
    return out


def extra_mnmg_ivf_pq():
    """The sharded (multi-chip) IVF-PQ program measured on ONE chip — a
    1-device mesh runs the full shard_map pipeline (global probe,
    ownership routing, grouped ADC, shard-local refinement, allgather
    merge), so this row prices the distributed machinery's overhead vs
    the plain grouped search at the identical 500k x 96 config. Recall
    parity with the multi-chip layout is asserted on an 8-device CPU mesh
    in tests/test_mnmg_ivf.py; this is the real-hardware shard program.
    """
    from raft_tpu.comms import (
        build_comms, mnmg_ivf_pq_build, mnmg_ivf_pq_search,
    )
    from raft_tpu.spatial.ann import IVFPQParams
    from bench.common import ann_bench_dataset, recall_at_k

    n, d, nq, k = 500_000, 96, 4096, 10
    x, q, true_np = ann_bench_dataset(n, d, nq, k)

    comms = build_comms(jax.devices()[:1])
    bparams = IVFPQParams(
        n_lists=2048, pq_dim=24, kmeans_n_iters=10, kmeans_init="random",
        max_list_cap=512,
    )
    xnp = np.asarray(x)

    def timed_build():
        t0 = time.perf_counter()
        out = mnmg_ivf_pq_build(comms, xnp, bparams)
        float(jnp.sum(out.codes_sorted[:, -1].astype(jnp.float32)))
        return out, time.perf_counter() - t0

    idx, build_s = timed_build()
    _, build_warm_s = timed_build()

    def search(qq):
        # qcap="throughput" resolves to the SAME 24 as the single-chip
        # grouped row (identical nq/n_lists/n_probes), so value vs that
        # row's value IS the sharding machinery's tax (VERDICT r4 weak-3:
        # the old qcap=48 here conflated tuning with shard_map overhead)
        return mnmg_ivf_pq_search(
            comms, idx, qq, k, n_probes=16, refine_ratio=4.0,
            qcap="throughput",
        )

    from bench.common import chained_dispatch_stats

    float(jnp.sum(search(q)[0]))  # compile + warm
    st = chained_dispatch_stats(
        lambda salt: q * (1.0 + 1e-6 * salt), search, escalate=1,
    )
    if st is None:
        return {"metric": "mnmg_ivf_pq", "error": "timing jitter-dominated"}
    return {
        "metric": f"mnmg_ivf_pq_1chip_{n}x{d}_q{nq}_k{k}_p16",
        "value": round(nq / (st["ms"] / 1e3), 1),
        "unit": "QPS",
        "spread": st["spread"],
        "repeats": st["repeats"],
        "escalations": st.get("escalations", 0),
        "adc_engine": _adc_engine(idx, nq, 16, qcap="throughput",
                                  refine_ratio=4.0),
        "recall_at_10": round(recall_at_k(search(q)[1], true_np), 4),
        "build_s": round(build_s, 2),
        "build_warm_s": round(build_warm_s, 2),
        "qcap": "throughput (=24, same as the grouped single-chip row)",
    }


def extra_mnmg_shard_100m():
    """The per-chip program at the TRUE DEEP-100M shard shape (VERDICT r4
    item 2): 12.5M rows x 96 on ONE chip — 1/8 of 100M on a v5e-8 —
    with bf16 raw vectors co-sharded for exact refinement (codes ~300 MB
    + raw ~2.4 GB, the docs/ivf_scale.md layout) and 4096 owned lists
    (32768 global / 8). Converts the "only engine left at 100M" claim
    from extrapolation to measurement:

    * ``value``: QPS of the shard program driving 16k queries whose
      probes ALL land on this shard (qcap="throughput"; at the cap-2048
      builds' 8,224 local lists that resolves to 24) — 8x the per-chip
      load of the real deployment, a lower bound.
    * ``qcap8_qps``: the same program at qcap=8 — the per-(list, query)
      occupancy the real 32768-list global probe map induces on each
      chip (mean occupancy 16384*16/32768 = 8), i.e. the realistic
      per-chip search rate in the 100M deployment.
    * ``measured_chip_qps``: ONE measured jitted program — the
      deployment-scale ~65k-centroid global coarse probe (two-level:
      ``attach_coarse_index`` makes it sub-linear in the centroid
      count) FUSED with the qcap-8 shard-local search
      (``expand_probe_set`` attaches the absent 7/8 of the centroid set
      with owner=-1; the query buffer is donated, no host sync).
    * ``sharded_e2e_qps``: the same fused program with
      ``merge_ways=8`` — the in-program allgather + select_k
      cross-shard merge runs at deployment width (reference
      knn_merge_parts, knn_brute_force_faiss.cuh:289-368), so probe +
      shard search + 8-way merge are ONE measured dispatch; nothing is
      modeled anymore (the old ``projected_100m_qps`` arithmetic is
      retired).
    * ``probe_flop_ratio`` / ``probe_recall_vs_flat``: the two-level
      probe's shape-accounted FLOP win over the flat centroid scan and
      its probed-list recall against the flat scan on this workload
      (the ``overprobe`` guardrail).
    """
    return _mnmg_shard_100m_impl("pq")


def extra_mnmg_shard_100m_flat():
    """Sharded IVF-Flat at the TRUE DEEP-100M shard shape — the engine
    that actually wins the 100M x 96 deployment on a v5e-8 (r5 finding,
    docs/ivf_scale.md "Flat beats PQ at the 100M shard shape").

    At d=96 the raw bf16 rows fit the mesh (100M x 96 x 2 B = 19.2 GB =
    2.4 GB/chip), so the exact-scoring list-sharded IVF-Flat
    (comms/mnmg_ivf_flat.py) needs no compression: no one-hot ADC
    materialization, no refinement pool — per-(list, slot) selection is
    kk = k = 10 instead of the PQ path's rr*k = 80, which is what bounds
    the PQ shard row under shard_map (exact lax.top_k; the approx-top-k
    custom call loses its fast lowering there). Measured on the same
    12.5M x 96 shard/queries as the PQ row: 2.3x the QPS at HIGHER
    recall (probe-coverage ~0.9997 against the f32-exact oracle vs the
    PQ row's refinement-bound recall — see docs/ivf_scale.md's recall
    footnote), and ~6x at the real per-chip occupancy qcap=8.

    Fields mirror the PQ shard row so the two engines read side-by-side:
    ``value`` = full-load throughput-qcap QPS, ``qcap8_qps`` =
    real-occupancy QPS, ``measured_chip_qps`` = the FUSED two-level
    deployment-probe + shard-search program measured as one dispatch,
    ``sharded_e2e_qps`` = the same program with the in-program 8-way
    allgather+select_k merge (``merge_ways=8``) — the whole serving path
    as one measured dispatch, nothing modeled. The PQ index remains the
    engine when codes-only compression is required (raw rows exceeding
    the mesh: higher d, fewer chips). Reference: the Flat branch of the
    FAISS dispatch, ann_quantized_faiss.cuh:115-142."""
    return _mnmg_shard_100m_impl("flat")


def _mnmg_shard_100m_impl(engine: str):
    """Shared harness for the two true-shard-shape rows: identical data
    synthesis, search/merge/probe timing, and oracle-recall protocol —
    only the build and search calls differ, so the engines read
    side-by-side and a timing fix can never apply to one row only."""
    from raft_tpu.comms import build_comms
    from raft_tpu.spatial.knn import brute_force_knn
    from bench.common import chained_dispatch_stats, recall_at_k
    from jax.sharding import NamedSharding, PartitionSpec

    n, d, nq, k = 12_500_000, 96, 16_384, 10
    n_blobs = 1000
    key = jax.random.PRNGKey(7)
    centers = jax.random.normal(key, (n_blobs, d), jnp.float32) * 6.0
    comms = build_comms(jax.devices()[:1])

    B = 2_500_000

    @jax.jit
    def synth_block(seed, start):
        rows = start + jnp.arange(B)
        noise = jax.random.normal(jax.random.fold_in(key, seed), (B, d))
        return (centers[rows % n_blobs] + noise).astype(jnp.bfloat16)

    x = jnp.concatenate([synth_block(i, i * B) for i in range(5)])
    kq = jax.random.fold_in(key, 99)
    q = (
        jnp.take(
            x, jax.random.randint(kq, (nq,), 0, n), axis=0
        ).astype(jnp.float32)
        + 0.3 * jax.random.normal(jax.random.fold_in(kq, 1), (nq, d),
                                  jnp.float32)
    )
    jax.block_until_ready(q)

    xg = jax.device_put(
        x[None],
        NamedSharding(comms.mesh, PartitionSpec(comms.axis, None, None)),
    )
    t0 = time.perf_counter()
    if engine == "pq":
        from raft_tpu.comms.mnmg_ivf import (
            mnmg_ivf_pq_build_distributed, mnmg_ivf_pq_search,
        )
        from raft_tpu.spatial.ann import IVFPQParams

        # max_list_cap=2048 (vs the auto 2x-mean = 6104): same L-scaling
        # as the flat row (selection, one-hot ADC, and the d2 buffers
        # all carry a max_list axis) — measured 5.8k -> 11.9k full-load
        # QPS at identical recall (0.967), qcap8 9.7k -> 15.5k (r5 cap
        # probe at this exact config)
        idx = mnmg_ivf_pq_build_distributed(comms, xg, IVFPQParams(
            n_lists=4096, pq_dim=24, kmeans_n_iters=8,
            kmeans_init="random", train_size=1 << 20,
            encode_block=1 << 20, store_raw=True, max_list_cap=2048,
        ))
        float(jnp.sum(idx.codes_sorted[:, -1].astype(jnp.float32)))

        # refine_ratio=8: the r5 probe/refine sweep at this shape
        # measured recall REFINEMENT-bound, not probe-bound — p=16/24/32
        # all plateau at 0.8823 with rr=4, while rr=8 at p=16 buys
        # recall 0.9575 for only ~5% QPS (6130 -> 5827; sweep readings
        # vs the then-bf16 oracle — the row's f32 oracle reads ~0.01
        # higher at the same config, docs/ivf_scale.md recall footnote)
        def make_search(qcap, index=idx, donate=False, merge_ways=None):
            def search(qq):
                return mnmg_ivf_pq_search(
                    comms, index, qq, k, n_probes=16, refine_ratio=8.0,
                    qcap=qcap, donate_queries=donate,
                    merge_ways=merge_ways,
                )
            return search

        metric = f"mnmg_ivf_pq_shard_{n}x{d}_q{nq}_k{k}_p16"
        index_gb = (idx.codes_sorted.nbytes + idx.vectors_sorted.nbytes)
        fields = {"refine_ratio": 8.0}
    else:
        from raft_tpu.comms.mnmg_ivf_flat import (
            mnmg_ivf_flat_build_distributed, mnmg_ivf_flat_search,
        )
        from raft_tpu.spatial.ann import IVFFlatParams

        # max_list_cap=2048 (vs the auto 2x-mean = 6104): selection, the
        # (LB, qcap, L) distance buffers, and padded slab reads all scale
        # with max_list, and the r5 cap ladder at this exact config
        # measured 13.3k -> 32.4k -> 49.9k full-load QPS (caps
        # 6104/3072/2048) at recall 0.9997/0.9999/0.9994, with qcap8
        # 62.5k -> 98.8k -> 128.1k; cap=1024 over-splits (probe slots
        # dilute across duplicate parent centroids: recall 0.9814,
        # qcap8 95.9k). 2048 is the measured knee.
        idx = mnmg_ivf_flat_build_distributed(comms, xg, IVFFlatParams(
            n_lists=4096, kmeans_n_iters=8, kmeans_init="random",
            max_list_cap=2048,
        ), metric="sqeuclidean")
        float(jnp.sum(idx.sorted_ids[:, -1].astype(jnp.float32)))

        def make_search(qcap, index=idx, donate=False, merge_ways=None):
            def search(qq):
                return mnmg_ivf_flat_search(
                    comms, index, qq, k, n_probes=16, qcap=qcap,
                    donate_queries=donate, merge_ways=merge_ways,
                )
            return search

        metric = f"mnmg_ivf_flat_shard_{n}x{d}_q{nq}_k{k}_p16"
        index_gb = idx.vectors_sorted.nbytes
        fields = {"note": "exact scoring, no compression needed at d=96 "
                          "(100M bf16 = 2.4 GB/chip on 8 chips)"}
    build_s = time.perf_counter() - t0  # ~ per-chip share of a 100M build
    del xg  # the resharded build input (2.4 GB) — free HBM for searches

    # "throughput" resolves from the split-list occupancy: 24 at the
    # cap-2048 builds (8,224 local lists; it was 48 at the old auto-cap
    # 4,445 — an explicit qcap=48 rerun will NOT reproduce these rows)
    sim = make_search("throughput")
    sim_out = sim(q)                  # warm + kept for the recall oracle
    float(jnp.sum(sim_out[0]))
    st = chained_dispatch_stats(
        lambda s: q * (1.0 + 1e-6 * s), sim, escalate=1,
    )
    if st is None:
        return {"metric": metric, "error": "jitter-dominated"}

    real = make_search(8)                          # true global occupancy
    float(jnp.sum(real(q)[0]))
    st8 = chained_dispatch_stats(
        lambda s: q * (1.0 + 1e-6 * s), real, escalate=1,
    )

    # the fused one-dispatch serving program at DEPLOYMENT probe scale:
    # the deployment holds 8x this shard's rows, hence ~8x its split
    # lists. The absent 7/8 of the global centroid set is synthesized
    # from this shard's own centroids + jitter (same spatial
    # distribution, so the fused probe dilutes this shard's ownership
    # the way a real 8-chip probe map would) and attached with owner=-1
    # (expand_probe_set); attach_coarse_index then builds the two-level
    # coarse quantizer over the ~65k-centroid probe set, so the fused
    # program's global probe is sub-linear in the centroid count (the
    # r5 flat scan was ~50 ms of the 16k-query dispatch) — one jitted
    # program runs the two-level global probe AND the qcap-8 shard
    # search, with the query buffer donated.
    from raft_tpu.comms.mnmg_ivf import attach_coarse_index, expand_probe_set
    from raft_tpu.spatial.ann.common import (
        coarse_probe_recall, probe_flop_accounting,
    )

    # total split lists over ALL ranks (owner carries one entry per
    # global split list — correct for any mesh size, where the previous
    # nl_pad - 1 derivation counted only one rank's share and silently
    # assumed P=1)
    n_shard_lists = int(idx.owner.shape[0])
    n_gcents = -(-8 * n_shard_lists // 128) * 128
    kc = jax.random.fold_in(key, 5)
    cents_f32 = jnp.asarray(idx.centroids, jnp.float32)
    sel = jax.random.randint(
        kc, (n_gcents - n_shard_lists,), 0, n_shard_lists
    )
    extra = cents_f32[sel] + 0.5 * jax.random.normal(
        jax.random.fold_in(kc, 1), (n_gcents - n_shard_lists, d),
        jnp.float32,
    )
    eidx = attach_coarse_index(expand_probe_set(idx, extra))
    flops = probe_flop_accounting(eidx.coarse, 16)
    # the overprobe guardrail, measured on this workload: probed-list
    # recall of the two-level probe vs the flat 65k-centroid scan
    probe_rec = coarse_probe_recall(q[:1024], eidx.centroids, eidx.coarse, 16)
    fused = make_search(8, index=eidx, donate=True)
    # warm on a FRESH buffer — the fused program donates its query input
    # and q is reused by the oracle below
    float(jnp.sum(fused(q + 0.0)[0]))
    stf = chained_dispatch_stats(
        lambda s: q * (1.0 + 1e-6 * s), fused, escalate=1,
    )

    # the END-TO-END serving program: the same fused dispatch with the
    # in-program cross-shard merge padded to deployment width
    # (merge_ways=8 — allgather + select_k over the 8-way payload inside
    # the ONE program; absent peers contribute +inf/-1, so results are
    # identical and the select runs at deployment width). Replaces the
    # retired projected_100m_qps arithmetic with a measured number.
    e2e = make_search(8, index=eidx, donate=True, merge_ways=8)
    float(jnp.sum(e2e(q + 0.0)[0]))
    ste = chained_dispatch_stats(
        lambda s: q * (1.0 + 1e-6 * s), e2e, escalate=1,
    )

    iv = sim_out[1]

    # recall vs exact oracle on a 1024-query subset, SLICED from the full
    # 16k-query run so it reflects the timed throughput-qcap config (a
    # subset search would re-resolve 'throughput' to qcap 8 over its own
    # tiny occupancy and overstate recall)
    qs = q[:1024]
    parts = [x[i * B:(i + 1) * B] for i in range(5)]
    # oracle scores in f32 over the bf16-stored rows — the same fidelity
    # the engines' own scoring/refinement uses. A bf16-rounded oracle
    # (compute_dtype=bfloat16) understated flat recall by 1.6%: near-tie
    # oracle-side rounding flips equidistant-neighbor picks, not probe
    # misses (docs/ivf_scale.md recall footnote)
    _, true_ids = brute_force_knn(
        parts, qs, k, metric=DistanceType.L2Expanded, use_fused=True,
    )
    rec = recall_at_k(np.asarray(iv)[:1024], np.asarray(true_ids))

    out = {
        "metric": metric,
        "value": round(nq / (st["ms"] / 1e3), 1),
        "unit": "QPS",
        "spread": st["spread"],
        "repeats": st["repeats"],
        "escalations": st.get("escalations", 0),
        "recall_at_10_vs_shard": round(rec, 4),
        "build_s": round(build_s, 2),
        "index_gb": round(index_gb / 1e9, 2),
        **fields,
    }
    if engine == "pq":
        # the driver's evidence that the Pallas path was active in the
        # one-dispatch serving rows
        out["adc_engine"] = _adc_engine(idx, nq, 16, qcap="throughput",
                                         refine_ratio=8.0)
        engine_stamp = out["adc_engine"]
    else:
        # the flat sibling stamp: which scan engine the shard-local
        # grouped search inside the fused program resolved to
        out["scan_engine"] = _scan_engine(idx, nq, 16, qcap="throughput")
        engine_stamp = out["scan_engine"]
    # ISSUE 11: whether the fused rows' two-level probe ran through the
    # shared scan-kernel core (it rides the engine's use_pallas static)
    out["probe_kernel"] = _probe_kernel(eidx, nq, 16, engine_stamp)
    out["n_probe_cents"] = n_gcents
    out["probe_flop_ratio"] = round(flops["ratio"], 2)
    out["probe_recall_vs_flat"] = round(probe_rec, 4)
    if st8 is not None:
        out["qcap8_qps"] = round(nq / (st8["ms"] / 1e3), 1)
    if stf is not None:
        out["measured_chip_qps"] = round(nq / (stf["ms"] / 1e3), 1)
        out["measured_chip_spread"] = stf["spread"]
    if ste is not None:
        # probe + shard search + 8-way merge, ONE measured dispatch —
        # nothing modeled (replaces the retired projected_100m_qps)
        out["sharded_e2e_qps"] = round(nq / (ste["ms"] / 1e3), 1)
        out["sharded_e2e_spread"] = ste["spread"]
    return out


def _timed_build_500k():
    """One process's view of the 500k x 96 IVF-PQ build (the extra_ivf_pq
    config): ``build_s`` = first build in this process (cold executables —
    XLA compile, or persistent-cache deserialize when the cache is warm),
    ``build_warm_s`` = second build (in-memory executables, pure
    compute). Driven by extra_warm_start in child processes."""
    from raft_tpu.random import make_blobs
    from raft_tpu.random.rng import RngState
    from raft_tpu.spatial.ann import IVFPQParams, ivf_pq_build

    x, _ = make_blobs(500_000, 96, n_clusters=1000, cluster_std=1.0,
                      state=RngState(7))
    bparams = IVFPQParams(
        n_lists=2048, pq_dim=24, kmeans_n_iters=10, kmeans_init="random",
        max_list_cap=512,
    )

    def timed(xx):
        t0 = time.perf_counter()
        out = ivf_pq_build(xx, bparams)
        float(jnp.sum(out.codes_sorted[-1].astype(jnp.float32)))
        return time.perf_counter() - t0

    b1 = timed(x)
    b2 = timed(x * jnp.float32(1.0001))
    return {"build_s": round(b1, 2), "build_warm_s": round(b2, 2)}


def extra_warm_start():
    """Fresh-process rebuild cost under the persistent compilation cache
    (docs/serving.md "Warm start"; ISSUE r6 acceptance: within ~2x
    ``build_warm_s`` at the 500k x 96 shape).

    Two child processes run the identical build against one shared cache
    dir: the first pays XLA compiles and seeds the cache, the second —
    a genuinely fresh process — deserializes executables instead of
    compiling. ``value`` is the second process's first-build time; the
    r5 finding this attacks is cold builds at 125-250 s vs 1.6-15 s
    warm, i.e. compile-dominated."""
    import os
    import tempfile

    env = dict(os.environ)
    env["JAX_COMPILATION_CACHE_DIR"] = tempfile.mkdtemp(
        prefix="raft_tpu_xla_cache_"
    )
    env["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0"
    env["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"] = "-1"
    runs = []
    for _ in range(2):
        out = subprocess.run(
            [sys.executable, __file__, "--timed-build-500k"],
            capture_output=True, text=True, env=env, timeout=900,
        )
        runs.append(json.loads(out.stdout.strip().splitlines()[-1]))
    fresh, warm = runs[1]["build_s"], runs[1]["build_warm_s"]
    return {
        "metric": "warm_start_build_500000x96",
        "unit": "s",
        "value": fresh,
        "cold_cache_build_s": runs[0]["build_s"],
        "build_warm_s": warm,
        "cache_speedup": round(runs[0]["build_s"] / max(fresh, 1e-9), 2),
        "within_2x_warm": fresh <= 2.0 * warm,
    }


def extra_serving():
    """The serving-latency surface: p50 dispatch latency at nq ∈
    {1, 128, 1024} for fused exact kNN + grouped IVF-Flat + grouped
    IVF-PQ at the shared 500k x 96 config, measured with the
    docs/serving.md recipe (explicit warmup-resolved qcap, warm program
    cache, chained serialized dispatches so the quotient is true
    program latency). Harness: bench/bench_serving.py.

    The persistent compilation cache is enabled for the sweep's setup
    (the recipe's own warm-start step): the 9 (engine, nq) programs and
    two index builds compile once, then later rounds deserialize."""
    import os.path

    from raft_tpu.core import enable_compilation_cache

    enable_compilation_cache(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".jax_cache"
    ))
    from bench.bench_serving import serving_latency_rows

    return serving_latency_rows()


def extra_mnmg_cross_host():
    """The cross-host serving row (ISSUE 9, docs/multihost.md): host-sim
    2x4 hierarchical ICI x DCN merge vs the flat 1x8 deployment-width
    allgather on identical shards — e2e QPS of both fused programs, the
    DCN byte model per query (the >= 4x acceptance), standalone
    merge-tail latency, and the whole-host die -> failover -> heal flip
    audited for zero retraces with coverage 1.0 and bit-identical
    results at R=2 host-aware placement. Harness:
    bench/bench_mnmg.py ``cross_host_row``."""
    from bench.bench_mnmg import cross_host_row

    return cross_host_row()


_EXTRAS = {
    "big_knn": extra_big_knn,
    "kmeans": extra_kmeans,
    "ivf_pq": extra_ivf_pq,
    "flat_scan_kernel": extra_flat_scan_kernel,
    "sq_scan_kernel": extra_sq_scan_kernel,
    "ivf_pq_10m": extra_ivf_pq_10m,
    "mnmg_ivf_pq": extra_mnmg_ivf_pq,
    "mnmg_shard_100m": extra_mnmg_shard_100m,
    "mnmg_shard_100m_flat": extra_mnmg_shard_100m_flat,
    "mnmg_cross_host": extra_mnmg_cross_host,
    "serving": extra_serving,
    "warm_start": extra_warm_start,
}
# per-extra subprocess timeout seconds (default 1200): the 12.5M shard
# builds + search-program compiles need more headroom
_EXTRA_TIMEOUT = {
    "mnmg_shard_100m": 2400, "ivf_pq_10m": 1800,
    "mnmg_shard_100m_flat": 2400, "serving": 2400, "warm_start": 2000,
    "mnmg_cross_host": 1800,
}


def _current_round():
    """The round being measured = the judged round in VERDICT.md + 1
    (no VERDICT = round 1). Used to exclude this round's own artifact
    from the regression reference: a re-run after the driver has already
    written BENCH_r{N}.json must not stamp vs_prev against itself."""
    import os.path
    import re

    p = os.path.join(os.path.dirname(os.path.abspath(__file__)), "VERDICT.md")
    try:
        with open(p) as f:
            m = re.search(r"round\s+(\d+)", f.read(4096), re.IGNORECASE)
        return int(m.group(1)) + 1 if m else None
    except OSError:
        # unreadable VERDICT (round 1 has none — but then no BENCH files
        # exist either): fall through to the exclude-newest heuristic
        # rather than silently disabling the regression reference
        return None


def _load_prev_bench():
    """Latest prior-round BENCH_r*.json rows as {metric: value} — the
    per-round regression reference (VERDICT r3: two double-digit
    regressions shipped unnoticed because no round-over-round tracking
    existed). Files sort NUMERICALLY on the round number (lexicographic
    order breaks past r99) and the current round's own file is skipped."""
    import glob
    import os.path
    import re

    cur = _current_round()
    rounds = []
    for p in glob.glob(
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_r*.json")
    ):
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        if m:
            rounds.append((int(m.group(1)), p))
    if cur is not None:
        rounds = [r for r in rounds if r[0] < cur]
    elif rounds:
        # unknown current round: assume the highest-numbered file IS this
        # round's own artifact and exclude it — self-comparison always
        # stamps vs_prev ~1.0 and masks regressions
        rounds.remove(max(rounds))
    # newest PARSED round wins: a round whose line overflowed the driver
    # cap stores parsed=null (r5 did) and must not blank the regression
    # reference for every later round
    for _, path in sorted(rounds, reverse=True):
        try:
            with open(path) as f:
                doc = json.load(f)
            row = doc.get("parsed", doc)
            if row is None:
                continue
            prev = {row["metric"]: row}
            for ex in row.get("extras", []):
                if "value" in ex:
                    prev[ex["metric"]] = ex
            for p_row in prev.values():   # a stale artifact must not
                for key in _RETIRED_KEYS:  # re-seed retired keys
                    p_row.pop(key, None)
            return prev
        except Exception:
            continue
    return {}


# companion fields tracked round-over-round alongside the primary value
# (VERDICT r4 weak-2: the kmeans bf16 companion lost 24% untracked
# because vs_prev covered only each row's primary value)
_COMPANIONS = ("bf16_iters_per_s", "f32_highest_gflops",
               "brute_force_same_shape_qps", "build_warm_s",
               "qcap8_qps", "measured_chip_qps", "sharded_e2e_qps",
               "flat_e2e_qps", "xla_qps")


def _stamp_vs_prev(row, prev):
    """Attach value / previous-round value ratios — for the primary value
    AND every companion field both rounds carry. A ratio smaller than the
    row's own measured spread is stamped ``vs_prev_significant: false``:
    regression tracking must not read the noise band as movement
    (VERDICT r5: sub-spread vs_prev wobble was being narrated as
    gains/regressions)."""
    p = prev.get(row.get("metric"))
    if not p:
        return row
    if "value" in row and p.get("value"):
        row["vs_prev"] = round(row["value"] / p["value"], 3)
        spread = row.get("spread")
        if spread is not None and abs(row["vs_prev"] - 1.0) < spread:
            row["vs_prev_significant"] = False
    for f in _COMPANIONS:
        if row.get(f) and p.get(f):
            row[f"vs_prev_{f}"] = round(row[f] / p[f], 3)
    return row


# keys kept on the PRINTED driver line; everything else (prose notes,
# secondary diagnostics) lives in the locally-written bench_full.json.
# The driver's artifact fails to parse past ~1,800 printed chars —
# r5's perf evidence never landed (BENCH_r05 parsed=null) because prose
# note fields pushed the line over.
_PRINT_KEYS = {
    "metric", "value", "unit", "spread", "repeats", "escalations",
    "error", "adc_engine",
    # the flat/SQ scan-engine stamp + the flat_scan_kernel/sq_scan_kernel
    # acceptance rows (ISSUES 10/11): kernel-vs-XLA QPS on one index,
    # recall both engines; probe_kernel stamps whether the shard rows'
    # two-level probe ran through the shared scan-kernel core
    "scan_engine", "xla_qps", "xla_recall_at_10", "speedup",
    "probe_kernel",
    "recall_at_10", "recall_at_10_vs_shard", "build_s", "build_warm_s",
    "bf16_iters_per_s", "f32_highest_gflops", "vs_baseline",
    "brute_force_same_shape_qps", "measured_chip_qps", "qcap8_qps",
    "sharded_e2e_qps", "probe_recall_vs_flat", "probe_flop_ratio",
    "vs_prev_significant", "extras",
    "rows", "engine", "nq", "p50_ms", "qcap",
    "within_2x_warm",
    # the serving resilience rows (bench/bench_serving.py): straggler
    # p99 with/without hedging and the 2x-overload shed behavior
    "scenario", "p99_ms", "hedged_p99_ms", "shed_rate",
    # the mutation tier's mixed read/write row (ISSUE 7,
    # docs/mutation.md): search QPS under concurrent ingest vs the
    # frozen engine, sustained ingest rate, mutation visibility
    "mixed_search_qps", "frozen_qps", "qps_ratio_vs_frozen",
    "ingest_qps", "upsert_visible_ms", "delete_masked_ms",
    # the open-loop executor row (ISSUE 8, docs/serving.md "Open-loop
    # serving"): measured saturation vs the raw program and the
    # offered-load sweep percentiles at 50/80/95% of saturation;
    # obs_overhead_pct (ISSUE 13, docs/observability.md) is the
    # telemetry tax — saturation with the metric registry enabled vs
    # RAFT_TPU_OBS=off, acceptance <= ~2%
    "program_qps", "saturation_qps", "qps_ratio_vs_program",
    "obs_overhead_pct",
    "p50_ms_50", "p99_ms_50", "p50_ms_80", "p99_ms_80",
    "p50_ms_95", "p99_ms_95", "shed_rate_95",
    # the cross-host serving row (ISSUE 9, docs/multihost.md): host-sim
    # hierarchical vs flat e2e QPS, the DCN byte model (the >= 4x
    # acceptance), merge-tail latency, and the zero-retrace host-flip
    # audit
    "flat_e2e_qps", "qps_ratio_vs_flat", "wire",
    "dcn_bytes_per_query", "dcn_bytes_ratio",
    "merge_ms_hier", "merge_ms_flat",
    "health_flip_retraces", "coverage_host_down", "host_down_bitident",
    # the program-audit stamp (ISSUE 12, docs/static_analysis.md "Two
    # tiers"): wall ms of the jaxpr-level contract gate run in a CPU
    # subprocess alongside the bench — 0 findings is implied by the
    # stamp's presence (a red audit stamps program_audit_error instead)
    "program_audit_ms", "program_audit_error",
    # the hot-traffic shaping row (ISSUE 15, docs/serving.md "Hot
    # traffic"): cache+coalescing saturation vs the uncached path under
    # a Zipf repeated-query mix (qps_uplift is the >= 1.5x acceptance;
    # cached_identical pins equal recall on the exact tier)
    "zipf_s", "n_templates", "uncached_qps", "cached_qps",
    "qps_uplift", "cache_hit_rate", "coalesce_rate",
    "p99_ms_cached", "p99_ms_uncached", "cached_identical",
    # the cold-tier row (ISSUE 17, docs/tiering.md "Reading the bench
    # row"): same index served at 1/capacity_x the HBM budget —
    # capacity_x / recall_vs_hot / bounded p99 are the acceptance,
    # tier_hit_rate_* the hit-rate-vs-QPS curve, fetch_overlap_pct the
    # async double-buffer evidence
    "capacity_x", "n_slots", "tiered_qps", "hot_qps",
    "qps_ratio_vs_hot", "tier_hit_rate", "fetch_overlap_pct",
    "recall_vs_hot", "tier_degraded", "tier_fetches",
    "tier_hit_rate_50", "tier_hit_rate_80", "tier_hit_rate_95",
    # the self-healing supervisor row (ISSUE 18, docs/robustness.md
    # "Self-healing"): scripted kill→reroute→heal→reintegrate under
    # open-loop Zipf — detection_ms / route_convergence_ms /
    # reintegration_ms are the acceptance stamps, the per-phase p99s
    # the degradation evidence, route_pushes/heals_ok/transitions the
    # debounce audit (pushes == confirmed transitions, no flap storms)
    "detection_ms", "route_convergence_ms", "reintegration_ms",
    "p99_ms_healthy", "p99_ms_degraded", "p99_ms_healed",
    "healed_p99_x", "route_pushes", "heals_ok", "transitions",
    "all_serving", "rate_rps", "gen_lag_ms",
    # the graph-ANN row (ISSUE 19, docs/graph_ann.md): one-dispatch
    # beam p50 vs the in-row IVF-Flat qcap-1 baseline at matched
    # recall — p50_ms / recall_at_10 / ivf_p50_ms / ivf_recall_at_10
    # are the acceptance, beam/degree/iters the served config
    "ivf_p50_ms", "ivf_recall_at_10", "beam", "degree", "iters",
    "ivf_qcap", "ivf_spread",
    # the durable-WAL ingest row (ISSUE 20, docs/robustness.md
    # "Durability"): acked-ingest QPS with fsync-durable acks vs the
    # non-durable apply — durability_ratio is the >= ~0.8 acceptance,
    # fsync_interval_ms/fsync_p50_ms/wal_mb_per_s the commit-path
    # evidence
    "durable_qps", "nondurable_qps", "durability_ratio",
    "fsync_interval_ms", "fsync_p50_ms", "wal_mb_per_s",
}


# keys RETIRED from the artifact (PR 4 replaced the modeled
# projected_100m_qps arithmetic with the measured sharded_e2e_qps, yet
# BENCH_r05's shard rows still carried all three): stripped from every
# printed row AND from prior-round rows before vs_prev stamping, so a
# stale artifact can never resurrect them
_RETIRED_KEYS = ("probe_global_ms", "projected_100m_qps", "merge8_ms")


# secondary keys dropped (in order, recursively incl. their vs_prev_*
# companions) when the printed line would exceed the driver's parse cap:
# r5's artifact landed parsed=null because prose pushed the line over,
# and a trimmed-but-parsing line beats a complete-but-unparsed one
_TRIM_ORDER = (
    "repeats", "within_2x_warm", "escalations", "probe_flop_ratio",
    "probe_kernel", "build_warm_s", "program_audit_ms",
    "obs_overhead_pct",
    # zipf_hot_traffic secondaries fall before its primary
    # uplift/hit-rate evidence does
    "n_templates", "zipf_s", "cached_identical", "coalesce_rate",
    "p99_ms_uncached", "uncached_qps",
    # cold_tier secondaries fall first; capacity_x / recall_vs_hot /
    # tier_hit_rate / tiered_qps / qps_ratio_vs_hot /
    # fetch_overlap_pct / tier_hit_rate_95 are acceptance evidence and
    # stay untrimmable
    # self_heal secondaries fall first; detection_ms /
    # route_convergence_ms / reintegration_ms / healed_p99_x /
    # p99_ms_degraded are acceptance evidence and stay untrimmable
    "gen_lag_ms", "rate_rps", "all_serving", "transitions",
    "route_pushes", "heals_ok", "p99_ms_healthy", "p99_ms_healed",
    "n_slots", "tier_fetches", "tier_degraded",
    "tier_hit_rate_50", "tier_hit_rate_80", "hot_qps",
    # graph_ann secondaries fall first; p50_ms / recall_at_10 /
    # ivf_p50_ms / ivf_recall_at_10 / beam / degree / iters are
    # acceptance evidence and stay untrimmable
    "ivf_spread", "ivf_qcap",
    # durable_ingest secondaries fall first; durable_qps /
    # nondurable_qps / durability_ratio are acceptance evidence and
    # stay untrimmable
    "fsync_interval_ms", "fsync_p50_ms", "wal_mb_per_s",
    "p50_ms_50", "p50_ms_80", "shed_rate_95", "p99_ms_50",
    "upsert_visible_ms", "delete_masked_ms", "ingest_qps", "frozen_qps",
    "merge_ms_flat", "merge_ms_hier", "wire", "dcn_bytes_per_query",
    "flat_e2e_qps",
    "f32_highest_gflops", "bf16_iters_per_s", "measured_chip_qps",
    "brute_force_same_shape_qps", "qcap8_qps", "build_s",
    # the flat_scan_kernel row's secondary engine fields fall before
    # its primary value/speedup/recall do
    "xla_recall_at_10", "xla_qps",
)


def _strip_key(row, key):
    row.pop(key, None)
    row.pop(f"vs_prev_{key}", None)
    for v in row.values():
        if isinstance(v, list):
            for e in v:
                if isinstance(e, dict):
                    _strip_key(e, key)


def _core_projection(row):
    """Last-resort projection: primary value + unit + spread per row."""
    keep = ("metric", "value", "unit", "spread", "error", "vs_prev")
    out = {k: row[k] for k in keep if k in row}
    if isinstance(row.get("extras"), list):
        out["extras"] = [_core_projection(e) for e in row["extras"]]
    return out


def _fit_line(doc, cap: int = 1800) -> str:
    """The printed driver line: the compact projection, trimmed key by
    key (``_TRIM_ORDER``) until it fits the ~1,800-char parse cap, with
    a json.loads round-trip self-check BEFORE printing — a line that
    cannot round-trip or fit must never reach stdout as the artifact
    (BENCH_r05 shipped parsed=null; full rows live in bench_full.json
    either way)."""
    c = _compact(doc)
    line = json.dumps(c)
    for key in _TRIM_ORDER:
        if len(line) <= cap:
            break
        _strip_key(c, key)
        line = json.dumps(c)
    if len(line) > cap:
        # per-(engine, nq) latency rows are the next-largest block
        _strip_key(c, "rows")
        line = json.dumps(c)
    if len(line) > cap:
        line = json.dumps(_core_projection(c))
    # self-check: the emitted artifact must parse back and fit
    parsed = json.loads(line)
    if not isinstance(parsed, dict) or len(line) > cap:
        print(f"bench: printed line is {len(line)} chars (> {cap} "
              "driver parse cap) even after trimming", file=sys.stderr)
    return line


def _round_val(v):
    if isinstance(v, float):
        return round(v, 1) if abs(v) >= 100 else round(v, 4)
    return v


def _compact(row):
    """The printed projection of a row: whitelisted keys plus any
    ``vs_prev*`` ratio, floats rounded, prose dropped (string values
    survive only under identity keys — a ``note`` moved into ``qcap``
    must not sneak back onto the line)."""
    out = {}
    for key, v in row.items():
        if key in _RETIRED_KEYS or \
                key.removeprefix("vs_prev_") in _RETIRED_KEYS:
            continue          # retired artifact keys never print again
        if key not in _PRINT_KEYS and not key.startswith("vs_prev"):
            continue
        if isinstance(v, str) and key not in (
            "metric", "unit", "error", "engine", "scenario",
            "adc_engine", "scan_engine", "probe_kernel", "wire",
            "program_audit_error",
        ):
            continue
        if isinstance(v, list) and v and isinstance(v[0], dict):
            out[key] = [_compact(e) for e in v]
        else:
            out[key] = _round_val(v)
    return out


def _program_audit_stamp() -> dict:
    """Run the jaxpr-level program-contract gate (ISSUE 12,
    docs/static_analysis.md "Two tiers") in its own CPU subprocess —
    the audit traces abstractly on the virtual 8-device CPU mesh, so it
    measures the same programs regardless of the bench host's backend —
    and stamp its wall time on the headline doc. A red or crashed audit
    stamps ``program_audit_error`` (truncated) instead of hiding."""
    import os
    import time as _time

    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    }
    t0 = _time.perf_counter()
    try:
        out = subprocess.run(
            [sys.executable, "-m", "raft_tpu.analysis", "--programs"],
            capture_output=True, text=True, timeout=600, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        ms = (_time.perf_counter() - t0) * 1e3
        if out.returncode != 0:
            tail = (out.stdout + out.stderr)[-200:]
            return {"program_audit_error":
                    f"exit {out.returncode}: {tail}"[:300]}
        return {"program_audit_ms": round(ms, 1)}
    except Exception as e:
        return {"program_audit_error": f"{type(e).__name__}: {e}"[:300]}


def main():
    gflops, gflops_hi, spread = headline_pairwise()
    prev = _load_prev_bench()
    # each extra runs in its own subprocess: a clean HBM arena per config
    # (a failed 14 GB allocation must not poison the next measurement).
    # The axon terminal multiplexes processes, so the parent holding a TPU
    # client does not lock children out (measured: all extras pass with
    # the parent's client live)
    extras = []
    for name in _EXTRAS:
        out = None
        try:
            out = subprocess.run(
                [sys.executable, __file__, "--extra", name],
                capture_output=True, text=True,
                timeout=_EXTRA_TIMEOUT.get(name, 1200),
            )
            line = out.stdout.strip().splitlines()[-1] if out.stdout.strip() else ""
            extras.append(_stamp_vs_prev(json.loads(line), prev))
        except Exception as e:
            tail = (out.stderr or "")[-200:] if out is not None else ""
            extras.append({
                "metric": name,
                "error": f"{type(e).__name__}: {e} {tail}"[:300],
            })
    doc = _stamp_vs_prev({
        "metric": "pairwise_l2_expanded_8192x8192x512_f32",
        "value": round(gflops, 1),
        "unit": "GFLOPS",
        "spread": spread,
        "repeats": 3,
        **_program_audit_stamp(),
        # XLA DEFAULT matmul precision: bf16-rounded operands with f32
        # accumulation — the fastest mode; the library default for f32
        # users is HIGHEST, recorded alongside (see BASELINE.md
        # "Comparison basis" and bench/bench_distance.py for the grid)
        "operand_mode": "bf16_operands_f32_accum (XLA default)",
        "f32_highest_gflops": round(gflops_hi, 1),
        "vs_baseline": round(gflops / 10_000.0, 3),
        "extras": extras,
    }, prev)
    # full artifact (every field, prose notes included) lands next to
    # the script; the PRINTED line is the compact driver-facing
    # projection, kept under the ~1,800-char parse cap
    import os.path

    with open(
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "bench_full.json"), "w"
    ) as f:
        json.dump(doc, f, indent=1)
    print(_fit_line(doc))


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--timed-build-500k":
        print(json.dumps(_timed_build_500k()))
    elif len(sys.argv) >= 3 and sys.argv[1] == "--extra":
        try:
            print(json.dumps(_EXTRAS[sys.argv[2]]()))
        except Exception as e:
            print(json.dumps({
                "metric": sys.argv[2],
                "error": f"{type(e).__name__}: {e}"[:300],
            }))
    else:
        main()
