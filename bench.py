"""Headline benchmark — pairwise L2 distance throughput on TPU.

Mirrors the reference's distance benchmark (cpp/bench/distance/distance_exp_l2.cu
via the shared harness cpp/bench/distance/distance_common.cuh): time the
expanded-L2 pairwise distance engine on a large square problem, using the
shared loop-in-jit harness (bench/common.py — two-point difference timing
cancels the ~100 ms fixed dispatch+fetch cost of the axon tunnel; a
full-output reduce pins the dependence so XLA cannot narrow the measured
computation).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

vs_baseline is value / 10_000 GFLOPS — a RAFT-on-A100 estimate for the f32
pairwise-distance suite (the reference publishes no absolute numbers;
BASELINE.md records `"published": {}`), i.e. vs_baseline >= 1.0 means we beat
the A100 reference estimate.
"""

import contextlib
import io
import json

import jax
import numpy as np

from bench.common import bench_fn
from raft_tpu.distance.pairwise import _expanded_impl
from raft_tpu.distance.distance_type import DistanceType


def main():
    m = n = 8192
    d = 512

    rng = np.random.default_rng(42)
    # f32 operands + default MXU precision: measured fastest on v5e (the
    # bf16-input path currently hits an XLA layout-conversion slowdown —
    # see bench/bench_distance.py for the full grid)
    x = jax.device_put(rng.standard_normal((m, d)).astype(np.float32))
    y = jax.device_put(rng.standard_normal((n, d)).astype(np.float32))

    with contextlib.redirect_stdout(io.StringIO()):  # suppress harness line
        ms = bench_fn(
            lambda a, b: _expanded_impl(DistanceType.L2Expanded, a, b, "default"),
            x, y, iters=40, name="headline",
        )

    gflops = 2.0 * m * n * d / (ms / 1e3) / 1e9
    print(
        json.dumps(
            {
                "metric": "pairwise_l2_expanded_8192x8192x512_f32",
                "value": round(gflops, 1),
                "unit": "GFLOPS",
                "vs_baseline": round(gflops / 10_000.0, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
