"""Elementwise / reduction primitive benchmarks — mirrors
cpp/bench/linalg/{add,map_then_reduce,matrix_vector_op,reduce}.cu
(shape grids from their *_input_vecs tables, scaled to one chip; the
ragged +1 variants probe that unaligned tails do not collapse the
bandwidth the way misaligned CUDA loads do)."""

import numpy as np
import jax
import jax.numpy as jnp

from bench.common import bench_fn
from raft_tpu.linalg.elementwise import add, map_then_reduce
from raft_tpu.linalg.matrix_vector import matrix_vector_op
from raft_tpu.linalg.reduction import reduce


def main():
    rng = np.random.default_rng(0)

    # add.cu: 256Mi elements (x3 arrays = 3 GB) scaled to 64Mi + a ragged
    # tail variant; bytes moved = 3 * len * 4 (two reads + one write)
    for length in (64 * 1024 * 1024, 64 * 1024 * 1024 + 1):
        a = jax.device_put(rng.standard_normal(length).astype(np.float32))
        b = jax.device_put(rng.standard_normal(length).astype(np.float32))
        bench_fn(
            add, a, b, name=f"linalg/add/{length}",
            work=3.0 * length * 4, unit="GB/s",
        )

    # map_then_reduce.cu: identity map + sum reduce
    for length in (1024 * 1024, 32 * 1024 * 1024, 128 * 1024 * 1024):
        x = jax.device_put(rng.standard_normal(length).astype(np.float32))
        bench_fn(
            lambda v: map_then_reduce(lambda e: e, v),
            x, name=f"linalg/map_then_reduce/{length}",
            work=float(length) * 4, unit="GB/s",
        )

    # matrix_vector_op.cu: rows x cols grid, broadcast along rows / cols
    for rows in (1024, 1024 * 1024):
        for cols in (128, 129):
            m = jax.device_put(
                rng.standard_normal((rows, cols)).astype(np.float32)
            )
            for along in (True, False):
                v = jax.device_put(
                    rng.standard_normal(cols if along else rows).astype(
                        np.float32
                    )
                )
                bench_fn(
                    lambda mm, vv, _a=along: matrix_vector_op(
                        mm, vv, jnp.add, along_rows=_a
                    ),
                    m, v,
                    name=f"linalg/matrix_vector_op/{rows}x{cols}"
                         f"/along_rows={along}",
                    work=2.0 * rows * cols * 4, unit="GB/s",
                )

    # reduce.cu: kInputSizes grid, along rows and cols
    for rows, cols in ((8192, 1024), (1024, 8192), (8192, 8192),
                       (32 * 1024, 1024), (1024, 32 * 1024),
                       (32 * 1024, 32 * 1024)):
        x = jax.device_put(
            rng.standard_normal((rows, cols)).astype(np.float32)
        )
        for axis in (0, 1):
            bench_fn(
                lambda v, _ax=axis: reduce(v, axis=_ax),
                x, name=f"linalg/reduce/{rows}x{cols}/axis={axis}",
                work=float(rows) * cols * 4, unit="GB/s",
            )


if __name__ == "__main__":
    main()
