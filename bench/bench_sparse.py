"""Sparse high-dim distance/kNN bench — the regime the reference's hash
strategy serves (sparse/distance/detail/coo_spmv_strategies/hash_strategy.cuh):
20-newsgroups-like shape, n ~ 20k docs, d ~ 100k vocabulary, ~100 nnz/row.

Two paths:
* CSR colblock — fully dynamic inputs, nothing of size O(rows x d) ever
  materialises (a dense index here would be 8 GB).
* prebuilt SparseColBlockIndex — build-once/search-many; per-block sorted
  segment-sum densification (measured 3.7x the scatter-add, and it touches
  only each block's own entries: 15x less scatter volume).
"""

import json

import numpy as np
import jax

from bench.common import bench_fn
from raft_tpu.sparse import csr_from_scipy
from raft_tpu.sparse.distance import (
    sparse_brute_force_knn, sparse_colblock_index_build,
)


def _scipy_rand(rng, m, d, nnz_per_row):
    import scipy.sparse as ss

    return ss.random(
        m, d, density=nnz_per_row / d, format="csr", dtype=np.float32,
        random_state=rng, data_rvs=lambda k: rng.random(k).astype(np.float32),
    )


def main():
    rng = np.random.default_rng(0)
    n, nq, d, k = 20_000, 2_000, 100_000, 10
    idx_sp = _scipy_rand(rng, n, d, 100)
    qry_sp = _scipy_rand(rng, nq, d, 100)
    index = jax.device_put(csr_from_scipy(idx_sp))
    queries = jax.device_put(csr_from_scipy(qry_sp))
    layout = jax.device_put(sparse_colblock_index_build(idx_sp, 4096))

    ms_csr = bench_fn(
        lambda i, q: sparse_brute_force_knn(
            i, q, k, metric="sqeuclidean", strategy="colblock",
        ),
        index, queries, iters=8, name="sparse_knn_csr_colblock",
    )
    ms_pre = bench_fn(
        lambda i, q: sparse_brute_force_knn(i, q, k, metric="sqeuclidean"),
        layout, queries, iters=8, name="sparse_knn_prebuilt",
    )
    ms_fast = bench_fn(
        lambda i, q: sparse_brute_force_knn(
            i, q, k, metric="sqeuclidean", precision="default",
        ),
        layout, queries, iters=8, name="sparse_knn_prebuilt_bf16",
    )
    print(json.dumps({
        "metric": "sparse_knn_n20k_d100k_nnz100_k10",
        "value": round(nq / (ms_pre / 1e3), 1),
        "unit": "QPS",
        "csr_path_qps": round(nq / (ms_csr / 1e3), 1),
        "bf16_gram_qps": round(nq / (ms_fast / 1e3), 1),
        "note": "prebuilt colblock index, f32-exact gram; dense index would be 8 GB",
    }))


if __name__ == "__main__":
    main()
