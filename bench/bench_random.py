"""RNG / generator benchmarks — mirrors cpp/bench/random/
{rng,make_blobs,permute}.cu (the distribution sweep, the blobs grid, and
the row-permute shapes).

Harness note: bench/common.py defeats loop hoisting by perturbing FLOAT
args per iteration, so every generator here takes a traced float ``t``
folded into a distribution parameter (the key itself is static — the
reference benches likewise reuse one generator state across iterations).
"""

import numpy as np
import jax

from bench.common import bench_fn
from raft_tpu.random import make_blobs
from raft_tpu.random.rng import (
    RngState, exponential, fill, gumbel, laplace, logistic, lognormal,
    normal, permute, rayleigh, uniform,
)

_S = RngState(7)


def main():
    # rng.cu distribution sweep at one large len; Gsamples/s
    length = 32 * 1024 * 1024
    dists = {
        "uniform": lambda t: uniform(_S, (length,), low=t * 0),
        "normal": lambda t: normal(_S, (length,), mu=t * 0),
        "lognormal": lambda t: lognormal(_S, (length,), mu=t * 0),
        "gumbel": lambda t: gumbel(_S, (length,), mu=t * 0),
        "logistic": lambda t: logistic(_S, (length,), mu=t * 0),
        "exp": lambda t: exponential(_S, (length,), lam=1.0 + t * 0),
        "rayleigh": lambda t: rayleigh(_S, (length,), sigma=1.0 + t * 0),
        "laplace": lambda t: laplace(_S, (length,), mu=t * 0),
        "fill": lambda t: fill(_S, (length,), 3.0 + t * 0),
    }
    t0 = np.float32(0.0)
    for name, gen in dists.items():
        bench_fn(
            gen, t0,
            name=f"random/rng/{name}/{length}",
            work=float(length), unit="Gsamples/s",
        )

    # make_blobs.cu grid (rows x cols x clusters)
    for rows in (100_000, 1_000_000):
        for cols in (10, 100):
            for clusters in (2, 10, 100):
                bench_fn(
                    lambda t, _r=rows, _c=cols, _k=clusters: make_blobs(
                        _r, _c, n_clusters=_k, state=_S,
                        cluster_std=1.0 + t * 0,
                    )[0],
                    t0,
                    name=f"random/make_blobs/{rows}x{cols}/k={clusters}",
                    work=float(rows) * cols, unit="Gsamples/s",
                )

    # permute.cu: row permutation of an (n, d) matrix (perms + gathered
    # copy, the needPerms=true + rowMajor variant)
    rng_np = np.random.default_rng(0)
    for rows in (32 * 1024, 1024 * 1024):
        for cols in (128, 129):
            x = jax.device_put(
                rng_np.standard_normal((rows, cols)).astype(np.float32)
            )
            bench_fn(
                lambda v: permute(_S, v.shape[0], x=v)[1],
                x, name=f"random/permute/{rows}x{cols}",
                work=2.0 * rows * cols * 4, unit="GB/s",
            )


if __name__ == "__main__":
    main()
