"""Serving-latency surface — p50 dispatch latency at small batch for the
three serving engines (fused exact kNN, grouped IVF-Flat, grouped
IVF-PQ), swept over nq ∈ {1, 128, 1024} at the shared 500k x 96 bench
config (docs/serving.md; the reference treats n_queries as a first-class
sweep axis, cpp/bench/spatial/knn.cu:34-60).

Methodology: each point is a chained-dispatch quotient
(bench/common.py) — the chain is device-serialized by a data
dependence, so with no pipelining the per-dispatch quotient IS the
program's dispatch-to-done latency, and the two-point difference
cancels the ~100 ms axon-tunnel round trip that a naive
time-one-dispatch-and-block measurement would report as "latency". The
median over the (spread-escalated 3-7) repeats is the reported p50.

The serving recipe under measurement is the docs/serving.md one:
explicit integer qcap resolved by ``index.warmup(nq)`` (no per-call
host sync, no data-dependent re-trace), program caches warmed before
the clock starts, one jitted program per (engine, nq).
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp

NQS = (1, 128, 1024)


def serving_latency_rows(
    n: int = 500_000, d: int = 96, k: int = 10, n_probes: int = 16,
    n_lists: int = 2048, nqs=NQS, engines=("fused_knn", "ivf_flat",
                                           "ivf_pq"),
    chain=(4, 32), escalate: int = 2,
):
    """One latency row per (engine, nq): ``{"engine", "nq", "p50_ms",
    "spread", "repeats", "qcap"?}`` (``"error"`` on a failed point so one
    engine cannot sink the sweep). Parameterized so tests can run a tiny
    config on CPU; the bench defaults are the shared 500k x 96 shape."""
    from bench.common import chained_dispatch_stats
    from raft_tpu.distance.distance_type import DistanceType
    from raft_tpu.random import make_blobs
    from raft_tpu.random.rng import RngState
    from raft_tpu.spatial.ann import (
        IVFFlatParams, IVFPQParams, ivf_flat_build, ivf_pq_build,
    )
    from raft_tpu.spatial.ann.ivf_flat import ivf_flat_search_grouped
    from raft_tpu.spatial.ann.ivf_pq import ivf_pq_search_grouped
    from raft_tpu.spatial.fused_knn import fused_l2_knn

    # same synthesis as bench.common.ann_bench_dataset (clustered blobs,
    # perturbed dataset-point queries) minus the exact oracle — latency
    # rows carry no recall claim, and the oracle would double the setup
    key = jax.random.PRNGKey(2)
    x, _ = make_blobs(n, d, n_clusters=min(1000, max(2, n // 100)),
                      cluster_std=1.0, state=RngState(7))
    base = jax.random.choice(key, x, shape=(max(nqs),), axis=0)
    qall = base + 0.3 * jax.random.normal(
        jax.random.fold_in(key, 1), (max(nqs), d), jnp.float32
    )
    jax.block_until_ready(qall)
    cap = max(64, 2 * -(-n // n_lists) // 8 * 8) if n >= 100_000 else 0

    built = {}

    def get_index(engine):
        if engine not in built:
            if engine == "ivf_flat":
                built[engine] = ivf_flat_build(x, IVFFlatParams(
                    n_lists=n_lists, kmeans_n_iters=10,
                    kmeans_init="random",
                    max_list_cap=cap or None,
                ), metric="sqeuclidean")
            elif engine == "ivf_pq":
                # the 500k QPS row's pq_dim=24; smaller d falls back to
                # the largest divisor <= 24 (tiny test configs)
                pq_dim = max(
                    m for m in range(1, d + 1) if d % m == 0 and m <= 24
                )
                built[engine] = ivf_pq_build(x, IVFPQParams(
                    n_lists=n_lists, pq_dim=pq_dim, kmeans_n_iters=10,
                    kmeans_init="random", max_list_cap=cap or None,
                ))
            elif engine == "fused_knn":
                norms = jnp.einsum(
                    "nd,nd->n", x, x, preferred_element_type=jnp.float32
                )
                built[engine] = norms
        return built[engine]

    rows = []
    for engine in engines:
        for nq in nqs:
            row = {"engine": engine, "nq": nq}
            try:
                qb = qall[:nq]
                if engine == "fused_knn":
                    norms = get_index(engine)

                    def run(qq):
                        return fused_l2_knn(
                            qq, x, k, metric=DistanceType.L2Expanded,
                            index_norms=norms,
                        )
                elif engine == "ivf_flat":
                    idx = get_index(engine)
                    qcap = idx.warmup(nq, k=k, n_probes=n_probes)
                    row["qcap"] = qcap

                    def run(qq, idx=idx, qcap=qcap):
                        return ivf_flat_search_grouped(
                            idx, qq, k, n_probes=n_probes, qcap=qcap,
                        )
                else:
                    idx = get_index(engine)
                    qcap = idx.warmup(
                        nq, k=k, n_probes=n_probes, refine_ratio=4.0,
                    )
                    row["qcap"] = qcap

                    def run(qq, idx=idx, qcap=qcap):
                        return ivf_pq_search_grouped(
                            idx, qq, k, n_probes=n_probes, qcap=qcap,
                            refine_ratio=4.0,
                        )

                warm = run(qb)[0]                    # compile + warm
                float(jnp.sum(jnp.where(jnp.isfinite(warm), warm, 0.0)))
                st = chained_dispatch_stats(
                    lambda s, qb=qb: qb * (1.0 + 1e-6 * s), run,
                    n1=chain[0], n2=chain[1], escalate=escalate,
                )
                if st is None:
                    row["error"] = "jitter-dominated"
                else:
                    row["p50_ms"] = round(st["ms"], 3)
                    row["spread"] = st["spread"]
                    row["repeats"] = st["repeats"]
            except Exception as e:                   # noqa: BLE001 — one
                # failed point must not sink the other 8 rows
                row["error"] = f"{type(e).__name__}: {e}"[:160]
            rows.append(row)
    return {
        "metric": f"serving_p50_{n}x{d}_k{k}_p{n_probes}",
        "unit": "ms",
        "rows": rows,
    }


if __name__ == "__main__":
    print(json.dumps(serving_latency_rows()))
