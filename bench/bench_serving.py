"""Serving-latency surface — p50 dispatch latency at small batch for the
three serving engines (fused exact kNN, grouped IVF-Flat, grouped
IVF-PQ), swept over nq ∈ {1, 128, 1024} at the shared 500k x 96 bench
config (docs/serving.md; the reference treats n_queries as a first-class
sweep axis, cpp/bench/spatial/knn.cu:34-60).

Methodology: each point is a chained-dispatch quotient
(bench/common.py) — the chain is device-serialized by a data
dependence, so with no pipelining the per-dispatch quotient IS the
program's dispatch-to-done latency, and the two-point difference
cancels the ~100 ms axon-tunnel round trip that a naive
time-one-dispatch-and-block measurement would report as "latency". The
median over the (spread-escalated 3-7) repeats is the reported p50.

The serving recipe under measurement is the docs/serving.md one:
explicit integer qcap resolved by ``index.warmup(nq)`` (no per-call
host sync, no data-dependent re-trace), program caches warmed before
the clock starts, one jitted program per (engine, nq).

Two resilience rows ride on the IVF-Flat engine (docs/serving.md
"Overload and shedding", docs/robustness.md "hedge-delay tuning"):

* ``hedged_straggler`` — per-request latency with a deterministic
  injected straggler (every N-th dispatch polls not-ready for ~8x p50,
  ``faults.inject_straggler``), measured unhedged (``p99_ms``) and
  through ``resilience.dispatch_hedged`` (``hedged_p99_ms``): the hedge
  collapses the straggler tail toward hedge_delay + p50.
* ``overload_2x`` — a timed open-loop arrival schedule at 2x the
  measured sustainable rate driven through an
  ``AdmissionController`` (bounded queue): ``p99_ms`` of ADMITTED
  requests stays bounded at ~(max_queue+1) service times and the
  excess load is shed with ``RaftOverloadError`` (``shed_rate``)
  instead of collapsing the queue.

A third ``mixed_ingest`` row measures the mutation tier
(docs/mutation.md): search QPS under concurrent streaming ingest next
to the frozen-index QPS (``qps_ratio_vs_frozen`` — acceptance >= ~0.8
at equal recall), sustained ``ingest_qps``, and the upsert->visible /
delete->masked latencies (:func:`mixed_ingest_row`).

The ``open_loop`` row (ISSUE 8, docs/serving.md "Open-loop serving")
measures the serving EXECUTOR, not the program: a deterministic seeded
Poisson arrival stream (``raft_tpu.testing.load``) is driven through
``raft_tpu.serving.ServingExecutor`` (shape-bucketed micro-batching +
async pipelined dispatch), and the row reports

* ``program_qps`` — the raw compiled-program QPS at the largest
  bucket (closed-loop chained quotient, the denominator of the
  acceptance ratio);
* ``saturation_qps`` — measured open-loop completion rate with the
  arrival stream offered ABOVE capacity (admission sheds the excess);
* ``qps_ratio_vs_program`` — saturation over program QPS: the
  executor's dispatch-gap overhead (acceptance >= ~0.8);
* ``p50_ms_50/p99_ms_50`` (and ``_80``, ``_95``) — per-request
  latency percentiles at 50%/80%/95% of the measured saturation —
  the offered-load sweep that shows WHERE the latency knee sits.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

NQS = (1, 128, 1024)


def _p99(ms_list) -> float:
    return float(np.percentile(np.asarray(ms_list), 99.0))


def _dispatch_lat_s(run, qb, reps: int = 16):
    lat = []
    for i in range(reps):
        qi = qb * (1.0 + 1e-6 * (i + 1))
        jax.block_until_ready(qi)
        t0 = time.perf_counter()
        jax.block_until_ready(run(qi))
        lat.append(time.perf_counter() - t0)
    lat.sort()
    return lat


def _dispatch_p50_s(run, qb, reps: int = 16) -> float:
    lat = _dispatch_lat_s(run, qb, reps)
    return lat[len(lat) // 2]


def hedged_straggler_row(run, qb, *, straggler_every: int = 8,
                         n_requests: int = 64,
                         straggler_s=None) -> dict:
    """p99 with a periodic injected straggler, unhedged vs hedged.

    ``run(q)`` is the warmed serving dispatch. Every ``straggler_every``-th
    call is wrapped in a ``DelayedReady`` that polls not-ready for
    ``straggler_s`` — the deterministic slow-chip schedule, identical
    in both arms (the injector's call counter is reset between them).
    The hedge delay is percentile-derived from measured base latencies
    (~2x the observed p94, the docs/robustness.md tuning rule: well
    above the NORMAL tail so jitter cannot fire spurious hedges that
    double the load, well below the straggler so the hedge still cuts
    it); the straggler defaults to the larger of 8x p50 and 5x the
    hedge delay. The hedged arm backs up through the UNwrapped ``run``
    (the real other-replica dispatch)."""
    from raft_tpu.core.interruptible import Interruptible
    from raft_tpu.resilience.deadline import dispatch_hedged
    from raft_tpu.testing import faults

    base = _dispatch_lat_s(run, qb)
    p50 = base[len(base) // 2]
    hedge_delay_s = max(0.002, 2.0 * base[-2])   # ~2x observed p94
    straggler_s = (
        max(0.02, 8.0 * p50, 5.0 * hedge_delay_s)
        if straggler_s is None else straggler_s
    )
    wrapped, audit = faults.inject_straggler(
        run, every=straggler_every, seconds=straggler_s
    )
    # warm the hedge machinery outside the measured window: one forced
    # hedge exercises the timeout raise + wait-any path so first-call
    # costs never land in a measured tail
    warm, _ = faults.inject_straggler(run, every=1, seconds=0.01)
    Interruptible.synchronize(
        dispatch_hedged(warm, qb * (1.0 + 1e-7), hedge=0.001,
                        backup_fn=run)
    )

    def measure(dispatch):
        lat_ms = []
        for i in range(n_requests):
            qi = qb * (1.0 + 1e-6 * (i + 1))
            jax.block_until_ready(qi)
            t0 = time.perf_counter()
            out = dispatch(qi)
            Interruptible.synchronize(out)
            lat_ms.append((time.perf_counter() - t0) * 1e3)
        return lat_ms

    unhedged = measure(wrapped)
    audit.calls = 0            # identical straggle schedule in both arms
    hedged = measure(
        lambda qi: dispatch_hedged(
            wrapped, qi, hedge=hedge_delay_s, backup_fn=run,
        )
    )
    return {
        "engine": "ivf_flat",
        "scenario": "hedged_straggler",
        "nq": int(qb.shape[0]),
        "p50_ms": round(p50 * 1e3, 3),
        "p99_ms": round(_p99(unhedged), 3),
        "hedged_p99_ms": round(_p99(hedged), 3),
        "hedge_delay_ms": round(hedge_delay_s * 1e3, 3),
        "straggler_every": straggler_every,
        "straggler_ms": round(straggler_s * 1e3, 1),
        "n_requests": n_requests,
    }


def overload_row(run, qb, *, over_factor: float = 2.0,
                 n_requests: int = 96, max_queue: int = 4) -> dict:
    """Open-loop arrivals at ``over_factor``x the sustainable rate
    through a bounded-queue ``AdmissionController``: admitted p99 stays
    bounded (~``(max_queue+1)`` service times) and the excess is shed
    with ``RaftOverloadError`` — the no-queue-collapse acceptance."""
    from raft_tpu import errors
    from raft_tpu.resilience import AdmissionController

    p50 = _dispatch_p50_s(run, qb)
    interval = p50 / over_factor
    ctrl = AdmissionController(max_concurrent=1, max_queue=max_queue)
    inputs = [qb * (1.0 + 1e-6 * (i + 1)) for i in range(n_requests)]
    jax.block_until_ready(inputs)
    lock = threading.Lock()
    ok_ms, n_shed, n_timeout = [], [0], [0]

    def handle(qi):
        t0 = time.perf_counter()
        try:
            # generous in-queue wait: the queue bound, not this timeout,
            # is what sheds load
            with ctrl.admit(timeout_s=60.0):
                jax.block_until_ready(run(qi))
            with lock:
                ok_ms.append((time.perf_counter() - t0) * 1e3)
        except errors.RaftOverloadError:
            with lock:
                n_shed[0] += 1
        except errors.RaftTimeoutError:
            with lock:
                n_timeout[0] += 1

    threads = []
    t0 = time.perf_counter()
    for i, qi in enumerate(inputs):
        lag = t0 + i * interval - time.perf_counter()
        if lag > 0:
            time.sleep(lag)
        th = threading.Thread(target=handle, args=(qi,))
        th.start()
        threads.append(th)
    for th in threads:
        th.join()
    st = ctrl.stats()
    row = {
        "engine": "ivf_flat",
        "scenario": "overload_2x",
        "nq": int(qb.shape[0]),
        "p50_ms": round(p50 * 1e3, 3),
        "offered_x": over_factor,
        "shed_rate": round(n_shed[0] / n_requests, 3),
        "max_queue": max_queue,
        "n_requests": n_requests,
        "queue_peak": st.peak_queue_depth,
        "timed_out": n_timeout[0],
    }
    if ok_ms:
        row["p99_ms"] = round(_p99(ok_ms), 3)
    return row


def mixed_ingest_row(idx, qb, *, k: int = 10, n_probes: int = 16,
                     ingest_batch: int = 256, delta_cap: int = 64,
                     chain=(2, 8), escalate: int = 1) -> dict:
    """The sustained mixed read/write row (ISSUE 7 acceptance): search
    QPS while EVERY dispatch also ingests an ``ingest_batch``-row upsert
    into the mutable tier, next to the frozen-index QPS of the same
    engine/config, plus the two mutation latencies a production caller
    cares about — upsert→visible and delete→masked (each measured
    through the real ack + serve path).

    Methodology: the three throughput numbers are chained-dispatch
    quotients (bench/common.py — ``escalations`` stamped like every QPS
    row). The mixed chain drives the ASYNC ingest path (the jitted
    upsert program, state threaded functionally, no per-batch ack sync)
    interleaved with the mutable serving search; ``frozen_qps`` is the
    plain frozen engine on the identical config, so
    ``qps_ratio_vs_frozen`` prices the whole mutation tier (tombstone
    fold + delta scan + concurrent ingest). Delta capacity may saturate
    over a long measured chain — rejected upserts run the identical
    program, so the quotient is unaffected (the visibility metrics use
    their own fresh ids)."""
    import dataclasses

    from bench.common import chained_dispatch_stats
    from raft_tpu.spatial.ann.ivf_flat import ivf_flat_search_grouped
    from raft_tpu.spatial.ann.mutation import (
        _upsert_impl, delete as mut_delete, mutable_search,
        mutable_warmup, upsert as mut_upsert, wrap_mutable,
    )

    nq, d = qb.shape
    mw = wrap_mutable(idx, delta_cap=delta_cap)
    qcap = mutable_warmup(mw, nq, k=k, n_probes=n_probes,
                          ingest_batch=ingest_batch)
    row = {
        "engine": "ivf_flat", "scenario": "mixed_ingest", "nq": int(nq),
        "ingest_batch": int(ingest_batch), "qcap": int(qcap),
    }

    # frozen-index reference: the plain engine at the identical config
    idx.warmup(nq, k=k, n_probes=n_probes, qcap=qcap)

    def run_frozen(qq):
        return ivf_flat_search_grouped(idx, qq, k, n_probes=n_probes,
                                       qcap=qcap)

    jax.block_until_ready(run_frozen(qb))
    st_f = chained_dispatch_stats(
        lambda s: qb * (1.0 + 1e-6 * s), run_frozen,
        n1=chain[0], n2=chain[1], escalate=escalate,
    )

    # ingest-only: the jitted upsert program, state threaded through a
    # cell (functional updates, no ack sync — the async serving path)
    ing_ids = jnp.arange(10_000_000, 10_000_000 + ingest_batch,
                         dtype=jnp.int32)
    cell = {"delta": mw.delta, "rm": mw.row_mask}

    def run_ingest(vb):
        nd, nrm, acc, _, _ = _upsert_impl(
            idx.centroids, cell["delta"], cell["rm"], mw.id_to_pos,
            vb, ing_ids,
        )
        cell["delta"], cell["rm"] = nd, nrm
        return acc.astype(jnp.float32)

    vb0 = jnp.tile(qb, (-(-ingest_batch // nq), 1))[:ingest_batch]
    jax.block_until_ready(run_ingest(vb0))
    st_i = chained_dispatch_stats(
        lambda s: vb0 * (1.0 + 1e-6 * s), run_ingest,
        n1=chain[0], n2=chain[1], escalate=escalate,
    )

    # mixed: every dispatch ingests one batch AND serves one search
    cell["delta"], cell["rm"] = mw.delta, mw.row_mask

    def run_mixed(qq):
        vb = jnp.tile(qq, (-(-ingest_batch // nq), 1))[:ingest_batch]
        nd, nrm, _, _, _ = _upsert_impl(
            idx.centroids, cell["delta"], cell["rm"], mw.id_to_pos,
            vb, ing_ids,
        )
        cell["delta"], cell["rm"] = nd, nrm
        cur = dataclasses.replace(mw, delta=nd, row_mask=nrm)
        return mutable_search(cur, qq, k, n_probes=n_probes, qcap=qcap)

    jax.block_until_ready(run_mixed(qb))
    st_m = chained_dispatch_stats(
        lambda s: qb * (1.0 + 1e-6 * s), run_mixed,
        n1=chain[0], n2=chain[1], escalate=escalate,
    )

    if st_f is not None:
        row["frozen_qps"] = round(nq / (st_f["ms"] / 1e3), 1)
    if st_i is not None:
        row["ingest_qps"] = round(ingest_batch / (st_i["ms"] / 1e3), 1)
    if st_m is not None:
        row["mixed_search_qps"] = round(nq / (st_m["ms"] / 1e3), 1)
        row["spread"] = st_m["spread"]
        row["repeats"] = st_m["repeats"]
        row["escalations"] = st_m.get("escalations", 0)
        if st_f is not None:
            row["qps_ratio_vs_frozen"] = round(
                row["mixed_search_qps"] / row["frozen_qps"], 3
            )
    if st_f is None and st_m is None:
        row["error"] = "jitter-dominated"
        return row

    # upsert→visible: ack one fresh-id batch whose row 0 equals the
    # probe query, then serve it back — measured on WARMED programs (the
    # qcap resolved above; the 1-row probe shape pre-compiled below), so
    # the number is the serving-path ack+serve latency, not a compile
    mw2 = wrap_mutable(idx, delta_cap=delta_cap)
    qc1 = mutable_warmup(mw2, 1, k=k, n_probes=n_probes)
    mut_delete(mw2, np.array([-1], np.int32))   # warm the B=1 delete
    probe = qb[:1] * 1.001
    vis_batch = jnp.concatenate([probe, vb0[1:]])
    vis_ids = np.arange(20_000_000, 20_000_000 + ingest_batch,
                        dtype=np.int32)
    t0 = time.perf_counter()
    mw3, acc = mut_upsert(mw2, vis_batch, vis_ids)
    iv = mutable_search(mw3, probe, k, n_probes=n_probes, qcap=qc1)[1]
    jax.block_until_ready(iv)
    vis_ms = (time.perf_counter() - t0) * 1e3
    if bool(acc[0]) and int(np.asarray(iv)[0, 0]) == int(vis_ids[0]):
        row["upsert_visible_ms"] = round(vis_ms, 3)
    # delete→masked: tombstone it and serve — the row must be gone
    t0 = time.perf_counter()
    mw4, found = mut_delete(mw3, vis_ids[:1])
    iv2 = mutable_search(mw4, probe, k, n_probes=n_probes, qcap=qc1)[1]
    jax.block_until_ready(iv2)
    del_ms = (time.perf_counter() - t0) * 1e3
    if bool(found[0]) and int(vis_ids[0]) not in np.asarray(iv2)[0].tolist():
        row["delete_masked_ms"] = round(del_ms, 3)
    return row


def durable_ingest_row(idx, qb, *, ingest_batch: int = 128,
                       n_batches: int = 24, delta_cap: int = 64,
                       fsync_intervals_ms=(0.0, 2.0)) -> dict:
    """The durable-WAL ingest row (ISSUE 20, docs/robustness.md
    "Durability"): acked-ingest QPS through
    :class:`raft_tpu.durability.wal.DurableIngest` (journal + apply +
    fsync-durable ack) next to the non-durable arm (the same jitted
    apply with a host sync per batch, no journal) — so
    ``durability_ratio`` prices exactly the WAL tax: encode + group
    commit + fsync wait. Acceptance >= ~0.8.

    ``fsync_intervals_ms`` sweeps the group-commit flush interval (0 =
    byte/immediate-triggered); the stamped primary
    ``durable_qps``/``fsync_interval_ms``/``fsync_p50_ms``/
    ``wal_mb_per_s`` come from the best interval, the full sweep rides
    in ``fsync_sweep`` (bench_full.json only). The WAL lives in a temp
    dir torn down with the row; every batch uses fresh ids, and a
    saturated delta rejects through the identical program in BOTH arms,
    so the quotient stays fair."""
    import tempfile

    from raft_tpu.durability import wal as wal_mod
    from raft_tpu.spatial.ann.mutation import (
        upsert as mut_upsert, wrap_mutable,
    )

    nq, d = qb.shape
    vb0 = np.asarray(
        jnp.tile(qb, (-(-ingest_batch // nq), 1))[:ingest_batch],
        np.float32,
    )
    row = {
        "engine": "ivf_flat", "scenario": "durable_ingest",
        "ingest_batch": int(ingest_batch), "n_batches": int(n_batches),
    }

    def batches(base):
        for b in range(n_batches):
            ids = np.arange(base + b * ingest_batch,
                            base + (b + 1) * ingest_batch, dtype=np.int32)
            yield vb0 * (1.0 + 1e-6 * (b + 1)), ids

    # non-durable arm: the same apply program, host-synced per batch
    # (the ack semantics minus durability — acc realized = batch landed)
    mw = wrap_mutable(idx, delta_cap=delta_cap)
    _, warm_acc = mut_upsert(mw, vb0, np.arange(ingest_batch,
                                                dtype=np.int32))
    np.asarray(warm_acc)                         # compile + warm
    mw = wrap_mutable(idx, delta_cap=delta_cap)
    t0 = time.perf_counter()
    for vb, ids in batches(30_000_000):
        mw, acc = mut_upsert(mw, vb, ids)
        np.asarray(acc)
    nd_s = time.perf_counter() - t0
    row["nondurable_qps"] = round(n_batches * ingest_batch / nd_s, 1)

    # durable arm, one run per swept fsync interval: WAL-first apply
    # with the ack resolved only after the group commit's fsync
    sweep = []
    for iv_ms in fsync_intervals_ms:
        fsync_ms = []

        def timed_fsync(fd, _lat=fsync_ms):
            t = time.perf_counter()
            os.fsync(fd)
            _lat.append((time.perf_counter() - t) * 1e3)

        with tempfile.TemporaryDirectory() as td:
            w = wal_mod.WalWriter(
                td, flush_interval_s=iv_ms / 1e3, name="bench-wal",
                fsync=timed_fsync,
            )
            ing = wal_mod.DurableIngest(
                wrap_mutable(idx, delta_cap=delta_cap), w)
            ing.upsert(vb0, np.arange(ingest_batch, dtype=np.int32))
            fsync_ms.clear()
            t0 = time.perf_counter()
            for vb, ids in batches(40_000_000):
                ing.upsert(vb, ids)
            du_s = time.perf_counter() - t0
            wal_bytes = sum(
                os.path.getsize(s)
                for s in wal_mod.segment_paths(td))
            ing.close()
        sweep.append({
            "fsync_interval_ms": float(iv_ms),
            "durable_qps": round(n_batches * ingest_batch / du_s, 1),
            "fsync_p50_ms": round(
                float(np.median(fsync_ms)), 4) if fsync_ms else 0.0,
            "n_fsyncs": len(fsync_ms),
            "wal_mb_per_s": round(wal_bytes / du_s / 1e6, 2),
        })

    best = max(sweep, key=lambda s: s["durable_qps"])
    row.update({k: best[k] for k in (
        "durable_qps", "fsync_interval_ms", "fsync_p50_ms",
        "wal_mb_per_s",
    )})
    row["durability_ratio"] = round(
        row["durable_qps"] / row["nondurable_qps"], 3)
    row["fsync_sweep"] = sweep
    return row


def _drive_open_loop(executor, schedule, qall, *, seed: int = 0,
                     rows_fn=None):
    """Replay one open-loop schedule through the executor; returns
    ``(latencies_ms, n_shed, achieved_qps, max_lag_s)``. Latency is
    submit→future-resolution wall time per COMPLETED request; achieved
    QPS counts completed query rows over the span from first submit to
    last completion (the open-loop throughput, sheds excluded).

    ``rows_fn(i, size)`` overrides the default random-unique row draw
    with the request's EXACT rows — the ``zipf_hot_traffic`` row maps
    each request's template id to a fixed block so hot templates
    re-arrive bitwise identical (no uniqueness perturbation: the
    result cache keys on the bytes)."""
    from raft_tpu import errors
    from raft_tpu.testing import load

    done = {}
    lock = threading.Lock()
    rng = np.random.default_rng(seed)
    q_pool = np.asarray(qall, np.float32)

    def submit(i, size):
        if rows_fn is not None:
            rows = rows_fn(i, size)
        else:
            rows = q_pool[rng.integers(0, q_pool.shape[0], size=size)]
            rows = rows * (1.0 + 1e-6 * (i + 1))
        fut = executor.submit(rows)

        def _stamp(_f, i=i):
            with lock:
                done[i] = time.perf_counter()

        fut.add_done_callback(_stamp)
        return fut

    results, stamps, max_lag = load.replay(
        schedule, submit, clock=time.perf_counter
    )
    lat_ms, n_shed, rows_done = [], 0, 0
    t_last = 0.0
    for i, r in enumerate(results):
        if isinstance(r, errors.RaftOverloadError):
            n_shed += 1
            continue
        if isinstance(r, BaseException):
            raise r
        r.result(timeout=120)            # surface dispatch failures
        # result() can return before add_done_callback has stamped
        # (set_result wakes waiters first, runs callbacks after) —
        # spin the tiny gap out instead of KeyError-ing the row
        while True:
            with lock:
                t_done = done.get(i)
            if t_done is not None:
                break
            time.sleep(0.0002)
        lat_ms.append((t_done - stamps[i]) * 1e3)
        rows_done += int(schedule.sizes[i])
        t_last = max(t_last, t_done)
    span = max(t_last - float(stamps[0]), 1e-9) if lat_ms else None
    qps = rows_done / span if span else 0.0
    return lat_ms, n_shed, qps, max_lag


def open_loop_row(make_run, qall, *, buckets=(128, 1024),
                  request_size: int = 16, n_requests: int = 256,
                  fracs=(0.5, 0.8, 0.95), flush_age_s: float = 0.002,
                  max_in_flight: int = 4, chain=(4, 32),
                  escalate: int = 2, seed: int = 11,
                  min_duration_s: float = 0.5,
                  max_requests: int = 20_000) -> dict:
    """The open-loop executor row (module docstring): saturation vs the
    raw program, then the offered-load sweep at ``fracs`` of measured
    saturation with p50/p99 per point.

    ``make_run(bucket)`` returns the WARMED serving closure for one
    bucket size (the bench warms ``index.warmup(bucket)`` per bucket);
    the executor routes each micro-batch to its bucket's closure.

    ``n_requests`` is a FLOOR: each measured point is stretched to at
    least ``min_duration_s`` of offered traffic at its own rate
    (capped at ``max_requests``) — at TPU rates a fixed request count
    would finish in milliseconds and measure noise, not serving."""
    from bench.common import chained_dispatch_stats
    from raft_tpu.resilience import AdmissionController
    from raft_tpu.serving import BucketSet, ServingExecutor
    from raft_tpu.testing.load import poisson_arrivals

    bset = BucketSet.of(buckets)
    runs = {b: make_run(b) for b in bset.sizes}
    d = int(np.asarray(qall).shape[1])

    def dispatch(batch, **_rt):
        return runs[int(batch.shape[0])](batch)

    # warm every bucket program before the clock starts
    for b in bset.sizes:
        jax.block_until_ready(runs[b](jnp.zeros((b, d), jnp.float32)))

    # the denominator: raw program QPS at the largest bucket,
    # closed-loop chained quotient (no executor in the path)
    big = bset.largest
    qb = jnp.asarray(np.asarray(qall, np.float32)[:big])
    st = chained_dispatch_stats(
        lambda s: qb * (1.0 + 1e-6 * s), runs[big],
        n1=chain[0], n2=chain[1], escalate=escalate,
    )
    row = {
        "engine": "ivf_flat", "scenario": "open_loop",
        "nq": big, "buckets": list(bset.sizes),
        "request_size": int(request_size),
        "n_requests": int(n_requests),
        "max_in_flight": int(max_in_flight),
    }
    if st is None:
        row["error"] = "jitter-dominated"
        return row
    program_qps = big / (st["ms"] / 1e3)
    row["program_qps"] = round(program_qps, 1)
    row["spread"] = st["spread"]
    row["repeats"] = st["repeats"]

    def fresh_executor():
        return ServingExecutor(
            dispatch, bset, dim=d, flush_age_s=flush_age_s,
            max_in_flight=max_in_flight,
            admission=AdmissionController(
                max_concurrent=max(1, 4 * big // request_size),
                max_queue=max(8, 4 * big // request_size),
            ),
        )

    def n_for(rate_rps):
        return int(min(max_requests,
                       max(n_requests, min_duration_s * rate_rps)))

    # saturation: offer ~1.5x the program rate; the completion rate IS
    # the executor's deliverable throughput (sheds excluded). Measured
    # TWICE — registry enabled (the production posture; this is the
    # reported saturation_qps) and RAFT_TPU_OBS=off — so the row stamps
    # the telemetry tax directly (`obs_overhead_pct`, ISSUE 13
    # acceptance: <= ~2%; the executor records its per-stage
    # histograms into the default registry either way, the gate just
    # turns every observe into an attribute load)
    from raft_tpu.obs import metrics as obsm

    rate_rps = 1.5 * program_qps / request_size
    prev_obs = obsm.set_enabled(True)
    try:
        with fresh_executor() as ex:
            _, _, sat_qps, sat_lag = _drive_open_loop(
                ex, poisson_arrivals(rate_rps, n_for(rate_rps),
                                     seed=seed, sizes=request_size),
                qall, seed=seed,
            )
        obsm.set_enabled(False)
        with fresh_executor() as ex:
            _, _, sat_qps_off, _ = _drive_open_loop(
                ex, poisson_arrivals(rate_rps, n_for(rate_rps),
                                     seed=seed, sizes=request_size),
                qall, seed=seed,
            )
    finally:
        obsm.set_enabled(prev_obs)
    row["saturation_qps"] = round(sat_qps, 1)
    row["qps_ratio_vs_program"] = round(sat_qps / program_qps, 3)
    if sat_qps_off > 0:
        row["obs_overhead_pct"] = round(
            100.0 * (1.0 - sat_qps / sat_qps_off), 2)
    # generator self-check (bench_full only): a lag comparable to the
    # mean inter-arrival gap means the measured rate was submit-bound
    row["gen_lag_ms_sat"] = round(sat_lag * 1e3, 3)

    # the offered-load sweep: p50/p99 at each fraction of saturation
    for frac in fracs:
        tag = f"{int(round(frac * 100))}"
        offered = frac * sat_qps / request_size
        if offered <= 0:
            continue
        n_point = n_for(offered)
        with fresh_executor() as ex:
            lat_ms, n_shed, qps, lag = _drive_open_loop(
                ex, poisson_arrivals(offered, n_point,
                                     seed=seed + int(frac * 100),
                                     sizes=request_size),
                qall, seed=seed + 1,
            )
        row[f"gen_lag_ms_{tag}"] = round(lag * 1e3, 3)
        if lat_ms:
            lat = np.asarray(lat_ms)
            row[f"p50_ms_{tag}"] = round(float(np.percentile(lat, 50)), 3)
            row[f"p99_ms_{tag}"] = round(float(np.percentile(lat, 99)), 3)
            row[f"achieved_qps_{tag}"] = round(qps, 1)
        if n_shed:
            row[f"shed_rate_{tag}"] = round(n_shed / n_point, 3)
    return row


def zipf_hot_traffic_row(make_run, qall, *, k: int,
                         buckets=(128, 1024), request_size: int = 16,
                         n_templates: int = 64, zipf_s: float = 1.1,
                         n_requests: int = 256,
                         flush_age_s: float = 0.002,
                         max_in_flight: int = 4, chain=(4, 32),
                         escalate: int = 2, seed: int = 23,
                         min_duration_s: float = 0.5,
                         max_requests: int = 20_000,
                         offered_x_cached: float = 4.0) -> dict:
    """The hot-traffic shaping row (ISSUE 15, docs/serving.md "Hot
    traffic"): saturation QPS and p99 under a Zipf(``zipf_s``)
    repeated-query mix, measured TWICE at fixed hardware — the plain
    executor (``uncached_qps``/``p99_ms_uncached``) vs the same
    executor with the result cache + request coalescing enabled
    (``cached_qps``/``p99_ms_cached``), plus ``qps_uplift`` (the >= 1.5x
    acceptance), ``cache_hit_rate`` and ``coalesce_rate`` from the
    executor's own counters, and ``cached_identical`` (a cached answer
    re-served for a hot template is bitwise the uncached program's —
    the exact tier serves at EQUAL recall by construction; the
    semantic tier stays off here, its guardrail is a per-deployment
    calibration).

    Traffic: ``n_templates`` fixed query blocks of ``request_size``
    rows; each request draws its template from
    :func:`raft_tpu.testing.load.zipf_template_weights` — hot
    templates re-arrive bitwise identical, exactly the traffic shape
    the cache keys on. The cached arm is offered
    ``offered_x_cached``x the raw program rate (the cache can clear
    MORE than program QPS, so saturating it needs more offered load
    than the uncached arm's 1.5x)."""
    from bench.common import chained_dispatch_stats
    from raft_tpu.resilience import AdmissionController
    from raft_tpu.serving import BucketSet, ResultCache, ServingExecutor
    from raft_tpu.testing.load import poisson_arrivals

    bset = BucketSet.of(buckets)
    runs = {b: make_run(b) for b in bset.sizes}
    d = int(np.asarray(qall).shape[1])

    def dispatch(batch, **_rt):
        return runs[int(batch.shape[0])](batch)

    for b in bset.sizes:
        jax.block_until_ready(runs[b](jnp.zeros((b, d), jnp.float32)))

    # the fixed template pool: template t IS a (request_size, d) block,
    # re-submitted verbatim on every arrival of t
    rng = np.random.default_rng(seed)
    q_pool = np.asarray(qall, np.float32)
    pool = np.stack([
        q_pool[rng.integers(0, q_pool.shape[0], size=request_size)]
        * (1.0 + 1e-6 * (t + 1))
        for t in range(n_templates)
    ])

    big = bset.largest
    qb = jnp.asarray(q_pool[:big])
    st = chained_dispatch_stats(
        lambda s: qb * (1.0 + 1e-6 * s), runs[big],
        n1=chain[0], n2=chain[1], escalate=escalate,
    )
    row = {
        "engine": "ivf_flat", "scenario": "zipf_hot_traffic",
        "nq": big, "request_size": int(request_size),
        "zipf_s": float(zipf_s), "n_templates": int(n_templates),
    }
    if st is None:
        row["error"] = "jitter-dominated"
        return row
    program_qps = big / (st["ms"] / 1e3)
    row["program_qps"] = round(program_qps, 1)
    row["spread"] = st["spread"]
    row["repeats"] = st["repeats"]

    def fresh_executor(cache: bool):
        rcache = None
        if cache:
            rcache = ResultCache(
                k, n_sets=max(64, 2 * n_templates), associativity=8,
                name="zipf_bench",
            )
        return ServingExecutor(
            dispatch, bset, dim=d, flush_age_s=flush_age_s,
            max_in_flight=max_in_flight,
            admission=AdmissionController(
                max_concurrent=max(1, 4 * big // request_size),
                max_queue=max(8, 4 * big // request_size),
            ),
            result_cache=rcache,
        )

    def n_for(rate_rps):
        return int(min(max_requests,
                       max(n_requests, min_duration_s * rate_rps)))

    def drive(ex, rate_rps, seed_pt):
        sched = poisson_arrivals(
            rate_rps, n_for(rate_rps), seed=seed_pt,
            sizes=request_size, zipf_s=zipf_s, n_templates=n_templates,
        )
        return _drive_open_loop(
            ex, sched, q_pool, seed=seed_pt,
            rows_fn=lambda i, _size, s=sched: pool[
                int(s.template_ids[i])],
        )

    results = {}
    for arm, offered_x in (("uncached", 1.5),
                           ("cached", offered_x_cached)):
        rate = offered_x * program_qps / request_size
        with fresh_executor(arm == "cached") as ex:
            _, _, sat_qps, _ = drive(ex, rate, seed)
            sat_stats = ex.stats()
        # p99 at 80% of the arm's OWN measured saturation
        p99_rate = 0.8 * sat_qps / request_size
        if p99_rate > 0:
            with fresh_executor(arm == "cached") as ex:
                lat_ms, _, _, _ = drive(ex, p99_rate, seed + 7)
            if lat_ms:
                row[f"p99_ms_{arm}"] = round(
                    float(np.percentile(np.asarray(lat_ms), 99)), 3)
        results[arm] = (sat_qps, sat_stats)

    row["uncached_qps"] = round(results["uncached"][0], 1)
    row["cached_qps"] = round(results["cached"][0], 1)
    if results["uncached"][0] > 0:
        row["qps_uplift"] = round(
            results["cached"][0] / results["uncached"][0], 3)
    st_c = results["cached"][1]
    if st_c.submitted:
        row["cache_hit_rate"] = round(
            st_c.cache_hits / st_c.submitted, 3)
        row["coalesce_rate"] = round(
            st_c.coalesced_requests / st_c.submitted, 3)

    # equal-recall spot check: the cached answer for a hot template is
    # bitwise the warmed program's own answer for that template block
    b0 = bset.select(request_size)
    padded = np.zeros((b0, d), np.float32)
    padded[:request_size] = pool[0]
    ref_ids = np.asarray(runs[b0](jnp.asarray(padded))[1])[:request_size]
    rc_spot = ResultCache(k, n_sets=max(64, 2 * n_templates),
                          associativity=8, name="zipf_spot")
    with ServingExecutor(dispatch, bset, dim=d,
                         flush_age_s=flush_age_s,
                         result_cache=rc_spot) as ex:
        ex.submit(pool[0]).result(timeout=60)
        # the cache fill is asynchronous (the demux thread writes it
        # AFTER resolving the caller) — wait for the insert so the
        # re-submit exercises the hit path, not a fill race
        t0 = time.monotonic()
        while rc_spot.stats().inserts < request_size \
                and time.monotonic() - t0 < 10.0:
            time.sleep(0.002)
        cached = ex.submit(pool[0]).result(timeout=60)
        hit = ex.stats().cache_hits >= 1
    row["cached_identical"] = bool(
        hit and np.array_equal(np.asarray(cached[1]), ref_ids))
    return row


def cold_tier_row(index, qall, *, k: int, n_probes: int,
                  capacity_x: float = 4.0, buckets=(128, 1024),
                  request_size: int = 16, n_templates: int = 64,
                  zipf_s: float = 1.1, n_requests: int = 256,
                  flush_age_s: float = 0.002, max_in_flight: int = 4,
                  chain=(4, 32), escalate: int = 2, seed: int = 29,
                  min_duration_s: float = 0.5,
                  max_requests: int = 20_000,
                  fracs=(0.5, 0.8, 0.95)) -> dict:
    """The popularity-tiered cold-tier row (ISSUE 17, docs/tiering.md
    "Reading the bench row"): the SAME index served two ways at fixed
    hardware — fully resident (``hot_qps``, the baseline every tier
    claim is priced against) vs through a
    :class:`~raft_tpu.tier.TieredListStore` whose hot "HBM" budget is
    ``1/capacity_x`` of the cold slab's bytes (``tiered_qps``), under
    the Zipf(``zipf_s``) template mix the tier exists for. Stamps:

    * ``capacity_x`` — measured cold/hot byte ratio (the >= 4x
      acceptance: the tier SERVES an index 4x its hot budget);
    * ``qps_ratio_vs_hot`` + the ``p99_ms_{50,80,95}`` sweep at
      fractions of the TIERED arm's own saturation (bounded p99);
    * ``tier_hit_rate`` (+ per-sweep-point ``tier_hit_rate_{tag}``) —
      the hit-rate-vs-QPS curve, post-convergence;
    * ``recall_vs_hot`` — measured id-overlap recall of the tiered
      answer vs the full-resident program ON the template traffic
      (the >= 0.95 acceptance);
    * ``fetch_overlap_pct`` — fetch spans stamped compute-overlapped
      (the executor was mid-flight), the async double-buffer evidence.

    The hot working set is converged ONCE (a gentle warm pass + fetcher
    drain) before any measured arm: the row prices the steady state,
    not the cold start — cold-start behavior is the degraded-probe
    guardrail's territory (tests/test_tier.py)."""
    from bench.common import chained_dispatch_stats
    from raft_tpu.resilience import AdmissionController
    from raft_tpu.serving import BucketSet, ServingExecutor
    from raft_tpu.spatial.ann.ivf_flat import ivf_flat_search_grouped
    from raft_tpu.testing.load import poisson_arrivals
    from raft_tpu.tier import (
        PromotionPolicy, SlabFetcher, TieredListStore,
    )

    bset = BucketSet.of(buckets)
    q_pool = np.asarray(qall, np.float32)
    d = int(q_pool.shape[1])
    qcaps = {b: index.warmup(b, k=k, n_probes=n_probes)
             for b in bset.sizes}

    def make_hot(b):
        def run(qq, qcap=qcaps[b]):
            return ivf_flat_search_grouped(
                index, qq, k, n_probes=n_probes, qcap=qcap,
            )
        return run

    runs = {b: make_hot(b) for b in bset.sizes}

    def hot_dispatch(batch, **_rt):
        return runs[int(batch.shape[0])](batch)

    for b in bset.sizes:
        jax.block_until_ready(runs[b](jnp.zeros((b, d), jnp.float32)))

    # the tier under test: hot budget = cold bytes / capacity_x
    storage = index.storage
    itemsize = np.asarray(index.data_sorted).dtype.itemsize
    cold_bytes = int(storage.n) * d * itemsize
    store = TieredListStore(
        index, hbm_budget_bytes=max(1, int(cold_bytes // capacity_x)),
        name="cold_tier", min_recall=0.95, touch_decay=0.95,
    )
    L = int(storage.max_list)
    big = bset.largest
    row = {
        "engine": "ivf_flat", "scenario": "cold_tier", "nq": big,
        "request_size": int(request_size), "zipf_s": float(zipf_s),
        "n_templates": int(n_templates), "n_slots": store.n_slots,
        "capacity_x": round(
            cold_bytes / (store.n_slots * L * d * itemsize), 2),
    }

    qb = jnp.asarray(q_pool[:big])
    st = chained_dispatch_stats(
        lambda s: qb * (1.0 + 1e-6 * s), runs[big],
        n1=chain[0], n2=chain[1], escalate=escalate,
    )
    if st is not None:
        program_qps = big / (st["ms"] / 1e3)
        row["spread"] = st["spread"]
        row["repeats"] = st["repeats"]
    else:
        # jitter-dominated host: a crude timed denominator beats
        # shipping no tier evidence at all (stamped by the missing
        # spread/repeats)
        t0 = time.perf_counter()
        for s in range(3):
            jax.block_until_ready(runs[big](qb * (1.0 + 1e-6 * s)))
        program_qps = 3 * big / max(time.perf_counter() - t0, 1e-9)
    row["program_qps"] = round(program_qps, 1)

    # the fixed Zipf template pool (the zipf_hot_traffic discipline:
    # hot templates re-arrive bitwise identical)
    rng = np.random.default_rng(seed)
    pool = np.stack([
        q_pool[rng.integers(0, q_pool.shape[0], size=request_size)]
        * (1.0 + 1e-6 * (t + 1))
        for t in range(n_templates)
    ])

    ex_box = {}

    def busy() -> bool:
        ex = ex_box.get("ex")
        return bool(ex is not None and ex.stats().in_flight > 0)

    def tier_dispatch(batch, tier=None, **_rt):
        return store.search(
            batch, k, n_probes=n_probes,
            qcap=qcaps[int(batch.shape[0])], runtime=tier,
        )

    def fresh_executor(tiered: bool):
        ex = ServingExecutor(
            tier_dispatch if tiered else hot_dispatch, bset, dim=d,
            flush_age_s=flush_age_s, max_in_flight=max_in_flight,
            admission=AdmissionController(
                max_concurrent=max(1, 4 * big // request_size),
                max_queue=max(8, 4 * big // request_size),
            ),
            runtime_provider=store.runtime if tiered else None,
        )
        ex_box["ex"] = ex
        return ex

    def n_for(rate_rps):
        return int(min(max_requests,
                       max(n_requests, min_duration_s * rate_rps)))

    def drive(ex, rate_rps, seed_pt):
        sched = poisson_arrivals(
            rate_rps, n_for(rate_rps), seed=seed_pt,
            sizes=request_size, zipf_s=zipf_s,
            n_templates=n_templates,
        )
        return _drive_open_loop(
            ex, sched, q_pool, seed=seed_pt,
            rows_fn=lambda i, _size, s=sched: pool[
                int(s.template_ids[i])],
        )

    policy = PromotionPolicy(demote_margin=1.25, min_touches=2.0,
                             max_moves=8)
    fetcher = SlabFetcher(store, window=4, policy=policy,
                          busy_fn=busy,
                          max_pending=4 * store.n_slots)
    try:
        # converge the hot set off the clock (misses -> async fills)
        with fresh_executor(True) as ex:
            drive(ex, max(1.0, 0.25 * program_qps / request_size),
                  seed + 3)
        fetcher.drain(60.0)
        s0 = store.stats()

        rate = 1.5 * program_qps / request_size
        with fresh_executor(False) as ex:
            _, _, hot_qps, _ = drive(ex, rate, seed)
        with fresh_executor(True) as ex:
            _, _, tiered_qps, _ = drive(ex, rate, seed)
        row["hot_qps"] = round(hot_qps, 1)
        row["tiered_qps"] = round(tiered_qps, 1)
        if hot_qps > 0:
            row["qps_ratio_vs_hot"] = round(tiered_qps / hot_qps, 3)

        # the hit-rate-vs-QPS sweep at fractions of the TIERED arm's
        # own measured saturation
        for frac in fracs:
            tag = f"{int(round(frac * 100))}"
            offered = frac * tiered_qps / request_size
            if offered <= 0:
                continue
            pre = store.stats()
            with fresh_executor(True) as ex:
                lat_ms, _, _, _ = drive(ex, offered,
                                        seed + int(frac * 100))
            post = store.stats()
            hits = post.probe_hits - pre.probe_hits
            misses = post.probe_misses - pre.probe_misses
            if hits + misses:
                row[f"tier_hit_rate_{tag}"] = round(
                    hits / (hits + misses), 3)
            if lat_ms:
                row[f"p99_ms_{tag}"] = round(
                    float(np.percentile(np.asarray(lat_ms), 99)), 3)

        send = store.stats()
        dh = send.probe_hits - s0.probe_hits
        dm = send.probe_misses - s0.probe_misses
        if dh + dm:
            row["tier_hit_rate"] = round(dh / (dh + dm), 3)
        row["fetch_overlap_pct"] = round(send.fetch_overlap_pct, 1)
        row["tier_fetches"] = send.fetches
    finally:
        fetcher.close()

    # measured recall of the tiered answer vs the full-resident
    # program ON the template traffic, post-convergence (the >= 0.95
    # acceptance; measure_recall also feeds the tier_recall gauge)
    recalls = [
        store.measure_recall(pool[t], k, n_probes=n_probes)
        for t in range(min(8, n_templates))
    ]
    row["recall_vs_hot"] = round(float(np.mean(recalls)), 4)
    row["tier_degraded"] = bool(store.degraded)
    return row


def self_heal_row(x, qall, *, k: int = 10, n_probes: int = 16,
                  replication: int = 2, n_lists: int = 32,
                  request_size: int = 8, n_templates: int = 32,
                  zipf_s: float = 1.1, kill_at_s: float = 0.6,
                  heal_at_s: float = 2.0, duration_s: float = 4.0,
                  max_rows: int = 65_536, consecutive: int = 2,
                  cooldown_s: float = 0.1, seed: int = 43) -> dict:
    """The self-healing supervisor row (ISSUE 18, docs/robustness.md
    "Self-healing"): one scripted kill→reroute→heal→reintegrate cycle
    against a live open-loop Zipf stream, with the SUPERVISOR doing all
    recovery — the schedule only flips the scripted health truth (and
    wrecks the dead rank's slabs, so the reroute is load-bearing, not
    cosmetic). Builds its own R-way replicated MNMG index over every
    visible device (needs >= 2; error-stamped row otherwise). Stamps:

    * ``detection_ms`` — kill instant → the monitor's confirmed down
      (the debounce cost: ``consecutive`` probes + tick cadence);
    * ``route_convergence_ms`` — kill instant → the supervisor's route
      push landing in the executor (acceptance: bounded, no manual
      call in the path);
    * ``reintegration_ms`` — heal signal → heal_done (checkpoint
      re-splice via ``recover_rank``; the recover program is warmed
      off the clock, so this prices the steady-state heal, not a
      first-compile);
    * ``p99_ms_healthy`` / ``p99_ms_degraded`` / ``p99_ms_healed`` —
      per-request p99 split by submit stamp into the three phases, and
      ``healed_p99_x`` (healed/healthy — the did-it-actually-recover
      ratio).

    Requests keep flowing through the whole cycle; admission is
    unbounded here because the row prices the failover path, not
    shedding (that is ``overload_2x``)."""
    import os
    import shutil
    import tempfile

    from raft_tpu.comms import (
        build_comms, mnmg_ivf_flat_build, mnmg_ivf_flat_search,
        place_index, recover_rank,
    )
    from raft_tpu.resilience import (
        FailoverPlan, HealActions, HealthMonitor, ReplicaPlacement,
        ServingSupervisor, ShardHealth,
    )
    from raft_tpu.serving import ServingExecutor
    from raft_tpu.spatial.ann import IVFFlatParams, save_index
    from raft_tpu.testing import chaos, load

    row = {
        "engine": "ivf_flat", "scenario": "self_heal",
        "nq": int(request_size), "request_size": int(request_size),
        "zipf_s": float(zipf_s), "n_templates": int(n_templates),
        "replication": int(replication),
    }
    devices = jax.devices()
    if len(devices) < 2:
        row["error"] = "self_heal needs >= 2 devices"
        return row
    n_ranks = len(devices)
    row["n_ranks"] = n_ranks
    comms = build_comms(devices)
    xs = np.asarray(x, np.float32)[:max_rows]
    idx0 = mnmg_ivf_flat_build(
        comms, xs,
        IVFFlatParams(n_lists=n_lists, kmeans_n_iters=4,
                      kmeans_init="random", seed=seed),
        metric="sqeuclidean",
    )
    rep = place_index(comms, idx0, replication=replication)
    tmp = tempfile.mkdtemp(prefix="raft_tpu_self_heal_")
    ckpt = os.path.join(tmp, "base.npz")
    try:
        save_index(rep, ckpt)
        cell = {"idx": rep}
        cell_lock = threading.Lock()
        qcap = int(request_size)
        d = int(np.asarray(qall).shape[1])

        def dispatch(batch, shard_mask=None, failover=None, **_rt):
            with cell_lock:
                idx = cell["idx"]
            return mnmg_ivf_flat_search(
                comms, idx, batch, k, n_probes=n_probes, qcap=qcap,
                shard_mask=(shard_mask if shard_mask is not None
                            else np.ones(n_ranks, np.int32)),
                failover=failover,
            )

        health = ShardHealth(n_ranks)
        placement = ReplicaPlacement.of_index(rep)
        monitor = HealthMonitor(n_ranks, consecutive=consecutive,
                                cooldown_s=cooldown_s,
                                clock=time.perf_counter)
        scripted = chaos.ScriptedHealth(n_ranks)
        dead = n_ranks // 2

        def recover(rank):
            with cell_lock:
                cell["idx"] = recover_rank(comms, cell["idx"], ckpt,
                                           rank)

        sup = ServingSupervisor(
            health, placement, scripted.probe,
            heal=HealActions(recover=recover), monitor=monitor,
            interval_s=0.01, step_deadline_s=120.0,
            clock=time.perf_counter, name="bench-self-heal",
        )

        # warm the serving AND recover programs off the clock, so the
        # stamps price the steady state, not first compiles
        plan0 = FailoverPlan.load_balanced(placement, health)
        q_pool = np.asarray(qall, np.float32)
        rng = np.random.default_rng(seed)
        pool = np.stack([
            q_pool[rng.integers(0, q_pool.shape[0], size=request_size)]
            * (1.0 + 1e-6 * (t + 1))
            for t in range(n_templates)
        ])
        jax.block_until_ready(dispatch(
            jnp.asarray(pool[0]), shard_mask=health.mask(),
            failover=plan0,
        ))
        recover_rank(comms, rep, ckpt, dead)      # discarded warm splice

        service_s = _dispatch_p50_s(
            lambda qq: dispatch(qq), jnp.asarray(pool[0]), reps=8,
        )
        rate_rps = max(4.0, 0.5 / max(service_s, 1e-4))
        n_requests = int(duration_s * rate_rps) + 1
        row["rate_rps"] = round(rate_rps, 1)
        row["n_requests"] = n_requests

        ex = ServingExecutor(
            dispatch, (qcap,), dim=d, flush_age_s=0.0,
            max_in_flight=2,
            runtime_inputs={"shard_mask": health.mask(),
                            "failover": plan0},
        )
        sup.register(ex)

        marks = {}

        def kill_fire():
            marks["kill"] = time.perf_counter()
            with cell_lock:
                idx = cell["idx"]
                cell["idx"] = dataclasses.replace(
                    idx,
                    vectors_sorted=jnp.asarray(idx.vectors_sorted)
                    .at[dead].set(0),
                    sorted_ids=jnp.asarray(idx.sorted_ids)
                    .at[dead].set(0),
                )
            scripted.set(dead, False)

        def heal_fire():
            marks["heal"] = time.perf_counter()
            scripted.set(dead, True)

        csched = chaos.ChaosSchedule(scripted=scripted, seed=seed)
        csched.at(kill_at_s, f"kill_rank_{dead}", kill_fire)
        csched.at(heal_at_s, f"heal_rank_{dead}", heal_fire)

        sched_load = load.poisson_arrivals(
            rate_rps, n_requests, seed=seed, sizes=request_size,
            zipf_s=zipf_s, n_templates=n_templates,
        )
        done = {}
        dlock = threading.Lock()

        def submit(i, size):
            fut = ex.submit(pool[int(sched_load.template_ids[i])])

            def _stamp(_f, i=i):
                with dlock:
                    done[i] = time.perf_counter()

            fut.add_done_callback(_stamp)
            return fut

        out = {}

        def drive():
            out["res"], out["stamps"], out["lag"] = load.replay(
                sched_load, submit, clock=time.perf_counter,
            )

        drv = threading.Thread(target=drive, daemon=True,
                               name="self-heal-load")
        drv.start()
        try:
            chaos.run_schedule(csched, duration_s=duration_s,
                               tick=lambda t: sup.step())
            # settle: a slow host may cross duration mid-reintegration
            t_end = time.perf_counter() + 60.0
            while (sup.stats().heals_ok < 1
                   and time.perf_counter() < t_end):
                sup.step()
                time.sleep(0.005)
            drv.join(timeout=120.0)
        finally:
            ex.close()
            sup.close()

        tl = sup.timeline()
        t_det = next((t for t, e, r in tl
                      if e == "confirmed_down" and r == dead), None)
        t_conv = None
        t_heal_done = next((t for t, e, r in tl
                            if e == "heal_done" and r == dead), None)
        if "kill" in marks:
            t_conv = next((t for t, e, _ in tl
                           if e == "route_pushed"
                           and t >= marks["kill"]), None)
            if t_det is not None:
                row["detection_ms"] = round(
                    (t_det - marks["kill"]) * 1e3, 1)
            if t_conv is not None:
                row["route_convergence_ms"] = round(
                    (t_conv - marks["kill"]) * 1e3, 1)
        if t_heal_done is not None and "heal" in marks:
            row["reintegration_ms"] = round(
                (t_heal_done - marks["heal"]) * 1e3, 1)

        lat = {"healthy": [], "degraded": [], "healed": []}
        stamps = out.get("stamps")
        for i, r in enumerate(out.get("res", ())):
            if isinstance(r, BaseException):
                continue
            r.result(timeout=120)
            # result() can return before the done-callback stamped —
            # same tiny race _drive_open_loop spins out
            while True:
                with dlock:
                    t_done = done.get(i)
                if t_done is not None:
                    break
                time.sleep(0.0002)
            t_sub = float(stamps[i])
            if "kill" not in marks or t_sub < marks["kill"]:
                phase = "healthy"
            elif t_heal_done is None or t_sub < t_heal_done:
                phase = "degraded"
            else:
                phase = "healed"
            lat[phase].append((t_done - t_sub) * 1e3)
        for phase, ms in lat.items():
            if len(ms) >= 5:
                row[f"p99_ms_{phase}"] = round(_p99(ms), 3)
        if len(lat["healthy"]) >= 5 and len(lat["healed"]) >= 5:
            h = _p99(lat["healthy"])
            if h > 0:
                row["healed_p99_x"] = round(_p99(lat["healed"]) / h, 3)
        st = sup.stats()
        row["route_pushes"] = st.route_pushes
        row["heals_ok"] = st.heals_ok
        row["transitions"] = monitor.transition_count
        row["all_serving"] = bool(all(
            s == "serving" for s in st.states.values()))
        row["gen_lag_ms"] = round(out.get("lag", 0.0) * 1e3, 3)
        return row
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def graph_ann_row(x, qall, ivf_index, *, k: int = 10,
                  n_probes: int = 16, degree: int = 16,
                  beams=(16, 32, 64), n_recall_q: int = 64,
                  chain=(4, 32), escalate: int = 2) -> dict:
    """The graph-ANN latency row (ISSUE 19, docs/graph_ann.md): the
    low-latency acceptance priced IN-ROW — the one-dispatch beam search
    at nq=1 vs the SAME corpus served by IVF-Flat at its
    latency-profile qcap-1 point, recall measured against an exact
    numpy oracle on ``n_recall_q`` queries. Stamps the graph arm's
    ``p50_ms``/``recall_at_10``, the baseline's
    ``ivf_p50_ms``/``ivf_recall_at_10``, and the ``beam``/``degree``/
    ``iters`` actually served: the smallest beam in ``beams`` whose
    recall lands within 0.01 of the baseline's (the acceptance bar —
    equal-or-better recall first, then the latency comparison means
    something)."""
    from bench.common import chained_dispatch_stats, recall_at_k
    from raft_tpu.spatial.ann import GraphParams, graph_build
    from raft_tpu.spatial.ann.graph import graph_search
    from raft_tpu.spatial.ann.ivf_flat import ivf_flat_search_grouped

    xn = np.asarray(x, np.float32)
    qn = np.asarray(qall, np.float32)
    n, k_eff = xn.shape[0], min(k, xn.shape[0])
    qr = qn[: min(n_recall_q, qn.shape[0])]
    # exact oracle in numpy: no jit compile for the odd recall shape
    d2 = ((qr * qr).sum(1)[:, None] + (xn * xn).sum(1)[None, :]
          - 2.0 * (qr @ xn.T))
    part = np.argpartition(d2, k_eff - 1, axis=1)[:, :k_eff]
    true = np.take_along_axis(
        part,
        np.argsort(np.take_along_axis(d2, part, axis=1), axis=1),
        axis=1,
    )
    row = {"engine": "graph", "scenario": "graph_ann", "nq": 1,
           "degree": min(degree, n - 1)}

    def p50_of(run, q1):
        jax.block_until_ready(run(q1))
        st = chained_dispatch_stats(
            lambda s, q1=q1: q1 * (1.0 + 1e-6 * s), run,
            n1=chain[0], n2=chain[1], escalate=escalate,
        )
        return st

    # baseline arm: IVF-Flat at ITS latency point (qcap-1, the serving
    # profile the graph index exists to beat)
    qcap1 = ivf_index.warmup(1, k=k_eff, n_probes=n_probes)
    row["ivf_qcap"] = qcap1

    def run_ivf(qq):
        return ivf_flat_search_grouped(
            ivf_index, qq, k_eff, n_probes=n_probes, qcap=qcap1,
        )

    qcap_r = ivf_index.warmup(qr.shape[0], k=k_eff, n_probes=n_probes)
    _, iv = ivf_flat_search_grouped(
        ivf_index, jnp.asarray(qr), k_eff, n_probes=n_probes,
        qcap=qcap_r,
    )
    ivf_rec = recall_at_k(iv, true)
    row["ivf_recall_at_10"] = round(ivf_rec, 4)
    st = p50_of(run_ivf, jnp.asarray(qn[:1]))
    if st is not None:
        row["ivf_p50_ms"] = round(st["ms"], 3)
        row["ivf_spread"] = st["spread"]

    # graph arm: smallest beam meeting the recall bar, then its p50
    gidx = graph_build(xn, GraphParams(degree=row["degree"], seed=0),
                       metric="sqeuclidean")
    beam, rec = None, 0.0
    for b in sorted({max(bm, k_eff) for bm in beams}):
        _, gi = graph_search(gidx, jnp.asarray(qr), k_eff, beam=b)
        beam, rec = b, recall_at_k(np.asarray(gi), true)
        if rec >= ivf_rec - 0.01:
            break
    row["beam"] = beam
    row["recall_at_10"] = round(rec, 4)
    it = gidx.warmup(1, k=k_eff, beam=beam)
    row["iters"] = it

    def run_graph(qq):
        return graph_search(gidx, qq, k_eff, beam=beam, iters=it)

    st = p50_of(run_graph, jnp.asarray(qn[:1]))
    if st is None:
        row["error"] = "jitter-dominated"
    else:
        row["p50_ms"] = round(st["ms"], 3)
        row["spread"] = st["spread"]
        row["repeats"] = st["repeats"]
    return row


def serving_latency_rows(
    n: int = 500_000, d: int = 96, k: int = 10, n_probes: int = 16,
    n_lists: int = 2048, nqs=NQS, engines=("fused_knn", "ivf_flat",
                                           "ivf_pq"),
    chain=(4, 32), escalate: int = 2,
    hedged: bool = True, overload: bool = True, mixed: bool = True,
    open_loop: bool = True, zipf: bool = True, cold_tier: bool = True,
    self_heal: bool = True, graph: bool = True, durable: bool = True,
):
    """One latency row per (engine, nq): ``{"engine", "nq", "p50_ms",
    "spread", "repeats", "qcap"?}`` (``"error"`` on a failed point so one
    engine cannot sink the sweep), plus — when ``ivf_flat`` is swept —
    the ``hedged_straggler`` and ``overload_2x`` resilience rows
    (:func:`hedged_straggler_row`, :func:`overload_row`). Parameterized
    so tests can run a tiny config on CPU; the bench defaults are the
    shared 500k x 96 shape."""
    from bench.common import chained_dispatch_stats
    from raft_tpu.distance.distance_type import DistanceType
    from raft_tpu.random import make_blobs
    from raft_tpu.random.rng import RngState
    from raft_tpu.spatial.ann import (
        IVFFlatParams, IVFPQParams, ivf_flat_build, ivf_pq_build,
    )
    from raft_tpu.spatial.ann.ivf_flat import ivf_flat_search_grouped
    from raft_tpu.spatial.ann.ivf_pq import ivf_pq_search_grouped
    from raft_tpu.spatial.fused_knn import fused_l2_knn

    # same synthesis as bench.common.ann_bench_dataset (clustered blobs,
    # perturbed dataset-point queries) minus the exact oracle — latency
    # rows carry no recall claim, and the oracle would double the setup
    key = jax.random.PRNGKey(2)
    x, _ = make_blobs(n, d, n_clusters=min(1000, max(2, n // 100)),
                      cluster_std=1.0, state=RngState(7))
    base = jax.random.choice(key, x, shape=(max(nqs),), axis=0)
    qall = base + 0.3 * jax.random.normal(
        jax.random.fold_in(key, 1), (max(nqs), d), jnp.float32
    )
    jax.block_until_ready(qall)
    cap = max(64, 2 * -(-n // n_lists) // 8 * 8) if n >= 100_000 else 0

    built = {}

    def get_index(engine):
        if engine not in built:
            if engine == "ivf_flat":
                built[engine] = ivf_flat_build(x, IVFFlatParams(
                    n_lists=n_lists, kmeans_n_iters=10,
                    kmeans_init="random",
                    max_list_cap=cap or None,
                ), metric="sqeuclidean")
            elif engine == "ivf_pq":
                # the 500k QPS row's pq_dim=24; smaller d falls back to
                # the largest divisor <= 24 (tiny test configs)
                pq_dim = max(
                    m for m in range(1, d + 1) if d % m == 0 and m <= 24
                )
                built[engine] = ivf_pq_build(x, IVFPQParams(
                    n_lists=n_lists, pq_dim=pq_dim, kmeans_n_iters=10,
                    kmeans_init="random", max_list_cap=cap or None,
                ))
            elif engine == "fused_knn":
                norms = jnp.einsum(
                    "nd,nd->n", x, x, preferred_element_type=jnp.float32
                )
                built[engine] = norms
        return built[engine]

    rows = []
    for engine in engines:
        for nq in nqs:
            row = {"engine": engine, "nq": nq}
            try:
                qb = qall[:nq]
                if engine == "fused_knn":
                    norms = get_index(engine)

                    def run(qq):
                        return fused_l2_knn(
                            qq, x, k, metric=DistanceType.L2Expanded,
                            index_norms=norms,
                        )
                elif engine == "ivf_flat":
                    idx = get_index(engine)
                    qcap = idx.warmup(nq, k=k, n_probes=n_probes)
                    row["qcap"] = qcap

                    def run(qq, idx=idx, qcap=qcap):
                        return ivf_flat_search_grouped(
                            idx, qq, k, n_probes=n_probes, qcap=qcap,
                        )
                else:
                    idx = get_index(engine)
                    qcap = idx.warmup(
                        nq, k=k, n_probes=n_probes, refine_ratio=4.0,
                    )
                    row["qcap"] = qcap

                    def run(qq, idx=idx, qcap=qcap):
                        return ivf_pq_search_grouped(
                            idx, qq, k, n_probes=n_probes, qcap=qcap,
                            refine_ratio=4.0,
                        )

                warm = run(qb)[0]                    # compile + warm
                float(jnp.sum(jnp.where(jnp.isfinite(warm), warm, 0.0)))
                st = chained_dispatch_stats(
                    lambda s, qb=qb: qb * (1.0 + 1e-6 * s), run,
                    n1=chain[0], n2=chain[1], escalate=escalate,
                )
                if st is None:
                    row["error"] = "jitter-dominated"
                else:
                    row["p50_ms"] = round(st["ms"], 3)
                    row["spread"] = st["spread"]
                    row["repeats"] = st["repeats"]
            except Exception as e:                   # noqa: BLE001 — one
                # failed point must not sink the other 8 rows
                row["error"] = f"{type(e).__name__}: {e}"[:160]
            rows.append(row)

    # resilience rows on the warmed IVF-Flat serving program: the hedged
    # straggler tail and the 2x-overload shed behavior (module docstring)
    if (hedged or overload) and "ivf_flat" in engines:
        try:
            idx = get_index("ivf_flat")
            nq_r = min(128, max(nqs))
            qb = qall[:nq_r]
            qcap_r = idx.warmup(nq_r, k=k, n_probes=n_probes)

            def run_r(qq, idx=idx, qcap=qcap_r):
                return ivf_flat_search_grouped(
                    idx, qq, k, n_probes=n_probes, qcap=qcap,
                )

            jax.block_until_ready(run_r(qb))
            if hedged:
                rows.append(hedged_straggler_row(run_r, qb))
            if overload:
                rows.append(overload_row(run_r, qb))
        except Exception as e:                       # noqa: BLE001
            rows.append({
                "engine": "ivf_flat", "scenario": "resilience",
                "error": f"{type(e).__name__}: {e}"[:160],
            })

    # the open-loop executor row (ISSUE 8): saturation vs the raw
    # program + the offered-load sweep with p50/p99 per point
    if open_loop and "ivf_flat" in engines:
        try:
            idx = get_index("ivf_flat")
            ol_buckets = tuple(sorted({nq for nq in nqs if nq > 1})
                               or {max(nqs)})

            def make_run(bucket, idx=idx):
                qcap = idx.warmup(bucket, k=k, n_probes=n_probes)

                def run(qq, qcap=qcap):
                    return ivf_flat_search_grouped(
                        idx, qq, k, n_probes=n_probes, qcap=qcap,
                    )
                return run

            rows.append(open_loop_row(
                make_run, np.asarray(qall),
                buckets=ol_buckets,
                request_size=max(1, min(16, max(ol_buckets) // 8)),
                n_requests=min(256, 32 * len(ol_buckets) * 4),
                chain=chain, escalate=escalate,
            ))
        except Exception as e:                       # noqa: BLE001
            rows.append({
                "engine": "ivf_flat", "scenario": "open_loop",
                "error": f"{type(e).__name__}: {e}"[:160],
            })

    # the hot-traffic shaping row (ISSUE 15): Zipf repeated-query mix,
    # cache+coalescing saturation vs the uncached path at fixed hardware
    if zipf and "ivf_flat" in engines:
        try:
            idx = get_index("ivf_flat")
            z_buckets = tuple(sorted({nq for nq in nqs if nq > 1})
                              or {max(nqs)})

            def make_run_z(bucket, idx=idx):
                qcap = idx.warmup(bucket, k=k, n_probes=n_probes)

                def run(qq, qcap=qcap):
                    return ivf_flat_search_grouped(
                        idx, qq, k, n_probes=n_probes, qcap=qcap,
                    )
                return run

            rows.append(zipf_hot_traffic_row(
                make_run_z, np.asarray(qall), k=k,
                buckets=z_buckets,
                request_size=max(1, min(16, max(z_buckets) // 8)),
                n_templates=min(64, max(8, 4 * len(z_buckets) * 8)),
                n_requests=min(256, 32 * len(z_buckets) * 4),
                chain=chain, escalate=escalate,
            ))
        except Exception as e:                       # noqa: BLE001
            rows.append({
                "engine": "ivf_flat", "scenario": "zipf_hot_traffic",
                "error": f"{type(e).__name__}: {e}"[:160],
            })

    # the popularity-tiered cold-tier row (ISSUE 17): same index at
    # 1/4 the "HBM" budget, hit-rate-vs-QPS sweep + recall-vs-hot
    if cold_tier and "ivf_flat" in engines:
        try:
            t_buckets = tuple(sorted({nq for nq in nqs if nq > 1})
                              or {max(nqs)})
            rows.append(cold_tier_row(
                get_index("ivf_flat"), np.asarray(qall), k=k,
                n_probes=n_probes, buckets=t_buckets,
                request_size=max(1, min(16, max(t_buckets) // 8)),
                n_templates=min(64, max(8, 4 * len(t_buckets) * 8)),
                n_requests=min(256, 32 * len(t_buckets) * 4),
                chain=chain, escalate=escalate,
            ))
        except Exception as e:                       # noqa: BLE001
            rows.append({
                "engine": "ivf_flat", "scenario": "cold_tier",
                "error": f"{type(e).__name__}: {e}"[:160],
            })

    # the self-healing supervisor row (ISSUE 18): scripted
    # kill→reroute→heal→reintegrate under open-loop Zipf —
    # detection/convergence/reintegration stamps + per-phase p99
    if self_heal and "ivf_flat" in engines:
        try:
            rows.append(self_heal_row(
                np.asarray(x), np.asarray(qall), k=k,
                n_probes=n_probes,
                n_lists=max(4, min(32, n_lists)),
                request_size=max(1, min(8, max(nqs))),
            ))
        except Exception as e:                       # noqa: BLE001
            rows.append({
                "engine": "ivf_flat", "scenario": "self_heal",
                "error": f"{type(e).__name__}: {e}"[:160],
            })

    # the graph-ANN low-latency row (ISSUE 19): one-dispatch beam
    # search vs the IVF-Flat qcap-1 baseline at matched recall
    if graph and "ivf_flat" in engines:
        try:
            rows.append(graph_ann_row(
                np.asarray(x), np.asarray(qall),
                get_index("ivf_flat"), k=k, n_probes=n_probes,
                chain=chain, escalate=escalate,
            ))
        except Exception as e:                       # noqa: BLE001
            rows.append({
                "engine": "graph", "scenario": "graph_ann",
                "error": f"{type(e).__name__}: {e}"[:160],
            })

    # the mutation tier's mixed read/write row (ISSUE 7): sustained
    # ingest QPS alongside search QPS, upsert→visible / delete→masked
    if mixed and "ivf_flat" in engines:
        try:
            nq_m = min(128, max(nqs))
            rows.append(mixed_ingest_row(
                get_index("ivf_flat"), qall[:nq_m], k=k,
                n_probes=n_probes,
                ingest_batch=min(256, max(8, nq_m * 2)),
                chain=(chain[0], max(chain[0] + 1, chain[1] // 4)),
                escalate=escalate,
            ))
        except Exception as e:                       # noqa: BLE001
            rows.append({
                "engine": "ivf_flat", "scenario": "mixed_ingest",
                "error": f"{type(e).__name__}: {e}"[:160],
            })

    # the durable-WAL ingest row (ISSUE 20, docs/robustness.md
    # "Durability"): acked-ingest QPS vs fsync interval, WAL tax
    # priced against the non-durable apply (durability_ratio >= ~0.8)
    if durable and "ivf_flat" in engines:
        try:
            nq_m = min(128, max(nqs))
            rows.append(durable_ingest_row(
                get_index("ivf_flat"), qall[:nq_m],
                ingest_batch=min(128, max(8, nq_m)),
            ))
        except Exception as e:                       # noqa: BLE001
            rows.append({
                "engine": "ivf_flat", "scenario": "durable_ingest",
                "error": f"{type(e).__name__}: {e}"[:160],
            })
    return {
        "metric": f"serving_p50_{n}x{d}_k{k}_p{n_probes}",
        "unit": "ms",
        "rows": rows,
    }


if __name__ == "__main__":
    print(json.dumps(serving_latency_rows()))
