"""k-means benchmark — the BASELINE.md config (make_blobs 1M x 128,
k=1024; reference cpp/include/raft/cluster/detail/kmeans.cuh:780 loop)."""

import json
import time

import numpy as np
import jax

from raft_tpu.cluster import KMeansParams, kmeans_fit


def main():
    rng = np.random.default_rng(0)
    n, d, k = 1_000_000, 128, 1024
    x = jax.device_put(rng.standard_normal((n, d)).astype(np.float32))

    # methodology: two programs (max_iter=5 vs 20, tol=0 so the bound binds)
    # timed on FRESH input values — the axon runtime memoizes executions
    # with identical inputs, so warmup runs use different data; the
    # iteration cost is the difference quotient, cancelling k-means++ init
    # (present in both runs).
    p5 = KMeansParams(n_clusters=k, max_iter=5, tol=0.0, seed=0)
    p20 = KMeansParams(n_clusters=k, max_iter=20, tol=0.0, seed=0)
    # compile p5 (scalar fetch: block_until_ready does not block through
    # the axon tunnel)
    float(kmeans_fit(x, p5).inertia)
    float(kmeans_fit(x, p20).inertia)  # compile p20

    import jax.numpy as jnp

    x2 = jax.block_until_ready(x * jnp.float32(1.0001))  # fresh values
    t0 = time.perf_counter()
    out5 = kmeans_fit(x2, p5)
    float(out5.inertia)
    t5 = time.perf_counter() - t0
    t0 = time.perf_counter()
    out20 = kmeans_fit(x2, p20)
    float(out20.inertia)
    t20 = time.perf_counter() - t0
    per_iter = (t20 - t5) / (int(out20.n_iter) - int(out5.n_iter))
    print(json.dumps({
        "name": f"kmeans/{n}x{d}k{k}",
        "s_per_iter": round(per_iter, 4),
        "iters_per_s": round(1.0 / per_iter, 3),
        "init_plus_fixed_s": round(t5 - 5 * per_iter, 3),
    }))


if __name__ == "__main__":
    main()
