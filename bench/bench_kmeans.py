"""k-means benchmark — the BASELINE.md config (make_blobs 1M x 128,
k=1024; reference cpp/include/raft/cluster/detail/kmeans.cuh:780 loop)."""

import json
import time

import numpy as np
import jax

from raft_tpu.cluster import KMeansParams, kmeans_fit


def main():
    rng = np.random.default_rng(0)
    n, d, k = 1_000_000, 128, 1024
    x = jax.device_put(rng.standard_normal((n, d)).astype(np.float32))

    iters = 5
    out = kmeans_fit(x, KMeansParams(n_clusters=k, max_iter=2, seed=0))
    jax.block_until_ready(out.centroids)  # compile + init
    t0 = time.perf_counter()
    out = kmeans_fit(
        x, KMeansParams(n_clusters=k, max_iter=iters, tol=0.0, seed=0)
    )
    jax.block_until_ready(out.centroids)
    dt = time.perf_counter() - t0
    per_iter = dt / max(int(out.n_iter), 1)
    print(json.dumps({
        "name": f"kmeans/{n}x{d}k{k}",
        "s_per_iter": round(per_iter, 3),
        "iters_per_s": round(1.0 / per_iter, 3),
        "n_iter": int(out.n_iter),
    }))


if __name__ == "__main__":
    main()
