"""Benchmark harness — analog of cpp/bench/common/benchmark.hpp
(fixture + cuda_event_timer). TPU methodology: the repeat loop lives inside
ONE jit (lax.fori_loop) because per-dispatch latency through the axon
tunnel (~10 ms) would otherwise dominate; a full-output reduce pins the
dependence so XLA cannot dead-code or narrow the measured computation.
"""

from __future__ import annotations

import json
import time
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def bench_fn(make_fn: Callable, *args, iters: int = 20, name: str = "",
             work: float = 0.0, unit: str = "GFLOPS"):
    """Time ``make_fn(*args)`` inside a fori_loop; returns ms/iter and
    prints one JSON line {name, ms, value, unit}."""

    @jax.jit
    def loop(*a):
        def body(i, acc):
            # perturb float inputs by i*0 so XLA cannot hoist the whole
            # computation out of the loop as loop-invariant
            def bump(x):
                if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
                    return x + jnp.asarray(i, x.dtype) * jnp.asarray(0, x.dtype)
                return x

            out = make_fn(*jax.tree.map(bump, a))
            leaves = [
                jnp.sum(l.astype(jnp.float32))
                for l in jax.tree.leaves(out)
                if hasattr(l, "astype")
            ]
            return acc + sum(leaves)
        return lax.fori_loop(0, iters, body, jnp.float32(0.0))

    loop(*args).block_until_ready()  # compile
    # best-of-3: the first timed run per process pays a large one-time
    # runtime warmup through the axon tunnel
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        float(loop(*args))
        best = min(best, time.perf_counter() - t0)
    ms = best / iters * 1e3
    rec = {"name": name, "ms_per_iter": round(ms, 4)}
    if work:
        rec["value"] = round(work / (ms / 1e3) / 1e9, 2)
        rec["unit"] = unit
    print(json.dumps(rec))
    return ms
