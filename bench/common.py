"""Benchmark harness — analog of cpp/bench/common/benchmark.hpp
(fixture + cuda_event_timer). TPU methodology:

1. the repeat loop lives inside ONE jit (lax.fori_loop) — per-dispatch
   latency through the axon tunnel would otherwise dominate;
2. the iteration count is a RUNTIME argument and the reported time is the
   two-point difference (t(n2) - t(n1)) / (n2 - n1), which cancels the
   ~100 ms fixed cost of a synchronous dispatch+fetch through the tunnel
   (measured: a trivial 20-iter and 400-iter loop both take ~103 ms total);
3. float inputs are perturbed by i*0 so XLA cannot hoist the body out of
   the loop, and every output element feeds a reduce so XLA cannot narrow
   the computation.
"""

from __future__ import annotations

import json
import time
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def _stats_of(f, reps: int = 5):
    """(median, relative spread) of ``reps`` wall-clock samples of f()."""
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        f()
        ts.append(time.perf_counter() - t0)
    ts.sort()
    med = ts[len(ts) // 2]
    return med, (ts[-1] - ts[0]) / max(med, 1e-12)


def _median_of(f, reps: int = 5) -> float:
    return _stats_of(f, reps)[0]


def bench_fn(make_fn: Callable, *args, iters: int = 40, name: str = "",
             work: float = 0.0, unit: str = "GFLOPS"):
    """Time ``make_fn(*args)``; returns ms/iter and prints one JSON line
    {name, ms_per_iter, value?, unit?}."""

    @jax.jit
    def loop(n, *a):
        def body(i, acc):
            def bump(x):
                if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
                    return x + jnp.asarray(i, x.dtype) * jnp.asarray(0, x.dtype)
                return x

            out = make_fn(*jax.tree.map(bump, a))
            leaves = [
                jnp.sum(l.astype(jnp.float32))
                for l in jax.tree.leaves(out)
                if hasattr(l, "astype")
            ]
            return acc + sum(leaves)
        return lax.fori_loop(0, n, body, jnp.float32(0.0))

    n0 = min(max(iters // 8, 1), 5_000)  # < cap so growth keeps n2 > n1
    float(loop(n0, *args))  # compile (n is a runtime arg: one program)
    t0 = _median_of(lambda: float(loop(n0, *args)), reps=3)
    # grow the loop length by MEASURED time until the compute delta
    # dominates the ~10-30 ms dispatch jitter. Growth is bounded by the
    # observed wall clock, so a mis-estimated per-iteration cost can never
    # schedule an hours-long fused loop (which the TPU watchdog would kill
    # as a "worker crash") — the failure mode of estimate-based sizing.
    n1, t1 = n0, t0
    n2 = min(4 * n0, 20_000)
    t2 = _median_of(lambda: float(loop(n2, *args)), reps=1)
    while t2 < 0.4 and n2 < 20_000:
        n1, t1 = n2, t2
        n2 = min(n2 * 4, 20_000)
        t2 = _median_of(lambda: float(loop(n2, *args)), reps=1)
    # refine both points with medians (resists asymmetric outliers)
    t1, sp1 = _stats_of(lambda: float(loop(n1, *args)))
    t2, sp2 = _stats_of(lambda: float(loop(n2, *args)))
    ms = max(t2 - t1, 1e-9) / (n2 - n1) * 1e3
    rec = {
        "name": name, "ms_per_iter": round(ms, 4),
        # spread of the dominant (long-loop) point over its 5 repeats —
        # the row-level drift band (VERDICT r4 weak-1)
        "spread": round(sp2, 3), "repeats": 5,
    }
    if work:
        rec["value"] = round(work / (ms / 1e3) / 1e9, 2)
        rec["unit"] = unit
    print(json.dumps(rec))
    return ms


def chained_dispatch_stats(make_input, run, n1: int = 2, n2: int = 8,
                           reps: int = 3, escalate: int = 0,
                           _salt0: int = 1, _escalations: int = 0):
    """Two-point timing for programs too large for the loop-in-jit harness
    (Pallas grid-step limits, multi-hundred-MB working sets): dispatch a
    chain of ``run(input_i + prev * 0)`` calls — device-serialized by the
    data dependence so only one call's transients are live — and take the
    median of ``reps`` difference quotients (T(n2) - T(n1)) / (n2 - n1).

    ``make_input(salt)`` must return a fresh input per salt (identical
    inputs would hit the axon result memoization). Salts increase
    strictly monotonically across every chain, repeat, AND escalation
    retry of one invocation, and start at 1 rather than 0 — overlapping
    bases would replay inputs an earlier chain already ran (and salt 0
    typically reproduces the caller's unsalted warm-up input), and the
    memoized prefix deflates that chain's measured time (a ~25% quotient
    bias at the escalated merge chain lengths). The chain dependence is sanitized to finite values
    so an inf-padded result cannot poison later inputs with NaN. Inputs
    are materialized before the clock starts.

    Returns ``{"ms", "ms_min", "spread", "repeats", "escalations"}`` —
    median, best,
    (max-min)/median relative spread over the positive quotients, and the
    repeat count (VERDICT r4 weak-1: single-shot timings made ±20%
    runtime-drift bands invisible; every row now carries its spread, the
    google-benchmark repeated-iteration discipline,
    cpp/bench/common/benchmark.hpp:64). None when all quotients are
    non-positive (jitter-dominated: too fast to resolve this way).

    Noisy rows earn more repeats automatically: when the spread over the
    initial ``reps`` quotients exceeds ``spread_target`` (0.1), two more
    quotients are collected, then two more — 3 -> 5 -> 7 — before
    reporting. The escalation runs its full budget even when one noisy
    batch drags the running median non-positive (the r5 ``ivf_pq_10m``
    row shipped spread 0.268 at repeats 3 because a single bad batch
    aborted the ladder); the best positive summary seen is what a
    fully-jittered ladder falls back to. A row whose spread still
    exceeds the target after ``max_reps`` repeats reports it honestly;
    downstream, bench.py stamps ``vs_prev_significant: false`` on any
    round-over-round ratio smaller than the row's own spread, so
    regression tracking never reads noise as signal.

    ``escalate``: retry up to this many times with 4x-longer chains when
    the result is jitter-dominated OR its spread still exceeds the
    target after the full repeat ladder — the one shared knob for
    programs whose signal must be stretched above the 1-core host's
    dispatch noise (no per-call-site hand-rolled retries). Every QPS row
    in bench.py passes ``escalate=1``.

    The returned summary stamps ``escalations`` — how many chain-length
    growths produced the REPORTED numbers — and the escalation decision
    is made on the spread computed AFTER each growth (the grown chain
    runs its own full repeat ladder and re-escalates while budget
    remains), so a row that converged only at the longer chain reports
    that chain's spread with its escalation count, and the driver can
    see a still-noisy row genuinely exhausted its budget (the r05
    ``ivf_pq_10m`` spread-0.268 row carried no such evidence).
    """
    def reduce_finite(out):
        leaf = jax.tree.leaves(out)[0]
        return jnp.sum(jnp.where(jnp.isfinite(leaf), leaf, 0.0))

    def timed(n, salt0):
        xs = [make_input(salt0 + i) for i in range(n)]
        float(sum(jnp.sum(x) for x in xs))  # materialize before the clock
        t0 = time.perf_counter()
        prev = jnp.float32(0.0)
        for x in xs:
            prev = reduce_finite(run(x + prev * 0))
        float(prev)
        return time.perf_counter() - t0

    off = _salt0
    quotients = []

    def add_quotient():
        nonlocal off
        t1 = timed(n1, off)
        off += n1
        t2 = timed(n2, off)
        off += n2
        quotients.append((t2 - t1) / (n2 - n1) * 1e3)

    def summarize():
        # the jitter guard takes the median over ALL quotients (negative
        # ones included): filtering negatives first would let one outlier
        # positive masquerade as a confident measurement on a
        # jitter-dominated workload
        ms = sorted(quotients)[len(quotients) // 2]
        pos = sorted(q for q in quotients if q > 0)
        spread = (pos[-1] - pos[0]) / ms if (pos and ms > 0) else 0.0
        return ms, pos, spread

    for rep in range(reps):
        add_quotient()
    ms, pos, spread = summarize()
    if ms <= 0:
        if escalate > 0:
            return chained_dispatch_stats(
                make_input, run, n1=4 * n1, n2=4 * n2, reps=reps,
                escalate=escalate - 1, _salt0=off,
                _escalations=_escalations + 1,
            )
        return None
    # spread-driven repeat escalation: 3 -> 5 -> 7 while the spread
    # exceeds the 0.1 band (see docstring). The ladder runs its FULL
    # budget even when one noisy batch drags the running median
    # non-positive — the best positive summary seen is the fallback —
    # so a single bad batch can no longer freeze a row at 3 repeats
    # with an untrustworthy spread (the r5 ivf_pq_10m failure mode)
    max_reps, spread_target = 7, 0.1
    n_used = len(quotients)
    best = (ms, pos, spread, n_used)
    while spread > spread_target and len(quotients) + 2 <= max_reps:
        add_quotient()
        add_quotient()
        ms, pos, spread = summarize()
        n_used = len(quotients)
        if ms > 0 and (best[0] <= 0 or spread < best[2]):
            best = (ms, pos, spread, n_used)
    if ms <= 0:
        ms, pos, spread, n_used = best
    if spread > spread_target and escalate > 0:
        # still noisy after the full repeat ladder: stretch the signal
        # with 4x-longer chains. The grown chain runs its OWN repeat
        # ladder and re-escalates on ITS post-growth spread while budget
        # remains; its summary wins whenever it is tighter.
        longer = chained_dispatch_stats(
            make_input, run, n1=4 * n1, n2=4 * n2, reps=reps,
            escalate=escalate - 1, _salt0=off,
            _escalations=_escalations + 1,
        )
        if longer is not None and longer["spread"] < spread:
            return longer
    return {
        "ms": ms,
        "ms_min": pos[0],
        "spread": round(spread, 3),
        "repeats": n_used,
        "escalations": _escalations,
    }


def chained_dispatch_ms(make_input, run, n1: int = 2, n2: int = 8,
                        reps: int = 3):
    """Median-ms convenience wrapper over :func:`chained_dispatch_stats`
    (None when jitter-dominated)."""
    st = chained_dispatch_stats(make_input, run, n1=n1, n2=n2, reps=reps)
    return None if st is None else st["ms"]


def ann_bench_dataset(n=500_000, d=96, nq=4096, k=10):
    """The shared clustered ANN bench config (500k x 96 default): blobs
    data, perturbed dataset-point queries, exact fused-kNN ground truth.
    Every ANN row comparing engines "at the identical config" (plain
    grouped IVF-PQ, the mnmg shard program) must draw from HERE so a
    shape/synthesis edit cannot silently break comparability.

    Data is clustered (make_blobs, 1000 centers) — the regime real
    embedding corpora live in; on isotropic Gaussian data recall@10
    measures ~0.19 for ANY inverted-file method at these settings (a
    property of the adversarial dataset, not the index).
    """
    import numpy as np

    from raft_tpu.distance.distance_type import DistanceType
    from raft_tpu.random import make_blobs
    from raft_tpu.random.rng import RngState
    from raft_tpu.spatial.fused_knn import fused_l2_knn

    key = jax.random.PRNGKey(2)
    x, _ = make_blobs(n, d, n_clusters=1000, cluster_std=1.0,
                      state=RngState(7))
    base = jax.random.choice(key, x, shape=(nq,), axis=0)
    q = base + 0.3 * jax.random.normal(
        jax.random.fold_in(key, 1), (nq, d), jnp.float32
    )
    _, true_ids = fused_l2_knn(q, x, k, metric=DistanceType.L2Expanded)
    return x, q, np.asarray(true_ids)


def recall_at_k(got_ids, true_np) -> float:
    """Set-intersection recall of (nq, k) result ids vs ground truth."""
    import numpy as np

    got = np.asarray(got_ids)
    hits = sum(
        len(set(g.tolist()) & set(t.tolist()))
        for g, t in zip(got, true_np)
    )
    return hits / true_np.size
