"""Pairwise-distance benchmarks — mirrors cpp/bench/distance/
distance_{exp_l2,unexp_l2,cosine,l1}.cu (shapes from the
DIST_BENCH_REGISTER grid) + fused_l2_nn.cu."""

import numpy as np
import jax

from bench.common import bench_fn
from raft_tpu.distance.pairwise import _expanded_impl, _unexpanded_impl
from raft_tpu.distance.fused_l2_nn import fused_l2_nn
from raft_tpu.distance.distance_type import DistanceType


def main():
    rng = np.random.default_rng(0)
    shapes = [(1024, 1024, 256), (4096, 4096, 512), (8192, 8192, 512)]
    for m, n, d in shapes:
        x = jax.device_put(rng.standard_normal((m, d)).astype(np.float32))
        y = jax.device_put(rng.standard_normal((n, d)).astype(np.float32))
        flops = 2.0 * m * n * d
        bench_fn(
            lambda a, b: _expanded_impl(DistanceType.L2Expanded, a, b, "default"),
            x, y, name=f"distance/l2_exp/{m}x{n}x{d}", work=flops,
        )
        bench_fn(
            lambda a, b: _expanded_impl(DistanceType.CosineExpanded, a, b, "default"),
            x, y, name=f"distance/cosine/{m}x{n}x{d}", work=flops,
        )
        if m <= 4096:
            bench_fn(
                lambda a, b: _unexpanded_impl(DistanceType.L1, a, b, 2.0, None),
                x, y, name=f"distance/l1_xla/{m}x{n}x{d}", work=m * n * d,
                unit="Gop/s",
            )
        bench_fn(
            lambda a, b: fused_l2_nn(a, b)[0],
            x, y, name=f"distance/fused_l2_nn/{m}x{n}x{d}", work=flops,
        )


if __name__ == "__main__":
    main()
