"""ANN benchmarks — IVF-Flat/IVF-PQ build + search (the reference's
IVF suites run through FAISS, ann_quantized_faiss.cuh; BASELINE.md names
IVF build+search as a target config)."""

import json
import time

import numpy as np
import jax

from raft_tpu.spatial.ann import (
    IVFFlatParams, ivf_flat_build, ivf_flat_search,
    IVFPQParams, ivf_pq_build, ivf_pq_search,
)


def main():
    rng = np.random.default_rng(0)
    n, d, nq, k = 500_000, 96, 4096, 10
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = jax.device_put(rng.standard_normal((nq, d)).astype(np.float32))

    for name, build, search, params in [
        ("ivf_flat", ivf_flat_build, ivf_flat_search,
         IVFFlatParams(n_lists=1024, kmeans_n_iters=10)),
        ("ivf_pq", ivf_pq_build, ivf_pq_search,
         IVFPQParams(n_lists=1024, pq_dim=12, kmeans_n_iters=10)),
    ]:
        t0 = time.perf_counter()
        index = build(x, params)
        jax.block_until_ready(jax.tree.leaves(index)[0])
        build_s = time.perf_counter() - t0

        d_, i_ = search(index, q, k, n_probes=32)  # compile
        jax.block_until_ready(d_)
        t0 = time.perf_counter()
        reps = 5
        for _ in range(reps):
            d_, i_ = search(index, q, k, n_probes=32)
        jax.block_until_ready(d_)
        search_s = (time.perf_counter() - t0) / reps
        print(json.dumps({
            "name": f"ann/{name}/{n}x{d}",
            "build_s": round(build_s, 2),
            "search_ms": round(search_s * 1e3, 2),
            "qps": round(nq / search_s),
        }))


if __name__ == "__main__":
    main()
