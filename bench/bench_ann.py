"""ANN benchmarks — IVF-Flat/IVF-PQ build + search (the reference's
IVF suites run through FAISS, ann_quantized_faiss.cuh; BASELINE.md names
IVF build+search as a target config).

Regime note (measured, v5e): at batch>=512 queries the MXU scores the WHOLE
dataset faster than the inverted lists can be gathered (random row gathers
cost more than dense flops on TPU), so exact brute force wins throughput
mode outright; IVF pays in small-batch latency mode where it prunes ~99% of
HBM reads. Both are benchmarked.
"""

import json
import time

import numpy as np
import jax
import jax.numpy as jnp

from raft_tpu.spatial.ann import (
    IVFFlatParams, ivf_flat_build, ivf_flat_search,
    IVFPQParams, ivf_pq_build, ivf_pq_search,
)
from raft_tpu.distance.distance_type import DistanceType
from raft_tpu.spatial.knn import _knn_single_part


def _force(d_):
    return float(jnp.sum(jnp.where(jnp.isfinite(d_), d_, 0)))


def main():
    rng = np.random.default_rng(0)
    n, d, k = 500_000, 96, 10
    x = rng.standard_normal((n, d)).astype(np.float32)
    xd = jax.device_put(x)
    q_small = jax.device_put(rng.standard_normal((32, d)).astype(np.float32))
    q_big = jax.device_put(rng.standard_normal((4096, d)).astype(np.float32))

    # throughput mode: exact brute force on the MXU
    d_, _ = _knn_single_part(q_big, xd, k, DistanceType.L2SqrtExpanded, 2.0, 65536, None)
    _force(d_)
    t0 = time.perf_counter()
    d_, _ = _knn_single_part(q_big * 1.0001, xd, k, DistanceType.L2SqrtExpanded, 2.0, 65536, None)
    _force(d_)
    dt = time.perf_counter() - t0
    print(json.dumps({
        "name": f"ann/brute_force_throughput/{n}x{d}",
        "search_ms": round(dt * 1e3, 1),
        "qps": round(4096 / dt),
    }))

    for name, build, search, params in [
        ("ivf_flat", ivf_flat_build, ivf_flat_search,
         IVFFlatParams(n_lists=1024, kmeans_n_iters=10)),
        ("ivf_pq", ivf_pq_build, ivf_pq_search,
         IVFPQParams(n_lists=1024, pq_dim=12, kmeans_n_iters=10)),
    ]:
        t0 = time.perf_counter()
        index = build(x, params)
        float(jnp.sum(index.centroids))
        build_s = time.perf_counter() - t0

        # latency mode: small batch, pruned reads
        d_, _ = search(index, q_small, k, n_probes=8)
        _force(d_)
        t0 = time.perf_counter()
        reps = 5
        for r in range(reps):
            d_, _ = search(index, q_small * (1.0 + 1e-6 * r), k, n_probes=8)
            _force(d_)
        lat_ms = (time.perf_counter() - t0) / reps * 1e3
        print(json.dumps({
            "name": f"ann/{name}_latency_q32/{n}x{d}",
            "build_s": round(build_s, 2),
            "search_ms": round(lat_ms, 2),
            "qps": round(32 / (lat_ms / 1e3)),
        }))


if __name__ == "__main__":
    main()
