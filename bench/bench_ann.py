"""ANN benchmarks — IVF-Flat/IVF-PQ build + search with recall@k
(the reference's IVF suites run through FAISS, ann_quantized_faiss.cuh;
BASELINE.md names IVF build+search as a target config).

Every search QPS line carries recall@10 against an exact oracle so the
numbers are falsifiable (VERDICT r1 weak #4).

Regime note (measured on v5e-1, n=500k d=96 batch=4096, this file):

* round-1 finding: per-query list gathers lose to dense MXU brute force
  at batch >= 512 (random gathers cost more than dense flops).
* round-2: query-grouped (list-major) search amortizes each list's load
  across all its probing queries — 8.4x the per-query IVF path and 2.5x
  the scan brute force in the same regime (145k vs 17k vs 59k QPS).
* the fused Pallas brute force (spatial/fused_knn.py) raised the dense
  bar to ~150k QPS *exact* at this scale, matching grouped IVF; IVF's
  grouped win over dense grows with n (dense compute scales with n,
  grouped IVF with probed volume only).
"""

import json
import time

import numpy as np
import jax
import jax.numpy as jnp

from bench.common import bench_fn, chained_dispatch_ms, chained_dispatch_stats
from raft_tpu.spatial.ann import (
    IVFFlatParams, ivf_flat_build, ivf_flat_search, ivf_flat_search_grouped,
    IVFPQParams, ivf_pq_build, ivf_pq_search, ivf_pq_search_grouped,
)
from raft_tpu.distance.distance_type import DistanceType
from raft_tpu.spatial.fused_knn import fused_l2_knn
from raft_tpu.spatial.knn import _knn_single_part


def recall_at_k(got_ids, true_ids):
    k = true_ids.shape[1]
    hits = sum(
        len(set(g.tolist()) & set(t.tolist()))
        for g, t in zip(np.asarray(got_ids), np.asarray(true_ids))
    )
    return hits / true_ids.size


def main():
    rng = np.random.default_rng(0)
    n, d, k = 500_000, 96, 10
    x = rng.standard_normal((n, d)).astype(np.float32)
    xd = jax.device_put(x)
    q_small = jax.device_put(rng.standard_normal((32, d)).astype(np.float32))
    nq = 4096
    q_big = jax.device_put(rng.standard_normal((nq, d)).astype(np.float32))

    # ground truth for recall (exact fused kNN)
    _, true_big = fused_l2_knn(q_big, xd, k, metric=DistanceType.L2Expanded)
    _, true_small = fused_l2_knn(q_small, xd, k, metric=DistanceType.L2Expanded)
    jax.block_until_ready((true_big, true_small))

    # throughput mode: dense exact baselines
    for name, fn in [
        ("bf_scan", lambda a, b: _knn_single_part(
            a, b, k, DistanceType.L2SqrtExpanded, 2.0, 65536, None)[0]),
        ("bf_fused", lambda a, b: fused_l2_knn(
            a, b, k, metric=DistanceType.L2SqrtExpanded)[0]),
    ]:
        ms = bench_fn(fn, q_big, xd, iters=4,
                      name=f"ann/{name}_throughput/{n}x{d}q{nq}",
                      work=2.0 * n * d * nq)
        print(json.dumps({
            "name": f"ann/{name}_throughput/{n}x{d}",
            "qps": round(nq / (ms / 1e3)), "recall_at_10": 1.0,
        }))

    # IVF-Flat: build, latency mode (per-query), throughput mode (grouped)
    t0 = time.perf_counter()
    index = ivf_flat_build(x, IVFFlatParams(n_lists=1024, kmeans_n_iters=10, kmeans_init="random"))
    float(jnp.sum(index.centroids))  # scalar fetch: the only real sync on axon
    build_s = time.perf_counter() - t0
    print(json.dumps({"name": f"ann/ivf_flat_build/{n}x{d}",
                      "build_s": round(build_s, 2)}))

    ms = bench_fn(lambda a: ivf_flat_search(index, a, k, n_probes=8)[0],
                  q_small, iters=6, name=f"ann/ivf_flat_latency_q32/{n}x{d}")
    r = recall_at_k(ivf_flat_search(index, q_small, k, n_probes=8)[1],
                    true_small)
    print(json.dumps({
        "name": f"ann/ivf_flat_latency_q32/{n}x{d}",
        "search_ms": round(ms, 2), "qps": round(32 / (ms / 1e3)),
        "recall_at_10": round(r, 4),
    }))

    for nprobe in (8, 16):
        ms = bench_fn(
            lambda a: ivf_flat_search_grouped(index, a, k, n_probes=nprobe)[0],
            q_big, iters=4,
            name=f"ann/ivf_flat_grouped_p{nprobe}/{n}x{d}q{nq}")
        r = recall_at_k(
            ivf_flat_search_grouped(index, q_big, k, n_probes=nprobe)[1],
            true_big)
        print(json.dumps({
            "name": f"ann/ivf_flat_grouped_p{nprobe}/{n}x{d}",
            "qps": round(nq / (ms / 1e3)), "recall_at_10": round(r, 4),
        }))

    # IVF-PQ: build + refined search + recall/n_probes sweep (VERDICT r1 #7)
    t0 = time.perf_counter()
    pq = ivf_pq_build(x, IVFPQParams(n_lists=1024, pq_dim=12, kmeans_n_iters=10,
                                     kmeans_init="random"))
    float(jnp.sum(pq.centroids))     # scalar fetch: the only real sync on axon
    build_s = time.perf_counter() - t0
    print(json.dumps({"name": f"ann/ivf_pq_build/{n}x{d}",
                      "build_s": round(build_s, 2)}))

    sweep = []
    for nprobe in (4, 8, 16, 32):
        ms = bench_fn(
            lambda a: ivf_pq_search(index=pq, queries=a, k=k,
                                    n_probes=nprobe, refine_ratio=4.0)[0],
            q_small, iters=6,
            name=f"ann/ivf_pq_refined_p{nprobe}_q32/{n}x{d}")
        r = recall_at_k(
            ivf_pq_search(pq, q_small, k, n_probes=nprobe,
                          refine_ratio=4.0)[1],
            true_small)
        sweep.append({"n_probes": nprobe, "search_ms": round(ms, 2),
                      "qps": round(32 / (ms / 1e3)),
                      "recall_at_10": round(r, 4)})
    print(json.dumps({"name": f"ann/ivf_pq_sweep_q32/{n}x{d}",
                      "refine_ratio": 4.0, "sweep": sweep}))

    # grouped (list-major) PQ throughput mode: one-hot ADC matmul on the
    # MXU instead of per-candidate LUT gathers. Timed by chained
    # dispatches (the grouped program is too large for the loop-in-jit
    # harness — same rationale as the headline bench's big-kNN config)
    for nprobe in (8, 16):
        def gsearch(a, nprobe=nprobe):
            return ivf_pq_search_grouped(
                index=pq, queries=a, k=k, n_probes=nprobe,
                refine_ratio=4.0, qcap=256,
            )

        jax.block_until_ready(gsearch(q_big)[0])  # compile + warm
        ms = chained_dispatch_ms(
            lambda salt: q_big * (1.0 + 1e-8 * salt), gsearch,
        )
        r = recall_at_k(gsearch(q_big)[1], true_big)
        rec = {
            "name": f"ann/ivf_pq_grouped_p{nprobe}/{n}x{d}",
            "recall_at_10": round(r, 4),
        }
        if ms is not None:
            rec["qps"] = round(nq / (ms / 1e3))
        else:
            rec["note"] = "quotient jitter-dominated at this scale"
        print(json.dumps(rec))

    bench_pq_adc_kernel()
    bench_flat_scan_kernel()
    bench_sq_scan_kernel()


def bench_flat_scan_kernel():
    """The flat scan-block microbench (ISSUE 10): the legacy XLA
    grouped-flat block — a materialized ``(LB, qcap, L)`` einsum
    distance tile fed to ``lax.top_k`` — vs the Pallas sub-chunk-min
    kernel, at FIXED shapes (the per-(list-block) scan work, isolated
    from probe/regroup/rerank) so the kernel speedup is tracked
    independently of the end-to-end flat QPS rows in bench.py.
    Spread-escalated via the shared chained-dispatch harness; on a
    non-TPU backend the kernel runs in interpret mode and the
    comparison is semantics-only."""
    import functools

    from raft_tpu.spatial.ann import flat_kernel

    LB, L, d, Q, kk = 8, 2048, 96, 48, 10
    interpret = jax.default_backend() != "tpu"
    rng = np.random.default_rng(11)
    qv = jax.device_put(rng.standard_normal((LB, Q, d)).astype(np.float32))
    slabs = jax.device_put(
        rng.standard_normal((LB, L, d)).astype(np.float32)
    )
    slabs_t = jnp.transpose(slabs, (0, 2, 1))
    bounds = jnp.tile(jnp.asarray([[0, L]], jnp.int32), (LB, 1))

    @jax.jit
    def xla_block(q_in):
        # the legacy per-block scan IS the anti-pattern the
        # wide-distance-materialize lint names: full distance tile
        # through HBM, selection re-reads it
        mn = jnp.einsum("bld,bld->bl", slabs, slabs,
                        preferred_element_type=jnp.float32)
        qn = jnp.einsum("bqd,bqd->bq", q_in, q_in,
                        preferred_element_type=jnp.float32)
        dots = jnp.einsum("bqd,bld->bql", q_in, slabs,
                          preferred_element_type=jnp.float32)
        d2 = qn[:, :, None] + mn[:, None, :] - 2.0 * dots
        vals, _ = jax.lax.top_k(-d2, kk)  # jaxlint: disable=wide-distance-materialize
        return -vals

    l_tile = flat_kernel.plan_l_tile(d, Q)     # the tile the impl plans

    @functools.partial(jax.jit, static_argnames=("interp",))
    def kernel_block(q_in, interp=interpret):
        return flat_kernel.flat_scan_subchunk_min(
            q_in, slabs_t, bounds, interpret=interp, l_tile=l_tile,
        )

    rec = {"name": f"ann/flat_scan_kernel/LB{LB}xL{L}xd{d}q{Q}"}
    for label, fn in (("xla", xla_block), ("pallas", kernel_block)):
        jax.block_until_ready(fn(qv))
        st = chained_dispatch_stats(
            lambda salt: qv * (1.0 + 1e-6 * salt), fn, escalate=1,
        )
        if st is None:
            rec[f"{label}_note"] = "jitter-dominated"
            continue
        rec[f"{label}_ms"] = round(st["ms"], 3)
        rec[f"{label}_spread"] = st["spread"]
        rec[f"{label}_escalations"] = st.get("escalations", 0)
    if "xla_ms" in rec and "pallas_ms" in rec:
        rec["speedup"] = round(rec["xla_ms"] / rec["pallas_ms"], 2)
    print(json.dumps(rec))


def bench_sq_scan_kernel():
    """The int8 SQ scan-block microbench (ISSUE 11): the XLA dequant
    scan — a full-width f32 dequant expansion of every slab block
    through HBM feeding a materialized distance tile — vs the Pallas
    in-kernel dequant+scan (spatial/ann/sq_kernel, on the shared
    scan-kernel core), at FIXED shapes so the kernel speedup is tracked
    independently of the e2e SQ QPS row in bench.py. The lax baseline
    here is the kernel's own op-for-op mirror: same bf16 rounding of
    the dequantized tile, so the comparison isolates the memory-path
    win (int8 crosses HBM at one byte/element and expands only in
    VMEM). Spread-escalated via the shared chained-dispatch harness;
    on a non-TPU backend the kernel runs in interpret mode and the
    comparison is semantics-only."""
    import functools

    from raft_tpu.spatial.ann import sq_kernel

    LB, L, d, Q = 8, 2048, 96, 48
    interpret = jax.default_backend() != "tpu"
    rng = np.random.default_rng(11)
    qv = jax.device_put(rng.standard_normal((LB, Q, d)).astype(np.float32))
    codes_t = jax.device_put(
        rng.integers(-128, 128, (LB, d, L)).astype(np.int8)
    )
    bounds = jnp.tile(jnp.asarray([[0, L]], jnp.int32), (LB, 1))
    vmin = jnp.asarray(rng.standard_normal(d).astype(np.float32))
    vscale = jnp.full((d,), 1.0 / 64.0, jnp.float32)

    @jax.jit
    def lax_block(q_in):
        return sq_kernel.sq_scan_subchunk_min_lax(
            q_in, codes_t, bounds, vmin, vscale
        )

    l_tile = sq_kernel.plan_l_tile(d, Q)       # the tile the impl plans

    @functools.partial(jax.jit, static_argnames=("interp",))
    def kernel_block(q_in, interp=interpret):
        return sq_kernel.sq_scan_subchunk_min(
            q_in, codes_t, bounds, vmin, vscale,
            interpret=interp, l_tile=l_tile,
        )

    rec = {"name": f"ann/sq_scan_kernel/LB{LB}xL{L}xd{d}q{Q}"}
    for label, fn in (("lax", lax_block), ("pallas", kernel_block)):
        jax.block_until_ready(fn(qv))
        st = chained_dispatch_stats(
            lambda salt: qv * (1.0 + 1e-6 * salt), fn, escalate=1,
        )
        if st is None:
            rec[f"{label}_note"] = "jitter-dominated"
            continue
        rec[f"{label}_ms"] = round(st["ms"], 3)
        rec[f"{label}_spread"] = st["spread"]
        rec[f"{label}_escalations"] = st.get("escalations", 0)
    if "lax_ms" in rec and "pallas_ms" in rec:
        rec["speedup"] = round(rec["lax_ms"] / rec["pallas_ms"], 2)
    print(json.dumps(rec))


def bench_pq_adc_kernel():
    """The ADC scan-block microbench: XLA one-hot matmul + per-block
    selection vs the Pallas sub-chunk-min kernel, at FIXED shapes (the
    two engines' per-(list-block) scan work, isolated from probe/LUT
    build/refine) — so the kernel speedup is tracked independently of
    the end-to-end index QPS rows in bench.py. Spread-escalated via the
    shared chained-dispatch harness; on a non-TPU backend the kernel
    runs in interpret mode and the comparison is semantics-only."""
    import functools

    from raft_tpu.spatial.ann import pq_kernel

    LB, L, M, K, Q, kk = 8, 2048, 12, 256, 48, 40
    interpret = jax.default_backend() != "tpu"
    rng = np.random.default_rng(11)
    luts = jax.device_put(
        rng.standard_normal((LB, Q, M * K)).astype(np.float32)
    )
    codes = jax.device_put(
        rng.integers(0, K, (LB, L, M)).astype(np.uint8)
    )
    codes_t = jnp.transpose(codes, (0, 2, 1))
    bounds = jnp.tile(jnp.asarray([[0, L]], jnp.int32), (LB, 1))

    @jax.jit
    def onehot_block(lut_in):
        # the legacy per-block scan: materialized one-hot, bf16
        # contraction, per-(list, slot) approx selection — the work the
        # kernel replaces (raft_tpu/spatial/ann/ivf_pq.py block_fn)
        onehot = (
            codes[..., None] == jnp.arange(K, dtype=jnp.uint8)
        ).astype(jnp.bfloat16)
        # the measured baseline IS the anti-pattern:
        d2 = jax.lax.dot_general(  # jaxlint: disable=adc-gather
            lut_in.astype(jnp.bfloat16),
            onehot.reshape(LB, L, M * K),
            (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        vals, _ = jax.lax.approx_min_k(d2, kk, recall_target=0.95)
        return vals

    l_tile = pq_kernel.plan_l_tile(M * K, Q)   # the tile the impl plans

    @functools.partial(jax.jit, static_argnames=("interp",))
    def kernel_block(lut_in, interp=interpret):
        return pq_kernel.pq_adc_subchunk_min(
            lut_in.astype(jnp.bfloat16), codes_t, bounds,
            interpret=interp, l_tile=l_tile,
        )

    rec = {"name": f"ann/pq_adc_kernel/LB{LB}xL{L}xM{M}xK{K}q{Q}"}
    for label, fn in (("onehot", onehot_block), ("pallas", kernel_block)):
        jax.block_until_ready(fn(luts))
        st = chained_dispatch_stats(
            lambda salt: luts * (1.0 + 1e-6 * salt), fn, escalate=1,
        )
        if st is None:
            rec[f"{label}_note"] = "jitter-dominated"
            continue
        rec[f"{label}_ms"] = round(st["ms"], 3)
        rec[f"{label}_spread"] = st["spread"]
        rec[f"{label}_escalations"] = st.get("escalations", 0)
    if "onehot_ms" in rec and "pallas_ms" in rec:
        rec["speedup"] = round(rec["onehot_ms"] / rec["pallas_ms"], 2)
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
