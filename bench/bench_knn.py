"""kNN + selection benchmarks — mirrors cpp/bench/spatial/knn.cu:34-60
({n_samples, n_dims, n_queries, k} grid, BUILD/SEARCH scopes) and
selection.cu (SelectKAlgo variants)."""

import numpy as np
import jax

from bench.common import bench_fn
from raft_tpu.distance.distance_type import DistanceType
from raft_tpu.spatial.knn import _knn_single_part
from raft_tpu.spatial.fused_knn import fused_l2_knn
from raft_tpu.spatial.selection import select_k, SelectKAlgo


def main():
    rng = np.random.default_rng(0)

    # brute-force search: SIFT-1M config + a smaller one
    for n, d, nq, k in [(100_000, 128, 1024, 10), (1_000_000, 128, 10_000, 10)]:
        index = jax.device_put(rng.standard_normal((n, d)).astype(np.float32))
        q = jax.device_put(rng.standard_normal((nq, d)).astype(np.float32))
        for mode, exact in [("exact", True), ("approx", False)]:
            ms = bench_fn(
                lambda a, b: _knn_single_part(
                    a, b, k, DistanceType.L2SqrtExpanded, 2.0, 65536, None,
                    exact,
                )[0],
                q, index,
                name=f"knn/bf_{mode}/{n}x{d}q{nq}k{k}", iters=5,
                work=2.0 * n * d * nq,
            )
            print(
                f'{{"name": "knn/qps_{mode}/{n}x{d}", '
                f'"qps": {round(nq / (ms / 1e3))}}}'
            )
        # fused Pallas chunk-min path (the reference fused_l2_knn analog);
        # VERDICT r1 #2: must beat the scan path >=1.2x to stay in "auto"
        ms = bench_fn(
            lambda a, b: fused_l2_knn(
                a, b, k, metric=DistanceType.L2SqrtExpanded
            )[0],
            q, index,
            name=f"knn/bf_fused/{n}x{d}q{nq}k{k}", iters=5,
            work=2.0 * n * d * nq,
        )
        print(
            f'{{"name": "knn/qps_fused/{n}x{d}", '
            f'"qps": {round(nq / (ms / 1e3))}}}'
        )

    # k-selection algos (selection.cu)
    dists = jax.device_put(rng.standard_normal((4096, 16384)).astype(np.float32))
    for algo in (SelectKAlgo.TOPK, SelectKAlgo.SORT):
        bench_fn(
            lambda dm: select_k(dm, 64, algo=algo)[0],
            dists, name=f"selection/{algo.name}/4096x16384k64", iters=10,
        )


if __name__ == "__main__":
    main()
